"""Benchmark entry point (driver contract: prints ONE JSON line).

Default: TPC-H Q1 on the TPU engine with a full component breakdown
(the VERDICT's diagnosability bar): upload, compile, DEVICE-RESIDENT
steady-state compute (the fused filter+group+aggregate program looped over a
resident batch with no host round trips), download, per-call dispatch
latency, end-to-end collect, and the columnar shuffle partition rate in
GB/s/chip (BASELINE.json's headline unit). The CPU engine (eager numpy, the
stand-in for CPU Spark in the reference's 4x-typical claim, docs/FAQ.md:66)
provides vs_baseline.

The primary value is device-resident rows/s: on a remote-tunnel chip the
end-to-end number is dominated by link latency variance, which says nothing
about the kernels; both are reported.

Env knobs: BENCH_SUITE (tpch | tpcds | tpcxbb | tpcxbb_suite | mortgage |
udf), BENCH_QUERY, BENCH_SCALE, BENCH_ITERS (timed iterations, default 5).
"""
import json
import os
import sys
import time


def _sync(x):
    import jax
    jax.block_until_ready(x)
    return x


def _hard_sync(res):
    """Materialize one scalar of a result tree on host: block_until_ready on
    the remote-tunnel backend returns at enqueue time, so a tiny download is
    the only trustworthy completion barrier."""
    import jax
    import numpy as np
    leaf = jax.tree_util.tree_leaves(res)[-1]
    np.asarray(leaf.ravel()[:1] if getattr(leaf, "ndim", 0) else leaf)
    return res


def _bench_tpch_q1(scale: float, iters: int) -> dict:
    import numpy as np
    import jax
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem, q1
    from spark_rapids_tpu.columnar.batch import DeviceBatch

    table = gen_lineitem(scale=scale, seed=42)
    n_rows = table.num_rows
    conf = {**BENCH_CONF, "spark.rapids.tpu.sql.string.maxBytes": "16"}

    # ---- CPU baseline first (single-core host; device threads would steal it)
    cpu_sess = TpuSession({**conf, "spark.rapids.tpu.sql.enabled": "false"})
    cpu_df = q1(cpu_sess.create_dataframe(table))
    t0 = time.perf_counter()
    cpu_result = cpu_df.collect()
    cpu_time = time.perf_counter() - t0

    # ---- upload -------------------------------------------------------------
    t0 = time.perf_counter()
    batch = DeviceBatch.from_arrow(table, 16)
    for c in batch.columns:       # barrier EVERY column's transfer
        _hard_sync(c.data[:1])
    upload_s = time.perf_counter() - t0

    # ---- chunked overlapped upload (transfer pipeline) ----------------------
    # chunk N+1 stages on host while chunk N's async device_put is in flight;
    # device-side concat assembles the final bucketed batch
    from spark_rapids_tpu.columnar import transfer as _transfer
    chunk_rows = max(1, n_rows // 8)
    pipe_stats = {}
    t0 = time.perf_counter()
    chunked = _transfer.upload_table(table, 16, chunk_rows=chunk_rows,
                                     max_inflight=2, stats=pipe_stats)
    upload_chunked_s = time.perf_counter() - t0
    del chunked

    # ---- device-resident compute: the fused Q1 aggregation program ----------
    import __graft_entry__ as graft
    step, _ = graft.entry_for_batch(batch)
    t0 = time.perf_counter()
    res = _hard_sync(step(np.int32(batch.num_rows), *graft.flatten(batch)))
    compile_s = time.perf_counter() - t0
    # variance reporting (round-4 VERDICT weak-4): N repeats of the timed
    # loop, median/min/max published so tunnel noise is distinguishable
    # from a kernel regression
    repeats = []
    for _ in range(max(3, min(5, iters))):
        t0 = time.perf_counter()
        for _ in range(iters):
            res = step(np.int32(batch.num_rows), *graft.flatten(batch))
        # ONE scalar-download barrier after the loop: the device stream
        # executes in order, so materializing the last result bounds all
        # iterations — the link round trip amortizes instead of deflating
        # every iteration
        _hard_sync(res)
        repeats.append((time.perf_counter() - t0) / iters)
    repeats.sort()
    compute_s = repeats[len(repeats) // 2]          # median

    # dispatch latency: enqueue without waiting for the result
    t0 = time.perf_counter()
    res = step(np.int32(batch.num_rows), *graft.flatten(batch))
    dispatch_s = time.perf_counter() - t0
    _hard_sync(res)

    # ---- download (the small grouped result) --------------------------------
    ng = int(res[-1])
    t0 = time.perf_counter()
    _ = [np.asarray(a) for a in res[:-1]]
    download_s = time.perf_counter() - t0

    # ---- end-to-end collect through the engine ------------------------------
    tpu_sess = TpuSession(conf)
    tpu_df = q1(tpu_sess.create_dataframe(table))
    tpu_result = tpu_df.collect()          # warm (scan cache + programs)
    t0 = time.perf_counter()
    for _ in range(max(iters // 2, 1)):
        tpu_result = tpu_df.collect()
    e2e_s = (time.perf_counter() - t0) / max(iters // 2, 1)
    assert tpu_result.num_rows == cpu_result.num_rows, (
        f"result mismatch: {tpu_result.num_rows} vs {cpu_result.num_rows}")

    # ---- cold end-to-end collect: upload INCLUDED (the BENCH_r05 12.55 s
    # wall this PR pipelines away). Programs are warm from the runs above;
    # scan cache off so each run actually pays its upload path. Chunked and
    # single-shot must produce bit-identical collect results.
    base_nc = {**conf, "spark.rapids.tpu.sql.scanCache.enabled": "false"}
    # single-shot FIRST: shared lazy-init/compile costs land on it, not on
    # the chunked run under measurement
    sess_single = TpuSession({**base_nc,
                              "spark.rapids.tpu.transfer.chunkRows": "0"})
    df_single = q1(sess_single.create_dataframe(table))
    t0 = time.perf_counter()
    res_single = df_single.collect()
    cold_single_s = time.perf_counter() - t0
    sess_chunk = TpuSession({**base_nc,
                             "spark.rapids.tpu.transfer.chunkRows":
                                 str(chunk_rows)})
    df_chunk = q1(sess_chunk.create_dataframe(table))
    t0 = time.perf_counter()
    res_chunk = df_chunk.collect()
    cold_chunked_s = time.perf_counter() - t0
    assert res_single.equals(res_chunk), (
        "chunked upload changed the collect result\n"
        f"single: {res_single.to_pydict()}\nchunked: {res_chunk.to_pydict()}")

    # ---- compressed columnar path: encoded vs decoded link bytes ------------
    compression = _bench_compression(table, conf)

    # ---- whole-stage fusion: fused vs unfused + 129-query coverage ----------
    fusion = _bench_fusion(table, conf, iters)

    # ---- concurrent query serving (scheduler + cross-query program cache) ---
    concurrent = _bench_concurrent(table, conf, scale)

    # ---- network serving (wire streaming + preemption p99) ------------------
    serving_net = _bench_serving_net(table, conf, scale)

    # ---- out-of-core degradation (ample vs 1/4 budget) ----------------------
    out_of_core = _bench_out_of_core(table, conf, scale)

    # ---- statistics-driven adaptive execution (skew-split OFF vs ON) --------
    adaptive = _bench_adaptive(conf, scale)

    # ---- structured tracing: disabled cost + span coverage ------------------
    observability = _bench_observability(table, conf, iters)

    # ---- columnar shuffle partition rate (GB/s/chip) ------------------------
    shuffle_gbps = _bench_shuffle(batch, iters)
    exchange_gbps = _bench_full_exchange(batch, conf, iters)

    # ---- NamedSharding-first mesh execution ---------------------------------
    mesh_section = _bench_mesh(table, conf, iters, exchange_gbps)

    dev_rps = n_rows / compute_s
    cpu_rps = n_rows / cpu_time
    return {
        "metric": "tpch_q1_device_resident_rows_per_sec",
        "value": round(dev_rps),
        "unit": "rows/s",
        "vs_baseline": round(dev_rps / cpu_rps, 3),
        "breakdown": {
            "rows": n_rows,
            "upload_s": round(upload_s, 4),
            "compile_s": round(compile_s, 2),
            "device_compute_s": round(compute_s, 4),
            "device_compute_s_min": round(repeats[0], 4),
            "device_compute_s_max": round(repeats[-1], 4),
            "device_rows_per_sec_spread": [round(n_rows / t) for t in
                                           (repeats[-1], repeats[0])],
            "dispatch_s": round(dispatch_s, 4),
            "download_s": round(download_s, 4),
            "pipeline": {
                "chunk_rows": chunk_rows,
                "max_inflight": 2,
                "upload_chunked_s": round(upload_chunked_s, 4),
                "upload_single_shot_s": round(upload_s, 4),
                "chunked_upload_speedup": round(
                    upload_s / upload_chunked_s, 3),
                "per_chunk_upload_s": pipe_stats["per_chunk_upload_s"],
                "upload_overlap_efficiency":
                    pipe_stats["upload_overlap_efficiency"],
                "inflight_high_water": pipe_stats["inflight_high_water"],
                # upload INCLUDED (vs BENCH_r05's 12.55 s upload wall)
                "end_to_end_cold_collect_s": round(cold_chunked_s, 4),
                "end_to_end_cold_collect_single_shot_s":
                    round(cold_single_s, 4),
            },
            "compression": compression,
            "fusion": fusion,
            "concurrent": concurrent,
            "serving_net": serving_net,
            "out_of_core": out_of_core,
            "adaptive": adaptive,
            "observability": observability,
            "mesh": mesh_section,
            "end_to_end_collect_s": round(e2e_s, 4),
            "end_to_end_rows_per_sec": round(n_rows / e2e_s),
            "cpu_engine_s": round(cpu_time, 3),
            "cpu_rows_per_sec": round(cpu_rps),
            "groups": ng,
            "shuffle_gb_per_sec_chip": shuffle_gbps,
            "shuffle_exchange_gb_per_sec": exchange_gbps,
            # honesty label for vs_baseline (round-3 VERDICT item 2): the
            # comparator is the repo's own eager-numpy CPU engine on this
            # host's SINGLE core. Real pyspark local[*] is not installable
            # here (no package, zero-egress image) and would not be
            # multi-core on a 1-core host anyway; the reference's "4x
            # typical" (docs/FAQ.md:66) is against multi-core Spark
            # executors, so treat vs_baseline as an upper bound and divide
            # by the executor core count for a like-for-like estimate.
            "baseline": "in-repo numpy engine, 1 host core",
        },
    }


def _bench_compression(table, conf: dict) -> dict:
    """Compressed columnar data path on a COLD parquet Q1 (scan cache off,
    every run pays its upload): H2D link bytes with the encoded path
    (dictionary indices + RLE runs shipped, decode/expansion in HBM,
    encoded-domain operators) vs the decoded path, with bit-identical
    collected results. ``link_bytes_decoded / link_bytes_encoded`` is the
    link-byte reduction the encoded path buys — it multiplies directly with
    the transfer pipeline's overlap (docs/compressed-data-path.md)."""
    import shutil
    import tempfile
    import os as _os
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import q1
    from spark_rapids_tpu.utils import metrics as um

    tmp = tempfile.mkdtemp(prefix="bench-comp-")
    path = _os.path.join(tmp, "lineitem.parquet")
    pq.write_table(table, path, row_group_size=max(1, table.num_rows // 4))
    base = {**conf, "spark.rapids.tpu.sql.scanCache.enabled": "false"}

    def run(extra: dict):
        sess = TpuSession({**base, **extra})
        df = q1(sess.read.parquet(path))
        df.collect()                         # warm programs; timed run next
        before = um.transfer_snapshot()
        t0 = time.perf_counter()
        out = df.collect()
        wall = time.perf_counter() - t0
        return out, um.transfer_delta(before), wall

    out_enc, d_enc, wall_enc = run({})
    out_dec, d_dec, wall_dec = run({
        "spark.rapids.tpu.io.parquet.deviceDictDecode.enabled": "false",
        "spark.rapids.tpu.sql.encodedDomain.enabled": "false"})
    shutil.rmtree(tmp, ignore_errors=True)
    # Q1 output is sorted by its grouping keys, so strict table equality is
    # the bit-identity bar: the encoded path must change NOTHING
    assert out_enc.equals(out_dec), (
        "encoded path changed Q1 results\n"
        f"encoded: {out_enc.to_pydict()}\ndecoded: {out_dec.to_pydict()}")
    enc_b = d_enc["transfer.encoded_bytes"]
    dec_b = d_dec["transfer.encoded_bytes"]    # decoded run ships plain
    up_s = d_enc["transfer.upload_seconds"]
    return {
        "link_bytes_encoded": int(enc_b),
        "link_bytes_decoded": int(dec_b),
        # < 1.0 = the encoded path shipped fewer bytes; the acceptance bar
        # on lineitem (dictionary + RLE columns) is <= 0.5 (>= 2x cut)
        "link_bytes_ratio": round(enc_b / dec_b, 4) if dec_b else 1.0,
        "link_reduction_x": round(dec_b / enc_b, 2) if enc_b else 0.0,
        "compression_ratio": d_enc["transfer.compression_ratio"],
        # decoded-equivalent bytes delivered per second of upload wall: the
        # effective link bandwidth the encoding buys
        "effective_gb_per_sec": (round(
            d_enc["transfer.decoded_equivalent_bytes"] / up_s / 1e9, 3)
            if up_s > 0 else 0.0),
        "encoded_domain_ops": int(d_enc["transfer.encoded_domain_ops"]),
        "cold_collect_encoded_s": round(wall_enc, 4),
        "cold_collect_decoded_s": round(wall_dec, 4),
    }


def _bench_fusion(table, conf: dict, iters: int) -> dict:
    """Whole-stage fusion (ROADMAP item 5 acceptance): Q1 fused vs unfused
    — bit-identical collect, >= 1 fused stage, warm device-compute delta,
    batches-not-materialized from the executed plan's metrics, a repeat-
    submission program-cache hit-rate — plus fusion COVERAGE measured by
    planning the full TPC-DS (99) + TPCx-BB (30) query sets (plan-only:
    coverage is a property of the plans, and 129 executions don't belong in
    a bench smoke)."""
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import q1
    from spark_rapids_tpu.plan.fusion import (fused_batches_not_materialized,
                                              fusion_stats)
    from spark_rapids_tpu.serving.program_cache import global_program_cache

    fused_sess = TpuSession(conf)
    unfused_sess = TpuSession({**conf,
                               "spark.rapids.tpu.sql.fusion.enabled":
                                   "false"})
    fdf = q1(fused_sess.create_dataframe(table))
    udf = q1(unfused_sess.create_dataframe(table))
    fused_out = fdf.collect()            # warm: compiles fused programs
    unfused_out = udf.collect()
    assert fused_out.equals(unfused_out), (
        "fusion changed Q1 results\n"
        f"fused: {fused_out.to_pydict()}\nunfused: {unfused_out.to_pydict()}")
    q1_stats = fusion_stats(fused_sess.last_plan)
    assert q1_stats["fused_stages"] >= 1, fused_sess.last_plan.tree_string()
    saved = fused_batches_not_materialized(fused_sess.last_plan)

    def best_of(df):
        best = None
        for _ in range(max(2, iters)):
            t0 = time.perf_counter()
            df.collect()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    fused_s = best_of(fdf)
    unfused_s = best_of(udf)

    # repeat submission through the scheduler: the fused plan's programs
    # must come out of the cross-query ProgramCache, not recompile
    cache = global_program_cache()
    fused_sess.submit(fdf).result(timeout=600)
    before = cache.snapshot_counters()
    h = fused_sess.submit(fdf)
    assert h.result(timeout=600).equals(fused_out)
    after = cache.snapshot_counters()
    hits = after["hits"] - before["hits"]
    misses = after["misses"] - before["misses"]
    repeat_hit_rate = hits / (hits + misses) if (hits + misses) else 1.0

    # coverage sweep: plan every TPC-DS + TPCx-BB query fused
    from spark_rapids_tpu.benchmarks.tpcds_data import gen_all as gen_tpcds
    from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES as TPCDS
    from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all as gen_tpcxbb
    from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES as TPCXBB
    sweep_sess = TpuSession({**conf,
                             "spark.rapids.tpu.sql.hasNans": "false",
                             "spark.rapids.tpu.sql.exec.NestedLoopJoin":
                                 "true",
                             "spark.rapids.tpu.sql.exec.CartesianProduct":
                                 "true"})
    sweep_scale = 0.002                  # plan shapes, not data volume
    ds = {k: sweep_sess.create_dataframe(v)
          for k, v in gen_tpcds(sweep_scale, seed=0).items()}
    bb = {k: sweep_sess.create_dataframe(v)
          for k, v in gen_tpcxbb(scale=sweep_scale, seed=0).items()}
    queries = fused_queries = total_stages = total_ops = 0
    for registry, dfs in ((TPCDS, ds), (TPCXBB, bb)):
        for fn in registry.values():
            queries += 1
            st = fusion_stats(fn(dfs)._executed_plan())
            total_stages += st["fused_stages"]
            total_ops += st["fused_ops"]
            if st["fused_stages"] >= 1:
                fused_queries += 1

    return {
        "q1_fused_stage_count": q1_stats["fused_stages"],
        "q1_ops_per_fused_stage": q1_stats["ops_per_fused_stage"],
        "batches_not_materialized": int(saved),
        "q1_warm_collect_fused_s": round(fused_s, 4),
        "q1_warm_collect_unfused_s": round(unfused_s, 4),
        # the fused-vs-unfused device-compute delta (>1 = fusion faster)
        "q1_fused_vs_unfused_x": round(unfused_s / fused_s, 3),
        "bit_identical": True,
        "repeat_hit_rate": round(repeat_hit_rate, 4),
        "coverage": {
            "queries": queries,
            "fused_queries": fused_queries,
            "fraction": round(fused_queries / queries, 4),
            "fused_stages": total_stages,
            "ops_per_fused_stage": (round(total_ops / total_stages, 3)
                                    if total_stages else 0.0),
        },
    }


def _serving_query_mix(sess, table):
    """The serving bench's repeat-query mix: 4 distinct TPC-H-shaped plan
    shapes over lineitem. Submitted 4x each = 16 interleaved queries whose
    repeats must hit the cross-query program cache. Shared with the
    warm-start probe subprocess so both processes build IDENTICAL plan
    shapes (and therefore identical cache keys)."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.benchmarks.tpch import q1

    df = sess.create_dataframe(table)
    return {
        "q1": q1(df),
        "filter_project": (df.filter(F.col("l_quantity") > F.lit(25.0))
                           .select("l_orderkey", "l_extendedprice",
                                   "l_returnflag")),
        "flag_agg": (df.groupBy("l_returnflag")
                     .agg(F.sum("l_extendedprice").alias("rev"),
                          F.avg("l_discount").alias("disc"))),
        "status_count": (df.filter(F.col("l_discount") > F.lit(0.02))
                         .groupBy("l_linestatus").count()),
    }


def _bench_concurrent(table, conf: dict, scale: float) -> dict:
    """Concurrent query serving (ROADMAP item 4 acceptance): 16 interleaved
    queries through the session scheduler vs the same 16 sequentially —
    aggregate rows/s must hold at ~sequential throughput while p50/p99
    latency and the program-cache hit rate on the repeat mix are reported;
    a SECOND server process then warm-starts from the on-disk plan-key
    index (>= 1 disk hit, asserted in nightly)."""
    import tempfile
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.serving.program_cache import global_program_cache
    from spark_rapids_tpu.utils.metrics import percentile

    cache_dir = tempfile.mkdtemp(prefix="bench-serving-")
    sconf = {**conf,
             "spark.rapids.tpu.serving.maxConcurrentQueries": "4",
             "spark.rapids.tpu.serving.cache.dir": cache_dir}
    sess = TpuSession(sconf)
    _ = sess.scheduler      # wire the on-disk index BEFORE the first compile
    shapes = _serving_query_mix(sess, table)
    mix = [(name, df) for _ in range(4) for name, df in shapes.items()]
    n_rows = table.num_rows

    # warm pass: programs compile once here; also the correctness reference
    expected = {name: df.collect() for name, df in shapes.items()}

    # sequential baseline: the same 16 queries back to back, warm
    t0 = time.perf_counter()
    for _, df in mix:
        df.collect()
    seq_wall = time.perf_counter() - t0

    # concurrent phase: submit all 16 at once; best-of-2 walls so a loaded
    # host doesn't read as a serving regression (the CI gate is a ratio)
    cache = global_program_cache()
    best = None
    for _ in range(2):
        before = cache.snapshot_counters()
        t0 = time.perf_counter()
        handles = [sess.submit(df, tenant=f"tenant{i % 4}",
                               label=f"{name}#{i}")
                   for i, (name, df) in enumerate(mix)]
        for h in handles:
            h.result(timeout=600)
        wall = time.perf_counter() - t0
        after = cache.snapshot_counters()
        if best is None or wall < best[0]:
            best = (wall, before, after, handles)
    conc_wall, before, after, handles = best
    for h, (name, _) in zip(handles, mix):
        assert h.result().equals(expected[name]), (
            f"concurrent {name} diverged from the sequential reference")
    hits = after["hits"] - before["hits"]
    misses = after["misses"] - before["misses"]
    hit_rate = hits / (hits + misses) if (hits + misses) else 1.0
    walls = sorted(h.metrics["wall_s"] for h in handles)
    seq_rps = 16 * n_rows / seq_wall
    agg_rps = 16 * n_rows / conc_wall

    warm = _serving_warm_start(scale, cache_dir, conf)
    return {
        "queries": len(mix),
        "distinct_shapes": len(shapes),
        "workers": 4,
        "sequential_wall_s": round(seq_wall, 4),
        "concurrent_wall_s": round(conc_wall, 4),
        "sequential_rows_per_sec": round(seq_rps),
        "aggregate_rows_per_sec": round(agg_rps),
        "aggregate_vs_sequential_x": round(agg_rps / seq_rps, 3),
        "p50_latency_s": round(percentile(walls, 50), 4),
        "p99_latency_s": round(percentile(walls, 99), 4),
        "program_cache_hit_rate": round(hit_rate, 4),
        "program_cache": cache.stats(),
        "warm_start": warm,
    }


def _serving_warm_start(scale: float, cache_dir: str, conf: dict) -> dict:
    """Restart story: a fresh server process pointed at the same serving
    cache directory submits the same query shapes; its first compiles of
    known plan keys count as DISK hits (the executables deserialize from
    the jax persistent compilation cache instead of compiling cold)."""
    import subprocess
    code = (
        "import json, sys\n"
        "import bench\n"
        "from spark_rapids_tpu.api import TpuSession\n"
        "from spark_rapids_tpu.benchmarks.tpch import gen_lineitem\n"
        "scale, cache_dir = float(sys.argv[1]), sys.argv[2]\n"
        "conf = json.loads(sys.argv[3])\n"
        "conf['spark.rapids.tpu.serving.cache.dir'] = cache_dir\n"
        "sess = TpuSession(conf)\n"
        "_ = sess.scheduler\n"
        "table = gen_lineitem(scale=scale, seed=42)\n"
        "shapes = bench._serving_query_mix(sess, table)\n"
        "hs = [sess.submit(df, label=n) for n, df in shapes.items()]\n"
        "[h.result(timeout=600) for h in hs]\n"
        "print('WARM ' + json.dumps("
        "sess.scheduler.stats()['program_cache']))\n")
    out = subprocess.run(
        [sys.executable, "-c", code, str(scale), cache_dir,
         json.dumps(conf)],
        capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("WARM ")]
    assert lines, (f"warm-start probe produced no stats\n"
                   f"stdout: {out.stdout[-1000:]}\n"
                   f"stderr: {out.stderr[-2000:]}")
    st = json.loads(lines[-1][len("WARM "):])
    return {"disk_hits": st["disk_hits"], "misses": st["misses"],
            "hits": st["hits"], "indexed_keys": st["indexed_keys"]}


def _logical_bytes(batch) -> int:
    """Column data + validity + lengths, EXCLUDING the f64 bit siblings
    (those are upload-time duplicates, not payload the shuffle moves
    twice)."""
    total = 0
    for c in batch.columns:
        total += c.data.size * c.data.dtype.itemsize + c.validity.size
        if c.lengths is not None:
            total += c.lengths.size * 4
    return total


def _bench_serving_net(table, conf: dict, scale: float) -> dict:
    """Network-native serving: wire streaming over TCP localhost (Arrow
    IPC frames through the shuffle transport, >= 1 partial batch before
    DONE, bit-identical assembly) and the preemption lever — one whale +
    interactive tenants on a single device permit, interactive
    submit-to-done p99 with batch-granularity preemption ON vs OFF, the
    whale completing with identical results both ways."""
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.serving.client import QueryServiceClient
    from spark_rapids_tpu.serving.server import QueryServer
    from spark_rapids_tpu.utils import metrics as um
    from spark_rapids_tpu.utils.metrics import percentile

    # ---- wire streaming over localhost -------------------------------------
    sess = TpuSession(conf)
    (sess.create_dataframe(table).repartition(4)
     .createOrReplaceTempView("lineitem"))
    server = QueryServer(sess)
    host, port = server.address
    client = QueryServiceClient([f"{host}:{port}"], TpuConf(conf))
    sql = ("SELECT l_orderkey, l_extendedprice FROM lineitem "
           "WHERE l_discount > 0.05")
    ref = sess.sql(sql).collect()
    bytes_before = um.SERVING_METRICS[um.SERVING_WIRE_BYTES_OUT].value
    t0 = time.perf_counter()
    handle = client.submit(sql)
    got = handle.result()
    wire_wall = time.perf_counter() - t0
    wire_bytes = (um.SERVING_METRICS[um.SERVING_WIRE_BYTES_OUT].value
                  - bytes_before)
    stream_ok = got.equals(ref)
    first_before_done = (handle.metrics["first_batch_s"]
                         < handle.metrics["wall_s"])
    stream_batches = handle.batches_delivered
    client.close()
    server.shutdown()
    sess.scheduler.shutdown(wait=False)

    # ---- preemption: whale + interactive p99 --------------------------------
    whale_rows = min(table.num_rows, 400_000)
    whale_table = table.slice(0, whale_rows)
    inter_table = table.slice(0, min(table.num_rows, 2_000))

    def run_mode(preempt: bool):
        DeviceManager.shutdown()
        s = TpuSession({
            **conf,
            "spark.rapids.tpu.sql.concurrentTpuTasks": "1",
            "spark.rapids.tpu.serving.maxConcurrentQueries": "4",
            "spark.rapids.tpu.serving.preemption.enabled":
                str(preempt).lower(),
            "spark.rapids.tpu.serving.preemption.starvationMs": "30"})
        whale_df = (s.create_dataframe(whale_table).repartition(16)
                    .groupBy("l_returnflag")
                    .agg(F.sum("l_extendedprice").alias("rev"))
                    .sort("l_returnflag"))
        inter_df = (s.create_dataframe(inter_table)
                    .groupBy("l_linestatus")
                    .agg(F.sum("l_quantity").alias("q"))
                    .sort("l_linestatus"))
        ref_whale = whale_df.collect()          # warm compiles
        inter_df.collect()
        wh = s.submit(whale_df, tenant="whale", label="whale")
        time.sleep(0.2)                         # whale takes the permit
        walls = []
        for i in range(3):
            t0 = time.perf_counter()
            ih = s.submit(inter_df, tenant="interactive", label=f"i{i}")
            ih.result(timeout=600)
            walls.append(time.perf_counter() - t0)
        whale_ok = wh.result(timeout=600).equals(ref_whale)
        preempts = wh.metrics["preemptions"]
        s.scheduler.shutdown(wait=False)
        return sorted(walls), preempts, whale_ok

    off_walls, _off_p, off_ok = run_mode(False)
    on_walls, preemptions, on_ok = run_mode(True)
    DeviceManager.shutdown()
    off_p99 = percentile(off_walls, 99)
    on_p99 = percentile(on_walls, 99)
    return {
        "wire_wall_s": round(wire_wall, 4),
        "wire_bytes_out": int(wire_bytes),
        "stream_batches": int(stream_batches),
        "first_batch_before_done": bool(first_before_done),
        "stream_bit_identical": bool(stream_ok),
        "interactive_p99_preempt_off_s": round(off_p99, 4),
        "interactive_p99_preempt_on_s": round(on_p99, 4),
        "preempt_speedup_x": round(off_p99 / on_p99, 3) if on_p99 else 0.0,
        "preemptions": int(preemptions),
        "whale_results_match": bool(off_ok and on_ok),
    }


def _bench_out_of_core(table, conf: dict, scale: float) -> dict:
    """Out-of-core degradation: Q1-shaped (filter+groupby) and Q3-shaped
    (join+groupby) runs at AMPLE budget vs the device budget clamped to
    ~1/4 of the measured working set. Reports rows/s both ways, grace
    partitions, recursion depth and bytes spilled per tier; asserts the
    clamped run completes with results matching ample (exact columns
    bitwise, variableFloatAgg sums to 1e-9 — the distributed float-sum
    contract, docs/out-of-core.md)."""
    import numpy as np
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.benchmarks.tpch import q1
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.testing import assert_tables_equal

    n_rows = table.num_rows
    rng = np.random.default_rng(11)
    n_ord = max(n_rows // 4, 2)
    import pyarrow as pa
    orders = pa.table({
        "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
        "o_pri": rng.integers(0, 5, n_ord).astype(np.int64)})

    def q3_shaped(sess, li, od):
        # Q3 shape: selective filter -> equi-join -> aggregate
        return (li.filter(F.col("l_quantity") < 30)
                .join(od, [("l_orderkey", "o_orderkey")])
                .groupBy("o_pri")
                .agg(F.sum("l_extendedprice").alias("rev"),
                     F.count(F.lit(1)).alias("n")))

    base = {**conf, "spark.rapids.tpu.sql.scanCache.enabled": "false"}
    out = {}
    working_set = 0
    for name, build in (("q1", lambda s: q1(s.create_dataframe(table))),
                        ("q3_shaped", lambda s: q3_shaped(
                            s, s.create_dataframe(table),
                            s.create_dataframe(orders)))):
        DeviceManager.shutdown()
        sess = TpuSession(base)
        df = build(sess)
        df.collect()                      # warm programs
        t0 = time.perf_counter()
        ref = df.collect()
        ample_s = time.perf_counter() - t0
        mm = sess.last_metrics.get("memory", {})
        assert mm.get("memory.spill_partitions", 0) == 0, (
            "ample-budget run unexpectedly partitioned", mm)
        # measured working set: what the operators' inputs occupy on device
        working_set = max(
            working_set,
            sess.last_metrics.get("transfer", {}).get(
                "transfer.upload_bytes", 0) or table.nbytes)
        budget = max(int(working_set // 4), 64 << 10)
        DeviceManager.shutdown()
        tiny = TpuSession({
            **base,
            "spark.rapids.tpu.memory.tpu.poolSizeBytes": str(budget),
            "spark.rapids.tpu.memory.host.spillStorageSize": str(budget)})
        tdf = build(tiny)
        tdf.collect()                     # warm programs at tiny budget
        t0 = time.perf_counter()
        got = tdf.collect()
        tiny_s = time.perf_counter() - t0
        mm = tiny.last_metrics.get("memory", {})
        # completion + correctness at 1/4 budget is the acceptance bar
        assert_tables_equal(ref, got, ignore_order=True, approx_float=1e-9)
        out[name] = {
            "rows": n_rows,
            "budget_bytes": budget,
            "ample_rows_per_sec": round(n_rows / max(ample_s, 1e-9)),
            "quarter_budget_rows_per_sec": round(n_rows / max(tiny_s, 1e-9)),
            "quarter_vs_ample_x": round(ample_s / max(tiny_s, 1e-9), 3),
            "spill_partitions": mm.get("memory.spill_partitions", 0),
            "recursion_depth_peak": mm.get("memory.recursion_depth_peak", 0),
            "bytes_spilled_to_host": mm.get("memory.bytes_spilled_to_host",
                                            0),
            "bytes_spilled_to_disk": mm.get("memory.bytes_spilled_to_disk",
                                            0),
            "pressure_events": mm.get("memory.pressure_events", 0),
            "results_match": True,
        }
        assert out[name]["spill_partitions"] >= 2, out[name]
    DeviceManager.shutdown()
    return out


def _bench_adaptive(conf: dict, scale: float) -> dict:
    """Statistics-driven adaptive execution v2 (ROADMAP item 2): a
    Zipf-skewed equi-join + group-by under a constrained device budget,
    adaptive OFF vs ON. OFF pays grace recursion on the hot partition —
    the hot KEY is indivisible for key-hash splitting, so recursion burns
    depth without relief; ON's skew-split slices the MAP axis (the only
    axis that can divide a single giant key) and the observed-statistics
    grace fanout keeps the fitting sub-joins single-pass. Asserts
    bit-identical results; ci/nightly.sh gates speedup_x >= 1.5. Also
    reports the re-fusion stage count and the dynamic broadcast-switch
    count on their canonical probe queries."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.testing import assert_tables_equal

    n = 60_000
    rng = np.random.default_rng(20)
    z = np.minimum(rng.zipf(1.3, n), 1000).astype(np.int64)
    fact = pa.table({"k": z, "v": np.arange(n, dtype=np.int64)})
    dims = pa.table({"k": np.arange(1, 1001, dtype=np.int64),
                     "w": rng.integers(0, 100, 1000).astype(np.int64)})
    hot_bytes = int(float((z == 1).mean()) * n * 16)

    pool = 256 << 10
    base = {**conf,
            "spark.rapids.tpu.sql.scanCache.enabled": "false",
            "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
            "spark.rapids.tpu.memory.tpu.poolSizeBytes": str(pool),
            "spark.rapids.tpu.memory.host.spillStorageSize": str(8 << 20)}
    adaptive = {**base,
                "spark.rapids.tpu.sql.adaptive.enabled": "true",
                "spark.rapids.tpu.sql.adaptive."
                "skewedPartitionThreshold.bytes": str(hot_bytes // 4),
                "spark.rapids.tpu.sql.adaptive.skewedPartitionFactor": "2.0",
                "spark.rapids.tpu.sql.adaptive."
                "advisoryPartitionSizeInBytes": str(max(hot_bytes // 8,
                                                        4096))}

    def q(s):
        lt = s.create_dataframe(fact).repartition(8).repartition(6, "k")
        rt = s.create_dataframe(dims).repartition(3).repartition(6, "k")
        return (lt.join(rt, "k").groupBy("k")
                .agg(F.count().alias("n"), F.sum("v").alias("sv")))

    def run(run_conf):
        DeviceManager.shutdown()
        s = TpuSession(run_conf)
        df = q(s)
        df.collect()                     # warm programs
        t0 = time.perf_counter()
        out = df.collect()
        dt = time.perf_counter() - t0
        return out, dt, s

    out_off, off_s, s_off = run(base)
    out_on, on_s, s_on = run(adaptive)
    assert "skew-split" in s_on.last_plan.tree_string()
    cols = sorted(out_on.column_names)
    order = [(c, "ascending") for c in cols]
    assert_tables_equal(out_off.select(cols).sort_by(order),
                        out_on.select(cols).sort_by(order))
    ad = s_on.last_metrics.get("adaptive", {})
    mm_off = s_off.last_metrics.get("memory", {})
    mm_on = s_on.last_metrics.get("memory", {})

    # re-fusion probe: a lone filter above a coalesced reader becomes a
    # fused stage only the post-AQE pass can build
    DeviceManager.shutdown()
    s_rf = TpuSession({**conf,
                       "spark.rapids.tpu.sql.adaptive.enabled": "true"})
    t7 = pa.table({"k": pa.array(np.arange(3000) % 7, type=pa.int64()),
                   "v": pa.array(np.arange(3000), type=pa.int64())})
    (s_rf.create_dataframe(t7).repartition(6, "k")
     .filter(F.col("v") > 10).collect())
    refused = s_rf.last_metrics.get("adaptive", {}).get(
        "adaptive.refused_stages", 0)
    assert refused >= 1, s_rf.last_plan.tree_string()

    # broadcast-switch probe: build side observed under the threshold only
    # after its filter ran (estimates cannot see the selectivity)
    DeviceManager.shutdown()
    s_bc = TpuSession({**conf,
                       "spark.rapids.tpu.sql.adaptive.enabled": "true",
                       "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes":
                           "1000"})
    lt = s_bc.create_dataframe(t7).repartition(4, "k")
    rt = (s_bc.create_dataframe(t7).filter(F.col("v") < 30)
          .repartition(3, "k"))
    lt.join(rt, "k").collect()
    switches = s_bc.last_metrics.get("adaptive", {}).get(
        "adaptive.broadcast_switches", 0)
    DeviceManager.shutdown()

    return {
        "rows": n,
        "hot_partition_bytes": hot_bytes,
        "device_pool_bytes": pool,
        "skewed_join_off_s": round(off_s, 3),
        "skewed_join_on_s": round(on_s, 3),
        # adaptive ON vs OFF on the skewed join (>1 = adaptive faster);
        # nightly gates this at >= 1.5
        "speedup_x": round(off_s / max(on_s, 1e-9), 3),
        "bit_identical": True,
        "skew_splits": ad.get("adaptive.skew_splits", 0),
        "coalesced_partitions": ad.get("adaptive.coalesced_partitions", 0),
        "refused_stages": refused,
        "broadcast_switches": switches,
        "spill_partitions_off": mm_off.get("memory.spill_partitions", 0),
        "spill_partitions_on": mm_on.get("memory.spill_partitions", 0),
        "recursion_depth_off": mm_off.get("memory.recursion_depth_peak", 0),
        "recursion_depth_on": mm_on.get("memory.recursion_depth_peak", 0),
    }


def _bench_observability(table, conf: dict, iters: int) -> dict:
    """Structured tracing (utils/tracing.py): Q1 warm with tracing OFF vs
    ON — span counts per layer, export validity, EXPLAIN ANALYZE — plus
    the deterministic disabled-cost bound: the disabled hook is one bool
    read + a shared no-op context manager, so (per-hook ns x observed
    hook sites) / warm wall bounds the tracing-off overhead without
    depending on run-to-run timer noise. The <2% acceptance gate rides
    that bound (ci/nightly.sh bench-smoke)."""
    import json as _json
    import tempfile
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import q1
    from spark_rapids_tpu.utils import tracing

    reps = max(3, min(5, iters))

    def warm_best(sess):
        df = q1(sess.create_dataframe(table))
        df.collect()                # warm: programs + scan cache
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            df.collect()
            best = min(best, time.perf_counter() - t0)
        return best

    off_s = warm_best(TpuSession(conf))
    # NO export path on the timed session: the per-action JSON write is
    # O(spans) file serialization and would inflate tracing_on_overhead_x
    on_sess = TpuSession({**conf,
                          "spark.rapids.tpu.trace.enabled": "true"})
    on_s = warm_best(on_sess)
    export = tempfile.mktemp(prefix="bench-trace-", suffix=".json")
    tracing.export_chrome(on_sess.last_trace, export)   # untimed
    doc = _json.load(open(export))
    events = doc.get("traceEvents", [])
    counts = tracing.layer_counts(on_sess.last_trace)
    analyze = on_sess.explain_analyze()

    # disabled-hook microbench: per-call cost of a span site with tracing
    # off. The guarded call-site shape is representative: hot sites check
    # TRACER.on BEFORE building their args dict, so the disabled path is
    # the bool read + the shared no-op context manager.
    n_calls = 200_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        cm = (tracing.span("bench", "exec", {"rows": n_calls, "b": 1})
              if tracing.TRACER.on else tracing._NULL_SPAN)
        with cm:
            pass
    disabled_hook_ns = (time.perf_counter() - t0) / n_calls * 1e9
    hook_sites = max(sum(counts.values()), 1)
    off_overhead_pct = disabled_hook_ns * hook_sites / (off_s * 1e9) * 100

    return {
        "q1_warm_off_s": round(off_s, 4),
        "q1_warm_on_s": round(on_s, 4),
        "tracing_on_overhead_x": round(on_s / off_s, 3),
        "disabled_hook_ns": round(disabled_hook_ns, 1),
        "hook_sites_per_action": hook_sites,
        #: deterministic bound on the tracing-OFF cost of the hooks
        "tracing_off_overhead_pct": round(off_overhead_pct, 4),
        "spans_total": len(events),
        "spans_by_layer": counts,
        "export_valid": bool(events)
        and all(e.get("ph") in ("X", "i") for e in events),
        "explain_analyze_ok": ("rows=" in analyze and "wall=" in analyze),
    }


def _bench_shuffle(batch, iters: int) -> float:
    """Device columnar shuffle partition rate: the fused map-side reorder
    (key hash -> byte-matrix pack -> Pallas partition kernel emitting
    quota-padded partition pieces + counts; shuffle/partition_kernel.py) in
    ONE program over the resident batch. GB/s = batch bytes through the
    exchange per second (BASELINE.json's 'GB/sec/chip columnar shuffle'
    unit). More work than round 3's metric, which stopped at the sorted
    reorder without emitting per-partition pieces."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.execs.exchange_execs import hash_partition_ids
    from spark_rapids_tpu.exprs.core import ColV
    from spark_rapids_tpu.shuffle import partition_kernel as pk

    if jax.default_backend() != "tpu":
        # the fused Pallas kernel only lowers on real TPU backends; a CPU
        # smoke run (ci/nightly.sh) publishes null rather than an interpret-
        # mode number that says nothing about the link or the chip
        return None

    cap = batch.capacity
    n_parts = 8
    spec = pk.PackSpec.for_batch(batch)
    assert spec is not None, "bench batch must be kernel-packable"
    geom = pk.KernelGeom.plan(cap, n_parts, spec.lanes)
    inner = pk.reorder_program(spec, geom, cap, interpret=False)
    key_dtype = batch.schema.fields[0].dtype

    @jax.jit
    def full(num_rows, *flat):
        kv = ColV(key_dtype, flat[0], flat[1], None)
        pids = hash_partition_ids(jnp, [kv], cap, n_parts)
        return inner(num_rows, pids, *flat)

    flat = pk._deflate(spec, batch)
    res = _hard_sync(full(np.int32(batch.num_rows), *flat))    # compile
    summary = np.asarray(res[1])
    assert summary[0], "f64 pack must be exact for the bench"
    assert summary[-1] == 0, "quota overflow"
    t0 = time.perf_counter()
    for _ in range(iters):
        res = full(np.int32(batch.num_rows), *flat)
    _hard_sync(res)    # in-order stream: one barrier bounds all iterations
    dt = (time.perf_counter() - t0) / iters
    return round(_logical_bytes(batch) / dt / 1e9, 3)


def _bench_full_exchange(batch, conf: dict, iters: int) -> float:
    """A FULL exchange, not just the map-side kernel: hash-partition on
    device, cache every piece in the spillable shuffle catalog, read every
    reduce partition back as device batches (TpuShuffleExchangeExec
    end-to-end — the RapidsCachingWriter + RapidsCachingReader round trip
    on one chip). Device-resident throughout; one scalar barrier."""
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.execs.base import ExecContext, LeafExec
    from spark_rapids_tpu.execs.exchange_execs import (HashPartitioning,
                                                       TpuShuffleExchangeExec)
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.memory.device_manager import DeviceManager

    class _Resident(LeafExec):
        is_device = True
        num_partitions = 1

        def execute(self, ctx):
            yield batch

    tconf = TpuConf(conf)
    dm = DeviceManager.initialize(tconf)
    key = BoundReference(0, batch.schema.fields[0].dtype, False)
    t_best = None
    for it in range(max(3, iters // 2 + 1)):
        exchange = TpuShuffleExchangeExec(
            HashPartitioning(8, (key,)), _Resident(batch.schema))
        cleanups = []
        t0 = time.perf_counter()
        outs = []
        for p in range(8):
            ctx = ExecContext(tconf, partition_id=p, num_partitions=8,
                              device_manager=dm, cleanups=cleanups)
            outs.extend(exchange.execute(ctx))
        _hard_sync(outs[-1].columns[0].data)
        dt = time.perf_counter() - t0
        for fn in cleanups:
            fn()
        if it > 1:  # first runs pay program + sub-batch-bucket compiles
            t_best = dt if t_best is None else min(t_best, dt)
    return round(_logical_bytes(batch) / t_best / 1e9, 3)


def _bench_mesh(table, conf: dict, iters: int, single_device_gbps) -> dict:
    """NamedSharding-first execution numbers (the MULTICHIP acceptance
    section): in-mesh hash exchange (one jitted all_to_all, data never
    leaving the devices) GB/s at each available device count, compared
    against (a) the single-device catalog exchange — the pre-mesh current
    path (``shuffle_exchange_gb_per_sec``) — and (b) the SAME mesh
    repartition bounced through the host (collective gather -> host pid +
    reorder -> re-scatter); ``in_mesh_vs_host_hop_x`` is in-mesh over (b)
    and CI gates it at >= 2x. Per-device Q1 rows/s on the sharded
    pipeline; ``host_hop_bytes`` asserted EXACTLY 0 across the collective
    path — only per-shard row counts sync to host.

    Bit-identity story: a no-reduction sharded pipeline (filter + project)
    collects bit-identical to single-device (the exchange is a pure
    permutation). Q1's float sums merge per-shard partials in shard order,
    so float cells agree to 1e-9 while every non-float column (keys,
    counts) is asserted bitwise."""
    import jax
    import numpy as np
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.benchmarks.tpch import q1
    from spark_rapids_tpu.execs import mesh_execs as me
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.mesh_batch import scatter_arrow
    from spark_rapids_tpu.utils import metrics as um

    avail = len(jax.devices())
    counts = [c for c in (1, 2, 4, 8) if c <= avail]
    section = {
        "devices": counts,
        "in_mesh_exchange_gb_per_sec": {},
        # the single-device catalog exchange (the pre-mesh current path)
        "single_device_exchange_gb_per_sec": single_device_gbps,
        # the same repartition THROUGH the host: collective gather ->
        # host pid + partition-major reorder -> re-scatter (what a mesh
        # exchange costs when data bounces off the host)
        "host_hop_exchange_gb_per_sec": None,
        "in_mesh_vs_host_hop_x": None,
        "host_hop_bytes": None,
        "per_device_rows_per_sec": None,
        "collect_bit_identical": None,
        "q1_exact_cols_bit_identical": None,
        "q1_float_max_rel_err": None,
    }
    smax = 16
    hop_metric = um.TRANSFER_METRICS[um.TRANSFER_HOST_HOP_BYTES]
    mb = None
    for n in counts:
        if n < 2:
            # one shard: nothing to exchange
            section["in_mesh_exchange_gb_per_sec"][str(n)] = None
            continue
        mesh = make_mesh(n)
        mb = scatter_arrow(table, mesh, smax)
        key = BoundReference(0, mb.schema.fields[0].dtype, False)
        builder = me._hash_pid_builder((key,), n)
        op_key = ("bench_mexchange", n, mb.schema, mb.local_capacity)
        out = me._mesh_repartition(mb, op_key, builder, smax=smax)  # compile
        nbytes = me._mesh_batch_bytes(mb)
        before_hop = hop_metric.value
        # best-of timing: the ratio below gates CI, so single-shot noise on
        # a loaded host must not read as a regression
        dt = None
        for _ in range(max(2, iters)):
            t0 = time.perf_counter()
            out = me._mesh_repartition(mb, op_key, builder, smax=smax)
            _hard_sync(out.columns[0].data)
            run = time.perf_counter() - t0
            dt = run if dt is None else min(dt, run)
        hop = hop_metric.value - before_hop
        assert hop == 0, (
            f"in-mesh exchange bounced {hop} bytes through the host")
        section["host_hop_bytes"] = 0
        section["in_mesh_exchange_gb_per_sec"][str(n)] = round(
            nbytes / dt / 1e9, 3)
    best = max((v for v in section["in_mesh_exchange_gb_per_sec"].values()
                if v), default=None)
    if mb is not None:
        # host-hop comparator at the widest mesh: identical repartition,
        # but the rows go device -> host -> device like the pre-mesh path
        from spark_rapids_tpu.execs.exchange_execs import hash_partition_ids
        from spark_rapids_tpu.exprs.core import ColV
        from spark_rapids_tpu.parallel.mesh_batch import gather_mesh
        nmax = counts[-1]
        mesh = mb.mesh
        nbytes = me._mesh_batch_bytes(mb)

        def host_hop_once():
            tbl = gather_mesh(mb).to_arrow()           # device -> host
            karr = np.asarray(tbl.column(0).combine_chunks())
            kv = ColV(mb.schema.fields[0].dtype, karr,
                      np.ones(len(karr), dtype=bool))
            pids = hash_partition_ids(np, [kv], len(karr), nmax)
            order = np.argsort(pids, kind="stable")
            return scatter_arrow(tbl.take(order), mesh, smax)  # host -> dev

        host_hop_once()                                # warm programs
        dt = None
        for _ in range(max(2, iters)):                 # best-of (CI gate)
            t0 = time.perf_counter()
            hh = host_hop_once()
            _hard_sync(hh.columns[0].data)
            run = time.perf_counter() - t0
            dt = run if dt is None else min(dt, run)
        section["host_hop_exchange_gb_per_sec"] = round(nbytes / dt / 1e9, 3)
        if best:
            section["in_mesh_vs_host_hop_x"] = round(
                best / section["host_hop_exchange_gb_per_sec"], 2)

    if avail < 2:
        return section
    nmax = counts[-1]
    mesh_conf = {**conf,
                 "spark.rapids.tpu.sql.mesh.enabled": "true",
                 "spark.rapids.tpu.sql.mesh.numDevices": str(nmax),
                 "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}
    single_conf = {**conf,
                   "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}
    ms = TpuSession(mesh_conf)
    ss = TpuSession(single_conf)

    # strict bitwise: permute-only sharded pipeline vs single device
    def proj(sess):
        df = sess.create_dataframe(table)
        return df.filter(F.col("l_quantity") > F.lit(25.0)).select(
            "l_orderkey", "l_extendedprice", "l_returnflag")
    mesh_proj = proj(ms).collect()
    assert any(nd.startswith("Mesh")
               for nd in ms.last_plan.tree_string().split()), \
        ms.last_plan.tree_string()
    single_proj = proj(ss).collect()
    section["collect_bit_identical"] = bool(mesh_proj.equals(single_proj))
    assert section["collect_bit_identical"], (
        "sharded filter+project collect is not bit-identical to "
        "single-device")

    # sharded Q1: exact columns bitwise, float sums to 1e-9
    mdf = q1(ms.create_dataframe(table))
    mesh_q1 = mdf.collect()          # warm (compiles mesh programs)
    t0 = time.perf_counter()
    runs = max(iters // 2, 1)
    for _ in range(runs):
        mesh_q1 = mdf.collect()
    q1_s = (time.perf_counter() - t0) / runs
    section["per_device_rows_per_sec"] = round(
        table.num_rows / q1_s / nmax)
    single_q1 = q1(ss.create_dataframe(table)).collect()
    import pyarrow as pa
    exact_ok = True
    max_rel = 0.0
    for name in single_q1.column_names:
        cs, cm = single_q1[name], mesh_q1[name]
        if pa.types.is_floating(cs.type):
            a = np.asarray(cs.to_numpy(zero_copy_only=False), dtype=np.float64)
            b = np.asarray(cm.to_numpy(zero_copy_only=False), dtype=np.float64)
            denom = np.maximum(np.abs(a), 1e-300)
            max_rel = max(max_rel, float(np.max(np.abs(a - b) / denom)))
        elif not cs.equals(cm):
            exact_ok = False
    section["q1_exact_cols_bit_identical"] = exact_ok
    section["q1_float_max_rel_err"] = max_rel
    assert exact_ok, "sharded Q1 non-float columns differ from single-device"
    assert max_rel < 1e-9, (
        f"sharded Q1 float aggregates off by {max_rel} (> 1e-9)")
    return section


def _bench_tpch_cold(scale: float, iters: int) -> dict:
    """Cold end-to-end Q1 from PARQUET (no scan cache): the pipelined scan
    (decode-ahead producer thread overlapping host decode with async
    host->device transfer; io/parquet.py) vs the serial read. The
    round-3 VERDICT item-8 bar: pipelined must beat serial by >= 1.5x
    is measured as serial_s / pipelined_s."""
    import tempfile
    import os as _os
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem, q1

    table = gen_lineitem(scale=scale, seed=42)
    tmp = tempfile.mkdtemp(prefix="bench-cold-")
    path = _os.path.join(tmp, "lineitem.parquet")
    pq.write_table(table, path, row_group_size=max(1, table.num_rows // 16))
    base = {**BENCH_CONF, "spark.rapids.tpu.sql.string.maxBytes": "16",
            "spark.rapids.tpu.sql.scanCache.enabled": "false"}

    def cold_run(prefetch: int) -> float:
        best = None
        for _ in range(max(1, iters // 2)):
            sess = TpuSession({**base,
                               "spark.rapids.tpu.io.scan.prefetchBatches":
                                   str(prefetch)})
            df = q1(sess.read.parquet(path))
            t0 = time.perf_counter()
            out = df.collect()
            dt = time.perf_counter() - t0
            assert out.num_rows > 0
            best = dt if best is None else min(best, dt)
        return best

    cold_run(2)                      # compile warmup (programs only)
    serial = cold_run(0)
    piped = cold_run(2)
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    compression = _bench_compression(table, base)
    return {"metric": "tpch_q1_cold_scan_seconds", "value": round(piped, 3),
            "unit": "s", "vs_baseline": round(serial / piped, 3),
            "breakdown": {"rows": table.num_rows,
                          "serial_s": round(serial, 3),
                          "pipelined_s": round(piped, 3),
                          "speedup": round(serial / piped, 3),
                          "compression": compression}}


def _bench_tpcxbb(scale: float, qname: str, iters: int) -> dict:
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all
    from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES

    tables = gen_all(scale=scale, seed=42)
    query = QUERIES[qname]
    n_rows = (tables["web_clickstreams"].num_rows if qname == "q5"
              else sum(v.num_rows for v in tables.values()))
    cpu_sess = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.enabled": "false"})
    cpu_t = {k: cpu_sess.create_dataframe(v) for k, v in tables.items()}
    t0 = time.perf_counter()
    cpu_result = query(cpu_t).collect()
    cpu_time = time.perf_counter() - t0

    tpu_sess = TpuSession(BENCH_CONF)
    tpu_t = {k: tpu_sess.create_dataframe(v) for k, v in tables.items()}
    tpu_result = query(tpu_t).collect()
    t0 = time.perf_counter()
    for _ in range(iters):
        tpu_result = query(tpu_t).collect()
    tpu_time = (time.perf_counter() - t0) / iters
    assert tpu_result.num_rows == cpu_result.num_rows
    rps = n_rows / tpu_time
    return {"metric": f"tpcxbb_{qname}_rows_per_sec", "value": round(rps),
            "unit": "rows/s",
            "vs_baseline": round(rps / (n_rows / cpu_time), 3)}


#: representative TPC-DS subset for the suite benchmark: scans + star joins
#: + aggregations + windows across the three sales channels, PLUS the heavy
#: multi-CTE/window decile (q4 three-channel year-over-year, q14 cross-
#: channel intersection, q23 best-customer CTE chain, q67 rollup+rank) so
#: the geomean cannot overstate suite health (round-3 VERDICT weak-4)
TPCDS_BENCH_QUERIES = ("q3", "q4", "q7", "q14", "q19", "q23", "q27", "q34",
                       "q42", "q52", "q55", "q67", "q68", "q96")


def _bench_query_suite(suite: str, scale: float, iters: int) -> dict:
    """Suite-level device perf: per-query warm times on the TPU engine and a
    geomean queries/hr headline (BASELINE.json's TPCx-BB unit). The scan
    cache keeps tables device-resident across queries, so warm times measure
    the compute path, not the host link."""
    import math
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF

    if suite == "tpcds":
        from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
        from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
        names = [q for q in TPCDS_BENCH_QUERIES if q in QUERIES]
    else:
        from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all
        from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES
        names = sorted(QUERIES, key=lambda q: int(q[1:]))
    only = os.environ.get("BENCH_QUERIES", "")
    subset = False
    if only:
        wanted = [q.strip() for q in only.split(",") if q.strip()]
        names = [q for q in names if q in wanted]
        if not names:
            raise SystemExit(f"BENCH_QUERIES={only!r} matches no {suite} "
                             "query")
        subset = True
    tables = gen_all(scale=scale, seed=42)

    cpu_sess = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.enabled": "false"})
    cpu_dfs = {k: cpu_sess.create_dataframe(v) for k, v in tables.items()}
    tpu_sess = TpuSession(BENCH_CONF)
    tpu_dfs = {k: tpu_sess.create_dataframe(v) for k, v in tables.items()}

    per_query = {}
    tpu_times, cpu_times = [], []
    for q in names:
        print(f"[suite] {q} ...", file=sys.stderr, flush=True)
        query = QUERIES[q]
        # identical treatment on both engines: one discarded warm-up run,
        # then best-of-iters (no cold-start asymmetry in vs_baseline)
        cpu_rows = query(cpu_dfs).collect().num_rows
        cpu_s = None
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            cpu_rows = query(cpu_dfs).collect().num_rows
            dt = time.perf_counter() - t0
            cpu_s = dt if cpu_s is None else min(cpu_s, dt)
        tpu_rows = query(tpu_dfs).collect().num_rows    # warm: compile+cache
        best = None
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            tpu_rows = query(tpu_dfs).collect().num_rows
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        assert tpu_rows == cpu_rows, f"{q}: {tpu_rows} != {cpu_rows}"
        print(f"[suite] {q} tpu={best:.3f}s cpu={cpu_s:.3f}s",
              file=sys.stderr, flush=True)
        per_query[q] = {"tpu_s": round(best, 4), "cpu_s": round(cpu_s, 4),
                        "rows": tpu_rows}
        tpu_times.append(best)
        cpu_times.append(cpu_s)

    geo = math.exp(sum(math.log(t) for t in tpu_times) / len(tpu_times))
    cpu_geo = math.exp(sum(math.log(t) for t in cpu_times) / len(cpu_times))
    return {
        # a BENCH_QUERIES subset must not publish (or regression-compare)
        # under the full suite's metric name
        "metric": (f"{suite}_subset_geomean_queries_per_hour" if subset
                   else f"{suite}_geomean_queries_per_hour"),
        "value": round(3600.0 / geo, 1),
        "unit": "queries/hr",
        "vs_baseline": round(cpu_geo / geo, 3),
        "breakdown": {
            "scale": scale,
            "queries": len(names),
            "geomean_s": round(geo, 4),
            "cpu_geomean_s": round(cpu_geo, 4),
            "per_query": per_query,
        },
    }


def _bench_mortgage_ml(scale: float, iters: int) -> dict:
    """BASELINE config 4: the Mortgage ETL pipeline ending at the
    ML-integration boundary cut — executed-plan batches handed over as
    device-resident jax arrays (the ColumnarRdd zero-copy export role),
    ready for an XGBoost-style consumer. Throughput = ETL input rows/s
    through to the device feature arrays."""
    from spark_rapids_tpu import ml
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.mortgage import (clean_acquisition_prime,
                                                      gen_acquisition,
                                                      gen_performance)
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF

    perf = gen_performance(scale=scale, seed=42)
    acq = gen_acquisition(scale=scale, seed=42)
    n_rows = perf.num_rows + acq.num_rows
    cpu_sess = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.enabled": "false"})

    def cpu_run():
        df = clean_acquisition_prime(cpu_sess.create_dataframe(perf),
                                     cpu_sess.create_dataframe(acq))
        return df.collect().num_rows

    cpu_rows = cpu_run()              # warm (identical treatment)
    cpu_s = None
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        cpu_rows = cpu_run()
        dt = time.perf_counter() - t0
        cpu_s = dt if cpu_s is None else min(cpu_s, dt)
    sess = TpuSession(BENCH_CONF)

    def run():
        df = clean_acquisition_prime(sess.create_dataframe(perf),
                                     sess.create_dataframe(acq))
        arrays = ml.device_arrays(df)
        # touch one scalar per column: the handoff must be materialized
        for arrs in arrays.values():
            _hard_sync(arrs[0])
        rows = next(iter(arrays.values()))[0].shape[0] if arrays else 0
        return rows, len(arrays)

    rows_out, ncols = run()          # warm (compiles + scan cache)
    assert rows_out == cpu_rows, f"row mismatch: {rows_out} != {cpu_rows}"
    best = None
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        rows_out, ncols = run()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    rps = n_rows / best
    return {"metric": "mortgage_etl_to_ml_rows_per_sec", "value": round(rps),
            "unit": "rows/s", "vs_baseline": round(cpu_s / best, 3),
            "breakdown": {"input_rows": n_rows, "feature_rows": rows_out,
                          "feature_columns": ncols,
                          "etl_plus_handoff_s": round(best, 4),
                          "cpu_engine_s": round(cpu_s, 4)}}


def _bench_udf_q1(scale: float, iters: int) -> dict:
    """BASELINE config 5: a row UDF compiled to columnar expressions riding
    the normal acceleration path on a TPC-H Q1-shaped aggregation, vs the
    same UDF on the row-at-a-time fallback."""
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem
    from spark_rapids_tpu.columnar.dtypes import DType

    table = gen_lineitem(scale=scale, seed=42)
    n_rows = table.num_rows

    def charge(price, tax):
        return price * (1.0 + tax)

    def q(sess):
        u = F.udf(charge, DType.DOUBLE)
        df = sess.create_dataframe(table)
        import datetime
        cutoff = datetime.date(1998, 9, 2)
        return (df.filter(F.col("l_shipdate") <= F.lit(cutoff))
                  .groupBy("l_returnflag", "l_linestatus")
                  .agg(F.sum(u(F.col("l_extendedprice"),
                               F.col("l_tax"))).alias("sum_charge"),
                       F.count(F.lit(1)).alias("cnt")))

    compiled = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.udfCompiler.enabled":
                               "true"})
    fallback = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.udfCompiler.enabled":
                               "false"})
    from spark_rapids_tpu.testing import assert_tables_equal
    ref = q(fallback).collect()
    out = q(compiled).collect()     # warm
    # values must MATCH, not just counts — a miscompiled UDF would otherwise
    # publish numbers for a wrong (or never-taken) path
    assert_tables_equal(ref, out, ignore_order=True, approx_float=1e-9)
    plan = compiled.last_plan.tree_string()
    assert "PythonUDF" not in plan, (
        f"UDF was not compiled to columnar expressions:\n{plan}")
    best = None
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        out = q(compiled).collect()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    # identical treatment: fallback is warm (ref run) and takes best-of-iters
    fb = None
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        q(fallback).collect()
        dt = time.perf_counter() - t0
        fb = dt if fb is None else min(fb, dt)
    rps = n_rows / best
    return {"metric": "udf_compiled_q1_rows_per_sec", "value": round(rps),
            "unit": "rows/s", "vs_baseline": round(fb / best, 3),
            "breakdown": {"rows": n_rows, "compiled_s": round(best, 4),
                          "row_fallback_s": round(fb, 4)}}


def main() -> None:
    suite = os.environ.get("BENCH_SUITE", "tpch")
    default_scale = {"tpch": "1.0", "tpcds": "0.5", "mortgage": "0.02",
                     "udf": "0.2"}.get(suite, "0.05")
    scale = float(os.environ.get("BENCH_SCALE", default_scale))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    if suite == "tpch":
        out = _bench_tpch_q1(scale, iters)
    elif suite == "tpch_cold":
        out = _bench_tpch_cold(scale, iters)
    elif suite == "tpcds":
        out = _bench_query_suite("tpcds", scale, iters)
    elif suite == "tpcxbb_suite":
        out = _bench_query_suite("tpcxbb", scale, iters)
    elif suite == "tpcxbb":
        out = _bench_tpcxbb(scale, os.environ.get("BENCH_QUERY", "q5"),
                            iters)
    elif suite == "mortgage":
        out = _bench_mortgage_ml(scale, iters)
    elif suite == "udf":
        out = _bench_udf_q1(scale, iters)
    else:
        raise SystemExit(f"unknown BENCH_SUITE {suite!r} "
                         "(tpch | tpch_cold | tpcds | tpcxbb | "
                         "tpcxbb_suite | mortgage | udf)")
    _flag_regression(out)
    print(json.dumps(out))


def _flag_regression(out: dict) -> None:
    """Regression guard (round-4 VERDICT weak-4): compare this run's value
    against the most recent recorded round's JSON for the same metric and
    flag a >20% drop in the breakdown (stderr too, for nightly logs)."""
    import glob
    import re
    prior, prior_round = None, -1
    for path in glob.glob(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m or int(m.group(1)) <= prior_round:
            continue
        try:
            with open(path) as f:
                rec = json.load(f).get("parsed") or {}
        except (OSError, ValueError):
            continue
        if rec.get("metric") == out.get("metric"):
            prior, prior_round = rec, int(m.group(1))
    if not prior or not prior.get("value"):
        return
    ratio = out["value"] / prior["value"]
    # seconds-valued metrics are lower-is-better: normalize the ratio to
    # "improvement factor" so the 0.8 gate means the same thing everywhere
    if out.get("unit") in ("s", "seconds"):
        ratio = 1.0 / ratio if ratio else 0.0
    bd = out.setdefault("breakdown", {})
    bd["vs_round"] = prior_round
    bd["vs_round_ratio"] = round(ratio, 3)
    if ratio < 0.8:
        bd["regression_flag"] = (f">20% below round {prior_round} "
                                 f"({prior['value']} -> {out['value']})")
        print(f"[bench] REGRESSION: {bd['regression_flag']}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    sys.exit(main())
