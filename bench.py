"""Benchmark entry point (driver contract: prints ONE JSON line).

Default: TPC-H Q1 (scan -> fused filter+aggregate -> sort) on the TPU engine
end-to-end, compared against the CPU engine (eager numpy, the stand-in for
CPU Spark in the reference's 4x-typical claim, docs/FAQ.md:66).
BENCH_SUITE=tpcxbb switches to the reference's headline TPCx-BB family
(BASELINE.md config 1); its multi-join plans sync per join phase, so over a
high-latency chip tunnel the default stays on the single-pipeline Q1.

Env knobs: BENCH_SUITE (tpch | tpcxbb, default tpch), BENCH_QUERY (query
name within the tpcxbb suite), BENCH_SCALE (table scale factor), BENCH_ITERS
(timed iterations after the compile warmup, default 3).
"""
import json
import os
import sys
import time


def _bench_tpch(scale: float):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem, q1

    table = gen_lineitem(scale=scale, seed=42)
    # lineitem's flag/status strings are 1 byte; a narrow device string width
    # cuts the byte-matrix staging/upload/compute by 16x vs the 256 default
    conf = {**BENCH_CONF, "spark.rapids.tpu.sql.string.maxBytes": "16"}
    tpu_sess = TpuSession(conf)
    cpu_sess = TpuSession({**conf,
                           "spark.rapids.tpu.sql.enabled": "false"})
    run_tpu = lambda: q1(tpu_sess.create_dataframe(table)).collect()  # noqa: E731
    run_cpu = lambda: q1(cpu_sess.create_dataframe(table)).collect()  # noqa: E731
    return "tpch_q1", table.num_rows, run_tpu, run_cpu


def _bench_tpcxbb(scale: float, qname: str):
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all
    from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES

    tables = gen_all(scale=scale, seed=42)
    query = QUERIES[qname]
    n_rows = (tables["web_clickstreams"].num_rows if qname == "q5"
              else sum(v.num_rows for v in tables.values()))
    tpu_sess = TpuSession(BENCH_CONF)
    cpu_sess = TpuSession({**BENCH_CONF,
                           "spark.rapids.tpu.sql.enabled": "false"})
    tpu_t = {k: tpu_sess.create_dataframe(v) for k, v in tables.items()}
    cpu_t = {k: cpu_sess.create_dataframe(v) for k, v in tables.items()}
    return (f"tpcxbb_{qname}", n_rows,
            lambda: query(tpu_t).collect(), lambda: query(cpu_t).collect())


def main() -> None:
    suite = os.environ.get("BENCH_SUITE", "tpch")
    # tpch default: 6M lineitem rows — large enough that per-dispatch link
    # latency amortizes and the device's throughput advantage over the eager
    # CPU engine shows. The tpcxbb tables stay small (19-table multi-join).
    default_scale = "1.0" if suite == "tpch" else "0.05"
    scale = float(os.environ.get("BENCH_SCALE", default_scale))
    iters = int(os.environ.get("BENCH_ITERS", "3"))

    if suite == "tpch":
        name, n_rows, run_tpu, run_cpu = _bench_tpch(scale)
    elif suite == "tpcxbb":
        qname = os.environ.get("BENCH_QUERY", "q5")
        name, n_rows, run_tpu, run_cpu = _bench_tpcxbb(scale, qname)
    else:
        raise SystemExit(f"unknown BENCH_SUITE {suite!r} (tpch | tpcxbb)")

    # CPU baseline first: the remote-device client's background threads would
    # otherwise steal host CPU from the single-core numpy run
    t0 = time.perf_counter()
    cpu_result = run_cpu()
    cpu_time = time.perf_counter() - t0

    tpu_result = run_tpu()  # warmup (compile)

    t0 = time.perf_counter()
    for _ in range(iters):
        run_tpu()
    tpu_time = (time.perf_counter() - t0) / iters

    assert tpu_result.num_rows == cpu_result.num_rows, (
        f"result mismatch: {tpu_result.num_rows} vs {cpu_result.num_rows}")

    tpu_rps = n_rows / tpu_time
    cpu_rps = n_rows / cpu_time
    print(json.dumps({
        "metric": f"{name}_rows_per_sec",
        "value": round(tpu_rps),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
