"""Benchmark entry point (driver contract: prints ONE JSON line).

Runs TPC-H Q1 over generated lineitem data end-to-end (host staging -> device
upload -> fused filter+aggregate+sort on TPU -> download) and compares against
the CPU engine (eager numpy, the stand-in for CPU Spark — the reference's
baseline in its 4x-typical-speedup claim, docs/FAQ.md:66).
"""
import json
import os
import sys
import time


def main() -> None:
    scale = float(os.environ.get("BENCH_SCALE", "0.05"))  # 300k rows default
    iters = int(os.environ.get("BENCH_ITERS", "5"))

    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem, q1
    from spark_rapids_tpu.api import TpuSession

    table = gen_lineitem(scale=scale, seed=42)
    n_rows = table.num_rows

    tpu_sess = TpuSession(BENCH_CONF)
    cpu_sess = TpuSession({**BENCH_CONF, "spark.rapids.tpu.sql.enabled": "false"})

    # warmup (compile)
    tpu_result = q1(tpu_sess.create_dataframe(table)).collect()

    t0 = time.perf_counter()
    for _ in range(iters):
        q1(tpu_sess.create_dataframe(table)).collect()
    tpu_time = (time.perf_counter() - t0) / iters

    t0 = time.perf_counter()
    cpu_result = q1(cpu_sess.create_dataframe(table)).collect()
    cpu_time = time.perf_counter() - t0

    # sanity: same group count
    assert tpu_result.num_rows == cpu_result.num_rows, (
        f"result mismatch: {tpu_result.num_rows} vs {cpu_result.num_rows}")

    tpu_rps = n_rows / tpu_time
    cpu_rps = n_rows / cpu_time
    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec",
        "value": round(tpu_rps),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
