import pytest

from spark_rapids_tpu import config
from spark_rapids_tpu.config import TpuConf


def test_defaults():
    conf = TpuConf()
    assert conf.sql_enabled is True
    assert conf.explain == "NONE"
    assert conf.batch_size_bytes == 1 << 31
    assert conf.concurrent_tpu_tasks == 2


def test_overrides_and_conversion():
    conf = TpuConf({
        "spark.rapids.tpu.sql.enabled": "false",
        "spark.rapids.tpu.sql.explain": "NOT_ON_TPU",
        "spark.rapids.tpu.sql.concurrentTpuTasks": "4",
    })
    assert conf.sql_enabled is False
    assert conf.explain == "NOT_ON_TPU"
    assert conf.concurrent_tpu_tasks == 4


def test_checker_rejects_bad_values():
    with pytest.raises(ValueError):
        TpuConf({"spark.rapids.tpu.sql.concurrentTpuTasks": "0"})
    with pytest.raises(ValueError):
        TpuConf({"spark.rapids.tpu.memory.tpu.allocFraction": "1.5"})


def test_rule_enable_keys_pass_through():
    conf = TpuConf({"spark.rapids.tpu.sql.expression.Add": "false"})
    assert conf.is_rule_enabled("spark.rapids.tpu.sql.expression.Add") is False
    assert conf.is_rule_enabled("spark.rapids.tpu.sql.expression.Subtract") is True


def test_doc_generation_covers_all_public_keys():
    docs = config.generate_docs()
    for entry in config.all_entries():
        if not entry.internal:
            assert entry.key in docs
