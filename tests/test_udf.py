"""UDF tests: row-wise fallback + bytecode-compiled columnar path
(udf-compiler OpcodeSuite analog)."""
import math

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import BoundReference
from spark_rapids_tpu.testing import assert_tables_equal
from spark_rapids_tpu.udf import UdfCompileError, compile_udf

COMPILE = {"spark.rapids.tpu.sql.udfCompiler.enabled": "true",
           "spark.rapids.tpu.sql.incompatibleOps.enabled": "true"}


def _cols(*dts):
    return tuple(BoundReference(i, dt, True) for i, dt in enumerate(dts))


def run_both(build, approx=None):
    """fallback result == compiled result, and the compiled plan is on TPU."""
    s_fb = TpuSession()
    fb = build(s_fb).collect()
    assert "no TPU implementation" in s_fb.last_explain
    s_c = TpuSession(COMPILE)
    comp = build(s_c).collect()
    assert "TpuProjectExec" in s_c.last_plan.tree_string(), s_c.last_explain
    assert_tables_equal(fb, comp, approx_float=approx)
    return comp


def test_arithmetic_and_branches():
    t = pa.table({"x": pa.array([1.0, -2.5, 0.5, 4.0]),
                  "y": pa.array([2.0, 3.0, 1.0, -1.0])})

    @F.udf(returnType="double")
    def f(x, y):
        if x > 0:
            return x + y * 2.0
        return abs(x - 1) if y > 0 else 0.0

    out = run_both(lambda s: s.create_dataframe(t).select(f("x", "y").alias("r")))
    assert out.column("r").to_pylist() == [5.0, 3.5, 2.5, 2.0]


def test_boolean_ops_and_comparisons():
    t = pa.table({"a": pa.array([1, 5, 10], type=pa.int64()),
                  "b": pa.array([2, 2, 2], type=pa.int64())})

    @F.udf(returnType="boolean")
    def g(a, b):
        return (a > b and a < 8) or a == 10

    out = run_both(lambda s: s.create_dataframe(t).select(g("a", "b").alias("r")))
    assert out.column("r").to_pylist() == [False, True, True]


def test_math_functions():
    t = pa.table({"x": pa.array([1.0, 4.0, 9.0])})

    @F.udf(returnType="double")
    def h(x):
        return math.sqrt(x) + math.log(x) - math.pow(x, 0.5)

    out = run_both(lambda s: s.create_dataframe(t).select(h("x").alias("r")),
                   approx=1e-9)
    assert out.column("r").to_pylist() == pytest.approx(
        [0.0, math.log(4.0), math.log(9.0)], abs=1e-9)


def test_min_max_round():
    t = pa.table({"x": pa.array([1.4, 2.6]), "y": pa.array([2.0, 1.0])})

    @F.udf(returnType="double")
    def m(x, y):
        return min(x, y) + max(x, y) + round(x)

    run_both(lambda s: s.create_dataframe(t).select(m("x", "y").alias("r")))


def test_string_methods_and_none_guard():
    t = pa.table({"s": pa.array(["a", "Bc", None, " d "])})

    @F.udf(returnType="string")
    def up(s):
        return s.upper() if s is not None else None

    @F.udf(returnType="boolean")
    def pref(s):
        return s.startswith("B") if s is not None else None

    out = run_both(lambda s: s.create_dataframe(t).select(
        up("s").alias("u"), pref("s").alias("p")))
    assert out.column("u").to_pylist() == ["A", "BC", None, " D "]
    assert out.column("p").to_pylist() == [False, True, None, False]


def test_in_tuple_and_len():
    t = pa.table({"a": pa.array([1, 2, 3], type=pa.int64()),
                  "s": pa.array(["ab", "c", "defg"])})

    @F.udf(returnType="boolean")
    def isin(a):
        return a in (1, 3)

    @F.udf(returnType="int")
    def slen(s):
        return len(s) if s is not None else None

    out = run_both(lambda s: s.create_dataframe(t).select(
        isin("a").alias("i"), slen("s").alias("n")))
    assert out.column("i").to_pylist() == [True, False, True]
    assert out.column("n").to_pylist() == [2, 1, 4]


def test_declared_return_type_cast():
    t = pa.table({"a": pa.array([1, 2], type=pa.int64())})

    @F.udf(returnType="long")
    def double_it(a):
        return a * 2

    out = run_both(lambda s: s.create_dataframe(t).select(
        double_it("a").alias("r")))
    assert out.schema.field("r").type == pa.int64()


def test_udf_in_filter_and_agg_pipeline():
    t = pa.table({"a": pa.array([1, 2, 3, 4], type=pa.int64()),
                  "g": pa.array(["x", "y", "x", "y"])})

    @F.udf(returnType="boolean")
    def keep(a):
        return a % 2 == 0

    s = TpuSession(COMPILE)
    out = (s.create_dataframe(t).filter(keep("a"))
           .groupBy("g").agg(F.sum("a").alias("sa")).sort("g").collect())
    # evens are 2 (g=y) and 4 (g=y)
    assert out.column("g").to_pylist() == ["y"]
    assert out.column("sa").to_pylist() == [6]
    # the compiled-UDF filter fuses into the device aggregation
    assert "TpuHashAggregateExec" in s.last_plan.tree_string()
    assert "CpuFilterExec" not in s.last_plan.tree_string()


def test_uncompilable_falls_back():
    t = pa.table({"a": pa.array([3, 4], type=pa.int64())})

    @F.udf(returnType="long")
    def looped(a):
        total = 0
        for i in range(3):
            total += a
        return total

    s = TpuSession(COMPILE)
    out = s.create_dataframe(t).select(looped("a").alias("r")).collect()
    # loop -> UdfCompileError -> row-wise fallback, still correct
    assert out.column("r").to_pylist() == [9, 12]
    assert "no TPU implementation" in s.last_explain


def test_compile_errors_direct():
    def loop_fn(a):
        total = 0
        for i in (1, 2):
            total += a
        return total

    with pytest.raises(UdfCompileError, match="not supported"):
        compile_udf(loop_fn, _cols(DType.LONG))
    with pytest.raises(UdfCompileError, match="closures|defaults"):
        y = 3
        compile_udf(lambda a: a + y, _cols(DType.LONG))
    with pytest.raises(UdfCompileError, match="takes"):
        compile_udf(lambda a, b: a, _cols(DType.LONG))


def test_is_none_compiles():
    t = pa.table({"x": pa.array([1.0, None, 3.0])})

    @F.udf(returnType="double")
    def nz(x):
        return 0.0 if x is None else x

    out = run_both(lambda s: s.create_dataframe(t).select(nz("x").alias("r")))
    assert out.column("r").to_pylist() == [1.0, 0.0, 3.0]
