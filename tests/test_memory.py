"""Memory layer tests: native allocator/queue, tiered spill stores, semaphore
(RapidsDeviceMemoryStoreSuite / RapidsHostMemoryStoreSuite / RapidsDiskStoreSuite /
RapidsBufferCatalogSuite / GpuSemaphoreSuite / AddressSpaceAllocatorSuite analog)."""
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DeviceBatch
from spark_rapids_tpu.memory import (BufferCatalog, BufferId, StorageTier,
                                     TpuSemaphore, build_store_chain)
from spark_rapids_tpu.native import AddressSpaceAllocator, HashedPriorityQueue
from spark_rapids_tpu.testing import assert_tables_equal


# ---------------------------------------------------------------- native layer
def test_allocator_first_fit_and_coalescing():
    a = AddressSpaceAllocator(1000)
    o1, o2, o3 = a.allocate(100), a.allocate(200), a.allocate(300)
    assert (o1, o2, o3) == (0, 100, 300)
    assert a.available == 400
    a.free(o2)
    assert a.num_free_blocks == 2
    assert a.allocate(150) == 100       # first fit reuses the hole
    a.free(o1), a.free(o3), a.free(100)
    assert a.available == 1000 and a.num_free_blocks == 1  # fully coalesced
    assert a.allocate(2000) is None
    a.close()


def test_allocator_fragmentation():
    a = AddressSpaceAllocator(300)
    offs = [a.allocate(100) for _ in range(3)]
    a.free(offs[0]); a.free(offs[2])
    assert a.available == 200
    assert a.largest_free_block == 100
    assert a.allocate(150) is None      # fragmented: no single block fits
    a.close()


def test_priority_queue_order_update_remove():
    q = HashedPriorityQueue()
    for k, p in [(1, 5.0), (2, 1.0), (3, 3.0)]:
        assert q.offer(k, p)
    assert not q.offer(1, 0.5)          # update, not insert
    assert q.poll() == (1, 0.5)
    assert q.peek() == (2, 1.0)
    assert q.remove(2)
    assert q.poll() == (3, 3.0)
    assert q.poll() is None
    q.close()


def test_priority_queue_fifo_among_equal():
    q = HashedPriorityQueue()
    for k in range(5):
        q.offer(k, 1.0)
    assert [q.poll()[0] for _ in range(5)] == [0, 1, 2, 3, 4]
    q.close()


# ---------------------------------------------------------------- spill tiers
def make_batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    t = pa.table({"x": pa.array(rng.integers(0, 100, n), type=pa.int64()),
                  "s": pa.array([f"row{i}" for i in range(n)])})
    return t, DeviceBatch.from_arrow(t, string_max_bytes=16)


def test_spill_chain_device_to_host_to_disk(tmp_path):
    catalog = BufferCatalog()
    per_batch, b0 = None, None
    t0, b = make_batch(64, 0)
    per_batch = b.device_size_bytes
    device, host, disk = build_store_chain(
        catalog, device_budget=per_batch * 2 + 10,
        host_budget=per_batch * 2 + 10, disk_dir=str(tmp_path))

    tables = {}
    for i in range(5):
        t, batch = make_batch(64, i)
        tables[i] = t
        device.add_batch(BufferId(i), batch, spill_priority=float(i))
    # budget 2 batches on device, 2 on host, rest on disk
    assert len(device) == 2 and len(host) == 2 and len(disk) == 1
    # coldest (lowest priority = oldest ids) spilled furthest
    buf = catalog.acquire(BufferId(0))
    assert buf.tier == StorageTier.DISK
    got = buf.get_batch().to_arrow()
    assert_tables_equal(tables[0], got)   # round-trip through disk
    buf.close()
    buf4 = catalog.acquire(BufferId(4))
    assert buf4.tier == StorageTier.DEVICE
    buf4.close()


def test_handle_oom_spills(tmp_path):
    catalog = BufferCatalog()
    t, b = make_batch(64, 0)
    size = b.device_size_bytes
    device, host, disk = build_store_chain(catalog, size * 10, size * 10,
                                           str(tmp_path))
    for i in range(3):
        _, batch = make_batch(64, i)
        device.add_batch(BufferId(i), batch)
    spilled = device.handle_oom(size * 2)
    assert spilled >= size * 2
    assert len(host) >= 2


def test_catalog_acquire_refcount():
    catalog = BufferCatalog()
    t, b = make_batch(16, 1)
    from spark_rapids_tpu.memory.buffer import SpillableBuffer
    buf = SpillableBuffer.from_batch(BufferId(7), b)
    catalog.register(buf)
    acq = catalog.acquire(BufferId(7))
    assert acq is buf and buf.refcount == 2
    acq.close()
    assert buf.refcount == 1
    assert catalog.acquire(BufferId(99)) is None


# ---------------------------------------------------------------- semaphore
def test_semaphore_limits_and_reentrancy():
    sem = TpuSemaphore(2)
    assert sem.acquire_if_necessary(task_id=1)
    assert sem.acquire_if_necessary(task_id=1)   # re-entrant, no double hold
    assert sem.active_holders == 1
    assert sem.acquire_if_necessary(task_id=2)
    assert not sem.acquire_if_necessary(task_id=3, timeout=0.05)
    sem.release_if_necessary(task_id=1)
    assert sem.acquire_if_necessary(task_id=3, timeout=1.0)
    sem.release_if_necessary(task_id=2)
    sem.release_if_necessary(task_id=3)
    assert sem.active_holders == 0


def test_semaphore_concurrent_tasks():
    sem = TpuSemaphore(2)
    peak = [0]
    active = [0]
    lock = threading.Lock()

    def work(tid):
        with sem.held(task_id=tid):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            import time
            time.sleep(0.02)
            with lock:
                active[0] -= 1

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert peak[0] <= 2


def test_buffer_id_range_check():
    with pytest.raises(ValueError):
        BufferId(0, 1 << 20)
    with pytest.raises(ValueError):
        BufferId(-1, 0)


def test_catalog_remove_store_owned(tmp_path):
    # regression (code review): catalog.remove must route through the owning
    # store so spill bookkeeping stays consistent
    catalog = BufferCatalog()
    t, b = make_batch(32, 0)
    device, host, disk = build_store_chain(catalog, 1 << 30, 1 << 30,
                                           str(tmp_path))
    device.add_batch(BufferId(1), b)
    assert len(device) == 1
    catalog.remove(BufferId(1))
    assert len(device) == 0 and device.used_bytes == 0
    assert catalog.acquire(BufferId(1)) is None


def test_semaphore_shared_task_id_no_permit_leak():
    # regression (code review): concurrent same-task acquires must not leak
    sem = TpuSemaphore(2)
    barrier = threading.Barrier(4)

    def work():
        barrier.wait()
        sem.acquire_if_necessary(task_id=5)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for th in threads: th.start()
    for th in threads: th.join()
    sem.release_if_necessary(task_id=5)
    assert sem.active_holders == 0
    # both permits must still be usable
    assert sem.acquire_if_necessary(task_id=1, timeout=0.1)
    assert sem.acquire_if_necessary(task_id=2, timeout=0.1)
    assert not sem.acquire_if_necessary(task_id=3, timeout=0)  # try-acquire


def test_host_arena_fragmentation_spills(tmp_path):
    # regression (code review): fragmented host arena spills to disk, not error
    catalog = BufferCatalog()
    t, b = make_batch(64, 0)
    size = b.device_size_bytes
    # host arena holds ~2 buffers
    device, host, disk = build_store_chain(catalog, size, int(size * 2.5),
                                           str(tmp_path))
    for i in range(6):
        _, batch = make_batch(64, i)
        device.add_batch(BufferId(i), batch)
    # everything still reachable
    for i in range(6):
        buf = catalog.acquire(BufferId(i))
        assert buf is not None
        buf.close()


def test_double_spill_is_compact_and_bit_exact(tmp_path):
    """Regression (code review): DOUBLE columns with a u64 bits sibling spill
    ONLY the bits (half the footprint), and survive host AND disk tiers
    bit-exactly — including NaN payloads and -0.0."""
    import math
    import struct
    vals = [1.5, -0.0, float("nan"), 1e-308, -math.inf, 3.141592653589793]
    t = pa.table({"d": pa.array(vals, type=pa.float64())})
    b = DeviceBatch.from_arrow(t, string_max_bytes=16)
    from spark_rapids_tpu.memory.buffer import SpillableBuffer, StorageTier
    buf = SpillableBuffer.from_batch(BufferId(991), b)
    has_bits = any(buf.bits_mask)
    host = buf.to_host()
    if has_bits:
        # compact layout: one u64 array + one validity per column, no f64 copy
        assert len(host.payload) == 2
        assert host.payload[0].dtype == np.uint64
    disk = host.to_disk(str(tmp_path))

    def bits_of(table):
        col = table.column("d").to_pylist()
        return [None if v is None else struct.pack("<d", v) for v in col]

    want = bits_of(t)
    for tier_buf in (host, disk):
        got_dev = bits_of(tier_buf.get_batch().to_arrow())
        got_host = bits_of(tier_buf.get_host_batch().to_arrow())
        assert got_dev == want, tier_buf.tier
        assert got_host == want, tier_buf.tier


def test_spill_carries_dictionary_encoding_host_and_disk(tmp_path):
    """Regression (PR 5 leftover): SpillableBuffer used to DROP column
    encodings on spill, so an unspilled batch decoded instead of re-entering
    the encoded domain. The descriptor must survive device -> host -> disk
    and rebuild as a live DictEncoding on unspill."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
    from spark_rapids_tpu.columnar.encoding import DictEncoding, enc_specs_of
    from spark_rapids_tpu.memory.buffer import SpillableBuffer, StorageTier

    cap, n, k = 32, 20, 3
    rng = np.random.default_rng(3)
    idx = jnp.asarray(rng.integers(0, k, cap).astype(np.int32))
    vals = jnp.asarray(np.array([11, 22, 33, 0, 0, 0, 0, 0], np.int64))
    enc = DictEncoding(idx, vals, k, None, "tok-spill")
    data = jnp.take(vals, idx)
    valid = jnp.asarray(np.arange(cap) < n)
    schema = Schema([Field("e", DType.LONG), Field("p", DType.LONG)])
    b = DeviceBatch(schema, (DeviceColumn(DType.LONG, data, valid,
                                          encoding=enc),
                             DeviceColumn(DType.LONG, data, valid)), n)
    buf = SpillableBuffer.from_batch(BufferId(992), b)

    host = buf.to_host()
    assert host.tier is StorageTier.HOST
    disk = host.to_disk(str(tmp_path))
    assert disk.tier is StorageTier.DISK
    for tier_buf in (host, disk):
        back = tier_buf.get_batch()
        e2 = back.columns[0].encoding
        assert e2 is not None, tier_buf.tier
        assert e2.token == "tok-spill" and e2.k_real == k
        assert np.array_equal(np.asarray(e2.indices), np.asarray(idx))
        assert np.array_equal(np.asarray(e2.values), np.asarray(vals))
        assert back.columns[1].encoding is None
        # the unspilled batch is eligible for encoded-domain execution again
        assert [s.ordinal for s in enc_specs_of(back)] == [0]
        # and the decoded payload itself is intact
        assert np.array_equal(np.asarray(back.columns[0].data)[:n],
                              np.asarray(data)[:n])


def test_spill_encoding_string_dictionary_roundtrip(tmp_path):
    """String dictionaries carry the [k, width] byte matrix + per-entry
    lengths through the host and disk tiers."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
    from spark_rapids_tpu.columnar.encoding import DictEncoding
    from spark_rapids_tpu.memory.buffer import SpillableBuffer

    cap, n, k, width = 16, 12, 2, 8
    idx = jnp.asarray((np.arange(cap) % k).astype(np.int32))
    mat = np.zeros((4, width), np.uint8)
    mat[0, :3] = list(b"foo")
    mat[1, :4] = list(b"barx")
    lens = jnp.asarray(np.array([3, 4, 0, 0], np.int32))
    vals = jnp.asarray(mat)
    enc = DictEncoding(idx, vals, k, lens, "tok-str")
    data = jnp.take(vals, idx, axis=0)
    row_lens = jnp.take(lens, idx)
    valid = jnp.asarray(np.arange(cap) < n)
    schema = Schema([Field("s", DType.STRING)])
    b = DeviceBatch(schema, (DeviceColumn(DType.STRING, data, valid, row_lens,
                                          encoding=enc),), n)
    disk = SpillableBuffer.from_batch(BufferId(993), b).to_host().to_disk(
        str(tmp_path))
    back = disk.get_batch()
    e2 = back.columns[0].encoding
    assert e2 is not None and e2.token == "tok-str"
    assert np.array_equal(np.asarray(e2.values), mat)
    assert np.array_equal(np.asarray(e2.lengths), np.asarray(lens))
    assert back.to_arrow().equals(b.to_arrow())
