"""Generate (explode/posexplode) tests — generate_expr pytest analog.

Scope mirrors the reference's v0 GpuGenerateExec: explode/posexplode of a
created array or array literal only, no outer (GpuGenerateExec.scala:66-80)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def base_table():
    return pa.table({
        "a": pa.array([1, 2, None], type=pa.int64()),
        "b": pa.array([10, 20, 30], type=pa.int64()),
        "s": pa.array(["x", "y", "z"]),
    })


def test_explode_created_array():
    t = base_table()
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "s", F.explode(F.array("a", "b")).alias("v")),
        ignore_order=True,
        expect_tpu_execs=["TpuGenerateExec"])
    assert cpu.num_rows == 6


def test_explode_golden_order():
    t = base_table()
    s = TpuSession()
    out = (s.create_dataframe(t)
           .select("s", F.explode(F.array("a", "b")).alias("v"))
           .sort("s", "v").collect())
    assert out.column("s").to_pylist() == ["x", "x", "y", "y", "z", "z"]
    # null sorts last within s="z" on arrow sort; check as sets per key
    assert out.column("v").to_pylist()[:4] == [1, 10, 2, 20]
    assert set(out.column("v").to_pylist()[4:]) == {30, None}


def test_posexplode_literal_list_with_null():
    t = base_table()
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "b", F.posexplode([100, None, 300])),
        ignore_order=True,
        expect_tpu_execs=["TpuGenerateExec"])
    assert cpu.num_rows == 9
    assert cpu.column_names == ["b", "pos", "col"]


def test_explode_mixed_types_common_type():
    t = base_table()
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.explode(F.array(F.col("a"), F.lit(0.5))).alias("v")),
        ignore_order=True)
    assert str(cpu.schema.field("v").type) == "double"


def test_explode_strings():
    t = base_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "a", F.explode(F.array(F.col("s"), F.lit("w"))).alias("v")),
        ignore_order=True,
        expect_tpu_execs=["TpuGenerateExec"])


def test_explode_then_aggregate():
    t = base_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .select(F.explode(F.array("a", "b")).alias("v"))
                   .groupBy("v").agg(F.count().alias("n"))),
        ignore_order=True,
        expect_tpu_execs=["TpuGenerateExec", "TpuHashAggregateExec"])


def test_explode_expressions_as_elements():
    t = base_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "b", F.explode(F.array(F.col("a") + F.lit(1),
                                   F.col("b") * F.lit(2))).alias("v")),
        ignore_order=True)


def test_two_generators_rejected():
    t = base_table()
    s = TpuSession()
    with pytest.raises(ValueError, match="one generator"):
        s.create_dataframe(t).select(F.explode(F.array("a")),
                                     F.explode(F.array("b")))


def test_explode_requires_created_array():
    with pytest.raises(ValueError, match="array"):
        F.explode(F.col("a"))


def test_explode_empty_input():
    t = base_table().slice(0, 0)
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.explode(F.array("a", "b")).alias("v")),
        ignore_order=True)
    assert cpu.num_rows == 0
