"""Tooling tests: shim loader, api-validation parity, config doc generation
(ShimLoader / ApiValidation / RapidsConf.help analog coverage)."""
import pathlib

from spark_rapids_tpu import api_validation, config, shims


def test_shim_loader_picks_provider():
    s = shims.get()
    assert isinstance(s, shims.JaxShims)
    import jax
    assert type(s).version_match(jax.__version__)


def test_shim_provider_selection_logic():
    assert shims.Jax05PlusShims.version_match("0.9.0")
    assert shims.Jax05PlusShims.version_match("0.5.1")
    assert not shims.Jax05PlusShims.version_match("0.4.30")
    assert shims.Jax04Shims.version_match("0.4.30")
    assert not shims.Jax04Shims.version_match("0.5.0")


def test_shim_rng_and_mesh_work():
    import jax
    s = shims.get()
    key = s.prng_key(7)
    v = jax.random.uniform(key, (3,))
    assert v.shape == (3,)
    assert s.tree_map(lambda x: x + 1, {"a": 1})["a"] == 2
    m = s.make_mesh(jax.devices()[:1], ("data",))
    assert m.axis_names == ("data",)


def test_exec_constructor_parity():
    """ApiValidation.scala analog: every Cpu/Tpu exec pair must agree on
    constructor parameters (conversion rules copy fields across)."""
    problems = api_validation.validate()
    assert not problems, "\n".join(problems)
    assert len(api_validation.exec_pairs()) >= 15


def test_config_docs_current():
    """docs/configs.md must match the registry (the reference regenerates
    docs/configs.md from RapidsConf and CI diffs it)."""
    path = pathlib.Path(__file__).resolve().parent.parent / "docs" / "configs.md"
    assert path.exists(), "run: python -m spark_rapids_tpu.config docs/configs.md"
    assert path.read_text() == config.generate_docs(), (
        "docs/configs.md is stale; regenerate with "
        "python -m spark_rapids_tpu.config docs/configs.md")
