"""Batch kernel tests: compaction, multi-key sort, grouping, segment reduction —
numpy eager vs jitted jax parity (analog of SortExecSuite / GpuCoalesceBatchesSuite
internals)."""
import numpy as np
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import DeviceBatch
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.ops import batch_kernels as bk


def colvs_np(table, smax=32):
    hb = HostBatch.from_arrow(table, smax)
    return [ColV(c.dtype, c.data, c.validity, c.lengths) for c in hb.columns], hb


def test_compact_stable():
    t = pa.table({"a": pa.array([10, 20, 30, 40, 50], type=pa.int64())})
    cols, hb = colvs_np(t)
    mask = np.array([True, False, True, False, True])
    out, n = bk.compact(np, mask, cols, 5)
    assert int(n) == 3
    assert out[0].data[:3].tolist() == [10, 30, 50]
    assert out[0].validity[:3].all() and not out[0].validity[3:].any()


def test_compact_jit_matches():
    data = np.arange(16, dtype=np.int64)
    valid = np.ones(16, dtype=bool)
    mask = (data % 3 == 0)
    col = ColV(__import__("spark_rapids_tpu.columnar.dtypes",
                          fromlist=["DType"]).DType.LONG, data, valid)
    out_np, n_np = bk.compact(np, mask, [col], 16)

    @jax.jit
    def f(d, v, m):
        c = ColV(col.dtype, d, v)
        out, n = bk.compact(jnp, m, [c], 16)
        return out[0].data, out[0].validity, n

    d, v, n = f(data, valid, mask)
    assert int(n) == int(n_np)
    np.testing.assert_array_equal(np.asarray(d)[:int(n)], out_np[0].data[:int(n_np)])


def sort_via(xp, table, keys_spec, num_rows, smax=32):
    if xp is np:
        cols, hb = colvs_np(table, smax)
    else:
        db = DeviceBatch.from_arrow(table, smax)
        cols = [ColV(c.dtype, c.data, c.validity, c.lengths) for c in db.columns]
    keys = [(cols[i], asc, nf) for i, asc, nf in keys_spec]
    order = bk.sort_indices(xp, keys, num_rows)
    return np.asarray(order)[:num_rows]


def test_sort_numeric_with_nulls_and_nan():
    nan = float("nan")
    t = pa.table({"a": pa.array([3.0, None, nan, 1.0, -0.0, 0.0], type=pa.float64())})
    # ascending, nulls first: None, -0/0 (stable), 1, 3, NaN
    order = sort_via(np, t, [(0, True, True)], 6)
    assert order.tolist() == [1, 4, 5, 3, 0, 2]
    # descending, nulls last: NaN, 3, 1, 0/-0, None
    order = sort_via(np, t, [(0, False, False)], 6)
    assert order.tolist() == [2, 0, 3, 4, 5, 1]


def test_sort_strings_and_multikey():
    t = pa.table({"s": pa.array(["b", "a", "ab", None, "a", ""]),
                  "i": pa.array([1, 2, 3, 4, 1, 5], type=pa.int32())})
    # sort by s asc nulls first, then i desc
    order = sort_via(np, t, [(0, True, True), (1, False, False)], 6)
    # expected: None, "", "a"(i=2), "a"(i=1), "ab", "b"
    assert order.tolist() == [3, 5, 1, 4, 2, 0]


def test_sort_device_matches_cpu():
    rng = np.random.default_rng(42)
    vals = rng.integers(-50, 50, 200)
    nulls = rng.random(200) < 0.2
    arr = pa.array([None if n else int(v) for v, n in zip(vals, nulls)],
                   type=pa.int64())
    strs = pa.array([None if rng.random() < 0.1 else
                     "".join(rng.choice(list("abc"), rng.integers(0, 5)))
                     for _ in range(200)])
    t = pa.table({"i": arr, "s": strs})
    spec = [(1, True, False), (0, False, True)]
    o_cpu = sort_via(np, t, spec, 200)
    o_dev = sort_via(jnp, t, spec, 200)
    # permutations may differ only within exact-tie groups; compare sorted values
    tt = t.take(o_cpu.tolist())
    td = t.take(o_dev.tolist())
    assert tt.equals(td)


def test_group_and_reduce():
    t = pa.table({"k": pa.array(["x", "y", "x", None, "y", None]),
                  "v": pa.array([1, 2, 3, 4, None, 6], type=pa.int64())})
    cols, hb = colvs_np(t)
    order = bk.sort_indices(np, [(cols[0], True, True)], 6)
    starts = bk.rows_equal_adjacent(np, [cols[0]], order, 6)
    gids = np.cumsum(starts) - 1
    assert gids.max() == 2  # groups: null, x, y
    v = cols[1]
    vd, vv = v.data[order], v.validity[order]
    s, sv = bk.segment_reduce(np, vd, vv, gids, 6, "sum")
    # null group: 4+6=10; x: 1+3=4; y: 2 (null ignored)
    assert s[:3].tolist() == [10, 4, 2]
    assert sv[:3].all()


@pytest.mark.parametrize("kind,expected,expected_valid", [
    ("sum", [4, 0], [True, False]),
    ("min", [1, 0], [True, False]),
    ("max", [3, 0], [True, False]),
])
def test_segment_reduce_all_null_group(kind, expected, expected_valid):
    data = np.array([1, 3, 7, 9], dtype=np.int64)
    validity = np.array([True, True, False, False])
    gids = np.array([0, 0, 1, 1])
    out_np, v_np = bk.segment_reduce(np, data, validity, gids, 2, kind)
    assert v_np.tolist() == expected_valid
    assert out_np[0] == expected[0]

    f = jax.jit(lambda d, v, g: bk.segment_reduce(jnp, d, v, g, 2, kind))
    out_j, v_j = f(data, validity, gids)
    assert np.asarray(v_j).tolist() == expected_valid
    assert int(out_j[0]) == expected[0]


def test_segment_minmax_nan_semantics():
    data = np.array([1.0, np.nan, np.nan, np.nan, 5.0], dtype=np.float64)
    validity = np.array([True, True, True, True, True])
    gids = np.array([0, 0, 1, 1, 1])
    mx, _ = bk.segment_reduce(np, data, validity, gids, 2, "max")
    mn, _ = bk.segment_reduce(np, data, validity, gids, 2, "min")
    assert np.isnan(mx[0]) and np.isnan(mx[1])  # max sees NaN -> NaN
    assert mn[0] == 1.0 and mn[1] == 5.0        # min ignores NaN unless all NaN
    data2 = np.array([np.nan, np.nan], dtype=np.float64)
    mn2, _ = bk.segment_reduce(np, data2, np.ones(2, bool), np.zeros(2, int), 1, "min")
    assert np.isnan(mn2[0])

    f = jax.jit(lambda d, v, g, k=0: bk.segment_reduce(jnp, d, v, g, 2, "max"))
    mxj, _ = f(data, validity, gids)
    assert np.isnan(np.asarray(mxj)[0])


def test_segment_first_last():
    data = np.array([10, 20, 30, 40], dtype=np.int64)
    validity = np.array([False, True, True, False])
    gids = np.array([0, 0, 1, 1])
    f_ig, fv = bk.segment_reduce(np, data, validity, gids, 2, "first",
                                 ignore_nulls=True)
    assert f_ig.tolist()[:2] == [20, 30] and fv[:2].all()
    f_no, fv2 = bk.segment_reduce(np, data, validity, gids, 2, "first",
                                  ignore_nulls=False)
    assert fv2.tolist()[:2] == [False, True]  # first row of group 0 is null
    l_ig, lv = bk.segment_reduce(np, data, validity, gids, 2, "last",
                                 ignore_nulls=True)
    assert l_ig.tolist()[:2] == [20, 30] and lv[:2].all()
