"""Elastic self-healing fleet: supervisor, autoscaler, overload shedding.

Covers the elasticity contracts (docs/serving.md "Elasticity and
overload"):
- supervisor: replica slots restart on death (process exit AND missed
  registry heartbeats) on the deterministic backoff schedule; the
  crash-loop breaker provably halts a replica that dies after every
  start (DEGRADED, surfaced in fleet_stats, re-armed by reset_slot);
  intentional scale-down drains gracefully and is never counted as a
  death;
- autoscaler decision core: watermark crossings scale only after the
  stability streak, in-band readings reset hysteresis (no flap),
  cooldowns suppress back-to-back actions, targets clamp to
  fleet.{min,max}Replicas, DEGRADED/stale/draining replicas are
  excluded from pressure and capacity;
- overload shedding: a tenant queue at serving.maxQueuedPerTenant sheds
  new submissions with a structured RETRYABLE OverloadedError carrying
  a retry-after hint (at the front door — admitted queries keep
  completing); the wire client honors the hint on its deterministic
  backoff; the per-client quota rejects with QuotaExceededError;
- serve_stats staleness: the background sampler tick keeps ``age_s``
  fresh; the snapshot stamps the PRE-call age so a dead sampler is
  visible despite the inline sample;
- convergence: a killed replica in a supervised registry fleet comes
  back within the restart-backoff bound and queries complete
  bit-identically with zero caller-visible errors.
"""
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.serving.client import (QueryServiceClient,
                                             WireQueryError)
from spark_rapids_tpu.serving.controller import (ControllerState, Decision,
                                                 FleetController,
                                                 ReplicaSnapshot,
                                                 ScalingPolicy, decide,
                                                 healthy_snapshots,
                                                 pick_scale_down_target,
                                                 replica_pressure)
from spark_rapids_tpu.serving.lifecycle import (OverloadedError,
                                                QuotaExceededError)
from spark_rapids_tpu.serving.server import QueryServer
from spark_rapids_tpu.serving.stats import ServeStatsWindow
from spark_rapids_tpu.serving.supervisor import (ReplicaSupervisor,
                                                 SlotState)
from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.utils import metrics as um
from spark_rapids_tpu.utils.errors import (RETRYABLE, classification_for,
                                           decode_error, encode_error,
                                           is_retryable)

BASE_CONF = {
    "spark.rapids.tpu.sql.string.maxBytes": "16",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}

FILTER_SQL = "SELECT k, v FROM t WHERE v > 0.5"

FAST_DIAL = {
    "spark.rapids.tpu.shuffle.maxRetries": "0",
    "spark.rapids.tpu.shuffle.connectTimeout": "2",
}


def make_session(extra=None):
    return TpuSession({**BASE_CONF, **(extra or {})})


def small_df(sess, n=64, seed=0):
    rng = np.random.default_rng(seed)
    return sess.create_dataframe(pa.table({
        "k": rng.integers(0, 8, n).astype("int64"),
        "v": rng.random(n)}))


def blocking_udf_df(sess, started, release, n_rows=2):
    """A DataFrame whose execution signals ``started`` then blocks on
    ``release`` — the controllable long query the shed tests drive."""
    def slow(x):
        started.set()
        release.wait(20)
        return x

    df = sess.create_dataframe(pa.table({"a": list(range(n_rows))}))
    return df.select(F.udf(slow, DType.LONG)(F.col("a")).alias("b"))


# ================================================== supervisor (FakeProc)

class FakeProc:
    """Injectable replica process: the supervisor state machine's unit-
    test double (poll/terminate/kill/addr, deaths on command)."""

    def __init__(self, addr):
        self.addr = addr
        self._rc = None
        self.terminated = False
        self.killed = False

    def poll(self):
        return self._rc

    def exit(self, rc=1):
        self._rc = rc

    def terminate(self):
        self.terminated = True
        self._rc = 0            # graceful drain finishes instantly

    def kill(self):
        self.killed = True
        self._rc = -9


SUP_CONF = {
    # the loop thread must never race the test's manual tick()s
    "spark.rapids.tpu.serving.fleet.superviseIntervalSeconds": "60",
    "spark.rapids.tpu.serving.fleet.restartBackoffMs": "1",
    "spark.rapids.tpu.serving.fleet.crashLoopThreshold": "3",
    "spark.rapids.tpu.serving.fleet.crashLoopWindowSeconds": "10",
}


def make_supervisor(spawned, extra=None):
    def spawn(slot_index):
        p = FakeProc(addr=f"127.0.0.1:{9000 + len(spawned)}")
        spawned.append(p)
        return p

    conf = TpuConf({**BASE_CONF, **SUP_CONF, **(extra or {})})
    return ReplicaSupervisor(conf, spawn=spawn)


def tick_until(sup, pred, timeout=10.0):
    deadline = time.time() + timeout
    while not pred():
        assert time.time() < deadline, "supervisor never converged"
        sup.tick()
        time.sleep(0.005)


def test_supervisor_spawns_fleet_and_restarts_dead_replica():
    spawned = []
    sup = make_supervisor(spawned)
    r0 = um.SERVING_METRICS[um.SERVING_RESTARTS].value
    try:
        sup.start(2)
        assert len(spawned) == 2
        assert sup.active_count() == 2
        assert sorted(sup.addresses()) == ["127.0.0.1:9000",
                                           "127.0.0.1:9001"]
        stats = sup.fleet_stats()
        assert stats["states"] == {"UP": 2}
        # death by exit -> BACKOFF on the retry schedule -> respawn
        spawned[0].exit(3)
        sup.tick()
        assert sup.fleet_stats()["states"].get("BACKOFF") == 1
        tick_until(sup, lambda: len(spawned) == 3)
        assert sup.fleet_stats()["states"] == {"UP": 2}
        assert um.SERVING_METRICS[um.SERVING_RESTARTS].value - r0 == 1
        # the initial spawns were NOT restarts
        assert sum(s["restarts"] for s in sup.fleet_stats()["slots"]) == 1
    finally:
        sup.stop()


def test_restart_backoff_is_deterministic_and_keyed_per_slot():
    """Two fleets with the same seed restart on IDENTICAL schedules
    (replayable chaos); distinct slots get distinct schedules."""
    base, seed = 200, 7
    a = [retry.backoff_ms(i, base, seed, key="supervisor:slot0")
         for i in range(4)]
    b = [retry.backoff_ms(i, base, seed, key="supervisor:slot0")
         for i in range(4)]
    other = [retry.backoff_ms(i, base, seed, key="supervisor:slot1")
             for i in range(4)]
    assert a == b
    assert a != other
    # and the supervisor schedules its respawn on exactly that delay
    spawned = []
    sup = make_supervisor(spawned, {
        "spark.rapids.tpu.serving.fleet.restartBackoffMs": str(base),
        "spark.rapids.tpu.serving.net.faults.seed": str(seed)})
    try:
        sup.start(1)
        spawned[0].exit(1)
        t0 = time.monotonic()
        sup.tick()
        slot = sup.fleet_stats()["slots"][0]
        assert slot["state"] == "BACKOFF" and slot["attempt"] == 1
        expected = retry.backoff_ms(0, base, seed,
                                    key="supervisor:slot0") / 1e3
        with sup._lock:
            delay = sup._slots[0].not_before - t0
        assert abs(delay - expected) < 0.1
    finally:
        sup.stop()


def test_crash_loop_breaker_halts_replica_that_always_dies():
    """The acceptance bound: a replica dying immediately after EVERY
    start stops being restarted after exactly crashLoopThreshold deaths
    — DEGRADED, surfaced, and excluded from capacity."""
    spawned = []

    def doomed_spawn(slot_index):
        p = FakeProc(addr=f"127.0.0.1:{9100 + len(spawned)}")
        p.exit(1)               # dies before the first supervision pass
        spawned.append(p)
        return p

    conf = TpuConf({**BASE_CONF, **SUP_CONF})
    sup = ReplicaSupervisor(conf, spawn=doomed_spawn)
    try:
        sup.start(1)
        tick_until(sup, lambda: sup.degraded_count() == 1)
        assert len(spawned) == 3        # threshold deaths, then silence
        n = len(spawned)
        for _ in range(20):
            sup.tick()
        assert len(spawned) == n, "DEGRADED slot must not respawn"
        stats = sup.fleet_stats()
        assert stats["degraded"] == 1 and stats["active"] == 0
        slot = stats["slots"][0]
        assert slot["state"] == "DEGRADED" and slot["recent_deaths"] >= 3
        # reset_slot re-arms the breaker once the cause is fixed
        assert sup.reset_slot(0)
        tick_until(sup, lambda: len(spawned) == n + 1)
        assert not sup.reset_slot(99)   # unknown slot: no-op
    finally:
        sup.stop()


def test_scale_down_drains_gracefully_and_is_not_a_death():
    spawned = []
    sup = make_supervisor(spawned)
    r0 = um.SERVING_METRICS[um.SERVING_RESTARTS].value
    try:
        sup.start(2)
        idx = sup.scale_down()          # newest active slot
        assert idx == 1
        assert spawned[1].terminated and not spawned[1].killed
        sup.tick()
        stats = sup.fleet_stats()
        assert stats["states"] == {"UP": 1, "STOPPED": 1}
        assert sup.active_count() == 1
        # an intentional stop is not a death: no restart, no breaker hit
        for _ in range(5):
            sup.tick()
        assert len(spawned) == 2
        assert um.SERVING_METRICS[um.SERVING_RESTARTS].value == r0
        assert stats["slots"][1]["recent_deaths"] == 0
        # scale_down by address picks the matching replica
        assert sup.scale_down("127.0.0.1:9000") == 0
        assert sup.scale_down() is None     # nothing left to retire
    finally:
        sup.stop()


def test_missed_heartbeat_counts_as_death(tmp_path):
    """A replica whose process is alive but whose registry heartbeat
    aged out is wedged: the supervisor kills and restarts it."""
    reg = tmp_path / "reg"
    reg.mkdir()
    spawned = []
    sup = make_supervisor(spawned, {
        "spark.rapids.tpu.serving.net.registryDir": str(reg),
        "spark.rapids.tpu.serving.health.livenessWindowSeconds": "0.2"})
    try:
        sup.start(1)
        # a fresh heartbeat: healthy, nothing happens
        (reg / "replica-0").write_text(spawned[0].addr)
        with sup._lock:
            sup._slots[0].started_at -= 10.0    # past the startup grace
        sup.tick()
        assert not spawned[0].killed
        # heartbeat stops (mtime ages past the liveness window)
        t = time.time() - 5
        import os
        os.utime(reg / "replica-0", (t, t))
        sup.tick()
        assert spawned[0].killed, "wedged replica must be killed"
        tick_until(sup, lambda: len(spawned) == 2)
    finally:
        sup.stop()


# ===================================================== autoscaler (pure)

POL = ScalingPolicy(min_replicas=1, max_replicas=4, up_watermark=0.8,
                    down_watermark=0.25, up_stable_ticks=2,
                    down_stable_ticks=3, up_cooldown_s=5.0,
                    down_cooldown_s=30.0, stale_after_s=10.0, queue_norm=4)


def snap(addr="a", state="UP", age=0.5, queue=0, budget=0.0, p99=0.0,
         open_q=0):
    return ReplicaSnapshot(addr=addr, state=state, age_s=age,
                           queue_depth=queue, budget_fraction=budget,
                           p99_wall_s=p99, queries_open=open_q)


def test_scale_up_fires_only_after_the_stability_streak():
    st = ControllerState()
    hot = [snap(budget=0.9)]
    d1 = decide(hot, 1, st, POL, now=100.0)
    assert d1.action == 0 and d1.pressure == 0.9
    d2 = decide(hot, 1, st, POL, now=101.0)
    assert d2.action == +1


def test_hysteresis_in_band_reading_resets_the_streak_no_flap():
    st = ControllerState()
    hot, mid = [snap(budget=0.9)], [snap(budget=0.5)]
    actions = []
    for i, snaps in enumerate([hot, mid, hot, mid, hot, mid, hot, mid]):
        actions.append(decide(snaps, 2, st, POL, now=100.0 + i).action)
    assert actions == [0] * 8, "oscillating load must never flap the fleet"


def test_cooldown_suppresses_back_to_back_scale_ups():
    st = ControllerState()
    hot = [snap(budget=0.95)]
    decide(hot, 1, st, POL, now=100.0)
    assert decide(hot, 1, st, POL, now=101.0).action == +1
    # streak rebuilds immediately, but the cooldown holds the action
    decide(hot, 2, st, POL, now=102.0)
    held = decide(hot, 2, st, POL, now=103.0)
    assert held.action == 0 and "cooldown" in held.reason
    # past the cooldown the pent-up streak releases
    assert decide(hot, 2, st, POL, now=106.5).action == +1


def test_scale_down_streak_floor_and_ceiling_clamps():
    st = ControllerState()
    cold = [snap(budget=0.05)]
    assert decide(cold, 2, st, POL, now=100.0).action == 0
    assert decide(cold, 2, st, POL, now=101.0).action == 0
    assert decide(cold, 2, st, POL, now=102.0).action == -1
    # at the floor a cold fleet holds instead of shrinking below min
    st2 = ControllerState()
    for i in range(6):
        d = decide(cold, 1, st2, POL, now=100.0 + i)
        assert d.action == 0
    assert "floor" in d.reason
    # at the ceiling a hot fleet holds instead of growing past max
    st3 = ControllerState()
    hot = [snap(budget=0.95)]
    for i in range(4):
        d = decide(hot, 4, st3, POL, now=100.0 + i)
        assert d.action == 0
    assert "ceiling" in d.reason
    # below the floor scales up immediately, pressure or not
    st4 = ControllerState()
    d = decide([], 0, st4, POL, now=100.0)
    assert d.action == +1 and "floor" in d.reason


def test_degraded_stale_and_draining_replicas_are_excluded():
    healthy_hot = snap(addr="a", budget=0.95)
    stale = snap(addr="b", age=99.0, budget=0.0)
    draining = snap(addr="c", state="DRAINING", budget=0.0)
    kept = healthy_snapshots([healthy_hot, stale, draining], POL)
    assert [s.addr for s in kept] == ["a"]
    st = ControllerState()
    # the stale idle replicas must not dilute the hot one's pressure
    d = decide([healthy_hot, stale, draining], 3, st, POL, now=100.0)
    assert d.pressure == 0.95 and d.healthy == 1
    # a replica that never sampled yet (age None) is fresh, not stale
    assert healthy_snapshots([snap(age=None)], POL)
    # ALL signals stale: hold rather than act on noise
    st2 = ControllerState()
    d = decide([stale], 2, st2, POL, now=100.0)
    assert d.action == 0 and d.pressure is None and d.healthy == 0


def test_pressure_folds_queue_budget_and_latency_signals():
    assert replica_pressure(snap(budget=0.6), POL) == 0.6
    assert replica_pressure(snap(queue=8), POL) == 2.0   # 8 / queue_norm 4
    assert replica_pressure(snap(budget=0.3, queue=2), POL) == 0.5
    lat = ScalingPolicy(queue_norm=4, p99_objective_s=2.0)
    assert replica_pressure(snap(p99=3.0), lat) == 1.5
    assert replica_pressure(snap(p99=3.0), POL) == 0.0   # objective off


def test_pick_scale_down_target_retires_least_loaded():
    snaps = [snap(addr="a", open_q=3), snap(addr="b", open_q=0, budget=0.1),
             snap(addr="c", open_q=0, budget=0.6)]
    assert pick_scale_down_target(snaps, POL) == "b"
    assert pick_scale_down_target([], POL) is None


class StubSupervisor:
    def __init__(self, active=1):
        self.active = active
        self.ups = 0
        self.downs = []

    def addresses(self):
        return []

    def active_count(self):
        return self.active

    def scale_up(self):
        self.ups += 1
        self.active += 1

    def scale_down(self, addr=None):
        self.downs.append(addr)
        self.active -= 1
        return 0


def test_controller_tick_actuates_and_counts(monkeypatch):
    conf = TpuConf({**BASE_CONF,
                    "spark.rapids.tpu.serving.fleet.scaleUpStableTicks": "1",
                    "spark.rapids.tpu.serving.fleet."
                    "scaleDownStableTicks": "1",
                    "spark.rapids.tpu.serving.fleet."
                    "scaleUpCooldownSeconds": "0",
                    "spark.rapids.tpu.serving.fleet."
                    "scaleDownCooldownSeconds": "0"})
    sup = StubSupervisor(active=2)
    ctl = FleetController(conf, sup)
    u0 = um.SERVING_METRICS[um.SERVING_SCALE_UPS].value
    d0 = um.SERVING_METRICS[um.SERVING_SCALE_DOWNS].value
    monkeypatch.setattr(ctl, "collect",
                        lambda: [snap(addr="a", budget=0.95)])
    d = ctl.tick(now=100.0)
    assert d.action == +1 and sup.ups == 1
    assert um.SERVING_METRICS[um.SERVING_SCALE_UPS].value - u0 == 1
    monkeypatch.setattr(ctl, "collect",
                        lambda: [snap(addr="a", budget=0.01)])
    d = ctl.tick(now=200.0)
    assert d.action == -1 and sup.downs == ["a"]
    assert um.SERVING_METRICS[um.SERVING_SCALE_DOWNS].value - d0 == 1
    assert ctl.last_decision is d


# ==================================================== overload shedding

def test_overloaded_error_is_retryable_and_roundtrips_the_codec():
    e = OverloadedError("queue full", retry_after_s=0.75)
    assert classification_for(e) == RETRYABLE and is_retryable(e)
    payload = encode_error(e)
    assert payload["code"] == "OVERLOADED"
    back = decode_error(payload)
    assert isinstance(back, OverloadedError)
    assert back.retry_after_s == 0.75
    q = decode_error(encode_error(QuotaExceededError("cap", 0.5)))
    assert isinstance(q, QuotaExceededError) and q.retry_after_s == 0.5
    assert is_retryable(q)


def test_scheduler_sheds_at_tenant_queue_bound_front_door_only():
    sess = make_session({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1",
        "spark.rapids.tpu.serving.maxQueuedPerTenant": "1",
        "spark.rapids.tpu.serving.stats.sampleIntervalSeconds": "0"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    queued = sess.submit(small_df(sess))        # tenant queue now at bound
    s0 = um.SERVING_METRICS[um.SERVING_SHEDS].value
    with pytest.raises(OverloadedError) as ei:
        sess.submit(small_df(sess, seed=1))
    assert ei.value.retry_after_s > 0
    assert um.SERVING_METRICS[um.SERVING_SHEDS].value - s0 == 1
    # sheds happen at the front door ONLY: everything admitted completes
    release.set()
    assert blocker.result(timeout=120) is not None
    assert queued.result(timeout=120) is not None
    # with the pressure gone, new submissions are admitted again
    assert sess.submit(small_df(sess, seed=2)).result(timeout=120) is not None
    sess.scheduler.shutdown()


def test_shed_retry_after_scales_with_queue_depth():
    sess = make_session({
        "spark.rapids.tpu.serving.overload.retryAfterSeconds": "0.2"})
    sched = sess.scheduler
    assert sched.shed_retry_after(0) >= 0.2
    assert (sched.shed_retry_after(2 * sched.max_concurrent)
            > sched.shed_retry_after(0)), \
        "a deeper queue must hint a longer retry-after"
    sched.shutdown()


def _serve(extra_conf=None, n=4000):
    sess = TpuSession({**BASE_CONF, **(extra_conf or {})})
    rng = np.random.default_rng(7)
    df = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 8, n).astype("int64"),
        "v": rng.random(n)})).repartition(2)
    df.createOrReplaceTempView("t")
    server = QueryServer(sess)
    host, port = server.address
    return sess, server, f"{host}:{port}"


def test_wire_overload_rejection_structured_with_retry_after():
    """A saturated replica sheds over the wire: the client raises the
    decoded OverloadedError (pinned submit), the hint rides the blob,
    and admitted queries keep completing underneath."""
    sess, server, addr = _serve({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1",
        "spark.rapids.tpu.serving.maxQueuedPerTenant": "1",
        "spark.rapids.tpu.serving.overload.retryAfterSeconds": "0.1"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    queued = sess.submit(small_df(sess))        # fill the tenant queue
    client = QueryServiceClient([addr], TpuConf({**BASE_CONF, **FAST_DIAL}))
    s0 = um.SERVING_METRICS[um.SERVING_SHEDS].value
    try:
        with pytest.raises(OverloadedError) as ei:
            client.submit(FILTER_SQL, replica=0)
        assert ei.value.retry_after_s > 0
        assert um.SERVING_METRICS[um.SERVING_SHEDS].value - s0 >= 1
        # never a timeout or opaque wire error: the shed is structured
        release.set()
        assert blocker.result(timeout=120) is not None
        assert queued.result(timeout=120) is not None
        # pressure gone: the same client's next submit is served
        got = client.submit(FILTER_SQL, replica=0).result()
        assert got.equals(sess.sql(FILTER_SQL).collect())
    finally:
        client.close()
        sess.scheduler.drain(timeout=60)
        server.shutdown()
        sess.scheduler.shutdown()


def test_client_honors_retry_after_hint_on_unpinned_submit():
    """An unpinned submit that finds EVERY replica shedding sleeps the
    max(hint, deterministic backoff) between passes and retries — it
    raises only after serving.overload.clientRetries extra passes."""
    sess, server, addr = _serve({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1",
        "spark.rapids.tpu.serving.maxQueuedPerTenant": "1",
        "spark.rapids.tpu.serving.overload.retryAfterSeconds": "0.15"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    queued = sess.submit(small_df(sess))
    client = QueryServiceClient(
        [addr], TpuConf({**BASE_CONF, **FAST_DIAL,
                         "spark.rapids.tpu.serving.overload."
                         "clientRetries": "2"}))
    try:
        t0 = time.monotonic()
        with pytest.raises(OverloadedError):
            client.submit(FILTER_SQL)
        elapsed = time.monotonic() - t0
        assert elapsed >= 0.25, \
            f"two retry passes must honor ~2x the 0.15s hint, got {elapsed}"
    finally:
        release.set()
        blocker.result(timeout=120)
        queued.result(timeout=120)
        client.close()
        sess.scheduler.drain(timeout=60)
        server.shutdown()
        sess.scheduler.shutdown()


def test_per_client_quota_rejects_structured_and_counts():
    sess, server, addr = _serve({
        "spark.rapids.tpu.serving.quota.maxConcurrentPerClient": "1"})
    client = QueryServiceClient([addr], TpuConf({**BASE_CONF, **FAST_DIAL}))
    q0 = um.SERVING_METRICS[um.SERVING_QUOTA_REJECTIONS].value
    try:
        first = client.submit(FILTER_SQL, replica=0)    # holds the quota
        with pytest.raises(QuotaExceededError) as ei:
            client.submit(FILTER_SQL, replica=0)
        assert ei.value.retry_after_s > 0
        assert (um.SERVING_METRICS[um.SERVING_QUOTA_REJECTIONS].value
                - q0 == 1)
        # draining the first stream frees the quota for the next submit
        ref = first.result()
        assert client.submit(FILTER_SQL, replica=0).result().equals(ref)
    finally:
        client.close()
        sess.scheduler.drain(timeout=60)
        server.shutdown()
        sess.scheduler.shutdown()


def test_unrequested_server_cancel_is_replica_loss_not_query_failure():
    """A server-side cancellation the client never asked for (peer-lost /
    shutdown cleanup racing the stream) surfaces RETRYABLE — replica
    loss, eligible for failover — while a cancellation the handle itself
    sent stays terminal (non-retryable)."""
    sess, server, addr = _serve({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    client = QueryServiceClient([addr], TpuConf({**BASE_CONF, **FAST_DIAL}))
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    try:
        h = client.submit(FILTER_SQL, replica=0)        # parked QUEUED
        server._queries[h.query_id].handle.cancel()     # cleanup, not us
        release.set()
        with pytest.raises(WireQueryError) as ei:
            h.result()
        assert ei.value.retryable, \
            "an unrequested cancellation must be retryable replica loss"
        assert ei.value.wire_code == "QUERY_CANCELLED"

        blocker.result(timeout=120)
        started.clear(); release.clear()
        blocker = sess.submit(blocking_udf_df(sess, started, release))
        assert started.wait(60)
        h2 = client.submit(FILTER_SQL, replica=0)
        h2._cancel_sent = True          # as if the handle sent a cancel
        server._queries[h2.query_id].handle.cancel()
        release.set()
        with pytest.raises(WireQueryError) as ei2:
            h2.result()
        assert not ei2.value.retryable, \
            "a cancellation this handle requested is terminal"
        assert ei2.value.wire_code == "QUERY_CANCELLED"
    finally:
        release.set()
        blocker.result(timeout=120)
        client.close()
        sess.scheduler.drain(timeout=60)
        server.shutdown()
        sess.scheduler.shutdown()


# ================================================== serve_stats staleness

def test_snapshot_age_is_the_pre_call_age_not_the_inline_sample():
    sess = make_session({
        "spark.rapids.tpu.serving.stats.sampleIntervalSeconds": "0"})
    sched = sess.scheduler
    w = ServeStatsWindow(window_s=60)
    assert w.age_s() is None
    first = w.snapshot(sched)
    assert first["age_s"] is None, "no prior sample: age must be None"
    time.sleep(0.25)
    second = w.snapshot(sched)
    # the inline sample refreshed the series, but the STAMP is the age
    # the series had when the request arrived — a dead sampler shows
    assert second["age_s"] >= 0.2
    assert w.age_s() < 0.2          # ...while the series itself is fresh
    sched.shutdown()


def test_background_sampler_keeps_series_fresh_and_stops_on_shutdown():
    sess = make_session({
        "spark.rapids.tpu.serving.stats.sampleIntervalSeconds": "0.05"})
    sched = sess.scheduler
    sched.start_stats_sampler()
    deadline = time.time() + 10
    while sched.serve_stats.age_s() is None:
        assert time.time() < deadline, "sampler never ticked"
        time.sleep(0.02)
    time.sleep(0.3)
    snap = sched.serve_stats.snapshot(sched)
    assert snap["age_s"] is not None and snap["age_s"] < 5.0
    assert len(snap["series"]) >= 3, "periodic tick must append samples"
    sched.shutdown()
    t = sched._sampler
    if t is not None:
        t.join(timeout=5)
        assert not t.is_alive()


# ===================================================== fleet convergence

class InProcReplica:
    """In-process replica behind the supervisor's process contract:
    terminate() drains gracefully, kill() is SIGKILL (the transport
    stops heartbeating but the 'process' stays alive — the wedged path
    until the supervisor kills it for real)."""

    def __init__(self, conf, table):
        self.sess = TpuSession(conf)
        df = self.sess.create_dataframe(table).repartition(2)
        df.createOrReplaceTempView("t")
        self.server = QueryServer(self.sess)
        host, port = self.server.address
        self.addr = f"{host}:{port}"
        self._exited = False

    def poll(self):
        return 0 if self._exited else None

    def terminate(self):
        def run():
            self.server.drain()
            deadline = time.time() + 30
            while not self.server.drained() and time.time() < deadline:
                time.sleep(0.05)
            self.server.shutdown()
            self.sess.scheduler.shutdown(wait=False)
            self._exited = True
        threading.Thread(target=run, daemon=True).start()

    def kill(self):
        self.server.shutdown()
        self.sess.scheduler.shutdown(wait=False)
        self._exited = True

    def wedge(self):
        """Stop heartbeating while staying 'alive': the missed-heartbeat
        death path, not the process-exit one."""
        t = self.server.transport
        (getattr(t, "_inner", None) or t).kill()


@pytest.mark.slow
def test_supervised_fleet_recovers_from_wedged_replica(tmp_path):
    """Chaos convergence: wedge one of two supervised replicas — the
    supervisor detects the missed heartbeats, kills and restarts it
    within the backoff bound, the registry re-discovers it, and client
    queries complete bit-identically with zero visible errors."""
    reg = str(tmp_path / "reg")
    rng = np.random.default_rng(7)
    table = pa.table({"k": rng.integers(0, 8, 4000).astype("int64"),
                      "v": rng.random(4000)})
    fleet_conf = {
        **BASE_CONF,
        "spark.rapids.tpu.serving.net.registryDir": reg,
        "spark.rapids.tpu.serving.health.heartbeatSeconds": "0.05",
        "spark.rapids.tpu.serving.health.livenessWindowSeconds": "0.4",
    }
    replicas = []

    def spawn(slot_index):
        r = InProcReplica(fleet_conf, table)
        replicas.append(r)
        return r

    sup = ReplicaSupervisor(TpuConf({**fleet_conf, **SUP_CONF, **{
        "spark.rapids.tpu.serving.fleet.restartBackoffMs": "20"}}),
        spawn=spawn)
    client = QueryServiceClient(
        registry_dir=reg,
        conf=TpuConf({**BASE_CONF, **FAST_DIAL,
                      "spark.rapids.tpu.serving.health."
                      "probeIntervalSeconds": "0"}))
    try:
        sup.start(2)
        ref = replicas[0].sess.sql(FILTER_SQL).collect()
        assert client.submit(FILTER_SQL).result().equals(ref)
        # wedge replica 0: alive, not heartbeating
        replicas[0].wedge()
        with sup._lock:     # skip the startup grace deterministically
            sup._slots[0].started_at -= 10.0
        deadline = time.time() + 30
        while len(replicas) < 3:
            assert time.time() < deadline, "supervisor never restarted"
            sup.tick()
            time.sleep(0.05)
        assert replicas[0]._exited, "wedged replica must be killed"
        assert sup.fleet_stats()["states"] == {"UP": 2}
        # the fleet serves correct, bit-identical results throughout;
        # a pass that races discovery retries — but never sees a wrong
        # or opaque terminal error
        deadline = time.time() + 30
        while True:
            try:
                assert client.submit(FILTER_SQL).result().equals(ref)
                break
            except (WireQueryError, OverloadedError):
                assert time.time() < deadline, "fleet never converged"
                time.sleep(0.1)
    finally:
        client.close()
        sup.stop()
        for r in replicas:
            if not r._exited:
                r.kill()
