"""I/O tests: write round-trips, save modes, dynamic partitions, partition
discovery, predicate pushdown, schema evolution (reference analogs:
ParquetWriterSuite, ParquetScanSuite, OrcScanSuite, CsvScanSuite,
parquet_test.py / orc_test.py / csv_test.py round-trips)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.testing import (assert_tables_equal,
                                      assert_tpu_and_cpu_equal)


def sample_table(n=200, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.choice(["x", "y", "z"], n).tolist()),
        "i": pa.array([None if rng.random() < 0.1 else int(v)
                       for v in rng.integers(-50, 50, n)], type=pa.int64()),
        "f": pa.array([None if rng.random() < 0.1 else float(v)
                       for v in rng.uniform(-5, 5, n)], type=pa.float64()),
    })


def _sess(**conf):
    return TpuSession({"spark.rapids.tpu.sql.enabled": "true", **conf})


def _plan_str(sess):
    return sess.last_plan.tree_string() if sess.last_plan else ""


# ------------------------------------------------------------- write round trips
@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_write_read_roundtrip_tpu(tmp_path, fmt):
    t = sample_table()
    sess = _sess()
    out = str(tmp_path / f"out_{fmt}")
    stats = getattr(sess.create_dataframe(t).write.mode("error"), fmt)(out)
    # the write command itself must have run on the TPU engine
    assert "TpuWriteFilesExec" in _plan_str(sess)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    assert stats.num_rows == t.num_rows
    assert stats.num_files >= 1
    back = getattr(sess.read, fmt)(out).collect()
    assert_tables_equal(t, back.cast(t.schema), ignore_order=True)


def test_write_csv_falls_back_to_cpu(tmp_path):
    t = sample_table()
    sess = _sess()
    out = str(tmp_path / "out_csv")
    sess.create_dataframe(t).write.option("header", "true").csv(out)
    plan = _plan_str(sess)
    assert "TpuWriteFilesExec" not in plan
    assert "CpuWriteFilesExec" in plan
    back = (sess.read.option("header", "true")
            .csv(out, schema=None).collect())
    assert back.num_rows == t.num_rows


def test_save_modes(tmp_path):
    t = sample_table(50)
    sess = _sess()
    out = str(tmp_path / "modes")
    w = lambda: sess.create_dataframe(t).write
    w().parquet(out)
    with pytest.raises(FileExistsError):
        w().parquet(out)
    w().mode("ignore").parquet(out)           # no-op
    assert sess.read.parquet(out).collect().num_rows == 50
    w().mode("append").parquet(out)
    assert sess.read.parquet(out).collect().num_rows == 100
    w().mode("overwrite").parquet(out)
    assert sess.read.parquet(out).collect().num_rows == 50


def test_max_records_per_file(tmp_path):
    t = sample_table(100)
    sess = _sess()
    out = str(tmp_path / "rolled")
    stats = (sess.create_dataframe(t).write
             .option("maxRecordsPerFile", 30).parquet(out))
    assert stats.num_files == 4  # 30+30+30+10
    assert sess.read.parquet(out).collect().num_rows == 100


def test_unsupported_codec_falls_back(tmp_path):
    t = sample_table(20)
    sess = _sess()
    out = str(tmp_path / "lz4hc")
    sess.create_dataframe(t).write.option("compression", "lz4").parquet(out)
    assert "TpuWriteFilesExec" not in _plan_str(sess)


# ------------------------------------------------------------- dynamic partitions
def test_partitioned_write_and_discovery(tmp_path):
    t = sample_table(300)
    sess = _sess()
    out = str(tmp_path / "parts")
    stats = (sess.create_dataframe(t).write.partitionBy("k").parquet(out))
    assert stats.num_partitions == 3
    assert os.path.isdir(os.path.join(out, "k=x"))
    # partition column must NOT be inside the data files
    a_file = next(os.path.join(dp, f) for dp, _, fs in os.walk(out)
                  for f in fs if f.endswith(".parquet"))
    assert "k" not in pq.read_schema(a_file).names
    back = sess.read.parquet(out).collect()
    # partition columns come back as trailing columns via discovery
    assert set(back.column_names) == {"i", "f", "k"}
    assert_tables_equal(
        t.select(["i", "f", "k"]), back.cast(t.select(["i", "f", "k"]).schema),
        ignore_order=True)


def test_partitioned_write_null_keys(tmp_path):
    t = pa.table({"k": pa.array(["a", None, "a", None]),
                  "v": pa.array([1, 2, 3, 4], type=pa.int64())})
    sess = _sess()
    out = str(tmp_path / "nullparts")
    sess.create_dataframe(t).write.partitionBy("k").parquet(out)
    assert os.path.isdir(os.path.join(out, "k=__HIVE_DEFAULT_PARTITION__"))
    back = sess.read.parquet(out).collect().sort_by("v")
    assert back.column("k").to_pylist() == ["a", None, "a", None]


def test_int_partition_values_typed(tmp_path):
    t = pa.table({"year": pa.array([2020, 2021, 2021], type=pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0])})
    sess = _sess()
    out = str(tmp_path / "typed")
    sess.create_dataframe(t).write.partitionBy("year").parquet(out)
    df = sess.read.parquet(out)
    f = df.schema().field("year")
    assert f.dtype.value in ("int", "long")
    got = df.filter(F.col("year") == 2021).collect()
    assert got.num_rows == 2


# ------------------------------------------------------------- pushdown
def test_row_group_clipping(tmp_path):
    from spark_rapids_tpu.exprs import (GreaterThan, LessThan, Literal,
                                        UnresolvedAttribute)
    from spark_rapids_tpu.io.parquet import clip_row_groups
    path = str(tmp_path / "rg.parquet")
    t = pa.table({"x": pa.array(range(1000), type=pa.int64())})
    pq.write_table(t, path, row_group_size=100)
    pf = pq.ParquetFile(path)
    assert pf.metadata.num_row_groups == 10
    f = GreaterThan(UnresolvedAttribute("x"), Literal.of(750))
    kept = clip_row_groups(pf, [f])
    assert kept == [7, 8, 9]
    f2 = LessThan(UnresolvedAttribute("x"), Literal.of(0))
    assert clip_row_groups(pf, [f2]) == []


def test_pushdown_end_to_end(tmp_path):
    path = str(tmp_path / "pd.parquet")
    t = pa.table({"x": pa.array(range(1000), type=pa.int64()),
                  "y": pa.array([i * 0.5 for i in range(1000)])})
    pq.write_table(t, path, row_group_size=100)
    sess = _sess()
    got = (sess.read.parquet(path).filter(F.col("x") >= 950).collect())
    assert got.num_rows == 50
    assert got.column("x").to_pylist() == list(range(950, 1000))
    # the scan exec must carry the pushed filters
    plan = _plan_str(sess)
    assert "TpuParquetScanExec" in plan


# ------------------------------------------------------------- schema evolution
def test_schema_evolution_missing_column(tmp_path):
    d = tmp_path / "evolve"
    d.mkdir()
    pq.write_table(pa.table({"a": pa.array([1, 2], type=pa.int64()),
                             "b": pa.array(["p", "q"])}),
                   str(d / "f1.parquet"))
    pq.write_table(pa.table({"a": pa.array([3], type=pa.int64())}),
                   str(d / "f2.parquet"))
    sess = _sess()
    back = sess.read.parquet(str(d)).collect().sort_by("a")
    assert back.column("a").to_pylist() == [1, 2, 3]
    assert back.column("b").to_pylist() == ["p", "q", None]


def test_orc_roundtrip_partitioned(tmp_path):
    t = sample_table(120)
    sess = _sess()
    out = str(tmp_path / "orcparts")
    sess.create_dataframe(t).write.partitionBy("k").orc(out)
    back = sess.read.orc(out).collect()
    assert back.num_rows == 120
    assert set(back.column("k").to_pylist()) == {"x", "y", "z"}


def test_mixed_type_partition_values(tmp_path):
    # k=1 and k=foo must both read back as strings once the column-wide
    # inferred type is STRING
    d = tmp_path / "mixed"
    (d / "k=1").mkdir(parents=True)
    (d / "k=foo").mkdir(parents=True)
    pq.write_table(pa.table({"v": pa.array([10], type=pa.int64())}),
                   str(d / "k=1" / "f.parquet"))
    pq.write_table(pa.table({"v": pa.array([20], type=pa.int64())}),
                   str(d / "k=foo" / "f.parquet"))
    sess = _sess()
    back = sess.read.parquet(str(d)).collect().sort_by("v")
    assert back.column("k").to_pylist() == ["1", "foo"]


def test_overwrite_replaces_plain_file(tmp_path):
    target = tmp_path / "plain"
    target.write_text("old")
    sess = _sess()
    sess.create_dataframe(sample_table(10)).write.mode("overwrite").parquet(
        str(target))
    assert sess.read.parquet(str(target)).collect().num_rows == 10


def test_csv_partition_discovery(tmp_path):
    d = tmp_path / "csvparts"
    (d / "k=a").mkdir(parents=True)
    (d / "k=b").mkdir(parents=True)
    import pyarrow.csv as pacsv
    pacsv.write_csv(pa.table({"v": pa.array([1, 2], type=pa.int64())}),
                    str(d / "k=a" / "f.csv"))
    pacsv.write_csv(pa.table({"v": pa.array([3], type=pa.int64())}),
                    str(d / "k=b" / "f.csv"))
    sess = _sess()
    back = (sess.read.option("header", "true").csv(str(d)).collect()
            .sort_by("v"))
    assert back.column("k").to_pylist() == ["a", "a", "b"]


def test_orc_stripe_pruning_and_chunking(tmp_path):
    """Stripe statistics (read straight from the file's metadata section —
    pyarrow exposes none) must prune non-matching stripes, and small stripes
    must coalesce to the reader's rows budget (GpuOrcScan.scala +
    OrcFilters.scala:194 analog)."""
    import datetime
    import numpy as np
    import pyarrow.orc as po
    from spark_rapids_tpu.exprs import (GreaterThanOrEqual, Literal,
                                        UnresolvedAttribute)
    from spark_rapids_tpu.io.orc import clip_stripes
    from spark_rapids_tpu.io.orc_meta import read_orc_meta

    path = str(tmp_path / "t.orc")
    t = pa.table({
        "k": pa.array(np.arange(10_000), type=pa.int64()),
        "s": pa.array([f"v{i:05d}" for i in range(10_000)]),
        "d": pa.array([datetime.date(2000, 1, 1)
                       + datetime.timedelta(days=i % 90)
                       for i in range(10_000)])})
    po.write_table(t, path, stripe_size=64 * 1024)

    meta = read_orc_meta(path)
    assert len(meta.stripes) > 4
    assert len(meta.stripe_stats) == len(meta.stripes)
    assert meta.stripe_stats[0]["k"].min == 0
    assert meta.stripe_stats[-1]["k"].max == 9_999
    assert meta.stripe_stats[0]["s"].min == "v00000"

    flt = GreaterThanOrEqual(UnresolvedAttribute("k"), Literal.of(9_000))
    kept = clip_stripes(path, [flt], len(meta.stripes))
    assert 0 < len(kept) < len(meta.stripes)

    # engine end to end: pushdown + correct rows, CPU vs TPU
    def build(sess):
        return (sess.read.orc(path)
                .filter(F.col("k") >= 9_000).select("k", "s"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.num_rows == 1_000

    # chunk coalescing: a small rows budget splits the scan into batches
    def build2(sess):
        sess.set_conf("spark.rapids.tpu.sql.reader.batchSizeRows", 3_000)
        return sess.read.orc(path).select("k")

    cpu = assert_tpu_and_cpu_equal(build2)
    assert cpu.num_rows == 10_000


# ----------------------------------------------------- input-file metadata
def test_input_file_name_and_block(tmp_path):
    """input_file_name/block_start/block_length ride the scan's per-file
    metadata (GpuInputFileBlock.scala)."""
    import os
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal
    paths = []
    for i in range(3):
        t = pa.table({"k": np.arange(i * 10, i * 10 + 10, dtype=np.int64)})
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(t, p)
        paths.append(p)
    s = TpuSession()
    out = s.read.parquet(str(tmp_path)).select(
        "k", F.input_file_name().alias("fn"),
        F.input_file_block_start().alias("bs"),
        F.input_file_block_length().alias("bl")).collect()
    rows = {r["k"]: r for r in out.to_pylist()}
    assert rows[5]["fn"].endswith("f0.parquet")
    assert rows[15]["fn"].endswith("f1.parquet")
    assert rows[25]["fn"].endswith("f2.parquet")
    assert all(r["bs"] == 0 for r in rows.values())
    assert rows[5]["bl"] == os.path.getsize(paths[0])
    # CPU-vs-TPU parity incl. aggregation over the metadata
    assert_tpu_and_cpu_equal(
        lambda sess: sess.read.parquet(str(tmp_path))
            .groupBy(F.input_file_name().alias("fn"))
            .agg(F.count("k").alias("c")),
        ignore_order=True)


def test_input_file_name_requires_file_scan():
    import pyarrow as _pa
    import pytest as _pytest
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    s = TpuSession()
    df = s.create_dataframe(_pa.table({"a": [1, 2]})).select(
        F.input_file_name().alias("f"))
    with _pytest.raises(Exception, match="file scan|unresolved|bound"):
        df.collect()


def test_input_file_meta_csv_and_orc(tmp_path):
    import numpy as np
    import pyarrow.orc as po_orc
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    t = pa.table({"v": np.arange(20, dtype=np.int64)})
    po_orc.write_table(t, str(tmp_path / "a.orc"))
    s = TpuSession()
    out = s.read.orc(str(tmp_path)).select(
        F.input_file_name().alias("fn")).collect()
    assert out.num_rows == 20
    assert all(x.endswith("a.orc") for x in out.column("fn").to_pylist())


def test_input_file_meta_hidden_columns_do_not_leak(tmp_path):
    """Meta referenced only in a filter: the hidden columns must not surface
    in the collected schema; a union with a non-file source gets Spark's
    '' / -1 defaults."""
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    for i in range(2):
        pq.write_table(
            pa.table({"k": np.arange(i * 5, i * 5 + 5, dtype=np.int64)}),
            str(tmp_path / f"f{i}.parquet"))
    s = TpuSession()
    out = s.read.parquet(str(tmp_path)).filter(
        F.input_file_name().contains("f0")).collect()
    assert out.column_names == ["k"], out.column_names
    assert sorted(out.column("k").to_pylist()) == [0, 1, 2, 3, 4]
    # union with an in-memory source: defaults align the branches
    u = s.read.parquet(str(tmp_path)).union(
        s.create_dataframe(pa.table({"k": pa.array([99], pa.int64())})))
    got = u.select("k", F.input_file_name().alias("fn")).collect()
    by_k = dict(zip(got.column("k").to_pylist(),
                    got.column("fn").to_pylist()))
    assert by_k[99] == ""
    assert by_k[0].endswith("f0.parquet")


def test_input_file_meta_through_projections(tmp_path):
    """The hidden columns thread through intermediate select()s, including
    union branches that project over a scan."""
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    for i in range(2):
        pq.write_table(
            pa.table({"k": np.arange(i * 5, i * 5 + 5, dtype=np.int64),
                      "x": np.full(5, i, dtype=np.int64)}),
            str(tmp_path / f"f{i}.parquet"))
    s = TpuSession()
    # metadata above an intermediate projection
    out = s.read.parquet(str(tmp_path)).select("k").select(
        "k", F.input_file_name().alias("fn")).collect()
    assert out.column_names == ["k", "fn"]
    by_k = dict(zip(out.column("k").to_pylist(),
                    out.column("fn").to_pylist()))
    assert by_k[0].endswith("f0.parquet") and by_k[9].endswith("f1.parquet")
    # union branch whose scan sits under a projection keeps the REAL path
    u = (s.read.parquet(str(tmp_path)).select("k")
         .union(s.create_dataframe(
             pa.table({"k": pa.array([99], pa.int64())})))
         .select("k", F.input_file_name().alias("fn")))
    got = dict(zip(u.collect().column("k").to_pylist(),
                   u.collect().column("fn").to_pylist()))
    assert got[99] == ""
    assert got[3].endswith("f0.parquet")


def test_parquet_scan_prefetch_matches_serial(tmp_path):
    """The decode-ahead pipelined scan (io.scan.prefetchBatches) must
    produce exactly the serial read's results."""
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal

    rng = np.random.default_rng(3)
    n = 20000
    t = pa.table({"k": rng.integers(0, 50, n).astype(np.int64),
                  "v": np.round(rng.standard_normal(n), 3),
                  "s": pa.array([f"r{int(x)}" for x in
                                 rng.integers(0, 90, n)])})
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=1500)

    def q(prefetch):
        sess = TpuSession({
            "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
            "spark.rapids.tpu.sql.scanCache.enabled": "false",
            "spark.rapids.tpu.io.scan.prefetchBatches": str(prefetch)})
        return (sess.read.parquet(path).groupBy("k")
                .agg(F.sum("v").alias("sv"), F.count("s").alias("c"))
                .sort("k")).collect()

    assert_tables_equal(q(0), q(3), approx_float=1e-9)


def test_parquet_legacy_calendar_rebase(tmp_path):
    """Round-4 VERDICT item 8 (RebaseHelper.scala:82,
    GpuParquetScan.scala:216): a parquet file carrying Spark-2.x writer
    metadata stores hybrid-Julian day counts — scans must apply the
    Julian->Gregorian rebase on ancient dates/timestamps, identically on
    both engines; modern files and non-Spark writers stay untouched."""
    import datetime
    import pyarrow.parquet as pq

    # stored day counts AS A SPARK 2.x FILE ENCODES THEM (hybrid calendar):
    # label 1582-10-04 stored as -141428; label 1000-01-01 via Julian math
    ancient_julian = [-141428, -354285, 0, 18262]  # last two: modern, no-op
    ts_us = [d * 86_400_000_000 + 7_200_000_000 for d in ancient_julian]
    # the ts column carries a NULL alongside |micros| > 2^53 values: a
    # float64 round-trip would silently round the ancient micros
    table = pa.table({
        "d": pa.array(ancient_julian + [None], pa.int32()).cast(pa.date32()),
        "ts": pa.array(ts_us + [None], pa.int64()).cast(pa.timestamp("us")),
        "v": [1.0, 2.0, 3.0, None, 5.0],
    })
    legacy_path = str(tmp_path / "legacy.parquet")
    meta = {b"org.apache.spark.version": b"2.4.4",
            b"org.apache.spark.legacyDateTime": b""}
    pq.write_table(table.replace_schema_metadata(meta), legacy_path)
    modern_path = str(tmp_path / "modern.parquet")
    pq.write_table(
        table.replace_schema_metadata({b"org.apache.spark.version": b"3.1.0"}),
        modern_path)

    expected_days = [-141438,          # Julian 1582-10-04 -> Spark anchor
                     None, 0, 18262]   # idx1 computed below; moderns no-op
    # independent label check for -354285: Julian y/m/d -> Gregorian ordinal
    jdn = -354285 + 2440588
    c = jdn + 32082; dd = (4 * c + 3) // 1461; e = c - (1461 * dd) // 4
    m = (5 * e + 2) // 153
    y, mo, da = dd - 4800 + m // 10, m + 3 - 12 * (m // 10), \
        e - (153 * m + 2) // 5 + 1
    expected_days[1] = datetime.date(y, mo, da).toordinal() - 719163

    for conf in ({"spark.rapids.tpu.sql.enabled": "false"},
                 {"spark.rapids.tpu.sql.enabled": "true"}):
        sess = TpuSession(conf)
        out = sess.read.parquet(legacy_path).collect()
        got = [None if v is None else (v - datetime.date(1970, 1, 1)).days
               for v in out.column("d").to_pylist()]
        assert got == expected_days + [None], (conf, got)
        ts = out.column("ts").cast(pa.int64()).to_pylist()
        assert ts == [ed * 86_400_000_000 + 7_200_000_000
                      for ed in expected_days] + [None], (conf, ts)
        # corrected-mode file: bytes pass through untouched
        out2 = sess.read.parquet(modern_path).collect()
        raw = [None if v is None else (v - datetime.date(1970, 1, 1)).days
               for v in out2.column("d").to_pylist()]
        assert raw == ancient_julian + [None], (conf, raw)


def test_parquet_device_dict_decode_bit_identical(tmp_path):
    """Round-4 VERDICT item 3: fixed-width columns ride the host link
    dictionary-encoded and decode on device via gather — results must be
    BIT-identical to the host-decoded path and the CPU engine, including
    nulls, doubles (bits sibling), dates, and a high-cardinality column
    that parquet falls back to PLAIN for."""
    import pyarrow.parquet as pq
    import numpy as np

    rng = np.random.default_rng(3)
    n = 20000
    table = pa.table({
        "qty": pa.array([None if i % 97 == 0 else float(rng.integers(1, 51))
                         for i in range(n)], pa.float64()),
        "disc": pa.array((rng.integers(0, 11, n) / 100.0)),
        "d": pa.array(rng.integers(8000, 8060, n), pa.int32()).cast(
            pa.date32()),
        "hi": pa.array(rng.random(n)),          # ~unique: PLAIN fallback
        "tag": pa.array([f"t{int(x)}" for x in rng.integers(0, 5, n)]),
        "k": pa.array(rng.integers(0, 1 << 40, n), pa.int64()),
    })
    path = str(tmp_path / "dict.parquet")
    pq.write_table(table, path, row_group_size=7000)

    from spark_rapids_tpu.testing import assert_tables_equal
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = cpu.read.parquet(path).collect()
    on = TpuSession({"spark.rapids.tpu.sql.enabled": "true"})
    off = TpuSession({
        "spark.rapids.tpu.sql.enabled": "true",
        "spark.rapids.tpu.io.parquet.deviceDictDecode.enabled": "false"})
    got_on = on.read.parquet(path).collect()
    got_off = off.read.parquet(path).collect()
    assert_tables_equal(exp, got_on)        # exact: no approx_float
    assert_tables_equal(exp, got_off)
    # an aggregation over the dict-decoded doubles matches exactly too
    # (the f64 bits sibling must come from the gathered dictionary bits)
    from spark_rapids_tpu.api import functions as F
    q = lambda s: (s.read.parquet(path).groupBy("tag")
                   .agg(F.min("qty").alias("mn"), F.max("disc").alias("mx"),
                        F.count("d").alias("c")).sort("tag").collect())
    assert_tables_equal(q(cpu), q(on))


def test_parquet_page_decode_scan_path(tmp_path):
    """The raw-page dict decode rides the TPU scan end-to-end: fixed-width
    columns from io/parquet_pages.py, strings via pyarrow read_dictionary,
    PLAIN-fallback + nulls mixed in — bit-identical to the CPU engine and
    to the decoded path, across page versions."""
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.testing import assert_tables_equal

    rng = np.random.default_rng(9)
    n = 150000
    table = pa.table({
        "k": pa.array(rng.integers(0, 40, n), pa.int64()),
        "price": pa.array([None if i % 501 == 0 else float(rng.integers(1, 9000)) / 100
                           for i in range(n)], pa.float64()),
        "dense": pa.array(rng.random(n)),
        "d": pa.array(rng.integers(8000, 8200, n), pa.int32()).cast(pa.date32()),
        "tag": pa.array([None if i % 997 == 0 else f"tag{int(x)}"
                         for i, x in enumerate(rng.integers(0, 23, n))]),
    })
    for ver in ("1.0", "2.0"):
        path = str(tmp_path / f"pages_{ver}.parquet")
        pq.write_table(table, path, row_group_size=40000,
                       data_page_version=ver)
        cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
        exp = cpu.read.parquet(path).collect()
        tpu = TpuSession({"spark.rapids.tpu.sql.enabled": "true"})
        off = TpuSession({
            "spark.rapids.tpu.sql.enabled": "true",
            "spark.rapids.tpu.io.parquet.deviceDictDecode.enabled": "false"})
        assert_tables_equal(exp, tpu.read.parquet(path).collect())
        assert_tables_equal(exp, off.read.parquet(path).collect())
        # filtered + aggregated through the encoded scan
        from spark_rapids_tpu.api import functions as F
        q = lambda s: (s.read.parquet(path)
                       .filter(F.col("price") > 10.0)
                       .groupBy("tag").agg(F.sum("price").alias("sp"),
                                           F.max("d").alias("md"))
                       .sort("tag").collect())
        assert_tables_equal(q(cpu), q(tpu), approx_float=1e-9)
