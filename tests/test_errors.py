"""Failure-ladder taxonomy + wire codec (utils/errors.py): the declared
classification contract, MRO-aware spec lookup, the lazy re-exports, and
codec round-trips over both transports (pickle keeps namedtuple fidelity;
a JSON hop list-ifies tuples and decode coerces them back)."""
import json
import pickle

import pytest

from spark_rapids_tpu.utils import errors as uerr


# ------------------------------------------------------------------ registry
def test_every_spec_resolves_to_its_home_class():
    for spec in uerr.TAXONOMY:
        klass = uerr.resolve(spec)
        assert issubclass(klass, BaseException), spec.home
        assert klass.__name__ == spec.name


def test_lazy_reexports_match_home_definitions():
    from spark_rapids_tpu.shuffle.manager import ShuffleFetchFailedError
    from spark_rapids_tpu.serving.lifecycle import QueryCancelledError
    assert uerr.ShuffleFetchFailedError is ShuffleFetchFailedError
    assert uerr.QueryCancelledError is QueryCancelledError
    with pytest.raises(AttributeError):
        uerr.NoSuchError


def test_classification_lookup_walks_the_mro():
    from spark_rapids_tpu.shuffle.manager import ShuffleFetchFailedError

    class ScopedFetchError(ShuffleFetchFailedError):
        pass

    err = ScopedFetchError("x", executor_id="e", blocks=())
    assert uerr.classification_for(err) == uerr.ESCALATION_SIGNAL
    spec = uerr.spec_for(err)
    assert spec is not None and spec.wire_code == "SHUFFLE_FETCH_FAILED"
    assert uerr.classification_for(ValueError("x")) is None
    assert not uerr.is_retryable(ValueError("x"))


def test_ladder_signals_cover_the_declared_set():
    assert set(uerr.ladder_signals()) == {
        "ShuffleFetchFailedError", "SpillCorruptionError", "WireQueryError",
        "ChecksumError", "QueryCancelledError"}


def test_cancellation_and_retryable_predicates():
    from spark_rapids_tpu.serving.lifecycle import (QueryCancelledError,
                                                    SchedulerDrainingError)
    assert uerr.is_cancellation(QueryCancelledError("bye"))
    assert uerr.is_retryable(SchedulerDrainingError("draining"))
    assert not uerr.is_retryable(QueryCancelledError("bye"))


# ---------------------------------------------------------------- wire codec
def test_fetch_error_roundtrip_keeps_namedtuple_blocks():
    """Pickle transport (executor-daemon control socket): block ids must
    arrive as the same namedtuples — recompute reads b.shuffle_id/b.map_id
    off the payload."""
    from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
    from spark_rapids_tpu.shuffle.manager import ShuffleFetchFailedError
    blocks = (ShuffleBlockId(7, 2, 0), ShuffleBlockId(7, 3, 1))
    err = ShuffleFetchFailedError("lost", executor_id="exec-3", blocks=blocks)
    wire = pickle.loads(pickle.dumps(uerr.encode_error(err)))
    back = uerr.decode_error(wire)
    assert isinstance(back, ShuffleFetchFailedError)
    assert back.executor_id == "exec-3"
    assert back.blocks == blocks
    assert back.blocks[0].map_id == 2
    assert back.wire_code == "SHUFFLE_FETCH_FAILED"


def test_json_hop_roundtrip_recoerces_tuples():
    from spark_rapids_tpu.serving.client import WireQueryError
    err = WireQueryError("stream died", 5)
    wire = json.loads(json.dumps(uerr.encode_error(err), default=str))
    back = uerr.decode_error(wire)
    assert isinstance(back, WireQueryError)
    assert back.batches_delivered == 5
    assert "stream died" in str(back)


def test_fields_ctor_roundtrip():
    from spark_rapids_tpu.memory.buffer import SpillCorruptionError
    err = SpillCorruptionError(path="/spill/x", expected=1, actual=2)
    back = uerr.decode_error(uerr.encode_error(err))
    assert isinstance(back, SpillCorruptionError)
    assert back.path == "/spill/x"
    assert (back.expected, back.actual) == (1, 2)


def test_unregistered_type_degrades_to_opaque():
    class HomegrownError(Exception):
        pass

    wire = uerr.encode_error(HomegrownError("who am i"))
    assert wire["code"] == "OPAQUE"
    back = uerr.decode_error(wire)
    assert isinstance(back, uerr.OpaqueWireError)
    assert not uerr.is_retryable(back)        # opaque is never retried


def test_decode_never_raises_on_garbage():
    for blob in (None, "not a dict", {"no": "code"},
                 {"code": "UNKNOWN_FUTURE", "message": "from v99"}):
        back = uerr.decode_error(blob)
        assert isinstance(back, uerr.OpaqueWireError), blob
    # unknown-but-coded payloads keep their code for observability
    assert uerr.decode_error(
        {"code": "UNKNOWN_FUTURE", "message": "m"}).wire_code == \
        "UNKNOWN_FUTURE"


def test_message_override_ships_traceback():
    wire = uerr.encode_error(ValueError("boom"), message="Traceback ...")
    assert wire["message"] == "Traceback ..."


# -------------------------------------------------------------------- absorb
def test_absorb_counts_by_context_and_type():
    from spark_rapids_tpu.serving.client import WireQueryError
    key = "test.ctx:WireQueryError"
    before = uerr.ABSORBED_COUNTS.get(key, 0)
    uerr.absorb(WireQueryError("dying stream", 1), "test.ctx")
    uerr.absorb(WireQueryError("dying stream", 2), "test.ctx")
    assert uerr.ABSORBED_COUNTS[key] == before + 2


def test_boundary_markers_are_transparent():
    @uerr.triage_boundary
    def t(x):
        return x + 1

    @uerr.wire_boundary
    def w(x):
        return x * 2

    assert t(1) == 2 and w(2) == 4
    assert t.__ladder_triage_boundary__ and w.__ladder_wire_boundary__
