"""Mortgage ETL benchmark tests (mortgage_test.py / MortgageSparkSuite
analog)."""
import pytest

from spark_rapids_tpu.benchmarks import mortgage as M
from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow


def _dfs(s, scale=0.02, seed=0):
    return (s.create_dataframe(M.gen_performance(scale, seed)),
            s.create_dataframe(M.gen_acquisition(scale, seed)))


def test_mortgage_etl_matches_cpu():
    cpu = assert_tpu_and_cpu_equal(
        lambda s: M.clean_acquisition_prime(*_dfs(s)),
        conf=BENCH_CONF, ignore_order=True, approx_float=1e-9)
    assert cpu.num_rows > 1000
    # the ETL keeps one row per performance record
    assert "delinquency_12" in cpu.column_names
    assert "seller_name" in cpu.column_names


def test_mortgage_aggregates_match_cpu():
    cpu = assert_tpu_and_cpu_equal(
        lambda s: M.simple_aggregates(*_dfs(s)),
        conf=BENCH_CONF, ignore_order=True, approx_float=1e-9)
    assert cpu.num_rows > 10


def test_seller_name_mapping_applied():
    from spark_rapids_tpu.api import TpuSession
    s = TpuSession()
    out = M.create_acquisition(
        s.create_dataframe(M.gen_acquisition(0.02, 0))).collect()
    names = set(out.column("seller_name").to_pylist())
    # canonical names replace the raw spellings; unmapped ones pass through
    assert "Bank of America" in names or "Witmer" in names
    assert not any(n.endswith("N.A.") for n in names)
    assert "OTHER" in names
