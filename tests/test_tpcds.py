"""TPC-DS store-channel queries: TPU engine vs CPU engine (tpcds_test.py /
TpcdsLikeSpark analog for the store-channel subset)."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

_SCALE = 0.01

# queries whose sort keys can tie -> unordered compare
_TIES = {"q3", "q7", "q19", "q34", "q42", "q43", "q46", "q52", "q55", "q59",
         "q65", "q68", "q73", "q79", "q89", "q98"}

_MIN_ROWS = {"q3": 1, "q7": 1, "q19": 1, "q34": 1, "q42": 1, "q43": 1,
             "q46": 1, "q52": 1, "q55": 1, "q59": 10, "q65": 1, "q68": 1,
             "q79": 10, "q89": 10, "q96": 1, "q98": 10}


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=3)


@pytest.mark.parametrize("qname", sorted(QUERIES, key=lambda n: int(n[1:])))
def test_tpcds_query_matches_cpu(qname, tables):
    cpu = assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qname](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=BENCH_CONF,
        ignore_order=qname in _TIES,
        approx_float=1e-9)
    assert cpu.num_rows >= _MIN_ROWS.get(qname, 0), (
        f"{qname} returned {cpu.num_rows} rows; the generator no longer "
        f"qualifies rows for its predicates")
