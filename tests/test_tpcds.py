"""TPC-DS store-channel queries: TPU engine vs CPU engine (tpcds_test.py /
TpcdsLikeSpark analog for the store-channel subset)."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

# queries whose sort keys can tie -> unordered compare
_TIES = {"q3", "q7", "q19", "q34", "q42", "q43", "q46", "q52", "q55", "q59",
         "q65", "q68", "q73", "q79", "q89", "q98",
         "q15", "q18", "q20", "q25", "q26", "q29", "q45", "q62", "q93",
         "q99",
         "q6", "q17", "q33", "q36", "q47", "q53", "q60", "q63", "q69",
         "q76", "q86",
         "q50", "q71",
         "q1", "q2", "q4", "q5", "q8", "q9", "q10", "q11", "q12", "q14",
         "q22", "q23", "q24", "q27", "q30", "q31", "q35", "q38", "q39",
         "q49", "q51", "q54", "q56", "q57", "q58", "q64", "q66", "q67",
         "q70", "q72", "q74", "q75", "q77", "q78", "q80", "q81", "q83",
         "q84", "q85", "q91", "q95"}

# Queries whose predicates the synthetic generator does not qualify at this
# scale (verified empty on the CPU engine at SF 0.01 AND 0.04): these cannot
# carry a row floor yet — every OTHER query must return rows (default floor
# 1, so a query pruned to nothing by a regression can no longer pass
# vacuously).
_KNOWN_EMPTY = {"q4", "q8", "q54", "q58", "q66", "q73", "q78", "q83", "q91"}

_MIN_ROWS = {"q3": 1, "q7": 1, "q19": 1, "q34": 1, "q42": 1, "q43": 1,
             "q46": 1, "q52": 1, "q55": 1, "q59": 10, "q65": 1, "q68": 1,
             "q79": 10, "q89": 10, "q96": 1, "q98": 10,
             "q15": 1, "q16": 1, "q18": 10, "q20": 5, "q21": 5, "q25": 1,
             "q26": 1, "q29": 1, "q32": 1, "q37": 1, "q40": 1, "q45": 1,
             "q62": 10, "q90": 1, "q92": 1, "q93": 10, "q94": 1, "q99": 10,
             "q6": 1, "q13": 1, "q17": 5, "q28": 1, "q33": 5, "q36": 10,
             "q44": 5, "q47": 10, "q53": 10, "q60": 1, "q63": 10, "q69": 5,
             "q76": 10, "q86": 10, "q88": 1,
             "q41": 1, "q48": 1, "q50": 1, "q61": 1, "q71": 1, "q82": 1,
             "q87": 1, "q97": 1,
             "q2": 10, "q9": 1, "q10": 1, "q22": 10, "q23": 1, "q27": 10,
             "q35": 10, "q38": 1, "q39": 10, "q49": 10, "q51": 1, "q56": 5,
             "q57": 10, "q64": 10, "q67": 10, "q70": 5, "q72": 10,
             "q77": 10, "q80": 10, "q84": 10, "q85": 1, "q95": 1}


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=3)


@pytest.fixture(autouse=True)
def _drop_compiled_executables():
    """Every query compiles fresh XLA programs; dropping them between tests
    keeps the accumulated compiled-program state bounded (the CPU backend has
    segfaulted compiling the ~47th large program of one process)."""
    yield
    import jax
    jax.clear_caches()


# scalar-aggregate queries always return one row, so the row-count guard is
# vacuous; assert the named aggregate actually saw qualifying rows instead.
# q13/q32/q90/q92/q96 are knowingly absent: their compound predicates
# (triple demographic+price bands, 1.3x-average excess discounts, narrow
# half-hour windows) legitimately qualify zero rows at this generator scale,
# so only engine parity is asserted for them.
_SCALAR_CHECK = {"q48": "sum_quantity", "q61": "total", "q87": "cnt",
                 "q97": "store_and_catalog"}


@pytest.mark.parametrize("qname", sorted(QUERIES, key=lambda n: int(n[1:])))
def test_tpcds_query_matches_cpu(qname, tables):
    cpu = assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qname](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=BENCH_CONF,
        ignore_order=qname in _TIES,
        approx_float=1e-9)
    floor = 0 if qname in _KNOWN_EMPTY else _MIN_ROWS.get(qname, 1)
    assert cpu.num_rows >= floor, (
        f"{qname} returned {cpu.num_rows} rows; the generator no longer "
        f"qualifies rows for its predicates")
    check = _SCALAR_CHECK.get(qname)
    if check is not None:
        v = cpu.column(check)[0].as_py()
        assert v is not None and v > 0, (
            f"{qname}: {check}={v!r}; the generator no longer qualifies "
            f"rows for its predicates")
