"""Transfer pipeline: chunked overlapped uploads, PipelinedExec bounded-async
dispatch, streaming collect, and the prefetch-producer lifecycle fixes."""
import gc
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import transfer
from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.dtypes import Schema
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.base import ExecContext, LeafExec
from spark_rapids_tpu.execs.pipeline import PipelinedExec
from spark_rapids_tpu.testing import assert_tables_equal
from spark_rapids_tpu.utils import metrics as um


def _mixed_table(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array(rng.integers(0, 1000, n), pa.int64()),
        "d": pa.array(rng.random(n) * 1e9, pa.float64()),
        "s": pa.array([f"v{v}" for v in rng.integers(0, 50, n)],
                      pa.string()).dictionary_encode(),
        "nn": pa.array([None if v % 7 == 0 else int(v)
                        for v in rng.integers(0, 100, n)], pa.int32()),
        "b": pa.array([bool(v % 2) for v in range(n)]),
    })


def _assert_batches_bit_equal(single: DeviceBatch, chunked: DeviceBatch):
    """Live rows bit-exact; padding past num_rows is garbage by contract
    (columnar/column.py) so only validity/bits — which both paths zero-pad —
    compare across the full capacity."""
    assert chunked.num_rows == single.num_rows
    assert chunked.capacity == single.capacity
    n = single.num_rows
    for ci, (a, b) in enumerate(zip(single.columns, chunked.columns)):
        assert np.array_equal(np.asarray(a.data[:n]), np.asarray(b.data[:n])), ci
        assert np.array_equal(np.asarray(a.validity), np.asarray(b.validity)), ci
        if a.lengths is not None:
            assert np.array_equal(np.asarray(a.lengths[:n]),
                                  np.asarray(b.lengths[:n])), ci
        assert (a.bits is None) == (b.bits is None), ci
        if a.bits is not None:
            assert np.array_equal(np.asarray(a.bits), np.asarray(b.bits)), ci


# --------------------------------------------------------------- chunk bounds
def test_chunk_bounds_splits_oversized():
    t = pa.table({"a": np.arange(10_000)})
    bounds = transfer.chunk_bounds(t, 3000)
    assert bounds[0] == 0
    sizes = [b - a for a, b in zip(bounds, bounds[1:] + [10_000])]
    assert all(s <= 3000 for s in sizes)
    assert sum(sizes) == 10_000


def test_chunk_bounds_single_chunk():
    t = pa.table({"a": np.arange(100)})
    assert transfer.chunk_bounds(t, 0) == [0]
    assert transfer.chunk_bounds(t, 100) == [0]
    assert transfer.chunk_bounds(t, 1000) == [0]


def test_chunk_bounds_prefers_record_batch_edges():
    parts = [pa.record_batch([pa.array(np.arange(900))], names=["a"])
             for _ in range(4)]
    t = pa.Table.from_batches(parts)
    bounds = transfer.chunk_bounds(t, 1000)
    # record-batch edges (multiples of 900) are taken instead of raw 1000s
    assert bounds == [0, 900, 1800, 2700]


# ------------------------------------------------------- chunked upload
def test_chunked_upload_bit_equal_mixed_schema():
    t = _mixed_table()
    single = DeviceBatch.from_arrow(t, 16)
    chunked = transfer.upload_table(t, 16, chunk_rows=700, max_inflight=2)
    _assert_batches_bit_equal(single, chunked)
    assert single.to_arrow().equals(chunked.to_arrow())


def test_chunked_upload_double_bits_sibling_carried():
    t = pa.table({"d": pa.array(np.random.default_rng(1).random(3000) * 1e18)})
    single = DeviceBatch.from_arrow(t, 16)
    chunked = transfer.upload_table(t, 16, chunk_rows=500)
    assert chunked.columns[0].bits is not None
    _assert_batches_bit_equal(single, chunked)


def test_chunked_upload_all_null_and_empty_chunks():
    t = pa.table({"x": pa.array([None] * 1000, pa.int32()),
                  "y": pa.array(["s"] * 1000, pa.string())})
    single = DeviceBatch.from_arrow(t, 16)
    chunked = transfer.upload_table(t, 16, chunk_rows=130)
    _assert_batches_bit_equal(single, chunked)


def test_upload_small_table_takes_single_shot_path():
    t = _mixed_table(64)
    stats = {}
    b = transfer.upload_table(t, 16, chunk_rows=1000, stats=stats)
    assert stats["chunks"] == 1
    _assert_batches_bit_equal(DeviceBatch.from_arrow(t, 16), b)


def test_upload_counts_transfer_metrics():
    before = um.transfer_snapshot()
    transfer.upload_table(_mixed_table(2000), 16, chunk_rows=300)
    delta = um.transfer_delta(before)
    assert delta[um.TRANSFER_UPLOAD_BYTES] > 0
    assert delta[um.TRANSFER_UPLOAD_SECONDS] > 0
    assert delta[um.TRANSFER_UPLOAD_CHUNKS] >= 5
    assert "transfer.upload_gb_per_sec" in delta


def test_stats_overlap_efficiency_bounds():
    stats = {}
    transfer.upload_table(_mixed_table(3000), 16, chunk_rows=400,
                          max_inflight=3, stats=stats)
    assert 0 < stats["upload_overlap_efficiency"] <= 1
    assert 1 <= stats["inflight_high_water"] <= 3
    assert len(stats["per_chunk_upload_s"]) == stats["chunks"]


# ------------------------------------------------------- concat bits handling
def test_concat_device_batches_carries_bits():
    from spark_rapids_tpu.execs.tpu_execs import concat_device_batches
    t1 = pa.table({"d": pa.array([1.5, 2.5, 3.5])})
    t2 = pa.table({"d": pa.array([4.5, 5.5])})
    b1 = DeviceBatch.from_arrow(t1, 16)
    b2 = DeviceBatch.from_arrow(t2, 16)
    out = concat_device_batches([b1, b2], b1.schema, 16)
    assert out.columns[0].bits is not None
    expect = np.array([1.5, 2.5, 3.5, 4.5, 5.5]).view(np.uint64)
    assert np.array_equal(np.asarray(out.columns[0].bits[:5]), expect)


def test_concat_device_batches_drops_partial_bits():
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.execs.tpu_execs import concat_device_batches
    b1 = DeviceBatch.from_arrow(pa.table({"d": pa.array([1.5, 2.5])}), 16)
    c = b1.columns[0]
    no_bits = DeviceBatch(b1.schema,
                          (DeviceColumn(c.dtype, c.data, c.validity),), 2)
    out = concat_device_batches([b1, no_bits], b1.schema, 16)
    assert out.columns[0].bits is None


# ------------------------------------------------------------- PipelinedExec
class _ListSource(LeafExec):
    """Device-batch source with optional injected fault at batch ``fail_at``
    and a cleanup flag so early-exit tests can assert the generator's
    finally ran."""

    is_device = True
    is_file_scan = True

    def __init__(self, batches, fail_at=None):
        super().__init__(batches[0].schema if batches else Schema([]))
        self.batches = batches
        self.fail_at = fail_at
        self.closed = False
        self.produced = 0

    def execute(self, ctx):
        try:
            for i, b in enumerate(self.batches):
                if self.fail_at is not None and i == self.fail_at:
                    raise RuntimeError(f"injected fault at batch {i}")
                self.produced += 1
                yield b
        finally:
            self.closed = True


def _batches(k, rows=8):
    return [DeviceBatch.from_arrow(
        pa.table({"v": pa.array(np.full(rows, i, np.int64))}), 16)
        for i in range(k)]


def test_pipelined_exec_preserves_order():
    src = _ListSource(_batches(12))
    pipe = PipelinedExec(src, depth=3)
    out = list(pipe.execute(ExecContext(TpuConf())))
    vals = [int(np.asarray(b.columns[0].data)[0]) for b in out]
    assert vals == list(range(12))
    assert src.closed


def test_pipelined_exec_propagates_injected_fault_in_order():
    src = _ListSource(_batches(10), fail_at=4)
    pipe = PipelinedExec(src, depth=2)
    got = []
    with pytest.raises(RuntimeError, match="injected fault at batch 4"):
        for b in pipe.execute(ExecContext(TpuConf())):
            got.append(int(np.asarray(b.columns[0].data)[0]))
    assert got == [0, 1, 2, 3]      # everything before the fault, in order
    assert src.closed


def test_pipelined_exec_early_close_stops_producer():
    src = _ListSource(_batches(50))
    pipe = PipelinedExec(src, depth=2)
    it = pipe.execute(ExecContext(TpuConf()))
    next(it)
    it.close()
    deadline = time.time() + 5
    while time.time() < deadline and not src.closed:
        time.sleep(0.01)
    assert src.closed
    # bounded: the producer never ran ahead by more than depth + handoff
    assert src.produced <= 2 + 2 + 1
    assert not [t for t in threading.enumerate()
                if t.name == "exec-pipeline" and t.is_alive()]


def test_pipelined_exec_depth_zero_passthrough():
    src = _ListSource(_batches(3))
    out = list(PipelinedExec(src, depth=0).execute(ExecContext(TpuConf())))
    assert len(out) == 3


def test_pipelined_exec_shares_semaphore_hold():
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    dm = DeviceManager.initialize()
    src = _ListSource(_batches(6))
    pipe = PipelinedExec(src, depth=2)
    ctx = ExecContext(TpuConf(), device_manager=dm)
    with dm.semaphore.held():
        assert dm.semaphore.active_holders == 1
        out = list(pipe.execute(ctx))
        assert len(out) == 6
        # producer nested into THIS task's hold: still one holder
        assert dm.semaphore.active_holders == 1
    assert dm.semaphore.active_holders == 0


class _PassThrough(LeafExec):
    """Device op with a pipelined child (device->host->device sandwich
    shape): nests pipeline boundaries like real plans do."""

    is_device = True

    def __init__(self, child):
        super().__init__(child.output)
        self.children = (child,)

    def execute(self, ctx):
        yield from self.children[0].execute(ctx)


def test_nested_pipelines_share_one_semaphore_permit():
    """Three nested pipeline boundaries under a 2-permit semaphore: every
    producer must fold into the OWNING TASK's hold (ctx.task_id), or the
    inner producers exhaust admission and the plan deadlocks."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    dm = DeviceManager.initialize()
    plan = PipelinedExec(_PassThrough(PipelinedExec(_PassThrough(
        PipelinedExec(_ListSource(_batches(5)), 2)), 2)), 2)
    done = {}

    def run():
        # the task thread builds its own ctx (as _run_partitions does), so
        # ctx.task_id is the thread that takes the semaphore hold
        ctx = ExecContext(TpuConf(), device_manager=dm)
        with dm.semaphore.held():
            done["out"] = list(plan.execute(ctx))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(20)
    assert not t.is_alive(), "nested pipelines deadlocked on the semaphore"
    assert len(done["out"]) == 5
    assert dm.semaphore.active_holders == 0


# ------------------------------------------------------- planner insertion
def _count_pipelined(plan):
    hits = 1 if isinstance(plan, PipelinedExec) else 0
    return hits + sum(_count_pipelined(c) for c in plan.children)


def test_planner_inserts_pipeline_over_scan(monkeypatch, tmp_path):
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": np.arange(1000, dtype=np.int64)}), path)
    sess = TpuSession()
    df = sess.read.parquet(path).filter(F.col("a") > 10)
    df.collect()
    assert _count_pipelined(sess.last_plan) == 1
    off = TpuSession({"spark.rapids.tpu.transfer.pipeline.enabled": "false"})
    df2 = off.read.parquet(path).filter(F.col("a") > 10)
    df2.collect()
    assert _count_pipelined(off.last_plan) == 0


def test_planner_skips_pipeline_on_single_core(monkeypatch, tmp_path):
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": np.arange(100, dtype=np.int64)}), path)
    sess = TpuSession()
    df = sess.read.parquet(path).filter(F.col("a") > 10)
    df.collect()
    assert _count_pipelined(sess.last_plan) == 0


# ------------------------------------------------------- parquet prefetch
def _write_grouped(tmp_path, rows=5000, groups=10):
    path = str(tmp_path / "g.parquet")
    pq.write_table(pa.table({
        "a": np.arange(rows, dtype=np.int64),
        "d": np.linspace(0.0, 1.0, rows),
    }), path, row_group_size=rows // groups)
    return path


def test_early_exit_limit_over_prefetched_scan(monkeypatch, tmp_path):
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    path = _write_grouped(tmp_path)
    sess = TpuSession({"spark.rapids.tpu.io.scan.prefetchBatches": "2",
                       "spark.rapids.tpu.sql.reader.batchSizeRows": "500"})
    out = sess.read.parquet(path).limit(7).collect()
    assert out.num_rows == 7
    gc.collect()
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.name == "parquet-scan-prefetch" and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, "prefetch producer thread leaked after early exit"


def test_prefetched_scan_error_propagates(monkeypatch, tmp_path):
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    path = _write_grouped(tmp_path, rows=1000, groups=2)
    sess = TpuSession({"spark.rapids.tpu.io.scan.prefetchBatches": "2"})
    df = sess.read.parquet(path)
    os.remove(path)     # fault: file disappears between plan and execute
    from spark_rapids_tpu.io.parquet import _clipped_groups_cached
    _clipped_groups_cached.cache_clear()
    with pytest.raises(Exception):
        df.collect()


def test_prefetch_device_propagation(monkeypatch, tmp_path, eight_devices):
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    import jax
    target = jax.devices()[1]
    path = _write_grouped(tmp_path, rows=600, groups=2)
    from spark_rapids_tpu.io.datasource import PartitionedFile
    from spark_rapids_tpu.io.parquet import TpuParquetScanExec
    schema = Schema.from_pa(pq.read_schema(path))
    scan = TpuParquetScanExec((PartitionedFile(path),), schema)
    ctx = ExecContext(TpuConf({
        "spark.rapids.tpu.io.scan.prefetchBatches": "2"}), device=target)
    batches = list(scan.execute(ctx))
    assert batches
    for b in batches:
        assert next(iter(b.columns[0].data.devices())) == target


# ------------------------------------------------------- streaming collect
def _q1ish(df):
    return (df.filter(F.col("i") > 100)
              .groupBy("s").agg(F.min("nn").alias("mn"),
                                F.max("d").alias("mx"),
                                F.count(F.lit(1)).alias("c"))
              .sort("s"))


def test_streaming_collect_matches_sync_collect():
    t = _mixed_table(3000, seed=3)
    res = {}
    for mode in ("true", "false"):
        sess = TpuSession({
            "spark.rapids.tpu.transfer.streamingCollect.enabled": mode,
            "spark.rapids.tpu.sql.scanCache.enabled": "false",
            "spark.rapids.tpu.transfer.chunkRows": "700"})
        res[mode] = _q1ish(sess.create_dataframe(t)).collect()
    assert_tables_equal(res["true"], res["false"])


def test_streaming_collect_many_batches_order(tmp_path):
    path = _write_grouped(tmp_path, rows=4000, groups=8)
    sess = TpuSession({"spark.rapids.tpu.sql.reader.batchSizeRows": "500",
                       "spark.rapids.tpu.transfer.maxInflight": "2"})
    out = sess.read.parquet(path).collect()
    assert np.array_equal(np.asarray(out.column("a")),
                          np.arange(4000, dtype=np.int64))
    tm = sess.last_metrics.get("transfer", {})
    assert tm.get(um.TRANSFER_DOWNLOAD_BYTES, 0) > 0


def test_streaming_collect_empty_result():
    sess = TpuSession()
    t = pa.table({"a": pa.array([1, 2, 3], pa.int64())})
    out = sess.create_dataframe(t).filter(F.col("a") > 99).collect()
    assert out.num_rows == 0
