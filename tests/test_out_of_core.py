"""Out-of-core operators: grace partitioning, footprint contract, faults.

Covers the degradation model (docs/out-of-core.md):
- size_estimate audit: every PhysicalExec subclass returns a real estimate
  or documents WHY None (the contract the footprint planner consumes);
- forced / predicted / reactive / fault-injected partitioning for hash
  aggregate, hash join and sort, each bit-identical to the single-pass run;
- recursion under a tiny budget stays bounded and completes;
- dictionary encodings and f64 bits siblings survive the partition split;
- the store's pressure callbacks and spilled-bytes-per-tier counters;
- observability: session.last_metrics["memory"] + per-query snapshots;
- the hot path stays untouched when everything fits.
"""
import importlib
import pkgutil

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.memory import faults as mfaults
from spark_rapids_tpu.memory.device_manager import DeviceManager
from spark_rapids_tpu.testing import assert_tables_equal

BASE_CONF = {
    "spark.rapids.tpu.sql.hasNans": "false",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}

TINY_BUDGET = {
    "spark.rapids.tpu.memory.tpu.poolSizeBytes": str(256 << 10),
    "spark.rapids.tpu.memory.host.spillStorageSize": str(256 << 10),
    "spark.rapids.tpu.sql.scanCache.enabled": "false",
}


@pytest.fixture(autouse=True)
def _fresh_memory_state():
    """Each test gets a fresh DeviceManager (budget confs differ wildly)
    and a fresh fault-plan schedule."""
    DeviceManager.shutdown()
    mfaults.reset_plans()
    yield
    DeviceManager.shutdown()
    mfaults.reset_plans()


def make_table(n=40000, seed=0, groups=64):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, groups, n).astype("int64"),
        "v": rng.integers(0, 1000, n).astype("int64"),
        "d": np.round(rng.random(n), 6),
    })


def agg_df(sess, table):
    return (sess.create_dataframe(table).groupBy("k")
            .agg(F.sum("v").alias("sv"), F.count(F.lit(1)).alias("c"),
                 F.sum("d").alias("sd")))


def assert_agg_equal(ref, got):
    """Integer keys/sums/counts bitwise; the variableFloatAgg double sum to
    1e-9 relative — the partitioned reduction runs at a different capacity
    bucket, so its XLA reduction tree (and last-ulp rounding) legitimately
    differs, exactly the mesh distributed-float-sum contract
    (docs/mesh-execution.md, docs/out-of-core.md)."""
    assert_tables_equal(ref.select(["k", "sv", "c"]),
                        got.select(["k", "sv", "c"]), ignore_order=True)
    assert_tables_equal(ref, got, ignore_order=True, approx_float=1e-9)


def mem_metrics(sess):
    return sess.last_metrics.get("memory", {})


# --------------------------------------------------------- size_estimate audit
def _all_exec_classes():
    import spark_rapids_tpu
    from spark_rapids_tpu.execs.base import PhysicalExec
    for pkg in ("execs", "io", "plan", "parallel", "memory"):
        mod = importlib.import_module(f"spark_rapids_tpu.{pkg}")
        for info in pkgutil.iter_modules(mod.__path__):
            importlib.import_module(f"spark_rapids_tpu.{pkg}.{info.name}")

    def subs(cls):
        out = set()
        for sc in cls.__subclasses__():
            out.add(sc)
            out |= subs(sc)
        return out
    # the contract binds the ENGINE's classes; test modules define throwaway
    # exec subclasses (fixtures) that are out of scope
    return {c for c in subs(PhysicalExec)
            if c.__module__.startswith("spark_rapids_tpu.")}


def test_size_estimate_contract_every_exec_class():
    """Every exec class defines size_estimate below PhysicalExec in its MRO
    or carries a non-empty size_estimate_none_reason — the footprint
    contract the out-of-core planner consumes. LeafExec is the one
    exempted pure-abstract base: concrete leaves must declare their own
    (scan file sizes, range row counts), and a new leaf that forgets
    fails here."""
    from spark_rapids_tpu.execs.base import LeafExec, PhysicalExec
    violations = []
    for cls in _all_exec_classes():
        if cls is LeafExec:
            continue
        defined = any("size_estimate" in k.__dict__
                      for k in cls.__mro__ if k is not PhysicalExec)
        reason = getattr(cls, "size_estimate_none_reason", None)
        if not defined and not (isinstance(reason, str) and reason.strip()):
            violations.append(f"{cls.__module__}.{cls.__name__}")
    assert not violations, (
        "exec classes missing a size_estimate or a documented None reason: "
        f"{sorted(violations)}")


def test_size_estimates_sane_on_simple_plan():
    sess = TpuSession(BASE_CONF)
    table = make_table(2000)
    df = agg_df(sess, table)
    plan = df._executed_plan()
    est = plan.size_estimate()
    assert est is not None and 0 < est < 10 * table.nbytes

    def walk(node):
        yield node
        for c in node.children:
            yield from walk(c)
    ws = [n.working_set_estimate() for n in walk(plan)]
    assert any(w is not None and w > 0 for w in ws), \
        "no working-set operator declared a footprint"


# --------------------------------------------------------- forced partitioning
def test_forced_partitions_aggregate_bit_identical():
    table = make_table()
    ref = agg_df(TpuSession(BASE_CONF), table).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    got = agg_df(sess, table).collect()
    assert_agg_equal(ref, got)
    mm = mem_metrics(sess)
    assert mm["memory.spill_partitions"] == 4, mm
    assert mm["memory.recursion_depth_peak"] >= 1, mm


def test_forced_partitions_join_bit_identical():
    rng = np.random.default_rng(3)
    left = make_table(20000, seed=1)
    right = pa.table({"k": rng.integers(0, 64, 4000).astype("int64"),
                      "w": rng.integers(0, 9, 4000).astype("int64")})
    def q(sess):
        return (sess.create_dataframe(left)
                .join(sess.create_dataframe(right), on="k")
                .groupBy("k").agg(F.count(F.lit(1)).alias("c"),
                                  F.sum("w").alias("sw")))
    ref = q(TpuSession(BASE_CONF)).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    got = q(sess).collect()
    assert_tables_equal(ref, got, ignore_order=True)
    assert mem_metrics(sess)["memory.spill_partitions"] >= 8


@pytest.mark.parametrize("how", ["left", "right", "left_semi", "left_anti"])
def test_forced_partitions_join_types(how):
    """Outer/semi/anti joins: unmatched-ness is decided inside a partition
    because BOTH sides of a key hash to the same one (nulls included)."""
    left = pa.table({"k": pa.array([1, 2, 2, None, 5, 6] * 50,
                                   type=pa.int64()),
                     "v": pa.array(list(range(300)), type=pa.int64())})
    right = pa.table({"k": pa.array([2, 3, None, 6] * 30, type=pa.int64()),
                      "w": pa.array(list(range(120)), type=pa.int64())})
    def q(sess):
        return (sess.create_dataframe(left)
                .join(sess.create_dataframe(right), on="k", how=how))
    ref = q(TpuSession(BASE_CONF)).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    got = q(sess).collect()
    assert_tables_equal(ref, got, ignore_order=True)


def test_forced_partitions_sort_exact_order():
    table = make_table(30000, seed=2)
    def q(sess):
        return (sess.create_dataframe(table)
                .sort("k", F.col("v").desc(), "d"))
    ref = q(TpuSession(BASE_CONF)).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    got = q(sess).collect()
    # STRICT order: the external sort's bound-ordered emission must equal
    # the single-pass stable sort bit-for-bit
    assert ref.equals(got)
    assert mem_metrics(sess)["memory.spill_partitions"] >= 4


def test_forced_partitions_sort_with_nulls():
    rng = np.random.default_rng(5)
    vals = rng.integers(0, 50, 5000).astype("float64")
    mask = rng.random(5000) < 0.1
    table = pa.table({"k": pa.array([v if not m else None
                                     for v, m in zip(vals, mask)],
                                    type=pa.float64()),
                      "r": pa.array(list(range(5000)), type=pa.int64())})
    def q(sess):
        return sess.create_dataframe(table).sort("k", "r")
    ref = q(TpuSession(BASE_CONF)).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    got = q(sess).collect()
    assert ref.equals(got)


def test_forced_partitions_empty_input():
    empty = make_table(0)
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "4"})
    assert agg_df(sess, empty).collect().num_rows == 0
    assert sess.create_dataframe(empty).sort("k").collect().num_rows == 0


# ----------------------------------------------------- predicted (plan hints)
def test_tiny_budget_predicts_partitioning_and_spills():
    table = make_table(60000)
    ref = agg_df(TpuSession(BASE_CONF), table).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF, **TINY_BUDGET})
    got = agg_df(sess, table).collect()
    assert_agg_equal(ref, got)
    mm = mem_metrics(sess)
    assert mm["memory.spill_partitions"] >= 2, mm
    assert mm["memory.bytes_spilled_to_host"] > 0, mm

    def walk(node):
        yield node
        for c in node.children:
            yield from walk(c)
    assert any(getattr(n, "grace_partitions", 0) > 0
               for n in walk(sess.last_plan)), \
        "planner did not annotate grace_partitions under a tiny budget"


def test_tiny_budget_sort_exact_and_recursion_bounded():
    table = make_table(60000, seed=7)
    ref = TpuSession(BASE_CONF).create_dataframe(table) \
        .sort("k", "v", "d").collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF, **TINY_BUDGET,
                       "spark.rapids.tpu.memory.outOfCore."
                       "maxRecursionDepth": "3"})
    got = sess.create_dataframe(table).sort("k", "v", "d").collect()
    assert ref.equals(got)
    mm = mem_metrics(sess)
    assert 1 <= mm["memory.recursion_depth_peak"] <= 3, mm


def test_footprint_pass_no_hints_with_ample_budget():
    sess = TpuSession(BASE_CONF)
    df = agg_df(sess, make_table(2000))
    plan = df._executed_plan()

    def walk(node):
        yield node
        for c in node.children:
            yield from walk(c)
    assert all(getattr(n, "grace_partitions", 0) == 0 for n in walk(plan))


def test_choose_partitions_scales_and_clamps():
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plan.footprint import choose_partitions
    conf = TpuConf()
    assert choose_partitions(1 << 20, 1 << 30, conf) == 2
    n = choose_partitions(1 << 30, 1 << 24, conf)
    assert n >= 64 and n & (n - 1) == 0          # pow2
    assert choose_partitions(1 << 40, 1 << 20, conf) == 256  # clamped


def test_degenerate_split_stops_recursion():
    """ONE key group exceeds the budget: no hash depth can split it, so
    after one degenerate probe the partition runs single-pass instead of
    burning the whole depth budget on re-splits."""
    n = 60000
    table = pa.table({"k": np.ones(n, dtype="int64"),
                      "v": np.arange(n, dtype="int64")})
    ref = TpuSession(BASE_CONF).create_dataframe(table) \
        .groupBy("k").agg(F.sum("v").alias("sv")).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF, **TINY_BUDGET})
    got = sess.create_dataframe(table).groupBy("k") \
        .agg(F.sum("v").alias("sv")).collect()
    assert_tables_equal(ref, got)
    mm = mem_metrics(sess)
    # initial split + at most one degenerate probe level
    assert mm["memory.recursion_depth_peak"] <= 2, mm


# ------------------------------------------------------------ fault injection
def test_alloc_fail_forces_reactive_path():
    table = make_table()
    ref = agg_df(TpuSession(BASE_CONF), table).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.faults.plan":
                           "alloc_fail:op=agg,after=1"})
    got = agg_df(sess, table).collect()
    assert_agg_equal(ref, got)
    mm = mem_metrics(sess)
    assert mm["memory.pressure_events"] >= 1, mm
    assert mm["memory.spill_partitions"] >= 2, mm
    plan = mfaults.plan_for_conf(sess.conf)
    assert ("alloc_fail", "agg", 1) in plan.fired


@pytest.mark.parametrize("op,build", [
    ("join", lambda s, t: (s.create_dataframe(t)
                           .join(s.create_dataframe(t.slice(0, 2000)
                                                    .select(["k"])),
                                 on="k")
                           .groupBy("k").count())),
    ("sort", lambda s, t: s.create_dataframe(t).sort("k", "v")),
])
def test_alloc_fail_other_operators(op, build):
    table = make_table(12000, seed=11)
    ref = build(TpuSession(BASE_CONF), table).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.faults.plan":
                           f"alloc_fail:op={op},after=1"})
    got = build(sess, table).collect()
    if op == "sort":
        assert ref.equals(got)
    else:
        assert_tables_equal(ref, got, ignore_order=True)
    assert any(f[0] == "alloc_fail" and f[1] == op
               for f in mfaults.plan_for_conf(sess.conf).fired)


def test_budget_clamp_shrinks_effective_budget():
    table = make_table(60000)
    ref = agg_df(TpuSession(BASE_CONF), table).collect()
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.sql.scanCache.enabled": "false",
                       "spark.rapids.tpu.memory.faults.plan":
                           "budget_clamp:fraction=0.0001,count=0"})
    got = agg_df(sess, table).collect()
    assert_agg_equal(ref, got)
    assert mem_metrics(sess)["memory.pressure_events"] >= 1


def test_fault_plan_deterministic_replay():
    spec_text = "alloc_fail:op=agg,after=2,count=2"
    a = mfaults.MemoryFaultPlan.parse(spec_text, seed=9)
    b = mfaults.MemoryFaultPlan.parse(spec_text, seed=9)
    for plan in (a, b):
        for _ in range(5):
            plan.on_admission("agg")
        plan.on_admission("sort")       # separate per-op counter
    assert a.fired == b.fired
    assert a.fired == [("alloc_fail", "agg", 2), ("alloc_fail", "agg", 3)]


def test_fault_plan_parse_errors():
    with pytest.raises(ValueError, match="unknown memory fault kind"):
        mfaults.MemoryFaultSpec.parse("explode:op=agg")
    with pytest.raises(ValueError, match="unknown op"):
        mfaults.MemoryFaultSpec.parse("alloc_fail:op=window")
    with pytest.raises(ValueError, match="unknown memory fault key"):
        mfaults.MemoryFaultSpec.parse("alloc_fail:nope=1")
    with pytest.raises(ValueError, match="fraction"):
        mfaults.MemoryFaultSpec.parse("budget_clamp:fraction=1.5")


def test_budget_clamp_probe_math():
    # a bare clamp is SUSTAINED (count defaults to 0 = every read)
    plan = mfaults.MemoryFaultPlan.parse("budget_clamp:fraction=0.25")
    assert plan.clamp_budget("agg", 1 << 20) == 1 << 18
    assert plan.clamp_budget("agg", 1 << 20) == 1 << 18
    plan2 = mfaults.MemoryFaultPlan.parse(
        "budget_clamp:fraction=0.5,after=2,count=1")
    assert plan2.clamp_budget("agg", 100) == 100      # window not open yet
    assert plan2.clamp_budget("agg", 100) == 50
    assert plan2.clamp_budget("agg", 100) == 100      # window closed


# ------------------------------------------------------- carriers + internals
def _encoded_batch():
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
    from spark_rapids_tpu.columnar.encoding import DictEncoding
    n, cap = 100, 128
    idx = np.arange(n, dtype=np.int32) % 4
    values = jnp.asarray(np.array([10, 20, 30, 40], dtype=np.int64))
    data = jnp.asarray(np.array([10, 20, 30, 40], dtype=np.int64)[idx])
    pad = np.zeros(cap - n, dtype=np.int64)
    data = jnp.concatenate([data, jnp.asarray(pad)])
    validity = jnp.asarray(np.arange(cap) < n)
    indices = jnp.concatenate([jnp.asarray(idx),
                               jnp.zeros(cap - n, jnp.int32)])
    enc = DictEncoding(indices, values, 4, None, token="t-test")
    col = DeviceColumn(DType.LONG, data, validity, encoding=enc)
    key = DeviceColumn(
        DType.LONG,
        jnp.concatenate([jnp.asarray(np.arange(n, dtype=np.int64) % 8),
                         jnp.asarray(pad)]), validity)
    schema = Schema([Field("g", DType.LONG, False),
                     Field("e", DType.LONG, False)])
    return DeviceBatch(schema, (key, col), n)


def test_split_carries_dictionary_encoding():
    from spark_rapids_tpu.execs.base import ExecContext
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.memory import grace
    batch = _encoded_batch()
    ctx = ExecContext()
    keys = (BoundReference(0, DType.LONG, False),)
    pieces = list(grace.split_batch(ctx, batch, keys, 4, depth=0))
    assert len(pieces) >= 2
    total = 0
    for _pid, piece in pieces:
        enc = piece.columns[1].encoding
        assert enc is not None and enc.token == "t-test"
        # invariant: data == values[indices] for live rows
        d = np.asarray(piece.columns[1].data)[:piece.num_rows]
        i = np.asarray(enc.indices)[:piece.num_rows]
        assert (d == np.asarray(enc.values)[i]).all()
        total += piece.num_rows
    assert total == batch.num_rows


def test_split_carries_double_bits():
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.execs.base import ExecContext
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.memory import grace
    rng = np.random.default_rng(0)
    table = pa.table({"k": rng.integers(0, 8, 200).astype("int64"),
                      "x": rng.random(200)})
    batch = DeviceBatch.from_arrow(table, 16)
    assert batch.columns[1].bits is not None
    ctx = ExecContext()
    keys = (BoundReference(0, DType.LONG, False),)
    out_rows = 0
    for _pid, piece in grace.split_batch(ctx, batch, keys, 4, depth=0):
        c = piece.columns[1]
        assert c.bits is not None
        live = np.asarray(c.bits)[:piece.num_rows]
        assert (live.view(np.float64)
                == np.asarray(c.data)[:piece.num_rows]).all()
        out_rows += piece.num_rows
    assert out_rows == batch.num_rows


def test_depth_salt_redistributes():
    """Keys that collide mod n at depth 0 spread at depth 1 — the property
    that makes fan-out recursion converge."""
    import jax.numpy as jnp
    from spark_rapids_tpu.execs.exchange_execs import hash_partition_ids
    from spark_rapids_tpu.exprs.core import ColV
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.memory.grace import _depth_seed
    keys = [ColV(DType.LONG, jnp.arange(4096, dtype=jnp.int64),
                 jnp.ones(4096, bool))]
    p0 = np.asarray(hash_partition_ids(jnp, keys, 4096, 8,
                                       seed=_depth_seed(0)))
    p1 = np.asarray(hash_partition_ids(jnp, keys, 4096, 8,
                                       seed=_depth_seed(1)))
    sub = p1[p0 == 0]
    assert len(np.unique(sub)) >= 4, "deeper hash did not redistribute"


def test_store_pressure_listener_fires():
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId
    from spark_rapids_tpu.memory.store import (BufferCatalog,
                                               build_store_chain)
    catalog = BufferCatalog()
    device, host, disk = build_store_chain(catalog, 64 << 10, 1 << 20)
    events = []
    device.add_pressure_listener(events.append)
    tab = pa.table({"x": np.arange(4096, dtype="int64")})
    for i in range(4):
        device.add_batch(BufferId(1 << 28, i),
                         DeviceBatch.from_arrow(tab, 16), float(i))
    assert events and sum(events) > 0
    device.remove_pressure_listener(events.append)
    n = len(events)
    device.add_batch(BufferId(1 << 28, 99), DeviceBatch.from_arrow(tab, 16),
                     99.0)
    assert len(events) == n          # unsubscribed
    for s in (device, host, disk):
        s.close()


def test_spilled_bytes_by_tier_counters():
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId
    from spark_rapids_tpu.memory.store import (BufferCatalog,
                                               build_store_chain)
    from spark_rapids_tpu.utils import metrics as um
    before_h = um.MEMORY_METRICS[um.MEM_SPILLED_TO_HOST].value
    before_d = um.MEMORY_METRICS[um.MEM_SPILLED_TO_DISK].value
    catalog = BufferCatalog()
    device, host, disk = build_store_chain(catalog, 48 << 10, 48 << 10)
    tab = pa.table({"x": np.arange(4096, dtype="int64")})
    for i in range(6):
        device.add_batch(BufferId(1 << 28, i),
                         DeviceBatch.from_arrow(tab, 16), float(i))
    assert um.MEMORY_METRICS[um.MEM_SPILLED_TO_HOST].value > before_h
    assert um.MEMORY_METRICS[um.MEM_SPILLED_TO_DISK].value > before_d
    for s in (device, host, disk):
        s.close()


def test_host_arena_overflow_lands_on_disk():
    """A buffer the host arena cannot hold (bigger than the whole arena, or
    the arena re-fragmented under concurrency) overflows straight to the
    disk tier instead of failing the spill cascade — out-of-core
    completion beats host staging."""
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId, StorageTier
    from spark_rapids_tpu.memory.store import (BufferCatalog,
                                               build_store_chain)
    catalog = BufferCatalog()
    # host arena (8 KB) is smaller than ONE spilled batch (~36 KB)
    device, host, disk = build_store_chain(catalog, 16 << 10, 8 << 10)
    tab = pa.table({"x": np.arange(4096, dtype="int64")})
    ids = [BufferId(1 << 28, i) for i in range(3)]
    for i, bid in enumerate(ids):
        device.add_batch(bid, DeviceBatch.from_arrow(tab, 16), float(i))
    assert len(disk) >= 1, "overflow never reached the disk tier"
    for bid in ids:          # every buffer still acquirable and intact
        buf = catalog.acquire(bid)
        assert buf is not None
        try:
            assert buf.get_batch().num_rows == 4096
        finally:
            buf.close()
    for s in (device, host, disk):
        s.close()


# ------------------------------------------------------------- observability
def test_memory_section_in_last_metrics_and_handle():
    from spark_rapids_tpu.utils.metrics import MEMORY_METRIC_NAMES
    table = make_table(8000)
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore.forcePartitions":
                           "2"})
    h = sess.submit(agg_df(sess, table))
    h.result(timeout=300)
    mm = sess.last_metrics["memory"]
    for name in MEMORY_METRIC_NAMES:
        assert name in mm, mm
    assert mm["memory.spill_partitions"] >= 2
    snap = h.snapshot()
    exec_mm = h.exec_metrics.get("memory")
    assert exec_mm and exec_mm["memory.spill_partitions"] >= 2, snap


def test_hot_path_untouched_with_ample_budget():
    table = make_table(8000)
    sess = TpuSession(BASE_CONF)
    agg_df(sess, table).collect()
    mm = mem_metrics(sess)
    assert mm["memory.pressure_events"] == 0, mm
    assert mm["memory.spill_partitions"] == 0, mm
    assert mm["memory.recursion_depth_peak"] == 0, mm


def test_no_buffer_leaks_after_out_of_core_query():
    table = make_table(60000)
    sess = TpuSession({**BASE_CONF, **TINY_BUDGET})
    dm = DeviceManager.initialize(sess.conf)
    ids_before = set(dm.catalog.ids())
    agg_df(sess, table).collect()
    sess.create_dataframe(table).sort("k", "v").collect()
    assert set(dm.catalog.ids()) == ids_before, \
        "grace partition buffers leaked past the query"
