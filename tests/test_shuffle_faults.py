"""Deterministic chaos tests for the fault-tolerant shuffle.

One test per fault class (connection drop, frame corruption, handler
failure, slow peer), each driving the REAL end-to-end shuffle protocol
(caching writer → metadata/transfer RPCs → chunked tag-addressed receives →
reader) through the FaultInjectingTransport with a fixed seed, asserting
both correct results AND that the recovery machinery (retry counters,
client eviction, checksum detection) actually engaged — a green run must
prove the fault fired and was absorbed, not that it never happened.

Plus unit tests for the backoff schedule, checksum round-trip, plan
parsing, scoped failure domains, and the reader's overall deadline.
"""
import queue
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.shuffle.codec import (ChecksumError, checksum_of,
                                            verify_checksum)
from spark_rapids_tpu.shuffle.faults import (FaultInjectingTransport,
                                             FaultPlan, FaultSpec)
from spark_rapids_tpu.shuffle.inprocess import _Fabric
from spark_rapids_tpu.shuffle.manager import (MapOutputTracker, ShuffleEnv,
                                              ShuffleFetchFailedError,
                                              ShuffleManager)
from spark_rapids_tpu.shuffle.retry import backoff_ms, backoff_schedule
from spark_rapids_tpu.utils import metrics as mt
from tests.test_shuffle import (collect_partition, sample_table,
                                write_partitioned)

FAULT_TRANSPORT = "spark_rapids_tpu.shuffle.faults.FaultInjectingTransport"


@pytest.fixture(autouse=True)
def fresh_fabric():
    _Fabric.reset()
    yield
    _Fabric.reset()


def fault_cluster(tmp_path, plan="", seed=7, n=2, extra=None):
    """n ShuffleEnvs riding the fault wrapper around the in-process fabric.
    Small bounce buffers force multi-chunk transfers (faults need frames to
    hit); small backoff keeps chaos tests fast. SHUFFLE_FAULTS_CODEC runs
    the whole chaos matrix over compressed payloads (ci/nightly.sh sets
    lz4, so corrupt-frame recovery is exercised on compressed frames)."""
    import os
    conf = TpuConf({
        "spark.rapids.tpu.shuffle.transport.class": FAULT_TRANSPORT,
        "spark.rapids.tpu.shuffle.faults.plan": plan,
        "spark.rapids.tpu.shuffle.faults.seed": seed,
        "spark.rapids.tpu.shuffle.bounceBuffers.size": 1024,
        "spark.rapids.tpu.shuffle.bounceBuffers.count": 16,
        "spark.rapids.tpu.shuffle.retryBackoffMs": 5,
        "spark.rapids.tpu.shuffle.compression.codec":
            os.environ.get("SHUFFLE_FAULTS_CODEC", "none"),
        **(extra or {})})
    envs = [ShuffleEnv(f"exec-{i}", conf, disk_dir=str(tmp_path / f"e{i}"))
            for i in range(n)]
    return (ShuffleManager(), *envs)


# ---------------------------------------------------------------------------------
# unit: backoff schedule + checksum round-trip + plan parsing
# ---------------------------------------------------------------------------------

def test_backoff_schedule_deterministic_and_exponential():
    a = backoff_schedule(4, base_ms=50, seed=3, key="transfer:b1")
    b = backoff_schedule(4, base_ms=50, seed=3, key="transfer:b1")
    assert a == b                                   # same seed+key replays
    assert a != backoff_schedule(4, 50, seed=4, key="transfer:b1")
    assert a != backoff_schedule(4, 50, seed=3, key="transfer:b2")
    for i, d in enumerate(a):
        lo, hi = 50 * (2 ** i) * 0.5, 50 * (2 ** i) * 1.5
        assert lo <= d <= hi                        # exponential + jitter band
    # the cap bounds runaway exponents
    assert backoff_ms(30, 50, 0, "k") == 10_000


def test_checksum_roundtrip_and_mismatch():
    buf = np.arange(10_000, dtype=np.int64).tobytes()
    crc = checksum_of(buf)
    verify_checksum(buf, crc)                       # clean round trip
    verify_checksum(buf, 0)                         # 0 = not computed
    corrupted = bytearray(buf)
    corrupted[1234] ^= 0xFF
    with pytest.raises(ChecksumError, match="checksum mismatch"):
        verify_checksum(bytes(corrupted), crc)


def test_table_meta_carries_checksum():
    from spark_rapids_tpu.columnar.host import HostBatch
    from spark_rapids_tpu.shuffle.table_meta import TableMeta, pack_host_batch
    buf, meta = pack_host_batch(HostBatch.from_arrow(sample_table(64)))
    assert meta.checksum == checksum_of(buf) != 0
    assert TableMeta.from_bytes(meta.to_bytes()).checksum == meta.checksum


def test_fault_plan_parsing():
    plan = FaultPlan.parse(
        "drop_conn:peer=exec-1,after=3;corrupt_frame:after=1,count=2;"
        "fail_request:req_type=metadata;delay_frame:delay_ms=25", seed=9)
    kinds = [s.kind for s in plan.specs]
    assert kinds == ["drop_conn", "corrupt_frame", "fail_request",
                     "delay_frame"]
    assert plan.specs[0].peer == "exec-1" and plan.specs[0].after == 3
    assert plan.specs[1].count == 2
    assert plan.specs[3].delay_ms == 25
    assert FaultPlan.parse("").empty
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("meteor_strike")
    # windowed firing: after=2,count=2 fires on events 2 and 3 only
    spec = FaultSpec("fail_request", after=2, count=2)
    assert [spec.fires(n) for n in (1, 2, 3, 4)] == [False, True, True, False]


def test_kill_peer_plan_counts_requests_and_frames_separately():
    """kill_peer (the failover chaos matrix's fault kind) is seeded and
    phase-targetable: req_type filters pick the submit/stream/drain phase
    (``data`` = the Nth outgoing data frame, mid-stream death), and the
    per-peer counters replay identically under a fixed plan."""
    plan = FaultPlan.parse("kill_peer:req_type=data,after=3", seed=7)
    assert [plan.on_kill_frame("p")
            for _ in range(3)] == [False, False, True]
    # request events with another req_type never advance the data spec
    assert not plan.on_kill_request("p", "serve.submit")
    replay = FaultPlan.parse("kill_peer:req_type=data,after=3", seed=7)
    assert [replay.on_kill_frame("p")
            for _ in range(3)] == [False, False, True]
    assert replay.fired == plan.fired == [("kill_peer", "p", 3)]
    # phase targeting: a submit-phase kill ignores stream traffic
    sub = FaultPlan.parse("kill_peer:req_type=serve.submit,after=1")
    assert not sub.on_kill_frame("p")
    assert sub.on_kill_request("p", "serve.submit")


def test_kill_peer_leaves_registry_entry_for_gc(tmp_path):
    """kill() is SIGKILL-shaped: the listener and sockets die, the
    heartbeat stops, but the registry file LINGERS — exactly the stale
    entry scan_registry's liveness-window GC must skip and collect."""
    import os
    import socket
    from spark_rapids_tpu.shuffle.tcp import TcpTransport, scan_registry
    reg = str(tmp_path / "reg")
    conf = TpuConf({"spark.rapids.tpu.shuffle.tcp.registryDir": reg})
    t = TcpTransport("exec-victim", conf)
    path = os.path.join(reg, "exec-victim")
    assert os.path.exists(path)
    mtime0 = os.path.getmtime(path)
    t.heartbeat()
    assert os.path.getmtime(path) >= mtime0
    host, port = t.address
    t.kill()
    # dead to the outside: new dials are refused...
    with pytest.raises(OSError):
        socket.create_connection((host, port), timeout=2)
    # ...the heartbeat is a no-op...
    old = time.time() - 120
    os.utime(path, (old, old))
    t.heartbeat()
    assert os.path.getmtime(path) == old, "killed transport heartbeat"
    # ...but the entry lingers (SIGKILL cannot retract it) until a
    # liveness-windowed scan garbage-collects it
    assert os.path.exists(path)
    assert scan_registry(reg, stale_after_s=5.0) == {}
    assert not os.path.exists(path)


# ---------------------------------------------------------------------------------
# chaos: one deterministic test per fault class
# ---------------------------------------------------------------------------------

def test_conn_drop_mid_fetch_recovers_via_retry(tmp_path):
    """THE acceptance bar: each remote peer's connection drops once
    mid-fetch; the reader reconnects (evicted client), re-fetches only the
    undelivered blocks, and the shuffle read completes with correct results
    — no ShuffleFetchFailedError."""
    mgr, e0, e1, e2 = fault_cluster(
        tmp_path, plan="drop_conn:after=2", n=3)
    sid, _ = mgr.register_shuffle(2)
    t1 = sample_table(800, seed=1)      # >1 KiB packed -> multi-chunk
    t2 = sample_table(600, seed=2)
    write_partitioned(mgr, e1, sid, 0, t1, 2)
    write_partitioned(mgr, e2, sid, 1, t2, 2)

    got = collect_partition(mgr, e0, sid, 0)    # both peers remote to e0
    expected = pa.concat_tables([t1.take(list(range(0, 800, 2))),
                                 t2.take(list(range(0, 600, 2)))])
    assert got.sort_by("f").equals(expected.sort_by("f"))
    # the drop actually fired on each remote peer and recovery engaged
    dropped = {p for k, p, _ in e0.transport.plan.fired if k == "drop_conn"}
    assert dropped == {"exec-1", "exec-2"}
    assert e0.metrics[mt.SHUFFLE_FETCH_RETRIES].value >= 2
    assert e0.metrics[mt.SHUFFLE_PEER_EVICTIONS].value >= 2


def test_corrupted_frame_caught_by_checksum_and_retried(tmp_path):
    """A flipped byte in one data frame surfaces as a checksum mismatch,
    counted and retried — the query still returns correct rows."""
    mgr, e0, e1 = fault_cluster(tmp_path, plan="corrupt_frame:after=2")
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(700, seed=3)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    got = collect_partition(mgr, e0, sid, 0)
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())
    assert e0.metrics[mt.SHUFFLE_CHECKSUM_FAILURES].value >= 1
    assert e0.metrics[mt.SHUFFLE_TRANSFER_RETRIES].value >= 1
    assert any(k == "corrupt_frame" for k, _, _ in e1.transport.plan.fired)


def test_corruption_without_checksum_would_pass_silently(tmp_path):
    """Negative control: with verification disabled the corrupted buffer is
    NOT caught (wrong bytes decode or error out downstream) — documents
    that the checksum is what stands between corruption and wrong answers."""
    mgr, e0, e1 = fault_cluster(
        tmp_path, plan="corrupt_frame:after=2",
        extra={"spark.rapids.tpu.shuffle.checksum.enabled": "false",
               # pinned to the copy codec: a real codec's decompressor can
               # catch the flip incidentally and retry, defeating this
               # negative control (the lz4 matrix run sets the codec env)
               "spark.rapids.tpu.shuffle.compression.codec": "none"})
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(700, seed=3)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    try:
        got = collect_partition(mgr, e0, sid, 0)
        # full-row comparison: the flipped byte lands in SOME column
        silently_wrong = not got.sort_by("f").equals(t.sort_by("f"))
    except Exception:  # noqa: BLE001 — a downstream decode error also proves it
        silently_wrong = True
    assert silently_wrong
    assert e0.metrics[mt.SHUFFLE_CHECKSUM_FAILURES].value == 0


def test_failed_request_handler_retried(tmp_path):
    """A request that fails once (dead handler / lost RPC) is retried with
    backoff and the fetch completes."""
    mgr, e0, e1 = fault_cluster(
        tmp_path, plan="fail_request:req_type=metadata;"
                       "fail_request:req_type=transfer")
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(300, seed=4)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    got = collect_partition(mgr, e0, sid, 0)
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())
    assert e0.metrics[mt.SHUFFLE_RPC_RETRIES].value >= 1
    assert e0.metrics[mt.SHUFFLE_TRANSFER_RETRIES].value >= 1


def test_slow_peer_and_duplicated_frames_absorbed(tmp_path):
    """Delayed frames ride out the (overall) fetch deadline and duplicated
    frames are absorbed without duplicate rows."""
    mgr, e0, e1 = fault_cluster(
        tmp_path, plan="delay_frame:after=1,count=3,delay_ms=40;"
                       "dup_frame:after=2,count=2")
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(700, seed=5)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    got = collect_partition(mgr, e0, sid, 0)
    assert got.num_rows == t.num_rows            # no dup rows, none missing
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())
    fired = {k for k, _, _ in e1.transport.plan.fired}
    assert {"delay_frame", "dup_frame"} <= fired


def test_unrecoverable_fault_names_executor_and_blocks(tmp_path):
    """Past maxRetries the error is scoped: it carries the failing executor
    and the undelivered blocks so callers recompute only those map outputs."""
    mgr, e0, e1 = fault_cluster(
        tmp_path, plan="fail_request:req_type=metadata,count=0",   # always
        extra={"spark.rapids.tpu.shuffle.maxRetries": 1})
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(50, seed=6)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    with pytest.raises(ShuffleFetchFailedError) as ei:
        collect_partition(mgr, e0, sid, 0)
    assert ei.value.executor_id == "exec-1"
    assert ei.value.blocks and all(b.shuffle_id == sid
                                   for b in ei.value.blocks)


# ---------------------------------------------------------------------------------
# scoped failure domains + eviction + deadline
# ---------------------------------------------------------------------------------

def test_peer_loss_scoped_to_failing_peer(tmp_path):
    """Losing one peer mid-read fails only ITS transactions: blocks from
    the healthy peer still arrive (TCP transport, per-peer pending tables)."""
    import pyarrow as pa
    from spark_rapids_tpu.shuffle.tcp import TcpTransport
    from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                    TransactionStatus)
    conf = TpuConf({
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg")})
    a = TcpTransport("exec-a", conf)
    b = TcpTransport("exec-b", conf)
    c = TcpTransport("exec-c", conf)
    try:
        conn_b = a.connect("exec-b")
        conn_c = a.connect("exec-c")
        lost = []
        a.add_peer_lost_listener(lost.append)
        # one pending receive per peer; kill b — only b's must fail
        rb = conn_b.receive(AddressLengthTag(bytearray(5), 5, tag=0x10),
                            lambda t: None)
        alt_c = AddressLengthTag(bytearray(5), 5, tag=0x20)
        rc = conn_c.receive(alt_c, lambda t: None)
        b.shutdown()
        rb.wait(10)
        assert rb.status is TransactionStatus.ERROR
        assert "lost" in rb.error_message
        # c's receive is untouched and still completes
        assert rc.status is TransactionStatus.IN_PROGRESS
        c.server.send("exec-a", AddressLengthTag.for_bytes(b"hello", 0x20),
                      lambda t: None).wait(10)
        rc.wait(10)
        assert rc.status is TransactionStatus.SUCCESS
        assert bytes(alt_c.buffer) == b"hello"
        assert lost == ["exec-b"]
    finally:
        a.shutdown()
        c.shutdown()


def test_dead_client_evicted_and_reconnect_possible(tmp_path):
    """ShuffleEnv drops the cached client when the peer dies (in-process
    fabric kill), so client_for() can build a fresh one. The per-peer
    connect lock survives — replacing it mid-connect could let a second
    caller dial a duplicate connection."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    c1 = e0.client_for("exec-1")
    assert e0.client_for("exec-1") is c1            # cached
    _Fabric.get().kill("exec-1")
    assert e0.metrics[mt.SHUFFLE_PEER_EVICTIONS].value == 1
    assert "exec-1" not in e0._clients
    assert "exec-1" in e0._connect_locks            # lock kept, reusable
    # revive the executor on the fabric; a fresh client connects
    e1b = ShuffleEnv("exec-1", e0.conf, disk_dir=str(tmp_path / "e1b"))
    c2 = e0.client_for("exec-1")
    assert c2 is not c1


def test_lost_blocks_fail_fast_without_retry(tmp_path):
    """Lost blocks are PERMANENT (only a map recompute brings them back):
    the reader must not burn its retry budget re-asking for them."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(40, seed=12)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    e1.shuffle_catalog.remove_shuffle(sid)      # data gone, tracker stale
    with pytest.raises(ShuffleFetchFailedError, match="lost blocks") as ei:
        collect_partition(mgr, e0, sid, 0)
    assert ei.value.executor_id == "exec-1" and ei.value.blocks
    assert e0.metrics[mt.SHUFFLE_FETCH_RETRIES].value == 0


def test_unreachable_peer_surfaces_scoped_fetch_failure(tmp_path):
    """A peer that cannot even be dialed (dead executor) surfaces as a
    scoped ShuffleFetchFailedError, never a bare ConnectionError."""
    mgr, e0, e1 = fault_cluster(
        tmp_path, extra={"spark.rapids.tpu.shuffle.maxRetries": 1,
                         "spark.rapids.tpu.shuffle.fetch.timeoutSeconds": 30})
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(40, seed=10)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    _Fabric.get().kill("exec-1")                # endpoint gone: connect fails
    with pytest.raises(ShuffleFetchFailedError) as ei:
        collect_partition(mgr, e0, sid, 0)
    assert ei.value.executor_id == "exec-1" and ei.value.blocks


def test_registry_file_removed_on_shutdown(tmp_path):
    """A restarted executor must not be resolvable at its dead address."""
    import os
    from spark_rapids_tpu.shuffle.tcp import TcpTransport
    reg = tmp_path / "reg"
    conf = TpuConf({
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(reg),
        "spark.rapids.tpu.shuffle.maxRetries": 0,
        "spark.rapids.tpu.shuffle.connectTimeout": 0.2})
    t = TcpTransport("exec-gone", conf)
    assert (reg / "exec-gone").exists()
    t.shutdown()
    assert not (reg / "exec-gone").exists()
    other = TcpTransport("exec-live", conf)
    try:
        with pytest.raises(ConnectionError, match="never registered"):
            other.connect("exec-gone")
    finally:
        other.shutdown()


def test_reader_timeout_is_overall_deadline(tmp_path):
    """A trickling-but-stuck fetch (events keep arriving, one block never
    does) times out at the overall deadline instead of resetting per event."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(50, seed=8)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    # sabotage AFTER metadata registration: blocks exist in the tracker but
    # e1 will never answer (handlers replaced by a black hole that only
    # keeps the connection chatty)
    e1.transport.server.register_request_handler(
        "transfer", lambda peer, payload: time.sleep(3600))
    from spark_rapids_tpu.shuffle.manager import CachingShuffleReader
    reader = CachingShuffleReader(e0, mgr.tracker, sid, 0, timeout=1.0)
    start = time.monotonic()
    with pytest.raises(ShuffleFetchFailedError, match="timed out"):
        list(reader.read())
    assert time.monotonic() - start < 10            # not 3600, not per-event


def test_connect_retries_until_peer_registers(tmp_path):
    """TCP connect outlasts a slow registry: the peer registers while the
    client is inside its backoff schedule."""
    from spark_rapids_tpu.shuffle.tcp import TcpTransport
    conf = TpuConf({
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg"),
        "spark.rapids.tpu.shuffle.connectTimeout": 0.3,
        "spark.rapids.tpu.shuffle.retryBackoffMs": 50})
    a = TcpTransport("exec-early", conf)
    result = {}

    def late_start():
        time.sleep(0.6)                 # past the first connect attempt
        result["b"] = TcpTransport("exec-late", conf)
        result["b"].server.register_request_handler(
            "ping", lambda peer, payload: b"pong")
    th = threading.Thread(target=late_start)
    th.start()
    try:
        conn = a.connect("exec-late")   # first attempt times out, retry wins
        tx = conn.request("ping", b"", lambda t: None).wait(10)
        assert tx.response == b"pong"
        assert a.metrics[mt.SHUFFLE_CONNECT_RETRIES].value >= 1
    finally:
        th.join()
        a.shutdown()
        if "b" in result:
            result["b"].shutdown()
