"""Fuzz tests: random schemas/batches with edge-value weighting through the
main operator surface, CPU engine vs TPU engine (data_gen.py + FuzzerUtils
analog coverage)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.datagen import (ALL_GENS, BooleanGen, ByteGen, DateGen,
                                      DoubleGen, FloatGen, IntegerGen, LongGen,
                                      NUMERIC_GENS, ShortGen, StringGen,
                                      TimestampGen, gen_table, random_gens)
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

FLOAT_AGG = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}
INCOMPAT = {"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"}

SEEDS = [0, 1, 2]


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_roundtrip_random_schema(seed):
    """Host->device->host round-trip preserves every value of a random
    schema (columnar interop fuzz)."""
    rng = np.random.default_rng(seed + 100)
    gens = random_gens(rng, n_cols=5)
    t = gen_table(gens, 150, seed)
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(*t.column_names))
    assert cpu.num_rows == 150


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_project_arithmetic(seed):
    gens = {"a": DoubleGen(), "b": DoubleGen(), "i": LongGen(),
            "j": IntegerGen()}
    t = gen_table(gens, 200, seed)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            (F.col("a") + F.col("b")).alias("add"),
            (F.col("a") * 2.0).alias("mul"),
            (F.col("a") > F.col("b")).alias("gt"),
            F.coalesce(F.col("a"), F.col("b")).alias("co"),
            (F.col("i") + F.col("j")).alias("iadd"),
            F.abs("j").alias("absj")))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_aggregate(seed):
    gens = {"k": IntegerGen(min_val=0, max_val=6),
            "v": DoubleGen(), "w": LongGen(min_val=-10**6, max_val=10**6),
            "f": FloatGen()}
    t = gen_table(gens, 300, seed)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("k").agg(
            F.sum("v").alias("sv"), F.avg("v").alias("av"),
            F.sum("w").alias("sw"), F.count("v").alias("cv"),
            F.min("f").alias("mf"), F.max("f").alias("xf")),
        conf=FLOAT_AGG, ignore_order=True, approx_float=1e-9)


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("how", ["inner", "left", "full"])
def test_fuzz_join(seed, how):
    lg = {"k": IntegerGen(min_val=0, max_val=12), "lv": DoubleGen()}
    rg = {"k": IntegerGen(min_val=0, max_val=12), "rv": StringGen()}
    lt = gen_table(lg, 120, seed)
    rt = gen_table(rg, 80, seed + 50)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "k", how),
        ignore_order=True)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_sort(seed):
    rng = np.random.default_rng(seed)
    gens = random_gens(rng, n_cols=3, pool=[DoubleGen, LongGen, StringGen,
                                            DateGen, BooleanGen])
    t = gen_table(gens, 150, seed)
    cols = list(t.column_names)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).sort(
            *([F.col(cols[0]).desc()] + cols[1:])))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_strings(seed):
    gens = {"s": StringGen(), "p": StringGen(min_len=1, max_len=3)}
    t = gen_table(gens, 150, seed)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.length("s").alias("len"),
            F.substring("s", 2, 3).alias("sub"),
            F.col("s").contains("a").alias("ca"),
            F.trim("s").alias("tr"),
            F.concat(F.col("s"), F.col("p")).alias("cc")))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_datetime(seed):
    gens = {"d": DateGen(), "ts": TimestampGen()}
    t = gen_table(gens, 150, seed)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            F.year("d").alias("y"), F.month("d").alias("m"),
            F.dayofmonth("d").alias("dm"), F.quarter("d").alias("q"),
            F.date_add("d", 31).alias("plus"),
            F.year("ts").alias("ty"), F.hour("ts").alias("th")))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_narrow_integrals(seed):
    gens = {"b": ByteGen(), "sh": ShortGen()}
    t = gen_table(gens, 150, seed)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            (F.col("b") + F.col("sh")).alias("add"),
            F.col("b").cast("int").alias("ci"),
            (F.col("sh") % 7).alias("mod")))
