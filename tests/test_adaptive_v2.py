"""Statistics-driven adaptive execution v2 (docs/adaptive.md).

Covers the PR-20 surface:
- StageStats at shuffle materialization: exact per-partition rows/bytes and
  the KMV distinct sketch per hash key column;
- skew-split readers: PartialReducerSpec map-axis slices, bit-identical
  (up to row order) across join types, the skewed group-by re-partition;
- post-AQE re-fusion: the rewritten region re-fuses into ``*(id)`` stages
  with their own program-cache keys;
- observed-size grace fanout: recursion depth under a tiny budget with
  observed statistics never exceeds the estimate-driven run;
- cost-based placement: plan-time demotion of tiny plans plus the
  AQE-observed CpuHashJoinExec switch;
- the adaptive counters in session.last_metrics and QueryHandle snapshots.
"""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.execs.exchange_execs import (ShuffleExchangeExecBase,
                                                   _kmv_estimate, _kmv_merge)
from spark_rapids_tpu.memory.device_manager import DeviceManager
from spark_rapids_tpu.plan.adaptive import (CustomShuffleReaderExecBase,
                                            PartialReducerSpec,
                                            legal_split_sides)
from spark_rapids_tpu.testing import assert_tables_equal

AQE = {"spark.rapids.tpu.sql.adaptive.enabled": "true"}

#: skew knobs scaled to test-size data: tiny skew threshold so the hot
#: partition trips it, tiny advisory size so the upstream round-robin
#: exchange keeps multiple map tasks (map_slices needs >1 contributing map)
SKEW = {**AQE,
        "spark.rapids.tpu.sql.adaptive.skewedPartitionThreshold.bytes": "64",
        "spark.rapids.tpu.sql.adaptive.skewedPartitionFactor": "2.0",
        "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes": "2048"}


def walk(node):
    yield node
    for c in node.children:
        yield from walk(c)


def skewed_table(n=2000, hot=0.8, seed=7):
    rng = np.random.default_rng(seed)
    k = np.where(rng.random(n) < hot, 0, rng.integers(1, 50, n))
    return pa.table({"k": pa.array(k, type=pa.int64()),
                     "v": pa.array(np.arange(n), type=pa.int64())})


def dim_table(m=50):
    return pa.table({"k": pa.array(np.arange(m), type=pa.int64()),
                     "w": pa.array(np.arange(m) * 10, type=pa.int64())})


def sort_all(t):
    cols = sorted(t.column_names)
    return t.select(cols).sort_by([(c, "ascending") for c in cols])


# ------------------------------------------------------------ stage statistics
def test_stage_stats_rows_bytes_ndv():
    t = pa.table({"k": pa.array(np.arange(1000) % 7, type=pa.int64()),
                  "v": pa.array(np.arange(1000), type=pa.int64())})
    s = TpuSession()
    s.create_dataframe(t).repartition(4, "k").filter(F.col("v") > 10).collect()
    ex = [n for n in walk(s.last_plan)
          if isinstance(n, ShuffleExchangeExecBase)][0]
    st = ex.stage_stats()
    assert st is not None
    assert st.partition_rows and len(st.partition_rows) == 4
    assert st.total_rows == 1000
    assert st.total_bytes == sum(st.partition_bytes) > 0
    # 7 distinct keys < the KMV pool size -> the estimate is exact
    assert st.key_distinct == (7,)
    assert "rows=1000" in st.describe()


def test_stage_stats_absent_before_run():
    t = dim_table()
    s = TpuSession()
    df = s.create_dataframe(t).repartition(3, "k")
    plan = df._executed_plan()
    ex = [n for n in walk(plan) if isinstance(n, ShuffleExchangeExecBase)][0]
    assert ex.stage_stats() is None


def test_kmv_estimator_skew_resistant():
    """A heavy hitter repeated 10k times must not evict the other distinct
    hashes from the pool (the dedup-before-truncate regression)."""
    rng = np.random.default_rng(0)
    small = np.uint32(1)                    # hot hash, smaller than the rest
    others = rng.integers(2, 1 << 32, 200, dtype=np.uint64).astype(np.uint32)
    pool = np.zeros(0, dtype=np.uint32)
    for _ in range(10):
        batch = np.concatenate([np.repeat(small, 10000), others])
        pool = _kmv_merge(pool, batch)
    est = _kmv_estimate(pool)
    true_ndv = len(np.unique(others)) + 1
    assert abs(est - true_ndv) / true_ndv < 0.5, (est, true_ndv)


def test_map_slices_cover_contributing_maps():
    t = skewed_table()
    s = TpuSession()
    (s.create_dataframe(t).repartition(8).repartition(6, "k")
     .filter(F.col("v") >= 0).collect())
    ex = [n for n in walk(s.last_plan)
          if isinstance(n, ShuffleExchangeExecBase)
          and n.num_partitions == 6][0]
    st = ex.stage_stats()
    hot = max(range(6), key=lambda p: st.partition_bytes[p])
    slices = ex.map_slices(hot, 4)
    assert len(slices) >= 2
    ids = [m for grp in slices for m in grp]
    assert len(ids) == len(set(ids))        # disjoint
    # contiguous ascending: reduce-side concat order is preserved
    assert ids == sorted(ids)


def test_partial_reducer_spec_repr():
    spec = PartialReducerSpec(pid=7, slice_index=0, num_slices=5,
                              map_ids=(0, 1))
    assert str(spec) == "p7[1/5]"


# --------------------------------------------------------------- skew splitting
@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_skew_split_join_bit_identical(how):
    def run(conf):
        s = TpuSession({"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes":
                            "1", **conf})
        lt = s.create_dataframe(skewed_table()).repartition(8) \
              .repartition(6, "k")
        rt = s.create_dataframe(dim_table()).repartition(4) \
              .repartition(6, "k")
        return lt.join(rt, "k", how=how).collect(), s

    on, s_on = run(SKEW)
    plan = s_on.last_plan.tree_string()
    assert "skew-split" in plan, plan
    off, _ = run({})
    assert_tables_equal(sort_all(off), sort_all(on))


def test_skew_split_tag_and_metrics():
    s = TpuSession({"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
                    **SKEW})
    lt = s.create_dataframe(skewed_table()).repartition(8).repartition(6, "k")
    rt = s.create_dataframe(dim_table()).repartition(4).repartition(6, "k")
    out = lt.join(rt, "k").collect()
    assert out.num_rows > 0
    plan = s.last_plan.tree_string()
    # EXPLAIN contract: [adaptive: skew-split p<pid>x<slices>]
    assert "[adaptive: skew-split p" in plan, plan
    adaptive = s.last_metrics["adaptive"]
    assert adaptive["adaptive.skew_splits"] >= 1, adaptive
    # the rewritten join reads partial specs on exactly one side
    readers = [n for n in walk(s.last_plan)
               if isinstance(n, CustomShuffleReaderExecBase)]
    partial = [n for n in readers
               if any(isinstance(e, PartialReducerSpec)
                      for spec in n.specs for e in spec)]
    assert partial, plan
    assert all(r.aligned_pairwise for r in partial)


def test_skew_split_disabled_by_conf():
    s = TpuSession({"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
                    "spark.rapids.tpu.sql.adaptive.skewSplit.enabled":
                        "false", **SKEW})
    lt = s.create_dataframe(skewed_table()).repartition(8).repartition(6, "k")
    rt = s.create_dataframe(dim_table()).repartition(4).repartition(6, "k")
    lt.join(rt, "k").collect()
    assert "skew-split" not in s.last_plan.tree_string()


def test_legal_split_sides():
    # the split side must NOT be a side that could have been broadcast
    # replicated wholesale -- it is the probe/stream side's complement
    assert legal_split_sides("inner") == [0, 1]
    assert legal_split_sides("left") == [0]
    assert legal_split_sides("left_semi") == [0]
    assert legal_split_sides("left_anti") == [0]


def test_skewed_groupby_repartition():
    rng = np.random.default_rng(3)
    k = np.where(rng.random(4000) < 0.8, 0, rng.integers(1, 40, 4000))
    t = pa.table({"k": pa.array(k, type=pa.int64()),
                  "v": pa.array(np.arange(4000), type=pa.int64())})

    def run(conf):
        s = TpuSession(conf)
        out = (s.create_dataframe(t).repartition(8).repartition(6, "k")
               .groupBy("k").agg(F.count().alias("n"), F.sum("v").alias("sv"))
               .sort("k").collect())
        return out, s

    on, s_on = run(SKEW)
    plan = s_on.last_plan.tree_string()
    # aggregates must NOT slice the reduce axis (a sliced partition would
    # double-count groups); they raise the grace fanout instead
    assert "skew-repartition" in plan, plan
    assert s_on.last_metrics["adaptive"]["adaptive.skew_splits"] >= 1
    off, _ = run({})
    assert_tables_equal(off, on)


# ------------------------------------------------------------------- re-fusion
def t7(n=3000):
    return pa.table({"k": pa.array(np.arange(n) % 7, type=pa.int64()),
                     "v": pa.array(np.arange(n), type=pa.int64())})


def test_refusion_creates_fused_stage():
    """A lone Filter above a coalesced device reader is not a fusable chain
    at plan time; the coalesce batches node the reader inserts makes it one
    — only the post-AQE re-fusion pass can see it."""
    from spark_rapids_tpu.serving.program_cache import global_program_cache

    cache = global_program_cache()
    before_keys = set(cache._programs.keys())

    s = TpuSession(AQE)
    out = (s.create_dataframe(t7()).repartition(6, "k")
           .filter(F.col("v") > 10).collect())
    assert out.num_rows == 3000 - 11
    plan = s.last_plan.tree_string()
    assert "*(1)" in plan, plan
    assert "re-fused" in plan, plan
    assert s.last_metrics["adaptive"]["adaptive.refused_stages"] >= 1
    # the re-fused stage compiled under its own program-cache key (R016:
    # fused signatures key the program, not the pre-AQE plan shape)
    new_stage_keys = [k for k in cache._programs.keys()
                     if k not in before_keys and "stage" in k]
    assert new_stage_keys, sorted(cache._programs.keys() - before_keys)

    # refusion off: same query, no fused stage, identical result
    s2 = TpuSession({**AQE,
                     "spark.rapids.tpu.sql.adaptive.refusion.enabled":
                         "false"})
    out2 = (s2.create_dataframe(t7()).repartition(6, "k")
            .filter(F.col("v") > 10).collect())
    assert "*(" not in s2.last_plan.tree_string()
    assert_tables_equal(out.sort_by("v"), out2.sort_by("v"))


def test_refusion_pipeline_matches_non_aqe():
    def run(conf):
        s = TpuSession(conf)
        return (s.create_dataframe(t7()).repartition(5, "k")
                .filter(F.col("v") > 100)
                .select("k", (F.col("v") * 2).alias("v2"))
                .groupBy("k").agg(F.sum("v2").alias("s"))
                .sort("k").collect())
    assert_tables_equal(run({}), run(AQE))


# ------------------------------------------------------- observed reader sizes
def test_reader_size_estimate_uses_observed_stats():
    """A selective filter upstream of the exchange: the static estimate is
    the full-table upper bound; the reader's estimate reflects the rows the
    stage actually materialized."""
    t = pa.table({"k": pa.array(np.arange(20000) % 7, type=pa.int64()),
                  "v": pa.array(np.arange(20000), type=pa.int64())})
    s = TpuSession(AQE)
    (s.create_dataframe(t).filter(F.col("v") < 20).repartition(4, "k")
     .filter(F.col("v") >= 0).collect())
    readers = [n for n in walk(s.last_plan)
               if isinstance(n, CustomShuffleReaderExecBase)]
    assert readers, s.last_plan.tree_string()
    r = readers[0]
    ex = [n for n in walk(r) if isinstance(n, ShuffleExchangeExecBase)][0]
    assert r.size_estimate() < ex.size_estimate() / 10
    # EXPLAIN surfaces observed vs estimated rows on the reader line
    plan = s.last_plan.tree_string()
    assert "rows=" in plan and "est~" in plan, plan


# ------------------------------------------------------- observed grace fanout
@pytest.fixture
def fresh_memory():
    DeviceManager.shutdown()
    yield
    DeviceManager.shutdown()


def test_grace_observed_fanout_bounds_recursion(monkeypatch, fresh_memory):
    """Under a tiny budget the fanout sized from OBSERVED input bytes never
    recurses deeper than the estimate-driven run, and stays bit-identical
    (integer aggregates)."""
    from spark_rapids_tpu.plan import footprint as fp

    TINY = {"spark.rapids.tpu.memory.tpu.poolSizeBytes": str(256 << 10),
            "spark.rapids.tpu.memory.host.spillStorageSize": str(256 << 10),
            "spark.rapids.tpu.sql.scanCache.enabled": "false",
            "spark.rapids.tpu.sql.hasNans": "false"}
    rng = np.random.default_rng(0)
    t = pa.table({"k": rng.integers(0, 64, 40000).astype("int64"),
                  "v": rng.integers(0, 1000, 40000).astype("int64")})

    def q(sess):
        return (sess.create_dataframe(t).repartition(4, "k").groupBy("k")
                .agg(F.sum("v").alias("sv"), F.count(F.lit(1)).alias("c")))

    # baseline: observed statistics unavailable -> hint/fanout sizing
    orig = fp.observed_input_bytes
    monkeypatch.setattr(fp, "observed_input_bytes",
                        lambda node, partition_id=None: None)
    s_est = TpuSession(TINY)
    ref = q(s_est).collect()
    depth_est = s_est.last_metrics["memory"]["memory.recursion_depth_peak"]
    assert depth_est >= 1    # the tiny budget did engage grace

    # observed run: record that the statistics path actually fired
    DeviceManager.shutdown()
    fired = []

    def spy(node, partition_id=None):
        r = orig(node, partition_id)
        if r is not None:
            fired.append(r)
        return r

    monkeypatch.setattr(fp, "observed_input_bytes", spy)
    s_obs = TpuSession(TINY)
    got = q(s_obs).collect()
    depth_obs = s_obs.last_metrics["memory"]["memory.recursion_depth_peak"]
    assert fired, "observed-statistics fanout never engaged"
    assert depth_obs <= depth_est, (depth_obs, depth_est)
    assert_tables_equal(ref, got, ignore_order=True)


# --------------------------------------------------------- cost-based placement
COST = {"spark.rapids.tpu.sql.adaptive.costModel.enabled": "true"}


def t13(n):
    return pa.table({"k": pa.array(np.arange(n) % 13, type=pa.int64()),
                     "v": pa.array(np.arange(n), type=pa.int64())})


def test_cost_model_plan_time_placement():
    s = TpuSession(COST)
    out = s.create_dataframe(t13(50)).filter(F.col("v") > 5).collect()
    assert "CpuFilterExec" in s.last_plan.tree_string()
    assert out.num_rows == 44

    s2 = TpuSession(COST)
    out2 = s2.create_dataframe(t13(100000)).filter(F.col("v") > 5).collect()
    p2 = s2.last_plan.tree_string()
    assert "TpuFilterExec" in p2 or "*(" in p2, p2
    assert out2.num_rows == 99994


def test_cost_model_off_by_default():
    s = TpuSession()
    s.create_dataframe(t13(50)).filter(F.col("v") > 5).collect()
    assert "CpuFilterExec" not in s.last_plan.tree_string()


def test_cost_model_aqe_observed_placement():
    """Estimates keep the join on-device at plan time (the filter passes
    its child's upper bound through); the OBSERVED exchange rows are tiny,
    so only AQE's runtime statistics can legally demote — and must, with
    the same result as the static plan."""
    conf = {**COST, **AQE,
            "spark.rapids.tpu.sql.adaptive.costModel.minDeviceRows": "1000",
            "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1"}

    def run(c):
        s = TpuSession(c)
        lt = (s.create_dataframe(t13(20000)).filter(F.col("v") < 20)
              .repartition(4, "k"))
        rt = (s.create_dataframe(t13(20000)).filter(F.col("v") < 10)
              .repartition(3, "k"))
        return lt.join(rt, "k").collect(), s

    out, s_aqe = run(conf)
    plan = s_aqe.last_plan.tree_string()
    assert "CpuHashJoinExec" in plan, plan
    assert "[adaptive: placement=cpu rows=" in plan, plan
    ref, s_ref = run({"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes":
                          "1"})
    assert "CpuHashJoinExec" not in s_ref.last_plan.tree_string()
    assert_tables_equal(sort_all(ref), sort_all(out))


# -------------------------------------------------------------- metrics wiring
def test_adaptive_counters_in_session_metrics():
    s = TpuSession(AQE)
    (s.create_dataframe(t7()).repartition(6, "k")
     .filter(F.col("v") > 10).collect())
    adaptive = s.last_metrics["adaptive"]
    for key in ("adaptive.skew_splits", "adaptive.coalesced_partitions",
                "adaptive.broadcast_switches", "adaptive.refused_stages"):
        assert key in adaptive, adaptive
    assert adaptive["adaptive.coalesced_partitions"] >= 1, adaptive


def test_adaptive_counters_in_query_handle():
    s = TpuSession(AQE)
    df = (s.create_dataframe(t7()).repartition(6, "k")
          .filter(F.col("v") > 10).select("k"))
    h = s.submit(df, label="adaptive-metrics")
    h.result(timeout=120)
    assert h.exec_metrics["adaptive"]["adaptive.coalesced_partitions"] >= 1
    snap = h.snapshot()
    assert snap["adaptive"]["adaptive.coalesced_partitions"] >= 1


def test_explain_coalesce_tag_format():
    s = TpuSession(AQE)
    (s.create_dataframe(t7()).repartition(6, "k")
     .filter(F.col("v") > 10).collect())
    plan = s.last_plan.tree_string()
    # [adaptive: coalesced 6->N | re-fused] with observed rows
    assert "[adaptive: coalesced 6→" in plan, plan
    assert "rows=3000" in plan, plan
