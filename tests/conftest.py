"""Test configuration: force JAX onto 8 virtual CPU devices.

Tests must not require the real TPU chip; multi-device sharding logic is exercised
on a virtual CPU mesh (mirrors how the driver dry-runs multichip compilation).
This must run before jax is imported anywhere.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin (registered by sitecustomize via PYTHONPATH) force-sets
# jax_platforms to "axon,cpu" at interpreter start, overriding the env var, and
# initializing its remote client hangs when the chip tunnel is busy. Tests are
# CPU-only: pin the config back to cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Free compiled-executable memory between test modules: one process
    accumulates thousands of XLA programs across the suite, and LLVM
    compiles near the end of the run can die under that heap pressure.
    The persistent on-disk cache keeps recompiles cheap."""
    yield
    import jax
    jax.clear_caches()
    from spark_rapids_tpu.execs import tpu_execs, evaluator
    tpu_execs._JIT_CACHE.clear() if hasattr(tpu_execs, "_JIT_CACHE") else None
    evaluator._JIT_CACHE.clear()
