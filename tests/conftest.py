"""Test configuration: force JAX onto 8 virtual CPU devices.

Tests must not require the real TPU chip; multi-device sharding logic is exercised
on a virtual CPU mesh (mirrors how the driver dry-runs multichip compilation).
This must run before jax is imported anywhere.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin (registered by sitecustomize via PYTHONPATH) force-sets
# jax_platforms to "axon,cpu" at interpreter start, overriding the env var, and
# initializing its remote client hangs when the chip tunnel is busy. Tests are
# CPU-only: pin the config back to cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs
