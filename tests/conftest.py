"""Test configuration: force JAX onto 8 virtual CPU devices.

Tests must not require the real TPU chip; multi-device sharding logic is exercised
on a virtual CPU mesh (mirrors how the driver dry-runs multichip compilation).
This must run before jax is imported anywhere.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs
