import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DType, DeviceBatch, Schema, bucket_capacity
from spark_rapids_tpu.testing import assert_tables_equal


def make_table():
    return pa.table({
        "i": pa.array([1, 2, None, 4, 5], type=pa.int32()),
        "l": pa.array([10, None, 30, 40, 50], type=pa.int64()),
        "d": pa.array([1.5, 2.5, 3.5, None, float("nan")], type=pa.float64()),
        "b": pa.array([True, False, None, True, False], type=pa.bool_()),
        "s": pa.array(["foo", "", None, "hello world", "zz"], type=pa.string()),
        "dt": pa.array([0, 1, 18262, None, -1], type=pa.date32()),
        "ts": pa.array([0, 1_000_000, None, 86_400_000_000, -5],
                       type=pa.timestamp("us", tz="UTC")),
    })


def test_bucket_capacity():
    assert bucket_capacity(0) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(1000) == 1024
    assert bucket_capacity(1000, bucketed=False) == 1000


def test_arrow_roundtrip_preserves_everything():
    t = make_table()
    batch = DeviceBatch.from_arrow(t, string_max_bytes=32)
    assert batch.num_rows == 5
    assert batch.capacity == 128  # bucketed
    back = batch.to_arrow()
    assert_tables_equal(t, back)


def test_empty_table_roundtrip():
    t = make_table().slice(0, 0)
    batch = DeviceBatch.from_arrow(t)
    assert batch.num_rows == 0
    assert batch.to_arrow().equals(t)


def test_unicode_strings_roundtrip():
    t = pa.table({"s": pa.array(["héllo", "日本語", "", None, "a" * 31])})
    batch = DeviceBatch.from_arrow(t, string_max_bytes=32)
    assert batch.to_arrow().equals(t)


def test_string_too_wide_raises():
    t = pa.table({"s": pa.array(["x" * 300])})
    with pytest.raises(ValueError, match="maxBytes"):
        DeviceBatch.from_arrow(t, string_max_bytes=256)


def test_schema_mapping():
    t = make_table()
    s = Schema.from_pa(t.schema)
    assert s.field("i").dtype == DType.INT
    assert s.field("s").dtype == DType.STRING
    assert s.field("dt").dtype == DType.DATE
    assert s.field("ts").dtype == DType.TIMESTAMP
    assert s.to_pa().equals(t.schema)


def test_padding_rows_are_invalid():
    t = make_table()
    batch = DeviceBatch.from_arrow(t, string_max_bytes=32)
    for col in batch.columns:
        validity = np.asarray(col.validity)
        assert not validity[batch.num_rows:].any()


def test_adaptive_string_widths():
    """Per-column width buckets: narrow columns stage narrow; mixed widths
    align inside binary kernels, range partitioning, and shuffle packing."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import (DeviceBatch,
                                                 string_width_bucket)
    assert string_width_bucket(0, 256) == 8
    assert string_width_bucket(3, 256) == 8
    assert string_width_bucket(9, 256) == 16
    assert string_width_bucket(300, 64) == 64
    t = pa.table({"flag": pa.array(["A", "B"]),
                  "city": pa.array(["Pleasant Hill", "Oak Grove Station"])})
    db = DeviceBatch.from_arrow(t, 256)
    assert db.column_by_name("flag").data.shape[-1] == 8
    assert db.column_by_name("city").data.shape[-1] == 32


def test_mixed_width_string_ops():
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal
    t = pa.table({"a": pa.array(["x", "yy", "zzz", None]),
                  "b": pa.array(["a-much-longer-value", "yy", None, "q"])})
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            (F.col("a") == F.col("b")).alias("eq"),
            (F.col("a") < F.col("b")).alias("lt"),
            F.concat(F.col("a"), F.col("b")).alias("cc"),
            F.coalesce(F.col("a"), F.col("b")).alias("co"),
            F.when(F.col("a") == "x", F.col("b")).otherwise(F.col("a"))
            .alias("sel")))


def test_long_prefix_on_narrow_column():
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F
    s = TpuSession()
    df = s.create_dataframe(pa.table({"s": pa.array(["ab", "cd"])}))
    assert df.filter(F.col("s").startswith("longer-than-bucket")).collect().num_rows == 0
    assert df.filter(F.col("s").like("longer-than-bucket%")).collect().num_rows == 0
