"""ML integration tests (ColumnarRdd / InternalColumnarRddConverter analog)."""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import ml
from spark_rapids_tpu.api import TpuSession, functions as F


def table():
    return pa.table({"x": pa.array([1.0, 2.0, 3.0, 4.0]),
                     "y": pa.array([10, None, 30, 40], type=pa.int64()),
                     "s": pa.array(["a", "bb", None, "d"])})


def test_device_batches_cut_boundary():
    s = TpuSession()
    df = s.create_dataframe(table()).filter(F.col("x") > 1.5)
    batches = list(ml.device_batches(df))
    assert sum(b.num_rows for b in batches) == 3
    # batches are device-resident (jax arrays, not numpy)
    import jax
    assert isinstance(batches[0].columns[0].data, jax.Array)


def test_device_arrays_values_and_validity():
    s = TpuSession()
    df = s.create_dataframe(table())
    arrs = ml.device_arrays(df)
    x_data, x_valid = arrs["x"]
    assert np.asarray(x_data).tolist() == [1.0, 2.0, 3.0, 4.0]
    assert np.asarray(x_valid).all()
    y_data, y_valid = arrs["y"]
    assert np.asarray(y_valid).tolist() == [True, False, True, True]
    s_data, s_valid, s_len = arrs["s"]
    assert np.asarray(s_len).tolist() == [1, 2, 0, 1]
    assert bytes(np.asarray(s_data)[1][:2]) == b"bb"


def test_device_arrays_after_aggregation():
    s = TpuSession()
    df = (s.create_dataframe(table())
          .groupBy().agg(F.sum("x").alias("sx"), F.count().alias("n")))
    arrs = ml.device_arrays(df)
    assert np.asarray(arrs["sx"][0]).tolist() == [10.0]
    assert np.asarray(arrs["n"][0]).tolist() == [4]


def test_cpu_fallback_gets_uploaded():
    """A CPU-only plan still hands back device arrays (upload fallback)."""
    s = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    df = s.create_dataframe(table()).filter(F.col("x") > 1.5)
    arrs = ml.device_arrays(df)
    assert np.asarray(arrs["x"][0]).tolist() == [2.0, 3.0, 4.0]
