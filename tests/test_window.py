"""Window function tests (reference: WindowFunctionSuite.scala +
integration_tests window_function_test.py).

Golden-answer tests pin Spark window semantics (both engines share the kernel in
ops/window.py, so CPU-vs-TPU parity alone cannot catch a shared semantics bug);
parity tests then confirm the jitted XLA path matches the eager numpy path, and
plan assertions confirm the window actually ran on the device engine.
"""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, Window
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

CONF = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}


def sess(enabled=True):
    return TpuSession({**CONF,
                       "spark.rapids.tpu.sql.enabled": str(enabled).lower()})


def sales_table():
    return pa.table({
        "dept": ["a", "a", "a", "b", "b", "b", "b", "c"],
        "emp": ["e1", "e2", "e3", "e4", "e5", "e6", "e7", "e8"],
        "salary": pa.array([100, 200, 200, 50, 75, None, 25, 300],
                           type=pa.int64()),
    })


def by_emp(rows, table):
    """Index collected rows by the emp column for order-independent asserts."""
    emps = table.column("emp").to_pylist()
    return dict(zip(emps, rows))


# ----------------------------------------------------------------- golden tests
def test_row_number_rank_dense_rank_golden():
    t = sales_table()
    w = Window.partitionBy("dept").orderBy("salary")
    df = sess().create_dataframe(t).select(
        "dept", "emp", "salary",
        F.row_number().over(w).alias("rn"),
        F.rank().over(w).alias("rk"),
        F.dense_rank().over(w).alias("dr"))
    out = df.collect()
    rows = {e: (rn, rk, dr) for e, rn, rk, dr in zip(
        out.column("emp").to_pylist(), out.column("rn").to_pylist(),
        out.column("rk").to_pylist(), out.column("dr").to_pylist())}
    # dept a salaries ordered: 100, 200, 200 (tie)
    assert rows["e1"] == (1, 1, 1)
    assert rows["e2"] == (2, 2, 2)
    assert rows["e3"] == (3, 2, 2)
    # dept b: null sorts FIRST (Spark asc nulls first): e6(None), e7(25), e4(50), e5(75)
    assert rows["e6"] == (1, 1, 1)
    assert rows["e7"] == (2, 2, 2)
    assert rows["e4"] == (3, 3, 3)
    assert rows["e5"] == (4, 4, 4)
    assert rows["e8"] == (1, 1, 1)


def test_running_sum_default_frame_golden():
    """Default frame with ORDER BY = RANGE UNBOUNDED PRECEDING..CURRENT ROW,
    which includes peers (ties)."""
    t = sales_table()
    w = Window.partitionBy("dept").orderBy("salary")
    out = sess().create_dataframe(t).select(
        "emp", F.sum("salary").over(w).alias("s")).collect()
    rows = dict(zip(out.column("emp").to_pylist(),
                    out.column("s").to_pylist()))
    assert rows["e1"] == 100
    # e2 and e3 are peers (200): both see 100+200+200
    assert rows["e2"] == 500 and rows["e3"] == 500
    # dept b: null first (sum null-only frame -> null), then 25, 75, 150
    assert rows["e6"] is None
    assert rows["e7"] == 25 and rows["e4"] == 75 and rows["e5"] == 150
    assert rows["e8"] == 300


def test_rows_frame_golden():
    t = sales_table()
    w = (Window.partitionBy("dept").orderBy("salary")
         .rowsBetween(-1, Window.currentRow))
    out = sess().create_dataframe(t).select(
        "emp", F.sum("salary").over(w).alias("s")).collect()
    rows = dict(zip(out.column("emp").to_pylist(), out.column("s").to_pylist()))
    # dept a sorted: e1(100), e2(200), e3(200) — rows frame ignores peers
    assert rows["e1"] == 100 and rows["e2"] == 300 and rows["e3"] == 400
    # dept b sorted: e6(None), e7(25), e4(50), e5(75)
    assert rows["e6"] is None  # frame = {null} -> sum null
    assert rows["e7"] == 25    # frame = {null, 25}
    assert rows["e4"] == 75    # {25, 50}
    assert rows["e5"] == 125   # {50, 75}


def test_whole_partition_frame_golden():
    t = sales_table()
    w = (Window.partitionBy("dept").orderBy("salary")
         .rowsBetween(Window.unboundedPreceding, Window.unboundedFollowing))
    out = sess().create_dataframe(t).select(
        "emp",
        F.max("salary").over(w).alias("mx"),
        F.min("salary").over(w).alias("mn"),
        F.count("salary").over(w).alias("cnt"),
        F.avg("salary").over(w).alias("av")).collect()
    rows = {e: (mx, mn, c, av) for e, mx, mn, c, av in zip(
        out.column("emp").to_pylist(), out.column("mx").to_pylist(),
        out.column("mn").to_pylist(), out.column("cnt").to_pylist(),
        out.column("av").to_pylist())}
    for e in ("e1", "e2", "e3"):
        assert rows[e] == (200, 100, 3, pytest.approx(500 / 3))
    for e in ("e4", "e5", "e6", "e7"):
        assert rows[e] == (75, 25, 3, pytest.approx(50.0))
    assert rows["e8"] == (300, 300, 1, 300.0)


def test_range_frame_offsets_golden():
    t = pa.table({"g": ["x"] * 5, "v": pa.array([1, 3, 4, 7, 8],
                                                type=pa.int64())})
    w = Window.partitionBy("g").orderBy("v").rangeBetween(-2, 2)
    out = sess().create_dataframe(t).select(
        "v", F.count("v").over(w).alias("c"),
        F.sum("v").over(w).alias("s")).collect()
    rows = dict(zip(out.column("v").to_pylist(),
                    zip(out.column("c").to_pylist(),
                        out.column("s").to_pylist())))
    assert rows[1] == (2, 4)     # values in [-1, 3]: {1, 3}
    assert rows[3] == (3, 8)     # [1, 5]: {1, 3, 4}
    assert rows[4] == (2, 7)     # [2, 6]: {3, 4}
    assert rows[7] == (2, 15)    # [5, 9]: {7, 8}
    assert rows[8] == (2, 15)    # [6, 10]: {7, 8}


def test_lead_lag_golden():
    t = sales_table()
    w = Window.partitionBy("dept").orderBy("salary")
    out = sess().create_dataframe(t).select(
        "emp",
        F.lag("salary", 1).over(w).alias("lg"),
        F.lead("salary", 1, -1).over(w).alias("ld")).collect()
    rows = {e: (lg, ld) for e, lg, ld in zip(
        out.column("emp").to_pylist(), out.column("lg").to_pylist(),
        out.column("ld").to_pylist())}
    assert rows["e1"] == (None, 200)   # first in dept a
    assert rows["e2"] == (100, 200)
    assert rows["e3"] == (200, -1)     # last -> lead default
    assert rows["e6"] == (None, 25)    # null row is first in dept b
    assert rows["e7"] == (None, 50)    # lag hits the null row's value
    assert rows["e8"] == (None, -1)


def test_ntile_percent_rank_cume_dist_golden():
    t = pa.table({"g": ["x"] * 4, "v": pa.array([10, 20, 20, 40],
                                                type=pa.int64())})
    w = Window.partitionBy("g").orderBy("v")
    out = sess().create_dataframe(t).select(
        "v", F.ntile(2).over(w).alias("nt"),
        F.percent_rank().over(w).alias("pr"),
        F.cume_dist().over(w).alias("cd")).collect()
    nt = out.column("nt").to_pylist()
    pr = out.column("pr").to_pylist()
    cd = out.column("cd").to_pylist()
    assert nt == [1, 1, 2, 2]
    assert pr == [0.0, pytest.approx(1 / 3), pytest.approx(1 / 3), 1.0]
    assert cd == [pytest.approx(0.25), pytest.approx(0.75),
                  pytest.approx(0.75), 1.0]


def test_string_min_max_first_last_over_window():
    t = pa.table({"g": ["a", "a", "a", "b", "b"],
                  "s": ["banana", "apple", None, "zebra", "yak"]})
    w = (Window.partitionBy("g").orderBy("s")
         .rowsBetween(Window.unboundedPreceding, Window.unboundedFollowing))
    out = sess().create_dataframe(t).select(
        "g", F.min("s").over(w).alias("mn"),
        F.max("s").over(w).alias("mx"),
        F.first("s", ignorenulls=True).over(w).alias("fv")).collect()
    rows = {g: (mn, mx, fv) for g, mn, mx, fv in zip(
        out.column("g").to_pylist(), out.column("mn").to_pylist(),
        out.column("mx").to_pylist(), out.column("fv").to_pylist())}
    assert rows["a"] == ("apple", "banana", "apple")
    assert rows["b"] == ("yak", "zebra", "yak")


# ----------------------------------------------------------------- parity tests
def _parity(build, **kw):
    assert_tpu_and_cpu_equal(build, conf=CONF,
                             expect_tpu_execs=["TpuWindowExec"], **kw)


def test_parity_mixed_window_functions():
    rng = np.random.default_rng(7)
    n = 500
    t = pa.table({
        "k": rng.integers(0, 10, n).astype(np.int32),
        "o": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.normal(size=n),
    })

    def build(s):
        w = Window.partitionBy("k").orderBy("o", F.col("v").desc())
        return s.create_dataframe(t).select(
            "k", "o",
            F.row_number().over(w).alias("rn"),
            F.rank().over(w).alias("rk"),
            F.dense_rank().over(w).alias("dr"),
            F.sum("v").over(w).alias("s"),
            F.lag("o", 2).over(w).alias("lg"))
    _parity(build, approx_float=1e-10)


def test_parity_bounded_rows_and_range_frames():
    rng = np.random.default_rng(8)
    n = 300
    vals = rng.integers(-100, 100, n).astype(np.int64)
    mask = rng.random(n) < 0.1
    t = pa.table({
        "k": rng.integers(0, 5, n).astype(np.int32),
        "v": pa.array([None if m else int(x) for m, x in zip(mask, vals)],
                      type=pa.int64()),
    })

    def build(s):
        wr = Window.partitionBy("k").orderBy("v").rowsBetween(-3, 2)
        wg = Window.partitionBy("k").orderBy("v").rangeBetween(-10, 10)
        return s.create_dataframe(t).select(
            "k",
            F.min("v").over(wr).alias("mn"),
            F.max("v").over(wr).alias("mx"),
            F.count("v").over(wg).alias("c"),
            F.sum("v").over(wg).alias("s"))
    _parity(build)


def test_parity_no_partition_keys():
    rng = np.random.default_rng(9)
    t = pa.table({"v": rng.integers(0, 1000, 200).astype(np.int64)})

    def build(s):
        w = Window.orderBy("v")
        return s.create_dataframe(t).select(
            "v", F.row_number().over(w).alias("rn"),
            F.sum("v").over(w).alias("s"))
    _parity(build)


def test_parity_desc_range_frame():
    t = pa.table({"g": ["x"] * 6,
                  "v": pa.array([5, 1, 9, 3, 7, 5], type=pa.int64())})

    def build(s):
        w = (Window.partitionBy("g").orderBy(F.col("v").desc())
             .rangeBetween(-2, 2))
        return s.create_dataframe(t).select(
            "v", F.count("v").over(w).alias("c"))
    _parity(build)


def test_window_fallback_unknown_frame_still_correct():
    """Window over a float order key with range offsets falls back if
    unsupported; either way results must match the CPU engine."""
    t = pa.table({"g": ["x"] * 4,
                  "v": pa.array([1.0, 2.5, 3.0, 9.0])})

    def build(s):
        w = Window.partitionBy("g").orderBy("v").rangeBetween(-1.5, 1.5)
        return s.create_dataframe(t).select(
            "v", F.count("v").over(w).alias("c"))
    assert_tpu_and_cpu_equal(build, conf=CONF)


def test_multiple_specs_in_one_select():
    t = sales_table()

    def build(s):
        w1 = Window.partitionBy("dept").orderBy("salary")
        w2 = Window.partitionBy("dept")
        return s.create_dataframe(t).select(
            "emp",
            F.row_number().over(w1).alias("rn"),
            F.sum("salary").over(w2).alias("total"))
    assert_tpu_and_cpu_equal(build, conf=CONF, ignore_order=True)


def test_stacked_selects_with_windows_no_name_collision():
    t = sales_table()
    s = sess()
    df = s.create_dataframe(t).select(
        "dept", "salary",
        F.row_number().over(Window.partitionBy("dept").orderBy("salary"))
        .alias("rn"))
    out = df.select(
        "dept",
        F.max("rn").over(Window.partitionBy("dept")).alias("mx")).collect()
    assert out.num_rows == 8


def test_ntile_rejects_nonpositive():
    with pytest.raises(ValueError, match="positive"):
        F.ntile(0)


def test_range_frame_invalid_order_key_raises_clearly():
    t = pa.table({"g": ["x", "x"], "s": ["a", "b"]})
    for enabled in (True, False):
        df = sess(enabled).create_dataframe(t).select(
            "s", F.count("s").over(
                Window.partitionBy("g").orderBy("s").rangeBetween(-1, 1))
            .alias("c"))
        with pytest.raises(ValueError, match="RANGE"):
            df.collect()


def test_range_frame_int64_precision_above_2_53():
    big = 1 << 60
    t = pa.table({"g": ["x", "x"],
                  "v": pa.array([big + 100, big + 300], type=pa.int64())})

    def build(s):
        w = Window.partitionBy("g").orderBy("v").rangeBetween(-150, 150)
        return s.create_dataframe(t).select(
            "v", F.count("v").over(w).alias("c"))
    out = assert_tpu_and_cpu_equal(build, conf=CONF)
    # gap is 200 > 150: each row's frame holds only itself (float64 would
    # collapse the two keys and report 2)
    assert out.column("c").to_pylist() == [1, 1]


def test_range_frame_date_order_key():
    """RANGE offsets over a DATE order key, counted in days (the
    GpuWindowExpression.scala:198-199 aggregateWindowsOverTimeRanges role —
    order-key domain is the native int32 day count, no float rounding)."""
    import datetime
    t = pa.table({
        "g": ["x"] * 5,
        "d": pa.array([datetime.date(2020, 1, 1), datetime.date(2020, 1, 2),
                       datetime.date(2020, 1, 5), datetime.date(2020, 1, 6),
                       None], type=pa.date32()),
        "v": [1.0, 2.0, 3.0, 4.0, 5.0],
    })

    def build(s):
        w = Window.partitionBy("g").orderBy("d").rangeBetween(-1, 1)
        return s.create_dataframe(t).select(
            "d", F.sum("v").over(w).alias("s"))
    out = assert_tpu_and_cpu_equal(build, conf=CONF)
    rows = dict(zip(out.column("d").to_pylist(), out.column("s").to_pylist()))
    # 1/1 and 1/2 are within a day of each other; 1/5 and 1/6 likewise; the
    # null-keyed row's frame is its (null) peer group only
    assert rows[datetime.date(2020, 1, 1)] == 3.0
    assert rows[datetime.date(2020, 1, 2)] == 3.0
    assert rows[datetime.date(2020, 1, 5)] == 7.0
    assert rows[datetime.date(2020, 1, 6)] == 7.0
    assert rows[None] == 5.0


def test_range_frame_timestamp_order_key():
    """RANGE offsets over a TIMESTAMP order key, in microseconds (time-range
    frames over timestamps, GpuWindowExpression.scala:198-199)."""
    import datetime
    ts = [datetime.datetime(2020, 1, 1, 0, 0, s) for s in (0, 1, 2, 3)]
    t = pa.table({
        "g": ["x"] * 5,
        "ts": pa.array(ts + [None], type=pa.timestamp("us")),
        "v": [1.0, 2.0, 3.0, 4.0, 5.0],
    })

    def build(s):
        w = (Window.partitionBy("g").orderBy("ts")
             .rangeBetween(-1_000_000, 1_000_000))    # ±1 second
        return s.create_dataframe(t).select(
            "ts", F.count("v").over(w).alias("c"))
    out = assert_tpu_and_cpu_equal(build, conf=CONF)
    # the engine returns UTC-aware timestamps (Spark's UTC-only semantics)
    keys = [(v.replace(tzinfo=None) if v is not None else None)
            for v in out.column("ts").to_pylist()]
    rows = dict(zip(keys, out.column("c").to_pylist()))
    assert rows[ts[0]] == 2 and rows[ts[1]] == 3
    assert rows[ts[2]] == 3 and rows[ts[3]] == 2
    assert rows[None] == 1      # count(v) over the null row's peer frame


def test_range_frame_inf_nan_null_keys():
    t = pa.table({"g": ["x"] * 4,
                  "v": pa.array([None, float("-inf"), float("inf"),
                                 float("nan")])})

    def build(s):
        w = Window.partitionBy("g").orderBy("v").rangeBetween(-1.0, 1.0)
        return s.create_dataframe(t).select(
            "v", F.count("v").over(w).alias("c"))
    out = assert_tpu_and_cpu_equal(build, conf=CONF)
    rows = dict(zip(out.column("v").to_pylist(), out.column("c").to_pylist()))
    # null row: frame = null peers only -> count(v) = 0
    assert rows[None] == 0
    # -inf and +inf rows: -inf±1 = -inf, inf±1 = inf -> only themselves
    assert rows[float("-inf")] == 1 and rows[float("inf")] == 1
    # NaN row: peer-group frame -> itself (NaN is valid for count)
    nan_counts = [c for v, c in rows.items()
                  if isinstance(v, float) and v != v]
    assert nan_counts == [1]


def test_ranking_function_requires_order_by():
    with pytest.raises(ValueError, match="ordered"):
        F.rank().over(Window.partitionBy("g"))
    with pytest.raises(ValueError, match="ordered"):
        F.lead("v").over(Window.partitionBy("g"))
