"""Mesh execution at capacity-forcing scale and under key skew — the
round-2 VERDICT's 'mesh tests never trigger capacity growth or skew'
gap. Asserts ride the exchange-sizing stats (the MapOutputStatistics
analog) and the ICI overflow re-run counter."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.execs import mesh_execs as me
from spark_rapids_tpu.testing import assert_tables_equal

pytestmark = pytest.mark.slow

MESH_CONF = {
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
}


def test_mesh_join_under_extreme_skew(eight_devices):
    """90% of fact rows share ONE join key: the hash exchange lands them all
    on one shard. The count pre-pass must size that shard's chunk ABOVE the
    even-split capacity (capacity growth), rows must be conserved, and the
    result must match the CPU engine."""
    rng = np.random.default_rng(83)
    n = 40000
    keys = np.where(rng.random(n) < 0.9, 7,
                    rng.integers(0, 1000, n)).astype(np.int64)
    fact = pa.table({"k": keys, "v": rng.integers(0, 100, n).astype(np.int64)})
    dim = pa.table({"k": np.arange(1000, dtype=np.int64),
                    "w": rng.integers(0, 10, 1000).astype(np.int64)})

    def q(s):
        return (s.create_dataframe(fact)
                .join(s.create_dataframe(dim), "k")
                .groupBy("w").agg(F.sum("v").alias("sv"),
                                  F.count("k").alias("c")))

    me.EXCHANGE_STATS.clear()
    s = TpuSession(MESH_CONF)
    out = q(s).collect()
    joins = [st for st in me.EXCHANGE_STATS if st["op"] == "mjoin_lpart"]
    assert joins, me.EXCHANGE_STATS
    st = joins[-1]
    even = st["rows"] // 8
    assert st["recv_max"] > 4 * even, (
        f"skewed shard should receive most rows: {st}")
    assert st["recv_max"] >= 0.85 * st["rows"], st
    # the receiving shard's capacity grew past the even split
    assert st["out_cap"] > even, st
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    assert_tables_equal(q(cpu).collect(), out, ignore_order=True)


def test_mesh_tpch_at_capacity_forcing_scale(eight_devices):
    """TPC-H Q3 + Q18 at 25x the mesh suite's scale: per-shard row counts
    cross multiple capacity buckets (growth/shrink on every exchange) and
    results still match the CPU engine exactly."""
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all
    from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
    tables = gen_all(0.05, seed=7)
    assert tables["lineitem"].num_rows > 250_000
    conf = {**BENCH_CONF, **MESH_CONF}
    me.EXCHANGE_STATS.clear()
    for qnum in (3, 18):
        s = TpuSession(conf)
        dfs = {k: s.create_dataframe(v) for k, v in tables.items()}
        out = QUERIES[qnum](dfs).collect()
        cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
        cdfs = {k: cpu.create_dataframe(v) for k, v in tables.items()}
        exp = QUERIES[qnum](cdfs).collect()
        assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-9)
    # the exchanges really carried capacity-bucket-crossing volumes
    assert any(st["chunk_cap"] >= 4096 for st in me.EXCHANGE_STATS), (
        me.EXCHANGE_STATS[:10])


def test_ici_overflow_rerun_fires_on_real_exchange(eight_devices):
    """The overflow-detect-and-re-run driver (shuffle/ici.py): a skewed
    repartition starting from an undersized chunk MUST flag and re-run with
    doubled capacity until no row is clamped — counter asserted, rows
    conserved, content exact."""
    import jax
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.mesh_batch import scatter_arrow
    from spark_rapids_tpu.shuffle import ici

    rng = np.random.default_rng(89)
    n = 8192
    # every row to shard 0: worst-case skew
    t = pa.table({"a": rng.integers(0, 1 << 30, n).astype(np.int64)})
    mesh = make_mesh(8)
    mb = scatter_arrow(t, mesh, 16)
    pids = jax.device_put(
        np.zeros(mesh.devices.size * mb.local_capacity, dtype=np.int32),
        jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data")))
    from spark_rapids_tpu.parallel.mesh_batch import flatten_mesh
    reruns_before = ici.RERUN_COUNT
    out_rows, flat = ici.ici_repartition(
        mesh, mb.schema, mb.local_capacity, mb.rows_dev(), pids,
        flatten_mesh(mb), chunk_capacity=64)
    assert ici.RERUN_COUNT > reruns_before, (
        "undersized chunk must trigger at least one overflow re-run")
    rows = np.asarray(out_rows)
    assert int(rows.sum()) == n and int(rows[0]) == n, rows
    got = np.sort(np.asarray(flat[0])[:n])
    assert np.array_equal(got, np.sort(t.column("a").to_numpy()))


def test_mesh_tpch_at_32_devices():
    """Round-4 VERDICT item 7: mesh lowering past 8 devices. Runs in a
    subprocess (the 32-device CPU topology must be set before jax loads)
    and executes TPC-H Q1+Q3 on a 32-device mesh vs the CPU engine."""
    import os
    import subprocess
    import sys
    script = r"""
import jax
jax.config.update("jax_platforms", "cpu")
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.benchmarks.tpch_data import gen_all
from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
from spark_rapids_tpu.testing import assert_tables_equal
assert jax.device_count() == 32, jax.devices()
tables = gen_all(0.002, seed=5)
mesh = TpuSession({
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.mesh.numDevices": "32",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.hasNans": "false",
    "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1"})
cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
for qn in (1, 3):
    out = QUERIES[qn]({k: mesh.create_dataframe(v)
                       for k, v in tables.items()}).collect()
    exp = QUERIES[qn]({k: cpu.create_dataframe(v)
                       for k, v in tables.items()}).collect()
    assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-6)
    print(f"q{qn} ok on 32-device mesh", flush=True)
"""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", script], env=env, cwd=repo,
                       capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-3000:]}"
    assert "q3 ok on 32-device mesh" in r.stdout
