"""Concurrent query serving: scheduler, program cache, lifecycle, fairness.

Covers the serving subsystem's contracts (docs/serving.md):
- scheduler: N concurrent queries complete, fair-share tenant admission
  (weighted deficit round-robin, FIFO within tenant), SQL submission;
- lifecycle: cooperative cancellation (QUEUED, RUNNING, and
  blocked-on-admission), deadlines, per-query metric snapshots, and the
  cancelled-query-releases-semaphore/catalog regression tests;
- program cache: cross-query reuse, shape-bucket keying, the concurrent-
  build latch, and the on-disk index warm start;
- the last_metrics data-race fix (atomic per-action snapshots);
- scan-cache in-flight upload latch;
- store concurrency: BufferCatalog acquire/remove + spill hammered from 8
  threads while a query runs.
"""
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.serving import (ProgramCache, QueryCancelledError,
                                      QueryState, QueryTimeoutError)
from spark_rapids_tpu.serving.scheduler import parse_tenant_weights

BASE_CONF = {
    "spark.rapids.tpu.sql.string.maxBytes": "16",
    "spark.rapids.tpu.serving.maxConcurrentQueries": "3",
    # double aggregations stay on the TPU engine (parallel-reduction float
    # ordering), so the tests exercise real device programs
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}


def make_session(extra=None):
    return TpuSession({**BASE_CONF, **(extra or {})})


def small_table(n=64, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 8, n).astype("int64"),
        "v": rng.random(n),
    })


def blocking_udf_df(sess, started, release, n_rows=2):
    """A DataFrame whose execution signals ``started`` and then blocks on
    ``release`` (row UDF on the fallback path) — the controllable slow
    query the cancellation/fairness tests drive."""
    def slow(x):
        started.set()
        release.wait(20)
        return x

    df = sess.create_dataframe(pa.table({"a": list(range(n_rows))}))
    return df.select(F.udf(slow, DType.LONG)(F.col("a")).alias("b"))


# ------------------------------------------------------------- scheduler
def test_concurrent_queries_all_complete():
    sess = make_session()
    t = small_table(256)
    df = (sess.create_dataframe(t).groupBy("k")
          .agg(F.sum("v").alias("s"), F.count(F.lit(1)).alias("c")))
    expected = df.collect()
    handles = [sess.submit(df, tenant=f"t{i % 3}") for i in range(9)]
    for h in handles:
        out = h.result(timeout=120)
        assert out.num_rows == expected.num_rows
        assert h.state is QueryState.DONE
        snap = h.snapshot()
        assert snap["queue_wait_s"] is not None
        assert snap["rows"] == expected.num_rows
    stats = sess.scheduler.stats()
    assert stats["states"]["DONE"] == 9
    assert stats["program_cache"]["hits"] > 0


def test_submit_sql_string():
    sess = make_session()
    sess.create_dataframe(small_table(32)).createOrReplaceTempView("t")
    h = sess.submit("SELECT k, COUNT(*) AS c FROM t GROUP BY k",
                    label="sql-smoke")
    out = h.result(timeout=120)
    assert out.num_rows > 0
    assert h.metrics.get("plan_key")


def test_submit_malformed_query_fails_handle():
    sess = make_session()
    h = sess.submit("SELECT definitely_not_a_column FROM nowhere")
    h.wait(120)
    assert h.state is QueryState.FAILED
    with pytest.raises(Exception):
        h.result(timeout=1)


def test_fair_share_interleaves_tenants():
    """With one worker, queued tenants are served by weighted deficit:
    [a, a, b] admits as a, b, a — not global FIFO."""
    sess = make_session({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release),
                          tenant="z")
    assert started.wait(60)
    order = []

    def tag_df(name):
        def tag(x):
            order.append(name)
            return x
        df = sess.create_dataframe(pa.table({"a": [1]}))
        return df.select(F.udf(tag, DType.LONG)(F.col("a")).alias("b"))

    ha1 = sess.submit(tag_df("a1"), tenant="a")
    ha2 = sess.submit(tag_df("a2"), tenant="a")
    hb1 = sess.submit(tag_df("b1"), tenant="b")
    release.set()
    assert blocker.result(timeout=120) is not None
    for h in (ha1, ha2, hb1):
        h.result(timeout=120)
    assert order == ["a1", "b1", "a2"]


def test_tenant_weights_conf_parse():
    assert parse_tenant_weights("etl:3,adhoc:1") == {"etl": 3.0,
                                                     "adhoc": 1.0}
    assert parse_tenant_weights("") == {}
    with pytest.raises(ValueError):
        parse_tenant_weights("noweight")
    with pytest.raises(ValueError):
        parse_tenant_weights("t:0")
    # the error must NAME the conf key, not just echo float()'s message
    with pytest.raises(ValueError, match="tenantWeights"):
        parse_tenant_weights("etl:abc")


def test_drain_timeout_zero_polls():
    sess = make_session({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    started, release = threading.Event(), threading.Event()
    h = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    t0 = time.time()
    assert sess.scheduler.drain(timeout=0) is False
    assert time.time() - t0 < 5          # returned immediately, no block
    release.set()
    assert h.result(timeout=120) is not None
    assert sess.scheduler.drain(timeout=30) is True


def test_terminal_handles_pruned_beyond_history(monkeypatch):
    from spark_rapids_tpu.serving import scheduler as sched_mod
    monkeypatch.setattr(sched_mod, "_HANDLE_HISTORY", 4)
    sess = make_session()
    df = sess.create_dataframe(small_table(16)).groupBy("k").count()
    handles = [sess.submit(df) for _ in range(10)]
    for h in handles:
        h.result(timeout=120)
    sess.submit(df).result(timeout=120)   # triggers a post-completion prune
    stats = sess.scheduler.stats()
    assert len(sess.scheduler.handles()) <= 5
    assert stats["submitted"] == 11       # pruned handles still counted
    assert stats["states"]["DONE"] == 11


# ---------------------------------------------------------- cancellation
def test_cancel_queued_query_never_runs():
    sess = make_session({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    ran = []

    def tag(x):
        ran.append(x)
        return x

    df = (sess.create_dataframe(pa.table({"a": [1]}))
          .select(F.udf(tag, DType.LONG)(F.col("a")).alias("b")))
    victim = sess.submit(df)
    assert victim.cancel()
    release.set()
    blocker.result(timeout=120)
    victim.wait(120)
    assert victim.state is QueryState.CANCELLED
    assert ran == []
    with pytest.raises(QueryCancelledError):
        victim.result(timeout=1)


def test_cancelled_running_query_releases_semaphore_and_catalog():
    """The acceptance-bar regression test: a query cancelled MID-RUN must
    free its device-semaphore hold and leave no exec buffers behind in
    the catalog (the finally chain runs on the cooperative unwind)."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    sess = make_session()
    dm = DeviceManager.initialize(sess.conf)
    ids_before = set(dm.catalog.ids())
    started, release = threading.Event(), threading.Event()

    def slow(x):
        started.set()
        release.wait(20)
        return x

    # repartition forces a shuffle exchange, whose blocks register in the
    # catalog during the run and must be unregistered by the cleanups
    df = (sess.create_dataframe(pa.table({"a": list(range(8))}))
          .select(F.udf(slow, DType.LONG)(F.col("a")).alias("b"))
          .repartition(4, F.col("b"))
          .groupBy("b").count())
    h = sess.submit(df, label="victim")
    assert started.wait(60)
    assert h.cancel()
    release.set()
    h.wait(120)
    assert h.state is QueryState.CANCELLED
    assert dm.semaphore.active_holders == 0
    assert set(dm.catalog.ids()) == ids_before
    # the device stays usable: a follow-up query completes normally
    out = sess.submit(sess.create_dataframe(small_table(16))
                      .groupBy("k").count()).result(timeout=120)
    assert out.num_rows > 0


def test_cancel_while_blocked_on_device_admission():
    """A query stuck BEHIND the device semaphore observes its cancel flag
    via the semaphore's cancel_check and unwinds without a permit."""
    sess = make_session({
        "spark.rapids.tpu.sql.concurrentTpuTasks": "1",
        "spark.rapids.tpu.serving.maxConcurrentQueries": "2"})
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    DeviceManager.shutdown()            # apply the 1-permit conf
    dm = DeviceManager.initialize(sess.conf)
    assert dm.semaphore.max_concurrent == 1
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    waiter = sess.submit(sess.create_dataframe(small_table(16))
                         .groupBy("k").count())
    # the waiter reaches ADMITTED (a worker picked it) then blocks on the
    # device semaphore held by the blocker
    deadline = time.time() + 30
    while waiter.state is QueryState.QUEUED and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.2)
    assert waiter.cancel()
    waiter.wait(120)
    assert waiter.state is QueryState.CANCELLED
    release.set()
    assert blocker.result(timeout=120) is not None
    assert dm.semaphore.active_holders == 0
    assert dm.semaphore.waiting == 0


def test_query_deadline_fails_with_timeout_error():
    sess = make_session()
    started, release = threading.Event(), threading.Event()
    df = blocking_udf_df(sess, started, release)
    h = sess.submit(df, timeout=0.3)
    assert started.wait(60)
    time.sleep(0.4)                     # run past the deadline
    release.set()
    h.wait(120)
    assert h.state is QueryState.FAILED
    with pytest.raises(QueryTimeoutError):
        h.result(timeout=1)


# ---------------------------------------------------------- program cache
def test_program_cache_cross_query_reuse_and_shape_buckets():
    """Two submissions of the same plan shape share programs, and tables
    whose row counts land in the same power-of-two capacity bucket share
    them too (the serving.shapeBuckets discipline)."""
    sess = make_session()

    def agg_over(table):
        return (sess.create_dataframe(table).filter(F.col("v") > 0.25)
                .groupBy("k").agg(F.sum("v").alias("s")))

    first = sess.submit(agg_over(small_table(100, seed=1)))
    first.result(timeout=120)
    # 100 and 120 rows both bucket to capacity 128 -> identical keys
    second = sess.submit(agg_over(small_table(120, seed=2)))
    second.result(timeout=120)
    pc2 = second.snapshot()["program_cache"]
    assert pc2["misses"] == 0, pc2
    assert pc2["hits"] > 0, pc2


def test_program_cache_build_latch_single_build():
    cache = ProgramCache(index_path="off")
    builds = []

    def builder():
        builds.append(1)
        time.sleep(0.05)
        return lambda x: x + 1

    outs = []

    def worker():
        outs.append(cache.get_or_build(("k",), builder))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(builds) == 1
    assert len({id(o) for o in outs}) == 1
    st = cache.stats()
    assert st["misses"] == 1 and st["hits"] == 7


def test_program_cache_disk_index_warm_start(tmp_path):
    """A second cache instance (a restarted server) pointed at the same
    index directory counts its first compile of a known key as a
    disk hit."""
    d = str(tmp_path)
    c1 = ProgramCache(index_path=d)
    c1.get_or_build(("plan", "sig", 128), lambda: (lambda x: x))
    assert c1.stats()["disk_hits"] == 0
    c2 = ProgramCache(index_path=d)
    c2.get_or_build(("plan", "sig", 128), lambda: (lambda x: x))
    st = c2.stats()
    assert st["misses"] == 1 and st["disk_hits"] == 1
    # an unknown key is a cold miss, not a disk hit
    c2.get_or_build(("other", 1), lambda: (lambda x: x))
    assert c2.stats()["disk_hits"] == 1


def test_program_cache_latch_wait_cancellable_and_clear_safe():
    """A query waiting on another query's in-flight build observes its
    cancel flag, and clear() during a build does not orphan the latch."""
    from spark_rapids_tpu.serving.lifecycle import QueryHandle, bind_query
    cache = ProgramCache(index_path="off")
    release = threading.Event()

    def slow_builder():
        release.wait(20)
        return lambda x: x

    builder_thread = threading.Thread(
        target=lambda: cache.get_or_build(("slow",), slow_builder))
    builder_thread.start()
    deadline = time.time() + 10
    while not cache._building and time.time() < deadline:
        time.sleep(0.005)
    cache.clear()       # must NOT drop the in-flight latch
    victim = QueryHandle(None, label="latch-victim")
    victim.cancel()
    errs = []

    def waiter():
        with bind_query(victim):
            try:
                cache.get_or_build(("slow",), slow_builder)
            except QueryCancelledError as e:
                errs.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    t.join(30)
    assert len(errs) == 1               # cancelled waiter unwound
    release.set()
    builder_thread.join(30)             # builder completes normally
    assert cache.get_or_build(("slow",), slow_builder) is not None


def test_program_cache_lru_bound():
    cache = ProgramCache(max_programs=4, index_path="off")
    for i in range(10):
        cache.get_or_build(("k", i), lambda: (lambda x: x))
    st = cache.stats()
    assert st["programs"] == 4 and st["evictions"] == 6


def test_plan_key_stable_across_row_counts():
    from spark_rapids_tpu.serving.program_cache import plan_key
    sess = make_session()
    k1 = plan_key(sess.create_dataframe(small_table(100))
                  .groupBy("k").count()._executed_plan(), sess.conf)
    k2 = plan_key(sess.create_dataframe(small_table(120))
                  .groupBy("k").count()._executed_plan(), sess.conf)
    k3 = plan_key(sess.create_dataframe(small_table(100))
                  .groupBy("k").agg(F.sum("v").alias("s"))
                  ._executed_plan(), sess.conf)
    assert k1 == k2
    assert k1 != k3


# ---------------------------------------------------- per-query metrics
def test_interleaved_collects_keep_metrics_separate():
    """The session.last_metrics data-race fix: concurrent queries get
    their own exec-metric snapshots, and the global alias is exactly one
    query's complete snapshot (never a mix)."""
    sess = make_session()
    df_a = (sess.create_dataframe(small_table(128, seed=3))
            .groupBy("k").agg(F.sum("v").alias("s")))
    df_b = (sess.create_dataframe(small_table(64, seed=4))
            .filter(F.col("v") > 0.5).select("k"))
    ha = sess.submit(df_a, label="a")
    hb = sess.submit(df_b, label="b")
    ha.result(timeout=120)
    hb.result(timeout=120)
    assert ha.exec_metrics and hb.exec_metrics
    assert "transfer" in ha.exec_metrics and "transfer" in hb.exec_metrics
    assert ha.exec_metrics is not hb.exec_metrics
    # the compatibility alias is one query's snapshot object, unmutated
    assert sess.last_metrics in (ha.exec_metrics, hb.exec_metrics) or \
        sess.last_metrics == ha.exec_metrics or \
        sess.last_metrics == hb.exec_metrics


# ------------------------------------------------------- scan-cache latch
def test_scan_cache_concurrent_miss_single_upload():
    from spark_rapids_tpu.memory.scan_cache import DeviceScanCache

    class FakeBatch:
        device_size_bytes = 128

    cache = DeviceScanCache(max_bytes=1 << 20)
    table = small_table(8)
    uploads = []

    def builder():
        uploads.append(1)
        time.sleep(0.05)
        return FakeBatch()

    outs = []
    threads = [threading.Thread(
        target=lambda: outs.append(cache.get_or_put(table, 16, builder)))
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(uploads) == 1
    assert len({id(o) for o in outs}) == 1


def test_scan_cache_builder_failure_releases_latch():
    from spark_rapids_tpu.memory.scan_cache import DeviceScanCache

    class FakeBatch:
        device_size_bytes = 128

    cache = DeviceScanCache(max_bytes=1 << 20)
    table = small_table(8)

    def failing():
        raise RuntimeError("upload died")

    with pytest.raises(RuntimeError):
        cache.get_or_put(table, 16, failing)
    # the key is not latched forever: a later builder succeeds
    out = cache.get_or_put(table, 16, FakeBatch)
    assert isinstance(out, FakeBatch)


# --------------------------------------------------- semaphore fairness
def test_semaphore_weighted_fairness_and_fifo():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    assert sem.acquire_if_necessary(task_id=999)
    order = []
    threads = []

    def waiter(name, tenant, tid):
        with sem.held(task_id=tid, tenant=tenant):
            order.append(name)

    for i, (name, tenant) in enumerate(
            [("a1", "a"), ("a2", "a"), ("b1", "b")]):
        t = threading.Thread(target=waiter, args=(name, tenant, 1000 + i))
        t.start()
        deadline = time.time() + 10
        while sem.waiting < i + 1 and time.time() < deadline:
            time.sleep(0.005)
        threads.append(t)
    sem.release_if_necessary(task_id=999)
    for t in threads:
        t.join(30)
    # deficit round-robin: a then b then a — FIFO within tenant a
    assert order == ["a1", "b1", "a2"]


def test_semaphore_weight_prefers_heavy_tenant():
    """Weighted deficit round-robin: with heavy:3, heavy admits 3 of the
    first 4 permits. From zero deficits: heavy wins the tie (name), then
    light's 0 deficit beats heavy's 1/3, then heavy (1/3, 2/3) beats
    light's 1 twice, then light drains."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    sem.set_tenant_weight("heavy", 3.0)
    assert sem.acquire_if_necessary(task_id=999)
    order = []
    threads = []
    for i, (name, tenant) in enumerate(
            [("l1", "light"), ("h1", "heavy"), ("l2", "light"),
             ("h2", "heavy"), ("h3", "heavy")]):
        def waiter(name=name, tenant=tenant, tid=2000 + i):
            with sem.held(task_id=tid, tenant=tenant):
                order.append(name)
        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.time() + 10
        while sem.waiting < i + 1 and time.time() < deadline:
            time.sleep(0.005)
        threads.append(t)
    sem.release_if_necessary(task_id=999)
    for t in threads:
        t.join(30)
    assert order == ["h1", "l1", "h2", "h3", "l2"]


def test_semaphore_late_joiner_does_not_monopolize():
    """Deficit counters are clamped on tenant (re)activation: a tenant
    joining after another has been served for a while must share from
    NOW on, not drain its whole historical 'debt' first."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    for i in range(5):      # tenant a has history
        assert sem.acquire_if_necessary(task_id=100 + i, tenant="a")
        sem.release_if_necessary(task_id=100 + i)
    assert sem.acquire_if_necessary(task_id=999)
    order = []
    threads = []
    for i, (name, tenant) in enumerate(
            [("a1", "a"), ("b1", "b"), ("b2", "b")]):
        def waiter(name=name, tenant=tenant, tid=3000 + i):
            with sem.held(task_id=tid, tenant=tenant):
                order.append(name)
        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.time() + 10
        while sem.waiting < i + 1 and time.time() < deadline:
            time.sleep(0.005)
        threads.append(t)
    sem.release_if_necessary(task_id=999)
    for t in threads:
        t.join(30)
    # without the activation clamp, b's zero deficit would admit b1 AND
    # b2 before a1 despite a1 queueing first
    assert order == ["a1", "b1", "b2"]


def test_semaphore_returning_tenant_not_starved():
    """The inverse of the late-joiner case: a tenant with long served
    history re-activating against a newcomer's backlog joins at the
    CURRENT floor instead of waiting for the newcomer to 'catch up' its
    entire history."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    for i in range(5):      # tenant a has long history
        assert sem.acquire_if_necessary(task_id=100 + i, tenant="a")
        sem.release_if_necessary(task_id=100 + i)
    assert sem.acquire_if_necessary(task_id=999)
    order = []
    threads = []
    for i, (name, tenant) in enumerate(
            [("b1", "b"), ("b2", "b"), ("b3", "b"), ("a1", "a")]):
        def waiter(name=name, tenant=tenant, tid=4000 + i):
            with sem.held(task_id=tid, tenant=tenant):
                order.append(name)
        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.time() + 10
        while sem.waiting < i + 1 and time.time() < deadline:
            time.sleep(0.005)
        threads.append(t)
    sem.release_if_necessary(task_id=999)
    for t in threads:
        t.join(30)
    # pre-fix, a1 would wait behind ALL of b's backlog (a's deficit 5 vs
    # b's 0); with the activation reset a re-enters at the floor
    assert order.index("a1") <= 1, order


def test_tenant_weights_conf_reaches_device_semaphore():
    """serving.tenantWeights must drive device admission even though the
    DeviceManager is created lazily AFTER the scheduler."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    sess = make_session({
        "spark.rapids.tpu.serving.tenantWeights": "etl:3,adhoc:1"})
    h = sess.submit(sess.create_dataframe(small_table(16))
                    .groupBy("k").count(), tenant="etl")
    h.result(timeout=120)
    sem = DeviceManager.get().semaphore
    assert sem._weights.get("etl") == 3.0
    assert sem._weights.get("adhoc") == 1.0


def test_program_cache_no_disk_hits_with_persistence_off():
    cache = ProgramCache(index_path="off")
    cache.get_or_build(("k", 1), lambda: (lambda x: x))
    cache.clear()                       # forces a rebuild of a known key
    cache.get_or_build(("k", 1), lambda: (lambda x: x))
    assert cache.stats()["disk_hits"] == 0


def test_scan_cache_latch_wait_is_cancellable():
    from spark_rapids_tpu.memory.scan_cache import DeviceScanCache

    class FakeBatch:
        device_size_bytes = 128

    cache = DeviceScanCache(max_bytes=1 << 20)
    table = small_table(8)
    release = threading.Event()

    def slow_builder():
        release.wait(20)
        return FakeBatch()

    builder_thread = threading.Thread(
        target=lambda: cache.get_or_put(table, 16, slow_builder))
    builder_thread.start()
    deadline = time.time() + 10
    while not cache._inflight and time.time() < deadline:
        time.sleep(0.005)
    cancelled = threading.Event()
    cancelled.set()

    def check():
        if cancelled.is_set():
            raise QueryCancelledError("stop")

    with pytest.raises(QueryCancelledError):
        cache.get_or_put(table, 16, lambda: FakeBatch(),
                         cancel_check=check)
    release.set()
    builder_thread.join(30)
    assert cache.get(table, 16) is not None


def test_semaphore_nesting_preserved():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    with sem.held(task_id=7):
        with sem.held(task_id=7):       # same task nests, no second permit
            assert sem.active_holders == 1
        assert sem.active_holders == 1
    assert sem.active_holders == 0


def test_semaphore_cancel_check_unblocks_waiter():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    assert sem.acquire_if_necessary(task_id=1)
    cancelled = threading.Event()

    def check():
        if cancelled.is_set():
            raise QueryCancelledError("stop")

    errs = []

    def waiter():
        try:
            with sem.held(task_id=2, cancel_check=check):
                pass
        except QueryCancelledError as e:
            errs.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.time() + 10
    while sem.waiting < 1 and time.time() < deadline:
        time.sleep(0.005)
    cancelled.set()
    t.join(30)
    assert len(errs) == 1
    assert sem.waiting == 0
    sem.release_if_necessary(task_id=1)
    # the permit is untouched and reusable
    assert sem.acquire_if_necessary(task_id=3, timeout=1)
    sem.release_if_necessary(task_id=3)


# --------------------------------------------------- store concurrency
def test_store_concurrency_under_running_query():
    """Hammer BufferCatalog acquire/remove and the spill path from 8
    threads while a query runs through the same DeviceManager: no
    exceptions, catalog consistent, every hammered buffer cleaned up."""
    import numpy as np
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.memory.store import INPUT_BATCH_PRIORITY

    DeviceManager.shutdown()
    sess = make_session({
        # small device budget so adds force spills down the chain
        "spark.rapids.tpu.memory.tpu.poolSizeBytes": str(256 << 10),
        "spark.rapids.tpu.memory.host.spillStorageSize": str(256 << 10)})
    dm = DeviceManager.initialize(sess.conf)
    ids_before = set(dm.catalog.ids())
    tab = pa.table({"x": np.arange(512, dtype="int64")})
    errors = []
    table_ids = [(1 << 27) + i for i in range(8)]

    def hammer(tid):
        try:
            rng = np.random.default_rng(tid)
            mine = []
            for i in range(12):
                bid = BufferId(tid, i)
                batch = DeviceBatch.from_arrow(tab, 16)
                dm.device_store.add_batch(bid, batch,
                                          INPUT_BATCH_PRIORITY)
                mine.append(bid)
                # interleave acquire/release/remove with other threads'
                # adds so spill + catalog paths race for real
                probe = mine[int(rng.integers(0, len(mine)))]
                buf = dm.catalog.acquire(probe)
                if buf is not None:
                    buf.close()
                if rng.random() < 0.3 and len(mine) > 1:
                    dm.catalog.remove(mine.pop(0))
            for bid in mine:
                dm.catalog.remove(bid)
        except Exception as e:          # noqa: BLE001 - asserted below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(tid,))
               for tid in table_ids]
    for t in threads:
        t.start()
    # a real query runs through the same manager meanwhile
    df = (sess.create_dataframe(small_table(256))
          .repartition(4, F.col("k")).groupBy("k")
          .agg(F.sum("v").alias("s")))
    h = sess.submit(df)
    out = h.result(timeout=180)
    for t in threads:
        t.join(60)
    assert not errors, errors
    assert out.num_rows > 0
    assert set(dm.catalog.ids()) == ids_before
    assert dm.semaphore.active_holders == 0
    DeviceManager.shutdown()


def test_out_of_core_spill_under_concurrency():
    """PR 11 extension of the 8-thread hammer: grace-PARTITIONED operators
    spill through the tiered store while other queries run and hammer
    threads churn the catalog — no exceptions, no buffer leaks (every
    grace partition/spill copy released), and results identical to the
    ample-budget single-pass run."""
    import numpy as np
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.memory.store import INPUT_BATCH_PRIORITY
    from spark_rapids_tpu.testing import assert_tables_equal

    rng = np.random.default_rng(0)
    big = pa.table({"k": rng.integers(0, 32, 40000).astype("int64"),
                    "v": rng.integers(0, 1000, 40000).astype("int64")})

    def q(s):
        return (s.create_dataframe(big).groupBy("k")
                .agg(F.sum("v").alias("s"), F.count(F.lit(1)).alias("c")))

    DeviceManager.shutdown()
    expected = q(make_session()).collect()
    DeviceManager.shutdown()
    sess = make_session({
        # tiny budget: the aggregate grace-partitions and its partitions
        # spill device -> host -> disk while everything else runs
        "spark.rapids.tpu.memory.tpu.poolSizeBytes": str(256 << 10),
        "spark.rapids.tpu.memory.host.spillStorageSize": str(256 << 10),
        "spark.rapids.tpu.sql.scanCache.enabled": "false"})
    dm = DeviceManager.initialize(sess.conf)
    ids_before = set(dm.catalog.ids())
    tab = pa.table({"x": np.arange(512, dtype="int64")})
    errors = []

    def hammer(tid):
        try:
            prng = np.random.default_rng(tid)
            mine = []
            for i in range(10):
                bid = BufferId(tid, i)
                dm.device_store.add_batch(bid, DeviceBatch.from_arrow(tab, 16),
                                          INPUT_BATCH_PRIORITY)
                mine.append(bid)
                probe = mine[int(prng.integers(0, len(mine)))]
                buf = dm.catalog.acquire(probe)
                if buf is not None:
                    buf.close()
                if prng.random() < 0.3 and len(mine) > 1:
                    dm.catalog.remove(mine.pop(0))
            for bid in mine:
                dm.catalog.remove(bid)
        except Exception as e:          # noqa: BLE001 - asserted below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=((1 << 27) + t,))
               for t in range(8)]
    for t in threads:
        t.start()
    handles = [sess.submit(q(sess)) for _ in range(3)]
    outs = [h.result(timeout=300) for h in handles]
    for t in threads:
        t.join(60)
    assert not errors, errors
    for h, out in zip(handles, outs):
        assert h.state is QueryState.DONE
        assert_tables_equal(expected, out, ignore_order=True,
                            approx_float=1e-9)
        mm = h.exec_metrics.get("memory", {})
        assert mm.get("memory.spill_partitions", 0) >= 2, mm
    assert set(dm.catalog.ids()) == ids_before, \
        "out-of-core partitions leaked under concurrency"
    assert dm.semaphore.active_holders == 0
    DeviceManager.shutdown()


def test_scheduler_shutdown_cancels_queued():
    sess = make_session({
        "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    started, release = threading.Event(), threading.Event()
    blocker = sess.submit(blocking_udf_df(sess, started, release))
    assert started.wait(60)
    queued = [sess.submit(sess.create_dataframe(small_table(16))
                          .groupBy("k").count()) for _ in range(3)]
    sess.scheduler.shutdown(wait=False)
    release.set()
    blocker.wait(120)
    for h in queued:
        h.wait(120)
        assert h.state is QueryState.CANCELLED
    with pytest.raises(RuntimeError):
        sess.scheduler.submit(sess.create_dataframe(small_table(8)))
