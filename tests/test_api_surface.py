"""pyspark user-surface conveniences: show/head/take/first/printSchema/
describe/sample/toDF/unionByName/intersect/subtract/dropna/fillna — the
day-one APIs a user migrating from the reference's Spark sessions reaches
for (exercised throughout the reference's pytest integration suite)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api.dataframe import Row, TpuSession
from spark_rapids_tpu.api import functions as F


@pytest.fixture()
def sess():
    return TpuSession()


@pytest.fixture()
def df(sess):
    return sess.create_dataframe(pa.table({
        "k": [1, 2, None, 4, 5],
        "v": [10.0, None, 30.0, 40.0, 50.0],
        "s": ["aa", "bb", None, "dd", "a-very-long-string-value"],
    }))


def test_take_head_first(df):
    rows = df.take(2)
    assert len(rows) == 2 and isinstance(rows[0], Row)
    assert rows[0].k == 1 and rows[0]["v"] == 10.0
    assert df.first().s == "aa"
    assert df.head() == rows[0]
    assert df.head(3)[2].k is None
    empty = df.filter(F.col("k") > 100)
    assert empty.head() is None and empty.take(5) == []


def test_show_and_print_schema(df, capsys):
    df.show(3)
    out = capsys.readouterr().out
    assert "|  k|" in out.replace(" ", " ") or "k" in out
    assert "null" in out
    df.show(5, truncate=10)
    out = capsys.readouterr().out
    assert "a-very-..." in out
    df.printSchema()
    out = capsys.readouterr().out
    assert out.startswith("root")
    assert " |-- k: long (nullable = true)" in out


def test_describe(df):
    out = df.describe("k", "v").collect()
    d = {r["summary"]: r for r in out.to_pylist()}
    assert d["count"]["k"] == "4"          # nulls excluded
    assert float(d["mean"]["v"]) == pytest.approx(32.5)
    assert d["min"]["k"] == "1" and d["max"]["k"] == "5"
    assert float(d["stddev"]["v"]) > 0


def test_sample_is_deterministic_and_bounded(sess):
    big = sess.create_dataframe(pa.table({"x": list(range(2000))}))
    a = big.sample(0.25, seed=7).collect()
    b = big.sample(0.25, seed=7).collect()
    assert a.num_rows == b.num_rows
    assert a.column("x").to_pylist() == b.column("x").to_pylist()
    assert 0 < a.num_rows < 2000
    assert abs(a.num_rows / 2000 - 0.25) < 0.1


def test_todf_and_rename(df):
    out = df.toDF("a", "b", "c")
    assert out.columns == ["a", "b", "c"]
    out = df.withColumnsRenamed({"k": "key", "s": "str"})
    assert out.columns == ["key", "v", "str"]
    with pytest.raises(ValueError):
        df.toDF("only-two", "names")


def test_union_by_name(sess):
    a = sess.create_dataframe(pa.table({"x": [1], "y": [2]}))
    b = sess.create_dataframe(pa.table({"y": [20], "x": [10]}))
    out = a.unionByName(b).collect()
    assert out.column("x").to_pylist() == [1, 10]
    assert out.column("y").to_pylist() == [2, 20]
    c = sess.create_dataframe(pa.table({"x": [99]}))
    with pytest.raises(ValueError):
        a.unionByName(c)
    out = a.unionByName(c, allowMissingColumns=True).collect()
    assert out.column("y").to_pylist() == [2, None]


def test_intersect_and_subtract_null_semantics(sess):
    a = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, None], "s": ["x", "x", "y", None]}))
    b = sess.create_dataframe(pa.table({
        "k": [1, None, 3], "s": ["x", None, "z"]}))
    inter = a.intersect(b).collect().to_pylist()
    # distinct + nulls compare equal (SQL INTERSECT)
    assert sorted(inter, key=repr) == sorted(
        [{"k": 1, "s": "x"}, {"k": None, "s": None}], key=repr)
    sub = a.subtract(b).collect().to_pylist()
    assert sub == [{"k": 2, "s": "y"}]


def test_dropna_modes(df):
    assert df.dropna().count() == 3               # rows with ANY null out
    assert df.dropna(how="all").count() == 5      # no all-null rows
    assert df.dropna(subset=["k"]).count() == 4
    assert df.dropna(thresh=3).count() == 3       # all three non-null


def test_fillna_scalar_and_dict(df):
    out = df.fillna(0).collect()
    assert out.column("k").to_pylist() == [1, 2, 0, 4, 5]
    assert out.column("v").to_pylist() == [10.0, 0.0, 30.0, 40.0, 50.0]
    assert out.column("s").to_pylist()[2] is None     # type-incompatible
    out = df.fillna({"s": "??", "v": -1.0}).collect()
    assert out.column("s").to_pylist()[2] == "??"
    assert out.column("v").to_pylist()[1] == -1.0
    assert out.column("k").to_pylist()[2] is None     # not in dict
    out = df.fillna("zz").collect()
    assert out.column("s").to_pylist()[2] == "zz"
    assert out.column("k").to_pylist()[2] is None


def test_conveniences_match_cpu_engine(sess):
    """The new surface lowers to ordinary plans: TPU and CPU engines agree."""
    t = pa.table({"k": [1, None, 3, 3], "v": [1.5, 2.5, None, 4.0]})
    on = TpuSession()
    off = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    for build in (lambda s: s.create_dataframe(t).dropna(),
                  lambda s: s.create_dataframe(t).fillna(9),
                  lambda s: s.create_dataframe(t).intersect(
                      s.create_dataframe(t)),
                  lambda s: s.create_dataframe(t).subtract(
                      s.create_dataframe(
                          pa.table({"k": [3], "v": [4.0]})))):
        a = build(on).collect()
        b = build(off).collect()
        assert sorted(a.to_pylist(), key=repr) == \
            sorted(b.to_pylist(), key=repr)


def test_dropna_fillna_nan_semantics(sess):
    """Code review: pyspark treats NaN as missing in float columns for
    na.drop/na.fill."""
    t = pa.table({"v": pa.array([1.0, float("nan"), None])})
    df = sess.create_dataframe(t)
    assert df.dropna().count() == 1
    out = df.fillna(0).collect().column("v").to_pylist()
    assert out == [1.0, 0.0, 0.0]


def test_sample_pyspark_call_forms(sess):
    big = sess.create_dataframe(pa.table({"x": list(range(500))}))
    a = big.sample(0.3, 5).collect()
    b = big.sample(False, 0.3, 5).collect()
    assert a.column("x").to_pylist() == b.column("x").to_pylist()
    with pytest.raises(NotImplementedError):
        big.sample(True, 0.3)
    with pytest.raises(TypeError):
        big.sample()


def test_except_all_raises(sess):
    a = sess.create_dataframe(pa.table({"x": [1, 1, 2]}))
    b = sess.create_dataframe(pa.table({"x": [1]}))
    with pytest.raises(NotImplementedError):
        a.exceptAll(b)


def test_show_tiny_truncate(df, capsys):
    df.show(truncate=2)
    out = capsys.readouterr().out
    assert "|a-|" in out            # plain cut, no ellipsis below width 4
    assert "..." not in out


def test_pivot_basic(sess):
    t = pa.table({"year": [2020, 2020, 2021, 2021, 2021],
                  "cat": ["a", "b", "a", "a", None],
                  "amt": [1.0, 2.0, 3.0, 4.0, 9.0]})
    df = sess.create_dataframe(t)
    out = (df.groupBy("year").pivot("cat").agg(F.sum("amt"))
           .sort("year").collect())
    # inferred values include the null pivot column (Spark semantics)
    assert out.column_names == ["year", "null", "a", "b"]
    assert out.column("a").to_pylist() == [1.0, 7.0]
    assert out.column("b").to_pylist() == [2.0, None]
    assert out.column("null").to_pylist() == [None, 9.0]
    # explicit values pin column order and include absent values
    out = (df.groupBy("year").pivot("cat", ["b", "a", "zzz"])
           .agg(F.sum("amt")).sort("year").collect())
    assert out.column_names == ["year", "b", "a", "zzz"]
    assert out.column("zzz").to_pylist() == [None, None]


def test_pivot_multiple_aggs_and_count(sess):
    t = pa.table({"k": [1, 1, 2], "p": ["x", "y", "x"],
                  "v": [10, 20, 30]})
    df = sess.create_dataframe(t)
    out = (df.groupBy("k").pivot("p")
           .agg(F.sum("v").alias("s"), F.count("v").alias("c"))
           .sort("k").collect())
    assert out.column_names == ["k", "x_s", "x_c", "y_s", "y_c"]
    assert out.column("x_s").to_pylist() == [10, 30]
    assert out.column("x_c").to_pylist() == [1, 1]
    assert out.column("y_c").to_pylist() == [1, 0]
    with pytest.raises(NotImplementedError):
        df.groupBy("k").pivot("p").agg(F.sum("v") + F.lit(1))


def test_pivot_null_values_and_count_distinct(sess):
    """Code review: null pivot values form a 'null' column; countDistinct
    composes with pivot."""
    t = pa.table({"k": [1, 1, 1, 2], "p": ["x", None, None, "x"],
                  "v": [5, 7, 7, 9]})
    df = sess.create_dataframe(t)
    out = df.groupBy("k").pivot("p").agg(F.sum("v")).sort("k").collect()
    assert out.column_names == ["k", "null", "x"]
    assert out.column("null").to_pylist() == [14, None]
    out = (df.groupBy("k").pivot("p", ["x", None])
           .agg(F.countDistinct("v")).sort("k").collect())
    assert out.column_names == ["k", "x", "null"]
    assert out.column("x").to_pylist() == [1, 1]
    assert out.column("null").to_pylist() == [1, 0]


def test_dropna_validates_how_and_fillna_keeps_int_type(sess):
    df = sess.create_dataframe(pa.table({"k": pa.array([1, None, 3],
                                                       type=pa.int64())}))
    with pytest.raises(ValueError):
        df.dropna(how="bogus")
    out = df.fillna(0.9).collect()
    assert out.schema.field("k").type == pa.int64()   # not widened
    assert out.column("k").to_pylist() == [1, 0, 3]   # cast like Spark


def test_new_surface_composes_with_mesh(eight_devices):
    """pivot/set-ops/na functions lower to ordinary plans, so they must
    distribute like any aggregate/union when the mesh is on."""
    mesh_sess = TpuSession({"spark.rapids.tpu.mesh.enabled": "true"})
    plain = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})

    def build(s):
        t = pa.table({"k": [i % 3 for i in range(300)],
                      "p": [["x", "y", "z"][i % 3] for i in range(300)],
                      "v": [float(i) if i % 7 else None
                            for i in range(300)]})
        df = s.create_dataframe(t).fillna(0.5).dropna()
        return df.groupBy("k").pivot("p", ["x", "y"]).agg(F.sum("v"))

    a = sorted(build(mesh_sess).collect().to_pylist(), key=repr)
    b = sorted(build(plain).collect().to_pylist(), key=repr)
    assert a == b


def test_sample_unseeded_draws_fresh_seed(sess):
    """Advisor (round 4): unseeded sample() must not pin rand(0) — two
    unseeded calls should (with overwhelming probability) pick different
    seeds. Asserted on the plan's rand seed, not row luck."""
    big = sess.create_dataframe(pa.table({"x": list(range(100))}))
    seeds = {repr(big.sample(0.5)._plan) for _ in range(8)}
    assert len(seeds) > 1
