"""Hash-ordered grouping fast path: row-hash semantics, collision detection,
boundary-scan reduction, and the filter/project fusion into aggregation."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.ops import batch_kernels as bk
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

col = F.col


def _colv(vals, dtype=DType.LONG, validity=None):
    data = np.asarray(vals)
    v = (np.ones(len(vals), bool) if validity is None
         else np.asarray(validity, bool))
    return ColV(dtype, data, v)


def test_hash_equal_keys_equal_hashes():
    a = _colv([1, 2, 1, 2, 3])
    h = bk.hash64_cols(np, [a])
    assert h[0] == h[2] and h[1] == h[3]
    assert h[0] != h[1] and h[0] != h[4]


def test_hash_grouping_semantics_null_nan_negzero():
    # null == null, NaN == NaN, -0.0 == 0.0 (Spark grouping equality)
    f = ColV(DType.DOUBLE,
             np.array([np.nan, np.nan, -0.0, 0.0, 1.0, 0.0]),
             np.array([True, True, True, True, False, False]))
    h = bk.hash64_cols(np, [f])
    assert h[0] == h[1]          # NaN == NaN
    assert h[2] == h[3]          # -0.0 == 0.0
    assert h[4] == h[5]          # null == null regardless of payload
    assert h[0] != h[2] and h[2] != h[4]


def test_hash_string_width_consistent():
    d1 = np.zeros((2, 8), np.uint8)
    d1[0, :3] = list(b"abc")
    d1[1, :3] = list(b"abc")
    s = ColV(DType.STRING, d1, np.ones(2, bool),
             np.array([3, 3], np.int32))
    h = bk.hash64_cols(np, [s])
    assert h[0] == h[1]


def test_hash_string_no_structured_collisions():
    """Java-hashCode-style pairs ('Aa'/'BB') must not collide: a linear
    base-31 fold would, permanently defeating the fast path."""
    pairs = [(b"Aa", b"BB"), (b"AaAa", b"BBBB"), (b"Aa", b"C#")]
    for l, r in pairs:
        d = np.zeros((2, 8), np.uint8)
        d[0, :len(l)] = list(l)
        d[1, :len(r)] = list(r)
        s = ColV(DType.STRING, d, np.ones(2, bool),
                 np.array([len(l), len(r)], np.int32))
        h = bk.hash64_cols(np, [s])
        assert h[0] != h[1], (l, r)


def test_float_hash_compiles_without_bitcast():
    """The TPU x64 emulation cannot compile f64 bitcasts (signbit included);
    the float hash must stay pure-arithmetic or it only breaks on hardware."""
    import jax
    import jax.numpy as jnp

    def h(data, validity):
        return bk.hash64_cols(jnp, [ColV(DType.DOUBLE, data, validity)])

    jaxpr = str(jax.make_jaxpr(h)(np.array([1.5, -2.0, 0.0]),
                                  np.array([True, True, False])))
    assert "bitcast" not in jaxpr, jaxpr
    # and parity: traced result equals the numpy path
    out = jax.jit(h)(np.array([1.5, -2.0, 0.0]), np.array([True, True, False]))
    ref = bk.hash64_cols(np, [ColV(DType.DOUBLE, np.array([1.5, -2.0, 0.0]),
                                   np.array([True, True, False]))])
    assert np.array_equal(np.asarray(out), ref)


def test_collision_detected_and_order_correct():
    keys = [_colv([5, 7, 5, 7, 9, 5])]
    order, h = bk.hash_group_order(np, keys, 6)
    starts = bk.rows_equal_adjacent(np, keys, order, 6)
    assert not bool(bk.detect_hash_collision(np, h, order, starts, 6))
    assert int(starts.sum()) == 3
    # forge a collision: all hashes equal but keys differ
    forged = np.zeros(6, dtype=np.uint64)
    order2 = np.arange(6)
    starts2 = bk.rows_equal_adjacent(np, keys, order2, 6)
    assert bool(bk.detect_hash_collision(np, forged, order2, starts2, 6))


def test_group_aggregate_hash_matches_sort():
    from spark_rapids_tpu.exprs import Count, Literal, Sum, bind_expression
    from spark_rapids_tpu.exprs.core import EvalCtx, UnresolvedAttribute
    from spark_rapids_tpu.columnar.dtypes import Schema
    from spark_rapids_tpu.columnar.host import HostBatch
    from spark_rapids_tpu.ops.aggregate import group_aggregate

    rng = np.random.default_rng(5)
    t = pa.table({"k": rng.integers(0, 50, 500),
                  "v": rng.integers(-100, 100, 500)})
    schema = Schema.from_pa(t.schema)
    hb = HostBatch.from_arrow(t, 8)
    colvs = [ColV(c.dtype, c.data, c.validity, c.lengths) for c in hb.columns]
    ectx = EvalCtx(np, colvs, 500, 8)
    keys = (bind_expression(UnresolvedAttribute("k"), schema),)
    fns = (Sum(bind_expression(UnresolvedAttribute("v"), schema)),
           Count(Literal.of(1)))

    ks, rs, n_s = group_aggregate(np, ectx, keys, fns, 500, 500)
    kh, rh, n_h, collision = group_aggregate(np, ectx, keys, fns, 500, 500,
                                             grouping="hash")
    assert not bool(collision)
    assert int(n_s) == int(n_h) == 50
    # same groups, different order: compare as key->value maps
    def as_map(kcols, rcols, n):
        return {int(kcols[0].data[i]): (int(rcols[0].data[i]),
                                        int(rcols[1].data[i]))
                for i in range(int(n))}
    assert as_map(ks, rs, n_s) == as_map(kh, rh, n_h)


def test_fused_filter_agg_plan_and_results():
    rng = np.random.default_rng(9)
    t = pa.table({"k": rng.integers(0, 5, 300),
                  "v": rng.integers(0, 100, 300),
                  "w": rng.integers(0, 10, 300)})

    def build(sess):
        return (sess.create_dataframe(t)
                .filter(col("v") < 50)
                .select("k", (col("v") * col("w")).alias("vw"))
                .groupBy("k").agg(F.sum("vw").alias("s"),
                                  F.count().alias("n"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    # golden
    import pandas as pd
    pdf = t.to_pandas()
    pdf = pdf[pdf.v < 50]
    g = (pdf.assign(vw=pdf.v * pdf.w).groupby("k")
         .agg(s=("vw", "sum"), n=("vw", "count")))
    assert cpu.column("s").to_pylist() == g["s"].tolist()
    assert cpu.column("n").to_pylist() == g["n"].tolist()


def test_fusion_removes_filter_exec_from_plan():
    rng = np.random.default_rng(11)
    t = pa.table({"k": rng.integers(0, 5, 100),
                  "v": rng.integers(0, 100, 100)})
    sess = TpuSession({})
    df = (sess.create_dataframe(t).filter(col("v") > 10)
          .groupBy("k").agg(F.count().alias("n")).sort("k"))
    df.collect()
    plan = sess.last_plan.tree_string()
    assert "TpuHashAggregateExec" in plan
    assert "TpuFilterExec" not in plan, plan


def test_fusion_preserves_nondeterministic_project():
    """A project computing rand() must not be inlined twice."""
    rng = np.random.default_rng(13)
    t = pa.table({"k": rng.integers(0, 5, 100)})
    sess = TpuSession({"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"})
    df = (sess.create_dataframe(t)
          .select("k", F.rand(42).alias("r"))
          .groupBy("k").agg(F.min("r").alias("lo"), F.max("r").alias("hi"))
          .sort("k"))
    out = df.collect()
    assert all(lo <= hi for lo, hi in zip(out.column("lo").to_pylist(),
                                          out.column("hi").to_pylist()))


def test_literal_group_key_after_fusion():
    """Project inlining can turn a grouping key into a literal (e.g.
    dropDuplicates over a withColumn(lit(...)) marker); scalar keys must
    broadcast before grouping."""
    t = pa.table({"k": pa.array([1, 2, 1, 3], type=pa.int64())})

    def build(sess):
        return (sess.create_dataframe(t)
                .withColumn("m", F.lit(1))
                .dropDuplicates()
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("k").to_pylist() == [1, 2, 3]
    assert cpu.column("m").to_pylist() == [1, 1, 1]


def test_group_cap_fallback_many_groups():
    """More groups than the scan-reduction bound re-runs the exact path."""
    from spark_rapids_tpu.ops import aggregate as agg_mod
    n = 2000
    t = pa.table({"k": np.arange(n), "v": np.ones(n, np.int64)})

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.sum("v").alias("s")).sort("k"))

    old = agg_mod.GROUP_CAP
    agg_mod.GROUP_CAP = 256
    try:
        cpu = assert_tpu_and_cpu_equal(build)
    finally:
        agg_mod.GROUP_CAP = old
    assert cpu.num_rows == n
    assert cpu.column("s").to_pylist() == [1] * n


# ---------------------------------------------------------------------------
# one-hot (sort-free, scatter-free) low-cardinality fast path
# ---------------------------------------------------------------------------
def _q1ish_inputs(n=400, nulls=True, seed=7):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 6, n)
    v = rng.integers(-100, 100, n).astype(np.float64)
    v[rng.random(n) < 0.1] = np.nan
    mk = pa.array(k, type=pa.int64())
    mv = pa.array(v, type=pa.float64(),
                  mask=(rng.random(n) < 0.15) if nulls else None)
    return pa.table({"k": mk, "v": mv})


def _run_group_aggregate(t, grouping, fns_builder=None):
    from spark_rapids_tpu.exprs import (Average, Count, Literal, Max, Min,
                                        Sum, bind_expression)
    from spark_rapids_tpu.exprs.core import EvalCtx, UnresolvedAttribute
    from spark_rapids_tpu.columnar.dtypes import Schema
    from spark_rapids_tpu.columnar.host import HostBatch
    from spark_rapids_tpu.ops.aggregate import group_aggregate

    schema = Schema.from_pa(t.schema)
    hb = HostBatch.from_arrow(t, 8)
    n = t.num_rows
    colvs = [ColV(c.dtype, c.data, c.validity, c.lengths) for c in hb.columns]
    ectx = EvalCtx(np, colvs, n, 8)
    b = lambda name: bind_expression(UnresolvedAttribute(name), schema)
    keys = (b("k"),)
    fns = (Sum(b("v")), Min(b("v")), Max(b("v")), Average(b("v")),
           Count(Literal.of(1)))
    return group_aggregate(np, ectx, keys, fns, n, n, grouping=grouping)


def _group_map(kcols, rcols, n):
    out = {}
    for i in range(int(n)):
        key = (int(kcols[0].data[i]) if kcols[0].validity[i] else None)
        vals = []
        for r in rcols:
            vals.append(float(r.data[i]) if r.validity[i] else None)
        out[key] = tuple(vals)
    return out


def test_onehot_matches_sort_with_nulls_and_nans():
    t = _q1ish_inputs()
    ks, rs, n_s = _run_group_aggregate(t, "sort")
    ko, ro, n_o, collision = _run_group_aggregate(t, "onehot")
    assert not bool(collision)
    assert int(n_s) == int(n_o)
    ms, mo = _group_map(ks, rs, n_s), _group_map(ko, ro, n_o)
    assert set(ms) == set(mo)
    for k in ms:
        for a, b in zip(ms[k], mo[k]):
            if a is None or b is None:
                assert a is b, (k, ms[k], mo[k])
            elif np.isnan(a) or np.isnan(b):
                assert np.isnan(a) and np.isnan(b), (k, ms[k], mo[k])
            else:
                assert abs(a - b) < 1e-9, (k, ms[k], mo[k])


def test_onehot_overflow_flagged():
    from spark_rapids_tpu.ops.aggregate import ONEHOT_CAP
    n = ONEHOT_CAP * 3
    t = pa.table({"k": np.arange(n), "v": np.ones(n, np.float64)})
    _, _, _, collision = _run_group_aggregate(t, "onehot")
    assert bool(collision)


def test_onehot_jit_matches_numpy():
    import jax
    import jax.numpy as jnp
    from spark_rapids_tpu.exprs import Count, Literal, Sum, bind_expression
    from spark_rapids_tpu.exprs.core import EvalCtx, UnresolvedAttribute
    from spark_rapids_tpu.columnar.dtypes import Schema
    from spark_rapids_tpu.columnar.host import HostBatch
    from spark_rapids_tpu.ops.aggregate import group_aggregate

    t = _q1ish_inputs(n=257)
    schema = Schema.from_pa(t.schema)
    hb = HostBatch.from_arrow(t, 8)
    n = t.num_rows
    b = lambda name: bind_expression(UnresolvedAttribute(name), schema)
    keys = (b("k"),)
    fns = (Sum(b("v")), Count(Literal.of(1)))

    flat = []
    for c in hb.columns:
        flat.append(c.data)
        flat.append(c.validity)

    def prog(*flat):
        colvs = [ColV(c.dtype, flat[2 * i], flat[2 * i + 1])
                 for i, c in enumerate(hb.columns)]
        ectx = EvalCtx(jnp, colvs, n, 8)
        ks, rs, ng, coll = group_aggregate(jnp, ectx, keys, fns, n, n,
                                           grouping="onehot")
        return ([k.data for k in ks] + [k.validity for k in ks]
                + [r.data for r in rs] + [r.validity for r in rs]
                + [ng, coll])

    jout = [np.asarray(a) for a in jax.jit(prog)(*flat)]
    colvs = [ColV(c.dtype, c.data, c.validity) for c in hb.columns]
    ectx = EvalCtx(np, colvs, n, 8)
    ks, rs, ng, coll = group_aggregate(np, ectx, keys, fns, n, n,
                                       grouping="onehot")
    assert not bool(coll) and not bool(jout[-1])
    assert int(ng) == int(jout[-2])
    m_np = _group_map(ks, rs, ng)
    kj = [ColV(DType.LONG, jout[0], jout[1])]
    rj = [ColV(DType.DOUBLE, jout[2], jout[4]),
          ColV(DType.LONG, jout[3], jout[5])]
    m_j = _group_map(kj, rj, int(jout[-2]))
    assert set(m_np) == set(m_j)
    for k in m_np:
        for a, b in zip(m_np[k], m_j[k]):
            if a is None or b is None:
                assert a is b, (k, m_np[k], m_j[k])
            elif np.isnan(a) or np.isnan(b):
                assert np.isnan(a) and np.isnan(b), (k, m_np[k], m_j[k])
            else:
                assert abs(a - b) < 1e-9, (k, m_np[k], m_j[k])


def test_key_words_null_vs_zero_and_float_canon():
    ints = ColV(DType.LONG, np.array([0, 0, 5]),
                np.array([True, False, True]))
    w = bk.key_words(np, ints)[0]
    vw = bk.validity_word(np, [ints])
    # data words canonicalize nulls to 0 — only the validity word separates
    # null from a genuine zero
    assert w[0] == w[1] and vw[0] != vw[1]

    f = ColV(DType.DOUBLE, np.array([-0.0, 0.0, np.nan, np.nan, 1.5, 2.5]),
             np.ones(6, bool))
    w0, w1 = bk.key_words(np, f)
    assert w0[0] == w0[1] and w1[0] == w1[1]      # -0.0 == 0.0
    assert w0[2] == w0[3] and w1[2] == w1[3]      # NaN == NaN
    assert (w0[4], w1[4]) != (w0[5], w1[5])       # distinct finites differ
    # injectivity across close values
    g = ColV(DType.DOUBLE, np.array([1.0, np.nextafter(1.0, 2.0)]),
             np.ones(2, bool))
    gw0, gw1 = bk.key_words(np, g)
    assert (gw0[0], gw1[0]) != (gw0[1], gw1[1])


def test_min_max_string_still_uses_hash_path():
    """String min/max is outside the one-hot path; the engine must fall back
    and stay correct."""
    t = pa.table({"k": pa.array([1, 1, 2, 2, 2]),
                  "s": pa.array(["b", "a", "z", "m", "q"])})

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.min("s").alias("lo"), F.max("s").alias("hi"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("lo").to_pylist() == ["a", "m"]
    assert cpu.column("hi").to_pylist() == ["b", "z"]
