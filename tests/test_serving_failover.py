"""Serving-fleet resilience: health-checked failover routing, stream-resume
retry, graceful drain.

Covers the fleet-resilience contracts (docs/serving.md "Failure model"):
- circuit breaker: consecutive failures -> OPEN (zero submissions) ->
  backed-off probes -> HALF_OPEN trial -> success closes;
- liveness: replicas heartbeat their registry-file mtime; discovery scans
  skip AND garbage-collect entries whose heartbeat stopped (a SIGKILL'd
  replica cannot retract its own file);
- failover with stream resume: a seeded mid-stream kill_peer on replica A
  resubmits the query to replica B with resume_from=<last seq delivered>;
  B re-runs and skips already-delivered frames (dedup by seq) — the
  assembled result is bit-identical with ZERO client-visible error, and
  serving.failovers / serving.resumed_batches attribute the event;
- graceful drain: serve.drain flips a replica to DRAINING — running
  queries finish, streams flush, new submissions reroute transparently;
- load-aware routing: the whale lands on the replica with free budget;
  an OPEN breaker receives zero submissions until its probe succeeds;
- deferred registration: a replica that was down (or undiscovered) at
  register_table time gets the missing views replayed on first route.
"""
import os
import socket
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.serving.client import (QueryServiceClient,
                                             RemoteQueryHandle,
                                             WireQueryError)
from spark_rapids_tpu.serving.health import (BREAKER_CLOSED, BREAKER_OPEN,
                                             CircuitBreaker, routing_score)
from spark_rapids_tpu.serving.server import QueryServer
from spark_rapids_tpu.shuffle.faults import FaultPlan
from spark_rapids_tpu.shuffle.tcp import scan_registry
from spark_rapids_tpu.utils import metrics as um

BASE_CONF = {
    "spark.rapids.tpu.sql.string.maxBytes": "16",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}

FILTER_SQL = "SELECT k, v FROM t WHERE v > 0.5"
AGG_SQL = "SELECT k, sum(v) AS s FROM t GROUP BY k ORDER BY k"


def make_table(n=20000, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 8, n).astype("int64"),
                     "v": rng.random(n)})


def serve(extra_conf=None, partitions=3, n=20000):
    """One in-process server over a session with view ``t`` registered."""
    sess = TpuSession({**BASE_CONF, **(extra_conf or {})})
    df = sess.create_dataframe(make_table(n))
    if partitions > 1:
        df = df.repartition(partitions)
    df.createOrReplaceTempView("t")
    server = QueryServer(sess)
    host, port = server.address
    return sess, server, f"{host}:{port}"


def _drain_schedulers(*sessions, timeout=60):
    for s in sessions:
        s.scheduler.drain(timeout=timeout)


def _zero_leak_check():
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    dm = DeviceManager.peek()
    if dm is None:
        return
    deadline = time.time() + 30
    while dm.semaphore.active_holders > 0 and time.time() < deadline:
        time.sleep(0.05)
    assert dm.semaphore.active_holders == 0
    assert dm.semaphore.waiting == 0


def _dead_address():
    """host:port nothing listens on (bound then released)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    host, port = s.getsockname()
    s.close()
    return f"{host}:{port}"


FAST_DIAL = {
    # a dead replica must cost milliseconds, not the default backoff walk
    "spark.rapids.tpu.shuffle.maxRetries": "0",
    "spark.rapids.tpu.shuffle.connectTimeout": "2",
}


# ------------------------------------------------------- circuit breaker
def test_circuit_breaker_threshold_open_probe_halfopen_close():
    before = um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value
    br = CircuitBreaker(threshold=2, backoff_ms=30.0, seed=7, key="r1")
    assert br.allow_submit()
    br.record_failure()
    assert br.allow_submit(), "below threshold must stay CLOSED"
    br.record_failure()
    assert br.state == BREAKER_OPEN
    assert not br.allow_submit()
    assert um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value - before == 1
    # no probe before the backoff elapses
    assert not br.probe_due(time.monotonic())
    deadline = time.time() + 5
    while not br.probe_due():
        assert time.time() < deadline, "backoff never elapsed"
        time.sleep(0.01)
    # HALF_OPEN trial: a failed probe re-opens with a DEEPER backoff and
    # does NOT re-count in breaker_opens (only CLOSED->OPEN transitions)
    br.record_failure()
    assert br.state == BREAKER_OPEN
    assert um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value - before == 1
    while not br.probe_due():
        assert time.time() < deadline
        time.sleep(0.01)
    br.record_success()
    assert br.state == BREAKER_CLOSED and br.allow_submit()
    # a success resets the consecutive-failure count
    br.record_failure()
    assert br.allow_submit()


def test_breaker_backoff_schedule_is_deterministic():
    a = CircuitBreaker(threshold=1, backoff_ms=100.0, seed=3, key="x")
    b = CircuitBreaker(threshold=1, backoff_ms=100.0, seed=3, key="x")
    a.record_failure()
    b.record_failure()
    assert abs(a._probe_at - b._probe_at) < 0.05


def test_breaker_half_open_admits_one_trial_at_a_time():
    """Review regression: while HALF_OPEN, only ONE probe trial owns the
    slot — concurrent submissions must not pile probes onto a dead
    replica (the claim re-offers only after the trial-timeout guard)."""
    br = CircuitBreaker(threshold=1, backoff_ms=1.0, trial_timeout_s=30.0)
    br.record_failure()                 # OPEN
    deadline = time.time() + 5
    while not br.probe_due():
        assert time.time() < deadline
        time.sleep(0.005)
    # the trial is claimed: every further caller is refused
    assert not br.probe_due()
    assert not br.probe_due()
    br.record_failure()                 # trial reported: OPEN again
    # a crashed trial must not wedge the breaker: an expired claim
    # re-offers the slot
    br2 = CircuitBreaker(threshold=1, backoff_ms=1.0, trial_timeout_s=0.01)
    br2.record_failure()
    while not br2.probe_due():
        assert time.time() < deadline
        time.sleep(0.005)
    time.sleep(0.03)                    # the claim expires unreported
    assert br2.probe_due()


def test_routing_score_prefers_free_budget():
    free = {"now": {"device_budget_bytes": 100, "device_budget_in_use": 0,
                    "admission_queue_depth": 0, "running_by_tenant": {}},
            "p99_wall_s": 0.0}
    busy = {"now": {"device_budget_bytes": 100, "device_budget_in_use": 90,
                    "admission_queue_depth": 2,
                    "running_by_tenant": {"etl": 1}},
            "p99_wall_s": 4.0}
    assert routing_score(free) > routing_score(None) > routing_score(busy)


# ------------------------------------------------------ kill_peer faults
def test_kill_peer_spec_parses_and_fires_deterministically():
    plan = FaultPlan.parse("kill_peer:req_type=data,after=2", seed=7)
    assert not plan.on_kill_frame("peer-1")
    assert plan.on_kill_frame("peer-1")
    assert ("kill_peer", "peer-1", 2) in plan.fired
    # request-phase targeting: only the filtered req_type counts
    plan2 = FaultPlan.parse("kill_peer:req_type=serve.submit,after=1")
    assert not plan2.on_kill_request("p", "serve.next")
    assert plan2.on_kill_request("p", "serve.submit")


# --------------------------------------------------- registry / liveness
def test_registry_scan_skips_and_gcs_stale_entries(tmp_path):
    reg = str(tmp_path)
    fresh, stale = os.path.join(reg, "query-server-aa"), \
        os.path.join(reg, "query-server-bb")
    for path, addr in ((fresh, "127.0.0.1:1111"), (stale, "127.0.0.1:2222")):
        with open(path, "w") as f:
            f.write(addr)
    with open(os.path.join(reg, "query-server-cc.tmp"), "w") as f:
        f.write("127.0.0.1:3333")        # half-written publication
    old = time.time() - 120
    os.utime(stale, (old, old))          # SIGKILL'd replica: no heartbeat
    live = scan_registry(reg, stale_after_s=5.0)
    assert live == {"query-server-aa": "127.0.0.1:1111"}
    assert not os.path.exists(stale), "stale entry must be GC'd"
    assert os.path.exists(fresh)
    # without a window nothing is GC'd (the shuffle layer's plain scan)
    assert "query-server-aa" in scan_registry(reg)


def test_registry_scan_distinguishes_missing_dir_from_unreadable(tmp_path):
    """Review regression: a registry dir that does not exist YET is an
    empty fleet ({}), but a transient scan failure must RAISE — reading
    it as 'every replica died' would eject a healthy fleet."""
    assert scan_registry(str(tmp_path / "not-yet")) == {}
    not_a_dir = tmp_path / "file"
    not_a_dir.write_text("127.0.0.1:1")
    with pytest.raises(OSError):
        scan_registry(str(not_a_dir))


def test_refresh_keeps_previous_view_when_registry_unreadable(tmp_path):
    """The client keeps its replica table through a transient registry
    failure instead of dropping every discovered replica."""
    reg = tmp_path / "reg"
    reg.mkdir()
    (reg / "query-server-aa").write_text("127.0.0.1:12345")
    client = QueryServiceClient(
        registry_dir=str(reg),
        conf=TpuConf({**BASE_CONF,
                      "spark.rapids.tpu.serving.health."
                      "probeIntervalSeconds": "0"}))
    try:
        assert {s.addr for s in client.replica_states()} \
            == {"127.0.0.1:12345"}
        # the dir becomes unreadable (simulated: swap it for a file)
        client.registry_dir = str(reg / "query-server-aa")
        client._refresh_replicas(force=True)
        assert {s.addr for s in client.replica_states()} \
            == {"127.0.0.1:12345"}, "transient failure ejected the fleet"
    finally:
        client.close()


def test_heartbeat_republishes_entry_gced_during_a_stall(tmp_path):
    """Review regression: a live replica whose entry was GC'd while it
    stalled past the liveness window must re-enter discovery on its next
    heartbeat, not stay ejected forever."""
    from spark_rapids_tpu.shuffle.tcp import TcpTransport
    reg = str(tmp_path / "reg")
    conf = TpuConf({"spark.rapids.tpu.shuffle.tcp.registryDir": reg})
    t = TcpTransport("exec-stalled", conf)
    try:
        path = os.path.join(reg, "exec-stalled")
        os.unlink(path)                 # a scanner GC'd us mid-stall
        t.heartbeat()                   # resume: must republish
        assert os.path.exists(path)
        host, port = t.address
        assert scan_registry(reg)["exec-stalled"] == f"{host}:{port}"
    finally:
        t.shutdown()


def test_replica_discovery_and_heartbeat_through_registry(tmp_path):
    reg = str(tmp_path / "serving-registry")
    conf = {"spark.rapids.tpu.serving.net.registryDir": reg,
            "spark.rapids.tpu.serving.health.heartbeatSeconds": "0.1"}
    sess_a, server_a, addr_a = serve(conf)
    sess_b, server_b, addr_b = serve(conf)
    client = QueryServiceClient(
        conf=TpuConf({**BASE_CONF,
                      "spark.rapids.tpu.serving.net.registryDir": reg,
                      "spark.rapids.tpu.serving.health."
                      "probeIntervalSeconds": "0"}))
    try:
        assert {s.addr for s in client.replica_states()} == {addr_a, addr_b}
        got = client.submit(AGG_SQL).result()
        assert got.equals(sess_a.sql(AGG_SQL).collect())
        # the heartbeat refreshes the registry mtime while the replica
        # lives, so a liveness-windowed scan keeps both entries
        time.sleep(0.3)
        assert len(scan_registry(reg, stale_after_s=5.0)) == 2
        # a KILLED replica stops heartbeating: its (lingering) entry ages
        # out of the window and discovery drops it from the rotation
        server_b.transport.kill()
        deadline = time.time() + 10
        while len(scan_registry(reg, stale_after_s=0.3)) > 1:
            assert time.time() < deadline, "killed replica never aged out"
            time.sleep(0.1)
        client._refresh_replicas(force=True)
        client.liveness_window = 0.3
        client._refresh_replicas(force=True)
        assert {s.addr for s in client.replica_states()} == {addr_a}
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()
        _drain_schedulers(sess_a, sess_b)


# ------------------------------------------- failover with stream resume
def test_failover_mid_stream_kill_bit_identical_with_resume():
    """The chaos bar: 2 replicas, a seeded kill_peer mid-stream on A; the
    submitted query completes through failover with results bit-identical
    to the single-replica collect, zero client-visible error, zero leaks
    on the survivor, and serving.failovers / serving.resumed_batches
    attribute the event."""
    sess_a, server_a, addr_a = serve(
        {"spark.rapids.tpu.serving.net.faults.plan":
             "kill_peer:req_type=data,after=2",
         "spark.rapids.tpu.serving.net.faults.seed": "7"}, partitions=5)
    sess_b, server_b, addr_b = serve(partitions=5)
    ref = sess_b.sql(FILTER_SQL).collect()
    client = QueryServiceClient([addr_a, addr_b],
                                TpuConf({**BASE_CONF, **FAST_DIAL}))
    f0 = um.SERVING_METRICS[um.SERVING_FAILOVERS].value
    r0 = um.SERVING_METRICS[um.SERVING_RESUMED_BATCHES].value
    try:
        h = client.submit(FILTER_SQL, replica=0)    # starts on A
        got = h.result()                            # A dies on frame 2
        assert got.equals(ref), "failover result diverged"
        assert h.failovers == 1
        assert h.replica == addr_b
        assert h.batches_delivered == 5
        assert um.SERVING_METRICS[um.SERVING_FAILOVERS].value - f0 == 1
        # B re-ran the query and SKIPPED the frame the client already
        # held (seq 0 was delivered before the kill; dedup by seq)
        assert um.SERVING_METRICS[
            um.SERVING_RESUMED_BATCHES].value - r0 >= 1
        fired = [f for f in server_a.transport.plan.fired
                 if f[0] == "kill_peer"]
        assert fired, "the seeded kill never fired"
        # zero leaks on the survivor: its query table drained at DONE
        deadline = time.time() + 10
        while server_b._queries and time.time() < deadline:
            time.sleep(0.05)
        assert not server_b._queries
        _drain_schedulers(sess_a, sess_b)
        _zero_leak_check()
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()


def test_failover_disabled_for_non_idempotent_submission():
    sess_a, server_a, addr_a = serve(
        {"spark.rapids.tpu.serving.net.faults.plan":
             "kill_peer:req_type=data,after=2",
         "spark.rapids.tpu.serving.net.faults.seed": "7"}, partitions=5)
    sess_b, server_b, addr_b = serve(partitions=5)
    client = QueryServiceClient([addr_a, addr_b],
                                TpuConf({**BASE_CONF, **FAST_DIAL}))
    try:
        h = client.submit(FILTER_SQL, replica=0, idempotent=False)
        with pytest.raises(WireQueryError) as ei:
            h.result()
        assert ei.value.batches_delivered == 1
        assert h.failovers == 0
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()
        _drain_schedulers(sess_a, sess_b)


def test_resume_from_skips_already_delivered_frames():
    """Dedup-by-seq unit: a submission carrying resume_from=N receives
    ONLY frames with seq > N, and they are byte-identical to the tail of
    a full-stream run."""
    sess, server, addr = serve(partitions=4)
    client = QueryServiceClient([addr], sess.conf)
    r0 = um.SERVING_METRICS[um.SERVING_RESUMED_BATCHES].value
    try:
        full = client.submit(FILTER_SQL)
        batches = list(full._drive(retain=False))
        assert len(batches) == 4
        addr2, conn, qid = client._submit_routed(
            FILTER_SQL, "default", 0.0, "", resume_from=1)
        h = RemoteQueryHandle(client, addr2, conn, qid, "", sql=FILTER_SQL)
        tail = list(h._drive(retain=False))
        assert len(tail) == 2, "frames 0 and 1 must be skipped"
        assert pa.concat_tables(tail).equals(pa.concat_tables(batches[2:]))
        assert um.SERVING_METRICS[
            um.SERVING_RESUMED_BATCHES].value - r0 == 2
    finally:
        client.close()
        server.shutdown()
        _drain_schedulers(sess)


# -------------------------------------------------------- graceful drain
def test_graceful_drain_finishes_running_and_reroutes_new():
    """Drain-under-load: the running query on the draining replica
    finishes and its stream flushes; every new submission reroutes to the
    healthy replica with NO caller-visible error; the drained replica
    reports DRAINING and reaches the drained (exit-ready) state."""
    sess_a, server_a, addr_a = serve(
        {"spark.rapids.tpu.serving.net.streamQueueDepth": "1"},
        partitions=6)
    sess_b, server_b, addr_b = serve(partitions=6)
    ref = sess_b.sql(FILTER_SQL).collect()
    client = QueryServiceClient(
        [addr_a, addr_b],
        TpuConf({**BASE_CONF,
                 "spark.rapids.tpu.serving.health.probeIntervalSeconds":
                     "0"}))
    d0 = um.SERVING_METRICS[um.SERVING_DRAINS].value
    try:
        h1 = client.submit(FILTER_SQL, replica=0)   # running on A
        it = h1.batches()
        first = next(it)                            # mid-stream
        ack = client.drain_replica(0)
        assert ack["state"] == "DRAINING"
        assert um.SERVING_METRICS[um.SERVING_DRAINS].value - d0 == 1
        assert server_a.draining
        # a second drain is idempotent
        client.drain_replica(0)
        assert um.SERVING_METRICS[um.SERVING_DRAINS].value - d0 == 1
        # serve_stats reports the state (what routers read)
        health = client.health(replica=0)
        assert health["state"] == "DRAINING"
        assert health["serve_stats"]["now"]["state"] == "DRAINING"
        # new submissions reroute transparently — zero caller-visible
        # errors while A is draining
        for _ in range(3):
            nh = client.submit(FILTER_SQL)
            assert nh.replica == addr_b
            assert nh.result().equals(ref)
        assert server_b.session.scheduler.stats()["submitted"] == 3
        assert sess_a.scheduler.stats()["submitted"] == 1
        # in-process submits to a draining scheduler are rejected too
        from spark_rapids_tpu.serving import SchedulerDrainingError
        with pytest.raises(SchedulerDrainingError):
            sess_a.submit(sess_a.sql(AGG_SQL))
        # the RUNNING query finishes and its stream flushes
        rest = list(it)
        assert pa.concat_tables([first] + rest).equals(ref)
        deadline = time.time() + 30
        while not server_a.drained() and time.time() < deadline:
            time.sleep(0.05)
        assert server_a.drained(), "drained replica never became exit-ready"
        _drain_schedulers(sess_a, sess_b)
        _zero_leak_check()
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()


# ------------------------------------------------- load-aware routing bar
def test_loadaware_routing_lands_on_free_replica_and_breaker_blocks():
    """Routing bar: with one replica footprint-saturated, new submissions
    land on the free replica; an OPEN breaker receives zero submissions
    until its probe succeeds."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.serving import QueryHandle
    DeviceManager.shutdown()
    budget_conf = {"spark.rapids.tpu.memory.tpu.poolSizeBytes":
                   str(64 << 20)}
    sess_a, server_a, addr_a = serve(budget_conf)
    sess_b, server_b, addr_b = serve(budget_conf)
    client = QueryServiceClient(
        [addr_a, addr_b],
        TpuConf({**BASE_CONF, **FAST_DIAL,
                 "spark.rapids.tpu.serving.health.probeIntervalSeconds": "0",
                 "spark.rapids.tpu.serving.failover."
                 "breakerFailureThreshold": "1",
                 "spark.rapids.tpu.serving.failover.breakerBackoffMs":
                     "100"}))
    b0 = um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value
    try:
        # saturate A's footprint ledger: half the device budget charged
        whale = QueryHandle("whale-ledger")
        server_a.session.scheduler.admission.admit(whale, 32 << 20)
        for _ in range(4):
            h = client.submit(AGG_SQL)
            assert h.replica == addr_b, "whale landed on the full replica"
            assert h.result().num_rows == 8
        assert server_b.session.scheduler.stats()["submitted"] == 4
        assert sess_a.scheduler.stats()["submitted"] == 0
        # per-replica serve_stats show the asymmetry the router used
        assert (client.stats(replica=0)["serve_stats"]["now"]
                ["device_budget_fraction"] > 0.4)
        assert (client.stats(replica=1)["serve_stats"]["now"]
                ["device_budget_fraction"] < 0.1)
        server_a.session.scheduler.admission.release(whale)

        # now KILL A: the first probe failure opens the breaker
        # (threshold 1) and A receives ZERO submissions while OPEN
        server_a.transport.kill()
        for _ in range(4):
            h = client.submit(AGG_SQL)
            assert h.replica == addr_b
            assert h.result().num_rows == 8
        st_a = client._replica_state(addr_a)
        assert st_a.breaker.state == BREAKER_OPEN
        assert um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value - b0 >= 1
        assert sess_a.scheduler.stats()["submitted"] == 0, \
            "an OPEN breaker must receive zero submissions"

        # replica returns on the SAME address: once the breaker's backoff
        # elapses, one health probe succeeds and closes it
        _host, port = server_a.address
        server_a.shutdown()
        sess_a2 = TpuSession(dict(BASE_CONF))
        sess_a2.create_dataframe(make_table()).repartition(3) \
            .createOrReplaceTempView("t")
        server_a2 = QueryServer(sess_a2, listen_port=port)
        try:
            deadline = time.time() + 15
            while st_a.breaker.state != BREAKER_CLOSED:
                assert time.time() < deadline, "breaker never closed"
                time.sleep(0.05)
                client.submit(AGG_SQL).result()     # probes ride routing
            # the recovered replica rejoins the rotation
            for _ in range(4):
                client.submit(AGG_SQL).result()
            assert sess_a2.scheduler.stats()["submitted"] >= 1
        finally:
            server_a2.shutdown()
            _drain_schedulers(sess_a2)
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()
        _drain_schedulers(sess_a, sess_b)
        DeviceManager.shutdown()


# ------------------------------------------------- deferred registration
def test_register_table_tolerates_down_replica():
    """One dead address must not brick registration or the client: the
    broadcast succeeds on the live replica and routing skips the corpse."""
    dead = _dead_address()
    sess_b, server_b, addr_b = serve()
    client = QueryServiceClient(
        [dead, addr_b],
        TpuConf({**BASE_CONF, **FAST_DIAL,
                 "spark.rapids.tpu.serving.health.probeIntervalSeconds":
                     "0"}))
    try:
        extra = pa.table({"x": [1, 2, 3]})
        client.register_table("extra", extra)       # must NOT raise
        got = client.submit("SELECT x FROM extra WHERE x > 1").result()
        assert got.to_pydict() == {"x": [2, 3]}
    finally:
        client.close()
        server_b.shutdown()
        _drain_schedulers(sess_b)


def test_breaker_open_resets_registration_ledger():
    """A replica declared dead (breaker OPEN) may come back as a NEW
    process on the same address: the client must forget what it thinks
    is registered there so the views are replayed, not skipped."""
    dead = _dead_address()
    client = QueryServiceClient(
        [dead],
        TpuConf({**BASE_CONF, **FAST_DIAL,
                 "spark.rapids.tpu.serving.failover."
                 "breakerFailureThreshold": "2"}))
    try:
        st = client._replica_state(dead)
        st.registered.add("extra")          # believed registered
        client._note_replica_failure(st)
        assert "extra" in st.registered     # one failure: still CLOSED
        client._note_replica_failure(st)    # threshold: OPEN
        assert st.breaker.state == BREAKER_OPEN
        assert not st.registered, "dead replica's ledger must reset"
    finally:
        client.close()


def test_probe_detects_restarted_incarnation_and_replays_views():
    """Review regression: a replica restarting behind the same address
    FASTER than the breaker threshold could notice reports a new
    replica_id in serve.health — the client must replay its temp views
    there, not trust the dead incarnation's ledger."""
    sess_a, server_a, addr_a = serve()
    client = QueryServiceClient(
        [addr_a],
        TpuConf({**BASE_CONF, **FAST_DIAL,
                 "spark.rapids.tpu.serving.health.probeIntervalSeconds":
                     "0"}))
    sess_a2 = server_a2 = None
    try:
        extra = pa.table({"x": [1, 2, 3]})
        client.register_table("extra", extra)
        sql = "SELECT x FROM extra WHERE x > 1"
        assert client.submit(sql).result().to_pydict() == {"x": [2, 3]}
        st = client._replica_state(addr_a)
        assert st.incarnation and "extra" in st.registered
        # restart on the SAME port: one observed failure at most (under
        # the default threshold 3 — the breaker never opens)
        _host, port = server_a.address
        server_a.shutdown()
        sess_a2 = TpuSession(dict(BASE_CONF))
        (sess_a2.create_dataframe(make_table()).repartition(3)
         .createOrReplaceTempView("t"))
        server_a2 = QueryServer(sess_a2, listen_port=port)
        deadline = time.time() + 30
        got = None
        while got is None:
            assert time.time() < deadline
            try:
                got = client.submit(sql).result()
            except WireQueryError:
                time.sleep(0.1)         # restart race: dial again
        assert got.to_pydict() == {"x": [2, 3]}, \
            "view was not replayed onto the new incarnation"
        assert st.incarnation == server_a2.transport.executor_id
    finally:
        client.close()
        server_a.shutdown()
        if server_a2 is not None:
            server_a2.shutdown()
            _drain_schedulers(sess_a2)
        _drain_schedulers(sess_a)


def test_register_table_fails_only_when_no_replica_reachable():
    client = QueryServiceClient([_dead_address()],
                                TpuConf({**BASE_CONF, **FAST_DIAL}))
    try:
        with pytest.raises(WireQueryError, match="no replica"):
            client.register_table("v", pa.table({"x": [1]}))
    finally:
        client.close()


def test_deferred_register_replays_on_late_discovered_replica(tmp_path):
    """A replica that joins AFTER the register_table broadcast gets the
    missing views replayed before its first routed submission."""
    reg = str(tmp_path / "reg")
    conf = {"spark.rapids.tpu.serving.net.registryDir": reg,
            "spark.rapids.tpu.serving.health.heartbeatSeconds": "0.1"}
    sess_a, server_a, addr_a = serve(conf)
    client = QueryServiceClient(
        conf=TpuConf({**BASE_CONF,
                      "spark.rapids.tpu.serving.net.registryDir": reg,
                      "spark.rapids.tpu.serving.health."
                      "probeIntervalSeconds": "0"}))
    sess_b = server_b = None
    try:
        extra = pa.table({"x": [1, 2, 3]})
        client.register_table("extra", extra)       # only A exists yet
        sql = "SELECT x FROM extra WHERE x > 1"
        assert client.submit(sql).result().to_pydict() == {"x": [2, 3]}
        sess_b, server_b, addr_b = serve(conf)      # late joiner
        client._refresh_replicas(force=True)
        assert {s.addr for s in client.replica_states()} == {addr_a, addr_b}
        # run the mix until B serves one — its first routed submission
        # must replay the registration, not fail with an unknown view
        deadline = time.time() + 30
        while server_b.session.scheduler.stats()["submitted"] == 0:
            assert time.time() < deadline, "routing never reached B"
            assert client.submit(sql).result().to_pydict() == {"x": [2, 3]}
        st_b = client._replica_state(addr_b)
        assert "extra" in st_b.registered
    finally:
        client.close()
        server_a.shutdown()
        if server_b is not None:
            server_b.shutdown()
            _drain_schedulers(sess_b)
        _drain_schedulers(sess_a)


# ------------------------------------------- serve_stats churn edge cases
def test_serve_stats_empty_window_percentiles_and_draining_state():
    from spark_rapids_tpu.serving.stats import ServeStatsWindow
    from spark_rapids_tpu.utils.metrics import percentile
    assert percentile([], 50.0) == 0.0
    assert percentile([], 99.0) == 0.0
    sess = TpuSession(BASE_CONF)
    win = ServeStatsWindow(window_s=1.0)    # windows clamp to >= 1 s
    win.record_wall(0.5)
    sched = sess.scheduler
    time.sleep(1.1)
    snap = win.snapshot(sched)          # wall aged out of the window
    assert snap["wall_samples"] == 0
    assert snap["p50_wall_s"] == 0.0 and snap["p99_wall_s"] == 0.0
    assert snap["now"]["state"] == "UP"
    # a DRAINING replica still reports a live series with its state
    sched.start_draining()
    snap = win.snapshot(sched)
    assert snap["now"]["state"] == "DRAINING"
    assert snap["series"], "a draining replica must keep sampling"
    sess.scheduler.shutdown(wait=False)


def test_serve_stats_tenant_gauges_after_cancelled_while_queued():
    """A cancelled-while-queued terminal must leave the per-tenant gauges
    sane: nothing queued for the tenant, no phantom running entry, and
    its wall sample still feeds the latency window."""
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.serving.maxConcurrentQueries": "1"})
    # h1 occupies the single worker; h2 waits QUEUED and is cancelled there
    big = sess.create_dataframe(make_table(200000)).repartition(8)
    h1 = sess.submit(big, tenant="etl")
    h2 = sess.submit(big, tenant="adhoc")
    assert h2.cancel()
    h1.result(timeout=300)
    deadline = time.time() + 30
    while not h2.done and time.time() < deadline:
        time.sleep(0.05)
    assert h2.done
    sched = sess.scheduler
    sample = sched.serve_stats.sample(sched)
    assert sample["queued_by_tenant"].get("adhoc", 0) == 0
    assert sample["running_by_tenant"].get("adhoc", 0) == 0
    assert sample["admission_queue_depth"] == 0
    snap = sched.serve_stats.snapshot(sched)
    # both terminals (DONE and CANCELLED) recorded wall samples
    assert snap["wall_samples"] >= 2
    sess.scheduler.shutdown(wait=False)
