"""ICI all-to-all repartition on the 8-device virtual mesh: rows must land on
the device matching their partition id, intact and compacted, for every dtype
incl. strings and nulls."""
import numpy as np
import pyarrow as pa

import jax

from spark_rapids_tpu.columnar.dtypes import Schema
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.parallel.mesh import make_mesh
from spark_rapids_tpu.shuffle.ici import build_ici_repartition


def _shard_inputs(tables, schema, local_cap, smax=64):
    """Per-device arrow tables -> (num_rows [n], pids, flat arrays)."""
    n_dev = len(tables)
    num_rows = np.array([t.num_rows for t in tables], np.int32)
    flats = None
    for d, t in enumerate(tables):
        hb = HostBatch.from_arrow(t.cast(schema.to_pa()), smax)
        cols = []
        for c in hb.columns:
            data = np.zeros((local_cap,) + c.data.shape[1:], c.data.dtype)
            data[:len(c.data)] = c.data
            validity = np.zeros(local_cap, bool)
            validity[:len(c.validity)] = c.validity
            cols.append((data, validity,
                         None if c.lengths is None else
                         np.pad(c.lengths, (0, local_cap - len(c.lengths)))))
        if flats is None:
            flats = [[] for _ in range(sum(2 if l is None else 3
                                           for _, _, l in cols))]
        i = 0
        for data, validity, lengths in cols:
            flats[i].append(data); i += 1
            flats[i].append(validity); i += 1
            if lengths is not None:
                flats[i].append(lengths); i += 1
    return num_rows, [np.concatenate(f) for f in flats]


def test_ici_repartition_roundtrip(eight_devices):
    n_dev, local_cap, smax = 8, 64, 32
    rng = np.random.default_rng(0)
    tables = []
    for d in range(n_dev):
        n = int(rng.integers(10, local_cap + 1))
        tables.append(pa.table({
            "k": pa.array(rng.integers(0, 1000, n), pa.int64()),
            "s": pa.array([None if i % 7 == 0 else f"d{d}r{i}"
                           for i in range(n)], pa.string()),
            "x": pa.array(rng.normal(size=n), pa.float64()),
        }))
    schema = Schema.from_pa(tables[0].schema)

    # partition id = k % n_dev (host-computed here; engine computes via hash expr)
    pids = np.zeros(n_dev * local_cap, np.int32)
    for d, t in enumerate(tables):
        k = np.asarray(t["k"])
        pids[d * local_cap:d * local_cap + len(k)] = k % n_dev

    num_rows, flat = _shard_inputs(tables, schema, local_cap, smax)
    mesh = make_mesh(n_dev)
    fn = build_ici_repartition(mesh, schema, local_cap)
    out = fn(num_rows, pids, *flat)
    out_rows = np.asarray(out[0])
    assert int(out[1]) == 0            # no clamped rows at full chunk capacity
    out_flat = [np.asarray(a) for a in out[2:]]

    # expected: all rows with k % 8 == p end up on device p
    full = pa.concat_tables(tables)
    k_all = np.asarray(full["k"])
    out_cap = n_dev * local_cap
    for p in range(n_dev):
        exp = full.take(np.nonzero(k_all % n_dev == p)[0])
        assert out_rows[p] == exp.num_rows
        sl = slice(p * out_cap, p * out_cap + exp.num_rows)
        got_k = out_flat[0][sl]
        got_k_valid = out_flat[1][sl]
        assert got_k_valid.all()
        assert sorted(got_k.tolist()) == sorted(
            np.asarray(exp["k"]).tolist())
        # strings: reassemble and compare as multisets
        sdata, svalid, slen = out_flat[2][sl], out_flat[3][sl], out_flat[4][sl]
        nkey = lambda x: (x is None, x or "")
        got_s = sorted(
            (None if not v else bytes(row[:l]).decode()
             for row, v, l in zip(sdata, svalid, slen)), key=nkey)
        assert got_s == sorted(exp["s"].to_pylist(), key=nkey)
        # floats exact
        got_x = out_flat[5][sl]
        assert sorted(got_x.tolist()) == sorted(np.asarray(exp["x"]).tolist())


def test_ici_repartition_empty_device(eight_devices):
    """A device with zero input rows still participates in the collective."""
    n_dev, local_cap = 8, 16
    tables = []
    for d in range(n_dev):
        n = 0 if d == 3 else 8
        tables.append(pa.table({"k": pa.array(np.arange(n) + 100 * d,
                                              pa.int64())}))
    schema = Schema.from_pa(tables[0].schema)
    pids = np.zeros(n_dev * local_cap, np.int32)   # everything to device 0
    num_rows, flat = _shard_inputs(tables, schema, local_cap)
    fn = build_ici_repartition(make_mesh(n_dev), schema, local_cap)
    out = fn(num_rows, pids, *flat)
    out_rows = np.asarray(out[0])
    assert int(out[1]) == 0
    assert out_rows[0] == 7 * 8
    assert (out_rows[1:] == 0).all()
    k = np.asarray(out[2])[:7 * 8]
    assert sorted(k.tolist()) == sorted(
        int(v) for d in range(n_dev) if d != 3 for v in np.arange(8) + 100 * d)


def test_ici_repartition_skew_overflow_guard(eight_devices):
    """A caller-shrunk chunk capacity with skewed pids must FLAG the clamped
    rows, and the safe driver must recover every row by re-running with a
    larger chunk (VERDICT: no silent row loss on skew)."""
    from spark_rapids_tpu.shuffle.ici import ici_repartition
    n_dev, local_cap = 8, 32
    tables = [pa.table({"k": pa.array(np.arange(local_cap) + 100 * d,
                                      pa.int64())}) for d in range(n_dev)]
    schema = Schema.from_pa(tables[0].schema)
    # extreme skew: every row goes to device 0, but chunk capacity is 4
    pids = np.zeros(n_dev * local_cap, np.int32)
    num_rows, flat = _shard_inputs(tables, schema, local_cap)
    mesh = make_mesh(n_dev)
    fn = build_ici_repartition(mesh, schema, local_cap, chunk_capacity=4)
    out = fn(num_rows, pids, *flat)
    clamped = int(out[1])
    assert clamped == n_dev * (local_cap - 4), clamped   # flagged, not lost

    # the safe driver retries with larger chunks until nothing is clamped
    out_rows, cols = ici_repartition(mesh, schema, local_cap, num_rows, pids,
                                     flat, chunk_capacity=4)
    out_rows = np.asarray(out_rows)
    assert out_rows[0] == n_dev * local_cap
    assert (out_rows[1:] == 0).all()
    k = np.asarray(cols[0])[:n_dev * local_cap]
    expect = sorted(int(v) for d in range(n_dev)
                    for v in np.arange(local_cap) + 100 * d)
    assert sorted(k.tolist()) == expect
