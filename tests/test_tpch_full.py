"""All 22 TPC-H queries: TPU engine vs CPU engine over the full 8-table
generated dataset (tpch_test.py analog — the reference runs Q1-Q22 "Like"
queries and compares CPU vs GPU collect output)."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpch_data import gen_all
from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.002

# queries whose final sort key can tie (floats aggregated in different orders
# still compare equal, but tied rows may swap) -> unordered compare
_TIES = {2, 3, 5, 9, 10, 11, 16, 18, 21}

# minimum expected result rows at this scale (0 = empty is legitimate for the
# spec's highly selective predicates at tiny scale)
_MIN_ROWS = {1: 4, 2: 1, 3: 1, 4: 5, 5: 1, 6: 1, 7: 4, 8: 1, 9: 10, 10: 1,
             11: 1, 12: 2, 13: 5, 14: 1, 15: 1, 16: 5, 17: 1, 19: 1, 20: 1,
             21: 1, 22: 1}


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=7)


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_matches_cpu(qnum, tables):
    conf = {**BENCH_CONF,
            # Q11/Q15/Q22 cross-join single-row aggregates; keep them on device
            "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
            "spark.rapids.tpu.sql.exec.CartesianProduct": "true"}
    cpu = assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qnum](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=conf,
        ignore_order=qnum in _TIES,
        approx_float=1e-9)
    assert cpu.num_rows >= _MIN_ROWS.get(qnum, 0), (
        f"q{qnum} returned {cpu.num_rows} rows; data generator no longer "
        f"qualifies rows for its predicates")
