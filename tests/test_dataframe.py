"""End-to-end DataFrame tests: CPU engine vs TPU plan-rewritten execution
(SparkQueryCompareTestSuite analog)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def sample_table():
    rng = np.random.default_rng(7)
    n = 500
    cat = rng.choice(["A", "B", "C", None], n, p=[0.4, 0.3, 0.2, 0.1]).tolist()
    qty = [None if rng.random() < 0.1 else int(v)
           for v in rng.integers(0, 100, n)]
    price = [None if rng.random() < 0.1 else float(v)
             for v in rng.uniform(0, 50, n)]
    return pa.table({
        "cat": pa.array(cat, type=pa.string()),
        "qty": pa.array(qty, type=pa.int64()),
        "price": pa.array(price, type=pa.float64()),
    })


def test_project_filter_e2e():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .filter((F.col("qty") > 50) & F.col("cat").isNotNull())
                   .select((F.col("qty") * 2).alias("dq"),
                           F.col("cat"),
                           (F.col("price") / F.col("qty")).alias("unit"))),
        expect_tpu_execs=["TpuProjectExec", "TpuFilterExec"])


def test_groupby_agg_e2e():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .groupBy("cat")
                   .agg(F.count().alias("n"),
                        F.sum("qty").alias("sq"),
                        F.min("qty").alias("mn"),
                        F.max("qty").alias("mx"),
                        F.avg("qty").alias("av"))),
        ignore_order=True,
        expect_tpu_execs=["TpuHashAggregateExec"])


def test_float_agg_gated_by_conf():
    t = sample_table()
    # default: float sum falls back to CPU
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("cat").agg(
            F.sum("price").alias("sp")),
        ignore_order=True)
    # with variableFloatAgg: runs on TPU
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("cat").agg(
            F.sum("price").alias("sp")),
        conf={"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"},
        ignore_order=True, approx_float=1e-12,
        expect_tpu_execs=["TpuHashAggregateExec"])


def test_sort_limit_e2e():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .sort(F.col("qty").desc(), F.col("cat").asc())
                   .limit(37)),
        expect_tpu_execs=["TpuSortExec", "TpuLimitExec"])


def test_union_and_range():
    assert_tpu_and_cpu_equal(
        lambda s: s.range(100).union(s.range(50))
                   .select((F.col("id") % 7).alias("m"))
                   .groupBy("m").count(),
        ignore_order=True,
        expect_tpu_execs=["TpuRangeExec", "TpuUnionExec"])


def test_global_agg_empty_and_nonempty():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).agg(F.count().alias("n"),
                                            F.sum("qty").alias("s")))
    empty = t.slice(0, 0)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(empty).agg(F.count().alias("n"),
                                                F.sum("qty").alias("s")))


def test_conditional_and_strings_e2e():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .select(F.when(F.col("qty") > 50, "big")
                            .when(F.col("qty") > 20, "mid")
                            .otherwise("small").alias("size"),
                           F.upper(F.col("cat")).alias("ucat"),
                           F.col("cat").like("%A%").alias("hasA"))),
        conf={"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"},
        expect_tpu_execs=["TpuProjectExec"])


def test_fallback_unsupported_expr():
    """General LIKE patterns now run on the device DFA engine; a NON-literal
    pattern still has no TPU kernel -> whole project falls back, results
    equal, explain names the reason (StringFallbackSuite analog)."""
    t = sample_table()

    def on_device(s):
        return s.create_dataframe(t).select(
            F.col("cat").like("%A_B%").alias("m"))

    from spark_rapids_tpu.testing import (assert_tables_equal,
                                          run_with_cpu_and_tpu)
    cpu, tpu, sess = run_with_cpu_and_tpu(
        on_device, conf={"spark.rapids.tpu.sql.incompatibleOps.enabled":
                         "true"})
    assert_tables_equal(cpu, tpu)
    assert "TpuProjectExec" in sess.last_plan.tree_string()

    # without the incompat opt-in, the byte-level engine is not used
    cpu, tpu, sess = run_with_cpu_and_tpu(on_device)
    assert_tables_equal(cpu, tpu)
    assert "byte-level" in sess.last_explain

    def falls_back(s):
        # {n} quantifiers are outside the device regex subset -> CPU fallback
        return s.create_dataframe(t).select(
            F.col("cat").rlike("A{2}").alias("m"))

    cpu, tpu, sess = run_with_cpu_and_tpu(
        falls_back, conf={"spark.rapids.tpu.sql.incompatibleOps.enabled":
                          "true"})
    assert_tables_equal(cpu, tpu)
    assert "TpuProjectExec" not in sess.last_plan.tree_string()
    assert "not supported by the device regex engine" in sess.last_explain


def test_explain_output():
    t = sample_table()
    s = TpuSession()
    df = s.create_dataframe(t).filter(F.col("qty") > 5)
    text = df.explain(print_out=False)
    assert "will run on TPU" in text
    assert "TpuFilterExec" in text


def test_count_action():
    t = sample_table()
    s = TpuSession()
    assert s.create_dataframe(t).count() == t.num_rows


def test_with_column_and_drop():
    t = sample_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(t)
                   .withColumn("double_qty", F.col("qty") * 2)
                   .drop("price")))


def test_count_column_ignores_nulls():
    # regression (code review): F.count(col) must count non-null only
    t = pa.table({"x": pa.array([1, None, 3], type=pa.int64())})
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).agg(F.count(F.col("x")).alias("n"),
                                            F.count().alias("all")))
    assert cpu.column("n").to_pylist() == [2]
    assert cpu.column("all").to_pylist() == [3]


def test_string_min_max_agg():
    # regression (code review): string min/max works on BOTH engines
    t = pa.table({"k": pa.array([1, 1, 2, 2, 2], type=pa.int32()),
                  "s": pa.array(["pear", "apple", None, "fig", "banana"])})
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("k").agg(
            F.min("s").alias("mn"), F.max("s").alias("mx")),
        ignore_order=True, expect_tpu_execs=["TpuHashAggregateExec"])
    d = dict(zip(cpu.column("k").to_pylist(),
                 zip(cpu.column("mn").to_pylist(), cpu.column("mx").to_pylist())))
    assert d == {1: ("apple", "pear"), 2: ("banana", "fig")}


def test_with_column_preserves_position():
    t = pa.table({"a": [1], "b": [2], "c": [3]})
    s = TpuSession()
    df = s.create_dataframe(t).withColumn("b", F.col("b") * 10)
    assert df.columns == ["a", "b", "c"]
    assert df.collect().to_pydict() == {"a": [1], "b": [20], "c": [3]}
