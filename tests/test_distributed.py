"""Distributed SPMD aggregation over the 8-device virtual mesh: partial agg per
shard -> all-gather over the mesh axis -> replicated final merge. Results must
match a single-device CPU aggregation exactly."""
import numpy as np
import pyarrow as pa

import jax

from spark_rapids_tpu.columnar.dtypes import Schema
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.exprs import (Alias, Average, Count, Literal, Max, Min, Sum,
                                    UnresolvedAttribute, bind_expression)
from spark_rapids_tpu.exprs.core import ColV, EvalCtx
from spark_rapids_tpu.ops.aggregate import group_aggregate
from spark_rapids_tpu.parallel.distributed import build_distributed_aggregate
from spark_rapids_tpu.parallel.mesh import make_mesh


def test_distributed_agg_matches_local(eight_devices):
    n_dev = 8
    local_cap = 128
    total = n_dev * local_cap
    rng = np.random.default_rng(3)

    keys = rng.integers(0, 10, total).astype(np.int64)
    vals = rng.integers(0, 100, total).astype(np.int64)
    val_valid = rng.random(total) < 0.9
    rows_per_shard = rng.integers(50, local_cap + 1, n_dev).astype(np.int32)

    # zero out dead rows per shard (padding invariants)
    key_valid = np.ones(total, dtype=bool)
    for d in range(n_dev):
        dead = np.arange(local_cap) >= rows_per_shard[d]
        sl = slice(d * local_cap, (d + 1) * local_cap)
        key_valid[sl][dead] = False
        val_valid[sl][dead] = False

    table_parts = []
    for d in range(n_dev):
        sl = slice(d * local_cap, d * local_cap + rows_per_shard[d])
        table_parts.append(pa.table({
            "k": pa.array(keys[sl]),
            "v": pa.array([None if not v else int(x)
                           for x, v in zip(vals[sl], val_valid[sl])],
                          type=pa.int64()),
        }))
    full = pa.concat_tables(table_parts)
    schema = Schema.from_pa(full.schema)

    kexpr = (bind_expression(UnresolvedAttribute("k"), schema),)
    fns = (Sum(bind_expression(UnresolvedAttribute("v"), schema)),
           Count(bind_expression(UnresolvedAttribute("v"), schema)),
           Min(bind_expression(UnresolvedAttribute("v"), schema)),
           Max(bind_expression(UnresolvedAttribute("v"), schema)),
           Average(bind_expression(UnresolvedAttribute("v"), schema)))

    # ---- single-device reference (CPU eager) --------------------------------
    hb = HostBatch.from_arrow(full)
    colvs = [ColV(c.dtype, c.data, c.validity, c.lengths) for c in hb.columns]
    ectx = EvalCtx(np, colvs, hb.num_rows, 64)
    ref_keys, ref_res, ref_ng = group_aggregate(np, ectx, kexpr, fns,
                                                hb.num_rows, hb.num_rows)
    ng = int(ref_ng)

    # ---- distributed --------------------------------------------------------
    mesh = make_mesh(n_dev)
    fn = build_distributed_aggregate(mesh, schema, kexpr, fns, local_cap)

    # build sharded flat inputs: per-device padded segments concatenated
    data_k = np.zeros(total, dtype=np.int64)
    valid_k = np.zeros(total, dtype=bool)
    data_v = np.zeros(total, dtype=np.int64)
    valid_v = np.zeros(total, dtype=bool)
    for d in range(n_dev):
        nrows = rows_per_shard[d]
        src = slice(d * local_cap, d * local_cap + nrows)
        dst = slice(d * local_cap, d * local_cap + nrows)
        data_k[dst] = keys[src]
        valid_k[dst] = True
        data_v[dst] = vals[src]
        valid_v[dst] = val_valid[src]

    out = fn(rows_per_shard, data_k, valid_k, data_v, valid_v)
    total_groups = int(out[-1])
    assert total_groups == ng

    # compare group results (sorted by key on both sides)
    got_k = np.asarray(out[0])[:total_groups]
    got_sum = np.asarray(out[2])[:total_groups]
    got_cnt = np.asarray(out[4])[:total_groups]
    got_min = np.asarray(out[6])[:total_groups]
    got_max = np.asarray(out[8])[:total_groups]
    got_avg = np.asarray(out[10])[:total_groups]

    order_ref = np.argsort(np.asarray(ref_keys[0].data)[:ng])
    order_got = np.argsort(got_k)
    np.testing.assert_array_equal(np.asarray(ref_keys[0].data)[:ng][order_ref],
                                  got_k[order_got])
    np.testing.assert_array_equal(np.asarray(ref_res[0].data)[:ng][order_ref],
                                  got_sum[order_got])
    np.testing.assert_array_equal(np.asarray(ref_res[1].data)[:ng][order_ref],
                                  got_cnt[order_got])
    np.testing.assert_array_equal(np.asarray(ref_res[2].data)[:ng][order_ref],
                                  got_min[order_got])
    np.testing.assert_array_equal(np.asarray(ref_res[3].data)[:ng][order_ref],
                                  got_max[order_got])
    np.testing.assert_allclose(np.asarray(ref_res[4].data)[:ng][order_ref],
                               got_avg[order_got], rtol=1e-12)
