"""Join tests: all join types, null/NaN keys, duplicates, empty sides, CPU vs
TPU parity (BroadcastHashJoinSuite / joins pytest analog)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def left_table():
    return pa.table({
        "k": pa.array([1, 2, 2, 3, None, 5], type=pa.int64()),
        "lv": pa.array(["a", "b", "c", "d", "e", "f"]),
    })


def right_table():
    return pa.table({
        "k": pa.array([2, 2, 3, 4, None], type=pa.int64()),
        "rv": pa.array([20, 21, 30, 40, 99], type=pa.int64()),
    })


NO_BROADCAST = {"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "-1"}


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_join_types(how):
    lt, rt = left_table(), right_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "k", how),
        ignore_order=True, conf=NO_BROADCAST,
        expect_tpu_execs=["TpuShuffledHashJoinExec"])


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_broadcast_join_types(how):
    """Small build sides take the broadcast strategy (BroadcastHashJoinSuite
    analog): same results, TpuBroadcastHashJoinExec + TpuBroadcastExchangeExec
    in the plan."""
    lt, rt = left_table(), right_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "k", how),
        ignore_order=True,
        expect_tpu_execs=["TpuBroadcastHashJoinExec", "TpuBroadcastExchangeExec"])


def test_broadcast_right_outer_builds_left():
    """An outer side cannot be broadcast: right outer join must build LEFT."""
    from spark_rapids_tpu.api import TpuSession
    lt, rt = left_table(), right_table()
    s = TpuSession()
    out = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "right")
           .collect())
    plan = s.last_plan.tree_string()
    assert "TpuBroadcastHashJoinExec" in plan
    assert out.num_rows == 7  # 5 matches + k=4 and null-key right rows


def test_broadcast_join_partitioned_stream():
    """The stream side keeps its partitioning; each partition joins against the
    one cached build batch."""
    lt, rt = left_table(), right_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(lt).repartition(3, "k")
                   .join(s.create_dataframe(rt), "k", "left")),
        ignore_order=True,
        expect_tpu_execs=["TpuBroadcastHashJoinExec"])


def test_full_join_never_broadcasts():
    from spark_rapids_tpu.api import TpuSession
    lt, rt = left_table(), right_table()
    s = TpuSession()
    s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "full").collect()
    assert "TpuShuffledHashJoinExec" in s.last_plan.tree_string()


def test_nested_loop_join_disabled_by_default():
    """GpuOverrides.scala:1688-1691 analog: brute-force joins stay on CPU
    unless explicitly enabled."""
    from spark_rapids_tpu.api import TpuSession
    lt = pa.table({"a": pa.array([1, 2], type=pa.int64())})
    rt = pa.table({"b": pa.array([10, 20, 30], type=pa.int64())})
    s = TpuSession()
    s.create_dataframe(lt).crossJoin(s.create_dataframe(rt)).collect()
    plan = s.last_plan.tree_string()
    assert "CpuNestedLoopJoinExec" in plan
    assert "disabled by default" in s.last_explain


def test_nested_loop_join_enabled():
    lt = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    rt = pa.table({"b": pa.array([10, 20, 30, None], type=pa.int64())})
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).crossJoin(s.create_dataframe(rt)),
        ignore_order=True,
        conf={"spark.rapids.tpu.sql.exec.NestedLoopJoin": "true"},
        expect_tpu_execs=["TpuBroadcastNestedLoopJoinExec"])
    assert cpu.num_rows == 12


def test_cartesian_product_enabled():
    """Keyless joins whose sides cannot broadcast go through
    CartesianProductExec. Since the PR 11 size_estimate audit, aggregates
    report a real upper bound (so they CAN broadcast by default); pinning
    the threshold to 0 recreates the no-broadcastable-side case."""
    from spark_rapids_tpu.api import TpuSession, functions as F
    lt = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    rt = pa.table({"b": pa.array([10, 20], type=pa.int64())})

    def build(s):
        left = s.create_dataframe(lt).groupBy("a").agg(F.count().alias("n"))
        right = s.create_dataframe(rt).groupBy("b").agg(F.count().alias("m"))
        return left.crossJoin(right)

    cpu = assert_tpu_and_cpu_equal(
        build, ignore_order=True,
        conf={"spark.rapids.tpu.sql.exec.CartesianProduct": "true",
              "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "0"},
        expect_tpu_execs=["TpuCartesianProductExec"])
    assert cpu.num_rows == 6


def test_inner_join_golden():
    lt, rt = left_table(), right_table()
    s = TpuSession()
    out = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k")
           .sort("k", "lv", "rv").collect())
    # k=2 matches 2x2 rows; k=3 matches 1; nulls never match
    assert out.column("k").to_pylist() == [2, 2, 2, 2, 3]
    assert out.column("lv").to_pylist() == ["b", "b", "c", "c", "d"]
    assert out.column("rv").to_pylist() == [20, 21, 20, 21, 30]


def test_left_join_golden():
    lt, rt = left_table(), right_table()
    s = TpuSession()
    out = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "left")
           .sort("lv", "rv").collect())
    assert out.num_rows == 8  # 5 matches + a,e,f unmatched
    d = dict(zip(out.column("lv").to_pylist(), out.column("rv").to_pylist()))
    assert d["a"] is None and d["e"] is None and d["f"] is None


def test_semi_anti_golden():
    lt, rt = left_table(), right_table()
    s = TpuSession()
    semi = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "left_semi")
            .sort("lv").collect())
    assert semi.column("lv").to_pylist() == ["b", "c", "d"]
    anti = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "left_anti")
            .sort("lv").collect())
    assert anti.column("lv").to_pylist() == ["a", "e", "f"]  # null key kept


def test_full_join_coalesced_key():
    lt, rt = left_table(), right_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "full"),
        ignore_order=True)
    s = TpuSession()
    out = (s.create_dataframe(lt).join(s.create_dataframe(rt), "k", "full")
           .collect())
    # 5 matched pairs + 3 unmatched left + 2 unmatched right (incl null-key)
    assert out.num_rows == 10
    assert 4 in out.column("k").to_pylist()  # right-only key appears coalesced


def test_string_keys_and_nan_keys():
    lt = pa.table({"s": pa.array(["x", "y", None, "z"]),
                   "v": pa.array([1, 2, 3, 4], type=pa.int64())})
    rt = pa.table({"s": pa.array(["y", "z", "z", None]),
                   "w": pa.array([20, 30, 31, 99], type=pa.int64())})
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "s"),
        ignore_order=True)
    nan = float("nan")
    lf = pa.table({"d": pa.array([1.0, nan, 2.0], type=pa.float64()),
                   "v": pa.array([1, 2, 3], type=pa.int64())})
    rf = pa.table({"d": pa.array([nan, 2.0], type=pa.float64()),
                   "w": pa.array([10, 20], type=pa.int64())})
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lf).join(s.create_dataframe(rf), "d"),
        ignore_order=True)
    assert cpu.num_rows == 2  # NaN == NaN matches (Spark NaN semantics)


def test_empty_sides():
    lt, rt = left_table(), right_table()
    empty_r = rt.slice(0, 0)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(empty_r), "k"),
        ignore_order=True)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).join(s.create_dataframe(empty_r), "k",
                                              "left"),
        ignore_order=True)


def test_cross_join():
    lt = pa.table({"a": pa.array([1, 2], type=pa.int64())})
    rt = pa.table({"b": pa.array([10, 20, 30], type=pa.int64())})
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(lt).crossJoin(s.create_dataframe(rt)),
        ignore_order=True)
    assert cpu.num_rows == 6


def test_join_then_agg_pipeline():
    """Joined data flows on into aggregation on device (TPC-H-q5-ish shape)."""
    lt, rt = left_table(), right_table()
    assert_tpu_and_cpu_equal(
        lambda s: (s.create_dataframe(lt)
                   .join(s.create_dataframe(rt), "k")
                   .groupBy("lv").agg(F.sum("rv").alias("srv"),
                                      F.count().alias("n"))),
        ignore_order=True, conf=NO_BROADCAST,
        expect_tpu_execs=["TpuShuffledHashJoinExec", "TpuHashAggregateExec"])


def test_mixed_dtype_keys_coerced():
    # regression (code review): int64 x float64 keys must widen, order-independent
    lt = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                   "v": pa.array([10, 20], type=pa.int64())})
    for rvals in ([1.5, 1.0], [1.0, 1.5]):
        rt = pa.table({"k": pa.array(rvals, type=pa.float64()),
                       "w": pa.array([100, 200], type=pa.int64())})
        cpu = assert_tpu_and_cpu_equal(
            lambda s: s.create_dataframe(lt).join(s.create_dataframe(rt), "k"),
            ignore_order=True)
        assert cpu.num_rows == 1
        assert cpu.column("v").to_pylist() == [10]


def test_condition_on_outer_join_rejected():
    from spark_rapids_tpu.plan import logical as lp
    from spark_rapids_tpu.api.dataframe import DataFrame
    from spark_rapids_tpu.exprs import GreaterThan, UnresolvedAttribute
    s = TpuSession()
    lt = s.create_dataframe({"k": [1], "a": [1]})
    rt = s.create_dataframe({"k": [1], "b": [2]})
    j = DataFrame(lp.Join(lt._plan, rt._plan, "left",
                          (UnresolvedAttribute("k"),), (UnresolvedAttribute("k"),),
                          GreaterThan(UnresolvedAttribute("b"),
                                      UnresolvedAttribute("a"))), s)
    with pytest.raises(NotImplementedError, match="inner"):
        j.collect()
