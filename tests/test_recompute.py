"""Lineage-scoped stage recompute tests (the robustness tentpole).

The escalation ladder under test: a reduce-side fetch that exhausts its
retries (PR 2) no longer fails the query — the stage driver re-executes
ONLY the lost map tasks from recorded lineage on surviving peers, replaces
their blocks exactly-once, and resumes the blocked reduce. Past
``shuffle.recompute.maxStageAttempts`` the scoped error re-surfaces for
the serving failover layer (PR 14) to own.

Three layers are covered:
- session-level chaos: a seeded mid-reduce ``kill_peer`` on a multi-peer
  cluster run completes with zero caller-visible errors, recomputes only
  the dead peer's map tasks, and collects bit-identically (float aggs
  within the documented 1e-9 carve-out — post-recompute row arrival order
  legitimately differs);
- the scoped error payload (executor_id + undelivered blocks) round-trips
  every boundary it crosses: multi-table blocks, metadata-missing blocks,
  two dead peers in one reduce window, and the process-executor control
  socket;
- disk-spill integrity: a corrupt spill file is a crc-detected LOST block
  (typed error, catalog drop) feeding the same recompute signal, never
  silently wrong bytes.
"""
import pickle

import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.shuffle.inprocess import _Fabric
from spark_rapids_tpu.shuffle.manager import ShuffleFetchFailedError
from spark_rapids_tpu.testing import assert_tables_equal
from spark_rapids_tpu.utils import metrics as mt
from tests.test_shuffle import (collect_partition, sample_table,
                                write_partitioned)
from tests.test_shuffle_faults import fault_cluster

FAULT_TRANSPORT = "spark_rapids_tpu.shuffle.faults.FaultInjectingTransport"


@pytest.fixture(autouse=True)
def fresh_fabric():
    _Fabric.reset()
    yield
    _Fabric.reset()


def _cluster_conf(extra=None):
    """Two in-process executors; tight retry/timeout knobs keep the faulted
    paths fast (the 300 s fetch-timeout default is sized for cold serving
    clusters, not chaos tests)."""
    conf = {
        "spark.rapids.tpu.sql.cluster.numExecutors": "2",
        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
        "spark.rapids.tpu.shuffle.retryBackoffMs": "5",
        "spark.rapids.tpu.shuffle.maxRetries": "1",
        "spark.rapids.tpu.shuffle.fetch.timeoutSeconds": "5",
    }
    conf.update(extra or {})
    return conf


def _kill_exec1_conf(extra=None):
    """exec-1 dies mid-stream on its first outgoing data frame (the
    ``owner`` filter keeps the shared plan from killing every executor)."""
    return _cluster_conf({
        "spark.rapids.tpu.shuffle.transport.class": FAULT_TRANSPORT,
        "spark.rapids.tpu.shuffle.faults.plan":
            "kill_peer:owner=exec-1,req_type=data,after=1",
        "spark.rapids.tpu.shuffle.faults.seed": "7",
        **(extra or {})})


def _tables(n=4000):
    fact = pa.table({"k": [i % 8 for i in range(n)],
                     "v": list(range(n)),
                     "f": [i * 0.25 for i in range(n)]})
    dim = pa.table({"k": list(range(8)),
                    "name": [f"n{i}" for i in range(8)]})
    return fact, dim


def _query(s, fact, dim):
    return (s.create_dataframe(fact).repartition(4, "k").groupBy("k")
            .agg(F.sum("v").alias("sv"), F.sum("f").alias("sf"))
            .join(s.create_dataframe(dim), "k")
            .filter(F.col("sv") > -500).sort("sv", "k"))


# ---------------------------------------------------------------------------------
# session-level: seeded mid-reduce executor death
# ---------------------------------------------------------------------------------

def test_kill_peer_mid_reduce_recomputes_only_lost_maps():
    """THE acceptance bar: a peer dying mid-reduce is a bounded
    re-execution, not a query loss — no caller-visible error, only the
    dead peer's map tasks replay, and the collect is bit-identical to the
    fault-free run (float aggs within 1e-9)."""
    fact, dim = _tables()
    ref_s = TpuSession(_cluster_conf())
    try:
        ref = _query(ref_s, fact, dim).collect()
    finally:
        ref_s._cluster_scheduler.close()
    _Fabric.reset()

    s = TpuSession(_kill_exec1_conf())
    try:
        before = mt.recompute_snapshot()
        got = _query(s, fact, dim).collect()
        delta = mt.recompute_delta(before)
        sched = s._cluster_scheduler
        total_maps = sum(st.num_tasks for st in sched.last_stages
                         if not st.is_result)
        assert delta["shuffle.recomputes"] >= 1, delta
        assert 1 <= delta["shuffle.recomputed_map_tasks"] < total_maps, (
            f"recompute must be SCOPED to the dead peer's maps: {delta} "
            f"vs {total_maps} total")
        assert delta["shuffle.recompute_escalations"] == 0, delta
        # the kill really happened (a green run must prove the fault fired)
        dead = [ex.executor_id for ex in sched.executors
                if not sched._executor_alive(ex)]
        assert dead == ["exec-1"], dead
        # per-shuffle lineage is driver memory, released with the shuffles
        assert sched._lineage == {}
        assert_tables_equal(ref, got, ignore_order=True, approx_float=1e-9)
    finally:
        s._cluster_scheduler.close()


def test_recompute_disabled_escalates_scoped_error():
    """maxStageAttempts=0 disables recompute: the scoped fetch error
    surfaces unchanged (the failover layer's signal) and the escalation
    counter records the handoff."""
    fact, dim = _tables(800)
    s = TpuSession(_kill_exec1_conf(
        {"spark.rapids.tpu.shuffle.recompute.maxStageAttempts": "0"}))
    try:
        before = mt.recompute_snapshot()
        with pytest.raises(ShuffleFetchFailedError) as ei:
            _query(s, fact, dim).collect()
        delta = mt.recompute_delta(before)
        assert delta["shuffle.recompute_escalations"] == 1, delta
        assert delta["shuffle.recomputes"] == 0, delta
        assert ei.value.executor_id == "exec-1"
        assert ei.value.blocks
    finally:
        s._cluster_scheduler.close()


def test_serving_submit_absorbs_recompute_and_records_metrics():
    """Serving integration: a submitted query rides out the mid-reduce
    death with no client-visible error and its handle carries the
    fault-recovery story (the ``shuffle`` exec-metrics block)."""
    fact, dim = _tables()
    s = TpuSession(_kill_exec1_conf())
    try:
        handle = s.submit(_query(s, fact, dim))
        got = handle.result(timeout=120)
        assert handle.error is None
        assert got.num_rows == 8
        shuf = handle.exec_metrics.get("shuffle", {})
        assert shuf.get("shuffle.recomputes", 0) >= 1, handle.exec_metrics
        assert shuf.get("shuffle.recompute_escalations", 1) == 0
    finally:
        s._cluster_scheduler.close()


@pytest.mark.slow
def test_tpch_q3_kill_peer_recompute():
    """TPC-H Q3 across two executors with a seeded mid-reduce kill:
    completes with zero caller-visible errors, recomputes a strict subset
    of the map tasks, and matches the CPU session bit-for-bit (1e-9 float
    carve-out)."""
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all
    from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
    tables = gen_all(0.002, seed=7)
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    cdfs = {k: cpu.create_dataframe(v).repartition(2)
            for k, v in tables.items()}
    exp = QUERIES[3](cdfs).collect()

    s = TpuSession({**BENCH_CONF, **_kill_exec1_conf()})
    try:
        before = mt.recompute_snapshot()
        dfs = {k: s.create_dataframe(v).repartition(2)
               for k, v in tables.items()}
        out = QUERIES[3](dfs).collect()
        delta = mt.recompute_delta(before)
        sched = s._cluster_scheduler
        total_maps = sum(st.num_tasks for st in sched.last_stages
                         if not st.is_result)
        assert delta["shuffle.recomputes"] >= 1, delta
        assert delta["shuffle.recomputed_map_tasks"] < total_maps, delta
        assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-9)
    finally:
        s._cluster_scheduler.close()


# ---------------------------------------------------------------------------------
# scoped error payload: executor_id + blocks round-trips every boundary
# ---------------------------------------------------------------------------------

def test_metadata_missing_blocks_reports_all_undelivered(tmp_path):
    """Regression (satellite fix): when the metadata response is missing
    SOME blocks, the scoped error must report ALL undelivered blocks for
    that peer — the answered blocks' transfers are never issued either, so
    under-reporting would leave the recompute scope short."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    write_partitioned(mgr, e1, sid, 0, sample_table(60, seed=1), 1)
    write_partitioned(mgr, e1, sid, 1, sample_table(60, seed=2), 1)
    # map 1's outputs vanish (spill corruption, eviction): metadata still
    # answers for map 0
    e1.shuffle_catalog.remove_map_outputs(sid, 1)
    with pytest.raises(ShuffleFetchFailedError, match="lost blocks") as ei:
        collect_partition(mgr, e0, sid, 0)
    assert ei.value.executor_id == "exec-1"
    got_maps = {b.map_id for b in ei.value.blocks}
    assert got_maps == {0, 1}, (
        f"ALL undelivered blocks must ride the error, got maps {got_maps}")


def test_multi_table_blocks_roundtrip_and_error_scope(tmp_path):
    """A block holding multiple tables (a map task that wrote its partition
    in several batches) delivers every table exactly once, and when lost it
    appears in the error payload once per BLOCK, not once per table."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(80, seed=3)
    # two write rounds for the same map id -> two tables under one block
    write_partitioned(mgr, e1, sid, 0, t, 1)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    assert len(e1.shuffle_catalog.metas(
        next(iter(e1.shuffle_catalog._by_shuffle[sid])))) == 2
    got = collect_partition(mgr, e0, sid, 0)
    assert got.num_rows == 2 * t.num_rows       # both tables, no dedup loss
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist() * 2)

    _Fabric.reset()
    mgr2, e0b, e1b = fault_cluster(tmp_path / "b")
    sid2, _ = mgr2.register_shuffle(1)
    write_partitioned(mgr2, e1b, sid2, 0, t, 1)
    write_partitioned(mgr2, e1b, sid2, 0, t, 1)
    e1b.shuffle_catalog.remove_shuffle(sid2)
    with pytest.raises(ShuffleFetchFailedError) as ei:
        collect_partition(mgr2, e0b, sid2, 0)
    blocks = list(ei.value.blocks)
    assert len(blocks) == len(set(blocks)) == 1, (
        f"one lost BLOCK, not one entry per table: {blocks}")


def test_two_dead_peers_scope_non_overlapping(tmp_path):
    """Two peers failing inside one reduce window: the scoped error names
    one peer and carries ONLY that peer's blocks — recompute sets derived
    per error never overlap."""
    mgr, e0, e1, e2 = fault_cluster(
        tmp_path, n=3,
        extra={"spark.rapids.tpu.shuffle.maxRetries": 1,
               "spark.rapids.tpu.shuffle.fetch.timeoutSeconds": 30})
    sid, _ = mgr.register_shuffle(1)
    write_partitioned(mgr, e1, sid, 0, sample_table(50, seed=4), 1)
    write_partitioned(mgr, e2, sid, 1, sample_table(50, seed=5), 1)
    owner_of = {st.map_id: st.executor_id
                for st in mgr.tracker._shuffles[sid].values()}
    _Fabric.get().kill("exec-1")
    _Fabric.get().kill("exec-2")
    with pytest.raises(ShuffleFetchFailedError) as ei:
        collect_partition(mgr, e0, sid, 0)
    err = ei.value
    assert err.executor_id in ("exec-1", "exec-2")
    assert err.blocks
    # every block in the payload belongs to the NAMED peer: the recompute
    # set for this error cannot overlap the other dead peer's
    for b in err.blocks:
        assert owner_of[b.map_id] == err.executor_id, (err.executor_id,
                                                       b, owner_of)


def test_fetch_error_payload_survives_daemon_boundary(tmp_path):
    """The ProcessExecutor control socket carries the scoped payload as a
    plain dict (executor daemon) and the driver reconstructs a faithful
    ShuffleFetchFailedError — pickle round-trip AND dict round-trip."""
    from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
    blocks = (ShuffleBlockId(3, 1, 0), ShuffleBlockId(3, 4, 0))
    err = ShuffleFetchFailedError("lost blocks on exec-9",
                                  executor_id="exec-9", blocks=blocks)
    back = pickle.loads(pickle.dumps(err))
    assert back.executor_id == "exec-9" and tuple(back.blocks) == blocks

    # the daemon's wire codec (parallel/executor_daemon.py encodes, the
    # driver's ProcessExecutor.submit decodes) -> faithful reconstruction
    from spark_rapids_tpu.utils import errors as uerr
    wire = uerr.encode_error(err)
    assert wire["code"] == "SHUFFLE_FETCH_FAILED"
    rebuilt = uerr.decode_error(wire)
    assert isinstance(rebuilt, ShuffleFetchFailedError)
    assert rebuilt.executor_id == "exec-9"
    assert tuple(rebuilt.blocks) == blocks
    assert "lost blocks" in str(rebuilt)
    # block ids keep their namedtuple shape: recompute reads b.map_id
    assert rebuilt.blocks[0].map_id == 1


def test_remove_map_outputs_scoped_to_one_map(tmp_path):
    """Exactly-once replacement's first half: dropping ONE map's outputs
    leaves sibling maps' blocks serving, and a second drop is a no-op."""
    mgr, e0, e1 = fault_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    write_partitioned(mgr, e1, sid, 0, sample_table(40, seed=6), 1)
    write_partitioned(mgr, e1, sid, 1, sample_table(40, seed=7), 1)
    removed = e1.shuffle_catalog.remove_map_outputs(sid, 1)
    assert removed >= 1
    assert e1.shuffle_catalog.remove_map_outputs(sid, 1) == 0   # idempotent
    blocks = list(e1.shuffle_catalog._by_shuffle.get(sid, []))
    assert blocks and all(b.map_id == 0 for b in blocks)
    # map 0's block still serves
    assert e1.shuffle_catalog.metas(blocks[0])


# ---------------------------------------------------------------------------------
# disk-spill integrity: crc on every spill write, verified on unspill
# ---------------------------------------------------------------------------------

def test_spill_crc_detects_disk_corruption(tmp_path):
    """A flipped byte in a spill file surfaces as SpillCorruptionError on
    unspill — typed, path-carrying, never silently wrong bytes."""
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import (BufferId,
                                                SpillCorruptionError,
                                                SpillableBuffer)
    b = DeviceBatch.from_arrow(sample_table(128, seed=8))
    disk = SpillableBuffer.from_batch(BufferId(4242), b).to_host().to_disk(
        str(tmp_path))
    assert disk.disk_crc32 is not None
    data = bytearray(open(disk.payload, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(disk.payload, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(SpillCorruptionError) as ei:
        disk.get_batch()
    assert ei.value.path == disk.payload
    assert ei.value.expected != ei.value.actual


def test_spill_crc_clean_roundtrip(tmp_path):
    """Control: an untouched spill file unspills bit-exactly through the
    crc gate."""
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.memory.buffer import BufferId, SpillableBuffer
    t = sample_table(128, seed=9)
    disk = SpillableBuffer.from_batch(
        BufferId(4243), DeviceBatch.from_arrow(t)).to_host().to_disk(
        str(tmp_path))
    assert disk.get_batch().to_arrow().equals(t)


def test_corrupt_shuffle_spill_is_lost_block_recompute_signal(tmp_path):
    """A shuffle-owned buffer whose spill file rots is a LOST block: the
    server drops the whole map task's outputs and the reader's next
    metadata pass reports them missing — the permanent scoped error that
    feeds the lineage recompute, not a retry loop over bad bytes."""
    import glob
    mgr, e0, e1 = fault_cluster(
        tmp_path, extra={"spark.rapids.tpu.shuffle.maxRetries": 1})
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(300, seed=10)
    write_partitioned(mgr, e1, sid, 0, t, 1)
    # force the map output all the way to disk, then rot every spill file
    assert e1.device_store.spill_to_size(0) > 0
    e1.host_store.spill_to_size(0)
    files = glob.glob(str(tmp_path / "e1" / "**" / "*.npz"), recursive=True)
    assert files, "expected on-disk spill files"
    for path in files:
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(raw))
    with pytest.raises(ShuffleFetchFailedError) as ei:
        collect_partition(mgr, e0, sid, 0)
    assert ei.value.executor_id == "exec-1" and ei.value.blocks
    # the corrupt map task's outputs are GONE from the serving catalog
    assert not e1.shuffle_catalog._by_shuffle.get(sid)
