"""Fused partition-reorder kernel (shuffle/partition_kernel.py): pack ->
Pallas kernel -> consolidate, in interpreter mode on the CPU backend (the
real-chip numbers live in bench.py). The reorder must move every live row to
exactly one partition piece bit-exactly; intra-partition ORDER is not
promised (shuffle semantics)."""
import datetime

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.shuffle import partition_kernel as pk


def _table(n, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "l": pa.array(rng.integers(-2**62, 2**62, n), type=pa.int64()),
        "i": pa.array(rng.integers(-2**31, 2**31 - 1, n), type=pa.int32()),
        "d": pa.array(np.round(rng.standard_normal(n) * 1e6, 2)),
        "s": pa.array([f"s{int(x)}" for x in rng.integers(0, 1000, n)]),
        "b": pa.array(rng.random(n) < 0.5),
        "dt": pa.array([datetime.date(2020, 1, 1)
                        + datetime.timedelta(days=int(x))
                        for x in rng.integers(0, 1000, n)],
                       type=pa.date32()),
        "ts": pa.array(rng.integers(0, 2**45, n), type=pa.timestamp("us")),
    })


def _with_nulls(t, seed=1):
    rng = np.random.default_rng(seed)
    cols = []
    for name in t.column_names:
        arr = t.column(name).combine_chunks()
        mask = rng.random(len(arr)) < 0.1
        cols.append(pa.array(arr.to_pylist(), type=arr.type,
                             mask=mask))
    return pa.table(dict(zip(t.column_names, cols)))


def _run(table, n_parts, seed=3):
    import jax.numpy as jnp
    batch = DeviceBatch.from_arrow(table, string_max_bytes=16)
    rng = np.random.default_rng(seed)
    pids_np = rng.integers(0, n_parts, batch.capacity).astype(np.int32)
    res = pk.split_batch_kernel(batch, jnp.asarray(pids_np), n_parts,
                                interpret=True)
    assert res is not None, "fast path unexpectedly refused the batch"
    out, stats, spec, geom = res
    pieces = {}
    for j in range(n_parts):
        sub = pk.consolidate(out, stats, j, spec, batch.schema, geom)
        if sub is not None:
            pieces[j] = sub.to_arrow()
    return batch, pids_np, pieces


def _rows_key(t):
    """Order-independent multiset of row tuples (timestamps normalized —
    the engine returns UTC-aware values, Spark's UTC-only semantics)."""
    def norm(v):
        return v.replace(tzinfo=None) if isinstance(v, datetime.datetime) \
            else v
    cols = [[norm(v) for v in t.column(i).to_pylist()]
            for i in range(t.num_columns)]
    return sorted(zip(*cols), key=repr)


@pytest.mark.parametrize("n_parts", [2, 4, 8])
def test_kernel_reorder_matches_reference(n_parts):
    table = _table(700)
    batch, pids, pieces = _run(table, n_parts)
    live_pids = pids[:table.num_rows]
    for j in range(n_parts):
        want = table.filter(pa.array(live_pids == j))
        got = pieces.get(j)
        if want.num_rows == 0:
            assert got is None or got.num_rows == 0
            continue
        assert got is not None and got.num_rows == want.num_rows, (
            f"partition {j}: {got and got.num_rows} != {want.num_rows}")
        assert _rows_key(got) == _rows_key(want), f"partition {j} differs"


def test_kernel_reorder_with_nulls():
    table = _with_nulls(_table(500, seed=7), seed=8)
    batch, pids, pieces = _run(table, 4, seed=9)
    live = pids[:table.num_rows]
    total = sum(p.num_rows for p in pieces.values())
    assert total == table.num_rows
    for j in range(4):
        want = table.filter(pa.array(live == j))
        if want.num_rows:
            assert _rows_key(pieces[j]) == _rows_key(want)


def test_kernel_refuses_wide_fanout():
    import jax.numpy as jnp
    batch = DeviceBatch.from_arrow(_table(100), string_max_bytes=16)
    pids = jnp.zeros(batch.capacity, jnp.int32)
    assert pk.split_batch_kernel(batch, pids, pk.MAX_PARTS + 1,
                                 interpret=True) is None


def test_kernel_overflow_falls_back():
    """Every row in one partition: the per-window segment bound (2x the
    even share) must overflow and return None (caller uses the sort path)."""
    import jax.numpy as jnp
    table = _table(600)
    batch = DeviceBatch.from_arrow(table, string_max_bytes=16)
    pids = jnp.zeros(batch.capacity, jnp.int32)   # all -> partition 0
    assert pk.split_batch_kernel(batch, pids, 8, interpret=True) is None


def test_uploaded_doubles_carry_bit_siblings():
    batch = DeviceBatch.from_arrow(_table(50), string_max_bytes=16)
    dcol = batch.columns[2]
    assert dcol.bits is not None
    # the f64 view is the bitcast of the bits
    assert np.asarray(dcol.data).view(np.uint64).tolist() == \
        np.asarray(dcol.bits).tolist()


def test_exchange_kernel_mode_matches_sort_path():
    """The engine's device exchange through the fused kernel (interpreter
    mode) must produce the same query results as the sort path."""
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal

    rng = np.random.default_rng(11)
    n = 3000
    t = pa.table({
        "k": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "v": pa.array(np.round(rng.standard_normal(n) * 100, 2)),
        "s": pa.array([f"x{int(i)}" for i in rng.integers(0, 30, n)]),
    })

    def q(sess):
        return (sess.create_dataframe(t).repartition(4, "k")
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("s").alias("c"))
                .sort("k"))

    conf = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}
    fast = TpuSession({**conf,
                       "spark.rapids.tpu.shuffle.kernel.mode": "interpret"})
    slow = TpuSession({**conf, "spark.rapids.tpu.shuffle.kernel.mode": "off"})
    out_fast = q(fast).collect()
    out_slow = q(slow).collect()
    assert_tables_equal(out_slow, out_fast, approx_float=1e-9)


def test_fused_program_shared_across_round_robin_offsets():
    """Round-robin offsets ride as runtime arguments (code review): two
    batches with different offsets must reuse ONE compiled fused program
    and still land every row."""
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.execs import tpu_execs
    from spark_rapids_tpu.execs.base import ExecContext, LeafExec
    from spark_rapids_tpu.execs.exchange_execs import (
        RoundRobinPartitioning, TpuShuffleExchangeExec)

    t = _table(600)
    batch = DeviceBatch.from_arrow(t, string_max_bytes=16)

    class _Leaf(LeafExec):
        is_device = True

        def execute(self, ctx):
            yield batch

    conf = TpuConf({"spark.rapids.tpu.shuffle.kernel.mode": "interpret",
                    "spark.rapids.tpu.sql.string.maxBytes": 16})
    ctx = ExecContext(conf)
    ex = TpuShuffleExchangeExec(RoundRobinPartitioning(4),
                                _Leaf(batch.schema))

    def fused_keys():
        return [k for k in tpu_execs._JIT_CACHE
                if isinstance(k, tuple) and k and k[0] == "exchange-fused"]

    r1 = ex._kernel_split(ctx, ex.partitioning, batch, 0, 4)
    n_after_first = len(fused_keys())
    r2 = ex._kernel_split(ctx, ex.partitioning, batch, 3, 4)
    assert len(fused_keys()) == n_after_first, \
        "new offset recompiled the fused exchange program"
    assert sum(b.num_rows for _, b in r1) == batch.num_rows
    assert sum(b.num_rows for _, b in r2) == batch.num_rows
    # offset shifts rows between partitions but preserves the multiset
    all1 = sorted(sum((_rows_key(b.to_arrow()) for _, b in r1), []), key=repr)
    all2 = sorted(sum((_rows_key(b.to_arrow()) for _, b in r2), []), key=repr)
    assert all1 == all2


def test_dma_index_plan_matches_take_order():
    """Code review (round 5): the DMA consolidation's host-side index math
    must place every row exactly where the take()-path puts it — simulated
    here in numpy, so CI covers it without a TPU. The DMA path itself is
    validated on-chip (experiments/consolidate_dma_all.py: EXACT match)."""
    import numpy as np
    from spark_rapids_tpu.shuffle.partition_kernel import (BLOCK,
                                                           KernelGeom,
                                                           dma_index_plan)

    rng = np.random.default_rng(11)
    geom = KernelGeom.plan(4096, 5, 76)
    for trial in range(6):
        counts = rng.integers(0, geom.quota - 64, (geom.groups, geom.n))
        if trial == 0:
            counts[:, 2] = 0            # an empty partition
        prefix8, nb8, ridx, ri_cap, dst_rows = dma_index_plan(counts, geom)
        # staging rows: flat index g*quota + r identifies each source row
        for j in range(geom.n):
            cj = counts[:, j]
            nb = cj // BLOCK
            # take-path layout: full blocks (g asc), then remainders (g asc)
            want = []
            for g in range(geom.groups):
                want.extend(g * geom.quota + r for r in range(nb[g] * BLOCK))
            for g in range(geom.groups):
                want.extend(g * geom.quota + nb[g] * BLOCK + r
                            for r in range(cj[g] - nb[g] * BLOCK))
            # DMA simulation: quota-sized copies at prefix8 (later copies
            # overwrite earlier tails), remainder block at nb8
            dst = np.full(dst_rows, -1, np.int64)
            for g in range(geom.groups):
                off = prefix8[j, g]
                dst[off:off + geom.quota] = g * geom.quota + np.arange(
                    geom.quota)
            rem_tot = int((cj - nb * BLOCK).sum())
            dst[nb8[j]:nb8[j] + ri_cap] = ridx[j]
            got = dst[:int(cj.sum())].tolist()
            assert got == want, (trial, j)
