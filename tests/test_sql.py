"""SQL frontend: parser/planner unit coverage + TPC-H Q1-Q22 as raw SQL
producing results identical to the DataFrame forms (the VERDICT's acceptance
bar; reference analog: Catalyst consuming TpchLikeSpark SQL)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpch_data import gen_all
from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
from spark_rapids_tpu.benchmarks.tpch_sql import SQL_QUERIES
from spark_rapids_tpu.sql.lexer import SqlError
from spark_rapids_tpu.testing import assert_tables_equal

_SCALE = 0.002

# queries whose final sort key can tie -> unordered compare
_TIES = {2, 3, 5, 9, 10, 11, 16, 18, 21}

_CONF = {**BENCH_CONF,
         "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
         "spark.rapids.tpu.sql.exec.CartesianProduct": "true"}


@pytest.fixture(scope="module")
def sql_session():
    tables = gen_all(_SCALE, seed=7)
    sess = TpuSession(_CONF)
    for name, tab in tables.items():
        sess.create_dataframe(tab).createOrReplaceTempView(name)
    return sess, tables


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(SQL_QUERIES))
def test_tpch_sql_matches_dataframe(qnum, sql_session):
    sess, tables = sql_session
    sql_out = sess.sql(SQL_QUERIES[qnum]).collect()
    df_out = QUERIES[qnum](
        {k: sess.create_dataframe(v) for k, v in tables.items()}).collect()
    # compare positionally: SQL output names come from the spec text and may
    # differ in case from the DataFrame aliases
    assert sql_out.num_rows == df_out.num_rows, (
        f"q{qnum}: {sql_out.num_rows} vs {df_out.num_rows} rows")
    sql_out = sql_out.rename_columns(df_out.column_names)
    assert_tables_equal(df_out, sql_out, ignore_order=qnum in _TIES,
                        approx_float=1e-9)


# ---------------------------------------------------------------------------
# small unit coverage
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mini():
    s = TpuSession(_CONF)
    t = pa.table({"k": pa.array([1, 1, 2, 2, 3], type=pa.int64()),
                  "v": pa.array([10, 20, 30, 40, None], type=pa.int64()),
                  "name": pa.array(["a", "b", "c", "d", "e"])})
    u = pa.table({"k": pa.array([1, 2, 4], type=pa.int64()),
                  "w": pa.array([1.5, 2.5, 4.5])})
    s.create_dataframe(t).createOrReplaceTempView("t")
    s.create_dataframe(u).createOrReplaceTempView("u")
    return s


def test_sql_agg_group_order(mini):
    out = mini.sql("select k, sum(v) as sv, count(v) as nv, count(*) as n "
                   "from t group by k order by k").collect()
    assert out.to_pydict() == {"k": [1, 2, 3], "sv": [30, 70, None],
                               "nv": [2, 2, 0], "n": [2, 2, 1]}


def test_sql_join_where_pushdown(mini):
    out = mini.sql("select t.name, u.w from t, u "
                   "where t.k = u.k and u.w > 2 order by t.name").collect()
    assert out.to_pydict() == {"name": ["c", "d"], "w": [2.5, 2.5]}


def test_sql_explicit_left_join(mini):
    out = mini.sql("select t.k, u.w from t left outer join u on t.k = u.k "
                   "and u.w > 2 order by t.k, t.name").collect()
    assert out.column("w").to_pylist() == [None, None, 2.5, 2.5, None]


def test_sql_between_like_case_isnull(mini):
    out = mini.sql(
        "select name, case when v between 15 and 35 then 'mid' "
        "when v is null then 'none' else 'out' end as bucket "
        "from t where name like '%' order by name").collect()
    assert out.column("bucket").to_pylist() == [
        "out", "mid", "mid", "out", "none"]


def test_sql_exists_and_in(mini):
    got = mini.sql("select name from t where exists "
                   "(select * from u where u.k = t.k) order by name"
                   ).collect()
    assert got.column("name").to_pylist() == ["a", "b", "c", "d"]
    got = mini.sql("select name from t where k not in (select k from u) "
                   "order by name").collect()
    assert got.column("name").to_pylist() == ["e"]


def test_sql_scalar_subqueries(mini):
    got = mini.sql("select name from t where v > (select avg(v) from t) "
                   "order by name").collect()
    assert got.column("name").to_pylist() == ["c", "d"]
    # correlated with compound item
    got = mini.sql(
        "select name from t where v >= (select 2 * min(w) from u "
        "where u.k = t.k) order by name").collect()
    assert got.column("name").to_pylist() == ["a", "b", "c", "d"]


def test_sql_derived_table_and_having(mini):
    got = mini.sql(
        "select big_k, count(*) as n from "
        "(select k as big_k, sum(v) as sv from t group by k having "
        "sum(v) > 25) as s group by big_k order by big_k").collect()
    assert got.to_pydict() == {"big_k": [1, 2], "n": [1, 1]}


def test_sql_error_messages(mini):
    with pytest.raises(SqlError):
        mini.sql("select nosuchcol from t")
    with pytest.raises(SqlError, match="ambiguous"):
        mini.sql("select k from t, u where t.k = u.k")
    with pytest.raises(KeyError, match="not found"):
        mini.sql("select * from nosuchtable")


def test_sql_date_interval_folding(mini):
    import datetime
    s = mini
    d = pa.table({"d": pa.array([datetime.date(1998, 9, 1),
                                 datetime.date(1998, 9, 3)])})
    s.create_dataframe(d).createOrReplaceTempView("dates")
    got = s.sql("select d from dates where "
                "d <= date '1998-12-01' - interval '90' day").collect()
    assert got.column("d").to_pylist() == [datetime.date(1998, 9, 1)]
    got = s.sql("select d from dates where "
                "d < date '1997-09-02' + interval '1' year").collect()
    assert got.num_rows == 1


def test_sql_postfix_precedence(mini):
    # a + 1 BETWEEN ... predicates over the SUM, not the literal
    got = mini.sql("select name from t where v + 5 between 20 and 36 "
                   "order by name").collect()
    assert got.column("name").to_pylist() == ["b", "c"]
    got = mini.sql("select name from t where k + 0 in (1, 3) "
                   "order by name").collect()
    assert got.column("name").to_pylist() == ["a", "b", "e"]


def test_sql_left_join_where_not_pushed(mini):
    # a WHERE filter on the null side of a LEFT JOIN runs post-join
    # (it eliminates null-extended rows; pushing it below would keep them)
    got = mini.sql("select t.k from t left outer join u on t.k = u.k "
                   "where u.w = 1.5 order by t.k").collect()
    assert got.column("k").to_pylist() == [1, 1]


def test_sql_not_in_null_semantics(mini):
    import pyarrow as _pa
    s = mini
    s.create_dataframe(_pa.table({
        "v": _pa.array([1, 2, None], type=_pa.int64())})
    ).createOrReplaceTempView("t3")
    s.create_dataframe(_pa.table({
        "w": _pa.array([1, None], type=_pa.int64())})
    ).createOrReplaceTempView("u3")
    s.create_dataframe(_pa.table({
        "w": _pa.array([], type=_pa.int64())})
    ).createOrReplaceTempView("u4")
    # NULL in the subquery -> every row is UNKNOWN -> empty result
    assert mini.sql("select v from t3 where v not in (select w from u3)"
                    ).collect().num_rows == 0
    # empty subquery -> NOT IN is true for every row, including NULL
    assert mini.sql("select v from t3 where v not in (select w from u4)"
                    ).collect().num_rows == 3
    # no nulls anywhere: plain anti-join semantics
    assert mini.sql("select v from t3 where v is not null and v not in "
                    "(select w from u3 where w is not null) order by v"
                    ).collect().column("v").to_pylist() == [2]


def test_sql_corr_covar(mini):
    got = mini.sql(
        "select corr(k, v) as c, covar_pop(k, v) as cp from t "
        "where v is not null").collect()
    assert got.num_rows == 1 and got.column("c")[0].as_py() is not None


# ----------------------------------------------------------- windows & rollup
def test_sql_window_functions():
    """ROW_NUMBER/RANK/SUM OVER (PARTITION BY ... ORDER BY ...) through the
    SQL frontend must match the DataFrame window API."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, Window
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal
    rng = np.random.default_rng(101)
    t = pa.table({"k": rng.integers(0, 6, 500).astype(np.int64),
                  "v": rng.integers(0, 1000, 500).astype(np.int64)})
    s = TpuSession()
    s.create_dataframe(t).createOrReplaceTempView("t")
    out = s.sql(
        "select k, v, row_number() over (partition by k order by v, v + k)"
        " as rn, rank() over (partition by k order by v) as rk,"
        " sum(v) over (partition by k order by v"
        "              rows between unbounded preceding and current row)"
        " as rsum, lag(v, 1) over (partition by k order by v, v * 2) as pv"
        " from t").collect()
    w = Window.partitionBy("k").orderBy("v", (F.col("v") + F.col("k")))
    wr = Window.partitionBy("k").orderBy("v")
    ws = Window.partitionBy("k").orderBy("v").rowsBetween(
        Window.unboundedPreceding, Window.currentRow)
    wl = Window.partitionBy("k").orderBy("v", (F.col("v") * 2))
    exp = s.create_dataframe(t).select(
        "k", "v",
        F.row_number().over(w).alias("rn"),
        F.rank().over(wr).alias("rk"),
        F.sum("v").over(ws).alias("rsum"),
        F.lag("v", 1).over(wl).alias("pv")).collect()
    assert_tables_equal(exp, out, ignore_order=True)


def test_sql_window_over_aggregate():
    """rank() OVER (ORDER BY sum(x)) after GROUP BY — the windows-after-
    aggregation shape TPC-DS leans on (q67-class)."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, Window
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal
    rng = np.random.default_rng(103)
    t = pa.table({"g": rng.integers(0, 10, 400).astype(np.int64),
                  "b": rng.integers(0, 3, 400).astype(np.int64),
                  "v": rng.integers(0, 100, 400).astype(np.int64)})
    s = TpuSession()
    s.create_dataframe(t).createOrReplaceTempView("t2")
    out = s.sql(
        "select g, b, sum(v) as sv,"
        " rank() over (partition by b order by sum(v) desc) as rk"
        " from t2 group by g, b").collect()
    w = Window.partitionBy("b").orderBy(F.col("sv").desc())
    exp = (s.create_dataframe(t).groupBy("g", "b")
           .agg(F.sum("v").alias("sv"))
           .select("g", "b", "sv", F.rank().over(w).alias("rk"))).collect()
    assert_tables_equal(exp, out, ignore_order=True)


def test_sql_rollup_and_cube():
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal
    rng = np.random.default_rng(107)
    t = pa.table({"a": rng.integers(0, 4, 300).astype(np.int64),
                  "b": rng.integers(0, 3, 300).astype(np.int64),
                  "v": rng.integers(0, 50, 300).astype(np.int64)})
    s = TpuSession()
    s.create_dataframe(t).createOrReplaceTempView("t3")
    out = s.sql("select a, b, sum(v) as sv, count(v) as c from t3"
                " group by rollup(a, b)").collect()
    exp = (s.create_dataframe(t).rollup("a", "b")
           .agg(F.sum("v").alias("sv"), F.count("v").alias("c"))).collect()
    assert_tables_equal(exp, out, ignore_order=True)
    out_c = s.sql("select a, b, max(v) as mv from t3"
                  " group by cube(a, b)").collect()
    exp_c = (s.create_dataframe(t).cube("a", "b")
             .agg(F.max("v").alias("mv"))).collect()
    assert_tables_equal(exp_c, out_c, ignore_order=True)


def test_sql_with_ctes():
    """WITH clause: chained CTEs, multiple references, view shadowing."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.testing import assert_tables_equal
    rng = np.random.default_rng(109)
    t = pa.table({"k": rng.integers(0, 8, 600).astype(np.int64),
                  "v": rng.integers(0, 100, 600).astype(np.int64)})
    s = TpuSession()
    s.create_dataframe(t).createOrReplaceTempView("base")
    out = s.sql(
        "with sums as (select k, sum(v) as sv from base group by k),"
        "     big as (select k, sv from sums where sv > 3000)"
        " select a.k, a.sv, b.sv as sv2 from big a join big b on a.k = b.k"
        " order by a.k").collect()
    df = s.create_dataframe(t).groupBy("k").agg(F.sum("v").alias("sv")) \
         .filter(F.col("sv") > 3000)
    exp = (df.select(F.col("k"), F.col("sv"))
             .join(df.select(F.col("k").alias("k2"),
                             F.col("sv").alias("sv2")),
                   F.col("k") == F.col("k2"))
             .select("k", "sv", "sv2").sort("k")).collect()
    assert_tables_equal(exp, out)
    # a CTE shadows a same-named view
    s.create_dataframe(pa.table({"x": pa.array([1], pa.int64())})) \
     .createOrReplaceTempView("shadow")
    got = s.sql("with shadow as (select 2 as x) "
                "select x from shadow").collect()
    assert got.column("x").to_pylist() == [2]


def test_sql_cte_with_window_rollup_combo():
    """A TPC-DS-shaped statement: CTE -> rollup -> window over aggregate."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession
    from spark_rapids_tpu.testing import assert_tables_equal
    rng = np.random.default_rng(113)
    t = pa.table({"cat": rng.integers(0, 4, 800).astype(np.int64),
                  "brand": rng.integers(0, 5, 800).astype(np.int64),
                  "sales": rng.integers(1, 500, 800).astype(np.int64)})
    s = TpuSession()
    s.create_dataframe(t).createOrReplaceTempView("sales_t")
    out = s.sql(
        "with results as ("
        "  select cat, brand, sum(sales) as s"
        "  from sales_t group by rollup(cat, brand))"
        " select cat, brand, s,"
        "        rank() over (partition by cat order by s desc) as rk"
        " from results order by cat, rk, brand").collect()
    assert out.num_rows > 0
    # spot-check: within each cat, rk follows descending s
    rows = out.to_pylist()
    by_cat = {}
    for r in rows:
        by_cat.setdefault(r["cat"], []).append(r)
    for cat, rs in by_cat.items():
        svals = [r["s"] for r in sorted(rs, key=lambda r: r["rk"])]
        assert svals == sorted(svals, reverse=True), (cat, svals)


def test_select_distinct_order_limit_semantics():
    """Regression (q82): DISTINCT applies before ORDER BY and LIMIT — the
    output must be deduplicated, fully sorted, and limited over the DISTINCT
    groups (not over the raw duplicated rows)."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    t = pa.table({"k": [3, 1, 2, 3, 1, 2, 3, 1], "v": [1] * 8})
    sess.create_dataframe(t).createOrReplaceTempView("dups")
    out = sess.sql("select distinct k from dups order by k").collect()
    assert out.column("k").to_pylist() == [1, 2, 3]
    out = sess.sql("select distinct k from dups order by k limit 2").collect()
    assert out.column("k").to_pylist() == [1, 2]
    # select * form
    out = sess.sql("select distinct * from dups order by k, v").collect()
    assert out.column("k").to_pylist() == [1, 2, 3]


def test_select_distinct_order_by_hidden_column_rejected():
    """Spark raises an analysis error for SELECT DISTINCT ordered by a
    non-selected column (the dedup group-by cannot preserve that order)."""
    import pyarrow as pa
    import pytest as _pytest
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.sql.planner import SqlError
    sess = TpuSession()
    t = pa.table({"k": [1, 2], "v": [9, 8]})
    sess.create_dataframe(t).createOrReplaceTempView("t2")
    with _pytest.raises(SqlError, match="DISTINCT"):
        sess.sql("select distinct k from t2 order by v")


def test_sql_intersect_except():
    """INTERSECT / EXCEPT set operations (the official TPC-DS q14/q38/q87
    texts use them): distinct rows, nulls compare equal, positional
    columns."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table(
        {"a": [1, 1, 2, 3, None]})).createOrReplaceTempView("ta")
    sess.create_dataframe(pa.table(
        {"b": [1, 3, None, 9]})).createOrReplaceTempView("tb")
    out = sess.sql("select a from ta intersect select b from tb").collect()
    assert sorted(out.column("a").to_pylist(),
                  key=lambda v: (v is None, v)) == [1, 3, None]
    out = sess.sql("select a from ta except select b from tb").collect()
    assert out.column("a").to_pylist() == [2]
    # uniform chains fold left; MIXED chains are refused (INTERSECT binds
    # tighter in standard SQL — left-folding would silently misparse)
    import pytest as _pytest
    from spark_rapids_tpu.sql.lexer import SqlError
    with _pytest.raises(SqlError, match="INTERSECT"):
        sess.sql("""select a from ta intersect select b from tb
                    except select 1 as x""")
    # the parenthesized (derived-table) form works
    out = sess.sql("""
        select a from (select a from ta intersect select b from tb) i
        except select 1 as x""").collect()
    assert sorted(out.column("a").to_pylist(),
                  key=lambda v: (v is None, v)) == [3, None]
    # uniform intersect chain still folds
    out = sess.sql("""select a from ta intersect select b from tb
                      intersect select 1 as x""").collect()
    assert out.column("a").to_pylist() == [1]


def test_sql_string_function_registry():
    """String/misc functions newly exposed to SQL match their DataFrame
    forms."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "s": ["hello world", "a,b,c", None, "  pad  "],
        "n": [7, -7, 3, None]})).createOrReplaceTempView("t")
    out = sess.sql("""
        select substr(s, 1, 5) as sub, lpad(s, 14, '*') as lp,
               rtrim(s) as rt, instr(s, 'o') as pos,
               replace(s, ',', ';') as rep, nvl(s, '??') as nv,
               char_length(s) as ln, pmod(n, 5) as pm,
               substring_index(s, ',', 2) as si
        from t""").collect()
    r = out.to_pylist()
    assert r[0]["sub"] == "hello" and r[0]["pos"] == 5
    assert r[1]["rep"] == "a;b;c" and r[1]["si"] == "a,b"
    assert r[2]["nv"] == "??"
    assert r[3]["rt"] == "  pad"
    assert r[0]["lp"] == "***hello world"
    assert r[1]["pm"] == 3            # Spark pmod: positive result
    assert r[2]["ln"] is None


def test_sql_function_arity_forms():
    """Code review: 2-arg substr/replace work (Spark semantics), trim chars
    are honored, and unsupported format/arity forms raise SqlError rather
    than silently returning wrong data."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({"s": ["000x0", "hello world"]})
                          ).createOrReplaceTempView("tf")
    out = sess.sql("""
        select substr(s, 7) as tail, replace(s, '0') as gone,
               ltrim('0', s) as lt, rtrim('0', s) as rt
        from tf""").collect()
    r = out.to_pylist()
    assert r[1]["tail"] == "world"
    assert r[0]["gone"] == "x"
    assert r[0]["lt"] == "x0" and r[0]["rt"] == "000x"
    for bad in ("select nvl(s, s, s) from tf",
                "select from_unixtime(1, 'yyyy') from tf",
                "select unix_timestamp(s, 'yyyy') from tf"):
        with pytest.raises(SqlError):
            sess.sql(bad)


def test_sql_pivot_clause():
    """Spark SQL PIVOT clause lowers to GroupedData.pivot with the
    implicit group-by over untouched columns."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "year": [2020, 2020, 2021, 2021],
        "q": ["q1", "q2", "q1", "q1"],
        "amt": [10.0, 20.0, 30.0, 40.0]})).createOrReplaceTempView("sales")
    out = sess.sql("""
        select * from sales
        pivot (sum(amt) for q in ('q1', 'q2'))
        order by year""").collect()
    assert out.column_names == ["year", "q1", "q2"]
    assert out.column("q1").to_pylist() == [10.0, 70.0]
    assert out.column("q2").to_pylist() == [20.0, None]
    # value aliases + multiple aliased aggregates + projection
    out = sess.sql("""
        select year, first_s, first_n from sales
        pivot (sum(amt) as s, count(amt) as n
               for q in ('q1' as first, 'q2' as second))
        order by year""").collect()
    assert out.column("first_s").to_pylist() == [10.0, 70.0]
    assert out.column("first_n").to_pylist() == [1, 2]
    # multiple aggs without aliases are refused
    with pytest.raises(SqlError, match="alias"):
        sess.sql("""select * from sales
                    pivot (sum(amt), count(amt) for q in ('q1'))""")
    # 'pivot' stays usable as an identifier
    sess.create_dataframe(pa.table({"pivot": [1, 2]})
                          ).createOrReplaceTempView("p2")
    assert sess.sql("select pivot from p2 order by pivot"
                    ).collect().column("pivot").to_pylist() == [1, 2]


def test_sql_pivot_aliased_single_agg_and_negative_values():
    """Code review: a value alias must rename the '{value}_{aggAlias}'
    column a single ALIASED aggregate generates, and negative literals
    are valid PIVOT IN values."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "g": [1, 1, 2], "k": [-1, 1, -1], "v": [10.0, 20.0, 30.0]})
    ).createOrReplaceTempView("tp")
    out = sess.sql("""
        select * from tp
        pivot (sum(v) as s for k in (-1 as neg, 1 as pos))
        order by g""").collect()
    assert out.column_names == ["g", "neg_s", "pos_s"]
    assert out.column("neg_s").to_pylist() == [10.0, 30.0]
    assert out.column("pos_s").to_pylist() == [20.0, None]


def test_sql_nulls_ordering_and_ordinals():
    """ORDER BY ... NULLS FIRST/LAST (official TPC-DS texts use it) and
    ordinal positions in ORDER BY / GROUP BY (Spark's
    orderByOrdinal/groupByOrdinal defaults)."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "g": ["a", "a", "b", "b"],
        "v": pa.array([3, None, 1, None], type=pa.int64())})
    ).createOrReplaceTempView("tn")
    out = sess.sql("select v from tn order by v nulls last").collect()
    assert out.column("v").to_pylist() == [1, 3, None, None]
    out = sess.sql("select v from tn order by v desc nulls first").collect()
    assert out.column("v").to_pylist() == [None, None, 3, 1]
    out = sess.sql("select g, sum(v) as sv from tn group by 1 "
                   "order by 2 desc").collect()
    assert out.to_pydict() == {"g": ["a", "b"], "sv": [3, 1]}
    with pytest.raises(SqlError, match="position"):
        sess.sql("select g from tn order by 5")
    with pytest.raises(SqlError, match="position"):
        sess.sql("select g from tn group by 3")


def test_sql_ordinals_in_pre_projection_branch_and_window_nulls():
    """Code review: ordinals must work when another sort key forces the
    pre-projection branch; NULLS ordering works inside window specs; a
    GROUP BY ordinal naming an aggregate is rejected clearly."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "g": ["a", "b", "a"],
        "v": pa.array([5, None, 1], type=pa.int64())})
    ).createOrReplaceTempView("tw")
    # ordinal + non-output key -> pre-projection sort still resolves
    out = sess.sql("select v as w from tw order by v + 0, 1").collect()
    assert out.column("w").to_pylist() == [None, 1, 5]
    out = sess.sql("select g as h, sum(v) as sv from tw group by g "
                   "order by g, 2").collect()
    assert out.to_pydict() == {"h": ["a", "b"], "sv": [6, None]}
    # window spec honors NULLS LAST
    out = sess.sql("select v, row_number() over (order by v nulls last) "
                   "as r from tw order by r").collect()
    assert out.column("v").to_pylist() == [1, 5, None]
    with pytest.raises(SqlError, match="aggregate"):
        sess.sql("select g, sum(v) from tw group by 2")


def test_sql_pivot_on_unaliased_subquery():
    """Advisor (round 4): FROM (subquery) PIVOT (...) without a derived-table
    alias must parse — 'pivot' is a soft keyword, not the alias."""
    import pyarrow as pa
    from spark_rapids_tpu.api.dataframe import TpuSession
    sess = TpuSession()
    sess.create_dataframe(pa.table({
        "year": [2020, 2020, 2021],
        "q": ["q1", "q2", "q1"],
        "amt": [10.0, 20.0, 30.0]})).createOrReplaceTempView("sales")
    out = sess.sql("""
        select * from (select year, q, amt from sales)
        pivot (sum(amt) for q in ('q1', 'q2'))
        order by year""").collect()
    assert out.column_names == ["year", "q1", "q2"]
    assert out.column("q1").to_pylist() == [10.0, 30.0]
    # an aliased subquery still pivots, and a bare unaliased derived table
    # (no pivot) also parses
    out = sess.sql("""
        select * from (select year, q, amt from sales) t
        pivot (sum(amt) for q in ('q1'))
        order by year""").collect()
    assert out.column("q1").to_pylist() == [10.0, 30.0]
    out = sess.sql(
        "select year from (select year from sales) order by year").collect()
    assert out.column("year").to_pylist() == [2020, 2020, 2021]
