"""TPC-H Q1/Q6 correctness: TPU plan vs CPU engine, bit-comparable modulo float
reduction order (tpch_test.py analog)."""
import pyarrow as pa

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF, gen_lineitem, q1, q6
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def test_q1_matches_cpu():
    t = gen_lineitem(scale=0.002, seed=11)  # 12k rows
    assert_tpu_and_cpu_equal(
        lambda s: q1(s.create_dataframe(t)),
        conf=BENCH_CONF,
        approx_float=1e-12,
        # the filter fuses into the aggregation's alive-mask
        expect_tpu_execs=["TpuHashAggregateExec", "TpuSortExec"])


def test_q6_matches_cpu():
    t = gen_lineitem(scale=0.002, seed=12)
    assert_tpu_and_cpu_equal(
        lambda s: q6(s.create_dataframe(t)),
        conf=BENCH_CONF,
        approx_float=1e-12,
        expect_tpu_execs=["TpuHashAggregateExec"])
