"""Shuffle layer tests.

Mirrors the reference's test strategy (SURVEY.md §4): MetaUtilsSuite-style
pack/roundtrip tests, and the mock-cluster shuffle protocol tests
(RapidsShuffleClientSuite / RapidsShuffleIteratorSuite) — multi-executor
behavior exercised in one process by driving the client/server state machines
over the in-process transport, no real network needed.
"""
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
from spark_rapids_tpu.shuffle.codec import (compress_batch, decompress_batch,
                                            get_codec)
from spark_rapids_tpu.shuffle.inprocess import _Fabric
from spark_rapids_tpu.shuffle.manager import (MapOutputTracker, ShuffleEnv,
                                              ShuffleFetchFailedError,
                                              ShuffleManager)
from spark_rapids_tpu.shuffle.table_meta import (DevicePackLayout, TableMeta,
                                                 device_pack, device_unpack,
                                                 layout_to_meta,
                                                 pack_host_batch,
                                                 unpack_host_batch)
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                InflightThrottle)


def sample_table(n=100, seed=0):
    rng = np.random.default_rng(seed)
    vals = rng.integers(0, 1000, n)
    mask = rng.random(n) < 0.1
    ints = pa.array([None if m else int(v) for v, m in zip(vals, mask)],
                    pa.int64())
    floats = pa.array(rng.normal(size=n), pa.float64())
    strs = pa.array([None if i % 13 == 0 else f"row-{i}" for i in range(n)],
                    pa.string())
    flags = pa.array([bool(i % 2) for i in range(n)], pa.bool_())
    return pa.table({"i": ints, "f": floats, "s": strs, "b": flags})


@pytest.fixture(autouse=True)
def fresh_fabric():
    _Fabric.reset()
    yield
    _Fabric.reset()


# ---------------------------------------------------------------------------------
# TableMeta + pack formats
# ---------------------------------------------------------------------------------

def test_host_pack_roundtrip():
    t = sample_table(257)
    hb = HostBatch.from_arrow(t)
    buf, meta = pack_host_batch(hb)
    assert meta.num_rows == 257
    back = unpack_host_batch(buf, meta)
    assert back.to_arrow().equals(hb.to_arrow())


def test_table_meta_wire_roundtrip():
    t = sample_table(50)
    _, meta = pack_host_batch(HostBatch.from_arrow(t))
    again = TableMeta.from_bytes(meta.to_bytes())
    assert again == meta
    assert again.schema == meta.schema


def test_device_pack_matches_host_unpack():
    """Device-packed bytes + layout meta must round-trip through the HOST
    unpack path — that's what makes the wire format tier-independent."""
    t = sample_table(200, seed=3)
    db = DeviceBatch.from_arrow(t)
    smax = int(db.column_by_name("s").data.shape[1])
    layout = DevicePackLayout.for_batch_shape(db.schema, db.capacity, smax)
    packed = device_pack(db, layout)
    meta = layout_to_meta(layout, db.num_rows)
    hb = unpack_host_batch(np.asarray(packed).tobytes(), meta)
    assert hb.to_arrow().equals(db.to_arrow())


def test_device_pack_unpack_on_device():
    t = sample_table(100, seed=7)
    db = DeviceBatch.from_arrow(t)
    smax = int(db.column_by_name("s").data.shape[1])
    layout = DevicePackLayout.for_batch_shape(db.schema, db.capacity, smax)
    back = device_unpack(device_pack(db, layout), layout, db.num_rows)
    assert back.to_arrow().equals(db.to_arrow())


def test_codecs_roundtrip():
    t = sample_table(500)
    buf, meta = pack_host_batch(HostBatch.from_arrow(t))
    for name in ("copy", "zlib"):
        wire, wmeta = compress_batch(buf, meta, get_codec(name))
        if name == "zlib":
            assert wmeta.codec == "zlib" and len(wire) < len(buf)
        raw, rmeta = decompress_batch(wire, wmeta)
        assert rmeta.codec == "copy"
        assert unpack_host_batch(raw, rmeta).to_arrow().equals(
            HostBatch.from_arrow(t).to_arrow())


def test_unknown_codec_rejected():
    with pytest.raises(ValueError, match="unknown shuffle codec"):
        get_codec("lz77")


# ---------------------------------------------------------------------------------
# transport primitives
# ---------------------------------------------------------------------------------

def test_bounce_buffer_pool_blocks_and_reuses():
    mgr = BounceBufferManager("t", 16, 2)
    a, b = mgr.acquire(2)
    assert mgr.try_acquire(1) is None
    done = []

    def later():
        got = mgr.acquire(1, timeout=5)
        done.append(got[0])
        got[0].close()
    th = threading.Thread(target=later)
    th.start()
    a.close()
    th.join(5)
    assert done and mgr.num_free == 1
    b.close()
    assert mgr.num_free == 2


def test_inflight_throttle_fifo():
    th = InflightThrottle(100)
    th.acquire(80)
    order = []

    def want(n, label):
        th.acquire(n)
        order.append(label)
        th.release(n)
    t1 = threading.Thread(target=want, args=(50, "big"))
    t1.start()
    import time
    time.sleep(0.05)
    th.release(80)
    t1.join(5)
    assert order == ["big"]
    # oversized requests clamp rather than deadlock
    th.acquire(10_000)
    th.release(10_000)


# ---------------------------------------------------------------------------------
# end-to-end: two executors, cached write, remote fetch
# ---------------------------------------------------------------------------------

def two_env_cluster(tmp_path, codec="none", conf_overrides=None):
    conf = TpuConf({"spark.rapids.tpu.shuffle.compression.codec": codec,
                    "spark.rapids.tpu.shuffle.bounceBuffers.size": 4096,
                    "spark.rapids.tpu.shuffle.bounceBuffers.count": 8,
                    **(conf_overrides or {})})
    e0 = ShuffleEnv("exec-0", conf, disk_dir=str(tmp_path / "e0"))
    e1 = ShuffleEnv("exec-1", conf, disk_dir=str(tmp_path / "e1"))
    mgr = ShuffleManager()
    return mgr, e0, e1


def write_partitioned(mgr, env, shuffle_id, map_id, table, num_parts):
    """Row i of `table` goes to partition i % num_parts."""
    writer = mgr.get_writer(env, shuffle_id, map_id, num_parts)
    parts = []
    n = table.num_rows
    for p in range(num_parts):
        idx = list(range(p, n, num_parts))
        sub = table.take(idx)
        parts.append((p, DeviceBatch.from_arrow(sub)))
    return writer.write(parts)


def collect_partition(mgr, env, shuffle_id, pid):
    rows = []
    for batch in mgr.get_reader(env, shuffle_id, pid).read():
        rows.append(batch.to_arrow())
    return pa.concat_tables(rows) if rows else None


@pytest.mark.parametrize("codec", ["none", "zlib"])
def test_two_executor_shuffle_roundtrip(tmp_path, codec):
    mgr, e0, e1 = two_env_cluster(tmp_path, codec)
    sid, _ = mgr.register_shuffle(2)
    t0 = sample_table(120, seed=1)
    t1 = sample_table(90, seed=2)
    write_partitioned(mgr, e0, sid, 0, t0, 2)
    write_partitioned(mgr, e1, sid, 1, t1, 2)

    # reducer on exec-0 pulls partition 0: local from e0 + remote from e1
    got = collect_partition(mgr, e0, sid, 0)
    exp_rows = ([t0.take(list(range(0, 120, 2)))] +
                [t1.take(list(range(0, 90, 2)))])
    expected = pa.concat_tables(exp_rows)
    # "f" values are unique normals -> sorting by them aligns full rows
    assert got.sort_by("f").equals(expected.sort_by("f"))

    # reducer on exec-1 pulls partition 1 (remote from e0 + local)
    got1 = collect_partition(mgr, e1, sid, 1)
    exp1 = pa.concat_tables([t0.take(list(range(1, 120, 2))),
                             t1.take(list(range(1, 90, 2)))])
    assert sorted(got1["f"].to_pylist()) == sorted(exp1["f"].to_pylist())


def test_shuffle_serves_spilled_buffers(tmp_path):
    """Map-side cache spills to host; remote fetch must still serve the data
    (BufferSendState acquires from whatever tier holds it)."""
    mgr, e0, e1 = two_env_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(64, seed=5)
    write_partitioned(mgr, e0, sid, 0, t, 1)
    spilled = e0.device_store.spill_to_size(0)   # force everything off-device
    assert spilled > 0
    got = collect_partition(mgr, e1, sid, 0)
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())


def test_reader_early_close_releases_unyielded_buffers(tmp_path):
    """Regression: read() retains EVERY buffer of a local block upfront
    (acquire_buffers); closing the generator mid-block — a LIMIT consumer
    stopping after the first batch — must release the not-yet-yielded
    tail's refcounts too, not just the buffer in hand."""
    mgr, e0, _e1 = two_env_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(30, seed=5)
    # one map task emitting THREE batches for the same (map, partition)
    # block — the multi-row-group repartition shape
    writer = mgr.get_writer(e0, sid, 0, 1)
    writer.write([(0, DeviceBatch.from_arrow(t.slice(i * 10, 10)))
                  for i in range(3)])
    block = mgr.tracker.blocks_by_executor(sid, 0)[e0.executor_id][0]
    probe = e0.shuffle_catalog.acquire_buffers(block)
    assert len(probe) == 3
    bufs = [b for b, _m in probe]
    for b in bufs:
        b.close()
    base = [b.refcount for b in bufs]            # owner-store refs only
    it = mgr.get_reader(e0, sid, 0).read()
    next(it)
    it.close()
    assert [b.refcount for b in bufs] == base


def test_empty_partitions_are_skipped(tmp_path):
    mgr, e0, e1 = two_env_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(4)
    t = sample_table(6, seed=9)
    # all rows land in partitions 0..3 with some empties at higher counts
    writer = mgr.get_writer(e0, sid, 0, 4)
    writer.write([(0, DeviceBatch.from_arrow(t))])  # only partition 0 has data
    assert collect_partition(mgr, e1, sid, 1) is None
    got = collect_partition(mgr, e1, sid, 0)
    assert got.num_rows == 6


def test_multi_chunk_transfer(tmp_path):
    """Buffers larger than one bounce buffer must walk the pool in chunks."""
    conf = TpuConf({"spark.rapids.tpu.shuffle.bounceBuffers.size": 1024,
                    "spark.rapids.tpu.shuffle.bounceBuffers.count": 4})
    e0 = ShuffleEnv("exec-0", conf, disk_dir=str(tmp_path / "e0"))
    e1 = ShuffleEnv("exec-1", conf, disk_dir=str(tmp_path / "e1"))
    mgr = ShuffleManager()
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(2000, seed=11)    # packed size >> 1 KiB
    write_partitioned(mgr, e0, sid, 0, t, 1)
    got = collect_partition(mgr, e1, sid, 0)
    assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())


def test_fetch_failure_surfaces(tmp_path):
    mgr, e0, e1 = two_env_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(1)
    t = sample_table(10)
    write_partitioned(mgr, e0, sid, 0, t, 1)
    # sabotage: remove the shuffle data on e0 but leave tracker metadata
    e0.shuffle_catalog.remove_shuffle(sid)
    with pytest.raises(ShuffleFetchFailedError):
        collect_partition(mgr, e1, sid, 0)


def test_unregister_shuffle_frees_buffers(tmp_path):
    mgr, e0, e1 = two_env_cluster(tmp_path)
    sid, _ = mgr.register_shuffle(2)
    t = sample_table(40)
    write_partitioned(mgr, e0, sid, 0, t, 2)
    assert len(e0.device_store) > 0
    mgr.unregister_shuffle(sid, [e0, e1])
    assert len(e0.device_store) == 0
    assert mgr.tracker.blocks_by_executor(sid, 0) == {}


def test_zstd_codec_roundtrip():
    """zstd codec (beyond the reference's in-repo copy codec): roundtrip
    through compress_batch/decompress_batch with real table bytes."""
    import numpy as np
    import pytest
    pytest.importorskip("zstandard")
    from spark_rapids_tpu.shuffle.codec import get_codec
    codec = get_codec("zstd")
    raw = np.arange(100000, dtype=np.int64).tobytes() + b"tail" * 1000
    comp = codec.compress(raw)
    assert len(comp) < len(raw) // 2
    assert codec.decompress(comp, len(raw)) == raw
