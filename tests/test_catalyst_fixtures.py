"""Golden Catalyst plan fixtures through the plan-rewrite engine.

Round-4 VERDICT item 9 (Plugin.scala:36-44 coupling surface): hand-authored
Spark-3.0-shaped physical plans — EnsureRequirements sort artifacts, SMJ,
partial/final aggregates, AQE stage wrappers, reused exchanges — load via
plan/catalyst_import.py onto cpu_execs and run through TpuOverrides, with
tag / convert / fallback decisions asserted, including the exchange-reuse
consistency case (RapidsMeta.scala:443 analog)."""
import json
import os

import pytest

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.exchange_execs import (CpuReusedExchangeExec,
                                                   CpuShuffleExchangeExec,
                                                   TpuReusedExchangeExec,
                                                   TpuShuffleExchangeExec)
from spark_rapids_tpu.execs.join_execs import (CpuSortMergeJoinExec,
                                               TpuBroadcastHashJoinExec,
                                               TpuShuffledHashJoinExec)
from spark_rapids_tpu.plan.catalyst_import import load_plan
from spark_rapids_tpu.plan.overrides import TpuOverrides

FIXTURES = os.path.join(os.path.dirname(__file__), "catalyst_fixtures")


def _load(name):
    with open(os.path.join(FIXTURES, name)) as f:
        return load_plan(json.load(f))


def _apply(plan, **conf):
    ov = TpuOverrides(TpuConf({
        "spark.rapids.tpu.sql.enabled": "true",
        # float aggregates gate on order-dependence like the reference;
        # enabled here so fixtures exercise conversion, with the gate
        # itself covered by test_exprs/test_hash_group
        "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
        **conf}))
    return ov.apply(plan), ov


def _nodes(plan):
    yield plan
    for c in plan.children:
        yield from _nodes(c)


def _names(plan):
    return [type(n).__name__ for n in _nodes(plan)]


def test_scan_filter_project_agg_chain_converts():
    out, ov = _apply(_load("scan_filter_project_agg.json"))
    names = _names(out)
    # the filter+project FUSE into the partial aggregate (whole-stage
    # fusion claims the fold as a FusedAggregateStageExec; fuse_device_ops
    # produces the same fold when fusion is off), so the converted chain is
    # agg/exchange/agg/scan, fully on-device
    for want in ("TpuHashAggregateExec", "TpuShuffleExchangeExec",
                 "TpuParquetScanExec"):
        assert want in names, (want, names)
    aggs = [n for n in names
            if n in ("TpuHashAggregateExec", "FusedAggregateStageExec")]
    assert len(aggs) == 2, names
    assert not any(n.startswith("Cpu") for n in names), names
    assert "will run on TPU" in ov.last_explain


def test_smj_replaced_by_hash_join_sorts_dropped():
    out, _ = _apply(_load("smj_with_sorts.json"))
    names = _names(out)
    assert "TpuShuffledHashJoinExec" in names
    # the EnsureRequirements join-key sorts vanish with the SMJ
    # (GpuSortMergeJoinExec behavior)
    assert not any("Sort" in n for n in names), names
    assert "TpuShuffleExchangeExec" in names


def test_smj_stays_cpu_when_replacement_disabled():
    out, ov = _apply(
        _load("smj_with_sorts.json"),
        **{"spark.rapids.tpu.sql.replaceSortMergeJoin.enabled": "false"})
    assert any(isinstance(n, CpuSortMergeJoinExec) for n in _nodes(out))
    assert "sort-merge join replacement is disabled" in ov.last_explain
    # children below the fallback join still convert (partial subtrees)
    assert "TpuShuffleExchangeExec" in _names(out)


def test_smj_left_semi_converts_with_left_only_output():
    out, _ = _apply(_load("smj_left_semi.json"))
    joins = [n for n in _nodes(out)
             if isinstance(n, TpuShuffledHashJoinExec)]
    assert len(joins) == 1 and joins[0].how == "left_semi"
    assert [f.name for f in joins[0].output] == ["k", "v"]


def test_broadcast_join_converts_to_tpu_pair():
    out, _ = _apply(_load("broadcast_join.json"))
    names = _names(out)
    assert "TpuBroadcastHashJoinExec" in names
    assert "TpuBroadcastExchangeExec" in names


def test_reused_exchange_converts_with_referent():
    out, _ = _apply(_load("reused_exchange.json"))
    reused = [n for n in _nodes(out) if isinstance(n, TpuReusedExchangeExec)]
    assert len(reused) == 1
    # the reused copy reads a CONVERTED referent, not the CPU node
    assert isinstance(reused[0].referent, TpuShuffleExchangeExec)


def test_reused_exchange_referent_gets_transitions():
    """Code review (round 5): the reused subtree must receive the same
    transition fixes as the main branch — a host-only referent child needs
    a HostToDeviceExec below the device exchange on BOTH copies."""
    out, _ = _apply(
        _load("reused_exchange.json"),
        # force the scan to stay host-side: the exchange's child is then a
        # CPU node and every device exchange needs a transition under it
        **{"spark.rapids.tpu.sql.exec.ParquetScan": "false"})
    exchanges = [n for n in _nodes(out)
                 if isinstance(n, TpuShuffleExchangeExec)]
    for ex in exchanges:
        child = ex.children[0]
        if type(child).__name__.startswith("PipelinedExec"):
            # the transfer pipeline may wrap the transition (insert_pipeline);
            # the transition itself must still be there underneath
            child = child.children[0]
        assert type(child).__name__ == "HostToDeviceExec", _names(out)


def test_reused_exchange_consistency_forces_pair_to_cpu():
    """RapidsMeta.scala:443: when the reused copy cannot convert, the
    (otherwise convertible) original must not convert either."""
    out, ov = _apply(
        _load("reused_exchange.json"),
        **{"spark.rapids.tpu.sql.exec.ReusedExchange": "false"})
    assert any(isinstance(n, CpuReusedExchangeExec) for n in _nodes(out))
    assert not any(isinstance(n, TpuShuffleExchangeExec)
                   for n in _nodes(out)), _names(out)
    assert any(isinstance(n, CpuShuffleExchangeExec) for n in _nodes(out))
    assert "exchange reuse consistency" in ov.last_explain


def test_aqe_stage_wrappers_dissolve_and_convert():
    out, _ = _apply(_load("aqe_stage_wrappers.json"))
    names = _names(out)
    assert "CpuQueryStageExec" not in names
    assert "TpuShuffleExchangeExec" in names
    assert "TpuHashAggregateExec" in names


def test_disabled_expression_causes_partial_fallback():
    out, ov = _apply(
        _load("project_mult.json"),
        **{"spark.rapids.tpu.sql.expression.Multiply": "false"})
    names = _names(out)
    assert "CpuProjectExec" in names          # falls back on the expr
    assert "TpuParquetScanExec" in names      # the scan still converts
    assert "disabled by spark.rapids.tpu.sql.expression.Multiply" \
        in ov.last_explain


def test_union_limit_converts():
    out, _ = _apply(_load("union_limit.json"))
    names = _names(out)
    assert "TpuLimitExec" in names
    assert "TpuUnionExec" in names


def test_importer_rejects_unknown_shapes():
    from spark_rapids_tpu.plan.catalyst_import import CatalystImportError
    with pytest.raises(CatalystImportError, match="unsupported plan class"):
        load_plan([{"class": "x.y.MysteryExec", "num-children": 0}])
    with pytest.raises(CatalystImportError, match="reuses"):
        load_plan([{"class": "x.exchange.ReusedExchangeExec",
                    "num-children": 0}])
