"""Whole-stage fusion (plan/fusion.py + execs/fused_execs.py): chain
collapse, bit-identity against the unfused path, the WholeStageCodegen-style
plan rendering, encoded-domain survival inside a fused stage, program-cache
routing, and the variableFloatAgg CPU-fallback gate."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.execs.fused_execs import (FUSED_BATCHES_SAVED,
                                                FUSED_OPS,
                                                FusedAggregateStageExec,
                                                FusedStageExec)
from spark_rapids_tpu.plan.fusion import (fused_batches_not_materialized,
                                          fused_stages, fusion_stats)
from spark_rapids_tpu.testing import assert_tables_equal

_CONF = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"}
_OFF = {**_CONF, "spark.rapids.tpu.sql.fusion.enabled": "false"}


def _table(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 7, n).astype(np.int64)),
        "v": pa.array(np.round(rng.uniform(0, 100, n), 3)),
        "w": pa.array(rng.integers(-50, 50, n).astype(np.int32)),
        "s": pa.array(np.array(["red", "green", "blue", "teal"])[
            rng.integers(0, 4, n)]),
    })


def _chain(sess):
    df = sess.create_dataframe(_table())
    return (df.filter(F.col("v") > 20.0)
              .select((F.col("v") * 2.0).alias("v2"), "k", "s",
                      (F.col("w") + 1).alias("w1"))
              .filter(F.col("w1") != 0))


def test_chain_collapses_and_is_bit_identical():
    on, off = TpuSession(_CONF), TpuSession(_OFF)
    got = _chain(on).collect()
    ref = _chain(off).collect()
    assert got.equals(ref)                 # bit-identity, order included
    stages = fused_stages(on.last_plan)
    assert len(stages) == 1 and isinstance(stages[0], FusedStageExec)
    assert len(stages[0].fused_ops) == 3   # filter + project + filter
    assert not fused_stages(off.last_plan)
    # the interior batches never materialized: 2 per input batch
    assert fused_batches_not_materialized(on.last_plan) >= 2


def test_tree_string_renders_star_stage_ids():
    sess = TpuSession(_CONF)
    _chain(sess).collect()
    text = sess.last_plan.tree_string()
    assert "*(1) TpuFilterExec" in text, text
    assert "*(1) TpuProjectExec" in text, text
    # stats agree with the rendered plan
    stats = fusion_stats(sess.last_plan)
    assert stats["fused_stages"] == 1 and stats["fused_ops"] == 3


def test_aggregate_fold_is_a_fused_stage_and_bit_identical():
    def q(sess):
        df = sess.create_dataframe(_table())
        return (df.filter(F.col("v") > 50.0)
                  .groupBy("k")
                  .agg(F.sum("v").alias("sv"),
                       F.count(F.lit(1)).alias("c"))
                  .sort("k"))
    on, off = TpuSession(_CONF), TpuSession(_OFF)
    got, ref = q(on).collect(), q(off).collect()
    # the unfused path folds through fuse_device_ops with IDENTICAL
    # expression trees, so this is bitwise equality, floats included
    assert got.equals(ref)
    stages = fused_stages(on.last_plan)
    assert len(stages) == 1 and isinstance(stages[0], FusedAggregateStageExec)
    assert "*(1) TpuHashAggregateExec" in on.last_plan.tree_string()
    assert "TpuFilterExec" not in on.last_plan.tree_string()
    assert stages[0].metrics[FUSED_OPS].value == 2       # filter + agg
    assert stages[0].metrics[FUSED_BATCHES_SAVED].value >= 1


def test_expand_chain_fuses_per_projection_variants():
    def q(sess):
        df = sess.create_dataframe(_table())
        return (df.filter(F.col("v") > 30.0)
                  .rollup("k", "s")
                  .agg(F.sum("v").alias("sv"),
                       F.count(F.lit(1)).alias("c")))
    on, off = TpuSession(_CONF), TpuSession(_OFF)
    got, ref = q(on).collect(), q(off).collect()
    assert_tables_equal(ref, got, ignore_order=True)
    stages = fused_stages(on.last_plan)
    # the Expand + the filter below it fuse into one multi-variant stage
    # (the rollup aggregate above consumes the variants)
    chain = [s for s in stages if isinstance(s, FusedStageExec)]
    assert chain, on.last_plan.tree_string()
    assert len(chain[0].variants) == 3     # (k,s), (k,null), (null,null)
    assert all(pred is not None for _, pred in chain[0].variants)


def test_fusion_disabled_by_conf():
    sess = TpuSession(_OFF)
    _chain(sess).collect()
    assert not fused_stages(sess.last_plan)
    assert "*(" not in sess.last_plan.tree_string()


def test_max_ops_splits_long_chains():
    sess = TpuSession({**_CONF, "spark.rapids.tpu.sql.fusion.maxOps": "2"})
    got = _chain(sess).collect()
    ref = _chain(TpuSession(_OFF)).collect()
    assert got.equals(ref)
    stages = fused_stages(sess.last_plan)
    assert stages and all(len(s.fused_ops) <= 2 for s in stages)


def test_float_agg_fallback_gating_respected():
    """Satellite regression (memory gotcha): a float-aggregate chain must
    NOT land on the device path — fused or not — unless variableFloatAgg is
    enabled; without this assert a fused-agg test can silently exercise the
    CPU engine and test nothing."""
    def q(sess):
        df = sess.create_dataframe(_table())
        return (df.filter(F.col("v") > 50.0).groupBy("k")
                  .agg(F.sum("v").alias("sv")).sort("k"))

    gated = TpuSession({"spark.rapids.tpu.sql.fusion.enabled": "true"})
    out_gated = q(gated).collect()
    plan = gated.last_plan.tree_string()
    assert not fused_stages(gated.last_plan), plan
    assert "TpuHashAggregateExec" not in plan, plan
    assert "CpuHashAggregateExec" in plan, plan

    allowed = TpuSession(_CONF)
    out_allowed = q(allowed).collect()
    assert any(isinstance(s, FusedAggregateStageExec)
               for s in fused_stages(allowed.last_plan)), \
        allowed.last_plan.tree_string()
    assert_tables_equal(out_gated, out_allowed, approx_float=1e-9)


def test_nondeterministic_exprs_break_the_chain():
    def q(sess):
        df = sess.create_dataframe(_table())
        return (df.filter(F.col("v") > 20.0)
                  .select("k", F.rand(42).alias("r"))
                  .filter(F.col("k") >= 0))
    sess = TpuSession(_CONF)
    q(sess).collect()
    # the rand() projection must not be substituted into anything
    for s in fused_stages(sess.last_plan):
        assert "TpuProjectExec" not in [n for n, _ in s.fused_ops]


def test_fused_stage_keeps_encoded_domain_predicate(tmp_path):
    """An encoded-eligible predicate inside a fused stage keeps running on
    dictionary indices (PR 4 composition)."""
    import pyarrow.parquet as pq
    from spark_rapids_tpu.utils import metrics as um
    t = _table(6000)
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=2000)

    def q(sess):
        df = sess.read.parquet(path)
        return (df.filter(F.col("s") == "red")
                  .select("k", (F.col("v") + 1.0).alias("v1"), "s"))

    def run(extra):
        sess = TpuSession({**_CONF, **extra,
                           "spark.rapids.tpu.sql.scanCache.enabled": "false"})
        before = um.TRANSFER_METRICS.snapshot()
        out = q(sess).collect()
        after = um.TRANSFER_METRICS.snapshot()
        ops = (after[um.TRANSFER_ENCODED_DOMAIN_OPS]
               - before[um.TRANSFER_ENCODED_DOMAIN_OPS])
        return out, ops, sess

    enc, enc_ops, sess = run({})
    stages = fused_stages(sess.last_plan)
    assert stages and stages[0].encoded_domain_ok
    assert enc_ops >= 1
    dec, dec_ops, _ = run(
        {"spark.rapids.tpu.sql.encodedDomain.enabled": "false"})
    assert dec_ops == 0
    assert enc.equals(dec)
    unfused, _, _ = run({"spark.rapids.tpu.sql.fusion.enabled": "false"})
    assert enc.equals(unfused)


def test_fused_programs_hit_the_program_cache_on_repeat():
    """Repeat submission of the same fused plan shape must be all hits —
    the fused plan-signature keys route through the serving ProgramCache."""
    from spark_rapids_tpu.serving.program_cache import global_program_cache
    sess = TpuSession(_CONF)
    df = _chain(sess)
    ref = df.collect()                      # compiles the fused programs
    cache = global_program_cache()
    before = cache.snapshot_counters()
    out = df.collect()
    after = cache.snapshot_counters()
    assert out.equals(ref)
    assert after["hits"] - before["hits"] >= 1
    assert after["misses"] - before["misses"] == 0


def _manual_env():
    """(conf, multi-batch device source exec, bound refs) for hand-built
    plan tests."""
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.columnar.transfer import upload_table_conf
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.execs.base import LeafExec
    from spark_rapids_tpu.exprs.core import BoundReference

    conf = TpuConf(_CONF)
    full = _table(3000)
    parts = [full.slice(0, 1000), full.slice(1000, 1000),
             full.slice(2000, 1000)]
    batches = [upload_table_conf(p, 16, conf) for p in parts]

    class _Source(LeafExec):
        is_device = True

        def execute(self, ctx):
            yield from batches

    src = _Source(batches[0].schema)
    k = BoundReference(0, DType.LONG, True, "k")
    v = BoundReference(1, DType.DOUBLE, True, "v")
    return conf, src, k, v


def _run_plan(plan, conf):
    from spark_rapids_tpu.execs.base import ExecContext
    ctx = ExecContext(conf)
    return pa.concat_tables([b.to_arrow() for b in plan.execute(ctx)])


def test_coalesce_above_expand_refuses_to_fuse():
    """Coalesce + Expand don't compose: unfused interleaves variant batches
    per ARRIVING batch while a concat-first fused stage would emit
    per-variant over the combined input — same rows, different order, and
    the contract is bit-identity order included. The pass must leave the
    chain unfused."""
    from spark_rapids_tpu.columnar.dtypes import Schema
    from spark_rapids_tpu.execs import tpu_execs as te
    from spark_rapids_tpu.execs.expand_execs import TpuExpandExec
    from spark_rapids_tpu.plan.fusion import fuse_stages

    conf, src, k, v = _manual_env()
    two_col = Schema(src.output.fields[:2])     # (k, v)
    chain = te.TpuCoalesceBatchesExec(
        TpuExpandExec(((k, v), (k, v)), src, two_col), target_bytes=1)
    ref = _run_plan(chain, conf)
    out = fuse_stages(chain, conf)
    assert not fused_stages(out), out.tree_string()
    assert _run_plan(out, conf).equals(ref)


def test_require_single_coalesce_above_filter_refuses_to_fuse():
    """A require_single coalesce concats exactly what reaches it; moving it
    below a selective filter would concat the RAW input into one HBM batch.
    The pass must refuse rather than regress peak memory."""
    from spark_rapids_tpu.execs import tpu_execs as te
    from spark_rapids_tpu.exprs.literals import Literal
    from spark_rapids_tpu.exprs.predicates import GreaterThan
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.plan.fusion import fuse_stages

    conf, src, k, v = _manual_env()
    chain = te.TpuCoalesceBatchesExec(
        te.TpuFilterExec(GreaterThan(v, Literal(90.0, DType.DOUBLE)), src),
        require_single=True)
    ref = _run_plan(chain, conf)
    out = fuse_stages(chain, conf)
    assert not fused_stages(out), out.tree_string()
    assert _run_plan(out, conf).equals(ref)

    # require_single BELOW the chain (nothing under it to distort) fuses
    below = te.TpuFilterExec(
        GreaterThan(v, Literal(90.0, DType.DOUBLE)),
        te.TpuProjectExec((k, v),
                          te.TpuCoalesceBatchesExec(src,
                                                    require_single=True)))
    fused = fuse_stages(below, conf)
    assert isinstance(fused, FusedStageExec), fused.tree_string()
    assert fused.coalesce == (1 << 31, True)
    assert _run_plan(fused, conf).equals(_run_plan(below, conf))


def test_coalesce_in_chain_and_multi_batch_input():
    """A manual multi-batch plan: Project -> Coalesce -> Filter fuses and
    matches the unfused execution batch-for-content."""
    from spark_rapids_tpu.columnar.dtypes import DType
    from spark_rapids_tpu.execs import tpu_execs as te
    from spark_rapids_tpu.exprs.arithmetic import Multiply
    from spark_rapids_tpu.exprs.literals import Literal
    from spark_rapids_tpu.exprs.misc import Alias
    from spark_rapids_tpu.exprs.predicates import GreaterThan
    from spark_rapids_tpu.plan.fusion import fuse_stages

    conf, src, k, v = _manual_env()
    chain = te.TpuProjectExec(
        (Alias(Multiply(v, Literal(3.0, DType.DOUBLE)), "v3"), Alias(k, "k")),
        te.TpuCoalesceBatchesExec(
            te.TpuFilterExec(GreaterThan(v, Literal(10.0, DType.DOUBLE)),
                             src),
            target_bytes=1))

    ref = _run_plan(chain, conf)
    fused = fuse_stages(chain, conf)
    assert isinstance(fused, FusedStageExec) and fused.coalesce is not None
    assert len(fused.fused_ops) == 3
    # only the Filter's interior output is elided — the coalesce concat
    # batch still materializes as the stage input and must not count
    assert fused.saved_per_batch == 1
    got = _run_plan(fused, conf)
    assert got.equals(ref)
    # target_bytes=1 flushes each of the 3 source batches individually
    assert fused.metrics[FUSED_BATCHES_SAVED].value == 3
