"""Distributed (mesh) execution of window / expand / generate / writes /
range partitioning — the operators the round-2 VERDICT flagged as gathering
to a single device. Every test asserts the Mesh* exec really ran (plan-shape
check) AND that results match the CPU engine."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, Window
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import (assert_tables_equal,
                                      assert_tpu_and_cpu_equal)

MESH_CONF = {
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}


def _rand_table(n=4000, seed=11):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 37, n).astype(np.int32),
        "b": rng.integers(0, 3, n).astype(np.int32),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
        "s": pa.array([f"row{int(i)}" for i in rng.integers(0, 50, n)]),
    })


def test_mesh_window_rank_and_agg(eight_devices):
    t = _rand_table()
    w = Window.partitionBy("k").orderBy("v")
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "k", "v", "s",
            F.row_number().over(w).alias("rn"),
            F.rank().over(w).alias("rk"),
            F.sum("v").over(w).alias("running")),
        conf=MESH_CONF, ignore_order=True,
        expect_tpu_execs=["MeshWindowExec"])


def test_mesh_window_multi_part_keys(eight_devices):
    t = _rand_table(seed=5)
    w = Window.partitionBy("k", "b").orderBy("v", "s")
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "k", "b", "v",
            F.avg("v").over(w).alias("ra"),
            F.lag("v", 1).over(w).alias("pv")),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshWindowExec"])


def test_unpartitioned_window_gathers(eight_devices):
    """No partition keys -> one global frame: must run single-device behind a
    gather (Spark's single-partition requirement), and still match."""
    t = _rand_table(800, seed=3)
    w = Window.orderBy("v")
    cpu = assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "v", F.row_number().over(w).alias("rn")),
        conf=MESH_CONF, ignore_order=True)
    assert cpu.num_rows == 800


def test_mesh_expand_rollup(eight_devices):
    t = _rand_table()
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).rollup("k", "b").agg(
            F.sum("v").alias("sv"), F.count("v").alias("cv")),
        conf=MESH_CONF, ignore_order=True,
        expect_tpu_execs=["MeshExpandExec"])


def test_mesh_expand_cube_strings(eight_devices):
    t = _rand_table(seed=19)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).cube("s", "b").agg(
            F.min("v").alias("mv"), F.max("s").alias("ms")),
        conf=MESH_CONF, ignore_order=True,
        expect_tpu_execs=["MeshExpandExec"])


def test_mesh_generate_explode(eight_devices):
    t = _rand_table(1200, seed=7)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).select(
            "k", F.explode(F.array(F.col("v"), F.col("v") * 2,
                                   F.lit(None))).alias("e")),
        conf=MESH_CONF, ignore_order=True,
        expect_tpu_execs=["MeshGenerateExec"])


def test_mesh_range_partition_sort(eight_devices):
    """Global sort on the mesh = sampled range repartition + local sort; the
    repartition must be a mesh exchange, not a gather."""
    t = _rand_table(6000, seed=23)
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).sort("v", "k"),
        conf=MESH_CONF,
        expect_tpu_execs=["MeshSortExec"])


def test_mesh_write_parquet_roundtrip(tmp_path, eight_devices):
    t = _rand_table(3000, seed=29)
    path = str(tmp_path / "out_parquet")
    s = TpuSession(MESH_CONF)
    df = s.create_dataframe(t)
    stats = df.write.mode("overwrite").parquet(path)
    assert stats is not None and stats.num_rows == 3000
    # one part file per non-empty shard (distributed write, not a gather)
    assert stats.num_files > 1
    back = TpuSession().read.parquet(path).collect()
    assert_tables_equal(t, back, ignore_order=True)


def test_mesh_write_partitioned_csv(tmp_path, eight_devices):
    t = _rand_table(500, seed=31)
    path = str(tmp_path / "out_csv")
    s = TpuSession(MESH_CONF)
    stats = s.create_dataframe(t).write.mode("overwrite") \
        .partitionBy("b").csv(path)
    assert stats is not None and stats.num_rows == 500
    back = TpuSession().read.csv(path).collect()
    assert back.num_rows == 500


def test_mesh_write_plan_shape(tmp_path, eight_devices):
    """The write plan must lower to MeshWriteFilesExec (no gather)."""
    t = _rand_table(1000, seed=37)
    path = str(tmp_path / "plan_parquet")
    s = TpuSession(MESH_CONF)
    s.create_dataframe(t).write.mode("overwrite").parquet(path)
    plan_str = s.last_plan.tree_string() if s.last_plan else ""
    assert "MeshWriteFilesExec" in plan_str, plan_str
    assert "MeshGatherExec" not in plan_str, plan_str


# ---------------------------------------------------------- mesh aggregation
def test_mesh_agg_high_cardinality_repartition(eight_devices):
    """~50k distinct keys > aggRepartitionThreshold: the partial buffers must
    hash-repartition over ICI and merge per shard (no replicated blowup), and
    still match the CPU engine exactly."""
    rng = np.random.default_rng(41)
    n = 60000
    t = pa.table({
        "k": rng.integers(0, 50000, n).astype(np.int64),
        "v": rng.integers(-100, 100, n).astype(np.int64),
    })
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("k").agg(
            F.sum("v").alias("sv"), F.count("v").alias("cv"),
            F.min("v").alias("mn")),
        conf={**MESH_CONF,
              "spark.rapids.tpu.sql.mesh.aggRepartitionThreshold": "1024"},
        ignore_order=True,
        expect_tpu_execs=["MeshHashAggregateExec"])


def test_mesh_agg_repartition_with_strings_and_nulls(eight_devices):
    rng = np.random.default_rng(43)
    n = 8000
    keys = [None if i % 97 == 0 else f"key_{int(i)}"
            for i in rng.integers(0, 3000, n)]
    t = pa.table({
        "k": pa.array(keys),
        "v": rng.standard_normal(n),
    })
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("k").agg(
            F.avg("v").alias("av"), F.count(F.lit(1)).alias("c")),
        conf={**MESH_CONF,
              "spark.rapids.tpu.sql.mesh.aggRepartitionThreshold": "64",
              "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"},
        ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshHashAggregateExec"])


def test_mesh_post_agg_stays_distributed(eight_devices):
    """Group-by output feeds a filter+sort: those must run as mesh execs now
    (the round-2 VERDICT flagged post-agg dropping to single-device)."""
    rng = np.random.default_rng(47)
    n = 20000
    t = pa.table({
        "k": rng.integers(0, 5000, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).groupBy("k").agg(
            F.sum("v").alias("sv")).filter(F.col("sv") > 300)
            .sort("sv", "k"),
        conf={**MESH_CONF,
              "spark.rapids.tpu.sql.mesh.aggRepartitionThreshold": "1024"},
        expect_tpu_execs=["MeshHashAggregateExec", "MeshFilterExec",
                          "MeshSortExec"])


def test_mesh_global_agg_no_keys(eight_devices):
    t = pa.table({"v": np.arange(10000, dtype=np.int64)})
    assert_tpu_and_cpu_equal(
        lambda s: s.create_dataframe(t).agg(
            F.sum("v").alias("s"), F.count("v").alias("c"),
            F.max("v").alias("m")),
        conf=MESH_CONF,
        expect_tpu_execs=["MeshHashAggregateExec"])


# ---------------------------------------------------------- shard-local scan
def _write_parts(tmp_path, n_files=6, rows=1500, seed=53, fmt="parquet"):
    import pyarrow.parquet as pq
    import pyarrow.orc as po_orc
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        t = pa.table({
            "k": rng.integers(0, 100, rows).astype(np.int64),
            "v": rng.standard_normal(rows),
            "s": pa.array([f"f{i}_{int(x)}" for x in
                           rng.integers(0, 30, rows)]),
        })
        p = str(tmp_path / f"part-{i}.{fmt}")
        if fmt == "parquet":
            pq.write_table(t, p)
        else:
            po_orc.write_table(t, p)
        paths.append(p)
    return str(tmp_path)


def test_mesh_parquet_scan_shard_local(tmp_path, eight_devices):
    """Multi-file parquet scan on the mesh must read shard-local (plan shows
    MeshFileScatterExec, no driver-side concat) and match the CPU engine."""
    d = _write_parts(tmp_path)
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"}) \
        .read.parquet(d).collect()
    s = TpuSession(MESH_CONF)
    out = s.read.parquet(d).groupBy("k").agg(
        F.sum("v").alias("sv"), F.count("s").alias("c")).collect()
    plan_str = s.last_plan.tree_string()
    assert "MeshFileScatterExec" in plan_str, plan_str
    cpu_agg = TpuSession({"spark.rapids.tpu.sql.enabled": "false"}) \
        .read.parquet(d).groupBy("k").agg(
            F.sum("v").alias("sv"), F.count("s").alias("c")).collect()
    assert_tables_equal(cpu_agg, out, ignore_order=True, approx_float=1e-9)
    assert cpu.num_rows == 9000


def test_mesh_orc_scan_shard_local(tmp_path, eight_devices):
    d = _write_parts(tmp_path, n_files=4, rows=700, seed=59, fmt="orc")
    s = TpuSession(MESH_CONF)
    out = s.read.orc(d).select(
        "k", (F.col("v") * 2).alias("v2")).collect()
    plan_str = s.last_plan.tree_string()
    assert "MeshFileScatterExec" in plan_str, plan_str
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"}) \
        .read.orc(d).select("k", (F.col("v") * 2).alias("v2")).collect()
    assert_tables_equal(cpu, out, ignore_order=True, approx_float=1e-9)


def test_mesh_parquet_scan_with_pruning_filter(tmp_path, eight_devices):
    """Row-group pruning changes per-file metadata counts; the shard-local
    read must still size its shards exactly."""
    import pyarrow.parquet as pq
    rng = np.random.default_rng(61)
    for i in range(3):
        t = pa.table({"k": np.arange(i * 1000, (i + 1) * 1000,
                                     dtype=np.int64),
                      "v": rng.standard_normal(1000)})
        pq.write_table(t, str(tmp_path / f"p{i}.parquet"),
                       row_group_size=250)
    s = TpuSession(MESH_CONF)
    out = s.read.parquet(str(tmp_path)).filter(F.col("k") >= 2600) \
        .collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"}) \
        .read.parquet(str(tmp_path)).filter(F.col("k") >= 2600).collect()
    assert_tables_equal(cpu, out, ignore_order=True, approx_float=1e-9)
    assert out.num_rows == 400


def test_mesh_csv_scan_falls_back_to_scatter(tmp_path, eight_devices):
    """CSV has no metadata counts: the mesh scan still works through the
    read-then-scatter fallback."""
    import csv as _csv
    for i in range(3):
        with open(tmp_path / f"c{i}.csv", "w", newline="") as fh:
            w = _csv.writer(fh)
            w.writerow(["a", "b"])
            for j in range(50):
                w.writerow([i * 100 + j, f"s{j}"])
    s = TpuSession(MESH_CONF)
    out = s.read.option("header", "true").csv(str(tmp_path)).collect()
    assert out.num_rows == 150


# ---------------------------------------------------------- AQE on the mesh
def _iter_plan(node):
    yield node
    for c in node.children:
        yield from _iter_plan(c)


def test_mesh_adaptive_broadcast_switch(eight_devices):
    """Plan-time estimates say 'big build side' (shuffled join); at runtime
    the filtered build materializes tiny — with AQE on, the mesh join must
    switch to the broadcast form from the OBSERVED size and still match."""
    rng = np.random.default_rng(67)
    n = 30000
    fact = pa.table({"k": rng.integers(0, 2000, n).astype(np.int64),
                     "v": rng.integers(0, 100, n).astype(np.int64)})
    dim = pa.table({
        "k": np.arange(2000, dtype=np.int64),
        # wide payload so the plan-time size estimate exceeds the threshold
        "pad": pa.array(["x" * 200] * 2000),
        "grp": pa.array([int(i % 7) for i in range(2000)],
                        type=pa.int64()),
    })

    def q(s):
        d = s.create_dataframe(dim).filter(F.col("grp") == 3) \
             .select("k", "grp")
        return s.create_dataframe(fact).join(d, "k") \
                .groupBy("grp").agg(F.sum("v").alias("sv"))

    threshold = str(64 * 1024)  # 64 KB: over the filtered build, under dim
    base = {**MESH_CONF,
            "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": threshold}
    s = TpuSession({**base, "spark.rapids.tpu.sql.adaptive.enabled": "true"})
    out = q(s).collect()
    joins = [nd for nd in _iter_plan(s.last_plan)
             if type(nd).__name__ == "MeshShuffledHashJoinExec"]
    assert joins, s.last_plan.tree_string()
    assert any(j.adapted_broadcast for j in joins), (
        "AQE should have switched the small observed build to broadcast")
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = q(cpu).collect()
    assert_tables_equal(exp, out, ignore_order=True)

    # same query, AQE off: no switch
    s2 = TpuSession({**base,
                     "spark.rapids.tpu.sql.adaptive.enabled": "false"})
    out2 = q(s2).collect()
    joins2 = [nd for nd in _iter_plan(s2.last_plan)
              if type(nd).__name__ == "MeshShuffledHashJoinExec"]
    assert joins2 and not any(j.adapted_broadcast for j in joins2)
    assert_tables_equal(exp, out2, ignore_order=True)


def test_mesh_adaptive_right_join_switch(eight_devices):
    """Broadcasting the LEFT side (legal for right joins) also adapts."""
    rng = np.random.default_rng(71)
    # big at plan time (~800 KB estimate -> shuffled join), tiny at runtime
    # after the filter (~8 KB observed -> adaptive broadcast-left)
    left = pa.table({"k": np.arange(4000, dtype=np.int64),
                     "pad": pa.array(["y" * 200] * 4000)})
    big = pa.table({"k": rng.integers(0, 40, 20000).astype(np.int64),
                    "v": rng.integers(0, 9, 20000).astype(np.int64)})

    def q(s):
        l = s.create_dataframe(left).filter(F.col("k") < 40)
        return l.join(s.create_dataframe(big), "k", "right") \
                .groupBy("k").agg(F.count("v").alias("c"))

    conf = {**MESH_CONF,
            "spark.rapids.tpu.sql.adaptive.enabled": "true",
            "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "100000"}
    s = TpuSession(conf)
    out = q(s).collect()
    joins = [nd for nd in _iter_plan(s.last_plan)
             if type(nd).__name__ == "MeshShuffledHashJoinExec"]
    assert joins and any(j.adapted_broadcast for j in joins), (
        "the broadcast-left (bi==0) adaptive path should have fired")
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    assert_tables_equal(q(cpu).collect(), out, ignore_order=True)
