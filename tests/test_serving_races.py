"""Regression tests for the data races R012 found and this PR fixed.

Each test hammers one fixed race from concurrent threads and asserts the
invariant the fix restored. They are regression DOCUMENTATION as much as
detection: the static gate (tests/test_analysis.py::
test_r012_real_package_clean) is what proves the locksets; these prove
the locked code still behaves under real contention — the circuit
breaker's single-trial claim, the metrics dict surviving concurrent
snapshots, the registration ledger staying consistent, the wire stream
surviving cancel-vs-next, the TCP rpc/peer tables under load.
"""
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.serving.client import (QueryServiceClient,
                                             WireQueryError)
from spark_rapids_tpu.serving.health import (BREAKER_CLOSED, BREAKER_OPEN,
                                             CircuitBreaker)
from spark_rapids_tpu.serving.lifecycle import QueryHandle
from spark_rapids_tpu.serving.server import QueryServer
from spark_rapids_tpu.shuffle.tcp import TcpTransport
from spark_rapids_tpu.shuffle.transport import TransactionStatus
from spark_rapids_tpu.utils import metrics as um

BASE_CONF = {
    "spark.rapids.tpu.sql.string.maxBytes": "16",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}


def _run_threads(fns):
    errors = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:   # noqa: BLE001 - surfaced by assert
            errors.append(e)

    ts = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errors, errors
    return errors


# ------------------------------------------------- circuit breaker (PR 14)
def test_breaker_opens_once_under_concurrent_failures():
    """8 threads hammer record_failure on a CLOSED breaker: exactly ONE
    transition to OPEN (one serving.breaker_opens bump), never several —
    the consecutive-failure counter and state flip share one lock."""
    br = CircuitBreaker(threshold=4, backoff_ms=10_000.0, key="x")
    before = um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value
    barrier = threading.Barrier(8)

    def fail():
        barrier.wait(5)
        for _ in range(50):
            br.record_failure()

    _run_threads([fail] * 8)
    assert br.snapshot()["state"] == BREAKER_OPEN
    assert um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].value - before == 1
    assert br.snapshot()["opens"] == 1


def test_breaker_single_half_open_trial():
    """Once the OPEN backoff elapses, concurrent probe_due callers race
    for the HALF_OPEN trial: exactly one wins the claim; the rest are
    refused until the trial reports."""
    br = CircuitBreaker(threshold=1, backoff_ms=0.0, key="y")
    br.record_failure()                  # -> OPEN, probe due immediately
    now = time.monotonic() + 1.0
    wins = []
    barrier = threading.Barrier(8)

    def probe():
        barrier.wait(5)
        if br.probe_due(now):
            wins.append(threading.get_ident())

    _run_threads([probe] * 8)
    assert len(wins) == 1, wins
    # the losing callers also must not have flipped anything: still
    # HALF_OPEN with the single trial in flight, zero submissions pass
    assert br.snapshot()["state"] == "HALF_OPEN"
    assert not br.allow_submit()


def test_breaker_probe_thread_racing_submit_threads():
    """The PR 14 shape end-to-end: submit threads drive failures and
    successes through CLOSED->OPEN->HALF_OPEN while a probe thread runs
    the trial schedule. Invariants: an OPEN breaker passes zero
    submissions, every transition lands in a legal state, and the final
    successful trial closes it."""
    br = CircuitBreaker(threshold=3, backoff_ms=1.0, seed=7, key="z")
    stop = threading.Event()
    illegal = []

    def submitter():
        while not stop.is_set():
            snap = br.snapshot()
            if snap["state"] not in ("CLOSED", "OPEN", "HALF_OPEN"):
                illegal.append(snap)
            if br.allow_submit():
                # a passed submission reports its outcome (mostly bad,
                # so the breaker keeps flipping under the prober)
                br.record_failure()
            time.sleep(0)

    def prober():
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if br.probe_due():
                if br.snapshot()["state"] != "HALF_OPEN":
                    illegal.append(br.snapshot())
                br.record_failure()      # failed trial: deeper backoff
            time.sleep(0.001)
        stop.set()

    _run_threads([submitter, submitter, submitter, prober])
    assert not illegal, illegal
    # one real probe success closes it from wherever it stands
    while not br.probe_due():
        time.sleep(0.001)
    br.record_success()
    assert br.snapshot()["state"] == BREAKER_CLOSED
    assert br.allow_submit()


# --------------------------------------------- handle metrics (scheduler)
def test_handle_metrics_writers_vs_concurrent_snapshots():
    """Pre-fix, admission/scheduler wrote handle.metrics keys without the
    handle lock while snapshot() iterated it under the lock — a growing
    dict iterated mid-resize raises RuntimeError. note_metric/metric
    route every cross-thread touch through the lock."""
    h = QueryHandle("SELECT 1")
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            h.note_metric(f"k{i % 64}", i)
            h.count_program(hit=bool(i % 2))
            i += 1

    def reader():
        for _ in range(150):
            snap = h.snapshot()
            assert snap["query_id"] == h.query_id
            h.metric("k1")
        stop.set()

    _run_threads([writer, writer, reader])


def test_set_tenant_weight_racing_stats_and_push():
    """Pre-fix, _push_weights_to_semaphore iterated the weight table
    while set_tenant_weight resized it under the cv."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    sess = TpuSession(BASE_CONF)
    sched = sess.scheduler
    DeviceManager.initialize(sess.conf)   # so the push actually iterates

    def setter(base):
        for i in range(80):
            sched.set_tenant_weight(f"t{base}-{i % 17}", 1.0 + i % 3)

    def pusher():
        for _ in range(80):
            sched._push_weights_to_semaphore()
            sched.stats()

    _run_threads([lambda: setter(0), lambda: setter(1), pusher])
    assert sched.stats()["weights"]


# ------------------------------------------------ wire serving (PR 12/14)
def _serve(extra=None, n=6000, partitions=3):
    sess = TpuSession({**BASE_CONF, **(extra or {})})
    rng = np.random.default_rng(11)
    df = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 8, n).astype("int64"),
        "v": rng.random(n)})).repartition(partitions)
    df.createOrReplaceTempView("t")
    server = QueryServer(sess)
    host, port = server.address
    return sess, server, f"{host}:{port}"


@pytest.mark.slow
def test_registered_ledger_concurrent_register_and_submit():
    """register_table from several threads while others submit: the
    per-replica registration ledger (a plain set, now mutated only under
    the client lock) ends complete and every query succeeds."""
    sess, server, addr = _serve()
    client = QueryServiceClient([addr], TpuConf(BASE_CONF))
    tables = {f"extra{i}": pa.table({"x": [i, i + 1]}) for i in range(4)}
    try:
        def register(name):
            def go():
                client.register_table(name, tables[name])
            return go

        def submit():
            got = client.submit(
                "SELECT k, sum(v) AS s FROM t GROUP BY k").result()
            assert got.num_rows > 0

        _run_threads([register(n) for n in tables] + [submit] * 3)
        st = client.replica_states()[0]
        assert set(tables) <= st.registered
        for name in tables:
            got = client.submit(f"SELECT x FROM {name}").result()
            assert got.num_rows == 2
    finally:
        client.close()
        server.shutdown()
        sess.scheduler.shutdown(wait=False)


@pytest.mark.slow
def test_cancel_racing_stream_next():
    """Client cancel races the serve.next poll: pre-fix _drop_query
    cleared the slice list without the stream lock while the poll popped
    it. The hammer asserts no crash and a fully-drained server table."""
    sess, server, addr = _serve(
        extra={"spark.rapids.tpu.serving.net.maxStreamBatchRows": "2"})
    client = QueryServiceClient([addr], TpuConf({
        **BASE_CONF,
        "spark.rapids.tpu.serving.failover.enabled": "false"}))
    try:
        for _ in range(6):
            h = client.submit("SELECT k, v FROM t WHERE v > 0.2")
            it = h.batches()
            next(it)                      # stream running

            def consume():
                try:
                    for _b in it:
                        pass
                except (WireQueryError, RuntimeError):
                    pass                  # cancelled underneath us: fine

            def cancel():
                try:
                    h.cancel()
                except WireQueryError:
                    pass                  # already gone: fine

            _run_threads([consume, cancel])
        deadline = time.time() + 10
        while server._queries and time.time() < deadline:
            time.sleep(0.05)
        assert not server._queries
    finally:
        client.close()
        server.shutdown()
        sess.scheduler.shutdown(wait=False)


# --------------------------------------------------- tcp transport (PR 2)
def test_tcp_rpc_table_under_concurrent_requests(tmp_path):
    """Caller threads insert rpcs while reader threads pop completions
    and the peer-lost sweep iterates — all through _rpc_lock now. After
    a kill, new requests fail with an error instead of hanging."""
    conf = TpuConf({
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg"),
        "spark.rapids.tpu.shuffle.maxRetries": "0",
        "spark.rapids.tpu.shuffle.connectTimeout": "5",
    })
    a = TcpTransport("races-a", conf)
    b = TcpTransport("races-b", conf)
    try:
        b.server.register_request_handler(
            "echo", lambda peer, payload: payload)
        conn = a.connect("races-b")

        def hammer(tag):
            for i in range(40):
                payload = f"{tag}:{i}".encode()
                tx = conn.request("echo", payload, lambda t: None)
                tx.wait(10)
                assert tx.status is TransactionStatus.SUCCESS
                assert tx.response == payload

        _run_threads([lambda: hammer(0), lambda: hammer(1),
                      lambda: hammer(2), lambda: hammer(3)])
        b.kill()
        # the reader observes the death, sweeps the rpc table and evicts
        # the peer atomically (the check-then-act the peers lock guards);
        # a fresh connect() then re-dials and fails fast, never hangs
        deadline = time.time() + 10
        while a._peer_by_id("races-b") is not None and \
                time.time() < deadline:
            time.sleep(0.02)
        assert a._peer_by_id("races-b") is None
        with pytest.raises(ConnectionError):
            a.connect("races-b")
    finally:
        a.shutdown()
        b.shutdown()
