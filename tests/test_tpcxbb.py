"""TPCx-BB queries: TPU engine vs CPU engine (tpcxbb_test.py /
TpcxbbLikeSpark analog — the reference's headline benchmark suite)."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all
from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES, UNSUPPORTED
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

# queries whose sort keys can tie (or that have no ordering) -> unordered
_TIES = {"q5", "q7", "q9", "q11", "q14", "q16", "q17", "q21", "q22", "q24"}

_MIN_ROWS = {"q1": 1, "q2": 1, "q3": 1, "q4": 1, "q5": 10, "q6": 1, "q7": 1,
             "q8": 2, "q9": 1, "q10": 10, "q11": 1, "q12": 1,
             "q13": 1, "q14": 1, "q15": 1, "q16": 1, "q17": 1, "q18": 1,
             "q19": 1, "q20": 10, "q21": 1, "q22": 1, "q23": 1, "q24": 1,
             "q25": 10, "q26": 1, "q27": 10, "q28": 10, "q29": 1, "q30": 1}


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=0)


def test_query_inventory_covers_all_30():
    """The reference runs 19 of 30 and throws for the rest
    (TpcxbbLikeSpark.scala:785-2069); this engine runs all 30 — the
    UDTF/UDF/python queries re-expressed with engine primitives."""
    assert len(QUERIES) == 30
    assert UNSUPPORTED == ()


# q15's least-squares slope (n*Σxy - Σx*Σy over date_sk^2-scale terms) is
# catastrophic-cancellation-prone, so engine-order differences surface earlier
_APPROX = {"q15": 1e-6}


@pytest.mark.parametrize("qname", sorted(QUERIES, key=lambda n: int(n[1:])))
def test_tpcxbb_query_matches_cpu(qname, tables):
    cpu = assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qname](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=BENCH_CONF,
        ignore_order=qname in _TIES,
        approx_float=_APPROX.get(qname, 1e-9))
    assert cpu.num_rows >= _MIN_ROWS.get(qname, 0), (
        f"{qname} returned {cpu.num_rows} rows; the generator no longer "
        f"qualifies rows for its predicates")
