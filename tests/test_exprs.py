"""Expression semantics tests: golden Spark behaviors + CPU (numpy eager) vs
device (jitted XLA) parity — the analog of the reference's ProjectExprSuite and
the pytest arithmetic/cmp/logic/conditionals/string/date_time files."""
import datetime

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DeviceBatch
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.execs.evaluator import eval_exprs_device, eval_exprs_host
from spark_rapids_tpu.exprs import (Abs, Add, Alias, And, AtLeastNNonNulls, CaseWhen,
                                    Cast, Ceil, Coalesce, Concat, Contains, DateAdd,
                                    DateDiff, DayOfMonth, DayOfWeek, Divide, EndsWith,
                                    EqualNullSafe, EqualTo, Floor, GreaterThan, Hour,
                                    If, In, IntegralDivide, IsNan, IsNotNull, IsNull,
                                    LastDay, Length, LessThan, Like, Literal, Log,
                                    Lower, Month, Multiply, NaNvl, Not, Or, Pmod, Pow,
                                    Remainder, ShiftLeft, ShiftRightUnsigned, Sqrt,
                                    StartsWith, StringTrim, Substring, Subtract,
                                    UnaryMinus, Upper, Year, bind_expression)
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.testing import assert_tables_equal

col = lambda n: __import__("spark_rapids_tpu.exprs", fromlist=["UnresolvedAttribute"]).UnresolvedAttribute(n)
lit = Literal.of


def run_both(table: pa.Table, *exprs, smax=64):
    """Evaluate exprs on CPU and device; assert identical; return CPU result."""
    from spark_rapids_tpu.columnar.dtypes import Schema
    schema = Schema.from_pa(table.schema)
    bound = tuple(bind_expression(e, schema) for e in exprs)
    hb = HostBatch.from_arrow(table, smax)
    cpu = eval_exprs_host(bound, hb, smax).to_arrow()
    db = DeviceBatch.from_arrow(table, smax)
    dev = eval_exprs_device(bound, db, smax).to_arrow()
    assert_tables_equal(cpu, dev)
    return cpu


def vals(t: pa.Table, i: int = 0):
    return t.column(i).to_pylist()


def test_arithmetic_null_propagation():
    t = pa.table({"a": pa.array([1, None, 3], type=pa.int32()),
                  "b": pa.array([10, 20, None], type=pa.int32())})
    out = run_both(t, Add(col("a"), col("b")), Subtract(col("a"), col("b")),
                   Multiply(col("a"), col("b")))
    assert vals(out, 0) == [11, None, None]
    assert vals(out, 1) == [-9, None, None]
    assert vals(out, 2) == [10, None, None]


def test_int_overflow_wraps_like_java():
    t = pa.table({"a": pa.array([2**31 - 1, -2**31], type=pa.int32())})
    out = run_both(t, Add(col("a"), lit(1)), Subtract(col("a"), lit(1)))
    assert vals(out, 0) == [-2**31, -2**31 + 1]
    assert vals(out, 1) == [2**31 - 2, 2**31 - 1]


def test_divide_semantics():
    t = pa.table({"a": pa.array([7, 7, -7, None], type=pa.int32()),
                  "b": pa.array([2, 0, 2, 2], type=pa.int32())})
    out = run_both(t, Divide(col("a"), col("b")),
                   IntegralDivide(col("a"), col("b")),
                   Remainder(col("a"), col("b")),
                   Pmod(col("a"), col("b")))
    assert vals(out, 0) == [3.5, None, -3.5, None]   # x/0 -> null, double result
    assert vals(out, 1) == [3, None, -3, None]       # trunc toward zero
    assert vals(out, 2) == [1, None, -1, None]       # Java % sign
    assert vals(out, 3) == [1, None, 1, None]        # pmod non-negative


def test_double_divide_by_zero_is_null():
    t = pa.table({"a": pa.array([1.0, -1.0, 0.0], type=pa.float64())})
    out = run_both(t, Divide(col("a"), lit(0.0)))
    assert vals(out) == [None, None, None]


def test_remainder_float():
    t = pa.table({"a": pa.array([7.5, -7.5], type=pa.float64())})
    out = run_both(t, Remainder(col("a"), lit(2.0)))
    assert vals(out) == [1.5, -1.5]


def test_comparisons_and_nan():
    nan = float("nan")
    t = pa.table({"a": pa.array([1.0, nan, 2.0, None], type=pa.float64()),
                  "b": pa.array([1.0, nan, nan, 1.0], type=pa.float64())})
    out = run_both(t, EqualTo(col("a"), col("b")), LessThan(col("a"), col("b")),
                   GreaterThan(col("a"), col("b")))
    assert vals(out, 0) == [True, True, False, None]   # NaN = NaN is true
    assert vals(out, 1) == [False, False, True, None]  # NaN greater than all
    assert vals(out, 2) == [False, False, False, None]


def test_kleene_and_or():
    t = pa.table({"a": pa.array([True, True, False, None, None]),
                  "b": pa.array([None, False, None, None, True])})
    out = run_both(t, And(col("a"), col("b")), Or(col("a"), col("b")))
    assert vals(out, 0) == [None, False, False, None, None]
    assert vals(out, 1) == [True, True, None, None, True]


def test_null_predicates():
    t = pa.table({"a": pa.array([1.0, None, float("nan")], type=pa.float64())})
    out = run_both(t, IsNull(col("a")), IsNotNull(col("a")), IsNan(col("a")))
    assert vals(out, 0) == [False, True, False]
    assert vals(out, 1) == [True, False, True]
    assert vals(out, 2) == [False, False, True]  # isnan(null) = false


def test_equal_null_safe():
    t = pa.table({"a": pa.array([1, None, None], type=pa.int64()),
                  "b": pa.array([1, 1, None], type=pa.int64())})
    out = run_both(t, EqualNullSafe(col("a"), col("b")))
    assert vals(out) == [True, False, True]


def test_in_semantics():
    t = pa.table({"a": pa.array([1, 2, None], type=pa.int32())})
    out = run_both(t, In(col("a"), (lit(1), lit(5))),
                   In(col("a"), (lit(1), Literal(None, DType.INT))))
    assert vals(out, 0) == [True, False, None]
    assert vals(out, 1) == [True, None, None]  # null in list: non-match -> null


def test_conditional():
    t = pa.table({"a": pa.array([1, 5, None], type=pa.int32())})
    out = run_both(
        t,
        If(GreaterThan(col("a"), lit(2)), lit(100), lit(-100)),
        CaseWhen(((EqualTo(col("a"), lit(1)), lit(10)),
                  (EqualTo(col("a"), lit(5)), lit(50))), lit(0)),
        CaseWhen(((EqualTo(col("a"), lit(1)), lit(10)),), None))
    assert vals(out, 0) == [-100, 100, -100]  # null pred -> else
    assert vals(out, 1) == [10, 50, 0]
    assert vals(out, 2) == [10, None, None]


def test_coalesce_nanvl():
    t = pa.table({"a": pa.array([None, 2.0, float("nan")], type=pa.float64()),
                  "b": pa.array([1.0, None, 7.0], type=pa.float64())})
    out = run_both(t, Coalesce((col("a"), col("b"))), NaNvl(col("a"), col("b")),
                   AtLeastNNonNulls(1, (col("a"),)))
    cv = vals(out, 0)
    assert cv[0] == 1.0 and cv[1] == 2.0 and np.isnan(cv[2])  # NaN is non-null
    nv = vals(out, 1)
    assert nv[0] is None and nv[1] == 2.0 and nv[2] == 7.0
    assert vals(out, 2) == [False, True, False]  # NaN doesn't count


def test_math_golden():
    t = pa.table({"a": pa.array([4.0, -1.0, 0.0], type=pa.float64())})
    out = run_both(t, Sqrt(col("a")), Log(col("a")), Pow(col("a"), lit(2.0)))
    sq = vals(out, 0)
    assert sq[0] == 2.0 and np.isnan(sq[1]) and sq[2] == 0.0
    assert vals(out, 1) == [np.log(4.0), None, None]  # log(<=0) -> null
    assert vals(out, 2) == [16.0, 1.0, 0.0]


def test_floor_ceil_to_long():
    t = pa.table({"a": pa.array([1.5, -1.5, 2.0], type=pa.float64())})
    out = run_both(t, Floor(col("a")), Ceil(col("a")))
    assert out.schema.field(0).type == pa.int64()
    assert vals(out, 0) == [1, -2, 2]
    assert vals(out, 1) == [2, -1, 2]


def test_unary_minus_abs():
    t = pa.table({"a": pa.array([5, -5, None], type=pa.int32())})
    out = run_both(t, UnaryMinus(col("a")), Abs(col("a")))
    assert vals(out, 0) == [-5, 5, None]
    assert vals(out, 1) == [5, 5, None]


def test_bitwise_shifts():
    t = pa.table({"a": pa.array([1, -8], type=pa.int32())})
    out = run_both(t, ShiftLeft(col("a"), lit(33)),   # Java masks: << 1
                   ShiftRightUnsigned(col("a"), lit(1)))
    assert vals(out, 0) == [2, -16]
    assert vals(out, 1) == [0, 2147483644]


def test_cast_matrix():
    t = pa.table({"d": pa.array([1.9, -1.9, float("nan"), 1e10], type=pa.float64()),
                  "l": pa.array([2**35 + 7, -1, 300, None], type=pa.int64())})
    out = run_both(t, Cast(col("d"), DType.INT), Cast(col("l"), DType.INT),
                   Cast(col("l"), DType.BYTE), Cast(col("d"), DType.BOOLEAN))
    assert vals(out, 0) == [1, -1, 0, 2**31 - 1]      # trunc, NaN->0, saturate
    assert vals(out, 1) == [7, -1, 300, None]          # long->int wraps low bits
    assert vals(out, 2) == [7, -1, 44, None]           # wrap to byte
    assert vals(out, 3) == [True, True, True, True]    # != 0 (NaN != 0)


def test_cast_int_to_string():
    t = pa.table({"l": pa.array([0, -1, 123456789012345, -2**63, None],
                                type=pa.int64())})
    out = run_both(t, Cast(col("l"), DType.STRING))
    assert vals(out) == ["0", "-1", "123456789012345", "-9223372036854775808", None]


def test_cast_bool_to_string():
    t = pa.table({"b": pa.array([True, False, None])})
    out = run_both(t, Cast(col("b"), DType.STRING))
    assert vals(out) == ["true", "false", None]


def test_cast_datetime():
    t = pa.table({"ts": pa.array([86_400_000_000 + 3_600_000_000, -1],
                                 type=pa.timestamp("us", tz="UTC"))})
    out = run_both(t, Cast(col("ts"), DType.DATE), Cast(col("ts"), DType.LONG))
    assert vals(out, 0) == [datetime.date(1970, 1, 2), datetime.date(1969, 12, 31)]
    assert vals(out, 1) == [90000, -1]  # floor seconds


def test_string_predicates():
    t = pa.table({"s": pa.array(["hello world", "Hello", "", None, "say hell no"])})
    out = run_both(t, StartsWith(col("s"), lit("hell")),
                   EndsWith(col("s"), lit("o")),
                   Contains(col("s"), lit("hell")))
    assert vals(out, 0) == [True, False, False, None, False]
    assert vals(out, 1) == [False, True, False, None, True]
    assert vals(out, 2) == [True, False, False, None, True]


def test_string_compare_ordering():
    t = pa.table({"a": pa.array(["apple", "b", "", "abc"]),
                  "b": pa.array(["apricot", "a", "a", "abc"])})
    out = run_both(t, LessThan(col("a"), col("b")), EqualTo(col("a"), col("b")))
    assert vals(out, 0) == [True, False, True, False]
    assert vals(out, 1) == [False, False, False, True]


def test_upper_lower_length():
    t = pa.table({"s": pa.array(["MiXeD", "héllo", None])})
    out = run_both(t, Upper(col("s")), Lower(col("s")), Length(col("s")))
    assert vals(out, 0) == ["MIXED", "HéLLO", None]  # ascii-only case map
    assert vals(out, 1) == ["mixed", "héllo", None]
    assert vals(out, 2) == [5, 5, None]  # char length, not bytes


def test_substring_spark_semantics():
    t = pa.table({"s": pa.array(["hello", "héllo", "ab"])})
    out = run_both(t, Substring(col("s"), lit(2), lit(3)),
                   Substring(col("s"), lit(-2), lit(2)),
                   Substring(col("s"), lit(0), lit(2)))
    assert vals(out, 0) == ["ell", "éll", "b"]
    assert vals(out, 1) == ["lo", "lo", "ab"]
    assert vals(out, 2) == ["he", "hé", "ab"]  # pos 0 behaves like 1


def test_concat_trim():
    t = pa.table({"a": pa.array(["foo", None, "  pad  "]),
                  "b": pa.array(["bar", "x", "y"])})
    out = run_both(t, Concat((col("a"), col("b"))), StringTrim(col("a")))
    assert vals(out, 0) == ["foobar", None, "  pad  y"]
    assert vals(out, 1) == ["foo", None, "pad"]


def test_like_patterns():
    t = pa.table({"s": pa.array(["hello", "help", "shell", "hell"])})
    out = run_both(t, Like(col("s"), lit("hell%")), Like(col("s"), lit("%ell")),
                   Like(col("s"), lit("%ell%")), Like(col("s"), lit("hell")))
    assert vals(out, 0) == [True, False, False, True]
    assert vals(out, 1) == [False, False, True, True]
    assert vals(out, 2) == [True, False, True, True]
    assert vals(out, 3) == [False, False, False, True]


def test_datetime_parts():
    t = pa.table({"d": pa.array([datetime.date(2020, 2, 29), datetime.date(1969, 12, 31),
                                 datetime.date(1600, 3, 1)], type=pa.date32())})
    out = run_both(t, Year(col("d")), Month(col("d")), DayOfMonth(col("d")),
                   DayOfWeek(col("d")), LastDay(col("d")))
    assert vals(out, 0) == [2020, 1969, 1600]
    assert vals(out, 1) == [2, 12, 3]
    assert vals(out, 2) == [29, 31, 1]
    assert vals(out, 3) == [7, 4, 4]  # sat, wed, wed (1=sunday..7=saturday)
    assert vals(out, 4) == [datetime.date(2020, 2, 29), datetime.date(1969, 12, 31),
                            datetime.date(1600, 3, 31)]


def test_date_arith_and_hour():
    t = pa.table({"d": pa.array([datetime.date(2020, 1, 31)], type=pa.date32()),
                  "ts": pa.array([3_600_000_000 * 30 + 123], type=pa.timestamp("us", tz="UTC"))})
    out = run_both(t, DateAdd(col("d"), lit(1)), DateDiff(col("d"), lit(datetime.date(2020, 1, 1))),
                   Hour(col("ts")))
    assert vals(out, 0) == [datetime.date(2020, 2, 1)]
    assert vals(out, 1) == [30]
    assert vals(out, 2) == [6]  # 30h mod 24


def test_alias_not():
    t = pa.table({"a": pa.array([True, False, None])})
    out = run_both(t, Alias(Not(col("a")), "neg"))
    assert out.column_names == ["neg"]
    assert vals(out) == [False, True, None]


def test_if_with_string_literal_branches():
    # regression: scalar string branches must broadcast against a column condition
    t = pa.table({"a": pa.array([1, 5, None], type=pa.int32())})
    out = run_both(t, If(GreaterThan(col("a"), lit(2)), lit("big"), lit("small")),
                   CaseWhen(((IsNull(col("a")), lit("none")),), lit("some")))
    assert vals(out, 0) == ["small", "big", "small"]
    assert vals(out, 1) == ["some", "some", "none"]


def test_coalesce_widens_and_null_literal():
    # regression (code review): coalesce must widen to the common type and accept
    # a NULL-typed first operand
    t = pa.table({"a": pa.array([None, 7], type=pa.int32())})
    out = run_both(t, Coalesce((col("a"), lit(2**40))),
                   Coalesce((Literal(None, DType.NULL), col("a"))))
    assert vals(out, 0) == [2**40, 7]
    assert vals(out, 1) == [None, 7]


def test_nanvl_null_left_stays_null():
    # regression (code review): NaNvl is null-intolerant on the left even when the
    # invalid slot's garbage data is NaN
    t = pa.table({"a": pa.array([None, float("nan")], type=pa.float64()),
                  "b": pa.array([float("nan"), 1.0], type=pa.float64())})
    out = run_both(t, NaNvl(Add(col("a"), col("b")), lit(9.0)))
    assert vals(out) == [None, 9.0]


def test_if_null_branch():
    t = pa.table({"a": pa.array([1, 5], type=pa.int32())})
    out = run_both(t, If(GreaterThan(col("a"), lit(2)), Literal(None, DType.NULL),
                         col("a")),
                   CaseWhen(((GreaterThan(col("a"), lit(2)),
                              Literal(None, DType.NULL)),), col("a")))
    assert vals(out, 0) == [1, None]
    assert vals(out, 1) == [1, None]
