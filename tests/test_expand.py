"""Expand / rollup / cube tests (reference: ExpandExecSuite.scala +
hash_aggregate_test.py rollup cases)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def data():
    return pa.table({
        "a": ["x", "x", "y", "y", None],
        "b": pa.array([1, 2, 1, 1, 1], type=pa.int32()),
        "v": pa.array([10, 20, 30, 40, 50], type=pa.int64()),
    })


def test_rollup_golden():
    s = TpuSession()
    out = s.create_dataframe(data()).rollup("a", "b").agg(
        F.sum("v").alias("s")).collect()
    rows = {(r["a"], r["b"]): r["s"] for r in out.to_pylist()}
    # full detail
    assert rows[("x", 1)] == 10 and rows[("x", 2)] == 20
    assert rows[("y", 1)] == 70
    # real null key stays distinct from rolled-up subtotals
    assert rows[(None, 1)] == 50
    # per-a subtotals (b rolled up)
    assert rows[("x", None)] == 30 and rows[("y", None)] == 70
    # grand total
    assert rows[(None, None)] == 150
    # rollup of (a=None detail) -> (None, None) subtotal for a=None
    # Spark emits a (null, null) row for BOTH the a=None subtotal and the grand
    # total; they collapse only if gid matched — ours keeps them distinct rows
    total_rows = [r for r in out.to_pylist()
                  if r["a"] is None and r["b"] is None]
    assert sorted(r["s"] for r in total_rows) == [50, 150]
    assert out.num_rows == 8


def test_cube_golden():
    s = TpuSession()
    out = s.create_dataframe(data()).cube("a", "b").agg(
        F.count("v").alias("c")).collect()
    # cube adds per-b subtotals on top of rollup
    rows = [r for r in out.to_pylist() if r["a"] is None and r["b"] == 1]
    # (None-as-group, b=1): count of all b=1 rows = 4; (a=None real, b=1) = 1
    assert sorted(r["c"] for r in rows) == [1, 4]


def test_rollup_parity_tpu():
    import numpy as np
    rng = np.random.default_rng(3)
    n = 400
    t = pa.table({
        "a": rng.integers(0, 4, n).astype(np.int32),
        "b": rng.integers(0, 3, n).astype(np.int32),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })

    def build(s):
        return s.create_dataframe(t).rollup("a", "b").agg(
            F.sum("v").alias("s"), F.count("v").alias("c"),
            F.min("v").alias("mn"))
    assert_tpu_and_cpu_equal(build, ignore_order=True,
                             expect_tpu_execs=["TpuExpandExec"])


def test_cube_parity_tpu():
    import numpy as np
    rng = np.random.default_rng(4)
    n = 200
    t = pa.table({
        "a": rng.integers(0, 3, n).astype(np.int64),
        "b": [None if x == 0 else str(x) for x in rng.integers(0, 3, n)],
        "v": rng.normal(size=n),
    })

    def build(s):
        return s.create_dataframe(t).cube("a", "b").agg(
            F.count("v").alias("c"), F.max("v").alias("mx"))
    assert_tpu_and_cpu_equal(
        build, ignore_order=True,
        conf={"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"})
