"""RAW SQL in, DISTRIBUTED execution out: TPC-DS SQL text through the
frontend with the mesh enabled — the full reference pipeline analog
(Catalyst parses TpcdsLikeSpark's SQL and the plugin distributes the
physical plan; here sql/ parses, plan/mesh_rewrite distributes).

A representative spread of shapes (star join, correlated avg subquery,
CTE chains, rollup+rank, cumulative windows, anti joins, full outer) —
the full 99 run distributed from their DataFrame forms in
test_tpcds_mesh.py and as SQL single-device in test_tpcds_sql.py; this
module pins the COMPOSITION."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_sql import SQL_QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

MESH_CONF = {
    **BENCH_CONF,
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.adaptive.enabled": "true",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
}

#: shape spread: q3 star join, q1 correlated avg, q2 CTE+union+ratio,
#: q18 rollup, q47 windows+self-join, q51 cumulative frames, q69 anti,
#: q82 distinct+semi, q88 8-way cross of scalar counts, q97 full outer
_QUERIES = ("q3", "q1", "q2", "q18", "q47", "q51", "q69", "q82", "q88",
            "q97")


_RAN = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_cache_clear():
    yield
    _RAN["n"] += 1
    if _RAN["n"] % 4 == 0:
        import gc

        import jax
        jax.clear_caches()
        from spark_rapids_tpu.execs import evaluator, tpu_execs
        if hasattr(tpu_execs, "_JIT_CACHE"):
            tpu_execs._JIT_CACHE.clear()
        evaluator._JIT_CACHE.clear()
        gc.collect()


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=0)


def _sql_df(tables, qname):
    def build(s):
        for name, tab in tables.items():
            s.create_dataframe(tab).createOrReplaceTempView(name)
        return s.sql(SQL_QUERIES[qname])
    return build


@pytest.mark.parametrize("qname", _QUERIES)
def test_tpcds_sql_on_mesh_matches_cpu(qname, tables, eight_devices):
    assert_tpu_and_cpu_equal(_sql_df(tables, qname), conf=MESH_CONF,
                             ignore_order=True, approx_float=1e-6)


def test_sql_rollup_really_distributes(tables, eight_devices):
    """The SQL-built rollup must lower to the mesh breadth operators, not
    silently gather to one device."""
    assert_tpu_and_cpu_equal(
        _sql_df(tables, "q18"), conf=MESH_CONF, ignore_order=True,
        approx_float=1e-6,
        expect_tpu_execs=["MeshExpandExec", "MeshHashAggregateExec"])
