"""Fused-vs-unfused bit-identity sweep over the full TPC-DS (99) and
TPCx-BB (30) query sets at CPU smoke scale: every query must collect the
SAME result with sql.fusion.enabled on and off, and the sweep reports (and
bounds from below) how many queries actually got >= 1 fused stage — fusion
coverage as a number, not an anecdote (ROADMAP item 5 rider)."""
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all as gen_tpcds
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES as TPCDS
from spark_rapids_tpu.benchmarks.tpcxbb_data import gen_all as gen_tpcxbb
from spark_rapids_tpu.benchmarks.tpcxbb_queries import QUERIES as TPCXBB
from spark_rapids_tpu.plan.fusion import fused_stages, fusion_stats
from spark_rapids_tpu.testing import assert_tables_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

_CONF = {
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.hasNans": "false",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
}

#: queries whose final sort keys can tie -> unordered compare (same set the
#: SQL-frontend sweep uses, tests/test_tpcds_sql.py)
_TIES = {"q19", "q27", "q34", "q42", "q46", "q52", "q55", "q65", "q68",
         "q73", "q79", "q88", "q96", "q15", "q26", "q7", "q21", "q25",
         "q29", "q37", "q82", "q90", "q92", "q93", "q50", "q62", "q99",
         "q3", "q43", "q48", "q84", "q61", "q32", "q41", "q45", "q20",
         "q12", "q98", "q33", "q56", "q60", "q6", "q67"}

_ALL = ([("tpcds", q) for q in sorted(TPCDS, key=lambda s: int(s[1:]))]
        + [("tpcxbb", q) for q in sorted(TPCXBB, key=lambda s: int(s[1:]))])

#: suite -> query -> fused stage count, filled by the parametrized sweep and
#: summarized by test_zz_fusion_coverage_summary (runs last: pytest keeps
#: definition order and the sweep is defined first)
_COVERAGE = {}

_RAN = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_cache_clear():
    """129 query pairs compile hundreds of XLA programs in one module (the
    test_tpcds_sql.py heap-pressure discipline)."""
    yield
    _RAN["n"] += 1
    if _RAN["n"] % 6 == 0:
        import jax
        jax.clear_caches()
        from spark_rapids_tpu.execs import evaluator, tpu_execs
        tpu_execs._JIT_CACHE.clear()
        evaluator._JIT_CACHE.clear()


@pytest.fixture(scope="module")
def sessions():
    fused = TpuSession(_CONF)
    unfused = TpuSession({**_CONF,
                          "spark.rapids.tpu.sql.fusion.enabled": "false"})
    tpcds = gen_tpcds(_SCALE, seed=0)
    tpcxbb = gen_tpcxbb(scale=_SCALE, seed=0)
    dfs = {
        "tpcds": ({k: fused.create_dataframe(v) for k, v in tpcds.items()},
                  {k: unfused.create_dataframe(v)
                   for k, v in tpcds.items()}),
        "tpcxbb": ({k: fused.create_dataframe(v)
                    for k, v in tpcxbb.items()},
                   {k: unfused.create_dataframe(v)
                    for k, v in tpcxbb.items()}),
    }
    return fused, unfused, dfs


@pytest.mark.parametrize("suite,qname", _ALL,
                         ids=[f"{s}-{q}" for s, q in _ALL])
def test_fused_vs_unfused_identity(sessions, suite, qname):
    fused_sess, unfused_sess, dfs = sessions
    query = (TPCDS if suite == "tpcds" else TPCXBB)[qname]
    fused_dfs, unfused_dfs = dfs[suite]
    got = query(fused_dfs).collect()
    n_stages = fusion_stats(fused_sess.last_plan)["fused_stages"]
    ref = query(unfused_dfs).collect()
    assert not fused_stages(unfused_sess.last_plan), \
        unfused_sess.last_plan.tree_string()
    _COVERAGE.setdefault(suite, {})[qname] = n_stages
    # bit-identity: fusion must change NOTHING about the result. Queries
    # with tie-prone final sort keys compare unordered (ties may legally
    # reorder between two otherwise-identical executions).
    if qname in _TIES and suite == "tpcds":
        assert_tables_equal(ref, got, ignore_order=True)
    else:
        assert got.equals(ref), f"{suite}/{qname} diverged under fusion"


def test_zz_fusion_coverage_summary():
    """Runs after the sweep: report coverage and hold a conservative floor
    so a pass regression (fusion silently matching nothing) fails loudly."""
    total = sum(len(v) for v in _COVERAGE.values())
    if total < len(_ALL):
        pytest.skip("sweep did not run to completion")
    fused_queries = sum(1 for v in _COVERAGE.values()
                        for n in v.values() if n >= 1)
    fraction = fused_queries / total
    print(f"\n[fusion-sweep] coverage: {fused_queries}/{total} "
          f"({fraction:.2%}) queries with >= 1 fused stage")
    # measured at introduction: 93/129 (72%) — the floor leaves headroom
    # for scale-dependent join-strategy drift, not for a broken pass
    assert fused_queries >= 60, _COVERAGE
    assert fraction >= 0.5, _COVERAGE
