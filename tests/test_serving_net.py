"""Network-native query service: wire protocol, streaming, chaos,
cancellation, footprint admission, preemption, multi-replica warm start.

Covers the serving wire contracts (docs/serving.md):
- Arrow-IPC streaming over the TCP shuffle machinery: partial batches
  arrive BEFORE the final one exists, assembled results are bit-identical
  to in-process collect();
- chaos (shuffle FaultPlan reused verbatim): corrupted result frames are
  RETRYABLE checksum failures; a dropped connection mid-stream fails the
  handle with its batches-delivered count, never hangs;
- cancellation over the wire AND client disconnect both release
  server-side resources (semaphore holds, catalog buffers, parked
  frames) through the PR 8 cooperative chain — zero leaked buffers;
- footprint admission: queries charged their working_set_estimate
  against the device budget wait instead of OOMing running queries;
  whales admit alone under the grace hint;
- batch-granularity preemption: a whale yields its device permit to a
  starved tenant at exec boundaries — interactive latency drops, whale
  results stay identical;
- two server processes sharing the on-disk program-cache index behind
  the routing client: the second replica warm-starts (disk hits).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.serving import QueryState, ResultStream
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.serving.client import (QueryServiceClient,
                                             WireQueryError)
from spark_rapids_tpu.serving.server import QueryServer
from spark_rapids_tpu.utils import metrics as um

BASE_CONF = {
    "spark.rapids.tpu.sql.string.maxBytes": "16",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
}


def make_table(n=20000, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 8, n).astype("int64"),
                     "v": rng.random(n)})


def serve(extra_conf=None, partitions=3, n=20000):
    """One in-process server over a session with view ``t`` registered."""
    sess = TpuSession({**BASE_CONF, **(extra_conf or {})})
    df = sess.create_dataframe(make_table(n))
    if partitions > 1:
        df = df.repartition(partitions)
    df.createOrReplaceTempView("t")
    server = QueryServer(sess)
    host, port = server.address
    return sess, server, f"{host}:{port}"


FILTER_SQL = "SELECT k, v FROM t WHERE v > 0.5"
AGG_SQL = "SELECT k, sum(v) AS s FROM t GROUP BY k ORDER BY k"


# ------------------------------------------------------------ wire codec
def test_wire_message_roundtrips():
    sr = wire.SubmitRequest("SELECT 1", "etl", 12.5, "lbl")
    assert wire.SubmitRequest.from_bytes(sr.to_bytes()) == sr
    assert wire.SubmitResponse.from_bytes(
        wire.SubmitResponse(42).to_bytes()).query_id == 42
    nr = wire.NextRequest(7, 3)
    assert wire.NextRequest.from_bytes(nr.to_bytes()) == nr
    batch = wire.NextResponse(wire.NEXT_BATCH, seq=2, nbytes=100,
                              checksum=0xDEAD)
    assert wire.NextResponse.from_bytes(batch.to_bytes()) == batch
    done = wire.NextResponse(wire.NEXT_DONE, batches=4,
                             metrics_json=b'{"a":1}', schema_ipc=b"xyz")
    assert wire.NextResponse.from_bytes(done.to_bytes()) == done
    err = wire.NextResponse(wire.NEXT_ERROR, error="boom")
    assert wire.NextResponse.from_bytes(err.to_bytes()) == err
    fr = wire.FetchRequest(7, 2, 1 << 40)
    assert wire.FetchRequest.from_bytes(fr.to_bytes()) == fr
    table = make_table(128)
    rr = wire.RegisterRequest.from_bytes(
        wire.RegisterRequest("view", wire.table_to_ipc(table)).to_bytes())
    assert wire.ipc_to_table(rr.ipc).equals(table)


def test_arrow_ipc_roundtrip_bit_identical():
    table = make_table(4096)
    assert wire.ipc_to_table(wire.table_to_ipc(table)).equals(table)
    empty = wire.ipc_to_table(wire.schema_to_ipc(table.schema))
    assert empty.num_rows == 0 and empty.schema.equals(table.schema)


# ----------------------------------------------------- end-to-end stream
def test_network_query_bit_identical_to_inprocess():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        got = client.submit(AGG_SQL).result()
        assert got.equals(sess.sql(AGG_SQL).collect())
        h = client.submit(FILTER_SQL)
        got = h.result()
        assert got.equals(sess.sql(FILTER_SQL).collect())
        assert h.batches_delivered >= 2       # one per repartition slice
    finally:
        client.close()
        server.shutdown()


def test_partial_batch_streams_before_completion():
    """The streaming contract: with a depth-1 stream and multiple result
    partitions, the client holds batch 0 while the query is still RUNNING
    server-side (the final batch does not exist yet)."""
    sess, server, addr = serve(
        {"spark.rapids.tpu.serving.net.streamQueueDepth": "1"},
        partitions=6)
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit(FILTER_SQL)
        it = h.batches()
        first = next(it)
        assert first.num_rows >= 0
        sq = list(server._queries.values())[0]
        assert not sq.handle.done, \
            "first batch should arrive while the query is still running"
        rest = list(it)
        got = pa.concat_tables([first] + rest)
        assert got.equals(sess.sql(FILTER_SQL).collect())
        assert h.metrics["first_batch_s"] < h.metrics["wall_s"]
        assert h.metrics["stream_batches"] == h.batches_delivered
    finally:
        client.close()
        server.shutdown()


def test_oversized_batches_slice_into_wire_frames():
    sess, server, addr = serve(
        {"spark.rapids.tpu.serving.net.maxStreamBatchRows": "1000"},
        partitions=1, n=5000)
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit("SELECT k, v FROM t")
        got = h.result()
        assert got.equals(sess.sql("SELECT k, v FROM t").collect())
        assert h.batches_delivered >= 5
    finally:
        client.close()
        server.shutdown()


def test_register_table_over_wire_and_empty_result():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        extra = pa.table({"x": [1, 2, 3]})
        client.register_table("extra", extra)
        got = client.submit("SELECT x FROM extra WHERE x > 1").result()
        assert got.to_pydict() == {"x": [2, 3]}
        # zero-batch result still assembles to the typed empty table
        empty = client.submit("SELECT x FROM extra WHERE x > 99").result()
        assert empty.num_rows == 0
        assert empty.schema.names == ["x"]
    finally:
        client.close()
        server.shutdown()


def test_submit_error_surfaces_not_hangs():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit("SELECT nope FROM not_a_table")
        with pytest.raises(WireQueryError):
            h.result()
    finally:
        client.close()
        server.shutdown()


# ------------------------------------------------------------------ chaos
def test_corrupt_result_frame_is_retryable_checksum_failure():
    """corrupt_frame on the SERVER transport flips one seeded byte of the
    first result frame: the client's crc32 catches it, backs off, and the
    parked copy retransmits — correct result, retry visible in metrics."""
    sess, server, addr = serve(
        {"spark.rapids.tpu.serving.net.faults.plan": "corrupt_frame:after=1",
         "spark.rapids.tpu.serving.net.faults.seed": "7"})
    client = QueryServiceClient([addr], TpuConf())
    before = um.SERVING_METRICS[um.SERVING_WIRE_RETRIES].value
    try:
        h = client.submit(FILTER_SQL)
        got = h.result()
        assert got.equals(sess.sql(FILTER_SQL).collect())
        retries = um.SERVING_METRICS[um.SERVING_WIRE_RETRIES].value - before
        assert retries >= 1
        fired = [f for f in server.transport.plan.fired
                 if f[0] == "corrupt_frame"]
        assert fired, "the seeded fault never fired"
    finally:
        client.close()
        server.shutdown()


def test_dropped_connection_mid_stream_fails_with_delivered_count():
    """drop_conn on the CLIENT transport kills the connection epoch on the
    Nth received frame: the handle fails promptly with the count of
    batches that arrived intact — never a hang."""
    sess, server, addr = serve(partitions=5)
    client = QueryServiceClient([addr], TpuConf({
        "spark.rapids.tpu.serving.net.faults.plan": "drop_conn:after=3",
        "spark.rapids.tpu.serving.net.faults.seed": "7",
        "spark.rapids.tpu.shuffle.maxRetries": "1",
        "spark.rapids.tpu.serving.net.rpcTimeoutSeconds": "30"}))
    try:
        h = client.submit(FILTER_SQL)
        t0 = time.perf_counter()
        with pytest.raises(WireQueryError) as ei:
            h.result()
        assert time.perf_counter() - t0 < 60, "the failure must be prompt"
        assert ei.value.batches_delivered == 2
        assert ei.value.batches_delivered == h.batches_delivered
    finally:
        client.close()
        server.shutdown()


def test_injected_request_failure_surfaces():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], TpuConf({
        "spark.rapids.tpu.serving.net.faults.plan":
            "fail_request:req_type=serve.submit,after=1",
        "spark.rapids.tpu.serving.net.faults.seed": "3"}))
    try:
        with pytest.raises(WireQueryError, match="injected"):
            client.submit(AGG_SQL)
        # the schedule fired once; the next submit goes through
        got = client.submit(AGG_SQL).result()
        assert got.equals(sess.sql(AGG_SQL).collect())
    finally:
        client.close()
        server.shutdown()


# ---------------------------------------------------- cancellation/leaks
def _zero_leak_check(sess):
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    dm = DeviceManager.peek()
    if dm is None:
        return
    deadline = time.time() + 30
    while dm.semaphore.active_holders > 0 and time.time() < deadline:
        time.sleep(0.05)
    assert dm.semaphore.active_holders == 0
    assert dm.semaphore.waiting == 0


def test_cancel_over_wire_releases_server_resources():
    sess, server, addr = serve(partitions=8, n=200000)
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit(FILTER_SQL)
        it = h.batches()
        next(it)                        # stream is live
        h.cancel()
        with pytest.raises(WireQueryError):
            for _ in it:
                pass
        sess.scheduler.drain(timeout=60)
        _zero_leak_check(sess)
        deadline = time.time() + 10
        while server._queries and time.time() < deadline:
            time.sleep(0.05)
        assert not server._queries, "cancelled query still parked"
    finally:
        client.close()
        server.shutdown()


def test_abandoned_stream_cancels_server_query():
    """Review regression: breaking out of batches() early (LIMIT-style
    consumption) must cancel the server-side query — its producer,
    device permit and parked frames release NOW, not at client
    disconnect."""
    sess, server, addr = serve(
        {"spark.rapids.tpu.serving.net.streamQueueDepth": "1"},
        partitions=8, n=200000)
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit(FILTER_SQL)
        for _batch in h.batches():
            break                       # abandon mid-stream
        deadline = time.time() + 30
        while server._queries and time.time() < deadline:
            time.sleep(0.05)
        assert not server._queries, "abandoned stream left the query open"
        sess.scheduler.drain(timeout=60)
        _zero_leak_check(sess)
    finally:
        client.close()
        server.shutdown()


def test_client_disconnect_cancels_and_frees_everything():
    """Mid-stream disconnect = cancellation: the transport's peer-lost
    signal cancels the peer's queries; the cooperative chain releases the
    semaphore hold and catalog buffers; parked frames and stream buffers
    drop. Zero leaked buffers."""
    sess, server, addr = serve(
        {"spark.rapids.tpu.serving.net.streamQueueDepth": "1"},
        partitions=8, n=200000)
    client = QueryServiceClient([addr], sess.conf)
    h = client.submit(FILTER_SQL)
    it = h.batches()
    next(it)                            # producer mid-stream, batches parked
    sq = list(server._queries.values())[0]
    client.close()                      # vanish without cancel
    deadline = time.time() + 30
    while server._queries and time.time() < deadline:
        time.sleep(0.05)
    assert not server._queries, "peer-lost cleanup never ran"
    assert sq.handle.cancel_requested
    sess.scheduler.drain(timeout=60)
    assert sq.handle.state in (QueryState.CANCELLED, QueryState.DONE)
    assert sq.parked is None and not sq.slices
    _zero_leak_check(sess)
    server.shutdown()


# ------------------------------------------------------ footprint admission
def test_footprint_admission_waits_instead_of_oom():
    """Two queries whose estimates exceed the tiny budget serialize: the
    second WAITS (visible in metrics) and both complete correctly."""
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    DeviceManager.shutdown()
    try:
        sess = TpuSession({**BASE_CONF,
                           "spark.rapids.tpu.memory.tpu.poolSizeBytes":
                               str(8 << 20),
                           "spark.rapids.tpu.serving.maxConcurrentQueries":
                               "4"})
        big = (sess.create_dataframe(make_table(400000))
               .groupBy("k").agg(F.sum("v").alias("s")))
        ref = big.collect()
        before = um.SERVING_METRICS[um.SERVING_ADMISSION_REJECTIONS].value
        handles = [sess.submit(big, label=f"big{i}") for i in range(3)]
        for h in handles:
            assert h.result(timeout=300).equals(ref)
        rejections = (um.SERVING_METRICS[
            um.SERVING_ADMISSION_REJECTIONS].value - before)
        assert rejections >= 1
        ests = [h.metrics["footprint_est_bytes"] for h in handles]
        assert all(e and e > 8 << 20 for e in ests)
        # over-budget estimates admit ALONE under the grace hint
        assert all(h.metrics["admission_grace_hint"] for h in handles)
        assert sum(h.metrics["admission_footprint_wait_s"] > 0
                   for h in handles) >= 1
        sess.scheduler.shutdown(wait=False)
    finally:
        DeviceManager.shutdown()


def test_footprint_admission_small_queries_unthrottled():
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.serving.maxConcurrentQueries": "4"})
    small = (sess.create_dataframe(make_table(256))
             .groupBy("k").agg(F.sum("v").alias("s")))
    ref = small.collect()
    handles = [sess.submit(small) for _ in range(4)]
    for h in handles:
        assert h.result(timeout=120).equals(ref)
        assert h.metrics["admission_footprint_wait_s"] == 0.0
    assert sess.scheduler.admission.stats()["admitted"] == 0


def test_footprint_admission_disabled_by_conf():
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    DeviceManager.shutdown()
    try:
        sess = TpuSession({**BASE_CONF,
                           "spark.rapids.tpu.memory.tpu.poolSizeBytes":
                               str(8 << 20),
                           "spark.rapids.tpu.serving.admission."
                           "byFootprint.enabled": "false"})
        big = (sess.create_dataframe(make_table(400000))
               .groupBy("k").agg(F.sum("v").alias("s")))
        h = sess.submit(big)
        assert h.result(timeout=300) is not None
        assert h.metrics["footprint_est_bytes"] is None
    finally:
        DeviceManager.shutdown()


# ------------------------------------------------------------- preemption
def _preemption_run(preempt: bool):
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    DeviceManager.shutdown()
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.sql.concurrentTpuTasks": "1",
                       "spark.rapids.tpu.serving.maxConcurrentQueries": "4",
                       "spark.rapids.tpu.serving.preemption.enabled":
                           str(preempt).lower(),
                       "spark.rapids.tpu.serving.preemption.starvationMs":
                           "30"})
    whale_df = (sess.create_dataframe(make_table(400000)).repartition(16)
                .groupBy("k").agg(F.sum("v").alias("s")).sort("k"))
    inter_df = (sess.create_dataframe(make_table(1000, seed=3))
                .groupBy("k").agg(F.sum("v").alias("s")).sort("k"))
    ref_whale = whale_df.collect()          # warm compiles
    ref_inter = inter_df.collect()
    wh = sess.submit(whale_df, tenant="whale", label="whale")
    time.sleep(0.3)                         # whale holds the single permit
    t0 = time.perf_counter()
    ih = sess.submit(inter_df, tenant="interactive", label="inter")
    inter_result = ih.result(timeout=300)
    inter_wall = time.perf_counter() - t0
    whale_result = wh.result(timeout=300)
    assert whale_result.equals(ref_whale), "preempted whale diverged"
    assert inter_result.equals(ref_inter)
    sess.scheduler.shutdown(wait=False)
    return inter_wall, wh.metrics["preemptions"]


def test_preemption_bounds_interactive_latency():
    """One whale + one interactive tenant on a single device permit: with
    preemption ON the whale yields at batch boundaries, so the interactive
    submit-to-done wall is a fraction of the preemption-OFF wall — and the
    whale still completes with identical results (asserted in the helper).
    """
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    try:
        _off_wall, off_preempts = _preemption_run(False)
        on_wall, on_preempts = _preemption_run(True)
    finally:
        DeviceManager.shutdown()
    # preemption is proven by the COUNTERS, not by racing the clock: a
    # wall-ratio assert (on < off * k) flakes whenever a loaded CI box
    # stretches the on-run or compresses the off-run. The whale yielding
    # at least once while the off-run never yields IS the behavior under
    # test; the wall check is a generous absolute sanity bound only.
    assert off_preempts == 0
    assert on_preempts >= 1, "the whale never yielded"
    assert um.SERVING_METRICS[um.SERVING_PREEMPTIONS].value >= 1
    assert on_wall < 120.0, (
        f"interactive query waited out the whole whale: on={on_wall:.3f}s")


def test_semaphore_yield_to_waiters_preserves_nesting():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    with sem.held(task_id=1, tenant="whale"):
        with sem.held(task_id=1, tenant="whale"):       # nested
            got = []
            t = threading.Thread(
                target=lambda: (sem.acquire_if_necessary(
                    task_id=2, tenant="fast"), got.append(True),
                    sem.release_if_necessary(task_id=2)))
            t.start()
            deadline = time.time() + 5
            while not sem.has_starved_waiter(exclude_tenant="whale",
                                             min_wait_s=0.01):
                assert time.time() < deadline
                time.sleep(0.01)
            assert sem.yield_to_waiters(task_id=1, tenant="whale")
            t.join(10)
            assert got == [True]
            assert sem.active_holders == 1      # we re-hold
        assert sem.active_holders == 1          # inner exit: still nested
    assert sem.active_holders == 0              # outer exit released


def test_semaphore_sibling_exit_during_yield_keeps_ledger_balanced():
    """Review regression: a pipeline-producer sibling exiting its scoped
    hold WHILE the consumer is mid-yield must keep the nesting ledger
    balanced — the old pop-and-restore approach double-counted the exited
    scope and leaked the permit forever."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    cm_consumer = sem.held(task_id=1, tenant="whale")
    cm_producer = sem.held(task_id=1, tenant="whale")
    cm_consumer.__enter__()
    cm_producer.__enter__()                 # sibling scope, nesting 2
    w_got, p_done = threading.Event(), threading.Event()

    def fast_tenant():
        sem.acquire_if_necessary(task_id=2, tenant="fast")
        w_got.set()
        assert p_done.wait(10)
        sem.release_if_necessary(task_id=2)

    def producer_exit():
        assert w_got.wait(10)               # yield definitely in flight
        cm_producer.__exit__(None, None, None)
        p_done.set()
    threads = [threading.Thread(target=fast_tenant),
               threading.Thread(target=producer_exit)]
    for t in threads:
        t.start()
    deadline = time.time() + 5
    while not sem.has_starved_waiter(exclude_tenant="whale",
                                     min_wait_s=0.01):
        assert time.time() < deadline
        time.sleep(0.01)
    assert sem.yield_to_waiters(task_id=1, tenant="whale")
    for t in threads:
        t.join(10)
    assert sem.active_holders == 1          # consumer re-holds
    cm_consumer.__exit__(None, None, None)
    assert sem.active_holders == 0, "permit leaked across the yield"
    # the permit is actually takeable again
    assert sem.acquire_if_necessary(task_id=3, timeout=1.0)
    sem.release_if_necessary(task_id=3)


def test_semaphore_sibling_enter_during_yield_joins_ledger():
    """A sibling ENTERING a scoped hold mid-yield joins the live nesting
    ledger (no second permit, no clobber); everything still releases."""
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    cm_consumer = sem.held(task_id=1, tenant="whale")
    cm_consumer.__enter__()
    w_got, p_entered = threading.Event(), threading.Event()
    producer_scope = []

    def fast_tenant():
        sem.acquire_if_necessary(task_id=2, tenant="fast")
        w_got.set()
        assert p_entered.wait(10)
        sem.release_if_necessary(task_id=2)

    def producer_enter():
        assert w_got.wait(10)               # consumer is mid-yield
        cm = sem.held(task_id=1, tenant="whale")
        cm.__enter__()
        producer_scope.append(cm)
        p_entered.set()
    threads = [threading.Thread(target=fast_tenant),
               threading.Thread(target=producer_enter)]
    for t in threads:
        t.start()
    deadline = time.time() + 5
    while not sem.has_starved_waiter(exclude_tenant="whale",
                                     min_wait_s=0.01):
        assert time.time() < deadline
        time.sleep(0.01)
    assert sem.yield_to_waiters(task_id=1, tenant="whale")
    for t in threads:
        t.join(10)
    producer_scope[0].__exit__(None, None, None)
    assert sem.active_holders == 1
    cm_consumer.__exit__(None, None, None)
    assert sem.active_holders == 0


def test_footprint_grace_whale_leaves_headroom_for_interactive():
    """Review regression: a grace-admitted whale charges the OOC headroom
    share — NOT the whole budget — so a small interactive query admits
    alongside it instead of being parked where preemption cannot see it.
    Two whales still serialize."""
    from spark_rapids_tpu.serving import QueryHandle
    from spark_rapids_tpu.serving.admission import FootprintAdmission
    budget = 10 << 20
    conf = TpuConf({"spark.rapids.tpu.memory.tpu.poolSizeBytes":
                    str(budget)})
    fa = FootprintAdmission(conf)
    whale = QueryHandle("w")
    fa.admit(whale, 50 << 20)               # 5x the budget: grace hint
    assert whale.metrics["admission_grace_hint"]
    charged = fa.stats()["charged_bytes"]
    assert charged < budget                 # headroom share, not all of it
    small = QueryHandle("s")
    fa.admit(small, budget - charged)       # fits the free share: no wait
    assert small.metrics["admission_footprint_wait_s"] == 0.0
    fa.release(small)
    # a second whale does NOT co-fit: it must wait until the first leaves
    waited = threading.Event()
    whale2 = QueryHandle("w2")

    def second_whale():
        fa.admit(whale2, 50 << 20)
        waited.set()
    t = threading.Thread(target=second_whale)
    t.start()
    assert not waited.wait(0.3), "two grace whales co-admitted"
    fa.release(whale)
    assert waited.wait(10)
    fa.release(whale2)
    t.join(10)
    assert fa.stats()["charged_bytes"] == 0


def test_client_cancel_receive_on_fetch_timeout():
    """Review regression: a timed-out fetch abandons its posted receive
    (tcp cancel_receive) so the stale tag does not pin a frame-sized
    buffer in the transport's pending table."""
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit(FILTER_SQL)
        assert h.result() is not None
        transport = client._transport
        conn = client._connection(addr)
        buf = bytearray(64)
        from spark_rapids_tpu.shuffle.transport import AddressLengthTag
        tag = 999_999_999
        conn.receive(AddressLengthTag(buf, 64, tag), lambda tx: None)
        assert tag in transport._pending_recvs
        conn.cancel_receive(tag)
        assert tag not in transport._pending_recvs
    finally:
        client.close()
        server.shutdown()


def test_semaphore_yield_without_hold_is_noop():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore
    sem = TpuSemaphore(1)
    assert not sem.yield_to_waiters(task_id=99, tenant="x")
    assert not sem.has_starved_waiter()


# ---------------------------------------------------------- ResultStream
def test_result_stream_bounded_and_ordered():
    s = ResultStream(depth=2)
    s.put("a")
    s.put("b")
    blocked = threading.Event()

    def producer():
        blocked.set()
        s.put("c")                      # blocks until a consumer pops
        s.finish()
    t = threading.Thread(target=producer)
    t.start()
    assert blocked.wait(5)
    assert s.next(1.0) == ("batch", "a")
    assert s.next(5.0) == ("batch", "b")
    assert s.next(5.0) == ("batch", "c")
    t.join(10)
    assert s.next(1.0) == ("done", None)


def test_result_stream_abandon_unblocks_producer():
    s = ResultStream(depth=1)
    s.put("a")
    done = []

    def producer():
        done.append(s.put("b"))         # blocked until abandon
    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.1)
    s.abandon()
    t.join(10)
    assert done == [False]              # dropped, not delivered
    assert s.put("c") is False          # never blocks again


def test_result_stream_error_propagates():
    s = ResultStream()
    s.fail(RuntimeError("boom"))
    kind, err = s.next(1.0)
    assert kind == "error" and "boom" in str(err)


# ---------------------------------------------------------------- metrics
def test_serving_section_in_last_metrics():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        h = client.submit(FILTER_SQL)
        assert h.result() is not None
        # the server-side action snapshot carries the serving delta (wire
        # counters are process-global and the wire layer drains the stream
        # concurrently, so only presence — not a count — is action-scoped)
        handles = sess.scheduler.handles()
        snap = handles[-1].exec_metrics
        assert "serving" in snap
        assert set(um.SERVING_METRIC_NAMES) <= set(snap["serving"])
        # and the session alias has the same section
        assert "serving" in sess.last_metrics
        # exact per-query counts live on the handle / DONE metrics
        assert h.metrics["stream_batches"] >= 1
        assert um.SERVING_METRICS[um.SERVING_STREAM_BATCHES].value >= 1
        assert um.SERVING_METRICS[um.SERVING_WIRE_BYTES_OUT].value > 0
    finally:
        client.close()
        server.shutdown()


def test_stats_rpc_reports_counters():
    sess, server, addr = serve()
    client = QueryServiceClient([addr], sess.conf)
    try:
        client.submit(AGG_SQL).result()
        st = client.stats()
        assert st["scheduler"]["states"].get("DONE", 0) >= 1
        assert "serving.wire_bytes_out" in st["serving"]
        assert st["queries_open"] == 0      # DONE queries pruned
    finally:
        client.close()
        server.shutdown()


# ------------------------------------------------- subprocess / replicas
def _spawn_server(args, env=None):
    import tempfile
    # stderr to a FILE, not a pipe: a chatty server (jax warnings, compile
    # logs) would fill an undrained 64K pipe and wedge mid-write
    errf = tempfile.NamedTemporaryFile(prefix="serving-err-", suffix=".log",
                                       delete=False, mode="w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.serving.server"] + args,
        stdout=subprocess.PIPE, stderr=errf, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})})
    line = proc.stdout.readline()
    if not line.startswith("SERVING "):
        errf.seek(0)
        raise AssertionError(
            f"server never came up: {line!r}\n{errf.read()[-2000:]}")
    _tag, host, port = line.split()
    return proc, f"{host}:{port}"


@pytest.mark.slow
def test_server_subprocess_tpch_q1_bit_identical():
    """The CI smoke shape: a server SUBPROCESS over TCP localhost, the
    client runs TPC-H Q1 SQL, >= 1 partial batch streams before
    completion, and the assembled result matches the in-process collect
    of the same SQL over the same deterministic data (float-agg carve-out
    per the documented contract)."""
    from spark_rapids_tpu.benchmarks.tpch import gen_lineitem
    from spark_rapids_tpu.testing import assert_tables_equal
    q1_sql = (
        "SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty, "
        "sum(l_extendedprice) AS sum_base_price, "
        "sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS "
        "sum_charge, avg(l_quantity) AS avg_qty, "
        "avg(l_extendedprice) AS avg_price, avg(l_discount) AS avg_disc, "
        "count(*) AS count_order FROM lineitem "
        "WHERE l_shipdate <= date '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus "
        "ORDER BY l_returnflag, l_linestatus")
    scan_sql = ("SELECT l_orderkey, l_extendedprice FROM lineitem "
                "WHERE l_discount > 0.05")
    proc, addr = _spawn_server(
        ["--tpch-lineitem", "0.002", "--partitions", "4",
         "--conf",
         "spark.rapids.tpu.sql.variableFloatAgg.enabled=true"])
    client = QueryServiceClient([addr], TpuConf(BASE_CONF))
    try:
        sess = TpuSession(BASE_CONF)
        (sess.create_dataframe(gen_lineitem(scale=0.002, seed=42))
         .repartition(4).createOrReplaceTempView("lineitem"))
        got = client.submit(q1_sql).result()
        assert_tables_equal(sess.sql(q1_sql).collect(), got,
                            approx_float=1e-9)
        h = client.submit(scan_sql)
        got2 = h.result()
        assert h.batches_delivered >= 2
        assert h.metrics["first_batch_s"] < h.metrics["wall_s"]
        assert got2.equals(sess.sql(scan_sql).collect())
    finally:
        client.close()
        proc.terminate()
        proc.wait(timeout=30)


@pytest.mark.slow
def test_two_replica_warm_start_through_routing_client(tmp_path):
    """N server processes share the on-disk program-cache index: replica
    A compiles the mix cold; replica B, pointed at the same cache dir,
    counts >= 1 disk_hit for the same query shapes — behind ONE routing
    client."""
    cache_dir = str(tmp_path / "serving-cache")
    common = ["--tpch-lineitem", "0.002", "--conf",
              "spark.rapids.tpu.sql.variableFloatAgg.enabled=true",
              "--conf",
              f"spark.rapids.tpu.serving.cache.dir={cache_dir}"]
    sql = ("SELECT l_returnflag, sum(l_extendedprice) AS rev FROM lineitem "
           "GROUP BY l_returnflag ORDER BY l_returnflag")
    proc_a, addr_a = _spawn_server(common)
    client = None
    proc_b = None
    try:
        client = QueryServiceClient([addr_a], TpuConf(BASE_CONF))
        ref = client.submit(sql).result()          # replica A compiles cold
        client.close()
        proc_b, addr_b = _spawn_server(common)
        client = QueryServiceClient([addr_a, addr_b], TpuConf(BASE_CONF))
        got = client.submit(sql, replica=1).result()
        assert got.equals(ref)
        stats_b = client.stats(replica=1)
        disk_hits = stats_b["scheduler"]["program_cache"]["disk_hits"]
        assert disk_hits >= 1, stats_b["scheduler"]["program_cache"]
    finally:
        if client is not None:
            client.close()
        proc_a.terminate()
        proc_a.wait(timeout=30)
        if proc_b is not None:
            proc_b.terminate()
            proc_b.wait(timeout=30)


# ------------------------------------------------------- serve.stats feed
def test_serve_stats_time_series_two_replicas():
    """serve.stats returns the rolling per-replica time-series load-aware
    routing needs: p50/p99 query wall over the window, device budget in
    use, admission queue depth, and running/queued per tenant — computed
    server-side, per replica."""
    sess_a, server_a, addr_a = serve()
    sess_b, server_b, addr_b = serve()
    client = QueryServiceClient([addr_a, addr_b], sess_a.conf)
    try:
        # replica A serves three queries; replica B serves one
        for _ in range(3):
            assert client.submit(AGG_SQL, tenant="etl",
                                 replica=0).result().num_rows == 8
        assert client.submit(FILTER_SQL, replica=1).result().num_rows > 0
        stats_a = client.stats(replica=0)["serve_stats"]
        stats_b = client.stats(replica=1)["serve_stats"]
        for st in (stats_a, stats_b):
            assert st["window_s"] > 0
            now = st["now"]
            for key in ("device_budget_bytes", "device_budget_in_use",
                        "device_budget_fraction", "admission_queue_depth",
                        "queued_by_tenant", "running_by_tenant",
                        "active_workers", "t"):
                assert key in now, (key, now)
            assert st["series"], "gauge series must not be empty"
            assert st["series"][-1]["t"] >= st["series"][0]["t"]
        # the latency window reflects each replica's OWN traffic
        assert stats_a["wall_samples"] >= 3, stats_a
        assert stats_b["wall_samples"] >= 1, stats_b
        assert stats_a["p99_wall_s"] >= stats_a["p50_wall_s"] > 0, stats_a
        assert stats_b["p50_wall_s"] > 0, stats_b
        # everything is idle at sampling time: no queued work remains
        assert stats_a["now"]["admission_queue_depth"] == 0
    finally:
        client.close()
        server_a.shutdown()
        server_b.shutdown()


def test_serve_stats_window_trims_and_tenant_gauges():
    """Wall samples and gauge samples older than the window drop; the
    per-tenant running/queued gauges see live queries."""
    import time as _time
    from spark_rapids_tpu.serving.stats import ServeStatsWindow

    class _FakeSched:
        def __init__(self, session):
            import threading
            self._cv = threading.Condition()
            self._queues = {}
            self._handles = []
            self._active = 0
            self.session = session
            from spark_rapids_tpu.serving.admission import FootprintAdmission
            self.admission = FootprintAdmission(session.conf)

    sess = TpuSession(BASE_CONF)
    win = ServeStatsWindow(window_s=1.0)
    sched = _FakeSched(sess)
    win.record_wall(0.25)
    win.sample(sched)
    snap = win.snapshot(sched)
    assert snap["wall_samples"] == 1 and snap["p50_wall_s"] == 0.25
    _time.sleep(1.1)
    snap = win.snapshot(sched)      # window passed: old samples trimmed
    assert snap["wall_samples"] == 0
    assert snap["p50_wall_s"] == 0.0
    # only the fresh sample taken by this snapshot remains in the series
    assert all(s["t"] >= _time.monotonic() - 1.0 for s in snap["series"])
