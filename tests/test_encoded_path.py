"""Compressed columnar data path: encoded upload bit-identity, device RLE
expansion, mixed-encoding parquet chunks, dictionary unification, the
encoded-domain filter/group-by/join rewrites, the lz4 shuffle codec, and
codec negotiation."""
import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar import encoding as ce
from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.dtypes import DType, Schema
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.base import ExecContext
from spark_rapids_tpu.execs.tpu_execs import concat_device_batches
from spark_rapids_tpu.io.datasource import PartitionedFile
from spark_rapids_tpu.io.parquet import TpuParquetScanExec
from spark_rapids_tpu.io.parquet_pages import (merge_runs, read_dict_column,
                                               rle_bp_runs)
from spark_rapids_tpu.testing import assert_tables_equal
from spark_rapids_tpu.utils import metrics as um


def _write(table: pa.Table, tmp_path, name="t.parquet", **kw) -> str:
    path = str(tmp_path / name)
    pq.write_table(table, path, **kw)
    return path


def _scan_batches(path, schema, conf=None):
    scan = TpuParquetScanExec((PartitionedFile(path),), schema)
    ctx = ExecContext(conf or TpuConf({}), partition_id=0, num_partitions=1)
    return list(scan.execute(ctx))


def _roundtrip(path, table, conf=None):
    batches = _scan_batches(path, Schema.from_pa(table.schema), conf)
    return pa.concat_tables(b.to_arrow() for b in batches), batches


# ------------------------------------------------------- upload bit-identity
def _encoded_vs_decoded_table():
    rng = np.random.default_rng(7)
    n = 5000
    return pa.table({
        # dictionary forms of every flavor the issue names
        "dict_str": pa.array(np.array(["aa", "bb", "cc"])[
            rng.integers(0, 3, n)]).dictionary_encode(),
        "dict_i64": pa.array(rng.integers(0, 9, n),
                             pa.int64()).dictionary_encode(),
        "dict_f64": pa.array(np.round(rng.uniform(0, 1, n), 2),
                             pa.float64()).dictionary_encode(),
        "nulls": pa.array([None if v % 11 == 0 else int(v)
                           for v in rng.integers(0, 6, n)],
                          pa.int64()).dictionary_encode(),
        "plain_f64": pa.array(rng.uniform(size=n) * 1e9),
    })


def test_encoded_upload_bit_identical_to_decoded():
    """Dictionary (string/int/double), null-bearing, and DOUBLE
    bits-sibling columns: the encoded upload must be bit-identical to the
    decoded single-shot upload of the same rows."""
    t = _encoded_vs_decoded_table()
    enc = DeviceBatch.from_arrow(t, 16)
    decoded_t = pa.table({f.name: (t.column(f.name).combine_chunks()
                                   .cast(f.type.value_type)
                                   if pa.types.is_dictionary(f.type)
                                   else t.column(f.name))
                          for f in t.schema})
    dec = DeviceBatch.from_arrow(decoded_t, 16)
    n = t.num_rows
    for ci, (a, b) in enumerate(zip(enc.columns, dec.columns)):
        valid = np.asarray(a.validity[:n])
        assert np.array_equal(valid, np.asarray(b.validity[:n])), ci
        # data at INVALID rows is garbage by contract (the encoded path
        # points null indices at dict slot 0, the decoded path stages 0)
        assert np.array_equal(np.asarray(a.data[:n])[valid],
                              np.asarray(b.data[:n])[valid]), ci
        assert (a.bits is None) == (b.bits is None), ci
        if a.bits is not None:
            assert np.array_equal(np.asarray(a.bits[:n])[valid],
                                  np.asarray(b.bits[:n])[valid]), ci
    # the f64 bits sibling survived the encoded path
    assert enc.column_by_name("dict_f64").bits is not None
    # encodings retained for unique dictionaries
    assert enc.column_by_name("dict_str").encoding is not None
    assert enc.column_by_name("dict_str").encoding.lengths is not None
    assert enc.column_by_name("plain_f64").encoding is None


def test_ree_upload_bit_identical_and_double_bits():
    ends = pa.array(np.array([100, 228, 412, 500], np.int32))
    vals = pa.array([1.5, -0.0, float("nan"), 3.75], pa.float64())
    ree = pa.RunEndEncodedArray.from_arrays(ends, vals)
    t = pa.table({"x": ree})
    plain = pa.table({"x": ce.ree_to_plain(ree)})
    a = DeviceBatch.from_arrow(t, 16).columns[0]
    b = DeviceBatch.from_arrow(plain, 16).columns[0]
    assert np.array_equal(np.asarray(a.bits[:500]), np.asarray(b.bits[:500]))
    assert np.array_equal(np.asarray(a.data[:500]), np.asarray(b.data[:500]),
                          equal_nan=True)
    # slicing an REE table stays encoded and exact (NaN == NaN comparison:
    # pa.Table.equals is NaN-strict)
    s = t.slice(150, 300)
    sa = DeviceBatch.from_arrow(s, 16)
    assert_tables_equal(plain.slice(150, 300), sa.to_arrow())


def test_upload_metrics_count_encoded_vs_decoded_bytes():
    t = _encoded_vs_decoded_table()
    before = um.TRANSFER_METRICS.snapshot()
    DeviceBatch.from_arrow(t, 16)
    after = um.TRANSFER_METRICS.snapshot()
    enc = after[um.TRANSFER_ENCODED_BYTES] - before[um.TRANSFER_ENCODED_BYTES]
    dec = (after[um.TRANSFER_DECODED_EQUIV_BYTES]
           - before[um.TRANSFER_DECODED_EQUIV_BYTES])
    assert 0 < enc < dec          # the encoding shrank the link


# ------------------------------------------------------------- runs parsing
def test_rle_bp_runs_matches_decode_and_merges():
    from spark_rapids_tpu.io.parquet_pages import rle_bp_decode
    # hand-built hybrid: RLE run of 7 x value 3, then a bit-packed group of
    # 8 (bit width 2), then RLE 5 x value 1
    def varint(v):
        out = b""
        while True:
            b7 = v & 0x7F
            v >>= 7
            out += bytes([b7 | (0x80 if v else 0)])
            if not v:
                return out
    bw = 2
    packed_vals = [0, 1, 2, 3, 0, 1, 2, 3]
    packed = np.packbits(
        np.array([[(v >> i) & 1 for i in range(bw)] for v in packed_vals],
                 np.uint8).reshape(-1), bitorder="little").tobytes()
    stream = (varint(7 << 1) + bytes([3])            # RLE 7 x 3
              + varint((1 << 1) | 1) + packed        # bit-packed group of 8
              + varint(5 << 1) + bytes([1]))         # RLE 5 x 1
    buf = memoryview(stream)
    count = 20
    expanded = rle_bp_decode(buf, bw, count)
    rv, rl = rle_bp_runs(buf, bw, count)
    assert np.array_equal(np.repeat(rv, rl), expanded)
    assert rl.sum() == count
    mv, ml = merge_runs(np.array([3, 3, 1, 1, 1, 2], np.int32),
                        np.array([2, 5, 1, 1, 3, 4], np.int64))
    assert mv.tolist() == [3, 1, 2] and ml.tolist() == [7, 5, 4]


def test_scan_keeps_rle_dominant_column_as_runs(tmp_path):
    n = 30000
    rng = np.random.default_rng(0)
    t = pa.table({"r": pa.array(np.sort(rng.integers(0, 15, n))
                                .astype(np.int64)),
                  "x": pa.array(rng.uniform(size=n))})
    path = _write(t, tmp_path, row_group_size=10000)
    pf = pq.ParquetFile(path)
    r = read_dict_column(path, pf.metadata, 0, 0, pa.int64(),
                         want_runs=True)
    assert pa.types.is_run_end_encoded(r.prefix.type)
    assert len(r.prefix.values) < 40           # runs, not rows
    out, _ = _roundtrip(path, t)
    assert out.equals(t)
    # conf off: still correct, via the dictionary-index form
    out2, batches2 = _roundtrip(path, t, TpuConf(
        {"spark.rapids.tpu.io.parquet.deviceRleExpand.enabled": "false"}))
    assert out2.equals(t)


def test_per_column_fallback_when_encoding_does_not_shrink(tmp_path):
    """A high-cardinality column whose dictionary form is BIGGER than the
    decoded column must fall back to the decoded read."""
    n = 20000
    rng = np.random.default_rng(1)
    t = pa.table({"hc": pa.array(rng.integers(0, 1 << 60, n, dtype=np.int64))})
    path = _write(t, tmp_path)
    pf = pq.ParquetFile(path)
    assert read_dict_column(path, pf.metadata, 0, 0, pa.int64()) is None
    out, batches = _roundtrip(path, t)
    assert out.equals(t)
    assert all(b.columns[0].encoding is None for b in batches)


# ------------------------------------------------- mixed-encoding boundary
def test_mixed_encoding_chunk_keeps_prefix_encoded(tmp_path):
    """The issue's boundary case: a PLAIN fallback mid-chunk must not decode
    the whole chunk on host — the dictionary prefix stays encoded, only the
    tail decodes, and the scan splits the row group at the boundary."""
    n = 50000
    rng = np.random.default_rng(2)
    vals = rng.integers(0, 1 << 40, n).astype(np.int64)
    vals[:2000] = vals[0]          # repeated head keeps early pages dict
    t = pa.table({"m": pa.array(vals),
                  "d": pa.array(rng.integers(0, 5, n).astype(np.int32))})
    path = _write(t, tmp_path, dictionary_pagesize_limit=2048,
                  data_page_size=4096, row_group_size=n)
    pf = pq.ParquetFile(path)
    r = read_dict_column(path, pf.metadata, 0, 0, pa.int64())
    assert r is not None and r.tail is not None
    assert pa.types.is_dictionary(r.prefix.type)    # prefix still encoded
    assert len(r.prefix) + len(r.tail) == n
    rebuilt = pa.concat_arrays([r.prefix.cast(pa.int64()), r.tail])
    assert rebuilt.equals(t.column("m").combine_chunks())
    out, _ = _roundtrip(path, t)
    assert out.equals(t)


# ----------------------------------------------- unification + concat carry
def test_unifier_tokens_make_concat_carry_encoding(tmp_path):
    n = 9000
    rng = np.random.default_rng(3)
    t = pa.table({
        "s": pa.array(np.array(["x", "y", "z", "w"])[rng.integers(0, 4, n)]),
        "k": pa.array(rng.integers(0, 30, n).astype(np.int64))})
    path = _write(t, tmp_path, row_group_size=3000)
    batches = _scan_batches(path, Schema.from_pa(t.schema))
    assert len(batches) >= 3
    for name in ("s", "k"):
        encs = [b.column_by_name(name).encoding for b in batches]
        assert all(e is not None for e in encs), name
        assert len({e.token for e in encs}) == 1, name
    merged = concat_device_batches(batches, batches[0].schema, 16)
    for name in ("s", "k"):
        enc = merged.column_by_name(name).encoding
        assert enc is not None, name
        # invariant: data == take(values, indices) on the live prefix
        col = merged.column_by_name(name)
        got = np.asarray(col.data[:n])
        exp = np.asarray(enc.values)[np.asarray(enc.indices[:n])]
        assert np.array_equal(got, exp), name
    # different dictionary streams (two separate scans) must NOT carry
    other = _scan_batches(path, Schema.from_pa(t.schema))
    mixed = concat_device_batches([batches[0], other[1]],
                                  batches[0].schema, 16)
    assert mixed.column_by_name("s").encoding is None


def test_unifier_remaps_into_prefix_compatible_dictionary():
    u = ce.DictionaryUnifier()
    a = pa.array(["b", "a", "b"]).dictionary_encode()
    b = pa.array(["c", "a"]).dictionary_encode()
    ua, tok_a = u.unify("col", a)
    ub, tok_b = u.unify("col", b)
    assert tok_a == tok_b
    assert ua.to_pylist() == ["b", "a", "b"]
    assert ub.to_pylist() == ["c", "a"]
    # append-only: the first dictionary is a prefix of the second
    assert ub.dictionary.to_pylist()[:len(ua.dictionary)] == \
        ua.dictionary.to_pylist()


# ------------------------------------------------- encoded-domain operators
_Q1_CONF = {"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
            "spark.rapids.tpu.sql.string.maxBytes": "16",
            "spark.rapids.tpu.sql.scanCache.enabled": "false"}
_DECODED = {"spark.rapids.tpu.sql.encodedDomain.enabled": "false",
            "spark.rapids.tpu.io.parquet.deviceDictDecode.enabled": "false"}


def _lineitem_parquet(tmp_path, n=20000):
    from spark_rapids_tpu.benchmarks.tpch import gen_lineitem
    t = gen_lineitem(scale=n / 6_000_000, seed=11)
    return _write(t, tmp_path, "lineitem.parquet",
                  row_group_size=max(1, t.num_rows // 3)), t


def test_q1_shaped_encoded_domain_equivalence(tmp_path):
    """TPC-H Q1 over parquet: encoded-domain grouping (string keys on
    dictionary indices) + encoded filter must match the decoded path
    bit-for-bit, and must actually run on the encoded domain."""
    from spark_rapids_tpu.benchmarks.tpch import q1
    path, _ = _lineitem_parquet(tmp_path)

    def run(extra):
        sess = TpuSession({**_Q1_CONF, **extra})
        before = um.TRANSFER_METRICS.snapshot()
        out = q1(sess.read.parquet(path)).collect()
        after = um.TRANSFER_METRICS.snapshot()
        ops = (after[um.TRANSFER_ENCODED_DOMAIN_OPS]
               - before[um.TRANSFER_ENCODED_DOMAIN_OPS])
        return out, ops, sess

    enc, enc_ops, sess = run({})
    dec, dec_ops, _ = run(_DECODED)
    assert enc.equals(dec)             # Q1 sorts its output: strict equality
    assert enc_ops >= 1 and dec_ops == 0
    # per-action transfer metrics expose the ratio
    ratio = sess.last_metrics["transfer"]["transfer.compression_ratio"]
    assert 0 < ratio < 1.0


def test_q3_shaped_encoded_domain_join_equivalence(tmp_path):
    """A Q3-shaped plan (filter + equi-join + group-by) over two parquet
    scans: encoded-domain join keys (different dictionary streams, device
    remap) must match the decoded path."""
    rng = np.random.default_rng(5)
    n, m = 15000, 400
    orders = pa.table({
        "o_key": pa.array(rng.integers(0, 300, n).astype(np.int64)),
        "seg": pa.array(np.array(["AUTO", "HOME", "SHIP"])[
            rng.integers(0, 3, n)]),
        "price": pa.array(np.round(rng.uniform(1, 100, n), 2))})
    cust = pa.table({
        "c_key": pa.array(rng.integers(0, 300, m).astype(np.int64)),
        "nation": pa.array(np.array(["US", "DE", "JP", "BR"])[
            rng.integers(0, 4, m)])})
    p1 = _write(orders, tmp_path, "orders.parquet", row_group_size=5000)
    p2 = _write(cust, tmp_path, "cust.parquet")

    def run(extra):
        sess = TpuSession({**_Q1_CONF, **extra})
        o = sess.read.parquet(p1)
        c = sess.read.parquet(p2)
        before = um.TRANSFER_METRICS.snapshot()
        out = (o.filter(F.col("seg") == "AUTO")
                .join(c, [("o_key", "c_key")], how="inner")
                .groupBy("nation")
                .agg(F.sum("price").alias("rev"),
                     F.count().alias("cnt"))
                .sort("nation")).collect()
        after = um.TRANSFER_METRICS.snapshot()
        ops = (after[um.TRANSFER_ENCODED_DOMAIN_OPS]
               - before[um.TRANSFER_ENCODED_DOMAIN_OPS])
        return out, ops

    enc, enc_ops = run({})
    dec, dec_ops = run(_DECODED)
    assert_tables_equal(dec, enc, approx_float=1e-9)
    assert enc_ops >= 1 and dec_ops == 0


def test_join_remap_path_fires_on_scan_joins(tmp_path):
    """Two direct scans with DIFFERENT dictionary streams joined on
    dict-encoded keys: the device remap path itself (not just the filter
    rewrite) must fire and match the decoded join. (In the Q3 shape the
    left filter's compaction drops encodings, so the join there falls back
    per-column — this pins the remap in isolation.)"""
    rng = np.random.default_rng(8)
    n, m = 12000, 300
    left = pa.table({
        "o_key": pa.array(rng.integers(0, 250, n).astype(np.int64)),
        "price": pa.array(np.round(rng.uniform(1, 100, n), 2))})
    right = pa.table({
        "c_key": pa.array(rng.integers(0, 250, m).astype(np.int64)),
        "w": pa.array(rng.integers(0, 9, m).astype(np.int64))})
    p1 = _write(left, tmp_path, "l.parquet", row_group_size=4000)
    p2 = _write(right, tmp_path, "r.parquet")

    def run(extra):
        sess = TpuSession({**_Q1_CONF, **extra})
        before = um.TRANSFER_METRICS.snapshot()
        out = (sess.read.parquet(p1)
               .join(sess.read.parquet(p2), [("o_key", "c_key")],
                     how="inner")
               .agg(F.count().alias("n"),
                    F.sum("price").alias("s"))).collect()
        after = um.TRANSFER_METRICS.snapshot()
        return out, (after[um.TRANSFER_ENCODED_DOMAIN_OPS]
                     - before[um.TRANSFER_ENCODED_DOMAIN_OPS])

    enc, enc_ops = run({})
    dec, dec_ops = run(_DECODED)
    assert enc_ops >= 1 and dec_ops == 0
    assert_tables_equal(dec, enc, approx_float=1e-9)


def test_encoded_filter_with_nulls_matches_decoded(tmp_path):
    rng = np.random.default_rng(6)
    n = 8000
    vals = [None if v % 9 == 0 else ["a", "b", "c"][v % 3]
            for v in rng.integers(0, 90, n)]
    t = pa.table({"s": pa.array(vals), "v": pa.array(np.arange(n))})
    path = _write(t, tmp_path, row_group_size=2000)

    def run(extra):
        sess = TpuSession({**_Q1_CONF, **extra})
        df = sess.read.parquet(path)
        return (df.filter(F.col("s") != "b").agg(
            F.count().alias("c"), F.sum("v").alias("sv"))).collect()

    assert run({}).equals(run(_DECODED))


def test_null_tolerant_predicates_stay_decoded(tmp_path):
    """IsNull / Coalesce produce NON-null verdicts from null inputs, which
    the dictionary-domain gather cannot represent — they must not rewrite
    (regression: `WHERE col IS NULL` returned 0 rows on encoded scans)."""
    vals = ["a", "b", None, "c"] * 2000
    t = pa.table({"s": pa.array(vals), "v": pa.array(np.arange(8000))})
    path = _write(t, tmp_path, row_group_size=2000)

    def run(q, extra):
        sess = TpuSession({**_Q1_CONF, **extra})
        return q(sess.read.parquet(path)).collect()

    for q in (lambda df: df.filter(F.col("s").isNull())
              .agg(F.count().alias("c")),
              lambda df: df.filter(F.col("s").isNotNull())
              .agg(F.count().alias("c")),
              lambda df: df.filter(F.coalesce(F.col("s"), F.lit("b")) == "b")
              .agg(F.count().alias("c"))):
        assert run(q, {}).equals(run(q, _DECODED))
    # null count sanity: isNull really selected the 2000 null rows
    sess = TpuSession(_Q1_CONF)
    got = (sess.read.parquet(path).filter(F.col("s").isNull())
           .agg(F.count().alias("c"))).collect()
    assert got.to_pydict()["c"] == [2000]


def test_unifier_preserves_negative_zero_and_nan_bits(tmp_path):
    """Float dictionaries dedupe by BIT PATTERN: -0.0 survives the unifier
    (regression: Python == collapsed it into +0.0) and equal-bit NaNs
    dedupe instead of growing the dictionary every row group."""
    t = pa.table({"z": pa.array([0.0, -0.0, float("nan"), 1.5] * 2000)})
    path = _write(t, tmp_path, row_group_size=1000)
    batches = _scan_batches(path, Schema.from_pa(t.schema),
                            TpuConf({}))
    out = pa.concat_tables(b.to_arrow() for b in batches)
    assert_tables_equal(t, out)
    neg = sum(1 for v in out["z"].to_pylist()
              if v == 0.0 and str(v).startswith("-"))
    assert neg == 2000
    # a dictionary whose values are distinct by BITS but equal by value
    # (-0.0 vs 0.0) is rightly rejected for index-domain execution
    assert all(b.columns[0].encoding is None for b in batches)
    # the unifier itself: bit-pattern keys keep -0.0 and dedupe equal NaNs
    u = ce.DictionaryUnifier()
    d = pa.array(np.array([0.0, -0.0, np.nan, 1.5])).dictionary_encode()
    u1, tok1 = u.unify("z", d)
    u2, tok2 = u.unify("z", d)
    assert tok1 == tok2
    assert len(u2.dictionary) == 4           # no growth on re-unify
    bits = np.asarray(u2.dictionary).view(np.uint64)
    assert len(set(bits.tolist())) == 4      # -0.0 and NaN bits intact


def test_dict_bucket_keeps_jit_shapes_stable(tmp_path):
    """A dictionary growing a few entries per row group must NOT change the
    encoding's padded shape each batch (jit cache keys include EncSpec.k —
    per-batch growth would recompile every encoded-domain program)."""
    rng = np.random.default_rng(9)
    parts = [np.array([f"v{j}" for j in rng.integers(0, 3 + 2 * i, 4000)])
             for i in range(4)]
    t = pa.table({"s": pa.array(np.concatenate(parts))})
    path = _write(t, tmp_path, row_group_size=4000)
    batches = _scan_batches(path, Schema.from_pa(t.schema))
    ks = [b.columns[0].encoding.k for b in batches]
    reals = [b.columns[0].encoding.k_real for b in batches]
    assert reals == sorted(reals) and reals[-1] > reals[0]  # it DID grow
    assert len(set(ks)) <= 2, ks      # but padded shapes stayed bucketed
    out = pa.concat_tables(b.to_arrow() for b in batches)
    assert out.equals(t)


def test_planner_pass_marks_only_reachable_operators(tmp_path):
    from spark_rapids_tpu.plan.encoded import count_encoded_domain
    path, _ = _lineitem_parquet(tmp_path, n=4000)
    from spark_rapids_tpu.benchmarks.tpch import q1
    sess = TpuSession(_Q1_CONF)
    q1(sess.read.parquet(path)).collect()
    assert count_encoded_domain(sess.last_plan) >= 1
    sess_off = TpuSession({**_Q1_CONF,
                           "spark.rapids.tpu.sql.encodedDomain.enabled":
                               "false"})
    q1(sess_off.read.parquet(path)).collect()
    assert count_encoded_domain(sess_off.last_plan) == 0


# ----------------------------------------------------------- lz4 + shuffle
def test_lz4_block_roundtrip_and_vectors():
    from spark_rapids_tpu.shuffle import lz4
    rng = np.random.default_rng(0)
    cases = [b"", b"a", b"abcd", b"a" * 29, os.urandom(10_000),
             bytes(rng.integers(0, 4, 50_000, dtype=np.uint8)),
             b"hello world " * 4000, bytes(10_000),
             os.urandom(13) + b"X" * 300 + os.urandom(7)]
    for c in cases:
        assert lz4.decompress(lz4.compress(c), len(c)) == c
    # spec vector: 5 literals + overlapping match (offset 5, len 10) + tail
    blk = (bytes([0x56]) + b"hello" + (5).to_bytes(2, "little")
           + bytes([0x50]) + b"hello")
    assert lz4.decompress(blk, 20) == b"hello" * 4
    with pytest.raises(ValueError):
        lz4.decompress(blk, 21)        # wrong size must not pass silently


def test_codec_registry_single_lookup_and_errors():
    from spark_rapids_tpu.shuffle.codec import (available_codecs,
                                                codec_available, get_codec)
    assert {"copy", "none", "zlib", "lz4"} <= set(available_codecs())
    with pytest.raises(ValueError, match="unknown shuffle codec"):
        get_codec("snappy")
    c = get_codec("lz4")
    buf = b"the quick brown fox " * 512
    assert c.decompress(c.compress(buf), len(buf)) == buf
    assert codec_available("definitely-not-a-codec") is False


def test_zlib_level_conf_reaches_codec():
    from spark_rapids_tpu.shuffle.codec import get_codec
    conf = TpuConf({"spark.rapids.tpu.shuffle.compression.zlib.level": "9"})
    assert get_codec("zlib", conf).level == 9
    assert get_codec("zlib").level == 1
    with pytest.raises(ValueError, match="zlib.level"):
        TpuConf({"spark.rapids.tpu.shuffle.compression.zlib.level": "11"})


def test_client_rejects_unknown_codec_early(tmp_path):
    from spark_rapids_tpu.shuffle.inprocess import _Fabric
    from spark_rapids_tpu.shuffle.manager import ShuffleEnv
    _Fabric.reset()
    try:
        env = ShuffleEnv("exec-0", TpuConf(
            {"spark.rapids.tpu.shuffle.compression.codec": "snappy"}),
            disk_dir=str(tmp_path / "e0"))
        env2 = ShuffleEnv("exec-1", TpuConf({}),
                          disk_dir=str(tmp_path / "e1"))
        with pytest.raises(ValueError, match="unknown shuffle codec"):
            env.client_for("exec-1")
    finally:
        _Fabric.reset()


def test_shuffle_lz4_fetch_and_negotiation(tmp_path):
    """lz4-compressed fetch returns exact rows; a codec-less peer
    negotiates the transfer down to copy (counted) instead of failing."""
    from spark_rapids_tpu.shuffle.inprocess import _Fabric
    from spark_rapids_tpu.shuffle.manager import ShuffleEnv, ShuffleManager
    from spark_rapids_tpu.utils import metrics as mt
    from tests.test_shuffle import (collect_partition, sample_table,
                                    write_partitioned)
    conf = TpuConf({"spark.rapids.tpu.shuffle.compression.codec": "lz4",
                    "spark.rapids.tpu.shuffle.bounceBuffers.size": 1024})
    mgr = ShuffleManager()
    t = sample_table(800, seed=1)
    expected = t.take(list(range(0, 800, 2)))
    _Fabric.reset()
    try:
        e0 = ShuffleEnv("exec-0", conf, disk_dir=str(tmp_path / "a0"))
        e1 = ShuffleEnv("exec-1", conf, disk_dir=str(tmp_path / "a1"))
        sid, _ = mgr.register_shuffle(2)
        write_partitioned(mgr, e1, sid, 0, t, 2)
        got = collect_partition(mgr, e0, sid, 0)
        assert got.sort_by("f").equals(expected.sort_by("f"))

        # negotiation: the serving peer supports only copy
        e1.server.supported_codecs = {"copy"}
        sid2, _ = mgr.register_shuffle(2)
        write_partitioned(mgr, e1, sid2, 0, t, 2)
        got2 = collect_partition(mgr, e0, sid2, 0)
        assert got2.sort_by("f").equals(expected.sort_by("f"))
        assert e1.metrics[mt.SHUFFLE_CODEC_FALLBACKS].value >= 1
    finally:
        _Fabric.reset()


def test_lz4_corrupt_frame_checksum_retry(tmp_path):
    """The PR 2 fault matrix composes with compression: a corrupted
    lz4-compressed frame is caught by the on-wire checksum BEFORE
    decompression and the retry succeeds."""
    from spark_rapids_tpu.shuffle.inprocess import _Fabric
    from spark_rapids_tpu.utils import metrics as mt
    from tests.test_shuffle import (collect_partition, sample_table,
                                    write_partitioned)
    from tests.test_shuffle_faults import fault_cluster
    _Fabric.reset()
    try:
        mgr, e0, e1 = fault_cluster(
            tmp_path, plan="corrupt_frame:after=2",
            extra={"spark.rapids.tpu.shuffle.compression.codec": "lz4"})
        sid, _ = mgr.register_shuffle(1)
        t = sample_table(700, seed=3)
        write_partitioned(mgr, e1, sid, 0, t, 1)
        got = collect_partition(mgr, e0, sid, 0)
        assert sorted(got["f"].to_pylist()) == sorted(t["f"].to_pylist())
        assert e0.metrics[mt.SHUFFLE_CHECKSUM_FAILURES].value >= 1
        assert e0.metrics[mt.SHUFFLE_TRANSFER_RETRIES].value >= 1
    finally:
        _Fabric.reset()
