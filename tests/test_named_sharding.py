"""NamedSharding-first execution on the forced 8-device CPU mesh.

The PR 7 acceptance suite: placement as a first-class ExecContext/plan
property, plan-time row-group -> shard assignment with uploads landing
directly on owning devices, the in-mesh all_to_all exchange with
``host_hop_bytes == 0``, sharded-vs-single-device bit identity, dictionary
encodings carried through exchange repack, the sharded-concat guard, and
the ICI/DCN boundary rule."""
import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import SingleDeviceSharding

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.parallel import placement as pl
from spark_rapids_tpu.testing import assert_tables_equal
from spark_rapids_tpu.utils import metrics as um

MESH_CONF = {
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.scanCache.enabled": "false",
}
SINGLE_CONF = {
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.scanCache.enabled": "false",
}


def _rand_table(n=4000, seed=11):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 37, n).astype(np.int64),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
        "x": rng.random(n),
        "s": pa.array([f"cat{int(i)}" for i in rng.integers(0, 9, n)]),
    })


def _write_parquet(table, tmpdir, row_groups=16, **kw):
    path = os.path.join(tmpdir, "t.parquet")
    pq.write_table(table, path,
                   row_group_size=max(1, table.num_rows // row_groups), **kw)
    return path


# ------------------------------------------------------------------ placement
def test_as_placement_normalizes(eight_devices):
    dev = eight_devices[3]
    p = pl.as_placement(dev)
    assert isinstance(p, SingleDeviceSharding)
    assert pl.placement_device(p) is dev
    assert pl.as_placement(None) is None
    s = NamedSharding(jax.sharding.Mesh(np.array(eight_devices), ("data",)),
                      P("data"))
    assert pl.as_placement(s) is s
    assert pl.is_sharded(s) and not pl.is_sharded(p)
    assert pl.placement_device(s) is None


def test_exec_context_device_is_placement(eight_devices):
    from spark_rapids_tpu.execs.base import ExecContext
    dev = eight_devices[5]
    # legacy device= argument normalizes; ctx.device stays device_put-usable
    ctx = ExecContext(device=dev)
    assert isinstance(ctx.placement, SingleDeviceSharding)
    arr = jax.device_put(np.arange(8), ctx.device)
    assert set(arr.sharding.device_set) == {dev}
    mesh = jax.sharding.Mesh(np.array(eight_devices), ("data",))
    ctx2 = ExecContext(placement=NamedSharding(mesh, P("data")))
    assert pl.is_sharded(ctx2.placement)


def test_upload_lands_on_placement(eight_devices):
    from spark_rapids_tpu.columnar.transfer import upload_table
    dev = eight_devices[6]
    b = upload_table(_rand_table(256), 16,
                     device=SingleDeviceSharding(dev))
    for c in b.columns:
        assert set(c.data.sharding.device_set) == {dev}


def test_placement_label(eight_devices):
    mesh = jax.sharding.Mesh(np.array(eight_devices), ("data",))
    assert pl.placement_label(None) == "default"
    assert pl.placement_label(
        NamedSharding(mesh, P("data"))).startswith("mesh[8]:P")
    assert pl.placement_label(NamedSharding(mesh, P())) == \
        "mesh[8]:replicated"
    assert pl.placement_label(
        SingleDeviceSharding(eight_devices[0])).startswith("device:")


# ------------------------------------------------------------------ ICI / DCN
class _FakeDev:
    def __init__(self, process_index, slice_index=None):
        self.process_index = process_index
        if slice_index is not None:
            self.slice_index = slice_index


def test_ici_groups_by_slice_and_process():
    devs = [_FakeDev(0, 0), _FakeDev(0, 0), _FakeDev(0, 1), _FakeDev(1, 1)]
    groups = pl.ici_groups(devs)
    assert sorted(len(g) for g in groups) == [1, 1, 2]
    assert pl.spans_dcn(devs)
    assert len(pl.largest_ici_group(devs)) == 2
    # one host, no slice attr (CPU backend): a single ICI domain
    cpu = [_FakeDev(0) for _ in range(8)]
    assert not pl.spans_dcn(cpu)
    assert pl.largest_ici_group(cpu) == cpu


def test_mesh_rewrite_respects_require_ici(eight_devices):
    """All 8 virtual CPU devices share process 0 / no slice: one ICI domain,
    so requireIci keeps the full mesh (clipping only bites on multi-slice
    topologies, where the TCP stack owns the DCN hop)."""
    s = TpuSession(MESH_CONF)
    out = s.create_dataframe(_rand_table(512)).groupBy("k").agg(
        F.sum("v").alias("sv")).collect()
    plan = s.last_plan.tree_string()
    assert "MeshHashAggregateExec" in plan, plan
    assert "mesh[8]" in plan, plan    # placement annotation, full domain
    assert out.num_rows == 37


# ------------------------------------------------------- plan-time assignment
def test_row_group_units_from_footer(eight_devices):
    t = _rand_table(3200)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write_parquet(t, tmp, row_groups=16)
        s = TpuSession(SINGLE_CONF)
        df = s.read.parquet(path)
        scan = df._executed_plan()
        while not getattr(scan, "is_file_scan", False):
            scan = scan.children[0]
        units = scan.row_group_units()
        assert len(units) == 16
        assert sum(rows for _, _, rows in units) == t.num_rows
        assert all(fi == 0 for fi, _, _ in units)


def test_plan_time_shard_assignment_balances(eight_devices):
    from spark_rapids_tpu.execs.mesh_execs import MeshFileScatterExec
    t = _rand_table(3200)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write_parquet(t, tmp, row_groups=16)
        s = TpuSession(MESH_CONF)
        out = s.read.parquet(path).groupBy("k").agg(
            F.count("v").alias("c")).collect()
        node = s.last_plan
        stack = [node]
        scatter = None
        while stack:
            nd = stack.pop()
            if isinstance(nd, MeshFileScatterExec):
                scatter = nd
            stack.extend(nd.children)
        assert scatter is not None, s.last_plan.tree_string()
        a = scatter.assignment
        assert a is not None, "plan-time assignment missing"
        assert sum(a.rows) == t.num_rows
        # LPT over 16 equal groups on 8 shards: 2 groups per shard
        assert all(len(u) == 2 for u in a.units)
        assert max(a.rows) - min(a.rows) <= max(a.rows) // 4
        cpu = TpuSession({**SINGLE_CONF,
                          "spark.rapids.tpu.sql.enabled": "false"})
        ref = cpu.read.parquet(path).groupBy("k").agg(
            F.count("v").alias("c")).collect()
        assert_tables_equal(ref, out, ignore_order=True)


def test_assigned_scan_lands_sharded(eight_devices):
    """Executing the planned scatter yields a MeshBatch whose buffers are
    committed NamedSharding arrays over all 8 devices — the scan uploaded
    each shard straight to its owner, no host-side whole-table staging."""
    from spark_rapids_tpu.execs.base import ExecContext
    from spark_rapids_tpu.execs.mesh_execs import MeshFileScatterExec
    t = _rand_table(3200)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write_parquet(t, tmp, row_groups=16)
        s = TpuSession(MESH_CONF)
        s.read.parquet(path).groupBy("k").agg(
            F.count("v").alias("c")).collect()
        stack, scatter = [s.last_plan], None
        while stack:
            nd = stack.pop()
            if isinstance(nd, MeshFileScatterExec):
                scatter = nd
            stack.extend(nd.children)
        (mb,) = list(scatter.execute(ExecContext(s.conf)))
        assert mb.num_rows == t.num_rows
        for c in mb.columns:
            assert len(c.data.sharding.device_set) == 8, c.data.sharding
            assert c.data.sharding.spec == P("data")
        # declared placement matches what landed
        assert pl.is_sharded(scatter.placement)


def test_file_granularity_conf_disables_plan_assignment(eight_devices):
    from spark_rapids_tpu.execs.mesh_execs import MeshFileScatterExec
    t = _rand_table(800)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write_parquet(t, tmp, row_groups=4)
        s = TpuSession({**MESH_CONF,
                        "spark.rapids.tpu.sql.mesh.scan.shardAssignment":
                            "file"})
        out = s.read.parquet(path).groupBy("k").agg(
            F.count("v").alias("c")).collect()
        stack, scatter = [s.last_plan], None
        while stack:
            nd = stack.pop()
            if isinstance(nd, MeshFileScatterExec):
                scatter = nd
            stack.extend(nd.children)
        assert scatter is not None and scatter.assignment is None
        assert out.num_rows == 37


def test_assigned_scan_mixed_encodings_per_shard(eight_devices):
    """Regression: one shard's row groups can yield DIFFERENT arrow
    encodings (dictionary vs plain vs REE) — they cannot concatenate as
    host tables, so the assigned path must upload per unit and combine on
    device. NaN/null/unicode ride along."""
    rng = np.random.default_rng(5)
    n = 4000
    t = pa.table({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "x": pa.array([float("nan") if i % 211 == 0 else v
                       for i, v in enumerate(rng.random(n))]),
        "s": pa.array([None if i % 89 == 0 else f"véç{int(i % 7)}"
                       for i in range(n)])})
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "t.parquet")
        pq.write_table(t, path, row_group_size=n // 9, use_dictionary=True)
        s = TpuSession(MESH_CONF)
        out = (s.read.parquet(path).filter(F.col("s").isNotNull())
               .groupBy("s").agg(F.count("x").alias("c")).collect())
        cpu = TpuSession({**SINGLE_CONF,
                          "spark.rapids.tpu.sql.enabled": "false"})
        ref = (cpu.read.parquet(path).filter(F.col("s").isNotNull())
               .groupBy("s").agg(F.count("x").alias("c")).collect())
        assert_tables_equal(ref, out, ignore_order=True)
        assert "MeshFileScatterExec" in s.last_plan.tree_string()


# ------------------------------------------------------------- host_hop_bytes
def test_in_mesh_exchange_zero_host_hop(eight_devices):
    from spark_rapids_tpu.execs import mesh_execs as me
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.mesh_batch import scatter_arrow
    mesh = make_mesh(8)
    mb = scatter_arrow(_rand_table(2048), mesh, 16)
    key = BoundReference(0, mb.schema.fields[0].dtype, False)
    hop = um.TRANSFER_METRICS[um.TRANSFER_HOST_HOP_BYTES]
    before = hop.value
    out = me._mesh_repartition(
        mb, ("t_zero_hop", mb.schema, mb.local_capacity),
        me._hash_pid_builder((key,), 8), smax=16)
    assert out.num_rows == mb.num_rows
    assert hop.value - before == 0, "all_to_all exchange touched the host"


def test_scatter_device_batch_counts_host_hop(eight_devices):
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.mesh_batch import scatter_device_batch
    db = DeviceBatch.from_arrow(_rand_table(512), 16)
    hop = um.TRANSFER_METRICS[um.TRANSFER_HOST_HOP_BYTES]
    before = hop.value
    mb = scatter_device_batch(db, make_mesh(8))
    assert mb.num_rows == 512
    assert hop.value - before >= db.device_size_bytes


def test_mesh_query_zero_host_hop(eight_devices):
    """A whole sharded query (scan -> filter -> hash exchange -> aggregate)
    moves NO exchange data through the host: scatter is an upload, the
    exchange is an all_to_all, only row counts sync."""
    t = _rand_table(4000)
    with tempfile.TemporaryDirectory() as tmp:
        path = _write_parquet(t, tmp, row_groups=8)
        s = TpuSession({**MESH_CONF,
                        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes":
                            "1"})
        hop = um.TRANSFER_METRICS[um.TRANSFER_HOST_HOP_BYTES]
        before = hop.value
        out = (s.read.parquet(path).filter(F.col("v") > F.lit(0))
               .groupBy("s").agg(F.sum("v").alias("sv")).collect())
        assert out.num_rows > 0
        plan = s.last_plan.tree_string()
        assert "MeshHashAggregateExec" in plan, plan
        assert hop.value - before == 0


# ------------------------------------------------------------- bit identity
def test_sharded_projection_collect_bit_identical(eight_devices):
    t = _rand_table(4000)
    def q(sess):
        return (sess.create_dataframe(t)
                .filter(F.col("v") > F.lit(100))
                .select("k", "x", "s"))
    mesh = q(TpuSession(MESH_CONF)).collect()
    single = q(TpuSession(SINGLE_CONF)).collect()
    assert mesh.equals(single), "permute-only pipeline must be bitwise equal"


def test_sharded_q1_vs_single_device(eight_devices):
    """Sharded TPC-H Q1: every non-float column bitwise identical; float
    aggregates (per-shard partials merged in shard order) agree to 1e-9 —
    the distributed-float-sum contract documented in
    docs/mesh-execution.md."""
    from spark_rapids_tpu.benchmarks.tpch import gen_lineitem, q1
    t = gen_lineitem(scale=0.002, seed=42)
    conf_extra = {"spark.rapids.tpu.sql.string.maxBytes": "16"}
    mesh = q1(TpuSession({**MESH_CONF, **conf_extra})
              .create_dataframe(t)).collect()
    single = q1(TpuSession({**SINGLE_CONF, **conf_extra})
                .create_dataframe(t)).collect()
    assert mesh.num_rows == single.num_rows
    max_rel = 0.0
    for name in single.column_names:
        cs, cm = single[name], mesh[name]
        if pa.types.is_floating(cs.type):
            a = cs.to_numpy(zero_copy_only=False)
            b = cm.to_numpy(zero_copy_only=False)
            max_rel = max(max_rel, float(np.max(
                np.abs(a - b) / np.maximum(np.abs(a), 1e-300))))
        else:
            assert cs.equals(cm), f"non-float column {name} differs"
    assert max_rel < 1e-9, max_rel


# ------------------------------------------------------------ encoding carry
def _dict_parquet(tmp, n=4000, seed=7):
    rng = np.random.default_rng(seed)
    t = pa.table({
        "k": rng.integers(0, 12, n).astype(np.int64),
        "s": pa.array([f"cat{int(i)}" for i in rng.integers(0, 9, n)]),
        "v": rng.integers(-100, 100, n).astype(np.int64)})
    path = os.path.join(tmp, "t.parquet")
    pq.write_table(t, path, row_group_size=n // 4, use_dictionary=True)
    return t, path


def test_exchange_carries_encoding(eight_devices):
    """Repartition over a dictionary-encoded scan: the exchange moves int32
    indices (transfer.exchange_encoded_ops fires), the multiset of rows is
    exactly preserved, and results downstream of the exchange match CPU."""
    import collections
    with tempfile.TemporaryDirectory() as tmp:
        t, path = _dict_parquet(tmp)
        s = TpuSession(SINGLE_CONF)
        enc_ops = um.TRANSFER_METRICS[um.TRANSFER_EXCHANGE_ENCODED_OPS]
        before = enc_ops.value
        out = s.read.parquet(path).repartition(4, "s").collect()
        assert enc_ops.value - before >= 1, "encoded exchange never fired"
        co = collections.Counter(zip(out["k"].to_pylist(),
                                     out["s"].to_pylist(),
                                     out["v"].to_pylist()))
        ct = collections.Counter(zip(t["k"].to_pylist(),
                                     t["s"].to_pylist(),
                                     t["v"].to_pylist()))
        assert co == ct, "exchange changed the row multiset"
        cpu = TpuSession({**SINGLE_CONF,
                          "spark.rapids.tpu.sql.enabled": "false"})
        ref = (cpu.read.parquet(path).repartition(4, "s").groupBy("s")
               .agg(F.sum("v").alias("sv")).collect())
        got = (s.read.parquet(path).repartition(4, "s").groupBy("s")
               .agg(F.sum("v").alias("sv")).collect())
        assert_tables_equal(ref, got, ignore_order=True)


def test_exchange_pieces_keep_token_and_invariant(eight_devices):
    """Exec-level check: output pieces of an encoded exchange carry the SAME
    dictionary token and satisfy data == take(values, indices) row-wise."""
    from spark_rapids_tpu.execs.base import ExecContext, LeafExec
    from spark_rapids_tpu.execs.exchange_execs import (HashPartitioning,
                                                       TpuShuffleExchangeExec)
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    with tempfile.TemporaryDirectory() as tmp:
        t, path = _dict_parquet(tmp, n=1000)
        s = TpuSession(SINGLE_CONF)
        df = s.read.parquet(path)
        scan = df._executed_plan()
        while not getattr(scan, "is_device", False):
            scan = scan.children[0]
        ctx = ExecContext(s.conf,
                          device_manager=DeviceManager.initialize(s.conf))
        batches = list(scan.execute(ctx))
        enc_cols = [ci for ci, c in enumerate(batches[0].columns)
                    if c.encoding is not None and c.encoding.token]
        assert enc_cols, "scan produced no token-carrying encodings"
        src_tokens = {ci: batches[0].columns[ci].encoding.token
                      for ci in enc_cols}

        class _Resident(LeafExec):
            is_device = True
            num_partitions = 1

            def execute(self, _ctx):
                yield from iter(batches)

        key = BoundReference(1, batches[0].schema.fields[1].dtype, True)
        exchange = TpuShuffleExchangeExec(HashPartitioning(4, (key,)),
                                          _Resident(batches[0].schema))
        cleanups = []
        total = 0
        for p in range(4):
            cctx = ExecContext(s.conf, partition_id=p, num_partitions=4,
                               device_manager=ctx.device_manager,
                               cleanups=cleanups)
            for piece in exchange.execute(cctx):
                total += piece.num_rows
                for ci in enc_cols:
                    e = piece.columns[ci].encoding
                    assert e is not None, "piece dropped the encoding"
                    assert e.token == src_tokens[ci]
                    n = piece.num_rows
                    data = np.asarray(piece.columns[ci].data)[:n]
                    vals = np.asarray(e.values)
                    idx = np.asarray(e.indices)[:n]
                    np.testing.assert_array_equal(
                        data, vals[idx], err_msg="piece invariant broken")
        assert total == sum(b.num_rows for b in batches)
        for fn in cleanups:
            fn()


def test_catalog_multi_batch_block_no_duplication(eight_devices):
    """Regression: a map task emitting several batches for one (map,
    partition) block must not index the block once per batch — consumers
    were re-reading every buffer N times (rows multiplied N-fold on any
    multi-row-group repartition)."""
    with tempfile.TemporaryDirectory() as tmp:
        t, path = _dict_parquet(tmp)
        s = TpuSession({**SINGLE_CONF,
                        "spark.rapids.tpu.sql.exchange.keepEncodings":
                            "false"})
        out = s.read.parquet(path).repartition(4, "s").collect()
        assert out.num_rows == t.num_rows


# -------------------------------------------------------------- concat guard
def test_concat_refuses_sharded_batch(eight_devices):
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    from spark_rapids_tpu.execs.tpu_execs import concat_device_batches
    from spark_rapids_tpu.parallel.mesh import make_mesh
    from spark_rapids_tpu.parallel.mesh_batch import scatter_arrow
    mesh = make_mesh(8)
    mb = scatter_arrow(_rand_table(1024), mesh, 16)
    sharded = DeviceBatch(mb.schema, mb.columns, mb.num_rows)
    plain = DeviceBatch.from_arrow(_rand_table(64, seed=3), 16)
    with pytest.raises(ValueError, match="gather it explicitly"):
        concat_device_batches([sharded, plain], sharded.schema, 16)
    with pytest.raises(ValueError, match="gather it explicitly"):
        concat_device_batches([sharded], sharded.schema, 16)
    # the EXPLICIT paths still work
    from spark_rapids_tpu.parallel.mesh_batch import gather_mesh
    db = gather_mesh(mb)
    assert db.num_rows == mb.num_rows
    out = concat_device_batches([db, plain], db.schema, 16)
    assert out.num_rows == db.num_rows + plain.num_rows
