"""All 22 TPC-H queries executed DISTRIBUTED over the 8-device virtual mesh
vs the CPU engine (the VERDICT's 'CPU-vs-mesh' bar: real queries — multi-join,
agg, sort, limit — running through the ICI exchange path, not just one
aggregate pattern)."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpch_data import gen_all
from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.002

_TIES = {2, 3, 5, 9, 10, 11, 16, 18, 21}

MESH_CONF = {
    **BENCH_CONF,
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
    # AQE rides the mesh: joins may switch to broadcast from observed sizes
    # mid-query — all 22 queries must still match the CPU engine
    "spark.rapids.tpu.sql.adaptive.enabled": "true",
}


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=7)


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_matches_cpu_on_mesh(qnum, tables, eight_devices):
    cpu = assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qnum](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF,
        ignore_order=qnum in _TIES,
        approx_float=1e-9)
    assert cpu.num_rows > 0 or qnum == 18


def test_mesh_execs_actually_ran(tables, eight_devices):
    """The mesh plan must really lower onto mesh operators (not silently fall
    back to single-device execution): a multi-join query distributed end to
    end, with the shuffled-join ICI exchange forced on."""
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES[3](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf={**MESH_CONF,
              "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1"},
        ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshScatterExec", "MeshShuffledHashJoinExec",
                          "MeshHashAggregateExec"])


def test_mesh_broadcast_join_ran(tables, eight_devices):
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES[3](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshScatterExec", "MeshBroadcastHashJoinExec"])


@pytest.mark.parametrize("qnum", [1, 3, 7, 18, 21])
def test_tpch_sql_on_mesh_matches_cpu(qnum, eight_devices):
    """RAW SQL text distributed over the mesh for TPC-H too (the TPC-DS
    composition lives in test_tpcds_sql_mesh.py)."""
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all as tpch_gen
    from spark_rapids_tpu.benchmarks.tpch_sql import SQL_QUERIES
    from spark_rapids_tpu.testing import run_with_cpu_and_tpu
    from spark_rapids_tpu.testing import assert_tables_equal
    tables = tpch_gen(0.002, seed=7)

    def build(s):
        for name, tab in tables.items():
            s.create_dataframe(tab).createOrReplaceTempView(name)
        return s.sql(SQL_QUERIES[qnum])

    cpu, tpu, _sess = run_with_cpu_and_tpu(build, MESH_CONF)
    assert_tables_equal(cpu, tpu, ignore_order=True, approx_float=1e-6)
