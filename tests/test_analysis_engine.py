"""Unit tests for the tpu-lint v2 interprocedural engine itself — CFG
construction (cfg.py), the forward dataflow (dataflow.py), and call-graph
name resolution (callgraph.py) — separate from the per-rule fixture tests
in test_analysis.py. The rules are only as sound as these invariants."""
import ast
import textwrap

from spark_rapids_tpu.analysis import SourceFile
from spark_rapids_tpu.analysis.callgraph import (CallGraph, module_name)
from spark_rapids_tpu.analysis.cfg import (Cond, LoopIter, WithEnter,
                                           WithExit, build_cfg,
                                           iter_functions, walk_local)
from spark_rapids_tpu.analysis import dataflow
from spark_rapids_tpu.analysis.exceptions import ExceptionFlow


def parse(text: str, path: str = "pkg/mod.py") -> SourceFile:
    return SourceFile(path, textwrap.dedent(text), path)


def cfg_of(text: str, name: str = "f"):
    src = parse(text)
    for qualname, node in iter_functions(src.tree):
        if qualname.split(".")[-1] == name:
            return build_cfg(node)
    raise AssertionError(f"no function {name}")


def blocks_calling(cfg, attr: str):
    """Blocks containing a call whose attribute name is ``attr``."""
    out = []
    for b in cfg.blocks.values():
        for item in b.items:
            if isinstance(item, ast.AST):
                for n in ast.walk(item):
                    if isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute) and \
                            n.func.attr == attr:
                        out.append(b)
    return out


def reaches(cfg, src_id: int, dst_id: int) -> bool:
    seen = set()
    stack = [src_id]
    while stack:
        bid = stack.pop()
        if bid == dst_id:
            return True
        if bid in seen:
            continue
        seen.add(bid)
        stack.extend(t for (t, _l) in cfg.blocks[bid].succs)
    return False


# ------------------------------------------------------------------- CFG
def test_if_else_creates_labeled_branches_and_join():
    cfg = cfg_of("""
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
        """)
    conds = [b for b in cfg.blocks.values()
             if b.items and isinstance(b.items[-1], Cond)]
    assert len(conds) == 1
    labels = sorted(lbl for (_t, lbl) in conds[0].succs)
    assert labels == ["false", "true"]


def test_early_return_gives_exit_two_predecessors():
    cfg = cfg_of("""
        def f(x):
            if x:
                return 1
            return 2
        """)
    preds = {bid for (bid, _l) in cfg.predecessors(cfg.exit)}
    assert len(preds) == 2


def test_try_finally_routes_return_through_finally():
    """A return inside try must reach exit ONLY via the finally block —
    the property R008's release-in-finally discipline rests on."""
    cfg = cfg_of("""
        def f(self):
            self.acq()
            try:
                return self.work()
            finally:
                self.rel()
        """)
    (ret_block,) = [b for b in cfg.blocks.values()
                    if any(isinstance(i, ast.Return) for i in b.items)]
    (fin_block,) = blocks_calling(cfg, "rel")
    # the return does not edge straight to exit…
    assert (cfg.exit, None) not in ret_block.succs
    # …it enters the finally, whose end reaches exit
    assert any(t == fin_block.id for (t, _l) in ret_block.succs)
    assert reaches(cfg, fin_block.id, cfg.exit)


def test_try_except_edges_body_to_handler():
    cfg = cfg_of("""
        def f(self):
            try:
                self.work()
            except ValueError:
                self.recover()
            self.after()
        """)
    (body,) = blocks_calling(cfg, "work")
    (handler,) = blocks_calling(cfg, "recover")
    (after,) = blocks_calling(cfg, "after")
    assert any(t == handler.id for (t, _l) in body.succs)
    assert reaches(cfg, handler.id, after.id)
    assert reaches(cfg, body.id, after.id)


def test_with_emits_enter_and_exit_markers():
    cfg = cfg_of("""
        def f(self):
            with self.lock:
                self.work()
        """)
    items = [i for b in cfg.blocks.values() for i in b.items]
    assert any(isinstance(i, WithEnter) for i in items)
    assert any(isinstance(i, WithExit) for i in items)


def test_with_early_return_skips_exit_marker_block():
    """A return inside with terminates the block stream — the WithExit
    marker only sits on the fall-through path."""
    cfg = cfg_of("""
        def f(self):
            with self.lock:
                return self.work()
        """)
    items = [i for b in cfg.blocks.values() for i in b.items]
    assert any(isinstance(i, WithEnter) for i in items)
    assert not any(isinstance(i, WithExit) for i in items)


def test_while_loop_has_back_edge():
    cfg = cfg_of("""
        def f(n):
            i = 0
            while i < n:
                i += 1
            return i
        """)
    assert len(cfg.back_edges()) == 1


def test_for_loop_back_edge_and_loopiter_marker():
    cfg = cfg_of("""
        def f(xs):
            total = 0
            for x in xs:
                total += x
            return total
        """)
    assert len(cfg.back_edges()) == 1
    items = [i for b in cfg.blocks.values() for i in b.items]
    assert any(isinstance(i, LoopIter) for i in items)


def test_break_exits_loop_without_back_edge_traversal():
    cfg = cfg_of("""
        def f(xs):
            for x in xs:
                if x:
                    break
            return 1
        """)
    (ret_block,) = [b for b in cfg.blocks.values()
                    if any(isinstance(i, ast.Return) for i in b.items)]
    # the break path reaches the return without re-entering the loop head
    assert reaches(cfg, cfg.entry, ret_block.id)
    assert len(cfg.back_edges()) == 1


def test_continue_targets_loop_head():
    cfg = cfg_of("""
        def f(xs):
            for x in xs:
                if x:
                    continue
                use(x)
            return 1
        """)
    # continue closes a second path to the loop head: entry-block edge plus
    # back-edges; the graph must still reach exit
    assert reaches(cfg, cfg.entry, cfg.exit)
    assert len(cfg.back_edges()) >= 1


def test_raise_with_no_handler_is_an_exit_path():
    cfg = cfg_of("""
        def f(self):
            if self.bad:
                raise RuntimeError("boom")
            return 1
        """)
    preds = {bid for (bid, _l) in cfg.predecessors(cfg.exit)}
    assert len(preds) == 2


def test_iter_functions_qualnames():
    src = parse("""
        def top():
            def inner():
                pass
        class C:
            def m(self):
                pass
            class D:
                def n(self):
                    pass
        """)
    names = {qn for qn, _n in iter_functions(src.tree)}
    assert names == {"top", "top.inner", "C.m", "C.D.n"}


def test_walk_local_does_not_descend_into_nested_defs():
    src = parse("""
        def outer():
            x = 1
            def inner():
                y = 2
            return x
        """)
    (outer,) = [n for qn, n in iter_functions(src.tree) if qn == "outer"]
    assigned = {t.id for n in walk_local(outer)
                if isinstance(n, ast.Assign)
                for t in n.targets if isinstance(t, ast.Name)}
    assert assigned == {"x"}


# -------------------------------------------------------------- dataflow
def _acquire_release_transfer(state, item, block):
    """Toy R008: gen 'held' on .acq(), kill on .rel()."""
    if not isinstance(item, ast.AST):
        return state
    for n in ast.walk(item):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr == "acq":
                state = state | {"held"}
            elif n.func.attr == "rel":
                state = state - {"held"}
    return state


def _exit_state(text):
    cfg = cfg_of(text)
    states = dataflow.run_forward(cfg, _acquire_release_transfer)
    return states.get(cfg.exit, frozenset())


def test_dataflow_finally_release_clears_exit_state():
    assert _exit_state("""
        def f(self):
            self.acq()
            try:
                return self.work()
            finally:
                self.rel()
        """) == frozenset()


def test_dataflow_early_return_unions_paths_at_exit():
    """May-analysis: one escaping path is enough for the fact to stand at
    exit, even when another path releases."""
    assert "held" in _exit_state("""
        def f(self):
            self.acq()
            if self.fast:
                return 1
            self.rel()
            return 2
        """)


def test_dataflow_loop_reaches_fixpoint():
    """A gen inside a loop converges (facts are a finite set); the held
    fact survives the back edge and escapes at fall-off."""
    assert "held" in _exit_state("""
        def f(self, xs):
            for x in xs:
                self.acq(x)
            return 1
        """)


def test_dataflow_branch_balanced_release_is_clean():
    assert _exit_state("""
        def f(self):
            self.acq()
            if self.a:
                self.rel()
            else:
                self.rel()
            return 1
        """) == frozenset()


# ------------------------------------------------------------- callgraph
def graph(*files) -> CallGraph:
    return CallGraph([parse(t, p) for (t, p) in files])


def test_module_name_normalization():
    assert module_name("spark_rapids_tpu/memory/store.py") == \
        "spark_rapids_tpu.memory.store"
    assert module_name("spark_rapids_tpu/analysis/__init__.py") == \
        "spark_rapids_tpu.analysis"


def test_self_method_resolution():
    g = graph(("""
        class C:
            def a(self):
                self.b()
            def b(self):
                pass
        """, "pkg/m.py"))
    assert g.callees("pkg/m.py::C.a") == {"pkg/m.py::C.b"}


def test_self_method_resolves_through_base_class():
    g = graph(("""
        class Base:
            def helper(self):
                pass
        class Child(Base):
            def run(self):
                self.helper()
        """, "pkg/m.py"))
    assert g.callees("pkg/m.py::Child.run") == {"pkg/m.py::Base.helper"}


def test_module_function_and_nested_sibling_resolution():
    g = graph(("""
        def util():
            pass
        def top():
            util()
            def inner():
                pass
            inner()
        """, "pkg/m.py"))
    assert g.callees("pkg/m.py::top") == {"pkg/m.py::util",
                                          "pkg/m.py::top.inner"}


def test_from_import_resolution_across_modules():
    g = graph(
        ("""
            def shared():
                pass
         """, "pkg/util.py"),
        ("""
            from pkg.util import shared
            def caller():
                shared()
         """, "pkg/m.py"))
    assert g.callees("pkg/m.py::caller") == {"pkg/util.py::shared"}


def test_module_alias_resolution():
    g = graph(
        ("""
            def helper():
                pass
         """, "pkg/util.py"),
        ("""
            import pkg.util as u
            def caller():
                u.helper()
         """, "pkg/m.py"))
    assert g.callees("pkg/m.py::caller") == {"pkg/util.py::helper"}


def test_attr_name_typing_resolution():
    """self.catalog = BufferCatalog() teaches the graph that any
    ``*.catalog.remove()`` goes to BufferCatalog.remove."""
    g = graph(
        ("""
            class BufferCatalog:
                def remove(self, bid):
                    pass
         """, "pkg/catalog.py"),
        ("""
            from pkg.catalog import BufferCatalog
            class DeviceManager:
                def __init__(self):
                    self.catalog = BufferCatalog()
                def drop(self, bid):
                    self.catalog.remove(bid)
         """, "pkg/dm.py"))
    assert "pkg/catalog.py::BufferCatalog.remove" in \
        g.callees("pkg/dm.py::DeviceManager.drop")


def test_unique_method_fallback_and_common_name_refusal():
    g = graph(("""
        class Only:
            def frobnicate(self):
                pass
            def get(self):
                pass
        def caller(x):
            x.frobnicate()
            x.get()
        """, "pkg/m.py"))
    # unique uncommon method name resolves; 'get' is builtin-collection
    # vocabulary and must NOT resolve through the fallback
    assert g.callees("pkg/m.py::caller") == {"pkg/m.py::Only.frobnicate"}


def test_instantiation_edges_to_init():
    g = graph(("""
        class Widget:
            def __init__(self):
                pass
        def make():
            return Widget()
        """, "pkg/m.py"))
    assert g.callees("pkg/m.py::make") == {"pkg/m.py::Widget.__init__"}


def test_reachable_is_depth_bounded():
    chain = "\n".join(
        f"def f{i}():\n    f{i + 1}()" for i in range(10)
    ) + "\ndef f10():\n    pass\n"
    g = graph((chain, "pkg/chain.py"))
    root = "pkg/chain.py::f0"
    shallow = g.reachable([root], max_depth=3)
    assert f"pkg/chain.py::f3" in shallow
    assert f"pkg/chain.py::f4" not in shallow
    deep = g.reachable([root], max_depth=64)
    assert f"pkg/chain.py::f10" in deep


def test_reachable_terminates_on_mutual_recursion():
    g = graph(("""
        def ping():
            pong()
        def pong():
            ping()
        """, "pkg/m.py"))
    got = g.reachable(["pkg/m.py::ping"], max_depth=1000)
    assert got == {"pkg/m.py::ping", "pkg/m.py::pong"}


def test_calls_inside_nested_defs_belong_to_the_nested_function():
    g = graph(("""
        def helper():
            pass
        def outer():
            def inner():
                helper()
            return inner
        """, "pkg/m.py"))
    assert "pkg/m.py::helper" not in g.callees("pkg/m.py::outer")
    assert g.callees("pkg/m.py::outer.inner") == {"pkg/m.py::helper"}


def test_nested_finally_abrupt_exit_routes_through_outer_finally():
    """Review regression: a return escaping two try/finally levels passes
    through BOTH finally bodies before reaching exit."""
    cfg = cfg_of("""
        def f(self):
            try:
                try:
                    return self.work()
                finally:
                    self.inner_cleanup()
            finally:
                self.outer_cleanup()
        """)
    (ret_block,) = [b for b in cfg.blocks.values()
                    if any(isinstance(i, ast.Return) for i in b.items)]
    (inner,) = blocks_calling(cfg, "inner_cleanup")
    (outer,) = blocks_calling(cfg, "outer_cleanup")
    assert (cfg.exit, None) not in ret_block.succs
    assert (cfg.exit, None) not in inner.succs
    assert reaches(cfg, ret_block.id, inner.id)
    assert reaches(cfg, inner.id, outer.id)
    assert reaches(cfg, outer.id, cfg.exit)


def test_break_does_not_execute_loop_else():
    """Review regression: ``break`` jumps past the for/while ``else``
    clause — routing it INTO the else body made R008 miss leaks released
    only on normal exhaustion."""
    cfg = cfg_of("""
        def f(self, items):
            for x in items:
                if x:
                    break
            else:
                self.on_exhausted()
            return 1
        """)
    (orelse_blk,) = blocks_calling(cfg, "on_exhausted")
    (head,) = [b for b in cfg.blocks.values()
               if b.items and isinstance(b.items[-1], LoopIter)]
    (cond,) = [b for b in cfg.blocks.values()
               if b.items and isinstance(b.items[-1], Cond)]
    (then_id,) = [t for (t, lbl) in cond.succs if lbl == "true"]
    # normal exhaustion (head FALSE) runs the else clause…
    (false_id,) = [t for (t, lbl) in head.succs if lbl == "false"]
    assert false_id == orelse_blk.id or reaches(cfg, false_id, orelse_blk.id)
    # …but the break path must NOT pass through it
    assert not reaches(cfg, then_id, orelse_blk.id)
    # both paths still reach the statement after the loop
    (ret_block,) = [b for b in cfg.blocks.values()
                    if any(isinstance(i, ast.Return) for i in b.items)]
    assert reaches(cfg, then_id, ret_block.id)
    assert reaches(cfg, orelse_blk.id, ret_block.id)


def test_nested_try_raise_reaches_outer_except():
    """Review regression: a raise inside a finally-only try must land in
    the ENCLOSING except — replacing the handler set per try level severed
    the outer release path and falsely flagged R008."""
    cfg = cfg_of("""
        def f(self):
            self.acq()
            try:
                try:
                    raise ValueError("x")
                finally:
                    self.log()
            except ValueError:
                self.rel()
        """)
    (raise_blk,) = [b for b in cfg.blocks.values()
                    if any(isinstance(i, ast.Raise) for i in b.items)]
    (handler_blk,) = blocks_calling(cfg, "rel")
    assert reaches(cfg, raise_blk.id, handler_blk.id)
    # no escape to exit that bypasses the handler: every raise successor
    # chain hits the handler before exit
    def reaches_avoiding(src_id, dst_id, avoid_id):
        seen, stack = set(), [src_id]
        while stack:
            bid = stack.pop()
            if bid == avoid_id:
                continue
            if bid == dst_id:
                return True
            if bid in seen:
                continue
            seen.add(bid)
            stack.extend(t for (t, _l) in cfg.blocks[bid].succs)
        return False
    assert not reaches_avoiding(raise_blk.id, cfg.exit, handler_blk.id)


def test_bare_call_does_not_capture_method_leaf_name():
    """Review regression: a bare call to a parameter/local named like some
    class's method must not resolve to that method through the module
    bare-name table."""
    g = graph(("""
        class Worker:
            def drain(self):
                pass
        def run_cb(drain):
            return drain()
        """, "pkg/m.py"))
    assert g.callees("pkg/m.py::run_cb") == set()


# ------------------------------------------------------- exception flow (v4)
def flow(*files) -> ExceptionFlow:
    return ExceptionFlow([parse(t, p) for (t, p) in files])


def test_may_raise_direct_and_propagated():
    f = flow(("""
        class BoomError(Exception):
            pass
        def leaf():
            raise BoomError("x")
        def mid():
            leaf()
        def top():
            mid()
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::leaf") == {"BoomError"}
    assert f.raises("pkg/m.py::mid") == {"BoomError"}
    assert f.raises("pkg/m.py::top") == {"BoomError"}


def test_handler_subtracts_by_builtin_hierarchy():
    """``except OSError`` catches a propagated ConnectionResetError; a
    sibling ``except ValueError`` does not."""
    f = flow(("""
        def leaf():
            raise ConnectionResetError("peer gone")
        def caught():
            try:
                leaf()
            except OSError:
                return None
        def missed():
            try:
                leaf()
            except ValueError:
                return None
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::caught") == frozenset()
    assert f.raises("pkg/m.py::missed") == {"ConnectionResetError"}


def test_handler_subtracts_by_package_class_hierarchy():
    f = flow(("""
        class EngineError(Exception):
            pass
        class FetchError(EngineError):
            pass
        def leaf():
            raise FetchError("x")
        def caught():
            try:
                leaf()
            except EngineError:
                return None
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::caught") == frozenset()


def test_bare_raise_and_raise_e_propagate_the_caught_subset():
    f = flow(("""
        def leaf():
            raise KeyError("k")
        def bare():
            try:
                leaf()
            except Exception:
                raise
        def named():
            try:
                leaf()
            except Exception as e:
                raise e
        def swallowed():
            try:
                leaf()
            except Exception:
                return None
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::bare") == {"KeyError"}
    assert f.raises("pkg/m.py::named") == {"KeyError"}
    assert f.raises("pkg/m.py::swallowed") == frozenset()


def test_convert_records_conversion_and_rewrites_the_escape_set():
    f = flow(("""
        class WrapError(Exception):
            pass
        def leaf():
            raise ValueError("v")
        def convert():
            try:
                leaf()
            except ValueError as e:
                raise WrapError("wrapped") from e
        def top():
            convert()
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::convert") == {"WrapError"}
    assert f.raises("pkg/m.py::top") == {"WrapError"}
    convs = [c for c in f.conversions if c.func.key == "pkg/m.py::convert"]
    assert len(convs) == 1
    assert convs[0].caught == {"ValueError"}
    assert convs[0].to_name == "WrapError"


def test_fixpoint_terminates_on_mutual_recursion():
    f = flow(("""
        def ping(n):
            if n:
                pong(n - 1)
            raise RuntimeError("depth")
        def pong(n):
            if n:
                ping(n - 1)
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::ping") == {"RuntimeError"}
    assert f.raises("pkg/m.py::pong") == {"RuntimeError"}


def test_finally_raises_union_in_and_body_escapes_survive():
    f = flow(("""
        def f():
            try:
                raise KeyError("k")
            finally:
                cleanup()
        def cleanup():
            raise OSError("close failed")
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::f") == {"KeyError", "OSError"}


def test_unresolved_clause_subtracts_all_but_records_no_handler_fact():
    """A dynamically-computed except clause keeps may-raise an
    under-approximation (subtracts everything) without fabricating a
    HandlerFlow fact the rules could flag."""
    f = flow(("""
        def classes():
            return (ValueError,)
        def f():
            try:
                raise KeyError("k")
            except classes():
                return None
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::f") == frozenset()
    assert [hf for hf in f.handler_flows
            if hf.func.key == "pkg/m.py::f"] == []


def test_handler_flow_reports_arrivals_and_departures():
    f = flow(("""
        def leaf():
            raise KeyError("k")
        def f():
            try:
                leaf()
            except LookupError as e:
                raise ValueError("bad lookup")
        """, "pkg/m.py"))
    (hf,) = [h for h in f.handler_flows if h.func.key == "pkg/m.py::f"]
    assert hf.clause_names == ("LookupError",)
    assert hf.caught == {"KeyError"}
    assert hf.raised == {"ValueError"}


def test_decorated_finds_boundary_markers_by_leaf_name():
    f = flow(("""
        from spark_rapids_tpu.utils.errors import triage_boundary
        from spark_rapids_tpu.utils import errors as uerr
        @triage_boundary
        def a():
            pass
        @uerr.wire_boundary
        def b():
            pass
        def c():
            pass
        """, "pkg/m.py"))
    assert [i.key for i in f.decorated("triage_boundary")] == ["pkg/m.py::a"]
    assert [i.key for i in f.decorated("wire_boundary")] == ["pkg/m.py::b"]


def test_nested_def_body_does_not_raise_on_the_defining_path():
    """Defining a nested function whose body raises contributes nothing
    until the nested function is actually called."""
    f = flow(("""
        def outer():
            def inner():
                raise ValueError("x")
            return inner
        def caller():
            outer()
        """, "pkg/m.py"))
    assert f.raises("pkg/m.py::outer") == frozenset()
    assert f.raises("pkg/m.py::caller") == frozenset()
    assert f.raises("pkg/m.py::outer.inner") == {"ValueError"}

# ------------------------------------------ capture engine (v5, captures.py)
def test_free_paths_lambda_and_comprehension_scoping():
    """The v5 free-variable extractor sees through lambda and
    comprehension bodies with proper shadowing: their params/targets bind,
    everything else is free."""
    from spark_rapids_tpu.analysis.captures import free_paths
    tree = ast.parse(textwrap.dedent("""
        def f(a):
            g = lambda y: y + b + a
            xs = [c * i for i in range(3)]
            def h():
                return d
            return g, xs, h
        """))
    free = free_paths(tree.body[0])
    assert {"b", "c", "d"} <= free
    assert not {"a", "y", "i", "g", "xs", "h"} & free


def test_free_paths_attr_chain_and_store_receiver():
    from spark_rapids_tpu.analysis.captures import free_paths
    tree = ast.parse(textwrap.dedent("""
        def f():
            obj.slot = other.deep.value
            return conf.get
        """))
    free = free_paths(tree.body[0])
    assert "obj" in free           # store target's receiver is a READ
    assert "other.deep.value" in free
    assert "conf.get" in free


def test_free_paths_nested_def_shadowing():
    from spark_rapids_tpu.analysis.captures import free_paths
    tree = ast.parse(textwrap.dedent("""
        def f(cap):
            def inner(cap):
                return cap + smax
            return inner
        """))
    free = free_paths(tree.body[0])
    assert "smax" in free and "cap" not in free


def test_lambda_calls_are_deferred_edges_not_reachability_edges():
    """R009's semantics must not regress: a closure defined under a lock
    is not RUNNING under it, so lambda-body calls stay out of
    ``edges``/``reachable`` — but the capture analysis can see them via
    ``deferred_edges``/``callees_all``."""
    src = parse("""
        def helper():
            return 1
        def f():
            g = lambda: helper()
            return g
        """, path="pkg/m.py")
    cg = CallGraph([src])
    f_key, h_key = "pkg/m.py::f", "pkg/m.py::helper"
    assert h_key not in cg.edges[f_key]
    assert h_key in cg.deferred_edges[f_key]
    assert h_key in cg.callees_all(f_key)
    assert cg.reachable([f_key]) == {f_key}


def test_direct_calls_do_not_duplicate_into_deferred_edges():
    src = parse("""
        def helper():
            return 1
        def f():
            return helper()
        """, path="pkg/m.py")
    cg = CallGraph([src])
    assert "pkg/m.py::helper" in cg.edges["pkg/m.py::f"]
    assert cg.deferred_edges["pkg/m.py::f"] == set()
