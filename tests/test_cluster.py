"""Multi-executor query execution over the shuffle-manager stack: exchanges
write through CachingShuffleWriter into per-executor catalogs and reducers
fetch local blocks from the catalog and remote blocks via the transport —
in-process fabric, real TCP sockets, and executors in separate OS processes.
The round-2 VERDICT bar: the same query produces identical results via the
mesh-ICI path and the manager-TCP path."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tables_equal


def _tables(seed=5):
    rng = np.random.default_rng(seed)
    n = 20000
    fact = pa.table({
        "k": rng.integers(0, 400, n).astype(np.int64),
        "v": rng.integers(-100, 100, n).astype(np.int64),
        "s": pa.array([f"s{int(x)}" for x in rng.integers(0, 40, n)]),
    })
    dim = pa.table({
        "k": np.arange(400, dtype=np.int64),
        "name": pa.array([f"n{i}" for i in range(400)]),
    })
    return fact, dim


def _query(s, fact, dim):
    return (s.create_dataframe(fact).repartition(4, "k")
            .groupBy("k").agg(F.sum("v").alias("sv"),
                              F.count("s").alias("c"))
            .join(s.create_dataframe(dim), "k")
            .filter(F.col("sv") > -500)
            .sort("sv", "k"))


def _cpu_expected(fact, dim):
    s = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    return _query(s, fact, dim).collect()


CLUSTER_CONF = {
    "spark.rapids.tpu.sql.cluster.numExecutors": "2",
    "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
}


def test_cluster_inprocess_matches_cpu():
    fact, dim = _tables()
    s = TpuSession(CLUSTER_CONF)
    out = _query(s, fact, dim).collect()
    assert_tables_equal(_cpu_expected(fact, dim), out, ignore_order=True)
    sched = s._cluster_scheduler
    try:
        stages = sched.last_stages
        assert len(stages) >= 3  # repartition + join/agg exchanges + result
        map_stages = [st for st in stages if not st.is_result]
        assert map_stages and all(st.statuses for st in map_stages), (
            "every map stage must register MapStatus through the manager")
    finally:
        sched.close()


def test_cluster_tcp_matches_mesh_ici(tmp_path, eight_devices):
    """The VERDICT bar: identical results for the same query via the
    mesh-ICI collectives path and the shuffle-manager TCP path."""
    fact, dim = _tables(seed=11)
    tcp = TpuSession({
        **CLUSTER_CONF,
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg"),
    })
    via_tcp = _query(tcp, fact, dim).collect()
    tcp._cluster_scheduler.close()
    mesh = TpuSession({
        "spark.rapids.tpu.sql.mesh.enabled": "true",
        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
    })
    via_mesh = _query(mesh, fact, dim).collect()
    assert_tables_equal(_cpu_expected(fact, dim), via_tcp, ignore_order=True)
    assert_tables_equal(via_mesh, via_tcp, ignore_order=True)


def test_cluster_round_robin_and_single_exchanges():
    rng = np.random.default_rng(19)
    t = pa.table({"a": rng.integers(0, 50, 5000).astype(np.int32),
                  "b": rng.standard_normal(5000)})
    s = TpuSession({**CLUSTER_CONF,
                    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"})
    out = (s.create_dataframe(t).repartition(5)
           .groupBy("a").agg(F.avg("b").alias("ab")).sort("a")).collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.create_dataframe(t).repartition(5)
           .groupBy("a").agg(F.avg("b").alias("ab")).sort("a")).collect()
    assert_tables_equal(exp, out, approx_float=1e-9)
    s._cluster_scheduler.close()


def test_cluster_file_scan_spreads_tasks(tmp_path):
    """Multi-file scans widen to several scan tasks spread across executors
    (FilePartition planning), so map stages really fan out."""
    import pyarrow.parquet as pq
    rng = np.random.default_rng(23)
    for i in range(6):
        pq.write_table(
            pa.table({"k": rng.integers(0, 90, 800).astype(np.int64),
                      "v": rng.integers(0, 10, 800).astype(np.int64)}),
            str(tmp_path / f"f{i}.parquet"))
    s = TpuSession(CLUSTER_CONF)
    out = (s.read.parquet(str(tmp_path)).repartition(4, "k")
           .groupBy("k").agg(F.sum("v").alias("sv")).sort("k")).collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.read.parquet(str(tmp_path)).repartition(4, "k")
           .groupBy("k").agg(F.sum("v").alias("sv")).sort("k")).collect()
    assert_tables_equal(exp, out)
    sched = s._cluster_scheduler
    try:
        first_map = sched.last_stages[0]
        assert first_map.num_tasks > 1, "scan stage should fan out"
        executors = {st.executor_id for st in first_map.statuses}
        assert len(executors) == 2, (
            f"map tasks should spread across executors, got {executors}")
    finally:
        sched.close()


def test_cluster_range_exchange_sort_order():
    """Global sort through the cluster: range stage runs single-task (global
    sample) but the sorted output must come back in partition order."""
    rng = np.random.default_rng(29)
    t = pa.table({"v": rng.integers(-10000, 10000, 8000).astype(np.int64),
                  "s": pa.array([f"x{i%97}" for i in range(8000)])})
    s = TpuSession(CLUSTER_CONF)
    out = s.create_dataframe(t).repartition(4).sort("v", "s").collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = cpu.create_dataframe(t).repartition(4).sort("v", "s").collect()
    assert_tables_equal(exp, out)  # exact order, not ignore_order
    s._cluster_scheduler.close()


def test_cluster_aqe_coalesces_skewed_reduce_tasks():
    """AQE partition coalescing on the cluster path
    (GpuCustomShuffleReaderExec.scala:122 role): a skewed shuffle whose
    observed MapStatus sizes show mostly-tiny reduce partitions runs FEWER
    reduce tasks than partitions, with identical results."""
    rng = np.random.default_rng(31)
    n = 30000
    # heavy skew: ~95% of rows hash to one key, the rest spread thin
    k = np.where(rng.random(n) < 0.95, 7,
                 rng.integers(0, 4000, n)).astype(np.int64)
    t = pa.table({"k": k, "v": rng.integers(-50, 50, n).astype(np.int64)})

    def q(sess):
        return (sess.create_dataframe(t).repartition(16, "k")
                .groupBy("k").agg(F.sum("v").alias("sv"),
                                  F.count("v").alias("c")).sort("k"))

    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = q(cpu).collect()

    s = TpuSession({
        **CLUSTER_CONF,
        "spark.rapids.tpu.sql.adaptive.enabled": "true",
        # advisory sized so the tiny partitions group but the stage still
        # runs more than one reduce task
        "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes":
            "65536",
    })
    out = q(s).collect()
    assert_tables_equal(exp, out)
    sched = s._cluster_scheduler
    try:
        stages = sched.last_stages
        # the stage consuming the 16-partition repartition exchange must
        # have fewer tasks than reduce partitions (observed-size grouping)
        consumer = stages[1]
        assert consumer.num_tasks < 16, (
            f"expected coalesced reduce tasks, got {consumer.num_tasks}")
        assert consumer.num_tasks >= 1
    finally:
        sched.close()


def test_cluster_task_slots_run_concurrently():
    """Per-executor task parallelism: with numExecutors=1 and taskSlots>1, a
    stage's tasks overlap in time inside the executor (stage parallelism
    scales with partitions, not executors)."""
    import threading as _threading
    import time as _time

    from spark_rapids_tpu.parallel import cluster as cl

    active = {"now": 0, "peak": 0}
    lock = _threading.Lock()
    orig = cl._run_task

    def traced(env, spec):
        with lock:
            active["now"] += 1
            active["peak"] = max(active["peak"], active["now"])
        try:
            _time.sleep(0.05)      # widen the overlap window
            return orig(env, spec)
        finally:
            with lock:
                active["now"] -= 1

    rng = np.random.default_rng(37)
    t = pa.table({"k": rng.integers(0, 500, 20000).astype(np.int64),
                  "v": rng.integers(0, 100, 20000).astype(np.int64)})
    s = TpuSession({
        "spark.rapids.tpu.sql.cluster.numExecutors": "1",
        "spark.rapids.tpu.sql.cluster.taskSlots": "4",
    })
    cl._run_task = traced
    try:
        out = (s.create_dataframe(t).repartition(8, "k")
               .groupBy("k").agg(F.sum("v").alias("sv")).sort("k")).collect()
    finally:
        cl._run_task = orig
        s._cluster_scheduler.close()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.create_dataframe(t).repartition(8, "k")
           .groupBy("k").agg(F.sum("v").alias("sv")).sort("k")).collect()
    assert_tables_equal(exp, out)
    assert active["peak"] >= 2, (
        f"tasks never overlapped in the single executor: peak="
        f"{active['peak']}")


@pytest.mark.slow
def test_cluster_two_os_processes_tpch(tmp_path):
    """End-to-end TPC-H query across two OS-process executors: control plane
    over the driver socket, shuffle data over executor-to-executor TCP."""
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all
    from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
    tables = gen_all(0.002, seed=7)
    conf = {
        **BENCH_CONF,
        "spark.rapids.tpu.sql.cluster.numExecutors": "2",
        "spark.rapids.tpu.sql.cluster.processExecutors": "true",
        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
    }
    s = TpuSession(conf)
    dfs = {k: s.create_dataframe(v).repartition(2)
           for k, v in tables.items()}
    out = QUERIES[3](dfs).collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    cdfs = {k: cpu.create_dataframe(v).repartition(2)
            for k, v in tables.items()}
    exp = QUERIES[3](cdfs).collect()
    try:
        assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-9)
        sched = s._cluster_scheduler
        execs = {st.executor_id
                 for stage in sched.last_stages for st in stage.statuses}
        assert len(execs) == 2, f"both processes must do map work: {execs}"
    finally:
        s._cluster_scheduler.close()


@pytest.mark.slow
def test_cluster_tpcds_queries(tmp_path):
    """TPC-DS star joins + rollups through the multi-executor stage
    scheduler (in-process executors, real shuffle protocol)."""
    from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
    from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
    tables = gen_all(0.01, seed=0)
    conf = {
        "spark.rapids.tpu.sql.cluster.numExecutors": "2",
        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
        "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
        "spark.rapids.tpu.sql.hasNans": "false",
    }
    s = TpuSession(conf)
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    try:
        for q in ("q3", "q27", "q42", "q96"):
            dfs = {k: s.create_dataframe(v).repartition(2)
                   for k, v in tables.items()}
            cdfs = {k: cpu.create_dataframe(v).repartition(2)
                    for k, v in tables.items()}
            out = QUERIES[q](dfs).collect()
            exp = QUERIES[q](cdfs).collect()
            assert_tables_equal(exp, out, ignore_order=True,
                                approx_float=1e-9)
    finally:
        if getattr(s, "_cluster_scheduler", None):
            s._cluster_scheduler.close()


def test_cluster_broadcast_built_once_per_executor(monkeypatch):
    """Round-4 VERDICT item 4: a broadcast exchange is cut into its own
    driver-built stage — the build side executes ONCE (not once per map
    task) and each executor process deserializes the shipped bytes once."""
    from spark_rapids_tpu.parallel.broadcast import BroadcastManager
    from spark_rapids_tpu.parallel.cluster import ClusterBroadcastReadExec

    fact, dim = _tables(seed=3)
    s = TpuSession({"spark.rapids.tpu.sql.cluster.numExecutors": "2"})
    # default broadcast threshold (10 MB): the 400-row dim broadcasts
    df = (s.create_dataframe(fact).repartition(4, "k")
           .join(s.create_dataframe(dim), "k")
           .groupBy("name").agg(F.sum("v").alias("sv")).sort("name"))

    counts = {}
    orig_remove = BroadcastManager.remove.__func__

    def spy_remove(cls, bid):
        counts[bid] = cls.deserialize_count(bid)
        orig_remove(cls, bid)

    monkeypatch.setattr(BroadcastManager, "remove", classmethod(spy_remove))
    out = df.collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.create_dataframe(fact).repartition(4, "k")
              .join(cpu.create_dataframe(dim), "k")
              .groupBy("name").agg(F.sum("v").alias("sv")).sort("name")
              .collect())
    sched = s._cluster_scheduler
    try:
        assert_tables_equal(exp, out, ignore_order=True)
        stages = sched.last_stages
        bstages = [st for st in stages if st.is_broadcast]
        assert len(bstages) == 1, "broadcast exchange must become a stage"
        # the driver-side build executed exactly once: the exchange's own
        # output metric saw the dim rows a single time
        assert bstages[0].root.metrics["numOutputRows"].value == dim.num_rows
        # consumers read through the once-per-executor cache, not a rebuild
        consumer = [st for st in stages
                    if any(isinstance(n, ClusterBroadcastReadExec)
                           for n in _walk(st.root))]
        assert consumer, "a stage must consume the broadcast read leaf"
        # in-process executors share the driver registry: ONE deserialize
        # total despite 4 map tasks
        assert counts and all(v == 1 for v in counts.values()), counts
    finally:
        sched.close()


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


@pytest.mark.slow
def test_cluster_two_processes_broadcast_join_tpch(tmp_path):
    """Round-4 VERDICT item 4 done-bar: a broadcast-join TPC-H query (Q2
    shape: tiny region/nation broadcast against part/partsupp/supplier)
    green on the 2-OS-process cluster with the default broadcast
    threshold."""
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all
    from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
    tables = gen_all(0.002, seed=9)
    conf = {
        **BENCH_CONF,
        "spark.rapids.tpu.sql.cluster.numExecutors": "2",
        "spark.rapids.tpu.sql.cluster.processExecutors": "true",
    }
    # repartition the fact-side tables only: a repartitioned dimension has
    # no size estimate, which would defeat static broadcast selection
    facts = {"part", "partsupp", "lineitem", "orders"}

    def mk(sess):
        return {k: (sess.create_dataframe(v).repartition(2) if k in facts
                    else sess.create_dataframe(v))
                for k, v in tables.items()}

    s = TpuSession(conf)
    out = QUERIES[2](mk(s)).collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = QUERIES[2](mk(cpu)).collect()
    try:
        assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-9)
        sched = s._cluster_scheduler
        assert any(st.is_broadcast for st in sched.last_stages), (
            "Q2's dimension joins must ride the broadcast-stage cut")
    finally:
        s._cluster_scheduler.close()


def test_cluster_cached_scan_inprocess():
    """Round-4 VERDICT item 6: df.cache() no longer hands cluster queries
    back to the single-process engine — cached scans stage and serve from
    the (shared, in-process) catalog."""
    from spark_rapids_tpu.execs.cache_execs import TpuCachedScanExec
    fact, dim = _tables(seed=21)
    s = TpuSession({"spark.rapids.tpu.sql.cluster.numExecutors": "2"})
    cached = s.create_dataframe(fact).cache()
    df = (cached.repartition(4, "k").groupBy("k")
                .agg(F.sum("v").alias("sv")).sort("k"))
    out = df.collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.create_dataframe(fact).repartition(4, "k").groupBy("k")
              .agg(F.sum("v").alias("sv")).sort("k").collect())
    sched = s._cluster_scheduler
    try:
        assert_tables_equal(exp, out, ignore_order=True)
        stages = sched.last_stages
        assert any(isinstance(n, TpuCachedScanExec)
                   for st in stages for n in _walk(st.root)), (
            "the cached scan must ride the cluster stages, not a fallback")
    finally:
        sched.close()


@pytest.mark.slow
def test_cluster_cached_scan_two_processes(monkeypatch):
    """Cached buffers ship ONCE per executor process (generation-tracked),
    serve from each executor's own spillable catalog, and a second action
    re-uses the shipped copy without re-shipping; unpersist drops them."""
    from spark_rapids_tpu.execs.cache_execs import TpuCachedScanExec
    from spark_rapids_tpu.parallel.cluster import ProcessExecutor
    fact, dim = _tables(seed=22)
    s = TpuSession({
        "spark.rapids.tpu.sql.cluster.numExecutors": "2",
        "spark.rapids.tpu.sql.cluster.processExecutors": "true",
    })
    pushes = []
    orig = ProcessExecutor.put_cache

    def spy(self, tid, gen, parts):
        pushes.append((self.executor_id, tid, gen))
        orig(self, tid, gen, parts)

    monkeypatch.setattr(ProcessExecutor, "put_cache", spy)
    cached = s.create_dataframe(fact).cache()

    def q():
        return (cached.repartition(4, "k").groupBy("k")
                .agg(F.sum("v").alias("sv"), F.count("s").alias("c"))
                .sort("k"))

    out1 = q().collect()
    assert len(pushes) == 2, f"one push per executor, got {pushes}"
    out2 = q().collect()          # second action: no re-ship
    assert len(pushes) == 2, f"re-shipped on second action: {pushes}"
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    exp = (cpu.create_dataframe(fact).repartition(4, "k").groupBy("k")
              .agg(F.sum("v").alias("sv"), F.count("s").alias("c"))
              .sort("k").collect())
    sched = s._cluster_scheduler
    try:
        assert_tables_equal(exp, out1, ignore_order=True)
        assert_tables_equal(exp, out2, ignore_order=True)
        assert any(isinstance(n, TpuCachedScanExec)
                   for st in sched.last_stages for n in _walk(st.root))
        cached.unpersist()
        assert not sched._shipped_caches, "unpersist must clear ship state"
        # a post-unpersist action recomputes (fresh generation ships again)
        out3 = q().collect()
        assert_tables_equal(exp, out3, ignore_order=True)
    finally:
        sched.close()


@pytest.mark.slow
def test_cluster_four_processes_tpch(tmp_path):
    """Round-4 VERDICT item 7: the TCP fabric past 2 executors — TPC-H Q3
    across FOUR OS-process executors, all doing map work."""
    from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
    from spark_rapids_tpu.benchmarks.tpch_data import gen_all
    from spark_rapids_tpu.benchmarks.tpch_queries import QUERIES
    tables = gen_all(0.002, seed=13)
    conf = {
        **BENCH_CONF,
        "spark.rapids.tpu.sql.cluster.numExecutors": "4",
        "spark.rapids.tpu.sql.cluster.processExecutors": "true",
        "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "1",
    }
    s = TpuSession(conf)
    dfs = {k: s.create_dataframe(v).repartition(4)
           for k, v in tables.items()}
    out = QUERIES[3](dfs).collect()
    cpu = TpuSession({"spark.rapids.tpu.sql.enabled": "false"})
    cdfs = {k: cpu.create_dataframe(v).repartition(4)
            for k, v in tables.items()}
    exp = QUERIES[3](cdfs).collect()
    try:
        assert_tables_equal(exp, out, ignore_order=True, approx_float=1e-9)
        sched = s._cluster_scheduler
        execs = {st.executor_id
                 for stage in sched.last_stages for st in stage.statuses}
        assert len(execs) == 4, f"all four processes must do map work: {execs}"
    finally:
        s._cluster_scheduler.close()
