"""AQE tests: custom shuffle reader coalescing + dynamic broadcast join switch
(GpuCustomShuffleReaderExec / optimizeAdaptiveTransitions analog coverage)."""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.plan.adaptive import coalesce_specs
from spark_rapids_tpu.testing import assert_tables_equal

AQE = {"spark.rapids.tpu.sql.adaptive.enabled": "true"}


def table(n=100):
    return pa.table({"a": pa.array(np.arange(n), type=pa.int64()),
                     "b": pa.array(np.arange(n) % 7, type=pa.int64())})


def test_coalesce_specs():
    # groups accumulate until the advisory size is reached
    assert coalesce_specs([10, 10, 10, 10], 25) == ((0, 1, 2), (3,))
    assert coalesce_specs([30, 30], 25) == ((0,), (1,))
    assert coalesce_specs([1, 1, 1], 1000) == ((0, 1, 2),)
    assert coalesce_specs([], 10) == ((),)
    # empty partitions fold into their neighbors
    assert coalesce_specs([0, 0, 50, 0], 25) == ((0, 1, 2), (3,))


def test_reader_coalesces_small_partitions():
    t = table()
    s = TpuSession(AQE)
    out = (s.create_dataframe(t).repartition(6, "b")
           .filter(F.col("a") > 10).collect())
    plan = s.last_plan.tree_string()
    assert "TpuCustomShuffleReaderExec" in plan
    assert out.num_rows == 89

    # same answer without AQE
    s2 = TpuSession()
    ref = (s2.create_dataframe(t).repartition(6, "b")
           .filter(F.col("a") > 10).collect())
    assert "CustomShuffleReader" not in s2.last_plan.tree_string()
    assert_tables_equal(ref.sort_by("a"), out.sort_by("a"))


def test_reader_respects_advisory_size():
    # a tiny advisory size keeps every (non-empty) partition separate -> no
    # reader; round-robin spreads rows so no partition is empty
    t = table()
    s = TpuSession({**AQE,
                    "spark.rapids.tpu.sql.adaptive."
                    "advisoryPartitionSizeInBytes": "1"})
    out = (s.create_dataframe(t).repartition(4)
           .filter(F.col("a") > 10).collect())
    assert "CustomShuffleReader" not in s.last_plan.tree_string()
    assert out.num_rows == 89


def test_dynamic_broadcast_join_switch():
    t = table()

    rt_t = pa.table({"b": pa.array(np.arange(100) % 7, type=pa.int64()),
                     "n": pa.array(np.arange(100), type=pa.int64())})

    # The build side's STATIC estimate cannot see the filter's
    # selectivity (PR 11 size_estimate audit: a filter passes its child
    # through as an upper bound, ~1.6 KB here), so with this threshold
    # static planning keeps the shuffled join; the OBSERVED materialized
    # exchange (30 filtered rows, ~480 B) sits below it, so only AQE's
    # runtime statistics can legally broadcast — the exact
    # estimate-vs-observation gap the switch exists for.
    threshold = {"spark.rapids.tpu.sql.broadcastJoinThreshold.bytes":
                     "1000"}

    def run(conf):
        s = TpuSession({**threshold, **conf})
        lt = s.create_dataframe(t).repartition(4, "b")
        rt = (s.create_dataframe(rt_t).filter(F.col("n") < 30)
              .repartition(3, "b"))
        return lt.join(rt, "b").sort("b", "a").collect(), s

    aqe_res, s_aqe = run(AQE)
    plan = s_aqe.last_plan.tree_string()
    assert "TpuBroadcastHashJoinExec" in plan
    assert "TpuBroadcastExchangeExec" in plan
    assert "TpuCustomShuffleReaderExec" in plan

    ref, s_ref = run({})
    assert "TpuShuffledHashJoinExec" in s_ref.last_plan.tree_string()
    assert_tables_equal(ref, aqe_res)


def test_static_broadcast_from_audited_estimates():
    """PR 11: the size_estimate audit gave aggregates/exchanges real
    upper bounds, so a build side KNOWN small at plan time broadcasts
    statically — no AQE needed (the Spark statistics-driven
    autoBroadcastJoinThreshold behavior)."""
    t = table()
    s = TpuSession()
    lt = s.create_dataframe(t).repartition(4, "b")
    rt = (s.create_dataframe(t).repartition(3, "b")
          .groupBy("b").agg(F.count().alias("n")))
    out = lt.join(rt, "b").sort("b", "a").collect()
    plan = s.last_plan.tree_string()
    assert "TpuBroadcastHashJoinExec" in plan, plan
    assert out.num_rows == 100


def test_broadcast_switch_respects_threshold():
    t = table(500)
    s = TpuSession({**AQE,
                    "spark.rapids.tpu.sql.broadcastJoinThreshold.bytes": "10"})
    lt = s.create_dataframe(t).repartition(4, "b")
    rt = (s.create_dataframe(t).repartition(3, "b")
          .groupBy("b").agg(F.count().alias("n")))
    out = lt.join(rt, "b").sort("b", "a").collect()
    plan = s.last_plan.tree_string()
    assert "TpuShuffledHashJoinExec" in plan, plan
    assert out.num_rows == 500


def test_aqe_on_cpu_engine():
    """The fallback engine adapts too (CpuCustomShuffleReaderExec)."""
    t = table()
    s = TpuSession({**AQE, "spark.rapids.tpu.sql.enabled": "false"})
    out = (s.create_dataframe(t).repartition(5, "b")
           .filter(F.col("a") > 10).collect())
    assert "CpuCustomShuffleReaderExec" in s.last_plan.tree_string()
    assert out.num_rows == 89


def test_aqe_full_query_pipeline():
    """Join + aggregate + sort under AQE matches non-AQE output."""
    t = table(300)

    def run(conf):
        s = TpuSession(conf)
        lt = s.create_dataframe(t).repartition(4, "b")
        rt = (s.create_dataframe(t).repartition(3, "b")
              .groupBy("b").agg(F.sum("a").alias("sa")))
        return (lt.join(rt, "b")
                .groupBy("b").agg(F.count().alias("n"), F.max("sa").alias("m"))
                .sort("b").collect())

    assert_tables_equal(run({}), run(AQE))


def test_broadcast_switch_restores_limit_semantics():
    """Regression: after the switch the join emits the stream partitioning;
    a limit planned for single-partition input must still see one partition."""
    t = table()
    def run(conf):
        s = TpuSession(conf)
        lt = s.create_dataframe(t).repartition(4, "b")
        rt = (s.create_dataframe(t).repartition(3, "b")
              .groupBy("b").agg(F.count().alias("n")))
        return lt.join(rt, "b").limit(5).collect()
    assert run(AQE).num_rows == 5
    assert run({}).num_rows == 5


def test_broadcast_switch_restores_agg_distribution():
    """Regression: non-co-partitioned aggregate above a switched join must
    still produce global groups."""
    t = table()
    def run(conf):
        s = TpuSession(conf)
        lt = s.create_dataframe(t).repartition(4, "b")
        rt = (s.create_dataframe(t).repartition(3, "b")
              .groupBy("b").agg(F.count().alias("n")))
        return (lt.join(rt, "b")
                .groupBy("a").agg(F.count().alias("c"))
                .sort("a").collect())
    assert_tables_equal(run({}), run(AQE))
