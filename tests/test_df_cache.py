"""df.cache()/persist(): cached plans materialize into the spillable store
and later actions scan the buffers instead of recomputing.

Reference behavior being mirrored: Spark's CacheManager substitutes cached
subtrees with InMemoryRelation, and the reference plugin accelerates scanning
that cache (HostColumnarToGpu.scala:222; pytest `cache` area, SURVEY.md §4).
"""
import os

import pyarrow as pa
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession, _iter_execs
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.execs.cache_execs import (CpuCachedScanExec,
                                                TpuCachedScanExec)
from spark_rapids_tpu.memory.buffer import StorageTier
from spark_rapids_tpu.memory.device_manager import DeviceManager


def _sess(**conf):
    return TpuSession(conf or None)


def _table(n=1000):
    return pa.table({
        "k": [i % 7 for i in range(n)],
        "v": [float(i) for i in range(n)],
        "s": [f"row{i % 13}" for i in range(n)],
    })


def _sorted_pylist(t: pa.Table):
    return sorted(t.to_pylist(), key=lambda r: tuple(str(v) for v in r.values()))


@pytest.fixture(autouse=True)
def _fresh_device_manager():
    DeviceManager.shutdown()
    yield
    DeviceManager.shutdown()


def test_cache_serves_second_action_from_store():
    sess = _sess()
    df = sess.create_dataframe(_table()).filter(F.col("k") > 2).cache()
    first = df.collect()
    entry = sess.cache_manager.lookup(df._plan)
    assert entry is not None and entry.is_materialized
    assert entry.buffer_ids, "materialization produced no buffers"
    # the executed plan now scans the cache on the TPU
    second = df.collect()
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    assert _sorted_pylist(first) == _sorted_pylist(second)


def test_derived_dataframe_reuses_cached_subtree():
    sess = _sess()
    base = sess.create_dataframe(_table()).withColumn(
        "v2", F.col("v") * 2.0).cache()
    base.count()                         # materialize
    derived = base.groupBy("k").agg(F.sum("v2").alias("s2"))
    got = derived.collect()
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    # oracle: same query without any caching
    plain = _sess()
    want = (plain.create_dataframe(_table())
            .withColumn("v2", F.col("v") * 2.0)
            .groupBy("k").agg(F.sum("v2").alias("s2"))).collect()
    assert _sorted_pylist(got) == _sorted_pylist(want)


def test_cache_is_lazy_until_first_action():
    sess = _sess()
    df = sess.create_dataframe(_table()).cache()
    entry = sess.cache_manager.lookup(df._plan)
    assert entry is not None and not entry.is_materialized
    df.collect()
    assert entry.is_materialized


def test_cache_serves_without_recompute(tmp_path):
    """Delete the source file after materialization: a recompute would fail,
    a true cache read succeeds."""
    path = str(tmp_path / "t.parquet")
    import pyarrow.parquet as pq
    pq.write_table(_table(200), path)
    sess = _sess()
    df = sess.read.parquet(path).cache()
    want = _sorted_pylist(df.collect())
    os.unlink(path)
    got = _sorted_pylist(df.filter(F.col("k") >= 0).collect())
    assert got == want


def test_unpersist_frees_buffers_and_recomputes():
    sess = _sess()
    df = sess.create_dataframe(_table()).cache()
    df.collect()
    entry = sess.cache_manager.lookup(df._plan)
    ids = list(entry.buffer_ids)
    assert df.is_cached
    df.unpersist()
    assert not df.is_cached
    catalog = DeviceManager.get().catalog
    live = set(catalog.ids())
    assert not any(bid in live for bid in ids)
    # still correct, just recomputed (no cached scan in the plan)
    df.collect()
    assert not any(isinstance(n, (TpuCachedScanExec, CpuCachedScanExec))
                   for n in _iter_execs(sess.last_plan))


def test_cached_buffers_spill_and_still_serve():
    """Squeeze the device budget so the cached batch spills down the chain;
    the scan re-uploads from host/disk and results stay identical."""
    sess = _sess()
    df = sess.create_dataframe(_table(2000)).cache()
    want = _sorted_pylist(df.collect())
    entry = sess.cache_manager.lookup(df._plan)
    dm = DeviceManager.get()
    dm.device_store.spill_to_size(0)     # force everything down a tier
    catalog = dm.catalog
    for bid in entry.buffer_ids:
        buf = catalog.acquire(bid)
        assert buf is not None
        assert buf.tier != StorageTier.DEVICE
        buf.close()
    got = _sorted_pylist(df.collect())
    assert got == want
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))


def test_cached_scan_cpu_fallback_matches():
    """With cachedScan.enabled=false the cache is served to the CPU engine
    (CpuCachedScanExec) and results match the TPU path."""
    sess = _sess(**{"spark.rapids.tpu.sql.cachedScan.enabled": False})
    df = sess.create_dataframe(_table()).filter(F.col("v") < 500).cache()
    got = df.collect()
    assert any(isinstance(n, CpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    assert not any(isinstance(n, TpuCachedScanExec)
                   for n in _iter_execs(sess.last_plan))
    on = _sess()
    want = on.create_dataframe(_table()).filter(F.col("v") < 500).collect()
    assert _sorted_pylist(got) == _sorted_pylist(want)


def test_two_consumers_materialize_once():
    sess = _sess()
    df = sess.create_dataframe(_table()).cache()
    a = df.groupBy("k").agg(F.count().alias("n")).collect()
    entry = sess.cache_manager.lookup(df._plan)
    ids_after_first = list(entry.buffer_ids)
    b = df.groupBy("k").agg(F.count().alias("n")).collect()
    assert list(entry.buffer_ids) == ids_after_first
    assert _sorted_pylist(a) == _sorted_pylist(b)


def test_cache_with_nulls_and_strings_roundtrip():
    t = pa.table({
        "k": pa.array([1, None, 3, None, 5], type=pa.int64()),
        "s": pa.array(["a", None, "ccc", "", None]),
        "d": pa.array([1.5, None, float("nan"), -0.0, 2.25]),
    })
    sess = _sess()
    df = sess.create_dataframe(t).cache()
    first = df.collect()
    second = df.collect()   # served from cache
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    assert first.to_pydict().keys() == second.to_pydict().keys()
    import math
    for col in first.column_names:
        fa, sa = first.column(col).to_pylist(), second.column(col).to_pylist()
        for x, y in zip(fa, sa):
            if isinstance(x, float) and isinstance(y, float) \
                    and math.isnan(x) and math.isnan(y):
                continue
            assert x == y, (col, x, y)


def test_clear_cache():
    sess = _sess()
    a = sess.create_dataframe(_table()).cache()
    b = sess.range(100).cache()
    a.collect(); b.collect()
    sess.clear_cache()
    assert not a.is_cached and not b.is_cached
    live = set(DeviceManager.get().catalog.ids())
    assert not live, f"cache buffers leaked: {live}"


def test_cache_under_mesh_session():
    """A mesh-enabled session still answers cached queries correctly (the
    cached scan is a single-device leaf; mesh lowering must compose or
    fall back, never corrupt)."""
    sess = _sess(**{"spark.rapids.tpu.mesh.enabled": True})
    df = sess.create_dataframe(_table()).cache()
    want = _sorted_pylist(df.collect())
    got = _sorted_pylist(df.collect())
    assert got == want


def test_cached_aggregate_feeds_join():
    sess = _sess()
    agg = (sess.create_dataframe(_table())
           .groupBy("k").agg(F.avg("v").alias("av")).cache())
    agg.collect()
    dim = sess.create_dataframe(pa.table({"k": [0, 1, 2, 3, 4, 5, 6],
                                          "name": list("abcdefg")}))
    got = dim.join(agg, "k").collect()
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    plain = _sess()
    want = plain.create_dataframe(pa.table(
        {"k": [0, 1, 2, 3, 4, 5, 6], "name": list("abcdefg")})).join(
        plain.create_dataframe(_table()).groupBy("k").agg(
            F.avg("v").alias("av")), "k").collect()
    assert _sorted_pylist(got) == _sorted_pylist(want)


def test_cpu_cached_scan_keeps_long_strings():
    """Regression: the CPU cached scan of a DEVICE-tier buffer must keep the
    stored string width, not re-narrow to the default 256 bytes."""
    long_s = "x" * 500
    sess = _sess(**{"spark.rapids.tpu.sql.cachedScan.enabled": False,
                    "spark.rapids.tpu.sql.string.maxBytes": 1024})
    df = sess.create_dataframe(pa.table({"s": [long_s, "short"]})).cache()
    got = df.collect()          # materializes, then CPU-scans the cache
    got2 = df.collect()
    assert any(isinstance(n, CpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    assert got.column("s").to_pylist() == [long_s, "short"]
    assert got2.column("s").to_pylist() == [long_s, "short"]


def test_materialization_captures_device_batches():
    """Device-final plans hand their batches straight to the store — the
    cached buffer starts in the DEVICE tier without an arrow round trip."""
    sess = _sess()
    df = sess.create_dataframe(_table()).filter(F.col("k") < 5).cache()
    df.collect()
    entry = sess.cache_manager.lookup(df._plan)
    catalog = DeviceManager.get().catalog
    for bid in entry.buffer_ids:
        buf = catalog.acquire(bid)
        assert buf.tier == StorageTier.DEVICE
        buf.close()


def test_cached_scan_falls_back_from_cluster():
    """A cluster session still answers cached queries (single-process
    fallback: the buffers live in the driver's catalog)."""
    sess = _sess(**{"spark.rapids.tpu.cluster.executors": 2})
    df = sess.create_dataframe(_table()).cache()
    want = _sorted_pylist(df.collect())
    got = _sorted_pylist(df.groupBy("k").agg(F.count().alias("n")).collect())
    plain = _sess()
    wantg = _sorted_pylist(plain.create_dataframe(_table())
                           .groupBy("k").agg(F.count().alias("n")).collect())
    assert got == wantg and want


def test_sql_over_cached_view():
    """sess.sql over a view whose DataFrame is cached must scan the cache
    (the reference accelerates Spark-cached tables under SQL the same
    way)."""
    sess = _sess()
    df = sess.create_dataframe(_table()).cache()
    df.createOrReplaceTempView("cached_t")
    first = sess.sql("select k, sum(v) as sv from cached_t group by k "
                     "order by k").collect()
    assert any(isinstance(n, TpuCachedScanExec)
               for n in _iter_execs(sess.last_plan))
    second = sess.sql("select count(*) as n from cached_t").collect()
    assert second.column("n")[0].as_py() == 1000
    assert first.num_rows == 7


def test_dropped_session_finalizer_frees_cached_buffers():
    """Advisor (round 4): dropping a TpuSession without clearCache() must not
    leak cached buffers in the process-global DeviceManager catalog — a
    weakref.finalize on the session frees them when the session is GC'd."""
    import gc
    sess = _sess()
    df = sess.create_dataframe(_table()).cache()
    df.collect()
    ids = list(sess.cache_manager.lookup(df._plan).buffer_ids)
    assert any(bid in set(DeviceManager.get().catalog.ids()) for bid in ids)
    del df, sess
    gc.collect()
    live = set(DeviceManager.get().catalog.ids())
    assert not any(bid in live for bid in ids)
