"""Device scan cache: repeated actions reuse the uploaded batch; identity,
eviction and the disable conf behave as documented."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession, functions as F
from spark_rapids_tpu.memory.scan_cache import DeviceScanCache, get_cache


def _table(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({"a": rng.integers(0, 10, n), "b": rng.random(n)})


def test_repeated_collect_hits_cache():
    t = _table()
    sess = TpuSession({})
    df = sess.create_dataframe(t).groupBy("a").agg(F.count().alias("s"))
    r1 = df.collect()
    cache = get_cache(2 << 30)
    assert cache.get(t, sess.conf.string_max_bytes) is not None
    before = cache.get(t, sess.conf.string_max_bytes)
    r2 = df.collect()
    after = cache.get(t, sess.conf.string_max_bytes)
    assert before is after, "second action should reuse the cached upload"
    assert r1.equals(r2)


class FakeBatch:
    def __init__(self, nbytes=0):
        self.device_size_bytes = nbytes


def test_identity_not_equality():
    """A different table object never hits, even with equal contents."""
    cache = DeviceScanCache(1 << 20)
    t1, t2 = _table(seed=1), _table(seed=1)
    cache.put(t1, 64, FakeBatch())
    assert cache.get(t1, 64) is not None
    assert cache.get(t2, 64) is None


def test_eviction_by_budget():
    cache = DeviceScanCache(100)
    tables = [_table(n=2, seed=i) for i in range(4)]
    for t in tables:
        cache.put(t, 64, FakeBatch(40))
    # 4 * 40 > 100: the two least-recently-used entries were evicted
    assert cache.get(tables[0], 64) is None
    assert cache.get(tables[1], 64) is None
    assert cache.get(tables[2], 64) is not None
    assert cache.get(tables[3], 64) is not None


def test_oversized_entry_not_cached():
    cache = DeviceScanCache(10)
    t = _table(n=2)
    cache.put(t, 64, FakeBatch(100))
    assert cache.get(t, 64) is None


def test_budget_shrink_evicts_on_get_cache():
    from spark_rapids_tpu.memory import scan_cache as sc
    cache = sc.get_cache(1000)
    cache.clear()
    t = _table(n=2, seed=42)
    cache.put(t, 64, FakeBatch(500))
    assert cache.get(t, 64) is not None
    sc.get_cache(100)  # shrink budget -> sweep
    assert cache.get(t, 64) is None
    cache.clear()


def test_disable_conf():
    t = _table(seed=7)
    sess = TpuSession({"spark.rapids.tpu.sql.scanCache.enabled": "false"})
    df = sess.create_dataframe(t).agg(F.count().alias("s"))
    df.collect()
    cache = get_cache(2 << 30)
    assert cache.get(t, sess.conf.string_max_bytes) is None


def test_dead_table_entry_dropped():
    cache = DeviceScanCache(1 << 20)
    t = _table(n=3, seed=9)
    cache.put(t, 64, FakeBatch())
    del t
    cache._evict()
    assert not cache._entries
