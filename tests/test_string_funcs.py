"""Extended string function family (locate/trim-sides/initcap/replace/pad/
substring_index): CPU (numpy eager) vs device (jitted XLA) parity plus golden
Spark-semantics checks.

Reference analog: stringFunctions.scala GpuStringLocate/GpuStringTrimLeft/
GpuStringTrimRight/GpuInitCap/GpuStringReplace/GpuStringLPad/GpuStringRPad/
GpuSubstringIndex and the pytest string tests. ASCII scope on device, like
the engine's Upper/Lower."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

col = F.col

STRINGS = ["hello world", "  padded  ", "a,b,c,d", "aaa", "", "ab",
           None, "one two  three", "xxabxxabxx", ",lead", "trail,",
           "no match here", "aaaa", " x "]


def _df(sess):
    return sess.create_dataframe(pa.table({"s": pa.array(STRINGS)}))


def _golden(build, expected):
    cpu = assert_tpu_and_cpu_equal(build)
    got = cpu.column(cpu.column_names[-1]).to_pylist()
    assert got == expected, f"got {got}\nexpected {expected}"


def test_locate():
    def build(sess):
        return _df(sess).select("s", F.locate("a", col("s"), 2).alias("p"))

    def ref(s):
        if s is None:
            return None
        return s.find("a", 1) + 1  # python 0-based from idx1 -> 1-based

    _golden(build, [ref(s) for s in STRINGS])


def test_locate_edges():
    def build(sess):
        return _df(sess).select(
            F.locate("", col("s")).alias("empty"),
            F.locate("a", col("s"), 0).alias("zero_start"),
            F.instr(col("s"), "b").alias("instr"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("empty").to_pylist() == [
        None if s is None else 1 for s in STRINGS]
    assert cpu.column("zero_start").to_pylist() == [
        None if s is None else 0 for s in STRINGS]
    assert cpu.column("instr").to_pylist() == [
        None if s is None else s.find("b") + 1 for s in STRINGS]


def test_trim_sides():
    def build(sess):
        return _df(sess).select(F.ltrim(col("s")).alias("l"),
                                F.rtrim(col("s")).alias("r"),
                                F.trim(col("s")).alias("b"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("l").to_pylist() == [
        None if s is None else s.lstrip(" ") for s in STRINGS]
    assert cpu.column("r").to_pylist() == [
        None if s is None else s.rstrip(" ") for s in STRINGS]
    assert cpu.column("b").to_pylist() == [
        None if s is None else s.strip(" ") for s in STRINGS]


def test_trim_custom_chars():
    def build(sess):
        return _df(sess).select(F.ltrim(col("s"), ",x").alias("l"),
                                F.rtrim(col("s"), ",x").alias("r"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("l").to_pylist() == [
        None if s is None else s.lstrip(",x") for s in STRINGS]
    assert cpu.column("r").to_pylist() == [
        None if s is None else s.rstrip(",x") for s in STRINGS]


def test_initcap():
    def build(sess):
        return _df(sess).select(F.initcap(col("s")).alias("t"))

    def ref(s):
        # Spark: lowercase, then uppercase after each single space
        out, prev_space = [], True
        for ch in s.lower():
            out.append(ch.upper() if prev_space else ch)
            prev_space = ch == " "
        return "".join(out)

    _golden(build, [None if s is None else ref(s) for s in STRINGS])


def test_replace():
    def build(sess):
        return _df(sess).select(F.replace(col("s"), "ab", "XYZ").alias("t"))

    _golden(build, [None if s is None else s.replace("ab", "XYZ")
                    for s in STRINGS])


def test_replace_delete_and_empty_search():
    def build(sess):
        return _df(sess).select(F.replace(col("s"), "a").alias("d"),
                                F.replace(col("s"), "", "zz").alias("e"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("d").to_pylist() == [
        None if s is None else s.replace("a", "") for s in STRINGS]
    # empty search -> unchanged (reference GpuStringReplace)
    assert cpu.column("e").to_pylist() == STRINGS


def test_replace_overlapping_needles():
    t = pa.table({"s": pa.array(["aaaa", "aaa", "aa", "a", ""])})

    def build(sess):
        return (sess.create_dataframe(t)
                .select(F.replace(col("s"), "aa", "b").alias("t")))

    cpu = assert_tpu_and_cpu_equal(build)
    # greedy left-to-right, non-overlapping: aaaa->bb, aaa->ba
    assert cpu.column("t").to_pylist() == ["bb", "ba", "b", "a", ""]


def test_pad():
    def build(sess):
        return _df(sess).select(F.lpad(col("s"), 8, "*-").alias("l"),
                                F.rpad(col("s"), 8, "*-").alias("r"))

    def lp(s):
        if len(s) >= 8:
            return s[:8]
        fill = "*-" * 8
        return fill[:8 - len(s)] + s

    def rp(s):
        if len(s) >= 8:
            return s[:8]
        fill = "*-" * 8
        return s + fill[:8 - len(s)]

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("l").to_pylist() == [
        None if s is None else lp(s) for s in STRINGS]
    assert cpu.column("r").to_pylist() == [
        None if s is None else rp(s) for s in STRINGS]


def test_pad_multibyte_char_semantics():
    """Spark lpad/rpad count CHARACTERS, not bytes: multibyte input must get
    the right padded char length and truncation must never split a UTF-8
    sequence (reference BasePad, stringFunctions.scala:709)."""
    strings = ["é", "héllo", "日本語のテキスト", "", "ab", None, "ééé"]

    def build(sess):
        df = sess.create_dataframe(pa.table({"s": pa.array(strings)}))
        return df.select(F.lpad(col("s"), 3, "x").alias("l"),
                         F.rpad(col("s"), 2, "x").alias("r"),
                         F.lpad(col("s"), 4, "ü-").alias("lm"))

    def lp(s, n, p):
        if len(s) >= n:
            return s[:n]
        fill = p * n
        return fill[:n - len(s)] + s

    def rp(s, n, p):
        if len(s) >= n:
            return s[:n]
        fill = p * n
        return s + fill[:n - len(s)]

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("l").to_pylist() == [
        None if s is None else lp(s, 3, "x") for s in strings]
    assert cpu.column("r").to_pylist() == [
        None if s is None else rp(s, 2, "x") for s in strings]
    assert cpu.column("lm").to_pylist() == [
        None if s is None else lp(s, 4, "ü-") for s in strings]


def test_pad_clamped_at_max_bytes_keeps_valid_utf8():
    """When the padded result overflows string.maxBytes, the byte clamp must
    round down to a char boundary — never emit a split UTF-8 sequence."""
    def build(sess):
        sess.set_conf("spark.rapids.tpu.sql.string.maxBytes", 256)
        df = sess.create_dataframe(pa.table({"s": pa.array(["a", "ééé"])}))
        return df.select(F.rpad(col("s"), 200, "é").alias("r"))

    cpu = assert_tpu_and_cpu_equal(build)
    for v in cpu.column("r").to_pylist():  # decodes cleanly, ends whole
        assert v.encode("utf-8").decode("utf-8") == v
        assert len(v.encode("utf-8")) <= 256


def test_substring_index():
    def build(sess):
        return _df(sess).select(
            F.substring_index(col("s"), ",", 2).alias("a"),
            F.substring_index(col("s"), ",", -1).alias("b"),
            F.substring_index(col("s"), ",", 0).alias("z"))

    def ref(s, cnt):
        parts = s.split(",")
        if cnt > 0:
            return s if len(parts) <= cnt else ",".join(parts[:cnt])
        return s if len(parts) <= -cnt else ",".join(parts[cnt:])

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("a").to_pylist() == [
        None if s is None else ref(s, 2) for s in STRINGS]
    assert cpu.column("b").to_pylist() == [
        None if s is None else ref(s, -1) for s in STRINGS]
    assert cpu.column("z").to_pylist() == [
        None if s is None else "" for s in STRINGS]


def test_null_literal_operands():
    """Null scalar operands match the reference's all-null / zero outputs."""
    from spark_rapids_tpu.api.column import Column
    from spark_rapids_tpu.exprs import (Literal, StringLocate, StringReplace,
                                        UnresolvedAttribute)
    from spark_rapids_tpu.columnar.dtypes import DType

    def build(sess):
        s = UnresolvedAttribute("s")
        return _df(sess).select(
            Column(StringLocate(Literal(None, DType.STRING), s,
                                Literal.of(1))).alias("null_sub"),
            Column(StringLocate(Literal.of("a"), s,
                                Literal(None, DType.INT))).alias("null_start"),
            Column(StringReplace(s, Literal(None, DType.STRING),
                                 Literal.of("x"))).alias("null_search"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("null_sub").to_pylist() == [None] * len(STRINGS)
    assert cpu.column("null_start").to_pylist() == [0] * len(STRINGS)
    assert cpu.column("null_search").to_pylist() == [None] * len(STRINGS)


def test_placement_on_tpu():
    # initcap is incompat-gated (ASCII-only case mapping), so opt in
    def build(sess):
        return _df(sess).select(F.initcap(F.replace(col("s"), "a", "b"))
                                .alias("t"))

    assert_tpu_and_cpu_equal(
        build,
        conf={"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"},
        expect_tpu_execs=["TpuProjectExec"])


def test_initcap_incompat_gated():
    """Without the incompat opt-in, initcap stays off the device (same gating
    as Upper/Lower's ASCII-only case mapping)."""
    from spark_rapids_tpu.testing import run_with_cpu_and_tpu

    def build(sess):
        return _df(sess).select(F.initcap(col("s")).alias("t"))

    _, _, sess = run_with_cpu_and_tpu(build)
    assert "initcap" in (sess.last_explain or "").lower() or \
        "InitCap" in (sess.last_explain or "")


def test_trim_rejects_non_ascii_trim_set():
    """Per-byte membership would strip partial UTF-8 sequences, so a
    multibyte trim set is rejected outright."""
    with pytest.raises(TypeError, match="ASCII"):
        F.ltrim(col("s"), "é").expr._trim_chars()


def test_locate_multibyte_char_positions():
    """Spark locate is character-based: multibyte chars count as one."""
    t = pa.table({"s": pa.array(["héllo", "ééa", "aéa", None])})

    def build(sess):
        return (sess.create_dataframe(t)
                .select(F.locate("l", col("s")).alias("l"),
                        F.locate("a", col("s"), 2).alias("a2")))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("l").to_pylist() == [3, 0, 0, None]
    # 'a' at char 1 in 'aéa' is before start=2; next is char 3
    assert cpu.column("a2").to_pylist() == [0, 3, 3, None]


def test_replace_grows_within_max_bytes():
    """Replacement longer than the search pattern grows rows up to the
    configured string width budget."""
    t = pa.table({"s": pa.array(["abab", "ab", "ba", None])})

    def build(sess):
        return (sess.create_dataframe(t)
                .select(F.replace(col("s"), "ab", "12345").alias("t")))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("t").to_pylist() == ["1234512345", "12345", "ba", None]
