"""Cross-process determinism of cache keys (the R016 root assumption).

The serving tier's warm-start contract (PR 8) and every R016 fix assume
that ``plan_key()`` and ``stable_key_hash()`` are pure functions of the
plan + conf — not of the process that computed them. Python's per-process
hash randomization (PYTHONHASHSEED) is the classic way this breaks: any
set/dict-iteration order leaking into a key repr produces keys that agree
within one process and disagree across restarts, which silently defeats
the on-disk program index (every warm start misses) without ever failing
a single-process test.

These tests run the key computation in TWO subprocesses with DIFFERENT
hash seeds and assert bit-for-bit agreement.
"""
import json
import os
import subprocess
import sys

_SCRIPT = r"""
import json, sys
import pyarrow as pa
from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
from spark_rapids_tpu.serving.program_cache import plan_key, stable_key_hash

sess = TpuSession({"spark.rapids.tpu.sql.string.maxBytes": "16"})
table = pa.table({
    "k": pa.array([1, 2, 3, 1], type=pa.int64()),
    "v": pa.array([0.5, 1.5, 2.5, 3.5], type=pa.float64()),
})
df = (sess.create_dataframe(table)
      .filter(F.col("v") > 1.0)
      .groupBy("k").agg(F.sum("v").alias("s")))
pk = plan_key(df._executed_plan(), sess.conf)

# representative program-cache keys: the shapes the R007 idiom set routes
# (agg / exchange / fused-stage), mixing Schema, DType and scalar buckets
schema = Schema([Field("k", DType.INT, True), Field("s", DType.STRING, True)])
keys = [
    ("agg", ("k",), ("sum",), None, (), (), schema, 1024, 16),
    ("exchange", schema, 2048, 16, 0, 0, 4),
    ("stage", ("project", "filter"), (), schema, schema, 4096, 16),
    ("mesh", "Jax05PlusShims", schema, 128, 64, "data"),
]
json.dump({"plan_key": pk,
           "hashes": [stable_key_hash(k) for k in keys]}, sys.stdout)
"""


def _run_keys(hashseed: str) -> dict:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", _SCRIPT], cwd=repo,
                          capture_output=True, text=True, env=env,
                          timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout)


def test_plan_key_and_hashes_agree_across_processes():
    a = _run_keys("1")
    b = _run_keys("2")
    assert a["plan_key"] == b["plan_key"]
    assert a["hashes"] == b["hashes"]


def test_stable_key_hash_is_repr_deterministic():
    """In-process spot check of the same property: the key vocabulary's
    reprs carry no memory addresses or unordered-collection iteration —
    the precondition for the subprocess test's bit-for-bit claim."""
    from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
    from spark_rapids_tpu.serving.program_cache import stable_key_hash
    s1 = Schema([Field("a", DType.INT, True), Field("b", DType.STRING, False)])
    s2 = Schema([Field("a", DType.INT, True), Field("b", DType.STRING, False)])
    k1 = ("agg", ("a",), s1, 1024, 16)
    k2 = ("agg", ("a",), s2, 1024, 16)
    assert stable_key_hash(k1) == stable_key_hash(k2)
    assert "0x" not in repr(k1)
