"""TPC-DS through the SQL frontend: raw SQL text must produce results
identical to the DataFrame translations (reference analog: Catalyst
consuming TpcdsLikeSpark.scala's SQL — TpcdsLikeSpark.scala:761)."""
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
from spark_rapids_tpu.benchmarks.tpcds_sql import SQL_QUERIES
from spark_rapids_tpu.testing import assert_tables_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

_CONF = {
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.tpu.sql.hasNans": "false",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
}

#: queries whose final sort keys can tie -> unordered compare
_TIES = {"q19", "q27", "q34", "q42", "q46", "q52", "q55", "q65", "q68",
         "q73", "q79", "q88", "q96", "q15", "q26", "q7", "q21", "q25",
         "q29", "q37", "q82", "q90", "q92", "q93", "q50", "q62", "q99",
         "q3", "q43", "q48", "q84", "q61", "q32", "q41", "q45", "q20",
         "q12", "q98", "q33", "q56", "q60",
         # non-unique sort keys (code review): ties may legally reorder
         "q6", "q67"}


_RAN = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_cache_clear():
    """40 SQL+DataFrame query pairs compile hundreds of XLA programs in ONE
    module; the per-module clear (conftest) is not enough — LLVM compiles
    near the end of the module die under the accumulated heap. Clear every
    few queries; the persistent on-disk cache keeps recompiles cheap."""
    yield
    _RAN["n"] += 1
    if _RAN["n"] % 6 == 0:
        import jax
        jax.clear_caches()
        from spark_rapids_tpu.execs import evaluator, tpu_execs
        if hasattr(tpu_execs, "_JIT_CACHE"):
            tpu_execs._JIT_CACHE.clear()
        evaluator._JIT_CACHE.clear()


@pytest.fixture(scope="module")
def sql_session():
    tables = gen_all(_SCALE, seed=0)
    sess = TpuSession(_CONF)
    for name, tab in tables.items():
        sess.create_dataframe(tab).createOrReplaceTempView(name)
    dfs = {k: sess.create_dataframe(v) for k, v in tables.items()}
    return sess, dfs


def test_sql_coverage_floor():
    """Full parity with the reference: every TPC-DS query runs as raw SQL
    (TpcdsLikeSpark.scala feeds all its queries through Catalyst as text;
    round-4 closes the same loop here — 99/99)."""
    assert set(SQL_QUERIES) == set(QUERIES), (
        sorted(set(QUERIES) - set(SQL_QUERIES)))


@pytest.mark.parametrize("qname", sorted(SQL_QUERIES,
                                         key=lambda n: int(n[1:])))
def test_tpcds_sql_matches_dataframe(qname, sql_session):
    sess, dfs = sql_session
    sql_out = sess.sql(SQL_QUERIES[qname]).collect()
    df_out = QUERIES[qname](dfs).collect()
    assert_tables_equal(df_out, sql_out, ignore_order=qname in _TIES,
                        approx_float=1e-7)
