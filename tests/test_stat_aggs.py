"""Statistical aggregates (stddev/variance/corr/covar) and DISTINCT-aggregate
rewrite: CPU-vs-TPU parity plus golden numpy/pandas cross-checks.

Reference analog: the pytest hash_aggregate tests; the reference GPU plugin
does not accelerate these in v0 (AggregateFunctions.scala covers
Count/Max/Min/Sum/Average/First/Last only) — this engine runs them on-device
through the same buffer-spec kernels.
"""
import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal, run_with_cpu_and_tpu

col = F.col


def _table(seed=7, n=200, nulls=True):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 8, n)
    x = rng.normal(size=n) * 10
    y = 3.0 * x + rng.normal(size=n)
    i = rng.integers(0, 5, n).astype(np.int64)
    kmask = rng.random(n) < 0.1 if nulls else np.zeros(n, bool)
    xmask = rng.random(n) < 0.15 if nulls else np.zeros(n, bool)
    ymask = rng.random(n) < 0.15 if nulls else np.zeros(n, bool)
    return pa.table({
        "k": pa.array([None if m else int(v) for v, m in zip(k, kmask)],
                      type=pa.int32()),
        "x": pa.array([None if m else float(v) for v, m in zip(x, xmask)]),
        "y": pa.array([None if m else float(v) for v, m in zip(y, ymask)]),
        "i": pa.array(i),
    })


def test_stddev_variance_matches_numpy():
    t = _table(nulls=False)

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.stddev("x").alias("sd"),
                     F.stddev_pop("x").alias("sdp"),
                     F.variance("x").alias("v"),
                     F.var_pop("x").alias("vp"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build, approx_float=1e-9)
    ks = cpu.column("k").to_pylist()
    karr = t.column("k").to_numpy()
    xarr = t.column("x").to_numpy()
    for row, kv in enumerate(ks):
        xs = xarr[karr == kv]
        assert cpu.column("sd")[row].as_py() == pytest.approx(np.std(xs, ddof=1))
        assert cpu.column("sdp")[row].as_py() == pytest.approx(np.std(xs))
        assert cpu.column("v")[row].as_py() == pytest.approx(np.var(xs, ddof=1))
        assert cpu.column("vp")[row].as_py() == pytest.approx(np.var(xs))


def test_corr_covar_matches_numpy():
    t = _table(nulls=False)

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.corr("x", "y").alias("c"),
                     F.covar_samp("x", "y").alias("cs"),
                     F.covar_pop("x", "y").alias("cp"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build, approx_float=1e-9)
    karr = t.column("k").to_numpy()
    xarr, yarr = t.column("x").to_numpy(), t.column("y").to_numpy()
    for row, kv in enumerate(cpu.column("k").to_pylist()):
        xs, ys = xarr[karr == kv], yarr[karr == kv]
        assert cpu.column("c")[row].as_py() == pytest.approx(
            np.corrcoef(xs, ys)[0, 1])
        assert cpu.column("cs")[row].as_py() == pytest.approx(
            np.cov(xs, ys, ddof=1)[0, 1])
        assert cpu.column("cp")[row].as_py() == pytest.approx(
            np.cov(xs, ys, ddof=0)[0, 1])


def test_stat_aggs_place_on_tpu():
    """With variableFloatAgg enabled, the whole aggregation runs on-device."""
    t = _table(nulls=True)

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.stddev("x").alias("sd"), F.corr("x", "y").alias("c"))
                .sort("k"))

    assert_tpu_and_cpu_equal(
        build,
        conf={"spark.rapids.tpu.sql.variableFloatAgg.enabled": "true"},
        approx_float=1e-9,
        expect_tpu_execs=["TpuHashAggregateExec"])


def test_stat_aggs_with_nulls_cpu_tpu_parity():
    t = _table(nulls=True)

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.stddev("x").alias("sd"),
                     F.variance("x").alias("v"),
                     F.corr("x", "y").alias("c"),
                     F.covar_pop("x", "y").alias("cp"),
                     F.count("x").alias("n"))
                .sort("k"))

    assert_tpu_and_cpu_equal(build, approx_float=1e-9)


def test_stat_aggs_degenerate_groups():
    # groups of size 1 -> stddev_samp/corr null; size 0 valid -> all null
    t = pa.table({
        "k": pa.array([0, 1, 1, 2], type=pa.int32()),
        "x": pa.array([5.0, 1.0, None, None]),
        "y": pa.array([2.0, 3.0, 4.0, 1.0]),
    })

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.stddev("x").alias("sd"),
                     F.stddev_pop("x").alias("sdp"),
                     F.corr("x", "y").alias("c"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("sd").to_pylist() == [None, None, None]
    assert cpu.column("sdp").to_pylist() == [0.0, 0.0, None]
    assert cpu.column("c").to_pylist() == [None, None, None]


def test_count_distinct_grouped_and_null_keys():
    t = _table(nulls=True)

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.countDistinct("i").alias("nd"),
                     F.sum("x").alias("sx"),
                     F.count().alias("n"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build, approx_float=1e-9)
    # golden: pandas nunique with null keys kept as a group
    import pandas as pd
    g = t.to_pandas().groupby("k", dropna=False)
    nd = {None if (isinstance(kv, float) and math.isnan(kv)) else int(kv): v
          for kv, v in g["i"].nunique().to_dict().items()}
    for row, kv in enumerate(cpu.column("k").to_pylist()):
        assert cpu.column("nd")[row].as_py() == nd[kv], f"group {kv}"


def test_count_distinct_counts_values_not_rows():
    t = pa.table({
        "k": pa.array([1, 1, 1, 2, 2], type=pa.int32()),
        "v": pa.array([3, 3, None, 4, 5], type=pa.int64()),
    })

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.countDistinct("v").alias("nd"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    # null is not counted (Spark count semantics); duplicates collapse
    assert cpu.column("nd").to_pylist() == [1, 2]


def test_sum_distinct():
    t = pa.table({
        "k": pa.array([1, 1, 1, 2], type=pa.int32()),
        "v": pa.array([3.0, 3.0, 2.0, 4.0]),
    })

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.sumDistinct("v").alias("sd"),
                     F.avg("v").alias("m"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("sd").to_pylist() == [5.0, 4.0]


def test_multiple_distinct_aggs_one_aggregation():
    """Exercises the multi-part join chain of the rewrite (parts[2:])."""
    t = pa.table({
        "k": pa.array([1, 1, 2, 2, None], type=pa.int32()),
        "v": pa.array([3, 3, 4, 5, 6], type=pa.int64()),
        "w": pa.array([1.0, 2.0, 2.0, 2.0, None]),
    })

    def build(sess):
        return (sess.create_dataframe(t).groupBy("k")
                .agg(F.countDistinct("v").alias("ndv"),
                     F.countDistinct("w").alias("ndw"),
                     F.count().alias("n"))
                .sort("k"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("ndv").to_pylist() == [1, 1, 2]
    assert cpu.column("ndw").to_pylist() == [0, 2, 1]
    assert cpu.column("n").to_pylist() == [1, 2, 2]


def test_distinct_agg_rejects_colliding_output_names():
    """The rewrite recombines subplans by name; colliding user-facing names
    (agg alias == key name, or duplicate key hints) must raise instead of
    silently misbinding."""
    import pytest
    from spark_rapids_tpu.api.dataframe import TpuSession
    t = pa.table({
        "k": pa.array([1, 1, 2], type=pa.int32()),
        "v": pa.array([3, 3, 4], type=pa.int64()),
    })
    sess = TpuSession.builder().getOrCreate()
    df = sess.create_dataframe(t)
    with pytest.raises(ValueError, match="duplicate output names"):
        df.groupBy("k").agg(F.countDistinct("v").alias("k"))
    with pytest.raises(ValueError, match="duplicate output names"):
        df.groupBy("k", "k").agg(F.countDistinct("v").alias("nd"))


def test_global_distinct_agg():
    t = _table(nulls=True)

    def build(sess):
        return sess.create_dataframe(t).agg(
            F.countDistinct("i").alias("nd"), F.count("i").alias("n"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("nd")[0].as_py() == len(set(
        v for v in t.column("i").to_pylist() if v is not None))


def test_distinct_agg_distributed_partitions():
    """Distinct rewrite composes with multi-partition execution + exchanges."""
    t = _table(nulls=True, n=500)

    def build(sess):
        df = sess.create_dataframe(t).repartition(4, "i")
        return (df.groupBy("k")
                .agg(F.countDistinct("i").alias("nd"),
                     F.stddev("x").alias("sd"))
                .sort("k"))

    assert_tpu_and_cpu_equal(build, approx_float=1e-9)
