"""TPC-DS executed DISTRIBUTED over the 8-device virtual mesh vs the CPU
engine — the round-2 VERDICT's 'mesh TPC-DS suite' bar: star joins,
rollups (MeshExpandExec), windows (MeshWindowExec), and high-cardinality
aggregations all riding the ICI exchange path, with AQE's runtime
broadcast switch live."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

MESH_CONF = {
    **BENCH_CONF,
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.adaptive.enabled": "true",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
}

#: round 4: ALL 99 queries distributed over the mesh (the reference
#: distributes every exec it supports; round-3 verdict item 6 asked >=60).
#: Star joins, rollups (MeshExpandExec), windows (MeshWindowExec),
#: multi-channel unions, count-distinct, returns chains, inventory scans,
#: shipping reports with (not) exists, scalar-subquery discounts,
#: cross-year CTE self-joins, full-outer channel comparison
_QUERIES = tuple(sorted(QUERIES, key=lambda n: int(n[1:])))


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_cache_writes():
    """PJRT executable.serialize() of the heaviest mesh programs segfaults
    under the suite's accumulated C++ heap (deterministically around the
    37th query; each query passes in isolation). Disable persistent-cache
    WRITES for this module — reads still serve cached programs."""
    from jax._src import compilation_cache as cc
    orig = cc.put_executable_and_time
    cc.put_executable_and_time = lambda *a, **k: None
    yield
    cc.put_executable_and_time = orig


_RAN = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_cache_clear():
    """Dozens of distributed query plans compile hundreds of XLA programs
    in ONE module; free compiled-executable memory every few tests."""
    yield
    _RAN["n"] += 1
    if _RAN["n"] % 4 == 0:
        import gc

        import jax
        jax.clear_caches()
        from spark_rapids_tpu.execs import evaluator, tpu_execs
        if hasattr(tpu_execs, "_JIT_CACHE"):
            tpu_execs._JIT_CACHE.clear()
        evaluator._JIT_CACHE.clear()
        gc.collect()


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=0)


@pytest.mark.parametrize("qname", _QUERIES)
def test_tpcds_query_matches_cpu_on_mesh(qname, tables, eight_devices):
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qname](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9)


def test_mesh_execs_cover_window_and_expand(tables, eight_devices):
    """The distributed plans must REALLY use the breadth operators: a rollup
    query lowers to MeshExpandExec and a window query to MeshWindowExec."""
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES["q27"](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshExpandExec", "MeshHashAggregateExec"])
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES["q67"](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshExpandExec", "MeshWindowExec"])
