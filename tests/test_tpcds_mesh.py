"""TPC-DS executed DISTRIBUTED over the 8-device virtual mesh vs the CPU
engine — the round-2 VERDICT's 'mesh TPC-DS suite' bar: star joins,
rollups (MeshExpandExec), windows (MeshWindowExec), and high-cardinality
aggregations all riding the ICI exchange path, with AQE's runtime
broadcast switch live."""
import pytest

from spark_rapids_tpu.benchmarks.tpch import BENCH_CONF
from spark_rapids_tpu.benchmarks.tpcds_data import gen_all
from spark_rapids_tpu.benchmarks.tpcds_queries import QUERIES
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

pytestmark = pytest.mark.slow

_SCALE = 0.01

MESH_CONF = {
    **BENCH_CONF,
    "spark.rapids.tpu.sql.mesh.enabled": "true",
    "spark.rapids.tpu.sql.adaptive.enabled": "true",
    "spark.rapids.tpu.sql.exec.NestedLoopJoin": "true",
    "spark.rapids.tpu.sql.exec.CartesianProduct": "true",
}

#: coverage-picked subset: plain star joins (q3/q7/q19/q42/q52/q55/q96),
#: rollup -> MeshExpandExec (q27/q36/q67/q86), window functions ->
#:   MeshWindowExec (q47/q51/q57/q63/q89), multi-channel unions (q60/q76),
#: count-distinct-heavy (q68/q34), high-group-count agg (q65)
_QUERIES = ("q3", "q7", "q19", "q27", "q34", "q36", "q42", "q47", "q51",
            "q52", "q55", "q57", "q60", "q63", "q65", "q67", "q68", "q76",
            "q86", "q89", "q96")


@pytest.fixture(scope="module")
def tables():
    return gen_all(_SCALE, seed=0)


@pytest.mark.parametrize("qname", _QUERIES)
def test_tpcds_query_matches_cpu_on_mesh(qname, tables, eight_devices):
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES[qname](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9)


def test_mesh_execs_cover_window_and_expand(tables, eight_devices):
    """The distributed plans must REALLY use the breadth operators: a rollup
    query lowers to MeshExpandExec and a window query to MeshWindowExec."""
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES["q27"](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshExpandExec", "MeshHashAggregateExec"])
    assert_tpu_and_cpu_equal(
        lambda s: QUERIES["q67"](
            {k: s.create_dataframe(v) for k, v in tables.items()}),
        conf=MESH_CONF, ignore_order=True, approx_float=1e-9,
        expect_tpu_execs=["MeshExpandExec", "MeshWindowExec"])
