"""Shuffle exchange + partitioning tests (reference analogs:
GpuPartitioningSuite, GpuSinglePartitioningSuite, HashSortOptimizeSuite
plan-shape assertions, repart integration tests)."""
import numpy as np
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api.functions import col, spark_partition_id
from spark_rapids_tpu.execs.exchange_execs import (CpuShuffleExchangeExec,
                                                   HashPartitioning,
                                                   RangePartitioning,
                                                   SinglePartitioning,
                                                   TpuShuffleExchangeExec,
                                                   hash_partition_ids)
from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.columnar.dtypes import DType


def _sessions():
    return (TpuSession({"spark.rapids.tpu.sql.enabled": "true"}),
            TpuSession({"spark.rapids.tpu.sql.enabled": "false"}))


def _data(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return {"a": rng.integers(0, 50, n).tolist(),
            "f": rng.normal(size=n).tolist(),
            "s": [f"k{int(v)}" for v in rng.integers(0, 11, n)]}


def test_repartition_preserves_rows():
    tpu, cpu = _sessions()
    data = _data()
    for sess in (tpu, cpu):
        t = sess.create_dataframe(data).repartition(5, "a").collect()
        assert sorted(t.column("a").to_pylist()) == sorted(data["a"])


def test_hash_partition_equal_keys_colocated():
    tpu, _ = _sessions()
    df = (tpu.create_dataframe(_data())
          .repartition(7, "s")
          .select(col("s"), spark_partition_id().alias("p")))
    t = df.collect()
    by_key = {}
    for s, p in zip(t.column("s").to_pylist(), t.column("p").to_pylist()):
        by_key.setdefault(s, set()).add(p)
    for key, parts in by_key.items():
        assert len(parts) == 1, f"{key} split across partitions {parts}"


def test_round_robin_balance():
    tpu, _ = _sessions()
    t = (tpu.create_dataframe(_data(300))
         .repartition(3)
         .select(spark_partition_id().alias("p"))).collect()
    counts = np.bincount(t.column("p").to_pylist(), minlength=3)
    assert counts.min() >= 80, counts  # roughly even

def test_global_sort_over_partitions():
    for sess in _sessions():
        df = (sess.create_dataframe(_data(400, seed=3))
              .repartition(4, "s").sort("a", "s"))
        t = df.collect()
        a = t.column("a").to_pylist()
        assert a == sorted(a)


def test_sort_desc_nulls_over_partitions():
    data = {"x": ([3, None, 1, 7, None, 2] * 30)}
    for sess in _sessions():
        t = (sess.create_dataframe(data).repartition(3)
             .sort(col("x").desc())).collect()
        xs = t.column("x").to_pylist()
        nn = [v for v in xs if v is not None]
        assert nn == sorted(nn, reverse=True)


def test_repartition_then_aggregate_parity():
    tpu, cpu = _sessions()
    data = _data(500, seed=5)
    res = []
    for sess in (tpu, cpu):
        t = (sess.create_dataframe(data).repartition(6, "s")
             .groupBy("s").count().sort("s")).collect()
        res.append(t.to_pydict())
    assert res[0] == res[1]


def test_exchange_plan_shape():
    tpu, _ = _sessions()
    df = tpu.create_dataframe(_data()).repartition(4, "a").groupBy("s").count()
    df.collect()
    plan = tpu.last_plan
    text = plan.tree_string()
    assert "TpuShuffleExchangeExec" in text


def test_exchange_falls_back_when_disabled():
    sess = TpuSession({
        "spark.rapids.tpu.sql.enabled": "true",
        "spark.rapids.tpu.sql.exec.ShuffleExchange": "false"})
    df = sess.create_dataframe(_data()).repartition(4, "a")
    t = df.collect()
    assert t.num_rows == 200
    assert "CpuShuffleExchangeExec" in sess.last_plan.tree_string()


def test_shuffle_cleanup_after_collect():
    tpu, _ = _sessions()
    from spark_rapids_tpu.memory.device_manager import DeviceManager
    tpu.create_dataframe(_data()).repartition(3, "a").collect()
    dm = DeviceManager.get()
    env = getattr(dm, "_exchange_shuffle_env", None)
    assert env is not None
    assert env.shuffle_catalog._blocks == {}


def test_hash_ids_null_and_nan_canonical():
    n = 8
    data = np.array([0.0, -0.0, np.nan, np.nan, 1.5, 1.5, 2.0, 3.0])
    validity = np.array([True] * 6 + [False, False])
    keys = [ColV(DType.DOUBLE, data, validity)]
    pids = hash_partition_ids(np, keys, n, 5)
    assert pids[0] == pids[1]      # -0.0 == 0.0
    assert pids[2] == pids[3]      # NaN == NaN
    assert pids[6] == pids[7]      # nulls co-located
    assert pids.dtype == np.int32


def test_string_hash_distribution():
    keys = [ColV(DType.STRING,
                 np.frombuffer("".join(f"key{i:04d}".ljust(8, "\0")
                                       for i in range(256)).encode(),
                               dtype=np.uint8).reshape(256, 8),
                 np.ones(256, bool), np.full(256, 7, np.int32))]
    pids = hash_partition_ids(np, keys, 256, 8)
    counts = np.bincount(pids, minlength=8)
    assert counts.min() > 10, counts


def test_fused_guard_rejects_double_subexpression():
    """Advisor (round 4): a hash key whose ROOT dtype is not DOUBLE but that
    computes over a DOUBLE column (cast, comparison) must not fuse — the
    fused program sees bitcast u64 bit-siblings where the per-batch paths
    see emulated f64 data, and the two can hash differently."""
    from spark_rapids_tpu.execs.exchange_execs import _NOT_FUSABLE
    from spark_rapids_tpu.exprs.core import BoundReference
    from spark_rapids_tpu.exprs.cast import Cast

    dbl = BoundReference(0, DType.DOUBLE)
    for key in (dbl,                              # root DOUBLE (old guard)
                Cast(dbl, DType.STRING),          # non-DOUBLE root, DOUBLE child
                Cast(Cast(dbl, DType.FLOAT), DType.INT)):  # nested
        part = HashPartitioning(4, keys=(key,))
        got = TpuShuffleExchangeExec._fused_pids_split(
            None, None, part, None, 0, 4, False)
        assert got is _NOT_FUSABLE, key


def test_fused_exchange_cast_double_key_correct():
    """End-to-end: repartition by a BOOLEAN comparison over a DOUBLE column
    keeps equal keys co-located and preserves every row."""
    tpu, cpu = _sessions()
    data = _data()
    outs = []
    for sess in (tpu, cpu):
        df = sess.create_dataframe(data)
        t = (df.repartition(4, col("f") > 0)
               .select(col("a"), col("f"), spark_partition_id().alias("p"))
               .collect())
        assert sorted(t.column("a").to_pylist()) == sorted(data["a"])
        by_key = {}
        for f, p in zip(t.column("f").to_pylist(), t.column("p").to_pylist()):
            by_key.setdefault(f, set()).add(p)
        assert all(len(ps) == 1 for ps in by_key.values())
        outs.append({k: next(iter(ps)) for k, ps in by_key.items()})
    # both engines must agree on the key -> partition assignment (the
    # disagreement the fused-path DOUBLE guard exists to prevent)
    assert outs[0] == outs[1]
