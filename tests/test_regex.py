"""Device regex engine (DFA over the byte matrix): general LIKE, RLIKE,
regexp_replace, split()[i], plus the new datetime/math/InSet expressions —
CPU (python re / numpy) vs device (jitted DFA scan) parity.

Reference analogs: stringFunctions.scala GpuLike/GpuRLike/GpuRegExpReplace/
GpuStringSplit, GpuInSet.scala:98, complexTypeExtractors.scala:88,
datetimeExpressions.scala unix-time family, mathExpressions.scala."""
import datetime

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

col = F.col
CONF = {"spark.rapids.tpu.sql.incompatibleOps.enabled": "true"}

STRINGS = ["hello world", "h3ll0", "aaa bbb ccc", "", None, "a,b,,c",
           "Customer XYZ Complaints", "MEDIUM POLISHED brass", "forest#12",
           "PROMO done", "xx12yy345", "no digits here"]


def _df(sess):
    return sess.create_dataframe(pa.table({"s": pa.array(STRINGS)}))


def test_general_like_patterns():
    def build(sess):
        return _df(sess).select(
            col("s").like("%Customer%Complaints%").alias("a"),
            col("s").like("h_ll_").alias("b"),
            col("s").like("%o_l%").alias("c"),
            col("s").like("a%c").alias("d"))

    cpu = assert_tpu_and_cpu_equal(build, conf=CONF)
    assert cpu.column("a").to_pylist()[6] is True
    assert cpu.column("b").to_pylist()[1] is True


def test_rlike():
    def build(sess):
        return _df(sess).select(
            col("s").rlike("[0-9]+").alias("digits"),
            col("s").rlike("^h").alias("starts_h"),
            col("s").rlike("b+ c").alias("bc"))

    cpu = assert_tpu_and_cpu_equal(build, conf=CONF)
    import re
    exp = [None if s is None else bool(re.search(r"[0-9]+", s))
           for s in STRINGS]
    assert cpu.column("digits").to_pylist() == exp


def test_regexp_replace():
    def build(sess):
        return _df(sess).select(
            F.regexp_replace(col("s"), "[0-9]+", "#").alias("r"),
            F.regexp_replace(col("s"), "l+", "L").alias("l"))

    cpu = assert_tpu_and_cpu_equal(build, conf=CONF)
    import re
    assert cpu.column("r").to_pylist() == [
        None if s is None else re.sub(r"[0-9]+", "#", s) for s in STRINGS]


def test_split_get_item():
    def build(sess):
        return _df(sess).select(
            F.split(col("s"), ",")[0].alias("p0"),
            F.split(col("s"), ",")[2].alias("p2"),
            F.split(col("s"), "[ ]+")[1].alias("w1"))

    cpu = assert_tpu_and_cpu_equal(build, conf=CONF)
    row = STRINGS.index("a,b,,c")
    assert cpu.column("p0").to_pylist()[row] == "a"
    assert cpu.column("p2").to_pylist()[row] == ""
    assert cpu.column("w1").to_pylist()[2] == "bbb"
    # out-of-range -> null
    assert cpu.column("p2").to_pylist()[0] is None


def test_unix_time_family_and_weekday():
    ts = [datetime.datetime(2001, 2, 3, 4, 5, 6),
          datetime.datetime(1969, 12, 31, 23, 59, 59), None]
    dates = [datetime.date(2020, 1, 6), datetime.date(1970, 1, 1), None]

    def build(sess):
        df = sess.create_dataframe(pa.table({
            "t": pa.array(ts, type=pa.timestamp("us")),
            "d": pa.array(dates)}))
        return df.select(
            F.unix_timestamp(col("t")).alias("ut"),
            F.to_unix_timestamp(col("d")).alias("ud"),
            F.from_unixtime(F.unix_timestamp(col("t"))).alias("fmt"),
            F.weekday(col("d")).alias("wd"))

    cpu = assert_tpu_and_cpu_equal(build)
    assert cpu.column("ut").to_pylist()[0] == int(
        ts[0].replace(tzinfo=datetime.timezone.utc).timestamp())
    assert cpu.column("fmt").to_pylist()[0] == "2001-02-03 04:05:06"
    assert cpu.column("wd").to_pylist() == [0, 3, None]  # Mon, Thu


def test_inset_large_list():
    vals = list(range(0, 4000, 7))
    t = pa.table({"v": pa.array([0, 7, 8, 3997, None, -7], type=pa.int64())})

    def build(sess):
        return sess.create_dataframe(t).select(
            col("v").isin(*vals).alias("m"))

    cpu = assert_tpu_and_cpu_equal(build)
    # 3997 = 7*571 IS in the set
    assert cpu.column("m").to_pylist() == [True, True, False, True, None,
                                           False]


def test_new_math_fns():
    t = pa.table({"x": pa.array([0.5, 1.5, -0.5, None])})

    def build(sess):
        return sess.create_dataframe(t).select(
            F.cot(col("x")).alias("cot"),
            F.asinh(col("x")).alias("ash"),
            F.atanh(col("x")).alias("ath"),
            F.log_base(2.0, col("x")).alias("lb"))

    cpu = assert_tpu_and_cpu_equal(build, approx_float=1e-12)
    assert cpu.column("lb").to_pylist()[2] is None  # log of negative -> null
    assert abs(cpu.column("cot").to_pylist()[0] - 1 / np.tan(0.5)) < 1e-12


def test_regex_fuzz_vs_python_re():
    """Random ASCII haystacks x a pattern pool: device DFA must agree with
    python re on match/replace for the supported subset."""
    import re
    rng = np.random.default_rng(11)
    alphabet = list("abc01 ,.")
    strs = ["".join(rng.choice(alphabet, rng.integers(0, 18)))
            for _ in range(120)] + [None]
    pats = [r"[0-9]+", r"a+b", r"(a|b)c", r"[a-c]*[0-9]", r"a.c", r"b,"]

    t = pa.table({"s": pa.array(strs)})
    for pat in pats:
        def build(sess, pat=pat):
            return sess.create_dataframe(t).select(
                col("s").rlike(pat).alias("m"),
                F.regexp_replace(col("s"), pat, "@").alias("r"))

        cpu = assert_tpu_and_cpu_equal(build, conf=CONF)
        rx = re.compile(pat)
        assert cpu.column("m").to_pylist() == [
            None if s is None else bool(rx.search(s)) for s in strs], pat
