"""Stale-program regressions for the R016 site fixes.

Each cached-program key widened in the capture-provenance PR guards a
concrete wrong-results shape: a builder observing a value the key omitted
would serve the FIRST caller's specialization to every later caller. These
tests pin (a) the failure mode itself against the real cache, and (b) the
widened keys at the real sites — provider identity, sharding specs, device
count — so a future key "simplification" reintroducing the collision fails
here, not in production results.
"""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu import device as _device  # noqa: F401 - jax setup
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_tpu.columnar.dtypes import DType, Field, Schema
from spark_rapids_tpu.execs.mesh_execs import _shard_jit
from spark_rapids_tpu.execs.tpu_execs import _JIT_CACHE, _cached_jit
from spark_rapids_tpu.parallel.mesh import DATA_AXIS, make_mesh
from spark_rapids_tpu.parallel.mesh_batch import gather_mesh, scatter_arrow
from spark_rapids_tpu.shuffle.ici import build_ici_repartition


def test_unkeyed_capture_serves_stale_program():
    """The hazard R016 machine-checks, reproduced against the real cache:
    a builder closing over a value its key omits returns the OLD
    specialization after the value changes — silently wrong results. The
    keyed variant below is the fix discipline every site in the package
    now follows."""
    captured = {"m": 2}

    def build():
        m = captured["m"]
        return lambda x: x * m

    f1 = _cached_jit(("r016-repro", "collision"), build)
    assert int(f1(jnp.int32(5))) == 10
    captured["m"] = 3
    f2 = _cached_jit(("r016-repro", "collision"), build)
    assert int(f2(jnp.int32(5))) == 10      # stale: still the m=2 program
    f3 = _cached_jit(("r016-repro", "keyed", captured["m"]), build)
    assert int(f3(jnp.int32(5))) == 15      # keyed: fresh specialization


def test_shard_jit_distinct_specs_get_distinct_programs():
    """Two callers sharing (mesh, key) but sharding differently must not
    share a compiled program — in_specs/out_specs are part of _shard_jit's
    inner key now."""
    mesh = make_mesh(2)

    def build():
        def fn(x):
            return x + 1
        return fn

    before = set(_JIT_CACHE)
    _shard_jit(mesh, ("r016-specs",), build, (P(DATA_AXIS),), (P(DATA_AXIS),))
    _shard_jit(mesh, ("r016-specs",), build, (P(),), (P(),))
    assert len(set(_JIT_CACHE) - before) == 2


def test_shard_jit_key_carries_shim_identity():
    """A shim-provider swap must never serve the old backend's shard_map
    program: the active provider's class name is an inner key component,
    resolved ONCE at key time (not re-read inside the cached builder)."""
    from spark_rapids_tpu import shims
    mesh = make_mesh(2)

    def build():
        def fn(x):
            return x * 2
        return fn

    _shard_jit(mesh, ("r016-shim",), build, (P(),), (P(),))
    name = type(shims.get()).__name__
    hits = [k for k in _JIT_CACHE
            if isinstance(k, tuple) and len(k) > 3 and k[0] == "mesh"
            and k[3] == ("r016-shim",)]
    assert hits and all(k[1] == name for k in hits)


def test_ici_repartition_key_carries_shim_identity():
    from spark_rapids_tpu import shims
    mesh = make_mesh(2)
    schema = Schema([Field("a", DType.INT, True)])
    build_ici_repartition(mesh, schema, 128)
    name = type(shims.get()).__name__
    hits = [k for k in _JIT_CACHE
            if isinstance(k, tuple) and k and k[0] == "ici-repart"]
    assert hits and all(k[1] == name for k in hits)


def test_gather_mesh_correct_across_device_counts():
    """The mesh-gather program reshapes over n_dev * cap: meshes of
    different device counts share the same (schema, local capacity) here
    — distinct programs must compile, and both must compact correctly in
    shard-major row order."""
    table = pa.table({"a": pa.array(range(12), type=pa.int64())})
    for n_dev in (2, 4):
        mb = scatter_arrow(table, make_mesh(n_dev), 16)
        db = gather_mesh(mb)
        assert db.num_rows == 12
        got = db.to_arrow().column("a").to_pylist()
        assert got == list(range(12)), (n_dev, got)
    keys = [k for k in _JIT_CACHE
            if isinstance(k, tuple) and k and k[0] == "mesh-gather"]
    n_devs = {k[4] for k in keys}
    assert {2, 4} <= n_devs
