"""TPU resource discovery (ExclusiveModeGpuDiscoveryPlugin analog)."""
import json
import subprocess
import sys

from spark_rapids_tpu import discovery


def test_discovery_script_protocol():
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.discovery"],
        capture_output=True, text=True, check=True,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": ":".join(sys.path)})
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["name"] == "tpu"
    assert len(doc["addresses"]) >= 1


def test_exclusive_claims_do_not_collide(tmp_path):
    d = str(tmp_path)   # isolated lock dir: parallel suites must not collide
    addrs = ["91", "92"]
    a = discovery.acquire_exclusive(addrs, lock_dir=d)
    b = discovery.acquire_exclusive(addrs, lock_dir=d)
    c = discovery.acquire_exclusive(addrs, lock_dir=d)
    try:
        assert a is not None and b is not None
        assert {a.address, b.address} == set(addrs)
        assert c is None  # everything claimed
    finally:
        for claim in (a, b):
            if claim:
                claim.release()
    # released devices are claimable again
    again = discovery.acquire_exclusive(addrs, lock_dir=d)
    assert again is not None
    again.release()
