"""TCP shuffle transport: the cross-host DCN path (UCX.scala analog) over
real sockets — same trait family as the in-process transport, exercised
in-process over loopback AND across two OS processes."""
import subprocess
import sys
import textwrap

import pyarrow as pa
import pytest

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.shuffle.tcp import TcpTransport
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                TransactionStatus)


def _conf(tmp_path):
    return TpuConf({
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg"),
        "spark.rapids.tpu.shuffle.bounceBuffers.size": 4096,
        "spark.rapids.tpu.shuffle.bounceBuffers.count": 8,
    })


def test_tcp_rpc_and_tagged_transfer(tmp_path):
    conf = _conf(tmp_path)
    a = TcpTransport("exec-a", conf)
    b = TcpTransport("exec-b", conf)
    try:
        b.server.register_request_handler(
            "echo", lambda peer, payload: b"from-b:" + payload)
        conn = a.connect("exec-b")
        tx = conn.request("echo", b"hello", lambda t: None).wait(10)
        assert tx.status is TransactionStatus.SUCCESS
        assert tx.response == b"from-b:hello"

        # tag-addressed transfer: b's server sends into a's posted receive
        buf = AddressLengthTag(bytearray(11), 11, tag=0x42)
        rx = conn.receive(buf, lambda t: None)
        sb = AddressLengthTag.for_bytes(b"payload-abc", tag=0x42)
        stx = b.server.send("exec-a", sb, lambda t: None).wait(10)
        assert stx.status is TransactionStatus.SUCCESS
        rx.wait(10)
        assert bytes(buf.buffer) == b"payload-abc"

        # error propagation: unknown handler -> transaction error
        err = conn.request("nope", b"", lambda t: None).wait(10)
        assert err.status is TransactionStatus.ERROR
        assert "no handler" in err.error_message
    finally:
        a.shutdown()
        b.shutdown()


def test_tcp_early_send_matches_late_receive(tmp_path):
    conf = _conf(tmp_path)
    a = TcpTransport("exec-a2", conf)
    b = TcpTransport("exec-b2", conf)
    try:
        conn = a.connect("exec-b2")
        # client sends BEFORE the server posts the receive: the data parks in
        # the early-data table and completes the receive when it arrives
        conn.send(AddressLengthTag.for_bytes(b"xyzzy", tag=7),
                  lambda t: None).wait(10)
        import time
        time.sleep(0.1)
        buf = AddressLengthTag(bytearray(5), 5, tag=7)
        # b posts the receive in its own transport (tag table is per process)
        rx_conn = b.connect("exec-a2")
        rx = rx_conn.receive(buf, lambda t: None).wait(10)
        assert rx.status is TransactionStatus.SUCCESS
        assert bytes(buf.buffer) == b"xyzzy"
    finally:
        a.shutdown()
        b.shutdown()


def test_two_executor_shuffle_roundtrip_over_tcp(tmp_path):
    """The VERDICT bar: the full cached-write/remote-fetch shuffle protocol
    (manager + catalogs + client/server state machines) riding the socket
    transport instead of the in-process fabric."""
    from tests.test_shuffle import (collect_partition, sample_table,
                                    two_env_cluster, write_partitioned)
    conf_overrides = {
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": str(tmp_path / "reg"),
    }
    mgr, e0, e1 = two_env_cluster(tmp_path, conf_overrides=conf_overrides)
    sid, _ = mgr.register_shuffle(2)
    t0 = sample_table(120, seed=1)
    t1 = sample_table(90, seed=2)
    write_partitioned(mgr, e0, sid, 0, t0, 2)
    write_partitioned(mgr, e1, sid, 1, t1, 2)
    got = collect_partition(mgr, e0, sid, 0)
    expected = pa.concat_tables([t0.take(list(range(0, 120, 2))),
                                 t1.take(list(range(0, 90, 2)))])
    assert got.sort_by("f").equals(expected.sort_by("f"))
    got1 = collect_partition(mgr, e1, sid, 1)
    exp1 = pa.concat_tables([t0.take(list(range(1, 120, 2))),
                             t1.take(list(range(1, 90, 2)))])
    assert sorted(got1["f"].to_pylist()) == sorted(exp1["f"].to_pylist())


_PEER_SCRIPT = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.shuffle.tcp import TcpTransport
    from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                    TransactionStatus)
    conf = TpuConf({{
        "spark.rapids.tpu.shuffle.transport.class":
            "spark_rapids_tpu.shuffle.tcp.TcpTransport",
        "spark.rapids.tpu.shuffle.tcp.registryDir": {reg!r}}})
    t = TcpTransport("exec-remote", conf)
    t.server.register_request_handler(
        "double", lambda peer, payload: payload * 2)
    # announce readiness, then serve until the driver kills us
    print("READY", flush=True)
    import time
    time.sleep(60)
""")


def test_cross_process_rpc(tmp_path):
    """Two OS processes: the peer registers over the registry directory, the
    local transport resolves and round-trips an RPC across the real network
    stack (the cross-host topology the in-process transport cannot cover)."""
    import os
    reg = str(tmp_path / "reg")
    script = _PEER_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        reg=reg)
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True,
                            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        line = proc.stdout.readline().strip()
        assert line == "READY", f"peer failed to start: {line}"
        conf = TpuConf({
            "spark.rapids.tpu.shuffle.tcp.registryDir": reg})
        local = TcpTransport("exec-local", conf)
        try:
            conn = local.connect("exec-remote")
            tx = conn.request("double", b"ab", lambda t: None).wait(15)
            assert tx.status is TransactionStatus.SUCCESS
            assert tx.response == b"abab"
        finally:
            local.shutdown()
    finally:
        proc.kill()
        proc.wait()
