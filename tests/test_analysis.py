"""tpu-lint coverage: every rule gets at least one true-positive and one
clean-negative fixture, plus suppression/baseline mechanics and a run over
the real package asserting zero non-baselined findings (the premerge gate's
contract)."""
import json
import os
import textwrap

import pytest

from spark_rapids_tpu.analysis import SourceFile, analyze_files
from spark_rapids_tpu.analysis import baseline as bl
from spark_rapids_tpu.analysis.__main__ import collect_files, main


def src(text: str, path: str = "mod.py") -> SourceFile:
    # fixtures concatenate the unindented GUARD line with an indented
    # triple-quoted body; dedent the body alone or dedent finds no common
    # prefix and leaves the fixture unparseable
    if text.startswith("from spark_rapids_tpu import device"):
        head, _, body = text.partition("\n")
        text = head + "\n" + textwrap.dedent(body)
    else:
        text = textwrap.dedent(text)
    return SourceFile(path, text, path)


def run(files, rules):
    if not isinstance(files, list):
        files = [files]
    res = analyze_files(files, rule_ids=set(rules))
    return res.findings


#: the x64 guard import every jax-importing module carries (keeps R003
#: quiet in fixtures that target other rules)
GUARD = "from spark_rapids_tpu import device as _device\n"


# ------------------------------------------------------------------ R001
def test_r001_jit_in_loop_flagged():
    fs = src(GUARD + """
        import jax
        def f(batches):
            outs = []
            for b in batches:
                fn = jax.jit(lambda x: x + 1)
                outs.append(fn(b))
            return outs
        """)
    found = run(fs, {"R001"})
    assert len(found) == 1 and "inside a loop" in found[0].message


def test_r001_immediate_invoke_flagged():
    fs = src(GUARD + """
        import jax
        def f(x):
            return jax.jit(lambda v: v * 2)(x)
        """)
    found = run(fs, {"R001"})
    assert len(found) == 1 and "invoked immediately" in found[0].message


def test_r001_cache_guard_clean():
    fs = src(GUARD + """
        import jax
        _PROGRAMS = {}
        def get(keys):
            fns = []
            for key in keys:
                fn = _PROGRAMS.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x)
                    _PROGRAMS[key] = fn
                fns.append(fn)
            return fns
        """)
    assert run(fs, {"R001"}) == []


def test_r001_module_level_jit_clean():
    fs = src(GUARD + """
        import jax
        def _impl(x):
            return x + 1
        fast = jax.jit(_impl)
        """)
    assert run(fs, {"R001"}) == []


# ------------------------------------------------------------------ R002
def test_r002_item_flagged_in_hot_path():
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="execs/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and ".item()" in found[0].message


def test_r002_scalar_cast_of_program_result_in_loop():
    fs = src(GUARD + """
        import jax
        def f(batches, build):
            fn = jax.jit(build)
            for b in batches:
                res = fn(b)
                n = int(res[-1])
                yield n
        """, path="ops/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and "inside a loop" in found[0].message


def test_r002_download_comprehension_in_loop():
    fs = src(GUARD + """
        import jax
        import numpy as np
        def f(batches, build):
            fn = jax.jit(build)
            for b in batches:
                flat = [np.asarray(a) for a in fn(b)]
                yield flat
        """, path="shuffle/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and "every output column" in found[0].message


def test_r002_nested_def_does_not_taint_outer_scope():
    """Regression: a nested helper's jit program must not make the OUTER
    function's unrelated loop variables look like device results."""
    fs = src(GUARD + """
        import jax
        def outer(host_counts, build):
            def helper(b):
                fn = jax.jit(build)
                res = fn(b)
                return res
            total = 0
            for res in host_counts:
                total += int(res)
            return total
        """, path="execs/foo.py")
    assert run(fs, {"R002"}) == []


def test_r002_clean_outside_loop_and_outside_hot_path():
    hot_clean = src(GUARD + """
        import jax
        import numpy as np
        def f(b, build):
            fn = jax.jit(build)
            res = fn(b)
            return int(res[-1])
        """, path="execs/foo.py")
    assert run(hot_clean, {"R002"}) == []
    # identical sync code outside the hot-path dirs is out of scope
    cold = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="benchmarks/foo.py")
    assert run(cold, {"R002"}) == []


# ------------------------------------------------------------------ R003
def test_r003_jax_import_without_device_guard():
    fs = src("""
        import jax
        def f(x):
            return jax.numpy.sum(x)
        """)
    found = run(fs, {"R003"})
    assert len(found) == 1 and "x32" in found[0].message


def test_r003_dtypeless_constructors():
    fs = src(GUARD + """
        import jax.numpy as jnp
        import numpy as np
        a = np.array([1, 2, 3])
        b = jnp.zeros(16)
        """)
    found = run(fs, {"R003"})
    assert len(found) == 2
    assert any("np.array" in f.message for f in found)
    assert any("jnp.zeros" in f.message for f in found)


def test_r003_clean_with_guard_and_dtypes():
    fs = src("""
        from spark_rapids_tpu import device as _device  # noqa: F401
        import jax.numpy as jnp
        import numpy as np
        a = np.array([1, 2, 3], dtype=np.int32)
        b = jnp.zeros(16, jnp.int32)
        c = jnp.arange(8, dtype=np.int32)
        strings = np.array(["CA", "TX"])  # non-numeric: dtype is unambiguous
        """)
    assert run(fs, {"R003"}) == []


# ------------------------------------------------------------------ R004
def test_r004_dead_and_unregistered_keys():
    config = src("""
        def _conf(key, conf_type, default, doc):
            pass
        USED = _conf("sql.used", bool, True, "read by engine.py")
        DEAD = _conf("sql.dead", bool, True, "never read")
        """, path="spark_rapids_tpu/config.py")
    engine = src("""
        from spark_rapids_tpu import config as cfg
        def f(conf):
            if conf.get(cfg.USED):
                return conf.get_raw("spark.rapids.tpu.sql.typoed.key")
        """, path="spark_rapids_tpu/engine.py")
    found = run([config, engine], {"R004"})
    assert len(found) == 2
    dead = [f for f in found if "never read" in f.message]
    unreg = [f for f in found if "not registered" in f.message]
    assert len(dead) == 1 and "sql.dead" in dead[0].message
    assert len(unreg) == 1 and "typoed" in unreg[0].message


def test_r004_needs_registry_in_scope():
    lone = src("""
        def f(conf):
            return conf.get_raw("spark.rapids.tpu.sql.anything.here")
        """, path="other/engine.py")
    assert run(lone, {"R004"}) == []


# ------------------------------------------------------------------ R005
def test_r005_real_exec_pairs_line_up():
    files = collect_files([os.path.join(_repo_root(), "spark_rapids_tpu")],
                          _repo_root())
    res = analyze_files(files, rule_ids={"R005"})
    assert res.findings == []


# ------------------------------------------------------------------ R006
def test_r006_blocking_calls_under_lock():
    fs = src(GUARD + """
        import threading
        class T:
            def __init__(self, sock, fut):
                self._lock = threading.Lock()
                self.sock = sock
                self.fut = fut
            def bad_send(self, data):
                with self._lock:
                    self.sock.sendall(data)
            def bad_wait(self):
                with self._lock:
                    return self.fut.result()
        """)
    found = run(fs, {"R006"})
    assert len(found) == 2
    assert any(".sendall()" in f.message for f in found)
    assert any(".result()" in f.message for f in found)


def test_r006_condition_wait_and_unlocked_io_clean():
    fs = src(GUARD + """
        import threading
        class Pool:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self._available = threading.Condition(self._lock)
                self.sock = sock
                self.free = []
            def acquire(self):
                with self._available:
                    while not self.free:
                        self._available.wait(1.0)
                    return self.free.pop()
            def send(self, data):
                self.sock.sendall(data)
        """)
    assert run(fs, {"R006"}) == []


# ------------------------------------------------------------------ R007
def test_r007_direct_jit_in_execute_flagged():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                fn = jax.jit(lambda x: x + 1)
                yield fn(ctx)
        """, path="execs/foo.py")
    found = run(fs, {"R007"})
    assert len(found) == 1 and "cross-query" in found[0].message


def test_r007_nested_helper_inside_execute_flagged():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def build():
                    return jax.jit(lambda x: x * 2)
                yield build()(ctx)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


def test_r007_cache_routes_clean():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                fn = _cached_jit(("k", ctx.cap),
                                 lambda: (lambda x: x + 1))
                g = cache.get_or_build(("k2",), lambda: jax.jit(f))
                yield fn(ctx), g(ctx)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_keyed_cache_guard_clean():
    fs = src(GUARD + """
        import jax
        _PROGRAMS = {}
        class FooExec:
            def execute(self, ctx):
                fn = _PROGRAMS.get(ctx.key)
                if fn is None:
                    fn = jax.jit(lambda x: x + 1)
                    _PROGRAMS[ctx.key] = fn
                yield fn(ctx)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_scoped_to_exec_layer():
    fs = src(GUARD + """
        import jax
        class Foo:
            def execute(self, ctx):
                return jax.jit(lambda x: x + 1)(ctx)
        """, path="ops/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_non_execute_function_clean():
    fs = src(GUARD + """
        import jax
        def helper():
            return jax.jit(lambda x: x + 1)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_named_builder_routed_through_cached_program_clean():
    """The FusedStageExec.cached_program idiom: the jit lives in a named
    builder function that execute hands to a sanctioned cache route (via a
    lambda wrapper binding the per-batch key values) — one compile per
    fused plan-signature key, not a bypass."""
    fs = src(GUARD + """
        import jax
        class FusedStageExec:
            def execute(self, ctx):
                def make(variants, cap):
                    def fn(num_rows, *flat):
                        return flat
                    return jax.jit(fn)
                for batch in ctx.batches:
                    key = ("stage", batch.capacity)
                    fn = self.cached_program(
                        key, lambda: make(ctx.variants, batch.capacity))
                    yield fn(batch)
        """, path="execs/fused_execs.py")
    assert run(fs, {"R007"}) == []


def test_r007_named_builder_passed_by_bare_name_clean():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def build():
                    return jax.jit(lambda x: x + 1)
                fn = self.cached_program(("k",), build)
                yield fn(ctx)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_named_builder_also_called_directly_still_flagged():
    """A builder that execute ALSO invokes directly per batch keeps its
    finding — the direct call is a genuine per-call compile, and the one
    routed use must not whitewash it."""
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def make(cap):
                    return jax.jit(lambda x: x + 1)
                fn = self.cached_program(("k",), lambda: make(8))
                for batch in ctx.batches:
                    yield make(batch.capacity)(batch)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


def test_r007_named_builder_called_eagerly_in_route_arg_flagged():
    """``cached_program(key, make(cap))`` (no lambda) runs the builder —
    and its jit — EVERY batch before the cache is even consulted: the
    eager call in the argument expression is a direct call, not a routed
    builder, and must keep its finding."""
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def make(cap):
                    return jax.jit(lambda x: x + 1)
                for batch in ctx.batches:
                    fn = self.cached_program(("k",), make(batch.capacity))
                    yield fn(batch)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


def test_r007_key_keyword_call_is_not_a_builder_position():
    """A function called inside ``key=...`` computes the key, eagerly and
    per batch — it is not a builder handed to the cache and must not
    exempt a jit it contains."""
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def keyed(b):
                    return ("k", jax.jit(lambda x: x)(b.capacity))
                for batch in ctx.batches:
                    fn = self.cached_program(key=keyed(batch),
                                             builder=ctx.build)
                    yield fn(batch)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


def test_r007_named_builder_not_routed_still_flagged():
    """A builder with the same shape that is NEVER handed to a cache route
    stays a finding — the recognition is route-scoped, not name-scoped."""
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def make(cap):
                    return jax.jit(lambda x: x + 1)
                yield make(ctx.cap)(ctx)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


# ---------------------------------------------------------- suppressions
def test_suppression_same_line_and_line_above():
    fs = src(GUARD + """
        def f(arr, brr):
            a = arr.sum().item()  # tpu-lint: disable=R002
            # justified: tiny scalar  # tpu-lint: disable=R002
            b = brr.sum().item()
            return a + b
        """, path="execs/foo.py")
    assert run(fs, {"R002"}) == []


def test_suppression_is_rule_specific():
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()  # tpu-lint: disable=R001
        """, path="execs/foo.py")
    assert len(run(fs, {"R002"})) == 1


# -------------------------------------------------------------- baseline
def test_baseline_absorbs_with_justification(tmp_path):
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="execs/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": "execs/foo.py",
        "code": found[0].code, "count": 1,
        "justification": "grandfathered: fixed in the next PR"}]}))
    new, absorbed = bl.apply_baseline(found, str(path))
    assert new == [] and absorbed == 1


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": "execs/foo.py", "code": "x = y.item()",
        "count": 1, "justification": ""}]}))
    with pytest.raises(bl.BaselineError):
        bl.load_baseline(str(path))


# ------------------------------------------------------- whole-tree gates
def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_is_lint_clean():
    """The premerge contract: the analyzer exits 0 over spark_rapids_tpu/
    with every rule active and only baselined/suppressed debt standing."""
    assert main([os.path.join(_repo_root(), "spark_rapids_tpu")]) == 0


def test_check_configs_gate():
    """--check-configs replaces the old premerge heredoc: docs/configs.md
    must match the registry (R004 drift runs in the normal lint pass)."""
    assert main(["--check-configs"]) == 0


def test_unparseable_file_fails_the_gate(tmp_path):
    """A file the analyzer cannot parse must fail the run, not silently
    vanish from coverage."""
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def f(:\n")
    errors = []
    files = collect_files([str(tmp_path)], str(tmp_path), errors)
    assert len(files) == 1 and len(errors) == 1
    assert "broken.py" in errors[0]
    assert main([str(tmp_path)]) == 1


# ------------------------------------------------------------------ R008
def test_r008_catalog_remove_vs_spill_leak_shape():
    """The pre-fix PR 8 bug: ``remove`` acquires (refcount retain), then the
    unregister-failed branch — a concurrent spill re-registered the copy at
    a lower tier — returns WITHOUT closing. Found originally by an 8-thread
    hammer test; R008 must catch the shape statically."""
    fs = src("""
        class ShuffleBufferCatalog:
            def remove(self, buffer_id):
                buf = self.catalog.acquire(buffer_id)
                if buf is None:
                    return False
                if self.catalog.unregister(buffer_id):
                    buf.close()
                    return True
                return False
        """, path="shuffle/catalog.py")
    found = run(fs, {"R008"})
    assert len(found) == 1
    assert "retained buffer never close()d" in found[0].message
    assert "'buf'" in found[0].message


def test_r008_semaphore_hold_escape():
    fs = src("""
        class Reader:
            def read(self):
                self.semaphore.acquire_if_necessary()
                if not self.blocks:
                    return []
                out = self.do_work()
                self.semaphore.release_if_necessary()
                return out
        """, path="shuffle/reader.py")
    found = run(fs, {"R008"})
    assert len(found) == 1
    assert "semaphore hold never release_if_necessary()d" in found[0].message


def test_r008_finally_release_clean():
    fs = src("""
        class Reader:
            def read(self):
                self.semaphore.acquire_if_necessary()
                try:
                    return self.do_work()
                finally:
                    self.semaphore.release_if_necessary()
        """, path="shuffle/reader.py")
    assert run(fs, {"R008"}) == []


def test_r008_none_guard_clean():
    """Branch sensitivity: the branch that proved the buffer None holds
    nothing — the acquire-then-guard idiom stays clean."""
    fs = src("""
        class C:
            def get(self, key):
                buf = self.catalog.acquire(key)
                if buf is None:
                    return None
                try:
                    return buf.get_batch()
                finally:
                    buf.close()
        """, path="memory/c.py")
    assert run(fs, {"R008"}) == []


def test_r008_handoff_ends_tracking():
    """Returning / storing / appending the buffer transfers ownership."""
    fs = src("""
        class C:
            def take(self, key):
                buf = self.catalog.acquire(key)
                return buf
            def stash(self, key):
                buf = self.catalog.acquire(key)
                self._held[key] = buf
            def collect(self, keys, out):
                for key in keys:
                    buf = self.catalog.acquire(key)
                    out.append(buf)
        """, path="memory/c.py")
    assert run(fs, {"R008"}) == []


def test_r008_with_held_scope_clean():
    """``with sem.held():`` is scoped — never tracked as a bare hold."""
    fs = src("""
        class Reader:
            def read(self):
                with self.semaphore.held():
                    if not self.blocks:
                        return []
                    return self.do_work()
        """, path="shuffle/reader.py")
    assert run(fs, {"R008"}) == []


def test_r008_build_latch_leak_and_clean():
    leak = src("""
        import threading
        class Cache:
            def get_or_put(self, key, builder):
                ev = threading.Event()
                self._inflight[key] = ev
                return builder()
        """, path="memory/cache.py")
    found = run(leak, {"R008"})
    assert len(found) == 1 and "build latch" in found[0].message

    clean = src("""
        import threading
        class Cache:
            def get_or_put(self, key, builder):
                ev = threading.Event()
                self._inflight[key] = ev
                try:
                    return builder()
                finally:
                    self._inflight.pop(key, None)
                    ev.set()
        """, path="memory/cache.py")
    assert run(clean, {"R008"}) == []


def test_r008_permit_released_by_nested_def_clean():
    """The shuffle client's release_once-closure idiom: a nested def
    releasing the receiver is a designed deferred handoff."""
    fs = src("""
        class Client:
            def fetch(self, blocks):
                self._throttle.acquire()
                def release_once():
                    self._throttle.release()
                self.start(blocks, on_done=release_once)
        """, path="shuffle/client.py")
    assert run(fs, {"R008"}) == []


def test_r008_raise_path_is_a_path():
    """An explicit raise escaping with a live hold is flagged; the same
    function releasing in a finally is clean."""
    fs = src("""
        class C:
            def f(self):
                self.sem.acquire_if_necessary()
                if self.bad:
                    raise RuntimeError("boom")
                self.sem.release_if_necessary()
        """, path="memory/c.py")
    found = run(fs, {"R008"})
    assert len(found) == 1 and "semaphore" in found[0].message


def test_r008_outer_except_release_clean():
    """Review regression: a raise inside a nested finally-only try lands in
    the OUTER except that releases — chaining handler levels instead of
    replacing them keeps this shape clean."""
    fs = src("""
        class C:
            def f(self):
                self.sem.acquire_if_necessary()
                try:
                    try:
                        raise ValueError("x")
                    finally:
                        self.log()
                except ValueError:
                    self.sem.release_if_necessary()
        """, path="memory/c.py")
    assert run(fs, {"R008"}) == []


def test_r008_break_skips_else_release():
    """Review regression: break exits past the loop's else clause, so a
    release living ONLY there leaks on every break path; releasing on both
    exits is clean."""
    leaky = src("""
        class C:
            def f(self, items):
                self.sem.acquire_if_necessary()
                for x in items:
                    if x:
                        break
                else:
                    self.sem.release_if_necessary()
        """, path="memory/c.py")
    found = run(leaky, {"R008"})
    assert len(found) == 1 and "semaphore" in found[0].message
    balanced = src("""
        class C:
            def f(self, items):
                self.sem.acquire_if_necessary()
                for x in items:
                    if x:
                        self.sem.release_if_necessary()
                        break
                else:
                    self.sem.release_if_necessary()
        """, path="memory/c.py")
    assert run(balanced, {"R008"}) == []


def test_r008_suppression_applies():
    fs = src("""
        class C:
            def f(self):
                # designed handoff: the daemon thread releases at shutdown
                self.sem.acquire_if_necessary()  # tpu-lint: disable=R008
                self.spawn_daemon()
        """, path="memory/c.py")
    assert run(fs, {"R008"}) == []


def test_r008_connection_handle_leak():
    """The serving wire layer's resource kind: a transport.connect()
    (socket + reader thread) that escapes on an early-exit path without
    close() or a handoff leaks the connection — the shape a routing
    client's dial-then-bail bug takes."""
    fs = src("""
        class Router:
            def dial(self, peer):
                conn = self.transport.connect(peer)
                if not self.accepting:
                    return None
                self._conns[peer] = conn
                return conn
        """, path="serving/client.py")
    found = run(fs, {"R008"})
    assert len(found) == 1
    assert "connection handle never close()d" in found[0].message
    assert "'conn'" in found[0].message


def test_r008_connection_handoffs_clean():
    """All three sanctioned connection handoffs end tracking: caching into
    a container, returning, and passing into a wrapping constructor whose
    result is bound (the shuffle manager's ShuffleClient idiom)."""
    fs = src("""
        class Router:
            def cache(self, peer):
                conn = self.transport.connect(peer)
                self._conns[peer] = conn
                return conn
            def wrap(self, peer):
                conn = self.transport.connect(peer)
                client = WireClient(self.transport, conn)
                self._clients[peer] = client
            def scoped(self, peer):
                conn = self.transport.connect(peer)
                try:
                    return self.handshake(conn)
                finally:
                    conn.close()
        """, path="serving/client.py")
    assert run(fs, {"R008"}) == []


# ------------------------------------------------------------------ R009
def test_r009_seeded_two_lock_cycle():
    fs = src("""
        class Store:
            def spill(self):
                with self._lock:
                    with self._free_cond:
                        pass
            def reclaim(self):
                with self._free_cond:
                    with self._lock:
                        pass
        """, path="memory/store.py")
    found = run(fs, {"R009"})
    assert len(found) == 1
    assert "lock-order cycle" in found[0].message
    assert "_lock" in found[0].message and "_free_cond" in found[0].message


def test_r009_consistent_order_clean():
    fs = src("""
        class Store:
            def spill(self):
                with self._lock:
                    with self._free_cond:
                        pass
            def reclaim(self):
                with self._lock:
                    with self._free_cond:
                        pass
        """, path="memory/store.py")
    assert run(fs, {"R009"}) == []


def test_r009_interprocedural_cycle_through_call_graph():
    """A -> B in one module, B -> A established through a method CALL in
    another: only the call graph sees the inversion."""
    a = src("""
        class Catalog:
            def register(self):
                with self._lock:
                    self.store.note()
            def peek(self):
                with self._lock:
                    pass
        """, path="memory/catalog2.py")
    b = src("""
        class Store:
            def note(self):
                with self._tier_lock:
                    pass
            def drain(self, catalog: Catalog):
                with self._tier_lock:
                    catalog.peek()
        """, path="memory/store2.py")
    found = run([a, b], {"R009"})
    assert len(found) == 1
    assert "_lock" in found[0].message and "_tier_lock" in found[0].message


def test_r009_reentrant_same_lock_not_a_cycle():
    """A -> A through a subclass hierarchy is re-entrancy, not inversion."""
    fs = src("""
        class Base:
            def outer(self):
                with self._lock:
                    self.inner()
        class Child(Base):
            def inner(self):
                with self._lock:
                    pass
        """, path="memory/tiers.py")
    assert run(fs, {"R009"}) == []


def test_r009_suppression_on_inner_acquisition():
    fs = src("""
        class Store:
            def spill(self):
                with self._lock:
                    # lock handoff protocol documented in module docstring
                    with self._free_cond:  # tpu-lint: disable=R009
                        pass
            def reclaim(self):
                with self._free_cond:
                    # reverse half of the documented handoff
                    with self._lock:  # tpu-lint: disable=R009
                        pass
        """, path="memory/store.py")
    assert run(fs, {"R009"}) == []


def test_r009_package_lock_graph_is_acyclic():
    """The real engine's lock graph must stay cycle-free: R009 over the
    whole package reports nothing (no baseline entries, no suppressions
    beyond inline-justified ones)."""
    root = _repo_root()
    files = collect_files([os.path.join(root, "spark_rapids_tpu")], root)
    from spark_rapids_tpu.analysis import analyze_files as _af
    res = _af(files, rule_ids={"R009"})
    assert res.findings == [], [f.render() for f in res.findings]


# ------------------------------------------------------------------ R010
def test_r010_queue_get_on_execute_path_flagged():
    fs = src("""
        import queue
        class FooExec:
            def execute(self, ctx):
                q = queue.Queue()
                self.start(q)
                while True:
                    item = q.get()
                    if item is None:
                        return
                    yield item
        """, path="execs/foo.py")
    found = run(fs, {"R010"})
    assert len(found) == 1
    assert "q.get()" in found[0].message
    assert "cancel" in found[0].message


def test_r010_timeout_poll_idiom_clean():
    fs = src("""
        import queue
        class FooExec:
            def execute(self, ctx):
                q = queue.Queue()
                while True:
                    try:
                        item = q.get(timeout=0.05)
                    except queue.Empty:
                        ctx.check_cancelled()
                        continue
                    yield item
        """, path="execs/foo.py")
    assert run(fs, {"R010"}) == []


def test_r010_interprocedural_wait_below_execute():
    fs = src("""
        class BarExec:
            def execute(self, ctx):
                return self._drain(ctx)
            def _drain(self, ctx):
                self._done_event.wait()
                return []
        """, path="execs/bar.py")
    found = run(fs, {"R010"})
    assert len(found) == 1 and "_done_event.wait()" in found[0].message


def test_r010_unreachable_daemon_clean():
    """A wait not reachable from any execute/serving root is outside the
    per-query cancellation contract."""
    fs = src("""
        class Daemon:
            def pump(self):
                self._ready_event.wait()
        """, path="execs/daemon.py")
    assert run(fs, {"R010"}) == []


def test_r010_non_exec_module_execute_clean():
    """`execute` outside execs/ (and non-worker serving functions) is not
    a root."""
    fs = src("""
        class Runner:
            def execute(self, ctx):
                self._done_event.wait()
        """, path="io/runner.py")
    assert run(fs, {"R010"}) == []


def test_r010_wait_with_timeout_clean():
    fs = src("""
        class FooExec:
            def execute(self, ctx):
                while not self._done_event.wait(0.05):
                    ctx.check_cancelled()
        """, path="execs/foo.py")
    assert run(fs, {"R010"}) == []


def test_r010_server_accept_loop_unbounded_flagged():
    """The serving server's run loop is a root: an UNBOUNDED wait there
    pins the process through signals and shutdown — serve_forever must
    poll bounded."""
    fs = src("""
        class QueryServer:
            def serve_forever(self):
                self._stop_event.wait()
        """, path="serving/server.py")
    found = run(fs, {"R010"})
    assert len(found) == 1 and "_stop_event.wait()" in found[0].message


def test_r010_server_accept_loop_bounded_poll_clean():
    """The sanctioned shape the real server uses: a bounded poll on the
    stop latch."""
    fs = src("""
        class QueryServer:
            def serve_forever(self):
                while not self._stop_event.wait(0.5):
                    pass
        """, path="serving/server.py")
    assert run(fs, {"R010"}) == []


# ------------------------------------------ interprocedural runtime budget
_INTERPROC_CACHE = {}


def _interprocedural_package_result():
    """One shared package scan of the interprocedural rules: the budget
    test and the R012 acceptance gate both read it — a second scan would
    re-pay the whole graph/registry build inside tier-1's wall clock."""
    if "res" not in _INTERPROC_CACHE:
        import time
        root = _repo_root()
        files = collect_files([os.path.join(root, "spark_rapids_tpu")],
                              root)
        from spark_rapids_tpu.analysis import analyze_files as _af
        t0 = time.monotonic()
        res = _af(files, rule_ids={"R008", "R009", "R010", "R012",
                                   "R013", "R014", "R015", "R016",
                                   "R017", "R018"})
        _INTERPROC_CACHE["res"] = res
        _INTERPROC_CACHE["elapsed"] = time.monotonic() - t0
    return _INTERPROC_CACHE["res"]


def test_interprocedural_rules_stay_inside_runtime_budget():
    """ISSUE 9's latency contract: the call-graph + CFG pass over the whole
    package must not blow up premerge (ci/premerge.sh guards the full run
    at 30 s; the interprocedural subset alone gets 20 s here). R012 rides
    the same shared graph build plus its own thread-root/escape registry,
    so it is budgeted with the others."""
    res = _interprocedural_package_result()
    elapsed = _INTERPROC_CACHE["elapsed"]
    assert elapsed < 20.0, f"interprocedural pass took {elapsed:.1f}s " \
        f"({res.rule_seconds})"


# ------------------------------------------------------ CLI surfaces (v2)
def test_format_json_findings(tmp_path, capsys):
    hot = tmp_path / "execs"
    hot.mkdir()
    (hot / "foo.py").write_text(
        "def f(arr):\n    return arr.sum().item()\n")
    rc = main([str(tmp_path), "--rules", "R002", "--format", "json",
               "--baseline", str(tmp_path / "nonexistent.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files_scanned"] == 1 and out["baselined"] == 0
    (finding,) = out["findings"]
    assert finding["rule"] == "R002"
    assert finding["path"].endswith("execs/foo.py")
    assert finding["line"] == 2
    assert ".item()" in finding["message"]
    assert finding["code"] == "return arr.sum().item()"


def test_list_suppressions_inventory(tmp_path, capsys):
    (tmp_path / "a.py").write_text(
        "def f(arr):\n"
        "    # justified: one designed scalar sync per batch\n"
        "    return arr.sum().item()  # tpu-lint: disable=R002\n")
    rc = main(["--list-suppressions", str(tmp_path)])
    text = capsys.readouterr().out
    assert rc == 0
    assert "a.py:3" in text and "R002" in text
    assert "justified: one designed scalar sync per batch" in text

    rc = main(["--list-suppressions", "--format", "json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    (entry,) = out["suppressions"]
    assert entry["line"] == 3 and entry["rules"] == ["R002"]
    assert "designed scalar sync" in entry["justification"]


def test_list_suppressions_package_all_justified():
    """Every inline suppression in the tree carries justification text —
    the satellite contract: suppressions document themselves."""
    root = _repo_root()
    files = collect_files([os.path.join(root, "spark_rapids_tpu")], root)
    from spark_rapids_tpu.analysis.__main__ import \
        _suppression_justification
    for fs in files:
        for lineno in fs.suppressions:
            just = _suppression_justification(fs, lineno)
            assert just, (f"{fs.display_path}:{lineno}: suppression "
                          f"without justification text")


def test_stale_baseline_entry_fails_strict(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": "ok.py", "code": "y = z.item()",
        "count": 1, "justification": "fixed long ago"}]}))
    # non-strict: the unused entry lingers silently (premerge tolerance)
    assert main([str(tmp_path), "--baseline", str(base)]) == 0
    capsys.readouterr()
    # strict (nightly): the stale entry fails with a remove-me message
    rc = main(["--strict", str(tmp_path), "--baseline", str(base)])
    text = capsys.readouterr().out
    assert rc == 1
    assert "stale baseline entry" in text and "remove me" in text


def test_live_baseline_entry_passes_strict_stale_check(tmp_path, capsys):
    """strict ignores the baseline for ABSORPTION but a still-matching
    entry is not stale — the finding itself is what strict reports."""
    hot = tmp_path / "execs"
    hot.mkdir()
    (hot / "foo.py").write_text(
        "def f(arr):\n    return arr.sum().item()\n")
    # out-of-repo files report their absolute path (collect_files falls
    # back to it when the repo-relative form would start with "..")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": str(hot / "foo.py"),
        "code": "return arr.sum().item()", "count": 1,
        "justification": "grandfathered"}]}))
    rc = main(["--strict", str(tmp_path), "--baseline", str(base),
               "--rules", "R002"])
    text = capsys.readouterr().out
    assert rc == 1
    assert "stale baseline entry" not in text
    assert ".item()" in text


def test_r008_nested_finally_outer_release_clean():
    """Review regression: an abrupt exit through NESTED try/finally must
    route through every enclosing finally — releasing in the outer one is
    clean."""
    fs = src("""
        class C:
            def f(self):
                self.sem.acquire_if_necessary()
                try:
                    try:
                        return self.work()
                    finally:
                        self.log()
                finally:
                    self.sem.release_if_necessary()
        """, path="memory/c.py")
    assert run(fs, {"R008"}) == []


def test_r009_closure_under_lock_creates_no_edge():
    """Review regression: a closure DEFINED under a lock does not RUN
    under it — its acquisitions must not create lock-order edges."""
    fs = src("""
        class Pool:
            def schedule(self):
                with self._lock:
                    def cb():
                        with self._free_cond:
                            pass
                    self.executor.submit(cb)
            def reclaim(self):
                with self._free_cond:
                    with self._lock:
                        pass
        """, path="memory/pool.py")
    assert run(fs, {"R009"}) == []


def test_r010_spelled_out_unbounded_get_still_flagged():
    """Review regression: q.get(True) / q.get(block=True) are the
    unbounded default restated, not a bound; non-blocking and timed forms
    stay clean."""
    fs = src("""
        import queue
        class FooExec:
            def execute(self, ctx):
                q = queue.Queue()
                a = q.get(True)
                b = q.get(block=True)
                c = q.get(False)
                d = q.get(block=False)
                e = q.get(timeout=0.05)
                g = q.get(True, 0.05)
        """, path="execs/foo.py")
    found = run(fs, {"R010"})
    # lines of q.get(True) and q.get(block=True) in the dedented fixture
    assert sorted(f.line for f in found) == [6, 7]


def test_stale_check_tolerates_subset_invocation(tmp_path, capsys):
    """Review regression: ``--strict one_file.py`` must not condemn a LIVE
    baseline entry for a file outside the analyzed set; only entries whose
    file is gone from disk are stale."""
    (tmp_path / "a.py").write_text("x = 1\n")
    (tmp_path / "b.py").write_text(
        "def f(arr):\n    return arr.sum().item()\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": str(tmp_path / "b.py"),
        "code": "return arr.sum().item()", "count": 1,
        "justification": "live entry for an unanalyzed file"}]}))
    assert main(["--strict", "--baseline", str(base),
                 str(tmp_path / "a.py")]) == 0
    capsys.readouterr()


# ------------------------------------------------------------------ R011
def test_r011_unregistered_bump_flagged():
    metrics = src("""
        TRANSFER_UPLOAD_BYTES = "transfer.upload_bytes"
        TRANSFER_METRIC_NAMES = (TRANSFER_UPLOAD_BYTES,)
        class MetricSet:
            pass
        TRANSFER_METRICS = MetricSet()
        """, path="spark_rapids_tpu/utils/metrics.py")
    engine = src("""
        from spark_rapids_tpu.utils import metrics as um
        def f(nbytes):
            um.TRANSFER_METRICS[um.TRANSFER_UPLOAD_BYTES].add(nbytes)
            um.TRANSFER_METRICS["transfer.upload_retries"].add(1)
        """, path="spark_rapids_tpu/engine.py")
    found = run([metrics, engine], {"R011"})
    assert len(found) == 1, found
    assert "transfer.upload_retries" in found[0].message
    assert "missing" in found[0].message


def test_r011_stale_registry_entry_flagged():
    metrics = src("""
        MEM_USED = "memory.used_bytes"
        MEM_DEAD = "memory.never_bumped"
        MEMORY_METRIC_NAMES = (MEM_USED, MEM_DEAD)
        class MetricSet:
            pass
        MEMORY_METRICS = MetricSet()
        """, path="spark_rapids_tpu/utils/metrics.py")
    engine = src("""
        from spark_rapids_tpu.utils import metrics as um
        def f(n):
            um.MEMORY_METRICS[um.MEM_USED].set_max(n)
        """, path="spark_rapids_tpu/engine.py")
    found = run([metrics, engine], {"R011"})
    assert len(found) == 1, found
    assert "memory.never_bumped" in found[0].message
    assert "always zero" in found[0].message


def test_r011_alias_and_ifexp_bumps_resolve():
    """The hot-loop alias (m = um.X_METRICS) and the conditional-key
    bump (m[A if cond else B]) both count as bump sites — the shapes
    transfer.py and store.py actually use."""
    metrics = src("""
        SPILL_HOST = "memory.spilled_host"
        SPILL_DISK = "memory.spilled_disk"
        MEMORY_METRIC_NAMES = (SPILL_HOST, SPILL_DISK)
        class MetricSet:
            pass
        MEMORY_METRICS = MetricSet()
        """, path="spark_rapids_tpu/utils/metrics.py")
    engine = src("""
        from spark_rapids_tpu.utils import metrics as um
        def f(n, to_host):
            m = um.MEMORY_METRICS
            m[um.SPILL_HOST if to_host else um.SPILL_DISK].add(n)
        """, path="spark_rapids_tpu/engine.py")
    assert run([metrics, engine], {"R011"}) == []


def test_r011_non_dotted_names_out_of_scope():
    """CamelCase per-operator metric names (per-exec MetricSets) and
    snake_case handle keys are NOT dotted section counters; neither
    direction applies to them."""
    metrics = src("""
        NUM_OUTPUT_ROWS = "numOutputRows"
        QUEUE_WAIT = "queue_wait_s"
        QUERY_METRIC_NAMES = (QUEUE_WAIT,)
        """, path="spark_rapids_tpu/utils/metrics.py")
    engine = src("""
        from spark_rapids_tpu.utils import metrics as um
        def f(exec_node, n):
            exec_node.metrics[um.NUM_OUTPUT_ROWS].add(n)
        """, path="spark_rapids_tpu/engine.py")
    assert run([metrics, engine], {"R011"}) == []


def test_r011_needs_registry_in_scope():
    lone = src("""
        def f(m, n):
            m["transfer.upload_bytes"].add(n)
        """, path="other/engine.py")
    assert run(lone, {"R011"}) == []


def test_r011_real_package_clean():
    files = collect_files([os.path.join(_repo_root(), "spark_rapids_tpu")],
                          _repo_root())
    res = analyze_files(files, rule_ids={"R011"})
    assert res.findings == [], [f.render() for f in res.findings]


# ------------------------------------------------------------------ R012
def _race_src(body: str, path: str = "spark_rapids_tpu/engine.py"):
    # dedent the indented body BEFORE prepending the unindented import
    # (same trap the GUARD fixtures document)
    return src("import threading\n" + textwrap.dedent(body), path=path)


def test_r012_shared_write_no_lock_flagged():
    fs = _race_src("""
        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.drain, daemon=True).start()
            def run(self):
                while True:
                    self.items.append(1)
            def drain(self):
                with self._lock:
                    return list(self.items)
        """)
    found = run(fs, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "Worker.items" in found[0].message
    assert "no common lock" in found[0].message


def test_r012_common_lock_clean():
    fs = _race_src("""
        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.drain, daemon=True).start()
            def run(self):
                while True:
                    with self._lock:
                        self.items.append(1)
            def drain(self):
                with self._lock:
                    return list(self.items)
        """)
    assert run(fs, {"R012"}) == []


def test_r012_disjoint_locksets_flagged():
    """Both sides locked — but by DIFFERENT locks; the locksets intersect
    to the empty set, the Eraser condition. Lock identity here is
    type-based (the attrs carry no lock-y names at all)."""
    fs = _race_src("""
        class Worker:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.table = {}
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.read, daemon=True).start()
            def run(self):
                with self._a:
                    self.table["k"] = 1
            def read(self):
                with self._b:
                    return self.table.get("k")
        """)
    found = run(fs, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "Worker.table" in found[0].message


def test_r012_queue_event_whitelist_clean():
    """queue.Queue / threading.Event attrs synchronize internally: their
    cross-thread method calls are the sanctioned channel."""
    fs = _race_src("""
        import queue
        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = queue.Queue()
                self.stop = threading.Event()
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.feed, daemon=True).start()
            def run(self):
                while not self.stop.is_set():
                    item = self.q.get(timeout=0.05)
            def feed(self):
                self.q.put(1)
                self.stop.set()
        """)
    assert run(fs, {"R012"}) == []


def test_r012_publish_snapshot_clean_but_rmw_flagged():
    """Every write a plain whole-attr store -> atomic snapshot publish
    (the last_metrics idiom), clean. A store that READS the attr it
    overwrites is a read-modify-write and loses the whitelist."""
    clean = _race_src("""
        class Pub:
            def __init__(self):
                self._lock = threading.Lock()
                self.snap = {}
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.read, daemon=True).start()
            def run(self):
                while True:
                    self.snap = {"n": 1}
            def read(self):
                with self._lock:
                    return self.snap
        """)
    assert run(clean, {"R012"}) == []
    rmw = _race_src("""
        class Ctr:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.read, daemon=True).start()
            def run(self):
                while True:
                    self.count = self.count + 1
            def read(self):
                with self._lock:
                    return self.count
        """)
    found = run(rmw, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "Ctr.count" in found[0].message


def test_r012_init_before_spawn():
    """Constructor writes BEFORE the first spawn happen before the object
    escapes to any thread: exempt. The same write moved AFTER the spawn
    races the started thread."""
    clean = _race_src("""
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.table = {}
                self.table["k"] = 1
                threading.Thread(target=self.run, daemon=True).start()
            def run(self):
                with self._lock:
                    return self.table.get("k")
        """)
    assert run(clean, {"R012"}) == []
    racy = _race_src("""
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.table = {}
                threading.Thread(target=self.run, daemon=True).start()
                self.table["k"] = 1
            def run(self):
                with self._lock:
                    return self.table.get("k")
        """)
    found = run(racy, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "W.table" in found[0].message


def test_r012_entry_locksets_flow_into_callees():
    """A helper only ever called under the lock inherits it (the
    *_locked naming convention, verified instead of trusted)."""
    fs = _race_src("""
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.flush, daemon=True).start()
            def run(self):
                with self._lock:
                    self._append_locked(1)
            def _append_locked(self, x):
                self.items.append(x)
            def flush(self):
                with self._lock:
                    self.items.clear()
        """)
    assert run(fs, {"R012"}) == []


def test_r012_single_root_not_shared():
    """One non-multi thread root touching an attr alone is not a race:
    sharing needs two distinct roots (or one multi-instance root)."""
    fs = _race_src("""
        class Solo:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
            def boot(self):
                threading.Thread(target=self.run, daemon=True).start()
            def run(self):
                self.items.append(1)
        """)
    assert run(fs, {"R012"}) == []


def test_r012_serving_surface_is_a_root():
    """The serving package's public API is documented thread-safe, so it
    is a MULTI root even with no Thread spawn in sight."""
    fs = _race_src("""
        class Thing:
            def __init__(self):
                self._lock = threading.Lock()
                self.jobs = []
            def submit(self, j):
                self.jobs.append(j)
            def drain(self):
                with self._lock:
                    return list(self.jobs)
        """, path="spark_rapids_tpu/serving/thing.py")
    found = run(fs, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "Thing.jobs" in found[0].message


def test_r012_reporting_gate_needs_lock_evidence():
    """A fully lock-free class shows no threading intent — either
    confined or a design question a lockset cannot arbitrate; R012
    stays silent (the RacerD gate)."""
    fs = _race_src("""
        class Bare:
            def __init__(self):
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.drain, daemon=True).start()
            def run(self):
                self.items.append(1)
            def drain(self):
                self.items.clear()
        """)
    assert run(fs, {"R012"}) == []


def test_r012_suppression_on_access_and_class():
    line_sup = _race_src("""
        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.drain, daemon=True).start()
            def run(self):
                # benign: drain tolerates a torn read by contract
                self.items.append(1)  # tpu-lint: disable=R012
            def drain(self):
                with self._lock:
                    return list(self.items)
        """)
    assert run(line_sup, {"R012"}) == []
    cls_sup = _race_src("""
        # thread-confined by contract: one consumer drives the handle
        class Handle:  # tpu-lint: disable=R012
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self.run, daemon=True).start()
                threading.Thread(target=self.drain, daemon=True).start()
            def run(self):
                self.items.append(1)
            def drain(self):
                with self._lock:
                    return list(self.items)
        """)
    assert run(cls_sup, {"R012"}) == []


def test_r012_leaked_thread_on_serving_path():
    racy = _race_src("""
        class Loop:
            def start(self):
                t = threading.Thread(target=self._run)
                t.start()
            def _run(self):
                pass
        """, path="spark_rapids_tpu/serving/loopd.py")
    found = run(racy, {"R012"})
    assert len(found) == 1, [f.render() for f in found]
    assert "non-daemon" in found[0].message
    daemon = _race_src("""
        class Loop:
            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()
            def _run(self):
                pass
        """, path="spark_rapids_tpu/serving/loopd.py")
    assert run(daemon, {"R012"}) == []
    joined = _race_src("""
        class Loop:
            def start(self):
                self._worker = threading.Thread(target=self._run)
                self._worker.start()
            def _run(self):
                pass
            def shutdown(self):
                self._worker.join()
        """, path="spark_rapids_tpu/serving/loopd.py")
    assert run(joined, {"R012"}) == []


def test_r012_real_package_clean():
    """The acceptance gate: zero unsuppressed R012 findings on the
    package after the PR's race fixes — no baseline debt. Shares the
    interprocedural budget test's package scan (one graph build instead
    of two keeps tier-1 inside its wall clock)."""
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R012"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------- CLI: sarif/profile
def test_sarif_output_parses_and_carries_findings(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(
        GUARD + "import jax\n"
        "def f(batches):\n"
        "    return [jax.jit(lambda x: x + 1)(b) for b in batches]\n")
    rc = main(["--format", "sarif", str(tmp_path / "bad.py")])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    runs = doc["runs"]
    assert len(runs) == 1
    results = runs[0]["results"]
    assert results and results[0]["ruleId"].startswith("R")
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] >= 1
    rules = {r["id"] for r in runs[0]["tool"]["driver"]["rules"]}
    assert "R012" in rules and "R001" in rules
    assert "ruleSeconds" in runs[0]["properties"]


def test_sarif_clean_run_is_empty_results(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = main(["--format", "sarif", str(tmp_path / "ok.py")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["runs"][0]["results"] == []


def test_profile_prints_per_rule_timings(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert main(["--profile", str(tmp_path / "ok.py")]) == 0
    err = capsys.readouterr().err
    lines = [ln for ln in err.splitlines() if ln.startswith("profile: R")]
    assert len(lines) >= 12        # every rule timed, R001..R012
    # slowest-first ordering: premerge's guard takes head -3 verbatim
    secs = [float(ln.split()[-1].rstrip("s")) for ln in lines]
    assert secs == sorted(secs, reverse=True)


# --------------------------------------------------- R013 (v4 ladder rules)
def test_r013_swallowed_signal_flagged():
    fs = src("""
        def fetch():
            raise ShuffleFetchFailedError("lost blocks")
        def caller():
            try:
                fetch()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    found = run(fs, {"R013"})
    assert len(found) == 1
    assert "ShuffleFetchFailedError" in found[0].message
    assert "triage" in found[0].message


def test_r013_bare_reraise_clean():
    fs = src("""
        def fetch():
            raise ChecksumError("bad frame")
        def caller():
            try:
                fetch()
            except Exception:
                log()
                raise
        def log():
            pass
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R013"}) == []


def test_r013_convert_to_registered_type_clean():
    fs = src("""
        def fetch():
            raise ChecksumError("bad frame")
        def caller():
            try:
                fetch()
            except Exception as e:
                raise WireQueryError(str(e), 0) from e
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R013"}) == []


def test_r013_triage_boundary_owner_exempt():
    fs = src("""
        from spark_rapids_tpu.utils.errors import triage_boundary
        def fetch():
            raise ShuffleFetchFailedError("lost blocks")
        @triage_boundary
        def retry_loop():
            try:
                fetch()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R013"}) == []


def test_r013_handler_routing_to_triage_boundary_clean():
    fs = src("""
        from spark_rapids_tpu.utils.errors import triage_boundary
        @triage_boundary
        def route(e):
            pass
        def fetch():
            raise SpillCorruptionError(path="p", expected=1, actual=2)
        def caller():
            try:
                fetch()
            except Exception as e:
                route(e)
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R013"}) == []


def test_r013_no_signal_on_path_clean():
    """A broad except on a path where no ladder signal may-raise is out of
    scope — the engine under-approximates, silence costs nothing here."""
    fs = src("""
        def load():
            raise ValueError("bad input")
        def caller():
            try:
                load()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R013"}) == []


def test_r013_real_package_clean():
    """Acceptance gate: zero R013 findings on the package after the PR's
    ladder fixes — every broad except on a signal path now re-raises,
    converts, or routes to a @triage_boundary."""
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R013"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------------------ R014
def test_r014_cancellation_laundering_flagged():
    fs = src("""
        def work():
            raise QueryCancelledError("caller gave up")
        def caller():
            try:
                work()
            except QueryCancelledError as e:
                raise ChecksumError("retry me") from e
        """, path="spark_rapids_tpu/engine.py")
    found = run(fs, {"R014"})
    assert len(found) == 1
    assert "CANCELLATION" in found[0].message
    assert "never be retried" in found[0].message


def test_r014_cancellation_to_cancellation_clean():
    fs = src("""
        def work():
            raise QueryCancelledError("caller gave up")
        def caller():
            try:
                work()
            except QueryCancelledError as e:
                raise QueryTimeoutError("deadline") from e
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R014"}) == []


def test_r014_unregistered_class_at_triage_boundary_flagged():
    fs = src("""
        from spark_rapids_tpu.utils.errors import triage_boundary
        class WeirdError(Exception):
            pass
        def work():
            raise WeirdError("x")
        @triage_boundary
        def boundary():
            try:
                work()
            except WeirdError:
                return None
        """, path="spark_rapids_tpu/engine.py")
    found = run(fs, {"R014"})
    assert len(found) == 1
    assert "WeirdError" in found[0].message
    assert "not registered" in found[0].message
    # anchored at the raise site, where the registration fix belongs
    assert found[0].line == 6


def test_r014_registered_class_at_triage_boundary_clean():
    fs = src("""
        from spark_rapids_tpu.utils.errors import triage_boundary
        class ChecksumError(Exception):
            pass
        def work():
            raise ChecksumError("x")
        @triage_boundary
        def boundary():
            try:
                work()
            except ChecksumError:
                return None
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R014"}) == []


def test_r014_real_package_clean():
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R014"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------------------ R015
def test_r015_codecless_class_crossing_wire_flagged():
    fs = src("""
        from spark_rapids_tpu.utils import errors as uerr
        class LocalOnlyError(Exception):
            pass
        def work():
            raise LocalOnlyError("x")
        @uerr.wire_boundary
        def serve():
            try:
                work()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    found = run(fs, {"R015"})
    assert len(found) == 1
    assert "LocalOnlyError" in found[0].message
    assert "OpaqueWireError" in found[0].message
    assert found[0].line == 6        # the raise site, not the boundary


def test_r015_registered_class_clean():
    fs = src("""
        from spark_rapids_tpu.utils import errors as uerr
        class ShuffleFetchFailedError(Exception):
            pass
        def work():
            raise ShuffleFetchFailedError("lost")
        @uerr.wire_boundary
        def serve():
            try:
                work()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R015"}) == []


def test_r015_builtins_degrade_by_design_clean():
    fs = src("""
        from spark_rapids_tpu.utils import errors as uerr
        def work():
            raise ValueError("x")
        @uerr.wire_boundary
        def serve():
            try:
                work()
            except Exception:
                return None
        """, path="spark_rapids_tpu/engine.py")
    assert run(fs, {"R015"}) == []


def test_r015_real_package_clean():
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R015"]
    assert found == [], [f.render() for f in found]


# ----------------------------------------------- inline-suppression staleness
def test_stale_suppression_reported():
    from spark_rapids_tpu.analysis.__main__ import stale_suppressions
    fs = src("x = 1  # tpu-lint: disable=R002\n", path="a.py")
    res = analyze_files([fs])
    msgs = stale_suppressions([fs], res)
    assert len(msgs) == 1
    assert "a.py:1" in msgs[0] and "R002" in msgs[0] and "remove" in msgs[0]


def test_live_suppression_not_stale():
    from spark_rapids_tpu.analysis.__main__ import stale_suppressions
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()  # tpu-lint: disable=R002
        """, path="spark_rapids_tpu/execs/engine.py")
    res = analyze_files([fs])
    assert [f for f in res.findings if f.rule == "R002"] == []
    assert stale_suppressions([fs], res) == []


def test_partially_stale_suppression_names_only_the_dead_ids():
    from spark_rapids_tpu.analysis.__main__ import stale_suppressions
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()  # tpu-lint: disable=R002,R006
        """, path="spark_rapids_tpu/execs/engine.py")
    res = analyze_files([fs])
    (msg,) = stale_suppressions([fs], res)
    assert "R006" in msg and "R002" not in msg.split("disable=")[1]


def test_strict_subset_run_skips_suppression_staleness(tmp_path, capsys):
    """Staleness only fires on full-package runs: a subset run never
    re-derives interprocedural findings and would condemn live
    suppressions."""
    (tmp_path / "a.py").write_text("x = 1  # tpu-lint: disable=R002\n")
    rc = main(["--strict", str(tmp_path)])
    text = capsys.readouterr().out
    assert rc == 0
    assert "STALE SUPPRESSION" not in text


def test_list_suppressions_marks_live_and_stale(tmp_path, capsys):
    hot = tmp_path / "execs"          # R002 only scans hot-path dirs
    hot.mkdir()
    (hot / "a.py").write_text(
        "from spark_rapids_tpu import device as _device\n"
        "def f(arr):\n"
        "    # justified: one designed scalar sync per batch\n"
        "    live = arr.sum().item()  # tpu-lint: disable=R002\n"
        "    return live  # tpu-lint: disable=R006\n")
    rc = main(["--list-suppressions", "--format", "json", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    by_line = {e["line"]: e for e in out["suppressions"]}
    assert by_line[4]["status"] == "live"
    assert by_line[5]["status"] == "stale"
    assert by_line[5]["stale_rules"] == ["R006"]


def test_package_suppressions_all_live():
    """The strict gate's suppression-hygiene contract on the real tree:
    every inline suppression still absorbs a finding."""
    from spark_rapids_tpu.analysis.__main__ import stale_suppressions
    root = _repo_root()
    files = collect_files([os.path.join(root, "spark_rapids_tpu")], root)
    res = analyze_files(files)
    assert stale_suppressions(files, res) == []


def test_sarif_rules_carry_help_uris(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = main(["--format", "sarif", str(tmp_path / "ok.py")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    rules = {r["id"]: r for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"R013", "R014", "R015"} <= set(rules)
    for rid, entry in rules.items():
        assert entry["helpUri"] == \
            f"docs/static-analysis.md#{rid.lower()}", entry

# ------------------------------------------------------------------ R016
def test_r016_unkeyed_closure_capture_flagged():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def split(schema, cap, n):
            key = ("exchange", schema, cap)
            def build():
                def fn(rows):
                    return rows * n
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R016"})
    assert len(found) == 1
    assert "'n'" in found[0].message
    assert "stale specialization" in found[0].message
    assert "widen the key" in found[0].message


def test_r016_keyed_capture_clean():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def split(schema, cap, n):
            key = ("exchange", schema, cap, n)
            def build():
                def fn(rows):
                    return rows * n
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    assert run(fs, {"R016"}) == []


def test_r016_lambda_builder_and_sibling_def_contribute_captures():
    """The satellite engine fix: a builder written as ``lambda: make(...)``
    observes everything the sibling ``make`` observes — both the lambda's
    own frees and the sibling's must classify."""
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def outer(key, cap, smax):
            def make(cap):
                def fn(x):
                    return x[:cap] * smax
                return fn
            return _cached_jit(key, lambda: make(cap))
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R016"})
    flagged = {f.message.split("captures '")[1].split("'")[0]
               for f in found}
    assert flagged == {"cap", "smax"}


def test_r016_listcomp_capture_flagged():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def go(key, cols):
            def build():
                def fn(x):
                    return [x * c for c in cols]
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R016"})
    assert len(found) == 1 and "'cols'" in found[0].message


def test_r016_forwarding_wrapper_clean():
    """A wrapper that routes its caller's builder through the cache is
    not a capture site: the builder parameter is invoked, so its contents
    are the CALLER's responsibility (the caller's own site is analyzed)."""
    fs = src("""
        from spark_rapids_tpu.serving.program_cache import global_program_cache
        def cached(key, builder):
            return global_program_cache().get_or_build(
                key, lambda: builder())
        """, path="spark_rapids_tpu/execs/engine.py")
    assert run(fs, {"R016"}) == []


def test_r016_const_and_derived_clean():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        _WIDTH = 64
        def go(schema, cap):
            key = ("k", schema, cap)
            total = cap * 2
            def build():
                def fn(x):
                    return x[:total] + _WIDTH
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    assert run(fs, {"R016"}) == []


def test_r016_keyed_default_arg_clean_unkeyed_flagged():
    """Pinning via a default arg does not sanction by itself — the pinned
    value must still be key-derived."""
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def go(schema, cap, extra):
            key = ("k", schema, cap)
            def build(cap=cap, extra=extra):
                def fn(x):
                    return x[:cap] + extra
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R016"})
    assert len(found) == 1 and "'extra'" in found[0].message


def test_r016_real_package_clean():
    """Acceptance gate: every cached-program builder in the package
    observes only key-derived, traced, or constant values — the PR's
    site fixes (widened keys, hoisted shim reads) hold."""
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R016"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------------------ R017
def test_r017_mutated_module_global_flagged():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        TABLE = {}
        def register(k, v):
            TABLE[k] = v
        def go(key):
            def build():
                def fn(x):
                    return x + len(TABLE)
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R017"})
    assert len(found) == 1
    assert "'TABLE'" in found[0].message
    assert "mutated in place" in found[0].message


def test_r017_keyed_mutable_attr_flagged():
    """Keying a mutable attr does not make it safe: the key repr may not
    change with the mutation, and the trace snapshot never does."""
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        class Exec:
            def __init__(self):
                self.caps = []
            def grow(self, c):
                self.caps.append(c)
            def run(self):
                key = ("k", self.caps)
                def build():
                    def fn(x):
                        return x * len(self.caps)
                    return fn
                return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R017"})
    assert len(found) == 1
    assert "'self.caps'" in found[0].message
    assert "in-place write sites" in found[0].message


def test_r017_unmutated_global_and_attr_clean():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        WIDTHS = (8, 16)
        class Exec:
            def __init__(self):
                self.caps = ()
            def run(self):
                key = ("k", self.caps)
                def build():
                    def fn(x):
                        return x * len(self.caps) + WIDTHS[0]
                    return fn
                return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    assert run(fs, {"R017"}) == []


def test_r017_real_package_clean():
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R017"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------------------ R018
def test_r018_metric_bump_in_trace_flagged():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def go(key, metrics):
            def build():
                def fn(x):
                    metrics.add(1)
                    return x + 1
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R018"})
    assert len(found) == 1
    assert "metric bump" in found[0].message
    assert "once per compile" in found[0].message


def test_r018_lock_and_host_io_in_trace_flagged():
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        class Exec:
            def run(self, key):
                def build():
                    def fn(x):
                        with self.lock:
                            print("running")
                        return x + 1
                    return fn
                return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    found = run(fs, {"R018"})
    kinds = sorted(f.message.split(" inside")[0] for f in found)
    assert len(found) == 2
    assert any("lock acquisition" in k for k in kinds)
    assert any("host call" in k for k in kinds)


def test_r018_effect_outside_trace_clean():
    """Effects in the BUILDER (but outside the returned callable) run once
    per build on the host — exactly where a compile-time log belongs."""
    fs = src("""
        from spark_rapids_tpu.execs.tpu_execs import _cached_jit
        def go(key, metrics):
            def build():
                metrics.add(1)
                def fn(x):
                    return x + 1
                return fn
            return _cached_jit(key, build)
        """, path="spark_rapids_tpu/execs/engine.py")
    assert run(fs, {"R018"}) == []


def test_r018_real_package_clean():
    res = _interprocedural_package_result()
    found = [f for f in res.findings if f.rule == "R018"]
    assert found == [], [f.render() for f in found]


# ------------------------------------------------------ --changed-only gate
def _seed_git_repo(tmp_path):
    import subprocess

    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True,
                       capture_output=True)
    hot = tmp_path / "execs"
    hot.mkdir()
    (hot / "old.py").write_text(
        "def f(arr):\n    return arr.sum().item()\n")
    git("init", "-q")
    git("-c", "user.email=t@t", "-c", "user.name=t", "add", "-A")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "seed")
    (hot / "new.py").write_text(
        "def g(arr):\n    return arr.sum().item()\n")


def test_changed_only_filters_findings_to_changed_files(tmp_path, capsys,
                                                        monkeypatch):
    """Fast-gate contract: the committed-and-unchanged file's finding is
    filtered; the untracked file's finding survives."""
    _seed_git_repo(tmp_path)
    monkeypatch.setattr("spark_rapids_tpu.analysis.__main__._repo_root",
                        lambda: str(tmp_path))
    rc = main(["--changed-only", "--base", "HEAD", "--rules", "R002",
               "--format", "json", str(tmp_path),
               "--baseline", str(tmp_path / "none.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["path"] for f in out["findings"]} == {"execs/new.py"}


def test_changed_only_without_git_falls_back_to_full_run(tmp_path, capsys,
                                                         monkeypatch):
    """Fail OPEN: no merge-base -> the full set is linted, never silently
    skipped."""
    hot = tmp_path / "execs"
    hot.mkdir()
    (hot / "a.py").write_text("def f(arr):\n    return arr.sum().item()\n")
    monkeypatch.setattr("spark_rapids_tpu.analysis.__main__._repo_root",
                        lambda: str(tmp_path))
    rc = main(["--changed-only", "--rules", "R002", "--format", "json",
               str(tmp_path), "--baseline", str(tmp_path / "none.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["path"] for f in out["findings"]} == {"execs/a.py"}
