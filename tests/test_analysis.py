"""tpu-lint coverage: every rule gets at least one true-positive and one
clean-negative fixture, plus suppression/baseline mechanics and a run over
the real package asserting zero non-baselined findings (the premerge gate's
contract)."""
import json
import os
import textwrap

import pytest

from spark_rapids_tpu.analysis import SourceFile, analyze_files
from spark_rapids_tpu.analysis import baseline as bl
from spark_rapids_tpu.analysis.__main__ import collect_files, main


def src(text: str, path: str = "mod.py") -> SourceFile:
    # fixtures concatenate the unindented GUARD line with an indented
    # triple-quoted body; dedent the body alone or dedent finds no common
    # prefix and leaves the fixture unparseable
    if text.startswith("from spark_rapids_tpu import device"):
        head, _, body = text.partition("\n")
        text = head + "\n" + textwrap.dedent(body)
    else:
        text = textwrap.dedent(text)
    return SourceFile(path, text, path)


def run(files, rules):
    if not isinstance(files, list):
        files = [files]
    res = analyze_files(files, rule_ids=set(rules))
    return res.findings


#: the x64 guard import every jax-importing module carries (keeps R003
#: quiet in fixtures that target other rules)
GUARD = "from spark_rapids_tpu import device as _device\n"


# ------------------------------------------------------------------ R001
def test_r001_jit_in_loop_flagged():
    fs = src(GUARD + """
        import jax
        def f(batches):
            outs = []
            for b in batches:
                fn = jax.jit(lambda x: x + 1)
                outs.append(fn(b))
            return outs
        """)
    found = run(fs, {"R001"})
    assert len(found) == 1 and "inside a loop" in found[0].message


def test_r001_immediate_invoke_flagged():
    fs = src(GUARD + """
        import jax
        def f(x):
            return jax.jit(lambda v: v * 2)(x)
        """)
    found = run(fs, {"R001"})
    assert len(found) == 1 and "invoked immediately" in found[0].message


def test_r001_cache_guard_clean():
    fs = src(GUARD + """
        import jax
        _PROGRAMS = {}
        def get(keys):
            fns = []
            for key in keys:
                fn = _PROGRAMS.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x)
                    _PROGRAMS[key] = fn
                fns.append(fn)
            return fns
        """)
    assert run(fs, {"R001"}) == []


def test_r001_module_level_jit_clean():
    fs = src(GUARD + """
        import jax
        def _impl(x):
            return x + 1
        fast = jax.jit(_impl)
        """)
    assert run(fs, {"R001"}) == []


# ------------------------------------------------------------------ R002
def test_r002_item_flagged_in_hot_path():
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="execs/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and ".item()" in found[0].message


def test_r002_scalar_cast_of_program_result_in_loop():
    fs = src(GUARD + """
        import jax
        def f(batches, build):
            fn = jax.jit(build)
            for b in batches:
                res = fn(b)
                n = int(res[-1])
                yield n
        """, path="ops/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and "inside a loop" in found[0].message


def test_r002_download_comprehension_in_loop():
    fs = src(GUARD + """
        import jax
        import numpy as np
        def f(batches, build):
            fn = jax.jit(build)
            for b in batches:
                flat = [np.asarray(a) for a in fn(b)]
                yield flat
        """, path="shuffle/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1 and "every output column" in found[0].message


def test_r002_nested_def_does_not_taint_outer_scope():
    """Regression: a nested helper's jit program must not make the OUTER
    function's unrelated loop variables look like device results."""
    fs = src(GUARD + """
        import jax
        def outer(host_counts, build):
            def helper(b):
                fn = jax.jit(build)
                res = fn(b)
                return res
            total = 0
            for res in host_counts:
                total += int(res)
            return total
        """, path="execs/foo.py")
    assert run(fs, {"R002"}) == []


def test_r002_clean_outside_loop_and_outside_hot_path():
    hot_clean = src(GUARD + """
        import jax
        import numpy as np
        def f(b, build):
            fn = jax.jit(build)
            res = fn(b)
            return int(res[-1])
        """, path="execs/foo.py")
    assert run(hot_clean, {"R002"}) == []
    # identical sync code outside the hot-path dirs is out of scope
    cold = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="benchmarks/foo.py")
    assert run(cold, {"R002"}) == []


# ------------------------------------------------------------------ R003
def test_r003_jax_import_without_device_guard():
    fs = src("""
        import jax
        def f(x):
            return jax.numpy.sum(x)
        """)
    found = run(fs, {"R003"})
    assert len(found) == 1 and "x32" in found[0].message


def test_r003_dtypeless_constructors():
    fs = src(GUARD + """
        import jax.numpy as jnp
        import numpy as np
        a = np.array([1, 2, 3])
        b = jnp.zeros(16)
        """)
    found = run(fs, {"R003"})
    assert len(found) == 2
    assert any("np.array" in f.message for f in found)
    assert any("jnp.zeros" in f.message for f in found)


def test_r003_clean_with_guard_and_dtypes():
    fs = src("""
        from spark_rapids_tpu import device as _device  # noqa: F401
        import jax.numpy as jnp
        import numpy as np
        a = np.array([1, 2, 3], dtype=np.int32)
        b = jnp.zeros(16, jnp.int32)
        c = jnp.arange(8, dtype=np.int32)
        strings = np.array(["CA", "TX"])  # non-numeric: dtype is unambiguous
        """)
    assert run(fs, {"R003"}) == []


# ------------------------------------------------------------------ R004
def test_r004_dead_and_unregistered_keys():
    config = src("""
        def _conf(key, conf_type, default, doc):
            pass
        USED = _conf("sql.used", bool, True, "read by engine.py")
        DEAD = _conf("sql.dead", bool, True, "never read")
        """, path="spark_rapids_tpu/config.py")
    engine = src("""
        from spark_rapids_tpu import config as cfg
        def f(conf):
            if conf.get(cfg.USED):
                return conf.get_raw("spark.rapids.tpu.sql.typoed.key")
        """, path="spark_rapids_tpu/engine.py")
    found = run([config, engine], {"R004"})
    assert len(found) == 2
    dead = [f for f in found if "never read" in f.message]
    unreg = [f for f in found if "not registered" in f.message]
    assert len(dead) == 1 and "sql.dead" in dead[0].message
    assert len(unreg) == 1 and "typoed" in unreg[0].message


def test_r004_needs_registry_in_scope():
    lone = src("""
        def f(conf):
            return conf.get_raw("spark.rapids.tpu.sql.anything.here")
        """, path="other/engine.py")
    assert run(lone, {"R004"}) == []


# ------------------------------------------------------------------ R005
def test_r005_real_exec_pairs_line_up():
    files = collect_files([os.path.join(_repo_root(), "spark_rapids_tpu")],
                          _repo_root())
    res = analyze_files(files, rule_ids={"R005"})
    assert res.findings == []


# ------------------------------------------------------------------ R006
def test_r006_blocking_calls_under_lock():
    fs = src(GUARD + """
        import threading
        class T:
            def __init__(self, sock, fut):
                self._lock = threading.Lock()
                self.sock = sock
                self.fut = fut
            def bad_send(self, data):
                with self._lock:
                    self.sock.sendall(data)
            def bad_wait(self):
                with self._lock:
                    return self.fut.result()
        """)
    found = run(fs, {"R006"})
    assert len(found) == 2
    assert any(".sendall()" in f.message for f in found)
    assert any(".result()" in f.message for f in found)


def test_r006_condition_wait_and_unlocked_io_clean():
    fs = src(GUARD + """
        import threading
        class Pool:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self._available = threading.Condition(self._lock)
                self.sock = sock
                self.free = []
            def acquire(self):
                with self._available:
                    while not self.free:
                        self._available.wait(1.0)
                    return self.free.pop()
            def send(self, data):
                self.sock.sendall(data)
        """)
    assert run(fs, {"R006"}) == []


# ------------------------------------------------------------------ R007
def test_r007_direct_jit_in_execute_flagged():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                fn = jax.jit(lambda x: x + 1)
                yield fn(ctx)
        """, path="execs/foo.py")
    found = run(fs, {"R007"})
    assert len(found) == 1 and "cross-query" in found[0].message


def test_r007_nested_helper_inside_execute_flagged():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                def build():
                    return jax.jit(lambda x: x * 2)
                yield build()(ctx)
        """, path="execs/foo.py")
    assert len(run(fs, {"R007"})) == 1


def test_r007_cache_routes_clean():
    fs = src(GUARD + """
        import jax
        class FooExec:
            def execute(self, ctx):
                fn = _cached_jit(("k", ctx.cap),
                                 lambda: (lambda x: x + 1))
                g = cache.get_or_build(("k2",), lambda: jax.jit(f))
                yield fn(ctx), g(ctx)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_keyed_cache_guard_clean():
    fs = src(GUARD + """
        import jax
        _PROGRAMS = {}
        class FooExec:
            def execute(self, ctx):
                fn = _PROGRAMS.get(ctx.key)
                if fn is None:
                    fn = jax.jit(lambda x: x + 1)
                    _PROGRAMS[ctx.key] = fn
                yield fn(ctx)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_scoped_to_exec_layer():
    fs = src(GUARD + """
        import jax
        class Foo:
            def execute(self, ctx):
                return jax.jit(lambda x: x + 1)(ctx)
        """, path="ops/foo.py")
    assert run(fs, {"R007"}) == []


def test_r007_non_execute_function_clean():
    fs = src(GUARD + """
        import jax
        def helper():
            return jax.jit(lambda x: x + 1)
        """, path="execs/foo.py")
    assert run(fs, {"R007"}) == []


# ---------------------------------------------------------- suppressions
def test_suppression_same_line_and_line_above():
    fs = src(GUARD + """
        def f(arr, brr):
            a = arr.sum().item()  # tpu-lint: disable=R002
            # justified: tiny scalar  # tpu-lint: disable=R002
            b = brr.sum().item()
            return a + b
        """, path="execs/foo.py")
    assert run(fs, {"R002"}) == []


def test_suppression_is_rule_specific():
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()  # tpu-lint: disable=R001
        """, path="execs/foo.py")
    assert len(run(fs, {"R002"})) == 1


# -------------------------------------------------------------- baseline
def test_baseline_absorbs_with_justification(tmp_path):
    fs = src(GUARD + """
        def f(arr):
            return arr.sum().item()
        """, path="execs/foo.py")
    found = run(fs, {"R002"})
    assert len(found) == 1
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": "execs/foo.py",
        "code": found[0].code, "count": 1,
        "justification": "grandfathered: fixed in the next PR"}]}))
    new, absorbed = bl.apply_baseline(found, str(path))
    assert new == [] and absorbed == 1


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R002", "path": "execs/foo.py", "code": "x = y.item()",
        "count": 1, "justification": ""}]}))
    with pytest.raises(bl.BaselineError):
        bl.load_baseline(str(path))


# ------------------------------------------------------- whole-tree gates
def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_is_lint_clean():
    """The premerge contract: the analyzer exits 0 over spark_rapids_tpu/
    with every rule active and only baselined/suppressed debt standing."""
    assert main([os.path.join(_repo_root(), "spark_rapids_tpu")]) == 0


def test_check_configs_gate():
    """--check-configs replaces the old premerge heredoc: docs/configs.md
    must match the registry (R004 drift runs in the normal lint pass)."""
    assert main(["--check-configs"]) == 0


def test_unparseable_file_fails_the_gate(tmp_path):
    """A file the analyzer cannot parse must fail the run, not silently
    vanish from coverage."""
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def f(:\n")
    errors = []
    files = collect_files([str(tmp_path)], str(tmp_path), errors)
    assert len(files) == 1 and len(errors) == 1
    assert "broken.py" in errors[0]
    assert main([str(tmp_path)]) == 1
