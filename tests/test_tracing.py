"""Structured query tracing (utils/tracing.py): the span ring, per-exec
spans, EXPLAIN ANALYZE, Chrome export, per-exec jax.profiler ranges, the
metric-registry coverage contract, and the per-action/per-query
recursion-depth attribution fix."""
import json
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.api import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.utils import metrics as um
from spark_rapids_tpu.utils import tracing

BASE_CONF = {
    "spark.rapids.tpu.sql.variableFloatAgg.enabled": "true",
    # single-threaded plan: per-node SELF times sum to the action wall
    # (producer threads are genuine concurrency and deliberately do not
    # subtract cross-thread)
    "spark.rapids.tpu.transfer.pipeline.enabled": "false",
}


def _table(rows: int = 4096) -> pa.Table:
    rng = np.random.default_rng(7)
    return pa.table({"k": rng.integers(0, 8, rows).astype("int64"),
                     "v": rng.random(rows)})


def _q(sess, table=None):
    df = sess.create_dataframe(table if table is not None else _table())
    return (df.filter(F.col("v") > 0.25)
            .groupBy("k").agg(F.sum("v").alias("s"),
                              F.count(F.lit(1)).alias("c")))


# ------------------------------------------------------------------ the ring
def test_ring_buffer_bounded_and_windowed():
    t = tracing.Tracer(capacity=16)
    with t.activate():
        for i in range(40):
            t.record(f"s{i}", "exec", i, 1)
        mark = t.mark()
        t.record("tail", "exec", 99, 1)
    assert len(t.since(0)) == 16          # bounded: oldest overwritten
    window = t.since(mark)
    assert [r.name for r in window] == ["tail"]


def test_disabled_mode_records_nothing():
    t = tracing.Tracer(capacity=32)
    assert t.span("x", "exec") is tracing._NULL_SPAN
    with t.span("x", "exec"):
        pass
    t.instant("y", "exec")
    t.record("z", "exec", 0, 1)
    assert t.since(0) == []
    assert not t.on


def test_span_records_on_exit():
    t = tracing.Tracer(capacity=32)
    with t.activate():
        with t.span("work", "transfer", {"bytes": 10}):
            pass
    (rec,) = t.since(0)
    assert rec.name == "work" and rec.cat == "transfer"
    assert rec.dur_ns >= 0 and rec.args == {"bytes": 10}
    ev = rec.to_event()
    assert ev["ph"] == "X" and ev["cat"] == "transfer"


# ------------------------------------------------------- traced action + EA
def test_explain_analyze_rows_and_wall_sum():
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.trace.enabled": "true"})
    out = _q(sess).collect()
    assert out.num_rows == 8
    text = sess.explain_analyze()
    assert "rows=8" in text                     # aggregate output observed
    assert "rows=4096" in text or "rows=" in text
    assert "wall=" in text and "self=" in text
    # per-node SELF times sum (within driver slack: planning, to_arrow,
    # admission live outside exec spans) to the action wall
    wall_ns = sess.last_action_wall_s * 1e9
    total_self = sum((tracing.observed_of(nd) or {}).get("self_ns", 0)
                    for nd in _iter_execs(sess.last_plan))
    assert 0 < total_self <= wall_ns * 1.1
    assert total_self >= wall_ns * 0.2


def _iter_execs(plan):
    yield plan
    for c in plan.children:
        yield from _iter_execs(c)


def test_untraced_action_renders_tree_without_stats():
    sess = TpuSession(BASE_CONF)
    _q(sess).collect()
    text = sess.explain_analyze()
    assert "TpuHashAggregateExec" in text or "FusedAggregate" in text \
        or "*(" in text
    assert "rows=" not in text


def test_chrome_export_valid_with_layers(tmp_path):
    path = str(tmp_path / "trace.json")
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.trace.enabled": "true",
                       "spark.rapids.tpu.trace.export.path": path,
                       # grace partitioning on: memory-layer spans
                       "spark.rapids.tpu.memory.outOfCore."
                       "forcePartitions": "2"})
    _q(sess).collect()
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert events, "no trace events exported"
    cats = {e["cat"] for e in events}
    # exec spans, transfer uploads, grace partitioning, admission wait
    assert {"exec", "transfer", "memory", "serving"} <= cats, cats
    for e in events:
        assert "name" in e and "ts" in e and e["ph"] in ("X", "i")
    assert doc["otherData"]["action_wall_s"] > 0
    counts = tracing.layer_counts(sess.last_trace)
    assert all(counts[c] >= 1 for c in
               ("exec", "transfer", "memory", "serving")), counts


def test_per_exec_profiler_ranges(monkeypatch):
    """TRACE_ENABLED's docstring promise (satellite): named profiler
    ranges PER OPERATOR, not just the one whole-action range."""
    names = []

    class FakeAnnotation:
        def __init__(self, name):
            names.append(name)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(tracing, "_TRACE_ANNOTATION", FakeAnnotation)
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.trace.enabled": "true"})
    _q(sess).collect()
    per_exec = [n for n in names if "#" in n]
    assert per_exec, f"no per-exec ranges, saw {sorted(set(names))[:10]}"
    # range names are op#plan_id — one per operator, not one per action
    assert any(n.split("#")[0].endswith("Exec") for n in per_exec)


def test_query_handle_analyze_export_and_spans(tmp_path):
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.trace.enabled": "true"})
    handle = sess.submit(_q(sess))
    out = handle.result(timeout=300)
    assert out.num_rows == 8
    text = handle.explain_analyze()
    assert "rows=8" in text and "wall=" in text
    path = str(tmp_path / "query.json")
    n = handle.export_trace(path)
    assert n >= 1
    doc = json.load(open(path))
    qids = {e["args"]["query_id"] for e in doc["traceEvents"]
            if "args" in e and "query_id" in e["args"]}
    assert qids == {handle.query_id}
    # serving lifecycle instants rode the query's spans
    names = {e["name"] for e in doc["traceEvents"]}
    assert any(nm.startswith("serving.state.") for nm in names), names


def test_handle_analyze_requires_tracing():
    sess = TpuSession(BASE_CONF)
    handle = sess.submit(_q(sess))
    handle.result(timeout=300)
    with pytest.raises(RuntimeError, match="trace.enabled"):
        handle.explain_analyze()


# ------------------------------------------------- registry coverage (S4)
def test_every_registry_section_in_last_metrics_and_handle():
    """Every *_METRIC_NAMES registry entry must be present in its
    session.last_metrics section after an action that exercises the
    engine, and in QueryHandle.exec_metrics — the full-tuple contract
    (was only spot-checked per section before)."""
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore."
                       "forcePartitions": "2"})
    df = _q(sess)
    df.collect()
    sections = {"transfer": um.TRANSFER_METRIC_NAMES,
                "memory": um.MEMORY_METRIC_NAMES,
                "serving": um.SERVING_METRIC_NAMES}
    for section, name_tuple in sections.items():
        got = sess.last_metrics[section]
        missing = [n for n in name_tuple if n not in got]
        assert not missing, f"last_metrics[{section!r}] missing {missing}"
    handle = sess.submit(df)
    handle.result(timeout=300)
    for section, name_tuple in sections.items():
        got = handle.exec_metrics[section]
        missing = [n for n in name_tuple if n not in got]
        assert not missing, f"exec_metrics[{section!r}] missing {missing}"
    # the action exercised the memory section for real
    assert sess.last_metrics["memory"]["memory.spill_partitions"] >= 2


# ------------------------------------- recursion-depth attribution (S1 fix)
def test_recursion_depth_thread_scoped_attribution():
    """The PR 11 round-2 race: the shared re-armed global misattributed
    depth under CONCURRENT overlap. The fix binds the peak to the action
    scope — two overlapping actions each see exactly their own."""
    results = {}
    barrier = threading.Barrier(2)

    def run(name, depth):
        with um.action_depth_scope() as holder:
            barrier.wait()          # both scopes open concurrently
            if depth:
                um.note_recursion_depth(depth)
            barrier.wait()          # neither scope closed yet
            results[name] = holder.peak

    threads = [threading.Thread(target=run, args=("deep", 3)),
               threading.Thread(target=run, args=("shallow", 0))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {"deep": 3, "shallow": 0}
    # the global keeps the process-lifetime high-water mark
    assert um.MEMORY_METRICS[um.MEM_RECURSION_DEPTH].value >= 3


def test_recursion_depth_per_query_and_per_action():
    sess = TpuSession({**BASE_CONF,
                       "spark.rapids.tpu.memory.outOfCore."
                       "forcePartitions": "2"})
    handle = sess.submit(_q(sess))
    handle.result(timeout=300)
    assert handle.metrics["recursion_depth_peak"] >= 1
    assert handle.exec_metrics["memory"]["memory.recursion_depth_peak"] >= 1
    # a LATER grace-free action reports 0 even though the process-global
    # lifetime maximum already advanced (per-action scope, not the global)
    clean = TpuSession(BASE_CONF)
    _q(clean).collect()
    assert clean.last_metrics["memory"]["memory.recursion_depth_peak"] == 0
    assert um.MEMORY_METRICS[um.MEM_RECURSION_DEPTH].value >= 1
