"""TPU resource discovery (ExclusiveModeGpuDiscoveryPlugin analog,
ExclusiveModeGpuDiscoveryPlugin.scala).

Spark executors discover accelerators through a resource-discovery script
that prints a JSON document {"name": ..., "addresses": [...]}; the
reference's plugin additionally picks an UNUSED GPU by taking an exclusive
OS-level lock per device so co-located executors never share a chip. This
module is both: ``python -m spark_rapids_tpu.discovery`` prints the
discovery JSON, and ``acquire_exclusive()`` flock-claims one visible TPU
device for the calling process (released on process exit).
"""
from __future__ import annotations

import fcntl
import json
import os
import tempfile
from dataclasses import dataclass
from typing import List, Optional


def visible_devices() -> List[str]:
    """Addresses of the visible TPU devices (device ids as strings)."""
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:  # CPU-only host (tests): virtual addresses
        devs = jax.devices()
    return [str(d.id) for d in devs]


def _lock_dir() -> str:
    d = os.path.join(tempfile.gettempdir(), "spark-rapids-tpu-locks")
    os.makedirs(d, exist_ok=True)
    return d


@dataclass
class DeviceClaim:
    address: str
    _fh: object

    def release(self) -> None:
        try:
            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()
        except OSError:
            pass


def acquire_exclusive(addresses: Optional[List[str]] = None,
                      lock_dir: Optional[str] = None
                      ) -> Optional[DeviceClaim]:
    """Claim ONE unused device via a per-device exclusive flock (the
    exclusive-mode selection loop of the reference's discovery plugin).
    Returns None when every visible device is already claimed. A lock file
    we cannot even open (another user's claim on a shared host) counts as
    claimed; the holder's recorded PID is only written AFTER the lock is
    ours (append mode never truncates a holder's record)."""
    d = lock_dir or _lock_dir()
    for addr in addresses if addresses is not None else visible_devices():
        try:
            fh = open(os.path.join(d, f"tpu-{addr}.lock"), "a")
        except OSError:
            continue  # unreadable/unwritable lock = someone else's device
        try:
            fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            fh.truncate(0)
            fh.write(str(os.getpid()))
            fh.flush()
            return DeviceClaim(addr, fh)
        except OSError:
            fh.close()
    return None


def main() -> int:
    """Spark resource-discovery script protocol: one JSON line."""
    print(json.dumps({"name": "tpu", "addresses": visible_devices()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
