"""Typed configuration system for the TPU accelerator.

TPU-native analog of the reference's ``RapidsConf`` builder DSL
(reference: sql-plugin RapidsConf.scala:235 ``conf(key)``, ~60 ``spark.rapids.*`` keys,
doc generation at RapidsConf.scala:641).

Every tunable in the framework is declared here with a type, default, and doc string.
``TpuConf`` is an immutable snapshot of key->value overrides layered over the defaults;
``generate_docs()`` emits the markdown configuration reference (analog of docs/configs.md).

Per-rule enable keys (``spark.rapids.tpu.sql.expression.<Name>`` etc.) are derived
dynamically by the rule registry (see plan/overrides.py), mirroring
GpuOverrides.scala:126 ``ReplacementRule.confKey``.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

_PREFIX = "spark.rapids.tpu"


@dataclass(frozen=True)
class ConfEntry:
    """One declared configuration key (analog of ConfEntry, RapidsConf.scala)."""

    key: str
    conf_type: type
    default: Any
    doc: str
    internal: bool = False
    checker: Optional[Callable[[Any], Optional[str]]] = None

    def convert(self, raw: Any) -> Any:
        if raw is None:
            return None
        if self.conf_type is bool:
            if isinstance(raw, bool):
                return raw
            return str(raw).strip().lower() in ("true", "1", "yes", "on")
        if self.conf_type is int:
            return int(str(raw), 0) if isinstance(raw, str) else int(raw)
        if self.conf_type is float:
            return float(raw)
        return str(raw)


_REGISTRY: Dict[str, ConfEntry] = {}
_REG_LOCK = threading.Lock()


def _conf(key: str, conf_type: type, default: Any, doc: str,
          internal: bool = False,
          checker: Optional[Callable[[Any], Optional[str]]] = None) -> ConfEntry:
    full = key if key.startswith(_PREFIX) else f"{_PREFIX}.{key}"
    entry = ConfEntry(full, conf_type, default, doc, internal, checker)
    with _REG_LOCK:
        if full in _REGISTRY:
            raise ValueError(f"duplicate conf key {full}")
        _REGISTRY[full] = entry
    return entry


def _positive(name: str) -> Callable[[Any], Optional[str]]:
    def check(v: Any) -> Optional[str]:
        return None if v > 0 else f"{name} must be > 0, got {v}"
    return check


def _fraction(name: str) -> Callable[[Any], Optional[str]]:
    def check(v: Any) -> Optional[str]:
        return None if 0.0 < v <= 1.0 else f"{name} must be in (0, 1], got {v}"
    return check


def _non_negative(name: str) -> Callable[[Any], Optional[str]]:
    def check(v: Any) -> Optional[str]:
        return None if v >= 0 else f"{name} must be >= 0, got {v}"
    return check


# --------------------------------------------------------------------------------------
# General / plan-rewrite keys (analog of spark.rapids.sql.* in RapidsConf.scala)
# --------------------------------------------------------------------------------------
SQL_ENABLED = _conf(
    "sql.enabled", bool, True,
    "Enable (true) or disable (false) TPU acceleration of Spark SQL plans. When disabled "
    "every operator runs on the CPU engine (analog of spark.rapids.sql.enabled).")

EXPLAIN = _conf(
    "sql.explain", str, "NONE",
    "Explain why parts of a query were or were not placed on the TPU. Values: NONE, "
    "NOT_ON_TPU (print only fallback reasons), ALL (analog of spark.rapids.sql.explain).")

INCOMPATIBLE_OPS = _conf(
    "sql.incompatibleOps.enabled", bool, False,
    "Enable operators that produce results slightly different from Spark's CPU semantics "
    "(e.g. float-sum ordering). Analog of spark.rapids.sql.incompatibleOps.enabled.")

HAS_NANS = _conf(
    "sql.hasNans", bool, True,
    "Assume floating point columns may contain NaN; some ops (joins/aggregates on float "
    "keys) fall back when true. Analog of spark.rapids.sql.hasNans.")

ENABLE_FLOAT_AGG = _conf(
    "sql.variableFloatAgg.enabled", bool, False,
    "Allow float/double aggregations whose result can vary with evaluation order "
    "(parallel reductions). Analog of spark.rapids.sql.variableFloatAgg.enabled.")

CACHED_SCAN_ENABLED = _conf(
    "sql.cachedScan.enabled", bool, True,
    "Scan df.cache()/persist() data on the TPU. Cached batches live in the tiered "
    "spillable store (device->host->disk); disabling this serves them to the CPU engine "
    "instead. Analog of the reference accelerating Spark-cached data (HostColumnarToGpu).")

SCAN_CACHE_ENABLED = _conf(
    "sql.scanCache.enabled", bool, True,
    "Keep device copies of scanned in-memory tables across actions, so repeated queries "
    "over the same DataFrame skip the host-to-device upload (device-tier analog of the "
    "RapidsBufferCatalog's cached batches).")

SCAN_CACHE_BYTES = _conf(
    "sql.scanCache.maxBytes", int, 2 << 30,
    "Upper bound on device bytes held by the scan cache; least-recently-used tables are "
    "evicted past it.")

ENABLE_CAST_FLOAT_TO_STRING = _conf(
    "sql.castFloatToString.enabled", bool, False,
    "Cast float/double to string on the TPU; formatting may differ from Java in corner "
    "cases. Analog of spark.rapids.sql.castFloatToString.enabled.")

TEST_CONF = _conf(
    "sql.test.enabled", bool, False,
    "Test-mode: assert every supported operator actually ran on the TPU "
    "(analog of spark.rapids.sql.test.enabled).", internal=True)

MAX_READER_BATCH_SIZE_ROWS = _conf(
    "sql.reader.batchSizeRows", int, 2147483647,
    "Soft cap on rows per batch produced by scans "
    "(analog of spark.rapids.sql.reader.batchSizeRows).", checker=_positive("batchSizeRows"))

MAX_READER_BATCH_SIZE_BYTES = _conf(
    "sql.reader.batchSizeBytes", int, 2147483647,
    "Soft cap on bytes per batch produced by scans "
    "(analog of spark.rapids.sql.reader.batchSizeBytes).", checker=_positive("batchSizeBytes"))

TPU_BATCH_SIZE_BYTES = _conf(
    "sql.batchSizeBytes", int, 1 << 31,
    "Target size for coalesced batches flowing between TPU operators (analog of "
    "spark.rapids.sql.batchSizeBytes; default 2 GiB).", checker=_positive("batchSizeBytes"))

STRING_MAX_BYTES = _conf(
    "sql.string.maxBytes", int, 256,
    "Fixed per-row byte width of device string columns. Device strings are stored as a "
    "[rows, maxBytes] uint8 matrix plus a length vector (TPU-friendly layout); rows longer "
    "than this fall back to CPU.", checker=_positive("string.maxBytes"))

ADAPTIVE_ENABLED = _conf(
    "sql.adaptive.enabled", bool, False,
    "Adaptive query execution: run shuffle map stages first, then re-plan with "
    "the observed statistics — coalesce small reduce partitions into "
    "CustomShuffleReader groups and switch shuffled hash joins to broadcast "
    "when the built side turned out small (spark.sql.adaptive.enabled role).")

ADAPTIVE_ADVISORY_PARTITION_BYTES = _conf(
    "sql.adaptive.advisoryPartitionSizeInBytes", int, 64 * 1024 * 1024,
    "Target post-shuffle partition size for AQE coalescing "
    "(spark.sql.adaptive.advisoryPartitionSizeInBytes role).",
    checker=_positive("advisoryPartitionSizeInBytes"))

ADAPTIVE_SKEW_SPLIT_ENABLED = _conf(
    "sql.adaptive.skewSplit.enabled", bool, True,
    "Skew-split readers under AQE (spark.sql.adaptive.skewJoin.enabled role): "
    "a reduce partition observed larger than skewedPartitionFactor x the "
    "median splits into map-id slices (PartialReducerPartitionSpec "
    "semantics); the consuming shuffled hash join reads the other side's "
    "whole partition once per slice, so the union of the per-slice joins is "
    "the unsplit join bit-identically up to row order. Hash aggregates over "
    "a skewed exchange re-partition by group key instead "
    "(split-then-reaggregate via the out-of-core grace machinery).")

ADAPTIVE_SKEW_FACTOR = _conf(
    "sql.adaptive.skewedPartitionFactor", float, 5.0,
    "A reduce partition is skewed when its observed size exceeds this factor "
    "times the median partition size of its shuffle "
    "(spark.sql.adaptive.skewJoin.skewedPartitionFactor role).",
    checker=_positive("skewedPartitionFactor"))

ADAPTIVE_SKEW_THRESHOLD_BYTES = _conf(
    "sql.adaptive.skewedPartitionThreshold.bytes", int, 64 * 1024 * 1024,
    "Minimum observed partition size for skew handling to engage — partitions "
    "under this are never split however lopsided the shuffle "
    "(spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes role).",
    checker=_positive("skewedPartitionThreshold.bytes"))

ADAPTIVE_REFUSION_ENABLED = _conf(
    "sql.adaptive.refusion.enabled", bool, True,
    "Re-run the whole-stage fusion pass over the AQE-rewritten plan so "
    "fusible chains the rewrite created (e.g. the CoalesceBatches inserted "
    "above a coalesced shuffle reader, under a not-yet-fused device op) "
    "compile as one program. Programs keep the normal expression-signature "
    "cache keys, so identical rewritten chains share compilations and "
    "distinct ones never collide.")

ADAPTIVE_COST_MODEL_ENABLED = _conf(
    "sql.adaptive.costModel.enabled", bool, False,
    "Cost-based CPU-vs-TPU placement (generalizing the static float-agg "
    "fallback): at plan time, operators whose estimated input is under "
    "costModel.minDeviceRows stay on the CPU engine — device dispatch and "
    "compile overhead dominates tiny inputs; under AQE, shuffled hash joins "
    "whose OBSERVED inputs are under the threshold are re-placed on the CPU "
    "engine even when the estimates said otherwise.")

ADAPTIVE_COST_MODEL_MIN_DEVICE_ROWS = _conf(
    "sql.adaptive.costModel.minDeviceRows", int, 4096,
    "Row-count threshold for the adaptive cost model: operators with fewer "
    "(estimated or observed) input rows than this run on the CPU engine when "
    "sql.adaptive.costModel.enabled is on.",
    checker=_positive("costModel.minDeviceRows"))

BROADCAST_JOIN_THRESHOLD = _conf(
    "sql.broadcastJoinThreshold.bytes", int, 10 * 1024 * 1024,
    "Maximum estimated build-side size for a join to use the broadcast hash "
    "join strategy (the spark.sql.autoBroadcastJoinThreshold role). Sides with "
    "unknown size never broadcast.")

REPLACE_SORT_MERGE_JOIN = _conf(
    "sql.replaceSortMergeJoin.enabled", bool, True,
    "Replace CPU sort-merge joins with TPU shuffled-hash joins, dropping the sorts "
    "(analog of spark.rapids.sql.replaceSortMergeJoin.enabled).")

UDF_COMPILER_ENABLED = _conf(
    "sql.udfCompiler.enabled", bool, False,
    "Compile Python row UDFs into columnar expression trees so they ride the normal "
    "acceleration path (analog of spark.rapids.sql.udfCompiler.enabled).")

MESH_ENABLED = _conf(
    "sql.mesh.enabled", bool, False,
    "Distributed SPMD execution over a jax.sharding.Mesh: device subtrees run "
    "sharded across the mesh data axis with exchanges as ICI collectives "
    "(all_to_all repartition, all-gather broadcast/merge) — the role the "
    "reference fills with one-task-per-GPU executors plus the UCX accelerated "
    "shuffle (RapidsShuffleInternalManager). With sql.adaptive.enabled, mesh "
    "shuffled joins switch to broadcast at runtime when a build side "
    "materializes under broadcastJoinThreshold (observed size, not an "
    "estimate — every mesh exchange counts before it compiles, so there is "
    "no host-side re-planning pass to run). Mesh aggregations always pick "
    "their merge strategy from actual partial-group counts "
    "(sql.mesh.aggRepartitionThreshold), adaptive flag or not.")

MESH_NUM_DEVICES = _conf(
    "sql.mesh.numDevices", int, 0,
    "Devices in the execution mesh; 0 uses every visible device.")

MESH_SCAN_ASSIGNMENT = _conf(
    "sql.mesh.scan.shardAssignment", str, "rowgroup",
    "How mesh file scans split work across shards: 'rowgroup' balances "
    "statistics-clipped parquet ROW GROUPS over shards AT PLAN TIME (exact "
    "footer row counts, greedy LPT — one huge file still spreads over the "
    "mesh) and each shard's read uploads straight onto its owning device "
    "through the chunked transfer pipeline; 'file' keeps the execute-time "
    "whole-file assignment (formats without row-group metadata always use "
    "it).",
    checker=lambda v: (None if v in ("rowgroup", "file")
                       else f"sql.mesh.scan.shardAssignment must be "
                            f"rowgroup | file, got {v!r}"))

MESH_REQUIRE_ICI = _conf(
    "sql.mesh.requireIci", bool, True,
    "Clip the collective-exchange mesh to ONE ICI domain (the largest "
    "single-slice, single-process device group): in-mesh all_to_all / "
    "all-gather exchanges then never ride DCN, whose loss/latency profile "
    "belongs to the fault-tolerant TCP shuffle stack (shuffle/tcp.py + "
    "retry/checksum layers) instead. Disable only to let XLA schedule "
    "collectives across slices itself.")

EXCHANGE_KEEP_ENCODINGS = _conf(
    "sql.exchange.keepEncodings", bool, True,
    "Shuffle exchanges carry dictionary-encoded columns as int32 INDICES "
    "plus the shared dictionary through the partition/repack kernels "
    "instead of materializing decoded values first — shuffled bytes shrink "
    "by the same ratio the encoded scan bought, and encoded-domain "
    "operators keep working downstream of an exchange (the dictionary "
    "token survives).")

PARQUET_DEVICE_DICT = _conf(
    "io.parquet.deviceDictDecode.enabled", bool, True,
    "TPU parquet scans keep fixed-width columns dictionary-encoded through "
    "the read and decode them ON DEVICE with a gather (narrow indices + the "
    "small dictionary cross the host link instead of the decoded column — "
    "the GpuParquetScan.scala:576 device-decode role for the dictionary "
    "encoding). Strings stay host-decoded.")

PARQUET_DEVICE_RLE = _conf(
    "io.parquet.deviceRleExpand.enabled", bool, True,
    "TPU parquet scans keep RLE-dominant dictionary-encoded column chunks "
    "as (run-ends, run-values) pairs across the host link and expand them "
    "in HBM with a jitted searchsorted gather — often hundreds of bytes on "
    "the wire for millions of rows. Chunks whose index stream is mostly "
    "bit-packed ship as dictionary indices instead; requires "
    "deviceDictDecode.")

ENCODED_DOMAIN = _conf(
    "sql.encodedDomain.enabled", bool, True,
    "Run filters, group-by keys and equi-join keys directly on dictionary "
    "INDICES when a column's encoded form survived upload "
    "(DeviceColumn.encoding): predicates evaluate over the k dictionary "
    "values and gather per row, grouping hashes narrow int32 keys instead "
    "of wide string byte-matrices, and joins match on remapped indices — "
    "key values materialize only for the surviving groups (late "
    "materialization).")

FUSION_ENABLED = _conf(
    "sql.fusion.enabled", bool, True,
    "Whole-stage fusion: collapse maximal chains of fusable device execs "
    "(Project / Filter / Expand / CoalesceBatches, plus the partial-"
    "aggregate fold) between pipeline breakers into one FusedStageExec "
    "whose whole chain traces into a SINGLE jitted XLA program — a filter "
    "becomes a mask threaded through the downstream expressions and no "
    "intermediate batch materializes in HBM between the fused operators "
    "(the WholeStageCodegenExec role; Flare's whole-pipeline compilation "
    "argument). Breakers (exchange, sort, join, limit, union, cache and "
    "mesh boundaries) end a stage; fused stages render with a '*(id)' "
    "prefix in the plan tree.")

FUSION_MAX_OPS = _conf(
    "sql.fusion.maxOps", int, 16,
    "Upper bound on operators collapsed into one fused stage; chains "
    "longer than this split so a pathological plan cannot trace one "
    "enormous XLA program (the spark.sql.codegen.maxFields spirit).",
    checker=_positive("fusion.maxOps"))

SCAN_PREFETCH_BATCHES = _conf(
    "io.scan.prefetchBatches", int, 2,
    "Device parquet scans decode and upload this many chunks ahead of the "
    "consumer on a producer thread, overlapping host decode with the "
    "asynchronous host->device transfer and device compute (the "
    "bufferTime/gpuDecodeTime overlap in GpuParquetScan). 0 reads "
    "serially.")

SHUFFLE_KERNEL_MODE = _conf(
    "shuffle.kernel.mode", str, "auto",
    "Map-side partition reorder strategy: 'auto' uses the fused Pallas "
    "kernel (one streaming HBM pass: MXU one-hot spread into quota-padded "
    "partition pieces, 25+ GB/s/chip measured vs 3.8 GB/s for the variadic "
    "sort) on real TPU backends and the sort path elsewhere; 'interpret' "
    "forces the kernel in Pallas interpreter mode (tests); 'off' always "
    "uses the sort path. Overflowing quotas or non-packable batches fall "
    "back to the sort path automatically.",
    checker=lambda v: (None if v in ("auto", "interpret", "off")
                       else f"shuffle.kernel.mode must be auto | interpret"
                            f" | off, got {v!r}"))

SHUFFLE_DMA_CONSOLIDATE = _conf(
    "shuffle.kernel.dmaConsolidate.enabled", bool, False,
    "Consolidate the partition kernel's quota-padded pieces with ONE "
    "pipelined-DMA compaction program (per-partition semaphores, n copies "
    "in flight, barrier-free unpack) instead of per-partition gather "
    "programs. TPU backends only; elsewhere the gather path runs. Off by "
    "default: it pays a 128-lane pad pass, measured ahead only on wide "
    "schemas (see docs/perf-notes.md round 5).")

SHUFFLE_FETCH_TIMEOUT = _conf(
    "shuffle.fetch.timeoutSeconds", int, 300,
    "How long a reduce-side reader waits for remote shuffle blocks before "
    "raising ShuffleFetchFailedError (the stage-retry signal). Cold cluster "
    "executors pay first-compile latency on the serving path, so this "
    "defaults well above the transfer time itself.")

CLUSTER_EXECUTORS = _conf(
    "sql.cluster.numExecutors", int, 0,
    "Multi-executor query execution: plans split into shuffle stages at "
    "exchange boundaries and tasks run across this many executors, each with "
    "its own shuffle environment (tiered stores + catalogs + transport "
    "server). Exchanges write through the caching shuffle writer and reducers "
    "fetch local blocks from their catalog and remote blocks via the "
    "transport client — the load-bearing RapidsShuffleInternalManager path "
    "(RapidsShuffleInternalManager.scala:194, RapidsCachingReader.scala). "
    "0 disables (single-process engine). Mutually exclusive with mesh "
    "execution.")

CLUSTER_PROCESS_EXECUTORS = _conf(
    "sql.cluster.processExecutors", bool, False,
    "Run each cluster executor as its own OS process (daemon spawned per "
    "executor, tasks dispatched over a control socket, shuffle data over the "
    "TCP transport) instead of in-process executors — the cross-host "
    "topology. Requires the TCP shuffle transport; a registry directory is "
    "created automatically when not configured.")

CLUSTER_TASK_SLOTS = _conf(
    "sql.cluster.taskSlots", int, 4,
    "Concurrent tasks per cluster executor: a stage fans one task per "
    "partition and each executor runs up to this many at once, so stage "
    "wall-clock scales with partitions rather than executors (the "
    "executor-cores role in Spark's task model). Device admission within "
    "each executor is still gated by the concurrentTpuTasks semaphore "
    "(GpuSemaphore.scala:74).", checker=_positive("cluster.taskSlots"))

MESH_AGG_REPARTITION_THRESHOLD = _conf(
    "sql.mesh.aggRepartitionThreshold", int, 8192,
    "Distributed aggregations whose total partial-group count exceeds this "
    "switch from all-gather-and-merge-everywhere to a hash repartition of the "
    "partial buffers by key (each shard merges only its own key range) — the "
    "partial/final split over a hash exchange the reference uses for "
    "arbitrary-cardinality group-bys (aggregate.scala:227 + "
    "GpuHashPartitioning). Small groupings keep the all-gather merge: one "
    "collective, no repartition program.")

# --------------------------------------------------------------------------------------
# Transfer pipeline (host link overlap; the HostToGpuCoalesceIterator pinned-
# memory async-H2D role, engineered per Theseus: the link, device compute and
# host decode must run concurrently, with BOUNDED in-flight buffers)
# --------------------------------------------------------------------------------------
TRANSFER_CHUNK_ROWS = _conf(
    "transfer.chunkRows", int, 1 << 20,
    "Host->device uploads larger than this many rows split into row chunks "
    "so chunk N+1 stages on host while chunk N's asynchronous device_put is "
    "in flight, then reassemble on device (one concat program per schema/"
    "capacity). 0 uploads every table in a single shot.",
    checker=_non_negative("transfer.chunkRows"))

TRANSFER_MAX_INFLIGHT = _conf(
    "transfer.maxInflight", int, 2,
    "Bound on in-flight transfers: at most this many upload chunks (and, "
    "with streaming collect, per-batch downloads) may be outstanding before "
    "the pipeline blocks on the oldest — bounded buffering instead of an "
    "unbounded queue so HBM and host staging memory cannot be overrun.",
    checker=_positive("transfer.maxInflight"))

TRANSFER_PIPELINE_ENABLED = _conf(
    "transfer.pipeline.enabled", bool, True,
    "Planner-inserted bounded-async dispatch between scan and compute "
    "stages: a PipelinedExec wrapper keeps up to transfer.pipeline.depth "
    "batches in flight on a producer thread instead of the strict "
    "pull-per-batch lockstep, sharing the consumer task's device-admission "
    "semaphore hold for backpressure. Skipped on single-core hosts (the "
    "producer thread would only contend with the consumer).")

TRANSFER_PIPELINE_DEPTH = _conf(
    "transfer.pipeline.depth", int, 2,
    "How many batches a PipelinedExec stage boundary keeps in flight "
    "between the producing scan and the consuming compute stage.",
    checker=_positive("transfer.pipeline.depth"))

TRANSFER_STREAMING_COLLECT = _conf(
    "transfer.streamingCollect.enabled", bool, True,
    "collect() enqueues each result batch's device->host download as soon "
    "as its program is dispatched (copy_to_host_async) instead of syncing "
    "then downloading the full result at the end, so D2H overlaps the "
    "remaining compute; at most transfer.maxInflight downloads are "
    "outstanding. Batch order, error propagation and per-operator metrics "
    "are preserved.")

# --------------------------------------------------------------------------------------
# Memory / scheduling (analog of spark.rapids.memory.*)
# --------------------------------------------------------------------------------------
CONCURRENT_TPU_TASKS = _conf(
    "sql.concurrentTpuTasks", int, 2,
    "Number of tasks that may hold the TPU concurrently; the device-admission semaphore "
    "blocks the rest (analog of spark.rapids.sql.concurrentGpuTasks).",
    checker=_positive("concurrentTpuTasks"))

DEVICE_POOL_FRACTION = _conf(
    "memory.tpu.allocFraction", float, 0.9,
    "Fraction of available HBM the buffer arena may occupy "
    "(analog of spark.rapids.memory.gpu.allocFraction).", checker=_fraction("allocFraction"))

DEVICE_POOL_BYTES = _conf(
    "memory.tpu.poolSizeBytes", int, 0,
    "Explicit HBM arena size in bytes; 0 means derive from allocFraction and the "
    "detected device memory.")

HOST_SPILL_STORAGE_SIZE = _conf(
    "memory.host.spillStorageSize", int, 1 << 30,
    "Bytes of host memory used to hold batches spilled from HBM "
    "(analog of spark.rapids.memory.host.spillStorageSize).",
    checker=_positive("spillStorageSize"))

OOC_ENABLED = _conf(
    "memory.outOfCore.enabled", bool, True,
    "Out-of-core execution for hash aggregate, shuffled/broadcast hash join "
    "and sort: when an operator's working set will not fit the device "
    "budget (planner footprint estimate up front, or runtime pressure "
    "reactively), the input is hash/range-partitioned by key into "
    "spillable partitions across the device->host->disk tiers and the "
    "operator recurses per partition — grace-style degradation instead of "
    "an HBM allocation failure (the RapidsBufferCatalog spill design). "
    "With ample budget the single-pass hot path runs unchanged.")

OOC_HEADROOM = _conf(
    "memory.outOfCore.headroomFraction", float, 0.8,
    "Fraction of the free device budget an operator's estimated working "
    "set may occupy before the out-of-core path engages; the rest is "
    "headroom for the operator's own intermediates (sort passes, join "
    "output) and concurrent queries.", checker=_fraction("headroomFraction"))

OOC_FANOUT = _conf(
    "memory.outOfCore.fanout", int, 8,
    "Grace partitions created per recursion level when runtime pressure "
    "triggers partitioning without a plan-time footprint estimate; each "
    "level re-partitions with a depth-salted hash, so colliding key groups "
    "separate on the next level.", checker=_positive("outOfCore.fanout"))

OOC_MAX_PARTITIONS = _conf(
    "memory.outOfCore.maxPartitions", int, 256,
    "Upper bound on grace partitions one operator creates per recursion "
    "level (clamps the planner's footprint-derived choice).",
    checker=_positive("outOfCore.maxPartitions"))

OOC_MAX_DEPTH = _conf(
    "memory.outOfCore.maxRecursionDepth", int, 4,
    "Bound on grace recursion depth. A partition that still exceeds the "
    "budget at the deepest level (e.g. one giant key group, which no hash "
    "can split) runs single-pass there — completion is preferred over "
    "enforcing the budget exactly.",
    checker=_positive("outOfCore.maxRecursionDepth"))

OOC_FORCE_PARTITIONS = _conf(
    "memory.outOfCore.forcePartitions", int, 0,
    "Force every out-of-core-capable operator to grace-partition its input "
    "into this many partitions regardless of budget (0 disables). "
    "Deterministic degradation-path testing knob — the partitioned plan "
    "runs even when everything would fit.",
    checker=_non_negative("outOfCore.forcePartitions"))

MEMORY_FAULTS_PLAN = _conf(
    "memory.faults.plan", str, "",
    "Deterministic HBM-pressure fault-injection plan (empty = no faults), "
    "mirroring shuffle.faults.plan. Semicolon-separated specs, e.g. "
    "'alloc_fail:op=agg,after=1;budget_clamp:fraction=0.25'. Kinds: "
    "alloc_fail (the Nth working-set admission check of a matching "
    "operator fails, forcing the reactive out-of-core path), budget_clamp "
    "(the effective device budget shrinks to fraction of its real value; "
    "sustained — count defaults to 0 = every read). "
    "Keys: op (agg | join | sort | *), after, count (0 = every event), "
    "fraction. Honored by memory/faults.py probes in the grace layer.")

MEMORY_FAULTS_SEED = _conf(
    "memory.faults.seed", int, 0,
    "Identity of the memory fault schedule: the schedule itself is fully "
    "deterministic from the plan text, and (plan, seed) keys one "
    "process-wide event-counter instance — a new seed starts a fresh "
    "chaos run, the same pair replays the same one.")

# --------------------------------------------------------------------------------------
# Shuffle (analog of spark.rapids.shuffle.*)
# --------------------------------------------------------------------------------------
SHUFFLE_TRANSPORT_CLASS = _conf(
    "shuffle.transport.class", str,
    "spark_rapids_tpu.shuffle.inprocess.InProcessTransport",
    "Fully qualified class of the shuffle transport used for peer-to-peer fetches "
    "(analog of spark.rapids.shuffle.transport.class selecting the UCX transport). "
    "InProcessTransport serves executors within one process; cross-host DCN transports "
    "implement the same traits. Mesh-local exchanges bypass this entirely via the ICI "
    "all_to_all path (shuffle/ici.py).")

SHUFFLE_TCP_PORT = _conf(
    "shuffle.tcp.listenPort", int, 0,
    "Listen port of the TCP shuffle transport's management/data socket "
    "(UCX.scala:113 startManagementPort analog); 0 picks an ephemeral port, "
    "published through the registry directory.")

SHUFFLE_TCP_REGISTRY = _conf(
    "shuffle.tcp.registryDir", str, "",
    "Directory where TCP-transport executors publish their host:port for peer "
    "discovery (the management-handshake rendezvous; shared storage or the "
    "control plane's executor registry on a real cluster).")

SHUFFLE_TCP_WORKER_THREADS = _conf(
    "shuffle.tcp.workerThreads", int, 2,
    "Request-handler worker threads per TCP transport (the server "
    "copy-executor pool). The shuffle data plane needs few; the serving "
    "wire protocol (serving/server.py) raises this so bounded-poll "
    "serve.next handlers from many clients do not head-of-line-block each "
    "other.", checker=_positive("shuffle.tcp.workerThreads"))

SHUFFLE_MAX_INFLIGHT_BYTES = _conf(
    "shuffle.maxReceiveInflightBytes", int, 1 << 30,
    "Per-client cap on bytes of shuffle data in flight "
    "(analog of spark.rapids.shuffle.ucx.maxReceiveInflightBytes).")

SHUFFLE_BOUNCE_BUFFER_SIZE = _conf(
    "shuffle.bounceBuffers.size", int, 4 << 20,
    "Size of each bounce buffer used to stage shuffle sends/receives.")

SHUFFLE_BOUNCE_BUFFER_COUNT = _conf(
    "shuffle.bounceBuffers.count", int, 32,
    "Number of bounce buffers per direction.")

SHUFFLE_COMPRESSION_CODEC = _conf(
    "shuffle.compression.codec", str, "none",
    "Codec for shuffle batches: none, copy (memcpy pseudo-codec for testing), "
    "lz4 (always available; the fast default for network-bound shuffles), "
    "zlib, zstd (needs the zstandard package) — analog of "
    "spark.rapids.shuffle.compression.codec. A peer that lacks the "
    "requested codec negotiates the transfer down to copy (TableMeta.codec "
    "carries the codec actually applied).")

SHUFFLE_ZLIB_LEVEL = _conf(
    "shuffle.compression.zlib.level", int, 1,
    "zlib compression level (0-9) for shuffle batches when "
    "shuffle.compression.codec=zlib; 1 favors speed, 9 ratio.",
    checker=lambda v: (None if 0 <= v <= 9
                       else f"zlib.level must be in [0, 9], got {v}"))


SHUFFLE_MAX_RETRIES = _conf(
    "shuffle.maxRetries", int, 3,
    "How many times a transient shuffle failure is retried before it becomes "
    "fatal, at every level of the stack: TCP connect attempts, metadata/"
    "transfer RPCs, per-block transfers (including checksum mismatches), and "
    "reduce-side per-peer re-fetches (which reconnect after a peer loss). "
    "0 disables retries — the first failure surfaces immediately as "
    "ShuffleFetchFailedError (the lineage-recompute signal).",
    checker=_non_negative("maxRetries"))

SHUFFLE_RETRY_BACKOFF_MS = _conf(
    "shuffle.retryBackoffMs", int, 50,
    "Base delay between shuffle retries. Attempt i sleeps roughly "
    "base * 2^i with deterministic jitter (seeded by the retry key), so "
    "retries from many reducers hitting one recovering peer spread out "
    "instead of stampeding.", checker=_positive("retryBackoffMs"))

SHUFFLE_CONNECT_TIMEOUT = _conf(
    "shuffle.connectTimeout", float, 30.0,
    "Seconds a single TCP shuffle connect attempt (registry resolution + "
    "socket establishment) may take before it counts as a transient failure "
    "and enters the retry/backoff schedule.",
    checker=_positive("connectTimeout"))

SHUFFLE_CHECKSUM_ENABLED = _conf(
    "shuffle.checksum.enabled", bool, True,
    "Verify a crc32 over every fetched shuffle buffer (computed by the "
    "server over the on-wire bytes, carried in TransferResponse/TableMeta). "
    "A mismatch marks the transfer as a retryable corruption instead of "
    "silently producing wrong rows; disabling skips client-side "
    "verification only.")

SHUFFLE_RECOMPUTE_MAX_STAGE_ATTEMPTS = _conf(
    "shuffle.recompute.maxStageAttempts", int, 2,
    "How many lineage-scoped recompute rounds one stage may run after its "
    "reduce side exhausts per-peer fetch retries (ShuffleFetchFailedError). "
    "Each round re-executes ONLY the lost map tasks on surviving executors "
    "and replaces their blocks exactly-once; past the budget the error "
    "re-surfaces and the serving failover path (replica re-run) owns "
    "recovery. 0 disables recompute — every fetch failure escalates "
    "directly, the pre-lineage behavior.",
    checker=_non_negative("maxStageAttempts"))

SHUFFLE_FAULTS_PLAN = _conf(
    "shuffle.faults.plan", str, "",
    "Deterministic fault-injection plan for chaos testing the shuffle stack "
    "(empty = no faults). Semicolon-separated specs, e.g. "
    "'drop_conn:peer=exec-1,after=3;corrupt_frame:after=1,count=2'. Kinds: "
    "drop_conn, corrupt_frame, delay_frame, dup_frame, fail_request. Only "
    "honored by the FaultInjectingTransport (shuffle/faults.py).")

SHUFFLE_FAULTS_SEED = _conf(
    "shuffle.faults.seed", int, 0,
    "Seed for the fault-injection plan's random choices (which byte a "
    "corrupt_frame flips, backoff jitter inside the harness) — the same "
    "seed replays the exact same chaos schedule.")

SHUFFLE_FAULTS_TRANSPORT = _conf(
    "shuffle.faults.transport.class", str,
    "spark_rapids_tpu.shuffle.inprocess.InProcessTransport",
    "Transport the FaultInjectingTransport wraps (in-process fabric or the "
    "TCP transport); all traffic flows through the wrapped transport with "
    "faults injected at the connection layer.")

# --------------------------------------------------------------------------------------
# I/O formats (analog of spark.rapids.sql.format.*)
# --------------------------------------------------------------------------------------
PARQUET_ENABLED = _conf(
    "sql.format.parquet.enabled", bool, True,
    "Enable TPU parquet scan/write as a whole.")
PARQUET_READ_ENABLED = _conf(
    "sql.format.parquet.read.enabled", bool, True, "Enable TPU parquet scans.")
PARQUET_WRITE_ENABLED = _conf(
    "sql.format.parquet.write.enabled", bool, True, "Enable TPU parquet writes.")
ORC_ENABLED = _conf(
    "sql.format.orc.enabled", bool, True, "Enable TPU ORC scan/write as a whole.")
ORC_READ_ENABLED = _conf(
    "sql.format.orc.read.enabled", bool, True, "Enable TPU ORC scans.")
ORC_WRITE_ENABLED = _conf(
    "sql.format.orc.write.enabled", bool, True, "Enable TPU ORC writes.")
CSV_ENABLED = _conf(
    "sql.format.csv.enabled", bool, True, "Enable TPU CSV scanning as a whole.")
CSV_READ_ENABLED = _conf(
    "sql.format.csv.read.enabled", bool, True, "Enable TPU CSV scans.")

# --------------------------------------------------------------------------------------
# Serving (concurrent query scheduler + cross-query program cache)
# --------------------------------------------------------------------------------------
SERVING_MAX_CONCURRENT = _conf(
    "serving.maxConcurrentQueries", int, 4,
    "How many submitted queries the session scheduler runs concurrently "
    "(the shared worker-pool size). Queries past the bound wait in their "
    "tenant's FIFO queue under fair-share admission; device admission "
    "within a running query is still gated by sql.concurrentTpuTasks.",
    checker=_positive("serving.maxConcurrentQueries"))

SERVING_TENANT_WEIGHTS = _conf(
    "serving.tenantWeights", str, "",
    "Per-tenant fair-share weights as 'tenant:weight,...' (e.g. "
    "'etl:3,adhoc:1'). Admission picks the queued tenant with the lowest "
    "served/weight deficit (FIFO within a tenant); unlisted tenants weigh "
    "1. The same weights drive the device-admission semaphore so a heavy "
    "tenant cannot starve the rest at either layer.")

SERVING_SHAPE_BUCKETS = _conf(
    "serving.shapeBuckets", bool, True,
    "Bucket row counts to powers of two in cross-query program-cache keys "
    "(the tpu-lint R001 discipline): row-count drift between batches of "
    "the same plan reuses one compiled program instead of recompiling per "
    "exact shape. Disabling keys programs on exact capacities — only for "
    "debugging recompile behavior.")

SERVING_QUERY_TIMEOUT = _conf(
    "serving.queryTimeoutSeconds", float, 0.0,
    "Default per-query deadline for submitted queries, enforced "
    "cooperatively at exec boundaries and in the pipeline producer; a "
    "query past its deadline fails with QueryTimeoutError and releases "
    "its device-semaphore hold and catalog buffers. 0 disables; "
    "session.submit(timeout=...) overrides per query.",
    checker=_non_negative("serving.queryTimeoutSeconds"))

SERVING_CACHE_DIR = _conf(
    "serving.cache.dir", str, "",
    "Directory of the serving program-cache's on-disk plan-key index "
    "(plus the jax persistent compilation cache it rides on): a restarted "
    "server warms compiled programs from disk instead of re-tracing them "
    "cold. Empty uses the process compilation-cache directory configured "
    "at startup (device.py); 'off' disables the index.")

SERVING_CACHE_MAX_PROGRAMS = _conf(
    "serving.cache.maxPrograms", int, 4096,
    "Upper bound on compiled programs the in-memory cross-query cache "
    "retains; least-recently-used programs are dropped past it (their "
    "on-disk compilation-cache entries survive, so a re-miss recompiles "
    "warm).", checker=_positive("serving.cache.maxPrograms"))

# --------------------------------------------------------------------------------------
# Serving: network wire protocol, footprint admission, preemption
# --------------------------------------------------------------------------------------
SERVING_NET_PORT = _conf(
    "serving.net.listenPort", int, 0,
    "Listen port of the query service's wire transport (Arrow IPC over the "
    "TCP shuffle framing); 0 picks an ephemeral port, printed by the server "
    "process at startup.")

SERVING_NET_TRANSPORT = _conf(
    "serving.net.transportClass", str,
    "spark_rapids_tpu.shuffle.tcp.TcpTransport",
    "Transport class the query service speaks over — the PR 2 "
    "framing/checksum/retry stack, NOT new plumbing. Any ShuffleTransport "
    "implementation works; tests swap in the in-process fabric.")

SERVING_NET_FAULTS_PLAN = _conf(
    "serving.net.faults.plan", str, "",
    "Deterministic wire-chaos plan for the query service (empty = none): "
    "the shuffle FaultPlan grammar (drop_conn / corrupt_frame / "
    "delay_frame / dup_frame / fail_request) injected by wrapping the "
    "serving transport in the FaultInjectingTransport — corrupted result "
    "frames must surface as retryable checksum failures, dropped "
    "connections as failed handles with a batches-delivered count.")

SERVING_NET_FAULTS_SEED = _conf(
    "serving.net.faults.seed", int, 0,
    "Seed for the serving wire-chaos plan's random choices; a fixed seed "
    "replays the same schedule (mirrors shuffle.faults.seed).")

SERVING_NET_POLL_MS = _conf(
    "serving.net.nextPollMs", int, 20,
    "How long a serve.next handler waits (bounded — the R010 discipline) "
    "for the query's next streamed batch before answering WAIT and "
    "releasing its transport worker thread; the client re-polls "
    "immediately, so this bounds handler occupancy, not stream latency.",
    checker=_positive("serving.net.nextPollMs"))

SERVING_NET_STREAM_DEPTH = _conf(
    "serving.net.streamQueueDepth", int, 4,
    "Bound on result batches buffered server-side per streaming query "
    "between the scheduler worker (producer) and the wire layer "
    "(consumer); a full queue backpressures the producer at its next "
    "batch boundary — bounded buffering, never an unbounded queue.",
    checker=_positive("serving.net.streamQueueDepth"))

SERVING_NET_MAX_STREAM_ROWS = _conf(
    "serving.net.maxStreamBatchRows", int, 1 << 20,
    "Result batches larger than this many rows are sliced into multiple "
    "wire frames before streaming, bounding per-frame memory on both ends "
    "(slices concatenate client-side to the bit-identical table). "
    "0 streams every exec batch whole.",
    checker=_non_negative("serving.net.maxStreamBatchRows"))

SERVING_NET_RPC_TIMEOUT = _conf(
    "serving.net.rpcTimeoutSeconds", float, 60.0,
    "Client-side bound on any single wire RPC (submit / next / fetch / "
    "cancel) and on each posted batch receive; an expired wait surfaces "
    "as a failed handle with its batches-delivered count, never a hang.",
    checker=_positive("serving.net.rpcTimeoutSeconds"))

SERVING_ADMIT_FOOTPRINT = _conf(
    "serving.admission.byFootprint.enabled", bool, True,
    "Admit RUNNING queries against the device budget using the plan's "
    "working_set_estimate (the PR 11 footprint contract) instead of a "
    "bare query count: a query whose estimate does not fit the free "
    "budget waits (cancellable, visible in "
    "serving.admission_rejections_footprint) until running queries "
    "release their share. A query larger than the whole budget is "
    "admitted under a grace hint, charged the out-of-core HEADROOM "
    "share of the budget — the grace/spill layer completes it within "
    "that share, and the remaining fraction stays free so interactive "
    "queries still reach the device semaphore (where preemption can "
    "see them) alongside a whale.")

SERVING_PREEMPT_ENABLED = _conf(
    "serving.preemption.enabled", bool, False,
    "Batch-granularity preemption of RUNNING queries: when another "
    "tenant's query has starved on device admission past "
    "preemption.starvationMs, a preemptible running query yields its "
    "device-semaphore permit at its next exec-boundary checkpoint "
    "(check_cancelled sites), optionally parks spillable device state "
    "down the grace/spill tiers, and re-acquires under fair share — so a "
    "whale cannot starve interactive tenants between its batches.")

SERVING_PREEMPT_STARVATION_MS = _conf(
    "serving.preemption.starvationMs", int, 50,
    "How long another tenant's head-of-line device-admission waiter must "
    "have been blocked before a running preemptible query yields at its "
    "next batch boundary.",
    checker=_positive("serving.preemption.starvationMs"))

SERVING_PREEMPT_PARK = _conf(
    "serving.preemption.parkSpillable", bool, True,
    "On yield, shed the device store down to the out-of-core headroom "
    "watermark (memory.outOfCore.headroomFraction) — coldest-first, so "
    "the overage parked down the host/disk tiers is in practice the "
    "yielding whale's grace partitions, and the admitted tenant gets "
    "immediate HBM headroom; parked state re-admits on next access. "
    "Disabling leaves parking to the store's reactive pressure path.")

# --------------------------------------------------------------------------------------
# Serving: replica health, failover, routing (the fleet-resilience layer)
# --------------------------------------------------------------------------------------

SERVING_NET_REGISTRY = _conf(
    "serving.net.registryDir", str, "",
    "Registry directory for serving-replica discovery (the shuffle "
    "registry-dir rendezvous applied to the query service): each replica "
    "publishes <dir>/<executor_id> containing host:port and refreshes the "
    "file's mtime as a liveness heartbeat; clients scan the directory to "
    "discover replicas, skipping (and garbage-collecting) entries whose "
    "heartbeat is older than serving.health.livenessWindowSeconds. Empty "
    "disables discovery — clients then need explicit addresses.")

SERVING_HEALTH_HEARTBEAT = _conf(
    "serving.health.heartbeatSeconds", float, 1.0,
    "How often a serving replica refreshes its registry-file mtime (the "
    "liveness heartbeat). A SIGKILL'd replica stops heartbeating, so its "
    "entry ages out of the liveness window and clients stop routing to "
    "it even though the process never removed its file.",
    checker=_positive("serving.health.heartbeatSeconds"))

SERVING_HEALTH_LIVENESS_WINDOW = _conf(
    "serving.health.livenessWindowSeconds", float, 5.0,
    "Registry entries whose heartbeat mtime is older than this are "
    "considered dead: discovery scans skip them and remove the stale "
    "file (a crashed replica cannot retract its own entry). Keep this "
    "a few multiples of serving.health.heartbeatSeconds so a slow "
    "heartbeat is not mistaken for a death.",
    checker=_positive("serving.health.livenessWindowSeconds"))

SERVING_HEALTH_PROBE_INTERVAL = _conf(
    "serving.health.probeIntervalSeconds", float, 2.0,
    "How often the client re-probes each replica's serve.health RPC "
    "(liveness + the serve.stats snapshot load-aware routing scores). "
    "Probes run on the routing path when the last snapshot is older "
    "than this; 0 probes before every routing decision (tests).",
    checker=_non_negative("serving.health.probeIntervalSeconds"))

SERVING_HEALTH_PROBE_TIMEOUT = _conf(
    "serving.health.probeTimeoutSeconds", float, 5.0,
    "Bound on one serve.health probe RPC — probes must fail fast so a "
    "hung replica costs the router one bounded wait, not the full "
    "serving.net.rpcTimeoutSeconds.",
    checker=_positive("serving.health.probeTimeoutSeconds"))

SERVING_FAILOVER_ENABLED = _conf(
    "serving.failover.enabled", bool, True,
    "Resubmit a mid-stream query to a healthy replica when its replica "
    "dies (connection lost / RPC timeout / exhausted frame retries), "
    "resuming the result stream from the last delivered batch sequence "
    "number: the new replica re-runs the query and skips already-"
    "delivered frames (dedup by seq — exactly-once delivery to the "
    "caller). Only queries marked idempotent fail over (the default for "
    "pure SELECTs); non-idempotent queries surface WireQueryError with "
    "batches_delivered as before.")

SERVING_FAILOVER_MAX_ATTEMPTS = _conf(
    "serving.failover.maxAttempts", int, 3,
    "How many times one query may fail over to another replica before "
    "the client gives up and surfaces the failure.",
    checker=_positive("serving.failover.maxAttempts"))

SERVING_BREAKER_THRESHOLD = _conf(
    "serving.failover.breakerFailureThreshold", int, 3,
    "Consecutive probe/submit/stream failures against one replica that "
    "flip its client-side circuit breaker OPEN. An OPEN replica receives "
    "ZERO submissions; only health probes (on the exponential-backoff "
    "schedule) go there, and one probe success closes the breaker.",
    checker=_positive("serving.failover.breakerFailureThreshold"))

SERVING_BREAKER_BACKOFF_MS = _conf(
    "serving.failover.breakerBackoffMs", int, 200,
    "Base backoff between an OPEN breaker's health probes; successive "
    "failed probes back off exponentially with deterministic jitter "
    "(the shuffle/retry.py schedule, seeded by serving.net.faults.seed).",
    checker=_positive("serving.failover.breakerBackoffMs"))

SERVING_ROUTING_POLICY = _conf(
    "serving.routing.policy", str, "loadaware",
    "How the client picks a replica for a new submission: 'loadaware' "
    "scores each healthy replica's latest serve.health snapshot (free "
    "device budget after footprint charges, queue depth + running "
    "count, p99 wall over the stats window) and routes to the best — "
    "the whale lands on the replica with free budget; 'roundrobin' is "
    "the PR 12 rotation. Replicas behind an OPEN breaker or DRAINING "
    "are excluded under either policy.",
    checker=lambda v: (None if v in ("loadaware", "roundrobin") else
                       f"serving.routing.policy must be 'loadaware' or "
                       f"'roundrobin', got {v!r}"))

# --------------------------------------------------------------------------------------
# Serving: elastic fleet (supervisor + autoscaler) and overload shedding
# --------------------------------------------------------------------------------------

SERVING_FLEET_MIN_REPLICAS = _conf(
    "serving.fleet.minReplicas", int, 1,
    "Lower bound on supervised replica slots: the autoscaler never "
    "scales the fleet below this many (DEGRADED crash-looping slots "
    "still count toward the bound — the controller cannot drain its "
    "way to an empty fleet).",
    checker=_positive("serving.fleet.minReplicas"))

SERVING_FLEET_MAX_REPLICAS = _conf(
    "serving.fleet.maxReplicas", int, 4,
    "Upper bound on supervised replica slots: scale-up stops here no "
    "matter the pressure — past it the front door sheds "
    "(serving.maxQueuedPerTenant / OverloadedError) instead of growing.",
    checker=_positive("serving.fleet.maxReplicas"))

SERVING_FLEET_SUPERVISE_INTERVAL = _conf(
    "serving.fleet.superviseIntervalSeconds", float, 0.2,
    "Supervisor sweep period: each tick polls every slot's process for "
    "exit, checks registry heartbeats against the liveness window, and "
    "restarts due slots on the deterministic backoff schedule.",
    checker=_positive("serving.fleet.superviseIntervalSeconds"))

SERVING_FLEET_RESTART_BACKOFF_MS = _conf(
    "serving.fleet.restartBackoffMs", int, 200,
    "Base delay before restarting a dead replica slot; successive "
    "deaths of the same slot back off exponentially with deterministic "
    "jitter (the shuffle/retry.py schedule, keyed by slot index), and "
    "the attempt counter resets after "
    "serving.fleet.stableUptimeSeconds of healthy uptime.",
    checker=_positive("serving.fleet.restartBackoffMs"))

SERVING_FLEET_STABLE_UPTIME = _conf(
    "serving.fleet.stableUptimeSeconds", float, 30.0,
    "A replica that stays up this long is considered stable: its slot's "
    "restart-backoff attempt counter resets, so the next (unrelated) "
    "death restarts fast instead of inheriting an old slow schedule.",
    checker=_positive("serving.fleet.stableUptimeSeconds"))

SERVING_FLEET_CRASH_LOOP_THRESHOLD = _conf(
    "serving.fleet.crashLoopThreshold", int, 3,
    "Crash-loop breaker: this many deaths of one slot within "
    "serving.fleet.crashLoopWindowSeconds stops the restart storm — the "
    "slot is marked DEGRADED (no further restarts, surfaced in fleet "
    "stats and excluded from the autoscaler's healthy count) instead of "
    "burning CPU forever. reset_slot() re-arms it after the operator "
    "fixes the cause.",
    checker=_positive("serving.fleet.crashLoopThreshold"))

SERVING_FLEET_CRASH_LOOP_WINDOW = _conf(
    "serving.fleet.crashLoopWindowSeconds", float, 10.0,
    "Sliding window the crash-loop breaker counts slot deaths over: "
    "deaths older than this no longer count toward the threshold.",
    checker=_positive("serving.fleet.crashLoopWindowSeconds"))

SERVING_FLEET_CONTROL_INTERVAL = _conf(
    "serving.fleet.controlIntervalSeconds", float, 1.0,
    "Autoscaler control-loop period: each tick aggregates serve.health "
    "snapshots across the fleet and makes one scaling decision "
    "(watermarks + hysteresis + cooldowns).",
    checker=_positive("serving.fleet.controlIntervalSeconds"))

SERVING_FLEET_SCALE_UP_WATERMARK = _conf(
    "serving.fleet.scaleUpWatermark", float, 0.8,
    "High watermark on the fleet pressure signal (max of normalized "
    "admission queue depth and device-budget fraction across healthy "
    "replicas): pressure at or above this for "
    "serving.fleet.scaleUpStableTicks consecutive ticks requests one "
    "more replica (bounded by maxReplicas and the up-cooldown).",
    checker=_fraction("serving.fleet.scaleUpWatermark"))

SERVING_FLEET_SCALE_DOWN_WATERMARK = _conf(
    "serving.fleet.scaleDownWatermark", float, 0.25,
    "Low watermark on the fleet pressure signal: pressure at or below "
    "this for serving.fleet.scaleDownStableTicks consecutive ticks "
    "retires one replica through the graceful-drain path (bounded by "
    "minReplicas and the down-cooldown). Keep it well under the high "
    "watermark — the dead band between them is the hysteresis that "
    "stops flapping.",
    checker=_fraction("serving.fleet.scaleDownWatermark"))

SERVING_FLEET_SCALE_UP_STABLE_TICKS = _conf(
    "serving.fleet.scaleUpStableTicks", int, 2,
    "Consecutive control ticks the pressure must hold at/above the high "
    "watermark before a scale-up fires (a one-tick spike is noise, not "
    "a trend).", checker=_positive("serving.fleet.scaleUpStableTicks"))

SERVING_FLEET_SCALE_DOWN_STABLE_TICKS = _conf(
    "serving.fleet.scaleDownStableTicks", int, 5,
    "Consecutive control ticks the pressure must hold at/below the low "
    "watermark before a scale-down fires — longer than the up "
    "requirement on purpose: growing late queues work, shrinking early "
    "sheds it.", checker=_positive("serving.fleet.scaleDownStableTicks"))

SERVING_FLEET_SCALE_UP_COOLDOWN = _conf(
    "serving.fleet.scaleUpCooldownSeconds", float, 5.0,
    "Minimum wall time between two scale-ups: a freshly started replica "
    "needs time to register and absorb load before the controller may "
    "conclude the fleet is still too small.",
    checker=_non_negative("serving.fleet.scaleUpCooldownSeconds"))

SERVING_FLEET_SCALE_DOWN_COOLDOWN = _conf(
    "serving.fleet.scaleDownCooldownSeconds", float, 30.0,
    "Minimum wall time between two scale-downs, and after any scale-up "
    "before the first scale-down — the asymmetry (longer than the up "
    "cooldown) biases the fleet toward capacity under oscillating load.",
    checker=_non_negative("serving.fleet.scaleDownCooldownSeconds"))

SERVING_FLEET_P99_OBJECTIVE = _conf(
    "serving.fleet.p99ObjectiveSeconds", float, 0.0,
    "Latency objective the autoscaler folds into fleet pressure: a "
    "replica's rolling-window p99 query wall divided by this objective "
    "becomes a pressure component alongside footprint and queue depth, "
    "so a fleet that is slow (not just full) still scales up. 0 "
    "disables the latency component.",
    checker=_non_negative("serving.fleet.p99ObjectiveSeconds"))

SERVING_MAX_QUEUED_PER_TENANT = _conf(
    "serving.maxQueuedPerTenant", int, 256,
    "Bound on one tenant's scheduler queue depth: a submission past it "
    "is shed at the front door with a structured RETRYABLE "
    "OverloadedError carrying a retry-after hint (counted in "
    "serving.sheds) instead of queueing without limit — one flooding "
    "tenant cannot OOM the scheduler. 0 disables the bound.",
    checker=_non_negative("serving.maxQueuedPerTenant"))

SERVING_QUOTA_MAX_PER_CLIENT = _conf(
    "serving.quota.maxConcurrentPerClient", int, 0,
    "Per-client concurrent-query quota at the serving wire: a client "
    "(wire peer) with this many open queries on a replica gets further "
    "submits rejected with a structured RETRYABLE QuotaExceededError "
    "(counted in serving.quota_rejections). 0 disables the quota.",
    checker=_non_negative("serving.quota.maxConcurrentPerClient"))

SERVING_OVERLOAD_RETRY_AFTER = _conf(
    "serving.overload.retryAfterSeconds", float, 0.25,
    "Base retry-after hint shipped inside OverloadedError / "
    "QuotaExceededError rejections; the server scales it with how far "
    "past the bound the tenant's queue is, and the client honors the "
    "hint (floored by its deterministic backoff schedule) before "
    "retrying.", checker=_positive("serving.overload.retryAfterSeconds"))

SERVING_OVERLOAD_CLIENT_RETRIES = _conf(
    "serving.overload.clientRetries", int, 2,
    "How many full rotation passes the client retries a submission that "
    "EVERY replica shed (each pass sleeps the max of the replicas' "
    "retry-after hints and the deterministic backoff for that attempt) "
    "before surfacing the OverloadedError to the caller.",
    checker=_non_negative("serving.overload.clientRetries"))

# --------------------------------------------------------------------------------------
# Observability (SQLMetrics / NVTX analog)
# --------------------------------------------------------------------------------------
METRICS_ENABLED = _conf(
    "metrics.enabled", bool, True,
    "Collect per-operator metrics (rows, batches, op time) — analog of SQLMetrics.")

TRACE_ENABLED = _conf(
    "trace.enabled", bool, False,
    "Structured query tracing (utils/tracing.py): record per-operator "
    "execute() spans (rows/batches/bytes, wall + self time, keyed by plan "
    "node id), transfer chunk upload / async download spans, shuffle "
    "fetch/retry events, grace partition/spill events, and serving "
    "lifecycle/admission/preemption/wire spans into a bounded ring "
    "buffer, and emit a named jax.profiler range PER OPERATOR (analog of "
    "the NVTX ranges). Feeds EXPLAIN ANALYZE (tree_string(analyze=True) "
    "/ QueryHandle.explain_analyze()) and the Chrome/Perfetto trace "
    "export. Off: every hook reduces to one boolean read (overhead "
    "gated in the nightly bench).")

TRACE_EXPORT_PATH = _conf(
    "trace.export.path", str, "",
    "When set (and trace.enabled), each action writes its span window as "
    "Chrome trace-event JSON to this path on completion — loadable in "
    "ui.perfetto.dev / chrome://tracing to inspect overlapped pipelines "
    "(chunked upload vs compute, streaming D2H). The file is rewritten "
    "per action (last-action semantics, like session.last_metrics); use "
    "QueryHandle.export_trace(path) for one specific query's spans.")

TRACE_BUFFER_SPANS = _conf(
    "trace.maxBufferedSpans", int, 65536,
    "Capacity of the tracing ring buffer: a long-running traced server "
    "overwrites its oldest spans past this bound instead of growing "
    "without limit. Exports and EXPLAIN ANALYZE see at most this many "
    "trailing spans.", checker=_positive("trace.maxBufferedSpans"))

SERVING_STATS_WINDOW = _conf(
    "serving.stats.windowSeconds", float, 300.0,
    "Rolling window of the serve.stats time-series (serving/stats.py): "
    "per-replica gauge samples (device budget in use, admission queue "
    "depth, running/queued per tenant) and query wall times older than "
    "this are dropped; p50/p99 query wall is computed over the window. "
    "The feed load-aware replica routing consumes (ROADMAP item 4).",
    checker=_positive("serving.stats.windowSeconds"))

SERVING_STATS_SAMPLE_INTERVAL = _conf(
    "serving.stats.sampleIntervalSeconds", float, 1.0,
    "Period of the scheduler's background gauge-sampler tick: before it, "
    "gauges were sampled only at terminal queries and stats requests, so "
    "an idle or wedged replica reported a stale time-series exactly when "
    "the autoscaler most needed truth. The daemon tick keeps the series "
    "fresh and snapshot() stamps its age (age_s) so consumers can treat "
    "a stalled sampler as unhealthy. 0 disables the tick (tests).",
    checker=_non_negative("serving.stats.sampleIntervalSeconds"))

SERVING_STATS_STALE_AFTER = _conf(
    "serving.stats.staleAfterSeconds", float, 10.0,
    "Snapshot age (serve_stats age_s — seconds since the last sampler "
    "tick) past which the autoscaler treats a replica's stats as stale: "
    "a stale replica is excluded from the pressure signal AND from the "
    "healthy count, so a wedged replica flat-lining its gauges cannot "
    "read as idle and trigger a scale-down.",
    checker=_positive("serving.stats.staleAfterSeconds"))


class TpuConf:
    """Immutable snapshot of configuration overrides (analog of RapidsConf)."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._values: Dict[str, Any] = {}
        if overrides:
            for key, raw in overrides.items():
                entry = _REGISTRY.get(key)
                if entry is None:
                    # Unknown keys under our prefix are kept for dynamic per-rule
                    # enable keys; anything else is ignored like Spark does.
                    self._values[key] = raw
                    continue
                val = entry.convert(raw)
                if entry.checker is not None:
                    err = entry.checker(val)
                    if err:
                        raise ValueError(f"{key}: {err}")
                self._values[key] = val

    def get(self, entry: ConfEntry) -> Any:
        if entry.key in self._values:
            return self._values[entry.key]
        return entry.default

    def get_raw(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def is_rule_enabled(self, conf_key: str, default: bool = True) -> bool:
        raw = self._values.get(conf_key)
        if raw is None:
            return default
        return str(raw).strip().lower() in ("true", "1", "yes", "on")

    def with_overrides(self, extra: Dict[str, Any]) -> "TpuConf":
        merged = dict(self._values)
        merged.update(extra)
        return TpuConf(merged)

    # Convenience properties for hot keys -------------------------------------------------
    @property
    def sql_enabled(self) -> bool: return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str: return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_bytes(self) -> int: return self.get(TPU_BATCH_SIZE_BYTES)

    @property
    def string_max_bytes(self) -> int: return self.get(STRING_MAX_BYTES)

    @property
    def is_test_enabled(self) -> bool: return self.get(TEST_CONF)

    @property
    def concurrent_tpu_tasks(self) -> int: return self.get(CONCURRENT_TPU_TASKS)

    @property
    def shuffle_transport_class(self) -> str: return self.get(SHUFFLE_TRANSPORT_CLASS)

    @property
    def shuffle_tcp_port(self) -> int: return self.get(SHUFFLE_TCP_PORT)

    @property
    def shuffle_tcp_registry(self) -> str: return self.get(SHUFFLE_TCP_REGISTRY)

    @property
    def shuffle_max_inflight_bytes(self) -> int:
        return self.get(SHUFFLE_MAX_INFLIGHT_BYTES)

    @property
    def shuffle_bounce_buffer_size(self) -> int:
        return self.get(SHUFFLE_BOUNCE_BUFFER_SIZE)

    @property
    def shuffle_bounce_buffer_count(self) -> int:
        return self.get(SHUFFLE_BOUNCE_BUFFER_COUNT)

    @property
    def shuffle_codec(self) -> str: return self.get(SHUFFLE_COMPRESSION_CODEC)

    @property
    def shuffle_max_retries(self) -> int: return self.get(SHUFFLE_MAX_RETRIES)

    @property
    def shuffle_retry_backoff_ms(self) -> int:
        return self.get(SHUFFLE_RETRY_BACKOFF_MS)

    @property
    def shuffle_connect_timeout(self) -> float:
        return self.get(SHUFFLE_CONNECT_TIMEOUT)

    @property
    def shuffle_checksum_enabled(self) -> bool:
        return self.get(SHUFFLE_CHECKSUM_ENABLED)

    @property
    def shuffle_recompute_max_stage_attempts(self) -> int:
        return self.get(SHUFFLE_RECOMPUTE_MAX_STAGE_ATTEMPTS)

    @property
    def shuffle_faults_plan(self) -> str: return self.get(SHUFFLE_FAULTS_PLAN)

    @property
    def shuffle_faults_seed(self) -> int: return self.get(SHUFFLE_FAULTS_SEED)

    @property
    def shuffle_faults_transport_class(self) -> str:
        return self.get(SHUFFLE_FAULTS_TRANSPORT)


def all_entries() -> List[ConfEntry]:
    return sorted(_REGISTRY.values(), key=lambda e: e.key)


def generate_docs(include_internal: bool = False) -> str:
    """Emit the markdown configuration reference (analog of RapidsConf.help(),
    RapidsConf.scala:641 -> docs/configs.md)."""
    lines = [
        "# TPU Accelerator Configuration",
        "",
        "All configs are set like ordinary Spark confs. Generated by "
        "`python -m spark_rapids_tpu.config`.",
        "",
        "| Name | Description | Default |",
        "|---|---|---|",
    ]
    for entry in all_entries():
        if entry.internal and not include_internal:
            continue
        lines.append(f"| {entry.key} | {entry.doc} | {entry.default} |")
    lines.append("")
    return "\n".join(lines)


def from_environ() -> TpuConf:
    """Build a TpuConf from SPARK_RAPIDS_TPU_* environment variables (key dots -> _)."""
    overrides: Dict[str, Any] = {}
    for env_key, val in os.environ.items():
        if env_key.startswith("SPARK_RAPIDS_TPU_"):
            key = _PREFIX + "." + env_key[len("SPARK_RAPIDS_TPU_"):].lower().replace("_", ".")
            overrides[key] = val
    return TpuConf(overrides)


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else None
    text = generate_docs()
    if out:
        with open(out, "w") as f:
            f.write(text)
    else:
        print(text)
