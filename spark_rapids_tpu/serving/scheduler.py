"""Session scheduler: N concurrent queries over a shared worker pool.

The admission story has two layers, both fair-share by tenant:

1. SCHEDULER admission — submitted queries wait in per-tenant FIFO queues;
   a shared pool of ``serving.maxConcurrentQueries`` workers picks the
   next query from the tenant with the lowest served/weight deficit
   (weighted deficit round-robin: a tenant with weight 3 is served three
   times as often as a tenant with weight 1, FIFO within each tenant).
   This bounds in-flight queries by conf, so one heavy tenant cannot
   occupy every worker.
2. DEVICE admission — each running query still takes the device-admission
   semaphore (memory/semaphore.py) for its action, with the SAME tenant
   weights, so HBM working sets are fair-shared too (the GpuSemaphore
   role, extended per Theseus's admission-controlled concurrency).

Per-query lifecycle, cancellation, deadlines and metric snapshots live on
the QueryHandle (lifecycle.py); the worker binds the handle thread-locally
so the program cache attributes hits/misses/compile time to it.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.serving.admission import FootprintAdmission
from spark_rapids_tpu.serving.lifecycle import (OverloadedError,
                                                QueryCancelledError,
                                                QueryHandle,
                                                QueryTimeoutError,
                                                ResultStream,
                                                SchedulerDrainingError,
                                                bind_query)
from spark_rapids_tpu.serving.program_cache import (configure_from_conf,
                                                    plan_key)
from spark_rapids_tpu.utils.errors import triage_boundary, wire_boundary
from spark_rapids_tpu.utils.fair_share import (activation_reset, pick_tenant,
                                               weight_of)


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """'etl:3,adhoc:1' -> {'etl': 3.0, 'adhoc': 1.0}; malformed entries
    raise (a silently dropped weight would silently unbalance serving)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.rpartition(":")
        if not sep or not name.strip():
            raise ValueError(
                f"serving.tenantWeights entry {part!r} is not tenant:weight")
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"serving.tenantWeights entry {part!r}: weight {w!r} is "
                f"not a number") from None
        if weight <= 0:
            raise ValueError(
                f"serving.tenantWeights: weight for {name!r} must be > 0")
        out[name.strip()] = weight
    return out


#: terminal handles kept for introspection; older ones are pruned at
#: submit so a long-running server's handle list (each holding its result
#: table) cannot grow without bound — callers keep their own references
_HANDLE_HISTORY = 4096


class SessionScheduler:
    """Fair-share scheduler over one TpuSession (created lazily by
    ``session.scheduler`` / ``session.submit``)."""

    def __init__(self, session):
        self.session = session
        conf = session.conf
        self.max_concurrent = conf.get(cfg.SERVING_MAX_CONCURRENT)
        self.default_timeout = conf.get(cfg.SERVING_QUERY_TIMEOUT) or None
        self._weights = parse_tenant_weights(
            conf.get(cfg.SERVING_TENANT_WEIGHTS))
        self._cv = threading.Condition()
        self._queues: Dict[str, deque] = {}
        self._served: Dict[str, float] = {}
        self._handles: List[QueryHandle] = []
        #: terminal states of handles pruned from the history, so stats()
        #: stays truthful after pruning
        self._pruned_states: Dict[str, int] = {}
        self._active = 0
        self._shutdown = False
        #: graceful drain: set by start_draining() — new submissions are
        #: rejected with the retryable SchedulerDrainingError while
        #: running/queued queries finish normally; serve_stats reports
        #: the state so routers stop sending traffic here
        self._draining = False
        self._workers: List[threading.Thread] = []
        self.program_cache = configure_from_conf(conf)
        #: footprint admission ledger (serving/admission.py): RUNNING
        #: queries are charged their working_set_estimate against the
        #: device budget instead of being bounded by count alone
        self.admission = FootprintAdmission(conf)
        #: rolling serve.stats window (serving/stats.py): per-replica
        #: gauges + p50/p99 query wall over serving.stats.windowSeconds —
        #: the feed load-aware replica routing consumes
        from spark_rapids_tpu.serving.stats import ServeStatsWindow
        self.serve_stats = ServeStatsWindow(
            conf.get(cfg.SERVING_STATS_WINDOW))
        self._preempt_enabled = conf.get(cfg.SERVING_PREEMPT_ENABLED)
        self._preempt_starve_s = (
            conf.get(cfg.SERVING_PREEMPT_STARVATION_MS) / 1e3)
        self._preempt_park = conf.get(cfg.SERVING_PREEMPT_PARK)
        #: front-door overload shed: one tenant's queue never grows past
        #: this bound — the submission is rejected with the RETRYABLE
        #: OverloadedError instead (0 disables)
        self._max_queued_per_tenant = conf.get(
            cfg.SERVING_MAX_QUEUED_PER_TENANT)
        self._retry_after_base = conf.get(cfg.SERVING_OVERLOAD_RETRY_AFTER)
        #: background gauge-sampler tick (started lazily beside the worker
        #: pool): keeps the serve.stats series fresh on an idle replica so
        #: snapshot age reads sampler liveness, not traffic
        self._sample_interval = conf.get(cfg.SERVING_STATS_SAMPLE_INTERVAL)
        self._sampler_stop = threading.Event()
        self._sampler: Optional[threading.Thread] = None
        self._push_weights_to_semaphore()

    # ---- configuration -----------------------------------------------------
    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError("tenant weight must be > 0")
        with self._cv:
            self._weights[tenant] = float(weight)
            self._cv.notify_all()
        self._push_weights_to_semaphore()

    def _push_weights_to_semaphore(self) -> None:
        """Mirror the scheduler's weights into the device-admission
        semaphore so both layers share one fairness policy. The weight
        table is snapshotted under the scheduler cv: set_tenant_weight
        mutates it concurrently and a dict resized mid-iteration raises
        (R012)."""
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        dm = DeviceManager.peek()
        if dm is not None:
            with self._cv:
                weights = dict(self._weights)
            for tenant, w in weights.items():
                dm.semaphore.set_tenant_weight(tenant, w)

    def _weight(self, tenant: str) -> float:
        return weight_of(self._weights, tenant)

    # ---- submission --------------------------------------------------------
    def submit(self, query: Any, tenant: str = "default",
               timeout: Optional[float] = None,
               label: Optional[str] = None,
               stream: Optional[ResultStream] = None) -> QueryHandle:
        """Enqueue a DataFrame or SQL string; returns immediately with the
        query's handle. Planning and execution happen on a worker, so a
        malformed query FAILS its handle instead of raising here.
        ``stream``, when given, receives each result batch as its download
        resolves — before the final batch exists (the wire layer's
        streaming-partial-results path)."""
        handle = QueryHandle(query, tenant=tenant,
                             timeout=(timeout if timeout is not None
                                      else self.default_timeout),
                             label=label, stream=stream)
        handle.preemptible = self._preempt_enabled
        handle.preempt_starvation_s = self._preempt_starve_s
        handle.preempt_park_spillable = self._preempt_park
        shed_depth = None
        with self._cv:
            if self._shutdown:
                raise RuntimeError("scheduler is shut down")
            if self._draining:
                raise SchedulerDrainingError(
                    "scheduler is draining: running queries finish, new "
                    "submissions must route to another replica")
            q = self._queues.get(tenant)
            if (self._max_queued_per_tenant
                    and q is not None
                    and len(q) >= self._max_queued_per_tenant):
                # front-door shed: the bound holds BEFORE the handle would
                # queue, so admitted/running queries are untouched and the
                # scheduler's memory stays bounded under a flooding tenant
                shed_depth = len(q)
            if shed_depth is None:
                if not q:
                    # deficit-round-robin activation reset (utils/
                    # fair_share.py): a late joiner cannot monopolize the
                    # workers, and a returning tenant is not starved by
                    # its own history
                    activation_reset(tenant,
                                     (t for t, w in self._queues.items()
                                      if w),
                                     self._served, self._weights)
                self._queues.setdefault(tenant, deque()).append(handle)
                self._handles.append(handle)
                if len(self._handles) > _HANDLE_HISTORY:
                    keep = []
                    excess = len(self._handles) - _HANDLE_HISTORY
                    for h in self._handles:
                        if excess > 0 and h.state.is_terminal:
                            self._pruned_states[h.state.value] = \
                                self._pruned_states.get(h.state.value, 0) + 1
                            excess -= 1
                        else:
                            keep.append(h)
                    self._handles = keep
                self._ensure_workers_locked()
            self._ensure_sampler_locked()
            self._cv.notify_all()
        if shed_depth is not None:
            from spark_rapids_tpu.utils import metrics as um
            um.SERVING_METRICS[um.SERVING_SHEDS].add(1)
            raise OverloadedError(
                f"tenant {tenant!r} queue at its bound "
                f"({shed_depth}/{self._max_queued_per_tenant}): submission "
                f"shed, retry after the hint",
                retry_after_s=self.shed_retry_after(shed_depth))
        return handle

    def shed_retry_after(self, depth: int) -> float:
        """Retry-after hint for a shed submission: the base conf hint
        scaled with how deep the tenant's queue is relative to the worker
        pool — a deeper backlog drains slower, so the hint grows with it
        (deterministic: no jitter here, the CLIENT adds its seeded
        backoff)."""
        scale = 1.0 + depth / max(1, self.max_concurrent)
        return round(self._retry_after_base * scale, 4)

    def _ensure_workers_locked(self) -> None:
        while len(self._workers) < self.max_concurrent:
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"serving-worker-{len(self._workers)}")
            self._workers.append(t)
            t.start()

    def _ensure_sampler_locked(self) -> None:
        """Start the periodic gauge-sampler daemon (once; caller holds
        the cv). Before this tick existed, gauges were sampled only at
        terminal queries and stats requests — an idle or wedged replica
        reported a stale series exactly when the autoscaler most needed
        truth. The tick keeps the series (and its age_s stamp) honest."""
        if (self._sampler is not None or self._shutdown
                or not self._sample_interval):
            return
        t = threading.Thread(target=self._sampler_loop, daemon=True,
                             name="serving-stats-sampler")
        self._sampler = t
        t.start()

    def start_stats_sampler(self) -> None:
        """Public start hook (the wire server calls it at startup so a
        replica reports a fresh series before its first query)."""
        with self._cv:
            self._ensure_sampler_locked()

    def _sampler_loop(self) -> None:
        # Event.wait is the bounded sleep (R010); no scheduler lock is
        # held anywhere in the loop — sample() takes the cv only inside
        # its gauge read (R006)
        while not self._sampler_stop.wait(self._sample_interval):
            self.serve_stats.sample(self)

    # ---- fair-share pick ---------------------------------------------------
    def _next_locked(self) -> Optional[QueryHandle]:
        import time as _time
        now = _time.monotonic()
        # admission-requeued heads sit out their deferral (the worker
        # pool's 0.2 s cv poll re-checks), so a budget-blocked whale
        # cannot head-of-line-block tenants whose queries would fit
        tenant = pick_tenant((t for t, q in self._queues.items()
                              if q and q[0].admit_ready(now)),
                             self._served, self._weights)
        if tenant is None:
            return None
        self._served[tenant] = self._served.get(tenant, 0.0) + 1.0
        return self._queues[tenant].popleft()

    # ---- the worker pool ---------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                handle = self._next_locked()
                while handle is None and not self._shutdown:
                    self._cv.wait(timeout=0.2)
                    handle = self._next_locked()
                if handle is None:      # shutdown with an empty queue
                    return
                self._active += 1
            try:
                self._run_handle(handle)
            finally:
                with self._cv:
                    self._active -= 1
                    self._cv.notify_all()

    def _run_handle(self, handle: QueryHandle) -> None:
        import contextlib
        from spark_rapids_tpu.utils import tracing as _tracing
        # trace the WHOLE handle run (lifecycle transitions, planning,
        # admission) — the action driver's own activation nests inside
        trace_scope = (_tracing.TRACER.activate()
                       if self.session.conf.get(cfg.TRACE_ENABLED)
                       else contextlib.nullcontext())
        try:
            with trace_scope:
                self._run_handle_traced(handle)
        finally:
            # EVERY terminal path — completion, failure, queued-cancel —
            # feeds the serve.stats latency window and takes a gauge
            # sample, so a replica draining cancellations still reports a
            # live series to the router
            self.serve_stats.record_wall(handle.metric("wall_s"))
            self.serve_stats.sample(self)

    # the ladder's cancellation sink AND the serving-wire serialization
    # boundary: exceptions caught here become the handle's terminal state,
    # which the server ships to clients via the utils/errors.py codec —
    # R014 checks arriving types are classified, R015 that they survive
    # the wire
    @triage_boundary
    @wire_boundary
    def _run_handle_traced(self, handle: QueryHandle) -> None:
        if handle.cancel_requested:     # cancelled while QUEUED
            handle.mark_admitted()
            handle.finish_cancelled()
            return
        handle.mark_admitted()
        with self._cv:
            has_weights = bool(self._weights)
        if has_weights:
            # the DeviceManager is created lazily by the first action, so
            # weights pushed at scheduler construction may have found no
            # semaphore yet — re-mirror them on the running path (cheap,
            # idempotent) so device admission is weighted from query one
            from spark_rapids_tpu.memory.device_manager import DeviceManager
            DeviceManager.initialize(self.session.conf)
            self._push_weights_to_semaphore()
        try:
            with bind_query(handle):
                handle.check_cancelled()
                if handle._planned is None:
                    df = self._as_dataframe(handle._work)
                    final = df._executed_plan()
                    handle.note_metric("plan_key",
                                       plan_key(final, self.session.conf))
                    from spark_rapids_tpu.plan.footprint import \
                        plan_working_set_estimate
                    handle._planned = (df, final,
                                       plan_working_set_estimate(final))
                df, final, estimate = handle._planned
                # footprint admission: charge the plan's predicted peak
                # device working set against the budget BEFORE running —
                # a query that does not fit is REQUEUED (plan cached on
                # the handle) so this worker stays free for queries that
                # do fit, instead of OOMing running queries or pinning
                # the slot while it waits
                if not self.admission.try_admit(handle, estimate):
                    if self._requeue_for_admission(handle):
                        return
                    raise QueryCancelledError(
                        f"{handle.label} (id {handle.query_id}) "
                        f"cancelled at shutdown")
                try:
                    handle._planned = None
                    handle.mark_running()
                    result = df._collect(query=handle, final=final)
                    if self.session.conf.get(cfg.TRACE_ENABLED):
                        # render EXPLAIN ANALYZE now: _finish drops the
                        # plan reference (bounded handle memory), so the
                        # text is the surviving record
                        handle._analyze_text = (
                            f"== Physical plan with observed stats "
                            f"(query {handle.query_id}, wall "
                            f"{time.perf_counter() - handle.submitted_at:.3f}"
                            f"s) ==\n"
                            + final.tree_string(analyze=True))
                finally:
                    self.admission.release(handle)
            handle.finish_ok(result)
        except QueryCancelledError as e:
            handle.finish_cancelled(e)
        except QueryTimeoutError as e:
            handle.finish_failed(e)
        except BaseException as e:      # noqa: BLE001 - surfaces in result()
            handle.finish_failed(e)

    def _requeue_for_admission(self, handle: QueryHandle) -> bool:
        """Put a budget-rejected handle back at its tenant's HEAD (FIFO
        preserved) with a short deferral before the next pick. False when
        the scheduler is shutting down — the caller cancels instead."""
        import time as _time
        with self._cv:
            if self._shutdown:
                return False
            handle._admit_not_before = _time.monotonic() + 0.05
            self._queues.setdefault(handle.tenant,
                                    deque()).appendleft(handle)
            self._cv.notify_all()
            return True

    def _as_dataframe(self, work):
        if isinstance(work, str):
            return self.session.sql(work)
        if hasattr(work, "_collect"):
            return work
        raise TypeError(
            f"submit() takes a DataFrame or a SQL string, got {type(work)}")

    # ---- introspection / lifecycle ----------------------------------------
    def handles(self) -> List[QueryHandle]:
        with self._cv:
            return list(self._handles)

    def start_draining(self) -> None:
        """Flip the scheduler to DRAINING: every later submit() raises
        the retryable SchedulerDrainingError while queued and running
        queries finish normally — pair with drain() to wait them out.
        One-way by design: a draining replica is on its way out."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted query reaches a terminal state.
        ``timeout=0`` is a non-blocking poll."""
        import time as _time
        deadline = (_time.perf_counter() + timeout
                    if timeout is not None else None)
        for h in self.handles():
            left = (None if deadline is None
                    else max(0.0, deadline - _time.perf_counter()))
            if not h.wait(left):
                return False
        return True

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            states: Dict[str, int] = dict(self._pruned_states)
            for h in self._handles:
                states[h.state.value] = states.get(h.state.value, 0) + 1
            queued = sum(len(q) for q in self._queues.values())
            out = {"submitted": (len(self._handles)
                                 + sum(self._pruned_states.values())),
                   "queued": queued,
                   "active": self._active, "states": states,
                   "served_by_tenant": dict(self._served),
                   "weights": dict(self._weights)}
        out["program_cache"] = self.program_cache.stats()
        return out

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop accepting work; cancel queued queries; optionally wait for
        running ones (cancellation stays cooperative — running queries
        finish or observe their cancel flag at the next checkpoint)."""
        self._sampler_stop.set()
        with self._cv:
            self._shutdown = True
            queued = [h for q in self._queues.values() for h in q]
            for q in self._queues.values():
                q.clear()
            # snapshot under the cv: a submit racing shutdown may still
            # be appending to the worker list (R012)
            workers = list(self._workers)
            self._cv.notify_all()
        for h in queued:
            h.cancel()
            h.finish_cancelled()
        if wait:
            for t in workers:
                t.join(timeout)
