"""Per-query lifecycle: handle, states, cancellation, deadlines, metrics.

A ``QueryHandle`` is the server-side identity of one submitted query — the
role Spark's jobGroup/SQLExecution id plays for a statement, extended with
the pieces an inference-serving stack needs:

- a state machine QUEUED -> ADMITTED -> RUNNING -> {DONE, FAILED,
  CANCELLED} with monotonic transition timestamps;
- COOPERATIVE cancellation and deadlines: ``cancel()`` only sets a flag;
  the running query observes it at exec boundaries (ExecContext.
  check_cancelled), in the pipeline producer, and while blocked on
  device-semaphore admission, then unwinds through the normal finally
  chain — so a cancelled query releases its semaphore hold and catalog
  buffers exactly like a failed one;
- per-query metric snapshots (queue wait, admission wait, compile time,
  program-cache hits/misses, transfer deltas, rows) replacing the racy
  process-global ``session.last_metrics`` as the source of truth; the
  global survives as a last-action alias.

``current_query()`` is the thread-scoped attribution point: the scheduler
worker (and any producer thread an exec spawns on the query's behalf)
binds the handle so the program cache can attribute hits/misses/compile
time without threading the handle through every call signature.
"""
from __future__ import annotations

import contextlib
import enum
import itertools
import threading
import time
from typing import Any, Dict, Optional

from spark_rapids_tpu.utils import tracing as _tracing


class QueryState(enum.Enum):
    QUEUED = "QUEUED"
    ADMITTED = "ADMITTED"
    RUNNING = "RUNNING"
    DONE = "DONE"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    @property
    def is_terminal(self) -> bool:
        return self in (QueryState.DONE, QueryState.FAILED,
                        QueryState.CANCELLED)


class QueryCancelledError(RuntimeError):
    """Raised inside a running query at the next cooperative checkpoint
    after ``cancel()``; surfaces from ``result()`` as the terminal error."""


class QueryTimeoutError(RuntimeError):
    """Raised at a cooperative checkpoint once the query's deadline passed
    (conf ``serving.queryTimeoutSeconds`` or ``submit(timeout=...)``)."""


class SchedulerDrainingError(RuntimeError):
    """Submission rejected because the scheduler/replica is DRAINING.

    This is a RETRYABLE REDIRECT, not a failure: running queries finish
    and streams flush, but no new work is accepted. The wire layer
    carries the type name to the client, which transparently reroutes
    the submission to another replica (the graceful-drain contract —
    zero caller-visible errors during a drain)."""


class OverloadedError(RuntimeError):
    """Submission shed at the front door: the tenant's scheduler queue is
    at its bound (``serving.maxQueuedPerTenant``) — the replica refuses
    to queue more rather than grow without limit. RETRYABLE by taxonomy;
    ``retry_after_s`` is the server's hint for when capacity is likely
    back (scaled with queue depth), which the routing client honors on
    its deterministic backoff before retrying the rotation. Load sheds
    BEFORE it queues, never mid-query: admitted queries are unaffected."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s or 0.0)


class QuotaExceededError(RuntimeError):
    """Submission rejected by the per-client concurrent-query quota
    (``serving.quota.maxConcurrentPerClient``): this wire peer already
    has its full allowance of open queries on the replica. RETRYABLE —
    the client's own queries finishing is what frees quota — but NOT
    reroutable: the quota is per client, so the client surfaces it to
    the caller (after honoring ``retry_after_s``) instead of shopping
    the submission to a peer replica."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s or 0.0)


_QUERY_IDS = itertools.count(1)


class ResultStream:
    """Bounded FIFO of streamed result batches between the scheduler
    worker (producer: ``QueryHandle.emit_batch``) and a consumer — the
    wire layer's serve.next handler, or any in-process subscriber.

    Bounded buffering, never an unbounded queue: a full stream
    backpressures the producer at its next batch boundary (bounded poll +
    the producer query's own cancel check, the R010 idiom). A consumer
    that goes away calls ``abandon()``; the producer then drops batches
    instead of blocking on a reader that will never come back."""

    def __init__(self, depth: int = 4):
        self.depth = max(1, depth)
        self._cv = threading.Condition()
        self._q: list = []
        self._state = "open"            # open | finished | failed
        self._error: Optional[BaseException] = None
        self._abandoned = False

    def put(self, table, cancel_check=None) -> bool:
        """Producer side: enqueue one result batch; blocks (bounded poll)
        while the stream is full. Returns False when the consumer
        abandoned the stream (the batch is dropped)."""
        with self._cv:
            while len(self._q) >= self.depth and not self._abandoned:
                self._cv.wait(0.05)
                if cancel_check is not None:
                    cancel_check()
            if self._abandoned:
                return False
            self._q.append(table)
            self._cv.notify_all()
            return True

    def finish(self) -> None:
        with self._cv:
            if self._state == "open":
                self._state = "finished"
            self._cv.notify_all()

    def fail(self, error: BaseException) -> None:
        with self._cv:
            if self._state == "open":
                self._state = "failed"
                self._error = error
            self._cv.notify_all()

    def abandon(self) -> None:
        """Consumer side: stop consuming; pending batches drop and the
        producer never blocks on this stream again."""
        with self._cv:
            self._abandoned = True
            self._q.clear()
            self._cv.notify_all()

    def next(self, timeout: float):
        """Consumer side: ``("batch", table)`` when one is ready within
        ``timeout`` seconds, ``("done", None)`` / ``("error", exc)`` once
        drained and terminal, else ``("wait", None)`` — the caller
        re-polls (a wire handler answers WAIT and frees its thread)."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cv:
            while True:
                if self._q:
                    batch = self._q.pop(0)
                    self._cv.notify_all()
                    return ("batch", batch)
                if self._state == "finished":
                    return ("done", None)
                if self._state == "failed":
                    return ("error", self._error)
                left = deadline - time.monotonic()
                if left <= 0:
                    return ("wait", None)
                self._cv.wait(left)

#: thread-scoped current query for metric attribution (a thread-local, not
#: a contextvar: exec producer threads rebind explicitly from ctx.query —
#: implicit contextvar inheritance does not cross threading.Thread anyway)
_TLS = threading.local()


def current_query() -> Optional["QueryHandle"]:
    return getattr(_TLS, "query", None)


@contextlib.contextmanager
def bind_query(handle: Optional["QueryHandle"]):
    """Bind ``handle`` as the thread's current query for the scope."""
    prev = getattr(_TLS, "query", None)
    _TLS.query = handle
    try:
        yield handle
    finally:
        _TLS.query = prev


class QueryHandle:
    """One submitted query: state, cancellation, deadline, metrics, result."""

    def __init__(self, query: Any, tenant: str = "default",
                 timeout: Optional[float] = None,
                 label: Optional[str] = None,
                 stream: Optional[ResultStream] = None):
        self.query_id = next(_QUERY_IDS)
        self.tenant = tenant
        self.label = label or f"query-{self.query_id}"
        #: optional streaming sink: each result batch is pushed here as its
        #: async D2H download resolves — BEFORE the final batch exists
        #: (the wire layer's partial-results path); collect() semantics are
        #: unchanged, the handle still carries the assembled result
        self.stream = stream
        #: batch-granularity preemption (scheduler-set from serving.
        #: preemption.* conf): when True, check_preempt yields the device
        #: permit to starved tenants at exec-boundary checkpoints
        self.preemptible = False
        self.preempt_starvation_s = 0.05
        self.preempt_park_spillable = True
        self._next_preempt_check = 0.0
        #: footprint-admission state (serving/admission.py + scheduler):
        #: the planned (df, final, estimate) cached across an admission
        #: requeue, the earliest re-pick time, and the first-rejection
        #: timestamp the admission wait metric is measured from
        self._planned = None
        self._admit_not_before = 0.0
        self._admission_rejected_at: Optional[float] = None
        #: the submitted work: a DataFrame or a SQL string (planned lazily
        #: in the worker so a malformed query FAILS its handle instead of
        #: raising in submit())
        self._work = query
        self._lock = threading.Lock()
        self._done_evt = threading.Event()
        self._cancel_evt = threading.Event()
        self.state = QueryState.QUEUED
        self.submitted_at = time.perf_counter()
        self.deadline = (self.submitted_at + timeout
                         if timeout and timeout > 0 else None)
        self._result = None
        self._error: Optional[BaseException] = None
        #: per-query metric snapshot; keys documented in docs/serving.md
        self.metrics: Dict[str, Any] = {
            "tenant": tenant,
            "queue_wait_s": None,
            "admission_wait_s": 0.0,
            "compile_s": 0.0,
            "program_cache": {"hits": 0, "misses": 0, "disk_hits": 0},
            "rows": None,
            "wall_s": None,
            #: streaming / preemption / admission story of THIS query
            "stream_batches": 0,
            "first_batch_s": None,
            "preemptions": 0,
            "preempt_wait_s": 0.0,
            "footprint_est_bytes": None,
            "admission_footprint_wait_s": 0.0,
            "admission_grace_hint": False,
            #: THIS query's grace-recursion high-water mark (per-handle
            #: attribution — exact under concurrent out-of-core queries,
            #: unlike the process-global lifetime maximum)
            "recursion_depth_peak": 0,
            #: THIS query's adaptive-rewrite decisions, accumulated across
            #: its actions (per-handle attribution of the adaptive.* deltas
            #: record_exec_metrics receives; utils/metrics.py
            #: ADAPTIVE_METRIC_NAMES)
            "adaptive": {},
        }
        #: EXPLAIN ANALYZE text rendered at completion when the query ran
        #: under trace.enabled (the plan itself is dropped at _finish to
        #: bound handle memory, so the rendering is captured eagerly)
        self._analyze_text: Optional[str] = None
        #: per-operator + transfer snapshot of the query's action(s); the
        #: per-handle replacement for session.last_metrics
        self.exec_metrics: Dict[str, Dict] = {}

    def admit_ready(self, now: float) -> bool:
        """Eligible for worker pickup: past any admission-requeue
        deferral (monotonic clock), or cancelled — a cancelled handle
        must be picked promptly so its terminal transition runs."""
        return self._cancel_evt.is_set() or now >= self._admit_not_before

    # ---- cooperative cancellation / deadline -------------------------------
    def cancel(self) -> bool:
        """Request cancellation. Returns True when the request could still
        take effect (query not already terminal). A QUEUED query is
        finished immediately by the scheduler at dequeue; a RUNNING one
        unwinds at its next checkpoint."""
        with self._lock:
            if self.state.is_terminal:
                return False
        self._cancel_evt.set()
        return True

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_evt.is_set()

    def check_cancelled(self) -> None:
        """The cooperative checkpoint: raises when cancellation was
        requested or the deadline passed. Called at exec boundaries
        (ExecContext.check_cancelled), in the pipeline producer, and while
        waiting on device-semaphore admission."""
        if self._cancel_evt.is_set():
            raise QueryCancelledError(
                f"{self.label} (id {self.query_id}) cancelled")
        if self.deadline is not None and time.perf_counter() > self.deadline:
            raise QueryTimeoutError(
                f"{self.label} (id {self.query_id}) exceeded its deadline")

    # ---- streaming partial results -----------------------------------------
    def emit_batch(self, table) -> None:
        """One result batch materialized (its async D2H resolved): record
        the streaming metrics and, when a ResultStream is attached, push it
        to the consumer — before the remaining batches exist. Called by the
        action driver (api/dataframe._run_partitions) per result batch."""
        with self._lock:
            self.metrics["stream_batches"] += 1
            if self.metrics["first_batch_s"] is None:
                self.metrics["first_batch_s"] = round(
                    time.perf_counter() - self.submitted_at, 6)
        if self.stream is not None:
            self.stream.put(table, cancel_check=self.check_cancelled)

    # ---- batch-granularity preemption --------------------------------------
    def check_preempt(self, ctx) -> None:
        """Preemption point, called from ExecContext.check_cancelled at
        exec boundaries: when another tenant's admission waiter has starved
        past the threshold, yield the device permit — optionally parking
        spillable device state down the grace/spill tiers first — and
        re-acquire under fair share. Only the thread OWNING the task's
        semaphore hold may yield it (producer threads share the hold and
        must not pull it out from under the consumer)."""
        if not self.preemptible or ctx is None:
            return
        if threading.get_ident() != ctx.task_id:
            return
        dm = ctx.device_manager
        if dm is None:
            return
        now = time.monotonic()
        if now < self._next_preempt_check:   # cheap rate limit per batch
            return
        self._next_preempt_check = now + 0.01
        sem = dm.semaphore
        if not sem.has_starved_waiter(exclude_tenant=self.tenant,
                                      min_wait_s=self.preempt_starvation_s):
            return
        # only an actual permit HOLDER parks and yields: a query passing
        # this checkpoint without a hold (CPU-fallback section, between
        # scoped holds) has nothing to give the starved tenant and must
        # not thrash the holder's device state on its behalf
        if not sem.holds_permit(ctx.task_id):
            return
        from spark_rapids_tpu.utils import metrics as um
        if self.preempt_park_spillable:
            store = dm.device_store
            if store is not None and store.budget_bytes:
                # shed the device tier down to the out-of-core HEADROOM
                # watermark so the admitted tenant has HBM room: the
                # overage is, by the store's spill priorities, this
                # query's grace partitions — the store is shared and
                # ownership-blind, but eviction is coldest-first, so
                # another tenant's hot buffers stay put; anything parked
                # re-admits on its next access
                from spark_rapids_tpu import config as _cfg
                headroom = ctx.conf.get(_cfg.OOC_HEADROOM)
                store.spill_to_size(int(store.budget_bytes * headroom))
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        if not sem.yield_to_waiters(task_id=ctx.task_id, tenant=self.tenant,
                                    cancel_check=self.check_cancelled):
            return
        waited = time.perf_counter() - t0
        _tracing.record("serving.preempt_yield", "serving", t0_ns,
                        time.perf_counter_ns() - t0_ns,
                        {"tenant": self.tenant}, query_id=self.query_id)
        um.SERVING_METRICS[um.SERVING_PREEMPTIONS].add(1)
        with self._lock:
            self.metrics["preemptions"] += 1
            self.metrics["preempt_wait_s"] = round(
                self.metrics["preempt_wait_s"] + waited, 6)

    # ---- state transitions (scheduler-driven) ------------------------------
    def _transition(self, state: QueryState) -> None:
        with self._lock:
            self.state = state
            self.metrics[f"t_{state.value.lower()}"] = (
                time.perf_counter() - self.submitted_at)
        _tracing.record(f"serving.state.{state.value}", "serving",
                        time.perf_counter_ns(), 0,
                        {"tenant": self.tenant, "label": self.label},
                        query_id=self.query_id)

    def mark_admitted(self) -> None:
        self._transition(QueryState.ADMITTED)
        self.note_metric("queue_wait_s", round(
            time.perf_counter() - self.submitted_at, 6))

    def mark_running(self) -> None:
        self._transition(QueryState.RUNNING)

    def _finish(self, state: QueryState,
                error: Optional[BaseException] = None,
                result=None) -> None:
        with self._lock:
            if self.state.is_terminal:
                return
            self.state = state
            self._error = error
            self._result = result
            self._work = None       # free the plan; the result is kept
            wall = self.metrics["wall_s"] = round(
                time.perf_counter() - self.submitted_at, 6)
            if result is not None and hasattr(result, "num_rows"):
                self.metrics["rows"] = result.num_rows
        self._done_evt.set()
        _tracing.record(f"serving.state.{state.value}", "serving",
                        time.perf_counter_ns(), 0,
                        {"tenant": self.tenant, "wall_s": wall},
                        query_id=self.query_id)
        # terminal state drains to the streaming consumer on EVERY path —
        # worker completion, queued-cancel, scheduler shutdown — so a wire
        # client always observes DONE or the error, never a silent stall
        if self.stream is not None:
            if state is QueryState.DONE:
                self.stream.finish()
            else:
                self.stream.fail(self._error)

    def finish_ok(self, result) -> None:
        self._finish(QueryState.DONE, result=result)

    def finish_failed(self, error: BaseException) -> None:
        self._finish(QueryState.FAILED, error=error)

    def finish_cancelled(self, error: Optional[BaseException] = None) -> None:
        self._finish(QueryState.CANCELLED,
                     error=error or QueryCancelledError(
                         f"{self.label} (id {self.query_id}) cancelled"))

    # ---- observability surfaces --------------------------------------------
    def note_recursion_depth(self, depth: int) -> None:
        """Grace layer attribution (utils.metrics.note_recursion_depth):
        this query reached recursion level ``depth``."""
        with self._lock:
            if depth > self.metrics["recursion_depth_peak"]:
                self.metrics["recursion_depth_peak"] = depth

    def explain_analyze(self) -> str:
        """EXPLAIN ANALYZE of this query's executed plan (per-node
        observed rows / batches / wall / self time / spill). Rendered by
        the scheduler worker at completion when the query ran under
        ``trace.enabled``; raises for untraced or still-running queries."""
        if self._analyze_text is None:
            raise RuntimeError(
                f"{self.label} (id {self.query_id}): no analyzed plan — "
                f"the query must COMPLETE under trace.enabled")
        return self._analyze_text

    def export_trace(self, path: str) -> int:
        """Write THIS query's spans (still present in the bounded ring)
        as Chrome trace-event JSON; returns the span count."""
        records = _tracing.TRACER.since(0, query_id=self.query_id)
        _tracing.export_chrome(records, path,
                               metadata={"query_id": self.query_id,
                                         "label": self.label})
        return len(records)

    # ---- metric attribution ------------------------------------------------
    def note_metric(self, key: str, value: Any) -> None:
        """Set one metrics key under the handle lock. The metrics dict is
        read by snapshot()/serve.stats from other threads while the
        owning worker fills it — every writer goes through the lock so a
        concurrent snapshot never iterates a resizing dict (R012)."""
        with self._lock:
            self.metrics[key] = value

    def metric(self, key: str, default: Any = None) -> Any:
        """Read one metrics key under the handle lock (the cross-thread
        read counterpart of note_metric)."""
        with self._lock:
            return self.metrics.get(key, default)

    def note_admission_wait(self, seconds: float) -> None:
        with self._lock:
            self.metrics["admission_wait_s"] = round(
                self.metrics["admission_wait_s"] + seconds, 6)

    def count_program(self, *, hit: bool, from_disk: bool = False) -> None:
        with self._lock:
            pc = self.metrics["program_cache"]
            if hit:
                pc["hits"] += 1
            else:
                pc["misses"] += 1
                if from_disk:
                    pc["disk_hits"] += 1

    def note_compile(self, seconds: float) -> None:
        with self._lock:
            self.metrics["compile_s"] = round(
                self.metrics["compile_s"] + seconds, 6)

    def record_exec_metrics(self, snapshot: Dict[str, Dict]) -> None:
        """Attach one action's per-operator + transfer snapshot. Multi-action
        queries (distinct-agg rewrites, pivots) accumulate keyed by action
        ordinal so nothing is overwritten."""
        with self._lock:
            ordinal = self.metrics.get("actions", 0)
            self.metrics["actions"] = ordinal + 1
            if ordinal == 0:
                self.exec_metrics.update(snapshot)
            else:
                self.exec_metrics.update(
                    {f"a{ordinal}:{k}": v for k, v in snapshot.items()})
            acc = self.metrics["adaptive"]
            for k, v in (snapshot.get("adaptive") or {}).items():
                acc[k] = acc.get(k, 0) + v

    # ---- results -----------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done_evt.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done_evt.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def result(self, timeout: Optional[float] = None):
        """Block for the collected arrow table; re-raises the query's error
        for FAILED/CANCELLED handles."""
        if not self._done_evt.wait(timeout):
            raise TimeoutError(
                f"{self.label} (id {self.query_id}) still "
                f"{self.state.value} after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time view of the handle: state + metrics (the per-query
        replacement for reading session.last_metrics)."""
        with self._lock:
            out = {"query_id": self.query_id, "label": self.label,
                   "tenant": self.tenant, "state": self.state.value}
            out.update({k: v for k, v in self.metrics.items()})
            out["program_cache"] = dict(self.metrics["program_cache"])
            out["adaptive"] = dict(self.metrics["adaptive"])
            return out

    def __repr__(self) -> str:
        return (f"QueryHandle(id={self.query_id}, tenant={self.tenant!r}, "
                f"state={self.state.value})")
